#!/usr/bin/env python
"""Lint the /metrics exposition against itself and the README.

Three failure classes, all exit 2:

1. An exposed metric family is missing `# HELP` text (every instrument
   in utils/metrics.py takes a help string — an empty one means somebody
   registered an instrument without documenting it).
2. A `trino_tpu_*` metric documented in the README does not appear in
   any scraped exposition — documentation drift, usually a renamed or
   deleted instrument.
3. A scraped `trino_tpu_*` family does not appear in the README — the
   other drift direction: somebody shipped an instrument without
   documenting it for operators.

README names are extracted from backtick spans; brace shorthand like
``trino_tpu_exchange_{fetched,served}_bytes_total`` expands to every
alternative, while label annotations (``{state=}``, ``{event="x"}``)
are stripped.

Usage:
    python scripts/metrics_lint.py [--readme README.md] TARGET...

where each TARGET is an ``http(s)://.../metrics`` URL or a file holding
a saved exposition.  All targets are unioned before the README check, so
coordinator-only and worker-only metrics both count as present.
"""

from __future__ import annotations

import argparse
import re
import sys
import urllib.request

NAME_RE = re.compile(r"`(trino_tpu_[A-Za-z0-9_{},\"=|]*)`")


def fetch(target: str) -> str:
    if target.startswith(("http://", "https://")):
        with urllib.request.urlopen(target, timeout=10) as resp:
            return resp.read().decode()
    with open(target) as f:
        return f.read()


def parse_exposition(text: str) -> tuple[dict[str, str], set[str]]:
    """(family -> HELP text, set of family names seen via # TYPE)."""
    helps: dict[str, str] = {}
    families: set[str] = set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            helps[name] = help_text.strip()
            families.add(name)
        elif line.startswith("# TYPE "):
            families.add(line[len("# TYPE "):].split()[0])
    return helps, families


def readme_metrics(path: str) -> set[str]:
    """Every trino_tpu_* metric name the README documents, brace patterns
    expanded, label annotations stripped."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        print(f"metrics_lint: cannot read {path}: {e}", file=sys.stderr)
        return set()
    out: set[str] = set()
    for tok in NAME_RE.findall(text):
        for name in _expand(tok):
            if name and not name.endswith("_"):
                out.add(name)
    return out


def _expand(tok: str) -> list[str]:
    m = re.search(r"\{([^{}]*)\}", tok)
    if not m:
        return [tok]
    inner = m.group(1)
    if "=" in inner or '"' in inner:
        # label annotation, not part of the metric name
        return _expand(tok[: m.start()] + tok[m.end():])
    parts = [p.strip() for p in inner.split(",")]
    out: list[str] = []
    for p in parts:
        out.extend(_expand(tok[: m.start()] + p + tok[m.end():]))
    return out


def lint(targets: list[str], readme: str) -> list[str]:
    failures: list[str] = []
    all_families: set[str] = set()
    for target in targets:
        try:
            helps, families = parse_exposition(fetch(target))
        except OSError as e:
            failures.append(f"cannot scrape {target}: {e}")
            continue
        all_families |= families
        for fam in sorted(families):
            if not helps.get(fam):
                failures.append(f"{target}: {fam} has no HELP text")
    if all_families:  # README drift only checkable with a live scrape
        documented = readme_metrics(readme)
        for name in sorted(documented):
            if name not in all_families:
                failures.append(
                    f"README documents {name} but no scraped target exposes it"
                )
        # reverse direction: every exposed trino_tpu_* family must be
        # documented — undocumented telemetry is invisible telemetry
        for fam in sorted(all_families):
            if fam.startswith("trino_tpu_") and fam not in documented:
                failures.append(
                    f"{fam} is exposed but the README does not document it"
                )
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("targets", nargs="+", help="metrics URLs or saved files")
    ap.add_argument("--readme", default="README.md")
    args = ap.parse_args(argv)
    failures = lint(args.targets, args.readme)
    if failures:
        for f in failures:
            print(f"FAIL {f}")
        print(f"metrics_lint: {len(failures)} problem(s)")
        return 2
    print(f"metrics_lint: ok ({len(args.targets)} target(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
