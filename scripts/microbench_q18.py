"""Microbenchmarks of the q18 hot kernels in isolation on the default device.

Each case is jitted on its own so device time attributes exactly; timing uses
back-to-back dispatch with one final block (tunnel RTT amortized away).
"""
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

from trino_tpu.utils.compilecache import enable_persistent_cache
enable_persistent_cache(_REPO)

import jax.numpy as jnp
import numpy as np

import trino_tpu  # noqa: F401  (enables x64)
from trino_tpu.data.types import BIGINT
from trino_tpu.ops.expr import ColumnVal
from trino_tpu.ops import relops

N = 8_388_608  # 8M lanes (q18 join frame capacity)
rng = np.random.default_rng(0)


def timeit(name, fn, *args, iters=4):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:44s} {dt*1e3:9.1f} ms   (first {compile_s:.1f}s)", flush=True)
    return dt


def col(arr):
    return ColumnVal(jnp.asarray(arr), None, None, BIGINT)


# ---- raw building blocks -------------------------------------------------
keys5 = [rng.integers(0, 1_500_000, N).astype(np.int64) for _ in range(5)]
vals = rng.integers(0, 50, N).astype(np.int64)
live = np.ones((N,), bool)

j_keys5 = [jnp.asarray(k) for k in keys5]
j_vals = jnp.asarray(vals)
j_live = jnp.asarray(live)

iota = jnp.arange(N, dtype=jnp.int32)


@jax.jit
def sort12(ks, live):
    ops = [(~live).astype(jnp.int8)]
    for k in ks:
        ops.append(jnp.zeros((N,), jnp.bool_))
        ops.append(k)
    return jax.lax.sort(ops + [iota], num_keys=len(ops))[-1]


@jax.jit
def sort2(k, live):
    ops = [(~live).astype(jnp.int8), k]
    return jax.lax.sort(ops + [iota], num_keys=2)[-1]


G4 = 4_194_304
G2 = 2_097_152


@jax.jit
def boundary(seg):
    gids = jnp.arange(G4, dtype=jnp.int32)
    starts = relops.searchsorted_tpu(seg, gids, side="left")
    ends = relops.searchsorted_tpu(seg, gids, side="right")
    return starts.sum() + ends.sum()


@jax.jit
def cumsum_diff(vals64, seg):
    ce = jnp.concatenate([jnp.zeros((1,), jnp.int64), jnp.cumsum(vals64)])
    gids = jnp.arange(G4, dtype=jnp.int32)
    starts = relops.searchsorted_tpu(seg, gids, side="left")
    ends = relops.searchsorted_tpu(seg, gids, side="right")
    return (jnp.take(ce, ends) - jnp.take(ce, starts)).sum()


seg_sorted = jnp.sort(rng.integers(0, G4, N).astype(np.int32))

timeit("lax.sort 11-operand 8M (5-key grouped sort)", sort12, j_keys5, j_live)
timeit("lax.sort 2-operand 8M (1-key sort)", sort2, j_keys5[0], j_live)
timeit("boundary searchsorted x2 (G=4M, n=8M)", boundary, seg_sorted)
timeit("cumsum+boundary diff sum (G=4M)", cumsum_diff, j_vals, seg_sorted)


# ---- full group_aggregate shapes ----------------------------------------
@jax.jit
def agg5(ks, v, live):
    kcols = [ColumnVal(k, None, None, BIGINT) for k in ks]
    out_keys, out_aggs, out_live, ng = relops.group_aggregate(
        kcols, [ColumnVal(v, None, None, BIGINT)],
        [relops.AggSpec("sum")], live, G4,
    )
    return out_aggs[0][0].sum() + ng


@jax.jit
def agg1(k, v, live):
    out_keys, out_aggs, out_live, ng = relops.group_aggregate(
        [ColumnVal(k, None, None, BIGINT)], [ColumnVal(v, None, None, BIGINT)],
        [relops.AggSpec("sum")], live, G2,
    )
    return out_aggs[0][0].sum() + ng


N6 = 6_291_456
k6 = jnp.asarray(rng.integers(0, 1_500_000, N6).astype(np.int64))
v6 = jnp.asarray(rng.integers(0, 50, N6).astype(np.int64))
l6 = jnp.ones((N6,), jnp.bool_)

timeit("group_aggregate 5 keys G=4M n=8M", agg5, j_keys5, j_vals, j_live)
timeit("group_aggregate 1 key G=2M n=6M", agg1, k6, v6, l6)


# ---- semi join shape -----------------------------------------------------
@jax.jit
def semi(probe_k, probe_live, build_k, build_live):
    cols, new_live, req = relops.equi_join(
        "semi",
        [ColumnVal(probe_k, None, None, BIGINT)], probe_live,
        [ColumnVal(build_k, None, None, BIGINT)], build_live,
        [ColumnVal(probe_k, None, None, BIGINT)],
        [ColumnVal(build_k, None, None, BIGINT)],
        None, 8_388_608,
    )
    return new_live.sum() + req


NP_, NB = 2_097_152, 4_194_304
pk = jnp.asarray(rng.integers(0, 1_500_000, NP_).astype(np.int64))
pl = jnp.asarray(np.arange(NP_) < 1_500_000)
bk = jnp.asarray(rng.integers(0, 1_500_000, NB).astype(np.int64))
bl = jnp.asarray(np.arange(NB) < 60)  # HAVING output: tiny live build

timeit("equi_join semi probe=2M build=4M C=8M", semi, pk, pl, bk, bl)
print("done", flush=True)
