#!/usr/bin/env python
"""Hard perf-regression gate over two consecutive BENCH_*.json runs.

Usage:
    python scripts/perf_gate.py OLD.json NEW.json [--wall-ratio 1.5]

Exit codes:
    0  no regression
    1  usage / unreadable input
    2  regression: the NEW run records warm_regressions absent from the
       OLD run, or a query's warm wall_s grew past --wall-ratio x OLD

Both the raw ``bench.py --json`` payload and the driver wrapper format
(``{"n": .., "cmd": .., "rc": .., "tail": .., "parsed": {...}}``) are
accepted — the gate reaches into ``parsed`` when present.

Gate semantics (deliberate):

* ``warm_regressions`` is compared as a *set of query names*: only
  regressions NEW introduces fail the gate.  An OLD file predating the
  field (PR-era formats without it) contributes the empty set — we do
  NOT recompute bounds from OLD's raw warm_s, because early runs carry
  cold-compile noise that would mask genuinely new regressions.
* The wall-ratio check only compares queries present in BOTH runs, so
  adding a query to the bench suite never trips the gate by itself.
* The ``multi_scale`` block (split-driven scale sweep, BENCH_MULTI_SCALE)
  is informational and never gated: its wall times come from a 2-worker
  HTTP cluster whose scheduling jitter dwarfs real regressions, and its
  invariance verdict is already enforced by tests/test_splits.py.
* The per-scale ``disk`` sub-block (spool/spill peak bytes, pressure
  reclaims, typed sheds — runtime/disk.py) is likewise informational
  with an unbounded tolerance: peak spool bytes scale with data size
  and split count, reclaim counts depend on GC timing, and a nonzero
  shed count is a *survivability* signal (retry rotated the work), not
  a perf regression.  The hard storage contracts live in
  tests/test_disk_governance.py; compare these numbers across runs by
  eye when tuning spool.disk-budget-bytes, never in this gate.
"""

from __future__ import annotations

import argparse
import json
import sys

DEFAULT_WALL_RATIO = 1.5
# ignore ratio blowups on sub-50ms walls: scheduler jitter, not perf
MIN_GATED_WALL_S = 0.05


def load(path: str) -> dict:
    """Parse one BENCH json, unwrapping the driver's {parsed: ...} shell."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a json object")
    return doc


def _regressed_queries(doc: dict) -> set[str]:
    out = set()
    for r in doc.get("warm_regressions") or []:
        if isinstance(r, dict) and r.get("query"):
            out.add(str(r["query"]))
        elif isinstance(r, str):
            out.add(r)
    return out


def compare(old: dict, new: dict, wall_ratio: float = DEFAULT_WALL_RATIO):
    """Return a list of human-readable failure strings (empty == pass)."""
    failures: list[str] = []

    fresh = _regressed_queries(new) - _regressed_queries(old)
    for q in sorted(fresh):
        detail = next(
            (
                r
                for r in new.get("warm_regressions") or []
                if isinstance(r, dict) and str(r.get("query")) == q
            ),
            {},
        )
        failures.append(
            f"new warm regression: {q} "
            f"(warm_s {detail.get('warm_s', '?')} > bound {detail.get('bound', '?')})"
        )

    # `multi_scale` (and any other top-level block) is deliberately not
    # consulted: the gate's contract is warm_regressions + queries only
    old_q = old.get("queries") or {}
    new_q = new.get("queries") or {}
    if isinstance(old_q, dict) and isinstance(new_q, dict):
        for q in sorted(set(old_q) & set(new_q)):
            ow = (old_q[q] or {}).get("wall_s")
            nw = (new_q[q] or {}).get("wall_s")
            if not isinstance(ow, (int, float)) or not isinstance(nw, (int, float)):
                continue
            if ow < MIN_GATED_WALL_S:
                continue
            if nw > ow * wall_ratio:
                failures.append(
                    f"wall regression: {q} wall_s {nw:.4f} > "
                    f"{wall_ratio:.2f}x old {ow:.4f}"
                )
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--wall-ratio", type=float, default=DEFAULT_WALL_RATIO)
    args = ap.parse_args(argv)
    try:
        old, new = load(args.old), load(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf_gate: cannot read input: {e}", file=sys.stderr)
        return 1
    failures = compare(old, new, args.wall_ratio)
    if failures:
        for f in failures:
            print(f"FAIL {f}")
        print(f"perf_gate: {len(failures)} regression(s) {args.old} -> {args.new}")
        return 2
    print(f"perf_gate: ok ({args.old} -> {args.new})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
