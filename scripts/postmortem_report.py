#!/usr/bin/env python3
"""Render a cross-node post-mortem bundle as one correlated timeline.

The coordinator writes a bundle (one JSONL file: header, QueryInfo,
journal records, then every node's flight-recorder slice) under the
spool dir on typed query failure, on sentinel-flagged anomalies, and on
demand via POST /v1/query/{id}/postmortem.  This script merges the
per-node lanes into a single wall-clock-ordered timeline with a lane
column per node and the failure/anomaly events highlighted — the
"what actually happened, across every machine, in order" view.

Usage:
    python scripts/postmortem_report.py PATH_OR_URL [--kinds k1,k2] [--limit N]

PATH_OR_URL is either the bundle file on disk
(<spool>/postmortem_<qid>/bundle.jsonl) or the coordinator's
GET /v1/query/{id}/postmortem URL.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

# event kinds that mark something going wrong — highlighted in the lane
FAILURE_KINDS = {
    "task_fail", "task_failed", "worker_dead", "compile_error",
    "disk_shed", "memory_revoke", "anomaly", "spool_reproduce",
}


def load_bundle(src: str) -> list[dict]:
    if src.startswith("http://") or src.startswith("https://"):
        with urllib.request.urlopen(src, timeout=10) as r:
            blob = r.read().decode("utf-8", errors="replace")
    else:
        with open(src, encoding="utf-8") as f:
            blob = f.read()
    recs = []
    for line in blob.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return recs


def _fmt_detail(ev: dict) -> str:
    drop = {"type", "seq", "kind", "node", "ts", "mono"}
    parts = []
    for k in ("query_id", "task_id", "trace_id"):
        v = ev.get(k)
        if v:
            parts.append(f"{k.split('_')[0]}={v}")
    for k, v in sorted((ev.get("detail") or {}).items()):
        if v is not None:
            parts.append(f"{k}={v}")
    for k, v in sorted(ev.items()):
        if k not in drop and k not in ("query_id", "task_id", "trace_id",
                                       "detail") and v is not None:
            parts.append(f"{k}={v}")
    return " ".join(parts)


def render(recs: list[dict], kinds=None, limit: int = 0) -> str:
    header = next((r for r in recs if r.get("type") == "header"), {})
    qinfo = next((r for r in recs if r.get("type") == "query_info"), {})
    journal = [r for r in recs if r.get("type") == "journal"]
    events = [r for r in recs if r.get("type") == "event"]
    if kinds:
        events = [e for e in events if e.get("kind") in kinds]

    out: list[str] = []
    qid = header.get("query_id", "?")
    out.append(f"POST-MORTEM  {qid}")
    out.append(
        f"  trigger: {header.get('trigger')}   state: {header.get('state')}"
        f"   events: {header.get('events')}"
        + (f" (+{header['events_dropped']} dropped over budget)"
           if header.get("events_dropped") else "")
    )
    if header.get("error"):
        out.append(f"  error: {header['error']}")
    for a in header.get("anomalies") or []:
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(a.items()) if k != "kind"
        )
        out.append(f"  anomaly: {a.get('kind')}" + (f" ({detail})" if detail else ""))
    if header.get("sql"):
        out.append(f"  sql: {header['sql'][:160]}")
    ledger = qinfo.get("phase_ledger") or {}
    if ledger:
        out.append(
            "  phases: "
            + ", ".join(
                f"{k[:-3]} {v:.0f}ms" for k, v in ledger.items()
                if isinstance(v, (int, float)) and k.endswith("_ms") and v
            )
        )

    # lane assignment: every node that emitted an event gets a column
    lanes: list[str] = []
    for ev in events:
        n = ev.get("node") or "?"
        if n not in lanes:
            lanes.append(n)
    out.append("")
    out.append(f"NODE LANES ({len(lanes)})")
    for i, n in enumerate(lanes):
        count = sum(1 for e in events if (e.get("node") or "?") == n)
        dead = " [unreachable at bundle time]" if n in (
            header.get("unreachable_nodes") or []
        ) else ""
        out.append(f"  lane {i}: {n}  ({count} events){dead}")
    for n in header.get("unreachable_nodes") or []:
        if n not in lanes:
            out.append(f"  (no lane): {n}  [unreachable, slice missing]")

    # merged timeline: wall-clock order across processes (seq breaks ties
    # inside one process's ring)
    events.sort(key=lambda e: (e.get("ts") or 0.0, e.get("seq") or 0))
    t0 = events[0].get("ts") if events else 0.0
    if limit and len(events) > limit:
        out.append(f"  ... showing last {limit} of {len(events)} events")
        events = events[-limit:]
    out.append("")
    out.append("TIMELINE")
    width = max((len(k) for k in (e.get("kind", "") for e in events)), default=10)
    for ev in events:
        lane_i = lanes.index(ev.get("node") or "?")
        glyphs = "".join(
            ("●" if i == lane_i else "│") for i in range(len(lanes))
        )
        mark = "!" if ev.get("kind") in FAILURE_KINDS else " "
        dt = (ev.get("ts") or t0) - t0
        out.append(
            f"{mark} t+{dt:8.3f}s {glyphs} {ev.get('kind', '?'):<{width}}"
            f"  {_fmt_detail(ev)}"
        )
    if journal:
        out.append("")
        out.append(f"JOURNAL ({len(journal)} records)")
        for j in journal:
            extras = {
                k: v for k, v in j.items()
                if k not in ("type", "kind", "query_id", "ts", "session")
                and v is not None
            }
            out.append(
                f"  {j.get('kind', '?'):<10} "
                + " ".join(f"{k}={v}" for k, v in sorted(extras.items()))
            )
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="postmortem_report")
    ap.add_argument("bundle", help="bundle.jsonl path or coordinator URL")
    ap.add_argument("--kinds", default="", help="comma-separated kind filter")
    ap.add_argument(
        "--limit", type=int, default=0, help="show only the last N events"
    )
    args = ap.parse_args(argv)
    recs = load_bundle(args.bundle)
    if not recs:
        print(f"no records in {args.bundle}", file=sys.stderr)
        return 1
    kinds = {k.strip() for k in args.kinds.split(",") if k.strip()} or None
    print(render(recs, kinds=kinds, limit=args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
