#!/usr/bin/env bash
# Observability smoke: spins a 2-worker cluster and asserts the whole
# observability plane end to end — distributed EXPLAIN ANALYZE with
# per-operator [rows, ms] annotations on every stage, the phase ledger
# and compile-signature attribution, Prometheus /metrics on coordinator
# AND workers (linted against the README via scripts/metrics_lint.py),
# the /v1/query listing + /v1/query/{id} QueryInfo endpoints with the
# history fallback after expiry, traceparent propagation into worker
# task spans, and the storage-governance plane — trino_tpu_disk_pool_*
# gauges on governed workers and a nonzero
# trino_tpu_spool_reproductions_total after SPOOL_LOST injection (the
# self-healing spool actually healing), plus the post-mortem plane —
# nonzero trino_tpu_flightrecorder_events_total, GET /v1/flightrecorder
# on both node roles, a seeded SLOW re-run carrying the `-- anomaly:`
# EXPLAIN ANALYZE footer, and the auto + on-demand post-mortem bundle
# round-trip over GET/POST /v1/query/{id}/postmortem, and the
# transactional write plane — a DML through the staged-commit protocol
# must carry the `-- txn:` footer and a nonzero
# trino_tpu_write_txn_total{outcome="committed"} counter, and the
# partition-tolerance plane — the cluster link matrix served on
# /v1/info (consumer -> producer -> grade) and a nonzero
# trino_tpu_hedged_fetches_total{outcome="won"} under an injected
# GRAY_SLOW producer (the hedged spool fetch actually racing), and the
# telemetry observatory — GET /v1/timeseries on both roles (federated
# cluster view on the coordinator, own-lane-only on workers) with a
# nonzero cpu series, `-- roofline:` / `-- device bandwidth:` /
# `-- exchange:` footers on the distributed EXPLAIN ANALYZE, and moving
# trino_tpu_exchange_bytes_total{direction} counters.
#
# Fast enough to run on every runtime/ or exec/ change; the same checks
# run under the tier-1 gate via tests/test_obs_plane.py.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import urllib.request

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.testing.runner import DistributedQueryRunner

SQL = ("select l_returnflag, count(*) c from lineitem "
       "where l_quantity < 30 group by l_returnflag order by c desc")


def get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.read().decode()


# a disk budget gives every worker a governed NodeDiskPool, so the
# trino_tpu_disk_pool_* gauges below have something to report
runner = DistributedQueryRunner(num_workers=2, disk_budget_bytes=64 << 20)
runner.register_catalog("tpch", TpchConnector(0.01))
runner.start()
try:
    rows = runner.query("explain analyze " + SQL)
    text = "\n".join(r[0] for r in rows)
    print(text)
    print()

    assert text.count("Fragment") >= 2, "expected a multi-stage plan"
    assert "-- cache:" in text, "expected the result-cache footer"
    bare = [
        ln for ln in text.splitlines()
        if ln.strip() and not ln.lstrip().startswith(("Fragment", "--", "wall:", "tasks:"))
        and "[rows:" not in ln
    ]
    assert not bare, f"operator lines missing stats: {bare}"
    assert "slowest operator:" in text and "cluster cpu:" in text

    coord = runner.coordinator
    base = coord.url

    with coord._lock:
        # newest record (insertion-ordered dict): the inner distributed
        # query the EXPLAIN ANALYZE statement ran
        qid = list(coord.queries)[-1]

    # result-cache plane: admit immediately, run the hot query twice, and
    # the hit counter must move (runtime/resultcache.py)
    coord.session.set("result_cache_min_recurrences", "0")
    runner.query(SQL)
    runner.query(SQL)

    # serving fast path (runtime/fastpath.py): PREPARE over the protocol,
    # EXECUTE twice with distinct parameters — miss then hit on the
    # parameterized plan cache — and EXPLAIN ANALYZE EXECUTE must carry
    # the `-- fastpath:` footer with the cache disposition
    from trino_tpu.client import StatementClient

    sc = StatementClient(base)
    sc.execute("PREPARE obs_fp FROM select l_returnflag, count(*) c "
               "from lineitem where l_quantity < ? group by l_returnflag "
               "order by l_returnflag")
    assert "obs_fp" in sc.prepared, "addedPrepare delta not applied"
    sc.execute("EXECUTE obs_fp USING 10.0")
    sc.execute("EXECUTE obs_fp USING 20.0")
    _, fprows = sc.execute("EXPLAIN ANALYZE EXECUTE obs_fp USING 20.0")
    fptext = "\n".join(r[0] for r in fprows)
    fplines = [ln for ln in fptext.splitlines() if ln.startswith("-- fastpath:")]
    assert fplines, f"expected a fastpath footer:\n{fptext}"
    assert "plan_cache=hit" in fplines[0], fplines
    print(f"fastpath: {fplines[0]}")

    mtext = get(base + "/metrics")
    assert "trino_tpu_queries_total" in mtext
    assert "trino_tpu_tasks_dispatched_total" in mtext
    hit_lines = [
        ln for ln in mtext.splitlines()
        if ln.startswith('trino_tpu_result_cache_events_total{event="hit"}')
    ]
    assert hit_lines and float(hit_lines[0].split()[-1]) > 0, (
        f"expected a nonzero result-cache hit counter: {hit_lines}"
    )
    print(f"coordinator /metrics: {len(mtext.splitlines())} lines ok "
          f"(result cache hits: {hit_lines[0].split()[-1]})")

    pc_hits = [
        ln for ln in mtext.splitlines()
        if ln.startswith('trino_tpu_plan_cache_events_total{event="hit"}')
    ]
    assert pc_hits and float(pc_hits[0].split()[-1]) > 0, (
        f"expected a nonzero plan-cache hit counter: {pc_hits}"
    )
    print(f"plan cache hits: {pc_hits[0].split()[-1]}")

    for w in runner.workers:
        wtext = get(f"{w.url}/metrics")
        assert "trino_tpu_worker_tasks_total" in wtext
        print(f"worker {w.url} /metrics: {len(wtext.splitlines())} lines ok")

    # documented-vs-exposed drift gate (scripts/metrics_lint.py): every
    # exposed family must carry HELP text and every README-documented
    # metric must be exposed by coordinator or a worker
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "metrics_lint", os.path.join("scripts", "metrics_lint.py"))
    mlint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mlint)
    targets = [base + "/metrics"] + [w.url + "/metrics" for w in runner.workers]
    failures = mlint.lint(targets, "README.md")
    assert not failures, f"metrics lint: {failures}"
    print(f"metrics_lint: {len(targets)} targets clean")

    # telemetry observatory (utils/timeseries.py + utils/roofline.py):
    # the distributed EXPLAIN ANALYZE must carry the roofline attribution
    # footers, GET /v1/timeseries must answer on BOTH roles (coordinator
    # federated, worker own-lane-only) with a nonzero cpu series, and the
    # per-link exchange accounting must move the direction-labelled
    # exchange byte counters on the workers
    rooflines = [ln for ln in text.splitlines() if ln.startswith("-- roofline:")]
    assert rooflines, f"expected roofline footers:\n{text[-800:]}"
    assert any("% of" in ln for ln in rooflines), rooflines
    devlines = [ln for ln in text.splitlines()
                if ln.startswith("-- device bandwidth:")]
    assert devlines, "expected the query-wide device bandwidth footer"
    exlines = [ln for ln in text.splitlines() if ln.startswith("-- exchange:")]
    assert exlines, "expected per-stage exchange throughput footers"
    print(f"roofline: {rooflines[0]}")
    print(f"exchange: {exlines[0]}")

    import time as _t
    want_nodes = {base} | {w.url for w in runner.workers}
    ts_deadline = _t.monotonic() + 15  # default 1 s ticks: allow a few
    while _t.monotonic() < ts_deadline:
        tsp = json.loads(get(base + "/v1/timeseries"))
        nodes = tsp.get("nodes") or {}
        if want_nodes <= set(nodes) and all(
            "cpu_s" in nodes[n] for n in want_nodes
        ):
            break
        _t.sleep(0.5)
    assert want_nodes <= set(nodes), (
        f"coordinator never federated all lanes: {sorted(nodes)}"
    )
    assert sum(v for _, v in nodes[base]["cpu_s"]) > 0, (
        "coordinator cpu_s series is all-zero"
    )
    wts = json.loads(get(runner.workers[0].url + "/v1/timeseries"))
    assert wts["node"] == runner.workers[0].url
    assert "cpu_s" in (wts.get("series") or {}), "worker lane missing cpu_s"
    print(f"/v1/timeseries: {len(nodes)} node lanes federated, "
          f"worker serves its own lane ok")

    exch_vals = []
    for w in runner.workers:
        for ln in get(f"{w.url}/metrics").splitlines():
            if ln.startswith("trino_tpu_exchange_bytes_total{"):
                exch_vals.append(float(ln.split()[-1]))
    assert exch_vals and max(exch_vals) > 0, (
        f"exchange byte counters did not move: {exch_vals}"
    )
    print(f"exchange_bytes_total: {len(exch_vals)} samples, "
          f"max {max(exch_vals):.0f} B")

    info = json.loads(get(f"{base}/v1/query/{qid}"))
    assert info["stage_count"] >= 2 and info["cpu_ms"] > 0
    ledger = info.get("phase_ledger") or {}
    assert ledger.get("executing_ms", 0) >= 0 and "compiling_ms" in ledger
    assert info.get("compile_signatures"), "expected named jit signatures"
    print(f"/v1/query/{qid}: {info['stage_count']} stages, "
          f"cpu {info['cpu_ms']:.0f} ms, "
          f"compile {ledger.get('compiling_ms', 0):.0f} ms ok")

    listing = json.loads(get(base + "/v1/query"))["queries"]
    assert any(q["query_id"] == qid for q in listing), "listing misses query"
    print(f"/v1/query: {len(listing)} queries listed")

    # history survives expiry: force-expire the live record, then the
    # /v1/query/{id} fallback must serve it from the history store
    coord.expire_query(qid)
    info2 = json.loads(get(f"{base}/v1/query/{qid}"))
    assert info2.get("expired"), "expected history fallback after expiry"
    listing2 = json.loads(get(base + "/v1/query"))["queries"]
    assert any(q["query_id"] == qid for q in listing2), "history not listed"
    print(f"/v1/query/{qid} after expiry: served from history ok")

    # data-plane kernel dispatch: a GROUP BY over a non-dictionary key run
    # in interpret mode must select the Pallas hash kernel — visible both
    # as an EXPLAIN ANALYZE `-- kernel:` footer line and as a
    # trino_tpu_kernel_dispatch_total{op="group_by",impl="pallas"} count
    from trino_tpu.ops import kernels as _kernels
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    eng.session.set("pallas_interpret", "true")
    before = _kernels._DISPATCH.value("group_by", "pallas")
    krows = eng.execute(
        "EXPLAIN ANALYZE select l_suppkey, sum(l_extendedprice) "
        "from lineitem group by l_suppkey"
    )
    ktext = "\n".join(str(r[0]) for r in krows)
    klines = [ln for ln in ktext.splitlines() if ln.startswith("-- kernel:")]
    assert any("pallas group_by" in ln for ln in klines), (
        f"expected a Pallas group_by dispatch line: {klines}"
    )
    after = _kernels._DISPATCH.value("group_by", "pallas")
    assert after > before, "kernel dispatch counter did not move"
    print(f"kernel dispatch: {klines[0]} (counter {before:.0f} -> {after:.0f})")

    # split-driven scan plane (runtime/splits.py): with split_driven_scans
    # on, a scan must morselize — visible as the `-- splits:` EXPLAIN
    # ANALYZE footer and a nonzero trino_tpu_splits_total on /metrics
    import tempfile as _tf
    coord.session.set("retry_policy", "TASK")
    coord.session.set("exchange_spool_dir",
                      _tf.mkdtemp(prefix="obs_split_spool_"))
    coord.session.set("split_driven_scans", "true")
    coord.session.set("split_target_rows", "8192")
    srows = runner.query("explain analyze " + SQL)
    stext = "\n".join(r[0] for r in srows)
    slines = [ln for ln in stext.splitlines() if ln.startswith("-- splits:")]
    assert slines, f"expected a splits footer:\n{stext[-800:]}"
    print(f"splits: {slines[0]}")
    smtext2 = get(base + "/metrics")
    done = [
        ln for ln in smtext2.splitlines()
        if ln.startswith('trino_tpu_splits_total{state="completed"}')
    ]
    assert done and float(done[0].split()[-1]) > 0, (
        f"expected a nonzero completed-splits counter: {done}"
    )
    print(f"splits completed counter: {done[0].split()[-1]}")
    coord.session.set("split_driven_scans", "false")

    # storage-governance plane (runtime/disk.py + the self-healing spool):
    # every governed worker must expose the disk-pool gauges, and a
    # SPOOL_LOST injection on a committed partition must drive a producer
    # reproduction — visible as a nonzero spool_reproductions_total
    for w in runner.workers:
        wtext = get(f"{w.url}/metrics")
        cap = [
            ln for ln in wtext.splitlines()
            if ln.startswith("trino_tpu_disk_pool_capacity_bytes{")
        ]
        assert cap and float(cap[0].split()[-1]) > 0, (
            f"expected a governed disk pool on {w.url}: {cap}"
        )
    print(f"disk pool gauges: {len(runner.workers)} workers governed ok")

    for i in range(len(runner.workers)):
        runner.inject_task_failure(i, mode="SPOOL_LOST")
    runner.query("select l_linestatus, sum(l_quantity) from lineitem "
                 "group by l_linestatus order by l_linestatus")
    for w in runner.workers:
        w.fault_injector.clear()
    mtext3 = get(base + "/metrics")
    repro = [
        ln for ln in mtext3.splitlines()
        if ln.startswith("trino_tpu_spool_reproductions_total")
        and not ln.startswith("#")
    ]
    assert repro and float(repro[0].split()[-1]) > 0, (
        f"expected a nonzero spool-reproduction counter: {repro}"
    )
    print(f"spool reproductions counter: {repro[0].split()[-1]}")

    # partition-tolerance plane (runtime/health.py): a GRAY_SLOW producer
    # (correct pages, 800 ms late, zero errors) must drive the hedged
    # spool fetch — the won counter moves — and the consumer-side link
    # matrix must surface on the coordinator's /v1/info via heartbeats
    def _hedged_won() -> float:
        vals = []
        for w in runner.workers:
            for ln in get(f"{w.url}/metrics").splitlines():
                if ln.startswith(
                    'trino_tpu_hedged_fetches_total{outcome="won"}'
                ):
                    vals.append(float(ln.split()[-1]))
        return max(vals) if vals else 0.0  # process-global: any node's view

    won_before = _hedged_won()
    # slow EVERY producer: with 2 workers the plan may place the whole
    # partial stage on either one, so a single-producer fault can miss
    # the one link the final stage actually fetches over
    for wi in range(len(runner.workers)):
        runner.gray_slow(producer_index=wi, delay_ms=800)
    runner.query("select l_suppkey, count(*) from lineitem "
                 "group by l_suppkey order by l_suppkey")
    for w in runner.workers:
        w.fault_injector.clear()
    won_after = _hedged_won()
    assert won_after > won_before, (
        f"hedged won counter did not move under GRAY_SLOW: "
        f"{won_before} -> {won_after}"
    )
    print(f"hedged fetches won counter: {won_before:.0f} -> {won_after:.0f}")

    import time as _time
    links = {}
    lm_deadline = _time.monotonic() + 15  # next heartbeat folds the rows
    while _time.monotonic() < lm_deadline:
        links = json.loads(get(base + "/v1/info")).get("links") or {}
        if links:
            break
        _time.sleep(0.5)
    assert links, "expected a cluster link matrix on /v1/info"
    cells = [
        (c, p, cell.get("state"))
        for c, row in links.items() for p, cell in row.items()
    ]
    assert all(s in ("HEALTHY", "DEGRADED", "SUSPECT", "DEAD")
               for _, _, s in cells), cells
    print(f"/v1/info link matrix: {len(links)} consumer rows, "
          f"{len(cells)} links graded ok")

    # flight-recorder plane (utils/flightrecorder.py): the event counter
    # must have moved, and both node roles must serve their ring slice
    mtext4 = get(base + "/metrics")
    frlines = [
        ln for ln in mtext4.splitlines()
        if ln.startswith("trino_tpu_flightrecorder_events_total{")
    ]
    assert frlines and sum(float(ln.split()[-1]) for ln in frlines) > 0, (
        f"expected nonzero flight-recorder event counters: {frlines[:3]}"
    )
    print(f"flightrecorder: {len(frlines)} event kinds counted")
    with coord._lock:
        fr_qid = list(coord.queries)[-1]
    fr = json.loads(get(f"{base}/v1/flightrecorder?query_id={fr_qid}"))
    assert fr["events"], "coordinator flight-recorder slice is empty"
    wfr = json.loads(get(f"{runner.workers[0].url}/v1/flightrecorder"
                         f"?query_id={fr_qid}"))
    assert all(e["node"] in (runner.workers[0].url,
                             f"worker:{runner.workers[0].port}")
               for e in wfr["events"]), "worker served another node's lane"
    print(f"GET /v1/flightrecorder: coord {len(fr['events'])} events, "
          f"worker {len(wfr['events'])} events ok")

    # anomaly sentinel + post-mortem: one clean baseline run, then a
    # seeded SLOW re-run must carry the `-- anomaly:` EXPLAIN ANALYZE
    # footer and auto-write a bundle; the on-demand POST must round-trip
    coord.session.set("result_cache_enabled", "false")
    coord.session.set("anomaly_min_samples", "1")
    ANOM_SQL = ("explain analyze select l_shipmode, count(*) c "
                "from lineitem group by l_shipmode order by l_shipmode")
    # warm the plan's jit signatures first (plain select: different
    # baseline key, so this run is NOT a baseline sample) — otherwise
    # first-compile cost inflates the clean baseline and the seeded SLOW
    # run lands right at the 2x anomaly factor instead of far past it
    runner.query("select l_shipmode, count(*) c from lineitem "
                 "group by l_shipmode order by l_shipmode")
    runner.query(ANOM_SQL)  # clean run -> baseline sample
    for i in range(len(runner.workers)):
        runner.inject_task_failure(i, task_id="*", mode="SLOW",
                                   delay_ms=2500, count=10)
    arows = runner.query(ANOM_SQL)
    for w in runner.workers:
        w.fault_injector.clear()
    atext = "\n".join(r[0] for r in arows)
    alines = [ln for ln in atext.splitlines() if ln.startswith("-- anomaly:")]
    assert any("SLOW_VS_BASELINE" in ln for ln in alines), (
        f"expected a SLOW_VS_BASELINE anomaly footer:\n{atext[-600:]}"
    )
    print(f"anomaly: {alines[0]}")
    with coord._lock:
        anom_qid = list(coord.queries)[-1]
    bundle = get(f"{base}/v1/query/{anom_qid}/postmortem")
    header = json.loads(bundle.splitlines()[0])
    assert header["type"] == "header" and header["query_id"] == anom_qid
    assert header["anomalies"], "auto-bundle missing the anomaly"
    req = urllib.request.Request(
        f"{base}/v1/query/{anom_qid}/postmortem", data=b"{}")
    with urllib.request.urlopen(req, timeout=30) as resp:
        pm = json.loads(resp.read())
    assert pm["trigger"] == "on_demand" and pm["events"] > 0
    amtext = get(base + "/metrics")
    assert 'trino_tpu_query_anomalies_total{kind="SLOW_VS_BASELINE"}' in amtext
    assert 'trino_tpu_postmortem_bundles_total{trigger="anomaly"}' in amtext
    print(f"postmortem: bundle {pm['events']} events from "
          f"{len(pm['nodes'])} nodes ok")
finally:
    runner.stop()

# ---------------------------------------------------------------- fleet plane
# two-coordinator fleet behind the router: kill the query's owner mid-flight
# and assert the failover observability — a nonzero
# trino_tpu_fleet_adoptions_total on the survivor and the `-- fleet:` footer
# on the adopted EXPLAIN ANALYZE (runtime/fleet.py)
import os
import tempfile
import threading
import time

import numpy as np

from trino_tpu.client import StatementClient
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT


class GatedMemoryConnector(MemoryConnector):
    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.gated_table = None

    def read_split(self, split, columns):
        if split.table == self.gated_table:
            assert self.gate.wait(timeout=120), "gate never opened"
        return super().read_split(split, columns)


conn = GatedMemoryConnector()
conn.create_table("build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)])
conn.insert("build", {"k": np.arange(50, dtype=np.int64),
                      "w": np.arange(50, dtype=np.int64) * 10})
conn.create_table("probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
conn.insert("probe", {"k": np.arange(2000, dtype=np.int64) % 50,
                      "v": np.arange(2000, dtype=np.int64)})

spool = tempfile.mkdtemp(prefix="obs_fleet_spool_")
fleet = DistributedQueryRunner(
    num_workers=2, default_catalog="memory", heartbeat_interval=0.3,
    num_coordinators=2, fleet_ttl_s=1.5,
)
fleet.register_catalog("memory", conn)
fleet.start()
try:
    for c in fleet.coordinators:
        c.session.set("retry_policy", "TASK")
        c.session.set("exchange_spool_dir", spool)
        c.session.set("resume_policy", "RESUME")

    FLEET_SQL = ("explain analyze select sum(v + w) from probe, build "
                 "where probe.k = build.k")
    conn.gated_table = "probe"

    class _Rider(threading.Thread):
        def __init__(self):
            super().__init__(daemon=True)
            self.client = StatementClient(fleet.client_url,
                                          reattach_max_elapsed_s=90.0)
            self.result = None
            self.error = None

        def run(self):
            try:
                self.result = self.client.execute(FLEET_SQL, timeout=120)
            except Exception as e:
                self.error = e

    rider = _Rider()
    rider.start()
    deadline = time.monotonic() + 60
    committed = lambda: any(
        os.path.exists(os.path.join(spool, n, "COMMITTED"))
        for n in (os.listdir(spool) if os.path.isdir(spool) else [])
    )
    while time.monotonic() < deadline and not committed():
        time.sleep(0.05)
    assert committed(), "build stage never spool-committed"

    owner = None
    for i, c in enumerate(fleet.coordinators):
        with c._lock:
            if any(not rec["done"].is_set() for rec in c.queries.values()):
                owner = i
    assert owner is not None, "no coordinator owns the in-flight query"
    fleet.kill_coordinator(owner)
    conn.gate.set()
    rider.join(timeout=120)
    assert rider.error is None, f"client saw a failure: {rider.error!r}"

    ftext = "\n".join(row[0] for row in rider.result[1])
    flt_lines = [ln for ln in ftext.splitlines() if ln.startswith("-- fleet:")]
    assert flt_lines and "adopted from" in flt_lines[0], (
        f"expected a fleet adoption footer:\n{ftext[-800:]}"
    )
    print(f"fleet: {flt_lines[0]}")

    survivor = fleet.coordinators[1 - owner]
    smtext = get(survivor.url + "/metrics")
    ad = [ln for ln in smtext.splitlines()
          if ln.startswith("trino_tpu_fleet_adoptions_total")
          and not ln.startswith("#")]
    assert ad and float(ad[0].split()[-1]) >= 1, (
        f"expected a nonzero adoption counter: {ad}"
    )
    assert 'trino_tpu_fleet_lease_transitions_total{event="expire"}' in smtext
    print(f"fleet adoptions counter: {ad[0].split()[-1]}")

    sinfo = json.loads(get(survivor.url + "/v1/info"))
    assert sinfo.get("fleet", {}).get("members"), "fleet info missing members"
    ui = get(survivor.url + "/ui")
    assert "origin" in ui, "/ui missing the fleet origin column"
    print(f"fleet /v1/info + /ui: "
          f"{len(sinfo['fleet']['members'])} members listed ok")

    # transactional write plane (runtime/txn.py): a DML through the
    # staged-commit protocol must carry the `-- txn:` EXPLAIN ANALYZE
    # footer and bump trino_tpu_write_txn_total{outcome="committed"}
    wrows = survivor.execute_query(
        "explain analyze insert into build select k + 1000, w from build")
    wtext = "\n".join(row[0] for row in wrows)
    wlines = [ln for ln in wtext.splitlines() if ln.startswith("-- txn:")]
    assert wlines and "outcome=committed" in wlines[0], (
        f"expected a committed txn footer:\n{wtext[-600:]}"
    )
    print(f"write txn: {wlines[0]}")
    wmtext = get(survivor.url + "/metrics")
    wc = [
        ln for ln in wmtext.splitlines()
        if ln.startswith('trino_tpu_write_txn_total{outcome="committed"}')
    ]
    assert wc and float(wc[0].split()[-1]) > 0, (
        f"expected a nonzero committed write-txn counter: {wc}"
    )
    print(f"write txn committed counter: {wc[0].split()[-1]}")
    print("OBS_SMOKE_OK")
finally:
    conn.gate.set()
    fleet.stop()
EOF
