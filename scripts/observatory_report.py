#!/usr/bin/env python3
"""Render the cluster telemetry observatory as text: per-node utilization
timelines (the /v1/timeseries federated view) plus the per-query roofline
table (achieved GB/s / GFLOP/s / %-of-roofline per executed signature).

Two sources:

- a LIVE coordinator URL — fetches GET /v1/timeseries for the cluster
  lanes and GET /v1/query?limit=N for recent queries' roofline figures
- a SAVED post-mortem bundle (bundle.jsonl) — reads the embedded
  ``type: timeseries`` slice and the bundle's QueryInfo

Usage:
    python scripts/observatory_report.py http://coordinator:8080
    python scripts/observatory_report.py <spool>/postmortem_<qid>/bundle.jsonl
    ... [--series cpu_s,rss_bytes] [--since SECS_AGO] [--width 60]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

# sparkline glyphs, lowest to highest
_TICKS = " .:-=+*#%@"


def _spark(points: list, width: int) -> str:
    """Values -> a fixed-width character sparkline (last `width` points)."""
    vals = [float(p[1]) for p in points][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(
        _TICKS[min(len(_TICKS) - 1, int((v - lo) / span * (len(_TICKS) - 1)))]
        for v in vals
    )


def _fmt_val(series: str, v: float) -> str:
    if series.endswith("_bytes"):
        return f"{v / (1 << 20):.1f}M" if v >= 1 << 20 else f"{v:.0f}B"
    if series == "cpu_s":
        return f"{v:.2f}s"
    return f"{v:g}"


def render_timeline(nodes: dict, series: list | None, width: int) -> list[str]:
    """{node: {series: [[ts, v], ...]}} -> per-node sparkline lanes."""
    out = []
    for node in sorted(nodes):
        out.append(f"node {node}")
        lanes = nodes[node] or {}
        for name in sorted(lanes):
            if series and name not in series:
                continue
            pts = lanes[name] or []
            if not pts:
                continue
            last = _fmt_val(name, float(pts[-1][1]))
            peak = _fmt_val(name, max(float(p[1]) for p in pts))
            out.append(
                f"  {name:<22} |{_spark(pts, width):<{width}}| "
                f"last {last}, peak {peak}, n={len(pts)}"
            )
        out.append("")
    return out


def render_roofline(queries: list[dict]) -> list[str]:
    """Recent queries' roofline tables (QueryInfo roofline + exchange)."""
    out = []
    for q in queries:
        roof = q.get("roofline") or {}
        sigs = roof.get("signatures") or []
        if not sigs and q.get("device_gb_per_sec") is None:
            continue
        dev = roof.get("device") or {}
        hdr = f"query {q.get('query_id', '?')}"
        if q.get("device_gb_per_sec") is not None:
            hdr += f"  device {q['device_gb_per_sec']:.3f} GB/s"
        if dev.get("hbm_gbps"):
            hdr += (
                f"  (roofline {dev['hbm_gbps']:g} GB/s"
                f" {dev.get('device_kind', '?')}, {dev.get('source', '?')})"
            )
        out.append(hdr)
        if sigs:
            out.append(
                f"  {'signature':<32} {'exec':>5} {'ms':>9} "
                f"{'GFLOP/s':>9} {'GB/s':>8} {'%roof':>6}"
            )
            for s in sigs:
                out.append(
                    f"  {s.get('signature', '?'):<32} "
                    f"{s.get('executes', 0):>5} "
                    f"{s.get('execute_ms', 0.0):>9.1f} "
                    f"{s.get('gflop_per_sec', 0.0):>9.3f} "
                    f"{s.get('gb_per_sec', 0.0):>8.3f} "
                    f"{s.get('pct_of_roofline', 0.0):>5.1f}%"
                )
        for st in q.get("exchange") or []:
            if not st.get("bytes"):
                continue
            rate = st.get("gb_per_sec")
            out.append(
                f"  exchange stage {st.get('stage_id')}: "
                f"{st.get('bytes', 0)} B / {st.get('wall_ms', 0.0):.1f} ms"
                + (f" = {rate:.3f} GB/s" if rate is not None else "")
                + f" over {len(st.get('links') or {})} link(s)"
            )
        out.append("")
    return out


def from_live(base: str, since: float | None, series: list | None) -> tuple:
    url = base.rstrip("/") + "/v1/timeseries"
    q = []
    if since is not None:
        q.append(f"since={time.time() - since}")
    if series:
        q.append("series=" + ",".join(series))
    if q:
        url += "?" + "&".join(q)
    with urllib.request.urlopen(url, timeout=10) as r:
        nodes = (json.loads(r.read()) or {}).get("nodes") or {}
    with urllib.request.urlopen(
        base.rstrip("/") + "/v1/query?limit=10", timeout=10
    ) as r:
        listing = json.loads(r.read())
    queries = listing if isinstance(listing, list) else (
        listing.get("queries") or []
    )
    # the listing may be shallow — fetch full records for roofline fields
    full = []
    for q_ in queries:
        qid = q_.get("query_id") if isinstance(q_, dict) else None
        if qid and "roofline" not in (q_ or {}):
            try:
                with urllib.request.urlopen(
                    base.rstrip("/") + f"/v1/query/{qid}", timeout=10
                ) as r:
                    full.append(json.loads(r.read()))
                continue
            except OSError:
                pass
        full.append(q_)
    return nodes, full


def from_bundle(path: str) -> tuple:
    nodes, queries = {}, []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "timeseries":
                nodes = rec.get("nodes") or {}
            elif rec.get("type") == "query_info":
                queries.append(rec)
    return nodes, queries


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="observatory_report.py")
    ap.add_argument("source", help="coordinator URL or bundle.jsonl path")
    ap.add_argument("--series", default=None,
                    help="comma-separated series filter (e.g. cpu_s,rss_bytes)")
    ap.add_argument("--since", type=float, default=None,
                    help="live mode: only points newer than SECS ago")
    ap.add_argument("--width", type=int, default=60,
                    help="sparkline width in characters")
    args = ap.parse_args(argv)
    series = [s for s in (args.series or "").split(",") if s] or None

    if args.source.startswith(("http://", "https://")):
        nodes, queries = from_live(args.source, args.since, series)
    else:
        nodes, queries = from_bundle(args.source)

    lines = ["== cluster timeline =="]
    lines += render_timeline(nodes, series, max(10, args.width))
    lines.append("== roofline attribution ==")
    roof = render_roofline(queries)
    lines += roof or ["(no queries with roofline figures)"]
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
