#!/usr/bin/env bash
# Chaos tier: TPC-H under seeded random fault schedules (ERROR / TIMEOUT /
# SLOW / EXCHANGE_DROP) on a retry_policy=TASK cluster, diffed against the
# sqlite oracle.  Deterministic: a failing schedule replays from its seed
# (tests/test_chaos.py::SEED).
#
# Subcommands (lifecycle chaos, tests/test_lifecycle.py):
#   drain   graceful drain mid-query — zero retries, zero quarantine
#   kill9   hard kill mid-query — recovery only via TASK retry from spool
# Memory-governance chaos (tests/test_memory_governance.py):
#   corrupt page-frame corruption mid-fetch — crc32 detect + token re-fetch
#   oom     MEMORY_PRESSURE pool shrink / blocked-on-memory / low-memory
#           killer / revocation-driven spill scenarios
# Compile-plane chaos (tests/test_compile_resilience.py):
#   compile COMPILE_SLOW / COMPILE_FAIL on cluster tasks — queries must
#           succeed via fallback, breaker stops churn, no hangs
# Coordinator-crash chaos (tests/test_recovery.py):
#   coordinator  kill the coordinator mid multi-stage query — journal
#                replay resumes it, committed stages re-read from the
#                spool (zero recompute), clients ride nextUri through
#                the restart, orphan tasks swept, spool GC'd
# Result-cache chaos (tests/test_result_cache.py):
#   cache   hot cached query under DML + worker kill + coordinator
#           restart — typed invalidation and the cold-restart contract
#           mean no step may ever return a stale row
# Split-driven scan chaos (tests/test_splits.py):
#   splits  worker kill mid-scan on a split-scheduling cluster — only the
#           LOST morsels re-read (split retries < total splits), committed
#           morsels served from the spool, zero client-visible failures;
#           plus SPLIT_LOST injection, the jit-signature scale-invariance
#           witness for tpch q01/q06 at two data scales, and the at-scale
#           kill drill on tpch lineitem (CHAOS_SF, default sf1)
# Storage-pressure chaos (tests/test_disk_governance.py):
#   disk    DISK_FULL pool shrink on one node mid-query (reclaim -> block
#           -> typed EXCEEDED_SPILL_LIMIT shed, retry rotates away) and
#           SPOOL_LOST committed-partition loss (coordinator reproduces
#           the producer under first-commit-wins, zero client-visible
#           failures, spool_reproductions_total > 0); disk-pool lease
#           accounting, ENOSPC conversion, reclaim escalation order.
#           CI runs at sf0.1-equivalent row counts; set CHAOS_SF to crank
#           the at-scale drill (sf10 is the acceptance bar on big hosts)
# Post-mortem chaos (tests/test_flightrecorder.py):
#   postmortem   worker kill mid-query -> cross-node flight-recorder
#                bundle renders one correlated timeline (kill + retry +
#                every surviving node's lane); anomaly-sentinel slow-run
#                drill; bundle survival across a coordinator restart
# Coordinator-fleet chaos (tests/test_fleet.py):
#   fleet   kill one coordinator of a two-member fleet mid multi-stage
#           query — a peer adopts it off the dead member's journal
#           (spool-committed stages re-read, zero recompute) and the
#           client rides through the router with zero visible failures;
#           plus lease lifecycle, GC mutual exclusion, shard stability
# Partition chaos (tests/test_multihost.py + tests/test_health.py):
#   partition  asymmetric A->B partition mid-query (producer 503s only one
#              consumer's fetches) and a GRAY_SLOW producer (correct but
#              late pages, zero errors) on a 3-worker spooled cluster —
#              the query completes byte-correct with zero client-visible
#              failures, hedged_fetches_total{outcome="won"} > 0, the
#              coordinator link matrix grades the impaired link while
#              BOTH endpoints stay un-quarantined; plus the LinkHealth
#              unit suite (EWMA grading, half-open probe, hedge quantile)
# Observatory chaos (tests/test_timeseries.py):
#   observe GRAY_SLOW + MEMORY_PRESSURE drill — memory-pool reserved
#           must rise then fall on the time-series plane and the
#           post-mortem timeseries slice must cover the window
# Write-plane chaos (tests/test_write_txn.py):
#   write   COMMIT_CRASH at every phase boundary of the staged-commit
#           protocol (intent / commit / ack) — the target table must be
#           byte-identical to the pre-image XOR the post-image, never
#           torn; restart replays uncommitted intents to a clean abort
#           with staging reclaimed and committed-unacked txns as a
#           no-op (exactly-once via the journal commit marker); plus
#           the two-writer snapshot-CAS conflict drills, the DISK_FULL
#           staging abort, the janitor reclaim sweep, and the fleet
#           adoption commit-marker guard
# No subcommand runs the full seeded chaos schedule suite (-m chaos).
#
# Not part of the tier-1 gate (marked slow); run it before touching the
# runtime/ or parallel/ layers.
set -euo pipefail
cd "$(dirname "$0")/.."

case "${1:-}" in
  drain)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_lifecycle.py -q \
        -k "drain" -p no:cacheprovider "$@"
    ;;
  kill9)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_lifecycle.py -q \
        -k "kill9" -p no:cacheprovider "$@"
    ;;
  corrupt)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_memory_governance.py -q \
        -k "corrupt" -p no:cacheprovider "$@"
    ;;
  oom)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_memory_governance.py -q \
        -k "memory_pressure or killer or blocked or revocation" \
        -p no:cacheprovider "$@"
    ;;
  compile)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_compile_resilience.py -q \
        -k "chaos" -p no:cacheprovider "$@"
    ;;
  coordinator)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_recovery.py -q \
        -p no:cacheprovider "$@"
    ;;
  splits)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_splits.py -q \
        -p no:cacheprovider "$@"
    ;;
  disk)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_disk_governance.py -q \
        -p no:cacheprovider "$@"
    ;;
  fleet)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q \
        -p no:cacheprovider "$@"
    ;;
  partition)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_health.py \
        tests/test_multihost.py -q \
        -k "health or asymmetric_partition or gray_slow" \
        -p no:cacheprovider "$@"
    ;;
  write)
    shift
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_write_txn.py \
        "tests/test_fleet.py::test_adoption_consults_commit_marker_never_double_applies" \
        -q -p no:cacheprovider "$@"
    ;;
  postmortem)
    shift
    # flight-recorder / post-mortem chaos (tests/test_flightrecorder.py):
    # kill a worker mid-query under TASK retry — the query succeeds AND
    # the cross-node bundle renders one correlated timeline with the kill,
    # the retry dispatch, and events from every surviving node; plus the
    # sentinel slow-run drill and the bundle-survives-restart drill
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_flightrecorder.py -q \
        -p no:cacheprovider "$@"
    ;;
  observe)
    shift
    # telemetry-observatory chaos (tests/test_timeseries.py): GRAY_SLOW
    # exchange pages stretch the window while tasks hold their memory
    # reservations and MEMORY_PRESSURE shrinks one pool mid-run — the
    # time-series plane must show memory-pool reserved RISING then
    # FALLING (and the capacity drop), and the post-mortem bundle's
    # timeseries slice must cover the pressure window
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_timeseries.py -q \
        -k "observe_drill" -p no:cacheprovider "$@"
    ;;
  cache)
    shift
    # result/fragment-cache staleness chaos (tests/test_result_cache.py):
    # hot cached query under DML + worker kill + coordinator restart — a
    # stale row count at any step fails the run
    exec env JAX_PLATFORMS=cpu python -m pytest tests/test_result_cache.py -q \
        -k "chaos or invalidat or restart" -p no:cacheprovider "$@"
    ;;
  *)
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
        -p no:cacheprovider "$@"
    ;;
esac
