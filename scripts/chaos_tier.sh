#!/usr/bin/env bash
# Chaos tier: TPC-H under seeded random fault schedules (ERROR / TIMEOUT /
# SLOW / EXCHANGE_DROP) on a retry_policy=TASK cluster, diffed against the
# sqlite oracle.  Deterministic: a failing schedule replays from its seed
# (tests/test_chaos.py::SEED).
#
# Not part of the tier-1 gate (marked slow); run it before touching the
# runtime/ or parallel/ layers.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
    -p no:cacheprovider "$@"
