"""Where does q03's cold warm-up time go?  Counts whole-plan compiles
(capacity retries), eager-sizing passes, and phases."""
import os, sys, time
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
from trino_tpu.utils.compilecache import enable_persistent_cache
enable_persistent_cache(_REPO)
import jax
print("backend:", jax.default_backend(), flush=True)

from tests.tpch_queries import QUERIES
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.engine import Engine
from trino_tpu.exec import compiler as C

orig_run = C.LocalExecutor._run
orig_trace = C._trace_plan
events = []
def timed_run(self, plan, inputs, caps):
    t0 = time.perf_counter()
    out = orig_run(self, plan, inputs, caps)
    dt = time.perf_counter() - t0
    events.append(("jit_run", dt, dict(caps)))
    print(f"  [jit_run] {dt:.2f}s caps={caps}", flush=True)
    return out
def timed_trace(plan, inputs, caps, **kw):
    t0 = time.perf_counter()
    out = orig_trace(plan, inputs, caps, **kw)
    dt = time.perf_counter() - t0
    events.append(("eager_trace", dt, dict(caps)))
    print(f"  [eager_trace] {dt:.2f}s caps={caps}", flush=True)
    return out
C.LocalExecutor._run = timed_run
C._trace_plan = timed_trace

qname = os.environ.get("Q", "q03")
sf = float(os.environ.get("SF", "1"))
eng = Engine()
eng.register_catalog("tpch", TpchConnector(sf))
t0 = time.perf_counter()
plan = eng.plan(QUERIES[qname])
t_plan = time.perf_counter() - t0
print(f"plan: {t_plan:.2f}s", flush=True)
t0 = time.perf_counter()
eng.executor.execute(plan)
print(f"first execute: {time.perf_counter()-t0:.2f}s", flush=True)
for kind, dt, caps in events:
    print(f"  {kind}: {dt:.2f}s caps={caps}", flush=True)
t0 = time.perf_counter()
eng.executor.execute(plan)
print(f"second execute: {time.perf_counter()-t0:.2f}s", flush=True)
