#!/bin/bash
# End-of-round cache warming: run the bench twice so (a) adaptive capacity
# tiers converge and compile, (b) the second run PROVES warm_s is within
# bounds — the state the driver's recorded bench run then inherits.
set -x
cd "$(dirname "$0")/.."
BENCH_BUDGET_S=${1:-2400} python bench.py
BENCH_BUDGET_S=600 python bench.py
