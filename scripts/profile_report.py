#!/usr/bin/env python
"""Phase-ledger / flame report over the persistent query history.

Reads the coordinator's history JSONL (TRINO_TPU_HISTORY_FILE, written by
runtime/history.py) and prints, per query, a flame-style breakdown of
where the wall went — queued / planning / compiling / executing /
exchange-wait / spill / blocked-on-memory — plus the per-signature
compile attribution (which XLA programs the query built, compile wall,
persistent-cache outcome).  With ``--trace`` it also stitches the JSONL
span export (TRINO_TPU_TRACE_FILE) for the same query ids and appends
the span flame underneath (scripts/trace_dump.py idiom).

Usage:
    python scripts/profile_report.py HISTORY.jsonl [--query QID]
        [--limit N] [--trace TRACE.jsonl] [--sort wall|compile]
"""

from __future__ import annotations

import argparse
import json
import sys

# phase key -> display label, in ledger order
PHASES = [
    ("queued_ms", "queued"),
    ("planning_ms", "planning"),
    ("starting_ms", "starting"),
    ("running_ms", "running"),
    ("compiling_ms", "compiling"),
    ("executing_ms", "executing"),
    ("exchange_wait_ms", "exchange-wait"),
    ("spill_ms", "spill"),
    ("blocked_on_memory_ms", "blocked-on-memory"),
    ("finishing_ms", "finishing"),
]


def load_history(path: str) -> list[dict]:
    """Newest-last records merged by query_id (same replay the store does)."""
    merged: dict[str, dict] = {}
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        print(f"profile_report: cannot read {path}: {e}", file=sys.stderr)
        return []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn write
        qid = rec.get("query_id")
        if not qid:
            continue
        if qid in merged:
            merged[qid].update(rec)
            merged[qid] = merged.pop(qid)  # refresh order
        else:
            merged[qid] = rec
    return list(merged.values())


def _bar(ms: float, total: float, width: int = 30) -> str:
    pct = 100.0 * ms / total if total else 0.0
    return f"{'#' * max(1 if ms > 0 else 0, int(pct * width / 100)):<{width}}"


def print_query(rec: dict) -> None:
    wall_ms = float(rec.get("wall_s") or 0.0) * 1e3
    sql = str(rec.get("sql") or "")[:100]
    print(
        f"=== {rec.get('query_id', '?')}  [{rec.get('state', '?')}]  "
        f"wall {wall_ms:.1f} ms  rows {rec.get('rows', '?')}"
    )
    if sql:
        print(f"    {sql}")
    if rec.get("error"):
        print(f"    error: {rec['error']}")
    ledger = rec.get("phase_ledger") or {}
    total = max(wall_ms, 1e-9)
    for key, label in PHASES:
        ms = ledger.get(key)
        if not isinstance(ms, (int, float)) or ms <= 0.0:
            continue
        pct = 100.0 * ms / total
        print(f"    {ms:10.1f} ms {pct:5.1f}% {_bar(ms, total)} {label}")
    for sig, s in (rec.get("compile_signatures") or {}).items():
        cache = s.get("cache") or {}
        cache_txt = ", ".join(f"{k}:{v}" for k, v in sorted(cache.items()) if v)
        print(
            f"    compile {sig} x{s.get('compiles', 0)} "
            f"{float(s.get('compile_s') or 0.0) * 1e3:.1f} ms"
            + (f" [{cache_txt}]" if cache_txt else "")
        )


def print_trace_for(rec: dict, trace_path: str) -> None:
    """Append the stitched span flame whose query_id attribute matches."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "trace_dump",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "trace_dump.py"),
    )
    td = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(td)
    qid = rec.get("query_id")
    for trace in td.stitch(td.load_roots(trace_path)).values():
        roots = trace["spans"]
        if not any(
            (s.get("attributes") or {}).get("query_id") == qid for s in roots
        ):
            continue
        wall = max((s.get("duration_ms", 0.0) for s in roots), default=0.0)
        print(f"    spans (trace {trace['trace_id']}):")
        for s in sorted(roots, key=lambda s: -s.get("duration_ms", 0.0)):
            td.print_flame(s, wall or 1.0, indent=3)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("history", help="history JSONL (TRINO_TPU_HISTORY_FILE)")
    ap.add_argument("--query", help="only this query_id")
    ap.add_argument("--limit", type=int, default=20)
    ap.add_argument("--trace", help="JSONL trace export to stitch in")
    ap.add_argument("--sort", choices=("wall", "compile"), default="wall")
    args = ap.parse_args(argv)

    recs = load_history(args.history)
    if args.query:
        recs = [r for r in recs if r.get("query_id") == args.query]
    if not recs:
        print("no history records found", file=sys.stderr)
        return 1

    def sort_key(r):
        if args.sort == "compile":
            return -float((r.get("phase_ledger") or {}).get("compiling_ms") or 0.0)
        return -float(r.get("wall_s") or 0.0)

    for rec in sorted(recs, key=sort_key)[: args.limit]:
        print_query(rec)
        if args.trace:
            print_trace_for(rec, args.trace)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
