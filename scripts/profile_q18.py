"""Per-operator profile of TPC-H q18/q03 on the current default device.

Uses LocalExecutor.explain_analyze's eager node hook for wall attribution
(RTT-inflated absolutes, honest relatives), after the jitted run has learned
capacities.  Prints one line per plan node: nid, type, ms, rows.
"""
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import jax

from trino_tpu.utils.compilecache import enable_persistent_cache
enable_persistent_cache(_REPO)

from tests.tpch_queries import QUERIES
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.engine import Engine
from trino_tpu.exec.compiler import _node_ids

qname = sys.argv[1] if len(sys.argv) > 1 else "q18"
sf = float(os.environ.get("BENCH_SF", "1"))

eng = Engine()
eng.register_catalog("tpch", TpchConnector(sf))
plan = eng.plan(QUERIES[qname])

t0 = time.perf_counter()
eng.executor.execute(plan)
print(f"warm (jitted) {time.perf_counter() - t0:.2f}s", flush=True)
t0 = time.perf_counter()
eng.executor.execute(plan)
print(f"steady wall {time.perf_counter() - t0:.3f}s", flush=True)
dev = eng.executor.steady_state_time(plan, iters=4)
print(f"device steady {dev:.3f}s", flush=True)

nodes = _node_ids(plan)
t0 = time.perf_counter()
page, stats = eng.executor.explain_analyze(plan)
print(f"explain_analyze pass {time.perf_counter() - t0:.1f}s", flush=True)
total = sum(s.get("ms", 0.0) for s in stats.values())
for nid in sorted(stats, key=lambda k: -stats[k].get("ms", 0.0)):
    s = stats[nid]
    node = nodes.get(nid)
    name = type(node).__name__ if node is not None else "?"
    detail = ""
    if node is not None and hasattr(node, "kind"):
        detail = f"/{node.kind}"
    print(
        f"nid={nid:3d} {name+detail:18s} ms={s.get('ms', 0.0):9.1f} "
        f"rows={s.get('rows', -1)}",
        flush=True,
    )
print(f"total eager ms={total:.0f}  caps={eng.executor._learned_caps.get(plan)}")
