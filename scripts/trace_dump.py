#!/usr/bin/env python
"""Flame summary of a JSONL trace export.

Every component (Engine, Coordinator, Worker) appends one JSON line per
finished ROOT span to the file named by TRINO_TPU_TRACE_FILE
(utils/tracing.py JsonlSpanExporter).  A distributed query therefore lands
as several lines sharing one trace_id: the coordinator's `query` span plus
each worker's `task` spans, stitched back together here via parent_id —
the zero-dependency analogue of viewing the reference's OpenTelemetry
export in Jaeger.

Usage:
    TRINO_TPU_TRACE_FILE=/tmp/trace.jsonl python ... (run queries) ...
    python scripts/trace_dump.py /tmp/trace.jsonl [--trace TRACE_ID]
"""

from __future__ import annotations

import argparse
import json
import sys


def load_roots(path: str) -> list[dict]:
    roots = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                roots.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn concurrent write: skip, don't die
    return roots


def stitch(roots: list[dict]) -> dict[str, dict]:
    """trace_id -> synthetic root whose children are the exported root
    spans, remote children nested under their parent_id when it's known."""
    by_trace: dict[str, list[dict]] = {}
    for r in roots:
        by_trace.setdefault(r.get("trace_id", "?"), []).append(r)
    out = {}
    for trace_id, spans in by_trace.items():
        # span_id -> exported span dict, covering nested children too, so a
        # worker task span whose parent_id is the coordinator's query span
        # (propagated via traceparent) nests under it
        known: dict[str, dict] = {}
        for s in spans:
            for sid, holder in _index(s):
                known[sid] = holder
        top = []
        for s in spans:
            parent = known.get(s.get("parent_id") or "")
            if parent is not None and parent is not s:
                parent.setdefault("children", []).append(s)
            else:
                top.append(s)
        out[trace_id] = {
            "trace_id": trace_id,
            "spans": top,
            "total_ms": sum(s.get("duration_ms", 0.0) for s in top),
        }
    return out


def _index(span):
    """Yield (span_id, owning span dict) for the span and all descendants."""
    sid = span.get("span_id")
    if sid:
        yield sid, span
    for c in span.get("children", []):
        yield from _index(c)


def print_flame(span: dict, total_ms: float, indent: int = 0) -> None:
    ms = span.get("duration_ms", 0.0)
    pct = 100.0 * ms / total_ms if total_ms else 0.0
    bar = "#" * max(1, int(pct / 5))
    attrs = span.get("attributes") or {}
    label = span.get("name", "?")
    for key in ("query_id", "task_id", "worker"):
        if key in attrs:
            label += f" {attrs[key]}"
    print(f"{'  ' * indent}{ms:10.1f} ms {pct:5.1f}% {bar:<20} {label}")
    for c in sorted(
        span.get("children", []), key=lambda c: -c.get("duration_ms", 0.0)
    ):
        print_flame(c, total_ms, indent + 1)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="JSONL trace file (TRINO_TPU_TRACE_FILE)")
    ap.add_argument("--trace", help="only this trace_id")
    args = ap.parse_args()

    traces = stitch(load_roots(args.path))
    if args.trace:
        traces = {k: v for k, v in traces.items() if k == args.trace}
    if not traces:
        print("no traces found", file=sys.stderr)
        return 1
    for trace_id, t in traces.items():
        roots = t["spans"]
        wall = max(
            (s.get("duration_ms", 0.0) for s in roots), default=0.0
        )
        print(f"=== trace {trace_id}  ({len(roots)} root span(s), "
              f"{wall:.1f} ms wall)")
        for s in sorted(roots, key=lambda s: -s.get("duration_ms", 0.0)):
            print_flame(s, wall or 1.0)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
