// Columnar page wire serde: framing + ZSTD block compression + checksum.
//
// The reference's equivalent is Java: execution/buffer/PagesSerdes.java:21 +
// PageSerializer/PageDeserializer with LZ4/ZSTD codecs
// (CompressionCodec.java:23-30) framing pages for the HTTP data plane and
// spill files.  Here it is native C++ (SURVEY §2.9: native where the
// reference is "native-equivalent"), exposed to Python via ctypes
// (trino_tpu/native/__init__.py) and used by the cross-host exchange data
// plane and the spill tier.
//
// Wire format (little-endian):
//   [u32 magic 0x54505047 'TPPG'] [u32 ncols] [u64 nrows]
//   per column: [u8 compressed?] [u64 raw_size] [u64 payload_size]
//   [u64 xxh-ish checksum of all payloads]
//   payloads...
//
// Columns whose zstd output does not beat raw by >= 10% ship uncompressed
// (the reference's minCompressionRatio logic in PagesSerdes).

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(__has_include) && __has_include(<zstd.h>)
#include <zstd.h>
#else
// No dev headers on this host: declare the minimal ZSTD surface ourselves
// and resolve it at load time from the system runtime (libzstd.so.1).  The
// simple-API ABI is stable across every zstd 1.x release.
extern "C" {
size_t ZSTD_compressBound(size_t srcSize);
size_t ZSTD_compress(void *dst, size_t dstCapacity, const void *src,
                     size_t srcSize, int compressionLevel);
size_t ZSTD_decompress(void *dst, size_t dstCapacity, const void *src,
                       size_t compressedSize);
unsigned ZSTD_isError(size_t code);
}
#endif

namespace {

constexpr uint32_t kMagic = 0x54505047u;

uint64_t mix_checksum(const uint8_t* data, uint64_t n, uint64_t seed) {
  // splitmix-style rolling checksum over 8-byte words (not cryptographic;
  // matches the role of the reference's XxHash64 page checksums)
  uint64_t h = seed ^ (n * 0x9E3779B97F4A7C15ull);
  uint64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    uint64_t w;
    std::memcpy(&w, data + i, 8);
    h ^= w;
    h *= 0xBF58476D1CE4E5B9ull;
    h ^= h >> 27;
  }
  uint64_t tail = 0;
  if (i < n) {
    std::memcpy(&tail, data + i, n - i);
    h ^= tail;
    h *= 0x94D049BB133111EBull;
    h ^= h >> 31;
  }
  return h;
}

struct Header {
  uint32_t magic;
  uint32_t ncols;
  uint64_t nrows;
};

}  // namespace

extern "C" {

// Upper bound on serialized size.
int64_t tt_serialize_bound(const int64_t* sizes, int32_t ncols) {
  int64_t total = sizeof(Header) + 8 /*checksum*/;
  for (int32_t c = 0; c < ncols; ++c) {
    total += 17;  // per-column header
    total += static_cast<int64_t>(ZSTD_compressBound(sizes[c]));
  }
  return total;
}

// Serialize ncols buffers into out; returns bytes written or -1.
int64_t tt_page_serialize(const uint8_t** bufs, const int64_t* sizes,
                          int32_t ncols, int64_t nrows, int32_t level,
                          uint8_t* out, int64_t out_cap) {
  uint8_t* p = out;
  Header h{kMagic, static_cast<uint32_t>(ncols),
           static_cast<uint64_t>(nrows)};
  std::memcpy(p, &h, sizeof(h));
  p += sizeof(h);

  uint8_t* headers = p;  // per-column headers written after payload sizing
  p += 17LL * ncols;
  uint8_t* checksum_pos = p;
  p += 8;

  uint64_t checksum = 0x5452494E4F545055ull;  // "TRINOTPU"
  for (int32_t c = 0; c < ncols; ++c) {
    const int64_t raw = sizes[c];
    uint8_t compressed = 0;
    uint64_t payload = 0;
    if (level > 0 && raw >= 256) {
      size_t zc = ZSTD_compress(p, out_cap - (p - out), bufs[c], raw, level);
      if (!ZSTD_isError(zc) && zc + zc / 10 < static_cast<size_t>(raw)) {
        compressed = 1;
        payload = zc;
      }
    }
    if (!compressed) {
      if (p + raw > out + out_cap) return -1;
      std::memcpy(p, bufs[c], raw);
      payload = raw;
    }
    checksum = mix_checksum(p, payload, checksum);
    uint8_t* hp = headers + 17LL * c;
    hp[0] = compressed;
    uint64_t raw64 = raw;
    std::memcpy(hp + 1, &raw64, 8);
    std::memcpy(hp + 9, &payload, 8);
    p += payload;
  }
  std::memcpy(checksum_pos, &checksum, 8);
  return p - out;
}

// Parse the frame: fills ncols, nrows and per-column raw sizes.  Returns 0
// on success, negative on corruption.
int32_t tt_page_peek(const uint8_t* data, int64_t len, int32_t* ncols,
                     int64_t* nrows, int64_t* raw_sizes,
                     int32_t max_cols) {
  if (len < static_cast<int64_t>(sizeof(Header))) return -1;
  Header h;
  std::memcpy(&h, data, sizeof(h));
  if (h.magic != kMagic) return -2;
  if (h.ncols > static_cast<uint32_t>(max_cols)) return -3;
  // truncated frame: every per-column header + the checksum must be present
  if (len < static_cast<int64_t>(sizeof(Header) + 17ull * h.ncols + 8))
    return -7;
  *ncols = h.ncols;
  *nrows = h.nrows;
  const uint8_t* hp = data + sizeof(Header);
  for (uint32_t c = 0; c < h.ncols; ++c) {
    uint64_t raw;
    std::memcpy(&raw, hp + 17ull * c + 1, 8);
    raw_sizes[c] = raw;
  }
  return 0;
}

// Decompress all columns into caller-allocated buffers (sized per
// tt_page_peek).  Verifies the checksum.  Returns 0 on success.
int32_t tt_page_deserialize(const uint8_t* data, int64_t len,
                            uint8_t** out_bufs) {
  if (len < static_cast<int64_t>(sizeof(Header))) return -1;
  Header h;
  std::memcpy(&h, data, sizeof(h));
  if (h.magic != kMagic) return -2;
  if (len < static_cast<int64_t>(sizeof(Header) + 17ull * h.ncols + 8))
    return -7;
  const uint8_t* hp = data + sizeof(Header);
  const uint8_t* p = hp + 17ull * h.ncols;
  uint64_t stored_checksum;
  std::memcpy(&stored_checksum, p, 8);
  p += 8;

  uint64_t checksum = 0x5452494E4F545055ull;
  for (uint32_t c = 0; c < h.ncols; ++c) {
    const uint8_t* colh = hp + 17ull * c;
    uint8_t compressed = colh[0];
    uint64_t raw, payload;
    std::memcpy(&raw, colh + 1, 8);
    std::memcpy(&payload, colh + 9, 8);
    if (p + payload > data + len) return -4;
    checksum = mix_checksum(p, payload, checksum);
    if (compressed) {
      size_t dc = ZSTD_decompress(out_bufs[c], raw, p, payload);
      if (ZSTD_isError(dc) || dc != raw) return -5;
    } else {
      std::memcpy(out_bufs[c], p, raw);
    }
    p += payload;
  }
  if (checksum != stored_checksum) return -6;
  return 0;
}

}  // extern "C"
