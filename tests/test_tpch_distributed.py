"""TPC-H differential suite on an 8-device virtual mesh.

The reference's DistributedQueryRunner pattern (testing/trino-testing/.../
DistributedQueryRunner.java:107): the full distributed stack — partial/final
aggregation, repartition/broadcast/gather exchanges as XLA collectives under
shard_map — exercised without TPU hardware, only the transport is local.
"""

import jax
import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES


@pytest.fixture(scope="module")
def dist_engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    eng = Engine(distributed=True, devices=jax.devices()[:8])
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_distributed(name, dist_engine, oracle):
    sql = QUERIES[name]
    got = dist_engine.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])
