"""Resource governance: admission control, queueing, rejection, declared-
memory admission, and query cancellation.

Reference: execution/resourcegroups/InternalResourceGroup.java (hierarchy,
hard concurrency, max queued), dispatcher/DispatchManager (queued phase),
memory/ClusterMemoryManager.java:92 (pool admission), TaskResource DELETE
(cancel)."""

import threading
import time

import pytest

from trino_tpu.runtime.resourcegroups import (
    QueryRejected, ResourceGroupConfig, ResourceGroupManager,
)


def test_concurrency_and_fifo_queue():
    mgr = ResourceGroupManager(ResourceGroupConfig(max_concurrency=1, max_queued=10))
    started = []
    mgr.submit("global", "q1", 0, lambda: started.append("q1"))
    mgr.submit("global", "q2", 0, lambda: started.append("q2"))
    mgr.submit("global", "q3", 0, lambda: started.append("q3"))
    assert started == ["q1"]
    mgr.finish("q1")
    assert started == ["q1", "q2"]
    mgr.finish("q2")
    mgr.finish("q3")
    assert started == ["q1", "q2", "q3"]


def test_queue_full_rejects():
    mgr = ResourceGroupManager(ResourceGroupConfig(max_concurrency=1, max_queued=1))
    mgr.submit("global", "q1", 0, lambda: None)
    mgr.submit("global", "q2", 0, lambda: None)
    with pytest.raises(QueryRejected):
        mgr.submit("global", "q3", 0, lambda: None)


def test_hierarchy_parent_limit():
    cfg = ResourceGroupConfig(
        "global", max_concurrency=1,
        subgroups=(
            ResourceGroupConfig("a", max_concurrency=1),
            ResourceGroupConfig("b", max_concurrency=1),
        ),
    )
    mgr = ResourceGroupManager(cfg)
    started = []
    mgr.submit("a", "qa", 0, lambda: started.append("qa"))
    # parent slot is taken: b's query queues even though b itself is free
    mgr.submit("b", "qb", 0, lambda: started.append("qb"))
    assert started == ["qa"]
    mgr.finish("qa")
    assert started == ["qa", "qb"]


def test_memory_admission():
    mgr = ResourceGroupManager(
        ResourceGroupConfig(max_concurrency=10, memory_limit_bytes=100)
    )
    started = []
    mgr.submit("global", "q1", 60, lambda: started.append("q1"))
    mgr.submit("global", "q2", 60, lambda: started.append("q2"))  # over limit
    assert started == ["q1"]
    mgr.finish("q1")
    assert started == ["q1", "q2"]


def test_oversized_budget_rejected_not_wedged():
    # a budget that can never fit must reject at submit, not queue forever
    mgr = ResourceGroupManager(
        ResourceGroupConfig(max_concurrency=10, memory_limit_bytes=100)
    )
    with pytest.raises(QueryRejected):
        mgr.submit("global", "qbig", 200, lambda: None)
    started = []
    mgr.submit("global", "q1", 50, lambda: started.append("q1"))
    assert started == ["q1"]  # group not wedged


def test_cancel_queued_atomicity():
    mgr = ResourceGroupManager(ResourceGroupConfig(max_concurrency=1))
    mgr.submit("global", "q1", 0, lambda: None)
    mgr.submit("global", "q2", 0, lambda: None)  # queued
    assert mgr.cancel_queued("q2") is True
    assert mgr.cancel_queued("q1") is False  # running: must not free the slot
    assert mgr.stats()["global"]["running"] == 1


def test_admission_and_cancel_via_coordinator(tpch_tiny):
    from trino_tpu.client import StatementClient
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=1)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        cli = StatementClient(runner.coordinator.url)
        # cancel a queued query deterministically: occupy the only slot
        runner.coordinator.resource_groups = __import__(
            "trino_tpu.runtime.resourcegroups", fromlist=["ResourceGroupManager"]
        ).ResourceGroupManager(ResourceGroupConfig(max_concurrency=1, max_queued=5))
        gate = threading.Event()
        release = threading.Event()

        def hog():
            runner.coordinator.resource_groups.submit(
                "global", "hog", 0, lambda: gate.set()
            )
            release.wait(30)
            runner.coordinator.resource_groups.finish("hog")

        t = threading.Thread(target=hog, daemon=True)
        t.start()
        assert gate.wait(5)
        qid = cli.submit("select count(*) from lineitem")
        time.sleep(0.2)
        assert cli.query_state(qid) == "QUEUED"
        assert cli.cancel(qid)
        deadline = time.monotonic() + 10
        while cli.query_state(qid) != "FAILED" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert cli.query_state(qid) == "FAILED"
        release.set()
        t.join(10)
        # the freed slot admits and completes a fresh query
        cols, rows = cli.execute("select count(*) from region")
        assert rows[0][0] == 5
        info = cli.server_info()
        assert "resource_groups" in info
    finally:
        runner.stop()
