"""Resource governance: admission control, queueing, rejection, declared-
memory admission, and query cancellation.

Reference: execution/resourcegroups/InternalResourceGroup.java (hierarchy,
hard concurrency, max queued), dispatcher/DispatchManager (queued phase),
memory/ClusterMemoryManager.java:92 (pool admission), TaskResource DELETE
(cancel)."""

import threading
import time

import pytest

from trino_tpu.runtime.resourcegroups import (
    QueryRejected, ResourceGroupConfig, ResourceGroupManager,
)


def test_concurrency_and_fifo_queue():
    mgr = ResourceGroupManager(ResourceGroupConfig(max_concurrency=1, max_queued=10))
    started = []
    mgr.submit("global", "q1", 0, lambda: started.append("q1"))
    mgr.submit("global", "q2", 0, lambda: started.append("q2"))
    mgr.submit("global", "q3", 0, lambda: started.append("q3"))
    assert started == ["q1"]
    mgr.finish("q1")
    assert started == ["q1", "q2"]
    mgr.finish("q2")
    mgr.finish("q3")
    assert started == ["q1", "q2", "q3"]


def test_queue_full_rejects():
    mgr = ResourceGroupManager(ResourceGroupConfig(max_concurrency=1, max_queued=1))
    mgr.submit("global", "q1", 0, lambda: None)
    mgr.submit("global", "q2", 0, lambda: None)
    with pytest.raises(QueryRejected):
        mgr.submit("global", "q3", 0, lambda: None)


def test_hierarchy_parent_limit():
    cfg = ResourceGroupConfig(
        "global", max_concurrency=1,
        subgroups=(
            ResourceGroupConfig("a", max_concurrency=1),
            ResourceGroupConfig("b", max_concurrency=1),
        ),
    )
    mgr = ResourceGroupManager(cfg)
    started = []
    mgr.submit("a", "qa", 0, lambda: started.append("qa"))
    # parent slot is taken: b's query queues even though b itself is free
    mgr.submit("b", "qb", 0, lambda: started.append("qb"))
    assert started == ["qa"]
    mgr.finish("qa")
    assert started == ["qa", "qb"]


def test_memory_admission():
    mgr = ResourceGroupManager(
        ResourceGroupConfig(max_concurrency=10, memory_limit_bytes=100)
    )
    started = []
    mgr.submit("global", "q1", 60, lambda: started.append("q1"))
    mgr.submit("global", "q2", 60, lambda: started.append("q2"))  # over limit
    assert started == ["q1"]
    mgr.finish("q1")
    assert started == ["q1", "q2"]


def test_oversized_budget_rejected_not_wedged():
    # a budget that can never fit must reject at submit, not queue forever
    mgr = ResourceGroupManager(
        ResourceGroupConfig(max_concurrency=10, memory_limit_bytes=100)
    )
    with pytest.raises(QueryRejected):
        mgr.submit("global", "qbig", 200, lambda: None)
    started = []
    mgr.submit("global", "q1", 50, lambda: started.append("q1"))
    assert started == ["q1"]  # group not wedged


def test_cancel_queued_atomicity():
    mgr = ResourceGroupManager(ResourceGroupConfig(max_concurrency=1))
    mgr.submit("global", "q1", 0, lambda: None)
    mgr.submit("global", "q2", 0, lambda: None)  # queued
    assert mgr.cancel_queued("q2") is True
    assert mgr.cancel_queued("q1") is False  # running: must not free the slot
    assert mgr.stats()["global"]["running"] == 1


def test_admission_and_cancel_via_coordinator(tpch_tiny):
    from trino_tpu.client import StatementClient
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=1)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        cli = StatementClient(runner.coordinator.url)
        # cancel a queued query deterministically: occupy the only slot
        runner.coordinator.resource_groups = __import__(
            "trino_tpu.runtime.resourcegroups", fromlist=["ResourceGroupManager"]
        ).ResourceGroupManager(ResourceGroupConfig(max_concurrency=1, max_queued=5))
        gate = threading.Event()
        release = threading.Event()

        def hog():
            runner.coordinator.resource_groups.submit(
                "global", "hog", 0, lambda: gate.set()
            )
            release.wait(30)
            runner.coordinator.resource_groups.finish("hog")

        t = threading.Thread(target=hog, daemon=True)
        t.start()
        assert gate.wait(5)
        qid = cli.submit("select count(*) from lineitem")
        time.sleep(0.2)
        assert cli.query_state(qid) == "QUEUED"
        assert cli.cancel(qid)
        deadline = time.monotonic() + 10
        while cli.query_state(qid) != "FAILED" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert cli.query_state(qid) == "FAILED"
        release.set()
        t.join(10)
        # the freed slot admits and completes a fresh query
        cols, rows = cli.execute("select count(*) from region")
        assert rows[0][0] == 5
        info = cli.server_info()
        assert "resource_groups" in info
    finally:
        runner.stop()


def test_weighted_fair_sibling_scheduling():
    """Siblings drain in weighted-fair share order: group a (weight 3) gets
    ~3x the admissions of group b (weight 1) while the shared parent slot
    pool is contended (reference: resourcegroups/WeightedFairQueue.java)."""
    from trino_tpu.runtime.resourcegroups import (
        ResourceGroupConfig, ResourceGroupManager,
    )

    root = ResourceGroupConfig(
        "global", max_concurrency=4, max_queued=100,
        subgroups=(
            ResourceGroupConfig("a", max_concurrency=4, scheduling_weight=3),
            ResourceGroupConfig("b", max_concurrency=4, scheduling_weight=1),
        ),
    )
    mgr = ResourceGroupManager(root)
    admitted: list[str] = []

    def starter(name):
        return lambda: admitted.append(name)

    # fill the parent with 4 running, queue 8 more per group
    for i in range(4):
        mgr.submit("a" if i % 2 == 0 else "b", f"seed{i}", 0, starter("seed"))
    for i in range(8):
        mgr.submit("a", f"a{i}", 0, starter("a"))
        mgr.submit("b", f"b{i}", 0, starter("b"))
    admitted.clear()
    # finish the seeds: each release triggers weighted-fair draining
    for i in range(4):
        mgr.finish(f"seed{i}")
    # drain everything by finishing whatever got admitted, in order
    done = set()
    queue_ids = [f"a{i}" for i in range(8)] + [f"b{i}" for i in range(8)]
    # keep finishing admitted queries until all drained
    for _ in range(40):
        for q in queue_ids:
            g = mgr._group_of.get(q)
            if g is not None and q in g.running and q not in done:
                done.add(q)
                mgr.finish(q)
    first8 = admitted[:8]
    a_share = sum(1 for x in first8 if x == "a")
    # weight 3:1 -> a should take ~6 of the first 8 admissions
    assert a_share >= 5, (a_share, admitted)


def test_cluster_memory_kill_biggest_query(tpch_tiny):
    """Cluster memory enforcement: when worker-reported buffered bytes
    exceed the cluster limit, the coordinator kills the query holding the
    most (reference: ClusterMemoryManager + TotalReservation LowMemoryKiller)
    — then degrades gracefully: the killed query is requeued through the
    out-of-core spill executor and still returns correct rows."""
    import threading
    import time

    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.testing import DistributedQueryRunner

    class GatedMemoryConnector(MemoryConnector):
        def __init__(self):
            super().__init__()
            self.gate = threading.Event()
            self.gated_table = None
            self.entered = 0
            self._elock = threading.Lock()

        def read_split(self, split, columns):
            if split.table == self.gated_table:
                with self._elock:
                    self.entered += 1
                assert self.gate.wait(timeout=60), "gate never opened"
            return super().read_split(split, columns)

    conn = GatedMemoryConnector()
    conn.create_table("build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)])
    conn.insert("build", {"k": np.arange(500, dtype=np.int64),
                          "w": np.arange(500, dtype=np.int64)})
    conn.create_table("probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("probe", {"k": np.arange(1000, dtype=np.int64) % 500,
                          "v": np.arange(1000, dtype=np.int64)})

    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.2,
        cluster_memory_limit_bytes=64,  # below the build stage's output
    )
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        conn.gated_table = "probe"
        qid = runner.coordinator.submit_query(
            "select sum(v + w) from probe, build where probe.k = build.k"
        )
        deadline = time.monotonic() + 60
        while conn.entered == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert conn.entered > 0
        # build output is buffered un-acked on workers; the heartbeat sweep
        # must mark the query for death
        deadline = time.monotonic() + 30
        while runner.coordinator.memory_kills == 0 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert runner.coordinator.memory_kills > 0, "no memory kill happened"
        conn.gate.set()
        sm = runner.coordinator.queries[qid]["sm"]
        deadline = time.monotonic() + 120
        while sm.state not in ("FINISHED", "FAILED") and time.monotonic() < deadline:
            time.sleep(0.1)
        # graceful degradation: the kill requeues through the out-of-core
        # executor instead of surfacing a failure
        assert sm.state == "FINISHED", f"{sm.state}: {sm.error}"
        assert runner.coordinator.memory_requeues > 0
        expect = int((np.arange(1000) + (np.arange(1000) % 500)).sum())
        assert runner.coordinator.queries[qid]["result"] == [(expect,)]
    finally:
        conn.gate.set()
        runner.stop()
