"""Failure-layer tests: Backoff schedules, circuit-breaker transitions,
the worker fault matrix, exchange-retry idempotency, quarantine-aware
dispatch, query expiry, and memory-kill degradation.

Unit tests drive the primitives with injected clocks/rngs (deterministic);
integration tests run an in-process coordinator + workers over loopback
HTTP with faults armed through the same POST /v1/inject_failure surface
the chaos tier uses.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from trino_tpu.runtime.failure import (
    OK,
    QUARANTINED,
    SUSPECT,
    Backoff,
    FailureDetector,
    FaultInjector,
)


# --------------------------------------------------------------- Backoff


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_backoff_delay_schedule():
    """min_delay * factor^k capped at max_delay; jitter=0 == exact."""
    b = Backoff(min_delay=0.05, max_delay=0.5, max_elapsed=100.0,
                factor=2.0, jitter=0.0, clock=FakeClock(), sleep=lambda s: None)
    delays = []
    for _ in range(6):
        b.failure()
        delays.append(b.delay())
    assert delays == [0.05, 0.1, 0.2, 0.4, 0.5, 0.5]


def test_backoff_jitter_bounded_and_seeded():
    import random

    b = Backoff(min_delay=0.1, max_delay=10.0, factor=1.0, jitter=0.25,
                rng=random.Random(7), clock=FakeClock(), sleep=lambda s: None)
    b.failure()
    ds = [b.delay() for _ in range(100)]
    assert all(0.075 <= d <= 0.125 for d in ds)
    b2 = Backoff(min_delay=0.1, max_delay=10.0, factor=1.0, jitter=0.25,
                 rng=random.Random(7), clock=FakeClock(), sleep=lambda s: None)
    b2.failure()
    assert [b2.delay() for _ in range(100)] == ds  # same seed, same schedule


def test_backoff_deadline_escalates_and_success_resets():
    clock = FakeClock()
    b = Backoff(min_delay=0.05, max_elapsed=1.0, clock=clock, sleep=lambda s: None)
    assert b.failure() is False  # first failure starts the streak
    clock.t = 0.5
    assert b.failure() is False
    clock.t = 1.0  # deadline since FIRST failure of the streak
    assert b.failure() is True
    b.success()
    assert b.failure_count == 0 and b.first_failure_at is None
    clock.t = 5.0
    assert b.failure() is False  # fresh streak after success


def test_backoff_sleep_uses_injected_sleep():
    slept = []
    b = Backoff(min_delay=0.05, jitter=0.0, clock=FakeClock(),
                sleep=slept.append)
    b.failure()
    b.sleep()
    assert slept == [0.05]


# ------------------------------------------------------- FailureDetector


def test_detector_ok_suspect_quarantine_cycle():
    clock = FakeClock()
    det = FailureDetector(probe_interval=4.0, clock=clock)
    url = "http://w0"
    assert det.state(url) == OK and det.is_dispatchable(url)
    det.record_failure(url)
    assert det.state(url) == SUSPECT
    assert det.is_dispatchable(url)  # degraded but still serving
    det.record_failure(url)  # 2nd consecutive -> breaker opens
    assert det.state(url) == QUARANTINED
    assert not det.is_dispatchable(url)


def test_detector_half_open_probe_restores():
    clock = FakeClock()
    det = FailureDetector(probe_interval=4.0, clock=clock)
    url = "http://w0"
    det.record_failure(url)
    det.record_failure(url)
    assert det.state(url) == QUARANTINED
    # inside the quarantine window: no probes, no dispatches
    clock.t = 2.0
    assert not det.should_probe(url)
    assert not det.is_dispatchable(url)
    # window opens: half-open probe allowed
    clock.t = 4.5
    assert det.should_probe(url)
    # failed probe restarts the clock
    det.record_failure(url)
    clock.t = 6.0
    assert not det.should_probe(url)
    clock.t = 9.0
    assert det.should_probe(url)
    # successful probe: full restore
    det.record_success(url, latency=0.01)
    assert det.state(url) == OK
    assert det.is_dispatchable(url)


def test_detector_suspect_recovers_on_success():
    det = FailureDetector(clock=FakeClock())
    url = "http://w0"
    det.record_failure(url)
    assert det.state(url) == SUSPECT
    for _ in range(5):  # ewma decays below suspect threshold
        det.record_success(url)
    assert det.state(url) == OK


def test_detector_reset_forgets_history():
    det = FailureDetector(clock=FakeClock())
    det.record_failure("http://w0")
    det.record_failure("http://w0")
    det.reset("http://w0")  # worker re-announced after restart
    assert det.state("http://w0") == OK
    assert det.snapshot()["http://w0"]["consecutive_failures"] == 0


# ---------------------------------------------------------- FaultInjector


def test_injector_error_is_one_shot():
    inj = FaultInjector()
    inj.arm(task_id="*", mode="ERROR")
    with pytest.raises(RuntimeError, match="injected failure"):
        inj.task_fault("q1_t0")
    inj.task_fault("q1_t1")  # rule consumed: no-op
    assert inj.fired == [("ERROR", "q1_t0")]


def test_injector_timeout_sleeps_then_raises():
    inj = FaultInjector()
    inj.arm(mode="TIMEOUT", delay_ms=250)
    slept = []
    with pytest.raises(RuntimeError, match="injected timeout"):
        inj.task_fault("t0", sleep=slept.append)
    assert slept == [0.25]


def test_injector_slow_delays_without_failing():
    inj = FaultInjector()
    inj.arm(mode="SLOW", delay_ms=100, count=2)
    slept = []
    inj.task_fault("t0", sleep=slept.append)
    inj.task_fault("t1", sleep=slept.append)
    inj.task_fault("t2", sleep=slept.append)  # exhausted
    assert slept == [0.1, 0.1]


def test_injector_exchange_drop_counted():
    inj = FaultInjector()
    inj.arm(mode="EXCHANGE_DROP", count=3)
    assert [inj.drop_fetch("t") for t in "abcd"] == [True, True, True, False]


def test_injector_task_prefix_matching():
    inj = FaultInjector()
    inj.arm(task_id="q_abc", mode="ERROR")
    inj.task_fault("q_xyz_f0_p0")  # no match: rule stays armed
    with pytest.raises(RuntimeError):
        inj.task_fault("q_abc_f1_p2")


def test_injector_probabilistic_seeded():
    def firings(seed):
        inj = FaultInjector()
        inj.arm(mode="EXCHANGE_DROP", count=10**6, probability=0.3, seed=seed)
        return [inj.drop_fetch("t") for _ in range(200)]

    a, b = firings(11), firings(11)
    assert a == b  # deterministic replay from the seed
    assert 20 < sum(a) < 100  # ~30% of 200
    assert firings(12) != a


def test_injector_rejects_unknown_mode():
    with pytest.raises(ValueError, match="unknown fault mode"):
        FaultInjector().arm(mode="KERNEL_PANIC")


def test_injector_clear():
    inj = FaultInjector()
    inj.arm(mode="ERROR")
    inj.clear()
    inj.task_fault("t0")  # disarmed: no raise


# ------------------------------------------------- cluster integration


@pytest.fixture(scope="module")
def mem_cluster():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.testing import DistributedQueryRunner

    conn = MemoryConnector()
    conn.create_table("t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    rng = np.random.default_rng(5)
    conn.insert("t", {
        "k": rng.integers(0, 50, 20_000).astype(np.int64),
        "v": rng.integers(0, 1000, 20_000).astype(np.int64),
    })
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="mem", heartbeat_interval=0.3
    )
    runner.register_catalog("mem", conn)
    runner.start()
    # fault-injection tests arm per-worker fault budgets and need every
    # repeated GROUP_SQL run to actually execute to consume them; a result
    # cache hit would leave armed faults to leak into later tests
    runner.coordinator.session.set("result_cache_enabled", "false")
    yield runner
    runner.stop()


GROUP_SQL = "select k, sum(v) as s, count(*) as c from t group by k order by k"


def test_inject_failure_http_rejects_unknown_mode(mem_cluster):
    req = urllib.request.Request(
        f"{mem_cluster.workers[0].url}/v1/inject_failure",
        data=json.dumps({"task_id": "*", "mode": "KERNEL_PANIC"}).encode(),
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_exchange_drop_retry_is_idempotent(mem_cluster):
    """Dropped page fetches retry through Backoff and resume from the ack
    token: the rows (counts AND sums — any double-counted page would skew
    both) are byte-identical with and without EXCHANGE_DROP armed."""
    clean = mem_cluster.query(GROUP_SQL)
    for i in range(len(mem_cluster.workers)):
        mem_cluster.inject_task_failure(
            worker_index=i, mode="EXCHANGE_DROP", count=2
        )
    faulted = mem_cluster.query(GROUP_SQL)
    assert faulted == clean
    dropped = [
        m for w in mem_cluster.workers for (m, _) in w.fault_injector.fired
        if m == "EXCHANGE_DROP"
    ]
    assert dropped, "no page fetch was actually dropped"


def test_slow_fault_recovers_without_retry_policy(mem_cluster):
    clean = mem_cluster.query(GROUP_SQL)
    mem_cluster.inject_task_failure(worker_index=0, mode="SLOW", delay_ms=200)
    assert mem_cluster.query(GROUP_SQL) == clean


def test_error_and_timeout_recover_under_task_retry(mem_cluster):
    clean = mem_cluster.query(GROUP_SQL)
    mem_cluster.coordinator.session.set("retry_policy", "TASK")
    try:
        mem_cluster.inject_task_failure(worker_index=0, mode="ERROR")
        assert mem_cluster.query(GROUP_SQL) == clean
        mem_cluster.inject_task_failure(worker_index=1, mode="TIMEOUT", delay_ms=100)
        assert mem_cluster.query(GROUP_SQL) == clean
    finally:
        mem_cluster.coordinator.session.set("retry_policy", "NONE")


def test_dead_worker_quarantined_and_not_dispatched(mem_cluster):
    """A worker that stops answering heartbeats trips the breaker: state
    QUARANTINED, excluded from alive_workers (so it receives no new
    dispatches), and queries keep succeeding on the survivors.  Uses its
    own cluster because the worker stays dead."""
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.testing import DistributedQueryRunner

    conn = MemoryConnector()
    conn.create_table("t", [ColumnSchema("k", BIGINT)])
    conn.insert("t", {"k": np.arange(1000, dtype=np.int64)})
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="mem", heartbeat_interval=0.2
    )
    runner.register_catalog("mem", conn)
    runner.start()
    try:
        dead = runner.workers[1]
        dead.stop()
        det = runner.coordinator.failure_detector
        deadline = time.monotonic() + 10
        while det.state(dead.url) != QUARANTINED and time.monotonic() < deadline:
            time.sleep(0.05)
        assert det.state(dead.url) == QUARANTINED
        assert not det.is_dispatchable(dead.url)
        assert dead.url not in runner.coordinator.alive_workers()
        rows = runner.query("select count(*) from t")
        assert rows == [(1000,)]
    finally:
        runner.stop()


def test_finished_queries_expire_after_max_age(mem_cluster):
    coord = mem_cluster.coordinator
    old_age = coord.query_expiration_seconds
    coord.query_expiration_seconds = 0.4
    try:
        qid = coord.submit_query("select count(*) from t")
        coord.queries[qid]["done"].wait(30)
        assert qid in coord.queries
        deadline = time.monotonic() + 10
        while qid in coord.queries and time.monotonic() < deadline:
            time.sleep(0.1)  # heartbeat sweep expires it
        assert qid not in coord.queries
    finally:
        coord.query_expiration_seconds = old_age


def test_memory_kill_requeues_through_spill_executor(mem_cluster):
    """A cluster-memory kill degrades instead of failing: the run loop
    observes requeue_spill and re-runs the query through the out-of-core
    executor (sequential slices, disk exchanges)."""
    coord = mem_cluster.coordinator
    clean = mem_cluster.query(GROUP_SQL)
    orig, requeues = coord._run_once, coord.memory_requeues

    def killed(record, attempt=0):
        record["requeue_spill"] = True  # what _enforce_cluster_memory sets
        record["cancel"] = True
        raise RuntimeError("Query killed: cluster memory limit exceeded")

    coord._run_once = killed
    try:
        got = mem_cluster.query(GROUP_SQL)
    finally:
        coord._run_once = orig
    assert got == clean
    assert coord.memory_requeues == requeues + 1
