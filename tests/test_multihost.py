"""Multi-host runtime tests: coordinator + workers over loopback HTTP
(reference pattern: DistributedQueryRunner.java:107), with the sqlite
oracle as the correctness reference and fault injection for the retry path.
"""

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES


@pytest.fixture(scope="module")
def cluster(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=3)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    yield runner
    runner.stop()


@pytest.mark.parametrize("name", ["q01", "q03", "q06", "q13", "q18"])
def test_multihost_tpch(name, cluster, oracle):
    sql = QUERIES[name]
    got = cluster.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])


def test_client_protocol(cluster, oracle):
    sql = "select count(*) from lineitem"
    got = cluster.query_via_protocol(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected)


def test_discovery_and_heartbeat(cluster):
    from trino_tpu.client import StatementClient

    info = StatementClient(cluster.coordinator.url).server_info()
    assert len(info["workers"]) == 3
    assert all(w["alive"] for w in info["workers"])


def test_task_failure_fails_query(cluster):
    cluster.inject_task_failure(worker_index=0, task_id="*")
    with pytest.raises(RuntimeError, match="injected|failed"):
        cluster.query("select sum(l_quantity) from lineitem")
    # the injection is one-shot per task id; subsequent queries succeed
    rows = cluster.query("select count(*) from lineitem")
    assert rows[0][0] > 0


def test_query_retry_policy(cluster):
    cluster.coordinator.session.set("retry_policy", "QUERY")
    try:
        cluster.inject_task_failure(worker_index=1, task_id="*")
        rows = cluster.query("select count(*) from orders")
        assert rows[0][0] > 0  # retried transparently
    finally:
        cluster.coordinator.session.set("retry_policy", "NONE")


def test_task_level_retry(cluster, oracle):
    """retry_policy=TASK re-schedules the failed task on another worker —
    the query completes without a whole-query retry (reference: FTE
    EventDrivenFaultTolerantQueryScheduler task retries)."""
    cluster.coordinator.session.set("retry_policy", "TASK")
    try:
        cluster.inject_task_failure(worker_index=0, task_id="*")
        sql = QUERIES["q03"]
        got = cluster.query(sql)
        assert_rows_equal(got, oracle.query(sql), ordered=ORDERED["q03"])
    finally:
        cluster.coordinator.session.set("retry_policy", "NONE")


def test_kill_worker_mid_query_task_retry(tpch_tiny, oracle):
    """A worker dying mid-query is routed around under retry_policy=TASK."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=3, heartbeat_interval=0.3)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        # warm: compile caches on all workers
        runner.query("select count(*) from lineitem")
        # kill one worker outright; its tasks become UNREACHABLE and must be
        # re-scheduled onto the surviving two
        runner.workers[1].stop()
        sql = "select sum(l_quantity), count(*) from lineitem"
        got = runner.query(sql)
        assert_rows_equal(got, oracle.query(sql))
    finally:
        runner.stop()


def test_streaming_chunked_exchange(cluster, oracle):
    """Chunked token-sequenced fetch reassembles exactly once even when the
    output spans many chunks (small chunk_rows forces multi-chunk buffers)."""
    from trino_tpu.runtime import wire

    old = wire.CHUNK_ROWS
    wire.CHUNK_ROWS = 512  # lineitem tiny ~60k rows -> ~120 chunks/buffer
    try:
        sql = "select l_orderkey, count(*) from lineitem group by l_orderkey"
        got = cluster.query(sql)
        assert_rows_equal(got, oracle.query(sql))
    finally:
        wire.CHUNK_ROWS = old


def test_kill_worker_with_finished_stage_output_mid_query():
    """The REAL mid-query window: a worker dies AFTER a producer stage
    FINISHED on it but while a consumer stage is still running.  Under
    retry_policy=TASK the scheduler must (a) re-schedule the dead worker's
    consumer task AND (b) recompute the producer output that died with the
    process — the heal path (coordinator.py) — instead of retrying fetches
    against the dead URL until exhaustion.

    Deterministic timing via a gated connector: probe-side read_split blocks
    until the test kills the worker, so the build stage is finished and
    buffered on every worker before the failure is injected."""
    import threading
    import time

    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.testing import DistributedQueryRunner

    class GatedMemoryConnector(MemoryConnector):
        def __init__(self):
            super().__init__()
            self.gate = threading.Event()
            self.gated_table = None
            self.entered = 0
            self._elock = threading.Lock()

        def read_split(self, split, columns):
            if split.table == self.gated_table:
                with self._elock:
                    self.entered += 1
                assert self.gate.wait(timeout=60), "test gate never opened"
            return super().read_split(split, columns)

    conn = GatedMemoryConnector()
    conn.create_table("build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)])
    conn.insert("build", {"k": np.arange(50, dtype=np.int64),
                          "w": np.arange(50, dtype=np.int64) * 10})
    conn.create_table("probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("probe", {"k": np.arange(2000, dtype=np.int64) % 50,
                          "v": np.arange(2000, dtype=np.int64)})

    runner = DistributedQueryRunner(num_workers=2, default_catalog="memory",
                                    heartbeat_interval=0.3)
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        sql = "select sum(v + w) from probe, build where probe.k = build.k"
        # expected value, computed directly
        expect = int((np.arange(2000) + (np.arange(2000) % 50) * 10).sum())

        conn.gated_table = "probe"
        qid = runner.coordinator.submit_query(sql)
        # wait until probe-stage tasks are inside read_split => every earlier
        # stage (incl. the build scan) has FINISHED and is buffered
        deadline = time.monotonic() + 60
        while conn.entered == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert conn.entered > 0, "probe stage never started"
        time.sleep(0.3)  # let remaining probe tasks reach the gate too
        runner.workers[1].stop()  # kills buffered build output + probe task
        conn.gate.set()

        sm = runner.coordinator.queries[qid]["sm"]
        deadline = time.monotonic() + 120
        while sm.state not in ("FINISHED", "FAILED") and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sm.state == "FINISHED", f"query {sm.state}: {sm.error}"
        rows = runner.coordinator.queries[qid]["result"]
        assert rows == [(expect,)]
    finally:
        conn.gate.set()
        runner.stop()


def test_asymmetric_partition_hedged_exchange(tpch_tiny, oracle, tmp_path):
    """Asymmetric partition drill (ISSUE acceptance): the A->B exchange
    link black-holes mid-cluster — worker B 503s every results fetch that
    identifies as coming from worker A, while B's heartbeats and every
    other consumer's fetches keep working.  The query must complete
    byte-correct with ZERO client-visible failures: A's LinkHealth grades
    the link DEAD and the hedged fetch serves B's committed partitions
    from the spool.  The coordinator's link matrix must report the
    impaired link while BOTH endpoints stay dispatchable (nobody is
    quarantined for a pairwise partition)."""
    import time

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.health import HEDGED_FETCHES
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=3, heartbeat_interval=0.3)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        runner.coordinator.session.set("exchange_spool_dir", str(tmp_path))
        # warm: compile caches AND each link's latency baseline/history
        runner.query("select count(*) from lineitem")
        won0 = HEDGED_FETCHES.value("won")
        # partition A->B only: B (producer) drops A's (consumer) fetches
        runner.partition_link(producer_index=1, consumer_index=0)
        sql = QUERIES["q18"]
        got = runner.query(sql)  # a raise here = client-visible failure
        assert_rows_equal(got, oracle.query(sql), ordered=ORDERED["q18"])
        # the hedge path actually carried traffic around the partition
        assert HEDGED_FETCHES.value("won") > won0
        # consumer-side verdict: A graded its link to B SUSPECT/DEAD
        a, b = runner.workers[0], runner.workers[1]
        assert a.link_health.state(b.url) in ("SUSPECT", "DEAD")
        # coordinator vantage: the matrix shows the impaired link...
        deadline = time.monotonic() + 10
        impaired = {}
        while time.monotonic() < deadline:
            impaired = {
                (c, p): cell["state"]
                for c, row in runner.coordinator.link_matrix().items()
                for p, cell in row.items()
                if cell.get("state") != "HEALTHY"
            }
            if (a.url, b.url) in impaired:
                break
            time.sleep(0.2)
        assert impaired.get((a.url, b.url)) in ("SUSPECT", "DEAD"), impaired
        # ...while neither endpoint is quarantined: a pairwise partition
        # is not a dead worker
        det = runner.coordinator.failure_detector
        assert det.is_dispatchable(a.url) and det.is_dispatchable(b.url)
    finally:
        runner.stop()


def test_gray_slow_producer_hedge_wins(tpch_tiny, oracle, tmp_path):
    """GRAY_SLOW drill: a producer serves exchange pages correctly but
    late — no errors anywhere, so only the hedge race (fetch in flight
    past the link's history-quantile delay -> spool re-read) keeps the
    query off the slow path.  Zero client-visible failures; the hedged
    won counter must move; the link grades from latency alone."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.health import HEDGED_FETCHES
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=3, heartbeat_interval=0.3)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        runner.coordinator.session.set("exchange_spool_dir", str(tmp_path))
        sql = QUERIES["q18"]
        # warm with the SAME query: its all-to-all exchanges give every
        # (consumer, producer) link a healthy baseline — a gray failure
        # is judged against the link's OWN history, so a link whose
        # first-ever sample is already slow cannot be graded
        runner.query(sql)
        won0 = HEDGED_FETCHES.value("won")
        runner.gray_slow(producer_index=1, delay_ms=800)
        got = runner.query(sql)
        assert_rows_equal(got, oracle.query(sql), ordered=ORDERED["q18"])
        assert HEDGED_FETCHES.value("won") > won0
        # latency-only grading: some consumer saw the slowdown.  The slow
        # primary responses land AFTER their hedge already won (the
        # losing fetch still reports its latency when it completes), so
        # give the last in-flight primaries a moment to score.
        import time

        b = runner.workers[1]
        grades: set = set()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            grades = {
                w.link_health.state(b.url)
                for w in runner.workers
                if w is not b
            }
            if grades & {"DEGRADED", "SUSPECT", "DEAD"}:
                break
            time.sleep(0.2)
        assert grades & {"DEGRADED", "SUSPECT", "DEAD"}, grades
        # nobody quarantined: heartbeats never touched the fault
        det = runner.coordinator.failure_detector
        assert all(det.is_dispatchable(w.url) for w in runner.workers)
    finally:
        runner.stop()


def test_statement_surface_via_coordinator(cluster, oracle):
    """DDL/DML/utility statements through the HTTP protocol: embedded
    SELECTs run distributed, metadata ops execute coordinator-side
    (reference: DataDefinitionTask family + the writer plan path)."""
    from trino_tpu.connectors.memory import MemoryConnector

    cluster.register_catalog("memory2", MemoryConnector())
    cluster.query_via_protocol(
        "create table memory2.t_stmt as "
        "select l_orderkey, l_quantity from lineitem where l_quantity > 45"
    )
    got = cluster.query_via_protocol(
        "select count(*), sum(l_quantity) from memory2.t_stmt"
    )
    want = oracle.query(
        "select count(*), sum(l_quantity) from lineitem where l_quantity > 45"
    )
    assert_rows_equal(got, want)
    cluster.query_via_protocol(
        "insert into memory2.t_stmt values (1, 2.5), (2, null)"
    )
    got = cluster.query_via_protocol(
        "select count(*), count(l_quantity) from memory2.t_stmt"
    )
    assert got[0][0] == want[0][0] + 2 and got[0][1] == want[0][0] + 1
    desc = cluster.query_via_protocol("describe memory2.t_stmt")
    assert ("l_quantity", "decimal(12,2)") in [tuple(r) for r in desc]
    cluster.query_via_protocol("drop table memory2.t_stmt")


def test_phased_schedule_overlaps_independent_subtrees(cluster, oracle):
    """PHASED mode (retry_policy=TASK) runs independent sibling stages
    CONCURRENTLY (reference: scheduler/policy/PhasedExecutionSchedule.java
    — stages whose dependencies are satisfied schedule together): in a
    UNION ALL of two aggregations over different tables, each branch is an
    independent subtree, so one branch must START before the other ENDS."""
    cluster.coordinator.session.set("retry_policy", "TASK")
    try:
        sql = """
          select count(*) as c from lineitem
          union all
          select count(*) as c from orders
        """
        got = cluster.query(sql)
        assert_rows_equal(got, oracle.query(sql), ordered=False)
        times = cluster.coordinator.last_stage_times
        assert len(times) >= 2, times
        ivs = sorted(times.values())
        overlapping = any(
            a_start < b_end and b_start < a_end
            for i, (a_start, a_end) in enumerate(ivs)
            for (b_start, b_end) in ivs[i + 1:]
        )
        assert overlapping, f"no overlapping stage intervals: {times}"
    finally:
        cluster.coordinator.session.set("retry_policy", "NONE")


def test_adaptive_memory_budget_grows_on_retry(cluster, oracle):
    """FTE adaptive retry (reference: ExponentialGrowthPartitionMemory
    Estimator): with a task memory budget too small for the plan, the FIRST
    attempt is refused by the worker executor; the retry re-runs with a 4x
    budget and succeeds ONLY because the estimate grew."""
    cluster.coordinator.session.set("retry_policy", "TASK")
    cluster.coordinator.session.set("task_memory_budget_bytes", 200_000)
    try:
        sql = QUERIES["q01"]
        got = cluster.query(sql)
        assert_rows_equal(got, oracle.query(sql), ordered=ORDERED["q01"])
    finally:
        cluster.coordinator.session.set("task_memory_budget_bytes", 0)
        cluster.coordinator.session.set("retry_policy", "NONE")


def test_memory_budget_refusal_without_retry_fails(cluster):
    """Same tiny budget under retry_policy=NONE: the refusal surfaces as a
    query failure (proves the budget is actually enforced — the adaptive
    test above passes BECAUSE the growth happens, not because the budget
    is ignored)."""
    import pytest as _pytest

    cluster.coordinator.session.set("task_memory_budget_bytes", 200_000)
    try:
        with _pytest.raises(Exception):
            cluster.query(QUERIES["q01"])
    finally:
        cluster.coordinator.session.set("task_memory_budget_bytes", 0)


def test_bucketed_table_skips_repartition(tpch_tiny, oracle):
    """Connector-bucketed execution (reference: BucketNodeMap +
    ConnectorNodePartitioningProvider): a memory table bucketed on the
    group key by the ENGINE's partition hash is born hash-partitioned, so
    the distributed plan aggregates WITHOUT a repartition exchange — and
    still agrees with an unbucketed run."""
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.plan.distribute import distribute
    from trino_tpu.plan.nodes import Exchange, walk
    from trino_tpu.plan.optimizer import optimize
    from trino_tpu.testing import DistributedQueryRunner

    rng = np.random.default_rng(3)
    n = 5000
    k = rng.integers(0, 97, n).astype(np.int64)
    v = rng.integers(0, 1000, n).astype(np.int64)

    conn = MemoryConnector()
    conn.create_table(
        "b", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)],
        bucketed_by=["k"], bucket_count=4,
    )
    conn.insert("b", {"k": k, "v": v})
    flat = MemoryConnector()
    flat.create_table("b", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    flat.insert("b", {"k": k, "v": v})

    sql = "select k, sum(v) as s, count(*) as c from b group by k order by k"
    runner = DistributedQueryRunner(num_workers=2, default_catalog="mem")
    runner.register_catalog("mem", conn)
    runner.start()
    try:
        # the distributed plan has NO repartition exchange
        coord = runner.coordinator
        plan = optimize(coord.planner.plan(sql), coord.catalogs, coord.session)
        dplan = distribute(plan, coord.catalogs, 2, coord.session,
                           connector_buckets=True)
        kinds = [n.kind for n in walk(dplan) if isinstance(n, Exchange)]
        assert "repartition" not in kinds, kinds
        got = runner.query(sql)
    finally:
        runner.stop()

    single = DistributedQueryRunner(num_workers=1, default_catalog="mem")
    single.register_catalog("mem", flat)
    single.start()
    try:
        want = single.query(sql)
    finally:
        single.stop()
    assert got == want
