"""Multi-host runtime tests: coordinator + workers over loopback HTTP
(reference pattern: DistributedQueryRunner.java:107), with the sqlite
oracle as the correctness reference and fault injection for the retry path.
"""

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES


@pytest.fixture(scope="module")
def cluster(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=3)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    yield runner
    runner.stop()


@pytest.mark.parametrize("name", ["q01", "q03", "q06", "q13", "q18"])
def test_multihost_tpch(name, cluster, oracle):
    sql = QUERIES[name]
    got = cluster.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])


def test_client_protocol(cluster, oracle):
    sql = "select count(*) from lineitem"
    got = cluster.query_via_protocol(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected)


def test_discovery_and_heartbeat(cluster):
    from trino_tpu.client import StatementClient

    info = StatementClient(cluster.coordinator.url).server_info()
    assert len(info["workers"]) == 3
    assert all(w["alive"] for w in info["workers"])


def test_task_failure_fails_query(cluster):
    cluster.inject_task_failure(worker_index=0, task_id="*")
    with pytest.raises(RuntimeError, match="injected|failed"):
        cluster.query("select sum(l_quantity) from lineitem")
    # the injection is one-shot per task id; subsequent queries succeed
    rows = cluster.query("select count(*) from lineitem")
    assert rows[0][0] > 0


def test_query_retry_policy(cluster):
    cluster.coordinator.session.set("retry_policy", "QUERY")
    try:
        cluster.inject_task_failure(worker_index=1, task_id="*")
        rows = cluster.query("select count(*) from orders")
        assert rows[0][0] > 0  # retried transparently
    finally:
        cluster.coordinator.session.set("retry_policy", "NONE")
