"""On-hardware differential tier: TPC-DS subset on the real TPU chip.

Same mechanism as tests/test_tpch_tpu.py (hardware subprocess, oracle diff
in the parent) over twelve TPC-DS queries spanning star joins, date-dim
filters, demographic cross joins, returns anti-joins, rollup, and
rank-over-aggregate — the shapes where TPU numerics (f32 Kahan floors,
emulated f64, limb-exact int64) could diverge from the CPU suite.

Reference: the per-connector on-hardware variants of the engine suites
(testing/trino-testing/.../AbstractTestQueries.java subclasses).
"""

import json
import os
import subprocess
import sys

import pytest

from tests.oracle import SqliteOracle, assert_rows_equal
from tests.tpcds_queries import ORDERED, QUERIES

_HW = os.environ.get("TRINO_TPU_HW_PLATFORM", "")
_SCALE = 0.002

_TPU_QUERIES = [
    "q03", "q07", "q19", "q42", "q52", "q55", "q65", "q68", "q79", "q85",
    "q96", "q98",
]

_RUNNER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
from trino_tpu.utils.compilecache import enable_persistent_cache
enable_persistent_cache({repo!r})
assert jax.default_backend() != "cpu", f"expected hardware, got {{jax.default_backend()}}"
from tests.tpcds_queries import QUERIES
from trino_tpu.connectors.tpcds import TpcdsConnector
from trino_tpu.runtime.engine import Engine

eng = Engine(default_catalog="tpcds")
eng.register_catalog("tpcds", TpcdsConnector({scale}))
out = {{}}
for name in {names!r}:
    rows = eng.query(QUERIES[name])
    out[name] = [list(r) for r in rows]
print("\nRESULT:" + json.dumps(out))
"""


@pytest.fixture(scope="module")
def tpcds_tpu_results():
    if not _HW or _HW == "cpu":
        pytest.skip("no TPU platform available (explicitly CPU)")
    env = dict(os.environ)
    if _HW == "auto":
        env.pop("JAX_PLATFORMS", None)
    else:
        env["JAX_PLATFORMS"] = _HW
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = _RUNNER.format(repo=repo, scale=_SCALE, names=_TPU_QUERIES)
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env, cwd=repo, capture_output=True, text=True, timeout=3600,
    )
    if proc.returncode != 0:
        pytest.skip(
            f"TPU subprocess failed (hardware unavailable?):\n{proc.stderr[-2000:]}"
        )
    payload = [l for l in proc.stdout.splitlines() if l.startswith("RESULT:")]
    assert payload, f"no RESULT line:\n{proc.stdout[-2000:]}"
    return json.loads(payload[-1][len("RESULT:"):])


@pytest.fixture(scope="module")
def tpcds_oracle_small():
    from trino_tpu.connectors.tpcds import TPCDS_SCHEMAS, tpcds_data

    needed = set()
    for q in _TPU_QUERIES:
        for t in TPCDS_SCHEMAS:
            if t in QUERIES[q]:
                needed.add(t)
    return SqliteOracle(
        {t: tpcds_data(t, _SCALE) for t in sorted(needed)},
        schemas=TPCDS_SCHEMAS,
    )


@pytest.mark.parametrize("name", _TPU_QUERIES)
def test_tpcds_on_tpu(name, tpcds_tpu_results, tpcds_oracle_small):
    got = [tuple(r) for r in tpcds_tpu_results[name]]
    want = tpcds_oracle_small.query(QUERIES[name])
    assert_rows_equal(got, want, ordered=ORDERED[name], rtol=1e-6)
