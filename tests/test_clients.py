"""DB-API 2.0 driver (the JDBC analogue) and faker connector tests."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def cluster(tpch_tiny):
    from trino_tpu.connectors.faker import FakerConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.data.types import BIGINT, DATE, DOUBLE, VARCHAR
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=2)
    runner.register_catalog("tpch", TpchConnector(0.01))
    faker = FakerConnector()
    faker.create_table(
        "events",
        [
            ColumnSchema("id", BIGINT),
            ColumnSchema("kind", VARCHAR),
            ColumnSchema("score", DOUBLE),
            ColumnSchema("day", DATE),
        ],
        rows=2000,
    )
    runner.register_catalog("faker", faker)
    runner.start()
    yield runner
    runner.stop()


def test_dbapi_basic(cluster):
    from trino_tpu.client.dbapi import connect

    with connect(cluster.coordinator.url) as conn:
        cur = conn.cursor()
        cur.execute("select n_name, n_regionkey from nation order by n_name limit 3")
        assert cur.rowcount == 3
        assert [d[0] for d in cur.description] == ["n_name", "n_regionkey"]
        rows = cur.fetchall()
        assert len(rows) == 3 and rows == sorted(rows)
        cur.execute("select count(*) from region")
        assert cur.fetchone() == (5,)
        assert cur.fetchone() is None


def test_dbapi_parameters_and_iteration(cluster):
    from trino_tpu.client.dbapi import connect

    conn = connect(cluster.coordinator.url)
    cur = conn.cursor()
    cur.execute(
        "select n_name from nation where n_regionkey = ? and n_name <> ?",
        (0, "doesn't-exist"),  # embedded quote exercises escaping
    )
    names = [r[0] for r in cur]
    assert len(names) == 5
    with pytest.raises(Exception):
        cur.execute("select * from nation where n_regionkey = ?", ())


def test_dbapi_errors(cluster):
    from trino_tpu.client.dbapi import DatabaseError, ProgrammingError, connect

    conn = connect(cluster.coordinator.url)
    cur = conn.cursor()
    with pytest.raises(DatabaseError):
        cur.execute("select nonexistent_col from nation")
    conn.close()
    with pytest.raises(ProgrammingError):
        conn.cursor()


def test_faker_deterministic_and_split_stable(cluster):
    from trino_tpu.connectors.faker import FakerConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT

    conn = FakerConnector()
    conn.create_table("t", [ColumnSchema("x", BIGINT)], rows=100)
    whole = conn.read_split(conn.get_splits("t", 1)[0], ["x"])["x"]
    parts = [conn.read_split(s, ["x"])["x"] for s in conn.get_splits("t", 4)]
    assert np.array_equal(np.concatenate(parts), whole)
    again = FakerConnector()
    again.create_table("t", [ColumnSchema("x", BIGINT)], rows=100)
    assert np.array_equal(
        again.read_split(again.get_splits("t", 1)[0], ["x"])["x"], whole
    )


def test_faker_queries(cluster):
    rows = cluster.query("select count(*), count(distinct kind) from faker.events")
    assert rows[0][0] == 2000 and 1 < rows[0][1] <= 32  # vocab size
    rows = cluster.query(
        "select kind, count(*) c from faker.events group by kind order by c desc limit 3"
    )
    assert len(rows) == 3 and rows[0][1] >= rows[2][1]
    rows = cluster.query(
        "select count(*) from faker.events where day >= date '2021-01-01'"
    )
    assert 0 < rows[0][0] < 2000


def test_spooled_client_protocol(tmp_path):
    """SPOOLED result protocol (reference: server/protocol/spooling +
    client/spooling SegmentLoader): with a client spool configured and the
    client advertising support, results come back via on-disk segment URIs
    — the response carries no inline data, the coordinator drops the rows
    from RAM, and the client's segment ack deletes the files."""
    import glob
    import json as _json
    import urllib.request

    from trino_tpu.client.client import StatementClient
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=2)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        runner.coordinator.session.set("client_spool_dir", str(tmp_path))
        sql = "select n_nationkey, n_name from nation order by n_nationkey"
        plain = StatementClient(runner.coordinator.url).execute(sql)
        cols, rows = StatementClient(
            runner.coordinator.url, spooled=True
        ).execute(sql)
        assert rows == plain[1]
        assert len(rows) == 25
        # inline protocol response for the spooled query had segments only
        qid = [
            q for q, rec in runner.coordinator.queries.items()
            if rec.get("segments") is not None
        ]
        assert qid, "no spooled query recorded"
        rec = runner.coordinator.queries[qid[0]]
        assert rec["result"] == []  # rows left coordinator RAM
        # acked segments were deleted from the spool dir
        assert glob.glob(str(tmp_path / f"{qid[0]}_seg*")) == []
    finally:
        runner.stop()
