"""Differential TPC-DS suite: engine vs sqlite oracle over identical data.

North-star config #4 (TPC-DS Q64/Q95-class plans).  Same pattern as
test_tpch.py / the reference's AbstractTestQueryFramework.assertQuery
(testing/trino-testing/.../AbstractTestQueryFramework.java:344): every query
runs on both engines and the row sets are diffed — several of these queries
legitimately return few or zero rows at tiny scale, so the oracle diff is
what distinguishes "correct" from "selectivity bug".
"""

import pytest

from tests.oracle import SqliteOracle, assert_rows_equal
from tests.tpcds_queries import ORDERED, QUERIES

SCALE = 0.002


@pytest.fixture(scope="module")
def tpcds_tables():
    from trino_tpu.connectors.tpcds import TPCDS_SCHEMAS, tpcds_data

    # only the tables the query subset touches, to keep oracle load fast
    needed = set()
    for sql in QUERIES.values():
        for t in TPCDS_SCHEMAS:
            if t in sql:
                needed.add(t)
    return {t: tpcds_data(t, SCALE) for t in sorted(needed)}


@pytest.fixture(scope="module")
def tpcds_oracle(tpcds_tables):
    from trino_tpu.connectors.tpcds import TPCDS_SCHEMAS

    return SqliteOracle(tpcds_tables, schemas=TPCDS_SCHEMAS)


@pytest.fixture(scope="module")
def engine():
    from trino_tpu.connectors.tpcds import TpcdsConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="tpcds")
    eng.register_catalog("tpcds", TpcdsConnector(SCALE))
    return eng


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpcds_query(name, engine, tpcds_oracle):
    sql = QUERIES[name]
    got = engine.query(sql)
    expected = tpcds_oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])
