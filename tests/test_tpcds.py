"""Differential TPC-DS suite: engine vs sqlite oracle over identical data.

North-star config #4 (TPC-DS Q64/Q95-class plans).  Same pattern as
test_tpch.py / the reference's AbstractTestQueryFramework.assertQuery
(testing/trino-testing/.../AbstractTestQueryFramework.java:344): every query
runs on both engines and the row sets are diffed — several of these queries
legitimately return few or zero rows at tiny scale, so the oracle diff is
what distinguishes "correct" from "selectivity bug".
"""

import pytest

from tests.oracle import SqliteOracle, assert_rows_equal
from tests.tpcds_queries import ORDERED, QUERIES

SCALE = 0.002

# Queries excluded from the tier-1 gate (`-m 'not slow'`).  The full
# parametrized suite takes ~15 min on the CPU mesh — alone over the tier-1
# wall budget — so every case that measured >= ~4 s (multi-channel UNION
# rollups, wide star joins, windowed year-over-year comparisons) runs only
# in the unmarked full suite.  q51/q58/q97 additionally hit sqlite oracle
# limitations and q59 a known mismatch — tracked independently of the gate.
# The remaining ~50 fast cases (~2.5 min total) keep every operator family
# covered: scans/filters (q03 q42 q52 q55), hash joins (q07 q19 q25 q26),
# group-by rollups (q43 q53 q65), semi/anti joins (q16 q94), CASE channels
# (q34 q73 q90), date windows (q12 q20 q98), subquery decorrelation
# (q01 q06 q30), and the north-star q64 shape via q64lite.
SLOW = frozenset({
    "q02", "q04", "q05", "q10", "q11", "q14", "q18", "q22", "q23", "q24",
    "q27", "q31", "q33", "q35", "q36", "q38", "q39", "q47", "q49", "q51",
    "q54", "q56", "q57", "q58", "q59", "q60", "q61", "q63", "q64", "q66",
    "q67", "q70", "q72", "q74", "q75", "q77", "q78", "q80", "q81", "q83",
    "q85", "q86", "q87", "q88", "q89", "q91", "q93", "q95", "q97",
})


@pytest.fixture(scope="module")
def tpcds_tables():
    from trino_tpu.connectors.tpcds import TPCDS_SCHEMAS, tpcds_data

    # only the tables the query subset touches, to keep oracle load fast
    needed = set()
    for sql in QUERIES.values():
        for t in TPCDS_SCHEMAS:
            if t in sql:
                needed.add(t)
    return {t: tpcds_data(t, SCALE) for t in sorted(needed)}


@pytest.fixture(scope="module")
def tpcds_oracle(tpcds_tables):
    from trino_tpu.connectors.tpcds import TPCDS_SCHEMAS

    return SqliteOracle(tpcds_tables, schemas=TPCDS_SCHEMAS)


@pytest.fixture(scope="module")
def engine():
    from trino_tpu.connectors.tpcds import TpcdsConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="tpcds")
    eng.register_catalog("tpcds", TpcdsConnector(SCALE))
    return eng


@pytest.mark.parametrize(
    "name",
    [
        pytest.param(n, marks=pytest.mark.slow) if n in SLOW else n
        for n in sorted(QUERIES)
    ],
)
def test_tpcds_query(name, engine, tpcds_oracle):
    sql = QUERIES[name]
    got = engine.query(sql)
    expected = tpcds_oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])
