"""Result & fragment cache plane (runtime/resultcache.py).

Covers the cache-correctness contract end to end: hit/miss/eviction,
typed DML invalidation (DELETE / UPDATE / MERGE and Iceberg commits),
time-travel and non-deterministic bypass, history-driven admission, the
two-client in-flight dedup race (one execution), fragment memoization
against the uncached oracle, and the crash-restart regression — a
resumed coordinator must come up COLD and never serve a pre-crash result
for a table whose snapshot advanced while it was down.
"""

import threading
import time

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.runtime.resultcache import (
    FragmentMemo, ResultCache, has_nondeterministic,
)
from trino_tpu.testing import DistributedQueryRunner

pytestmark = pytest.mark.smoke


# ------------------------------------------------------------------ helpers


class CountingMemoryConnector(MemoryConnector):
    """Counts read_split calls per table (proof of what re-executed) and
    can block reads on a gate for deterministic concurrency tests."""

    def __init__(self):
        super().__init__()
        self.reads: dict[str, int] = {}
        self.gate = threading.Event()
        self.gate.set()
        self._rlock = threading.Lock()

    def read_split(self, split, columns):
        with self._rlock:
            self.reads[split.table] = self.reads.get(split.table, 0) + 1
        assert self.gate.wait(timeout=60), "test gate never opened"
        return super().read_split(split, columns)


def _make_conn():
    conn = CountingMemoryConnector()
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("t", {
        "k": np.arange(100, dtype=np.int64),
        "v": (np.arange(100, dtype=np.int64) % 7) * 10,
    })
    return conn


@pytest.fixture()
def runner():
    conn = _make_conn()
    r = DistributedQueryRunner(num_workers=2, default_catalog="memory")
    r.register_catalog("memory", conn)
    r.start()
    r.coordinator.session.set("result_cache_min_recurrences", "0")
    r.conn = conn
    yield r
    r.stop()


def _run(runner, sql):
    """Submit through the managed path and return (rows, record) — the
    record carries the cached flag and the cache disposition."""
    coord = runner.coordinator
    qid = coord.submit_query(sql)
    rec = coord.queries[qid]
    assert rec["done"].wait(timeout=120), "query never finished"
    assert rec["sm"].state == "FINISHED", rec["sm"].error
    return rec["result"], rec


SQL = "select v, count(*) from t group by v order by v"


# ---------------------------------------------------------- hit/miss basics


def test_second_identical_query_hits(runner):
    rows1, rec1 = _run(runner, SQL)
    reads1 = dict(runner.conn.reads)
    rows2, rec2 = _run(runner, SQL)
    assert not rec1.get("cached")
    assert rec2.get("cached") is True
    assert rows2 == rows1
    # a hit runs NOTHING on the cluster: no new connector reads, no stages
    assert runner.conn.reads == reads1
    assert rec2["query_info"]["stage_count"] == 0
    assert rec2["query_info"]["cache"]["disposition"] == "hit"
    # hits still reach the history store (admission feeds on recurrences)
    hist = [
        h for h in runner.coordinator.history.list(limit=10)
        if h.get("query_id") == rec2["sm"].query_id
    ]
    assert hist and hist[0].get("cached") is True


def test_textually_different_equivalent_plans_share_entry(runner):
    _run(runner, "select k from t where k < 5 order by k")
    rows, rec = _run(runner, "SELECT k FROM t WHERE k < 5 ORDER BY k")
    assert rec.get("cached") is True
    assert rows == [(i,) for i in range(5)]


def test_disabled_session_property_bypasses(runner):
    _run(runner, SQL)
    runner.coordinator.session.set("result_cache_enabled", "false")
    _, rec = _run(runner, SQL)
    assert not rec.get("cached")


# ------------------------------------------------------- typed invalidation


def test_delete_invalidates(runner):
    rows1, _ = _run(runner, "select count(*) from t")
    assert rows1 == [(100,)]
    _run(runner, "delete from t where k < 10")
    rows2, rec2 = _run(runner, "select count(*) from t")
    assert not rec2.get("cached")
    assert rows2 == [(90,)]


def test_update_invalidates(runner):
    rows1, _ = _run(runner, "select sum(v) from t")
    _run(runner, "update t set v = 0 where k >= 0")
    rows2, rec2 = _run(runner, "select sum(v) from t")
    assert not rec2.get("cached")
    assert rows2 == [(0,)]
    assert rows2 != rows1


def test_merge_invalidates(runner):
    runner.conn.create_table(
        "s", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    runner.conn.insert("s", {
        "k": np.arange(5, dtype=np.int64),
        "v": np.full(5, 999, dtype=np.int64),
    })
    rows1, _ = _run(runner, "select max(v) from t")
    _run(
        runner,
        "merge into t using s on t.k = s.k "
        "when matched then update set v = s.v",
    )
    rows2, rec2 = _run(runner, "select max(v) from t")
    assert not rec2.get("cached")
    assert rows2 == [(999,)]
    assert rows1 != rows2


def test_insert_invalidates(runner):
    _run(runner, "select count(*) from t")
    _run(runner, "insert into t values (1000, 1)")
    rows, rec = _run(runner, "select count(*) from t")
    assert not rec.get("cached")
    assert rows == [(101,)]


# ------------------------------------------ snapshot versioning (iceberg)


def _iceberg_runner(tmp_path, journal=False):
    from trino_tpu.connectors.iceberg import IcebergConnector

    conn = IcebergConnector(str(tmp_path / "wh"))
    r = DistributedQueryRunner(
        num_workers=2, default_catalog="iceberg",
        journal_path=(str(tmp_path / "journal.jsonl") if journal else None),
    )
    r.register_catalog("iceberg", conn)
    r.start()
    r.coordinator.session.set("result_cache_min_recurrences", "0")
    r.conn = conn
    return r


def test_external_iceberg_commit_invalidates(tmp_path):
    """A commit that never touched the engine (external writer bumping the
    snapshot id) is caught by the version-vector mismatch at lookup — the
    typed ``invalidated`` path, not TTL luck."""
    r = _iceberg_runner(tmp_path)
    try:
        _run(r, "create table ice (k bigint)")
        _run(r, "insert into ice values (1), (2)")
        rows1, _ = _run(r, "select count(*) from ice")
        _, rec = _run(r, "select count(*) from ice")
        assert rec.get("cached") is True and rows1 == [(2,)]
        # external commit: straight through the connector, no engine hook
        r.conn.insert("ice", {"k": np.array([3], dtype=np.int64)})
        rows2, rec2 = _run(r, "select count(*) from ice")
        assert not rec2.get("cached")
        assert rows2 == [(3,)]
    finally:
        r.stop()


def test_time_travel_bypasses(tmp_path):
    r = _iceberg_runner(tmp_path)
    try:
        _run(r, "create table ice (k bigint)")
        _run(r, "insert into ice values (1)")
        _run(r, "insert into ice values (2), (3)")
        for _ in range(2):
            rows, rec = _run(r, 'select k from "ice@2" order by k')
            assert rows == [(1,)]
            assert not rec.get("cached")
            assert rec["cache"]["disposition"] == "bypass"
    finally:
        r.stop()


# ------------------------------------------------- non-determinism bypass


def test_nondeterministic_bypasses(runner):
    # random() < 2.0 is always true — deterministic RESULT, but the call
    # makes the statement uncacheable (folded to a constant at plan time,
    # so only the AST check can see it)
    for _ in range(2):
        _, rec = _run(runner, "select count(*) from t where random() < 2.0")
        assert not rec.get("cached")
        assert rec["cache"]["disposition"] == "bypass"
        assert rec["cache"]["reason"] == "nondeterministic"


def test_has_nondeterministic_on_ast():
    from trino_tpu.sql import statements as S

    det = S.parse_statement("select k + 1 from t where k < 3")
    rnd = S.parse_statement("select k from t where random() < 0.5")
    assert not has_nondeterministic(det.query)
    assert has_nondeterministic(rnd.query)


# --------------------------------------------------- history-driven admission


def test_admission_threshold(runner):
    runner.coordinator.session.set("result_cache_min_recurrences", "3")
    sql = "select min(k), max(k) from t"
    # run N sees N-1 history records for the signature: runs 1-4 execute
    # (admission opens at run 4, which stores), run 5 is the first hit
    for i in range(4):
        _, rec = _run(runner, sql)
        assert not rec.get("cached"), f"run {i + 1} cached too early"
    _, rec = _run(runner, sql)
    assert rec.get("cached") is True


# ------------------------------------------------------ eviction / TTL (unit)


def test_lru_eviction_under_bytes_budget():
    rows = [("x" * 100,)]  # one entry estimates to 64 + 24 + 48 + 16 + 100
    c = ResultCache(max_bytes=2 * 252 + 50)  # room for two entries, not three
    k1 = ("h1", (("m.t", 0),))
    k2 = ("h2", (("m.t", 0),))
    k3 = ("h3", (("m.t", 0),))
    c.store(k1, rows, ["c"])
    c.store(k2, rows, ["c"])
    assert c.lookup(k1) is not None  # k1 now MRU
    c.store(k3, rows, ["c"])  # over budget: k2 (LRU) goes
    assert c.lookup(k2) is None
    assert c.lookup(k1) is not None
    assert c.lookup(k3) is not None


def test_ttl_expiry_and_oversized_store():
    c = ResultCache(max_bytes=10_000)
    key = ("h", (("m.t", 0),))
    c.store(key, [(1,)], ["c"])
    e = c._entries[key]
    e.created -= 100.0  # age it past any ttl
    assert c.lookup(key, ttl_s=1.0) is None
    # a single result larger than the whole budget is never stored
    c.store(("big", ()), [("y" * 20_000,)], ["c"])
    assert c.lookup(("big", ())) is None


def test_stale_version_vector_dropped_as_invalidated():
    c = ResultCache(max_bytes=10_000)
    old = ("h", (("m.t", 1),))
    new = ("h", (("m.t", 2),))
    c.store(old, [(1,)], ["c"])
    assert c.lookup(new) is None  # same planhash, moved table: drops old
    assert c.lookup(old) is None


def test_invalidate_table_unit():
    c = ResultCache(max_bytes=10_000)
    c.store(("h1", (("m.t", 1),)), [(1,)], ["c"])
    c.store(("h2", (("m.u", 1),)), [(2,)], ["c"])
    assert c.invalidate_table("m", "t") == 1
    assert c.lookup(("h1", (("m.t", 1),))) is None
    assert c.lookup(("h2", (("m.u", 1),))) is not None


# ------------------------------------------------------- in-flight dedup race


def test_concurrent_identical_queries_execute_once(runner):
    coord = runner.coordinator
    # baseline: connector reads of one full execution
    _run(runner, SQL)
    reads_per_exec = sum(runner.conn.reads.values())
    coord.result_cache.clear()
    runner.conn.reads.clear()

    runner.conn.gate.clear()  # block execution mid-scan
    q1 = coord.submit_query(SQL)
    r1 = coord.queries[q1]
    # wait until the leader is actually executing (a read arrived)
    for _ in range(600):
        if runner.conn.reads.get("t"):
            break
        time.sleep(0.05)
    q2 = coord.submit_query(SQL)
    r2 = coord.queries[q2]
    runner.conn.gate.set()
    assert r1["done"].wait(timeout=120) and r2["done"].wait(timeout=120)
    assert r1["sm"].state == "FINISHED", r1["sm"].error
    assert r2["sm"].state == "FINISHED", r2["sm"].error
    assert r1["result"] == r2["result"]
    # exactly ONE execution hit the connector; exactly one record is a hit
    assert sum(runner.conn.reads.values()) == reads_per_exec
    assert [bool(r1.get("cached")), bool(r2.get("cached"))].count(True) == 1


# ------------------------------------------------------- fragment memoization


JOIN_SQL = (
    "select sum(a.v + b.v) from t a, t b where a.k = b.k and b.k < 50"
)


def test_fragment_memo_reuses_leaf_scans(tmp_path):
    conn = _make_conn()
    r = DistributedQueryRunner(num_workers=2, default_catalog="memory")
    r.register_catalog("memory", conn)
    r.start()
    coord = r.coordinator
    coord.session.set("retry_policy", "TASK")
    coord.session.set("exchange_spool_dir", str(tmp_path / "spool"))
    # partitioned join: BOTH scan sides become leaf scan+filter fragments
    coord.session.set("join_distribution_type", "PARTITIONED")
    # admission never opens: every run re-executes, so the second run's
    # reuse can only come from the fragment memo
    coord.session.set("result_cache_min_recurrences", "99")
    try:
        rows1, rec1 = _run(r, JOIN_SQL)
        assert rec1.get("memo_misses", 0) >= 1
        assert len(coord.fragment_memo) >= 1
        reads1 = sum(conn.reads.values())
        rows2, rec2 = _run(r, JOIN_SQL)
        assert rows2 == rows1
        assert rec2.get("memo_hits", 0) >= 1
        # memoized leaf fragments re-read the spool, not the connector
        assert sum(conn.reads.values()) == reads1
        # oracle: same rows with the whole plane off
        coord.session.set("result_cache_enabled", "false")
        rows3, _ = _run(r, JOIN_SQL)
        assert rows3 == rows1
    finally:
        r.stop()


def test_fragment_memo_invalidated_by_dml(tmp_path):
    conn = _make_conn()
    r = DistributedQueryRunner(num_workers=2, default_catalog="memory")
    r.register_catalog("memory", conn)
    r.start()
    coord = r.coordinator
    coord.session.set("retry_policy", "TASK")
    coord.session.set("exchange_spool_dir", str(tmp_path / "spool"))
    coord.session.set("join_distribution_type", "PARTITIONED")
    coord.session.set("result_cache_min_recurrences", "99")
    try:
        rows1, _ = _run(r, JOIN_SQL)
        _run(r, "delete from t where k = 1")
        rows2, rec2 = _run(r, JOIN_SQL)
        assert not rec2.get("memo_hits")  # version vector moved
        assert rows2 != rows1
    finally:
        r.stop()


def test_fragment_key_rejects_non_leaf():
    class Frag:
        inputs = [1]
        output_kind = "hash"
        root = None

    assert FragmentMemo.fragment_key(Frag(), {}, None) is None


# ------------------------------------------- crash-restart cold-cache contract


def test_restart_never_serves_pre_crash_snapshot(tmp_path):
    """Satellite regression: the cache is never journaled.  A coordinator
    that cached a result, died, and missed an external snapshot advance
    must come up cold and re-execute — the pre-crash rows would be stale."""
    r = _iceberg_runner(tmp_path, journal=True)
    try:
        _run(r, "create table ice (k bigint)")
        _run(r, "insert into ice values (1), (2)")
        rows1, _ = _run(r, "select count(*) from ice")
        _, rec = _run(r, "select count(*) from ice")
        assert rec.get("cached") is True and rows1 == [(2,)]

        port = r.kill_coordinator()
        # snapshot advances while the coordinator is down
        r.conn.insert("ice", {"k": np.array([3, 4], dtype=np.int64)})
        r.restart_coordinator(port)
        r.coordinator.session.set("result_cache_min_recurrences", "0")

        rows2, rec2 = _run(r, "select count(*) from ice")
        assert not rec2.get("cached"), "restarted coordinator served stale"
        assert rows2 == [(4,)]
        assert r.coordinator.result_cache.stats()["entries"] <= 1
    finally:
        r.stop()


# ----------------------------------------------------------- cache chaos tier


def test_chaos_no_stale_reads_under_dml_and_failures(tmp_path):
    """scripts/chaos_tier.sh cache: a hot cached query interleaved with
    DML, a worker kill, and a coordinator restart must never return a
    stale row count at any point."""
    conn = _make_conn()
    r = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.2,
        journal_path=str(tmp_path / "journal.jsonl"),
    )
    r.register_catalog("memory", conn)
    r.start()
    coord = r.coordinator
    coord.session.set("result_cache_min_recurrences", "0")
    coord.session.set("retry_policy", "TASK")
    coord.session.set("exchange_spool_dir", str(tmp_path / "spool"))
    sql = "select count(*) from t"
    expected = 100
    try:
        for _ in range(2):  # warm + hit
            rows, _ = _run(r, sql)
            assert rows == [(expected,)]

        _run(r, "delete from t where k < 10")
        expected -= 10
        rows, rec = _run(r, sql)
        assert rows == [(expected,)] and not rec.get("cached")

        r.kill_worker(0)  # cached entries must survive OR re-execute right
        rows, _ = _run(r, sql)
        assert rows == [(expected,)]

        _run(r, "insert into t values (2000, 1), (2001, 2)")
        expected += 2
        rows, rec = _run(r, sql)
        assert rows == [(expected,)] and not rec.get("cached")

        port = r.kill_coordinator()
        conn.truncate("t")  # external mutation while the coordinator is down
        expected = 0
        r.restart_coordinator(port)
        r.coordinator.session.set("result_cache_min_recurrences", "0")
        # the replacement coordinator re-learns liveness from heartbeats;
        # wait for the detector to quarantine the worker killed above so
        # scheduling lands on the survivor
        dead = r.workers[0].url
        deadline = time.time() + 15
        while dead in r.coordinator.alive_workers() and time.time() < deadline:
            time.sleep(0.1)
        rows, rec = _run(r, sql)
        assert rows == [(expected,)], "stale read after restart"
        assert not rec.get("cached")
    finally:
        r.stop()


# ------------------------------------------------------ observability surface


def test_explain_analyze_cache_footer(runner):
    _run(runner, SQL)
    _run(runner, SQL)  # second: the plain query would hit
    rows, _ = _run(runner, f"explain analyze {SQL}")
    text = "\n".join(r[0] for r in rows)
    assert "-- cache: hit" in text
    assert "key=" in text


def test_metrics_families_present(runner):
    import urllib.request

    _run(runner, SQL)
    _run(runner, SQL)
    with urllib.request.urlopen(
        f"{runner.coordinator.url}/metrics", timeout=10
    ) as resp:
        body = resp.read().decode()
    assert 'trino_tpu_result_cache_events_total{event="hit"}' in body
    assert "trino_tpu_result_cache_bytes" in body
    assert "trino_tpu_fragment_memo_events_total" in body
