"""On-hardware differential tier: TPC-H on the real TPU chip vs the oracle.

The CPU suite (conftest.py) hard-forces JAX_PLATFORMS=cpu for true float64,
so nothing it runs touches the chip.  This tier re-enables the hardware
platform in a subprocess, runs a TPC-H subset there — through the Pallas
fused-aggregation kernel where eligible — and diffs the rows against the
sqlite oracle in the parent:

- integer results (counts, BIGINT sums) must be EXACT: the limb-decomposed
  MXU path (ops/pallas/segreduce.py) guarantees bit-exact int64 on hardware
  that has no native int64 or float64.
- doubles compare at 1e-6 relative: the Kahan-compensated f32 matmul floor
  is ~1e-8; the engine is deterministic run-to-run (fixed reduction trees),
  which the reference's threaded Java engine is not.

Reference pattern: AbstractTestQueryFramework.assertQuery
(testing/trino-testing/.../AbstractTestQueryFramework.java:344) — same
differential idea, with hardware in the loop.

Skipped when no TPU platform is available (e.g. plain CPU CI).
"""

import json
import os
import subprocess
import sys

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import QUERIES

_HW = os.environ.get("TRINO_TPU_HW_PLATFORM", "")
_SCALE = 0.01

# ALL 22 TPC-H queries run on the chip (round-4 verdict asked for the full
# suite: TPU-specific numerics — Kahan f32 floors, f64 emulation, limb-exact
# int64 — are only proven where they actually run), plus window coverage
# (w01) and the SPMD shard_map path on the chip itself (q03_dist runs
# through Engine(distributed=True) over a 1-device mesh — collectives
# compile and execute on hardware).  The persistent compile cache keeps
# repeat runs to seconds; the first run pays one compile per query.
_TPU_QUERIES = sorted(QUERIES) + ["w01"]
_TPU_DISTRIBUTED = ["q03"]  # run again through shard_map on the chip

# window-function coverage (TPC-H itself has no OVER clauses)
_EXTRA_SQL = {
    "w01": """
        select l_orderkey, l_linenumber,
               sum(l_quantity) over (partition by l_orderkey) as oq,
               row_number() over (partition by l_orderkey
                                  order by l_linenumber) as rn
        from lineitem
        where l_orderkey < 200
        order by l_orderkey, l_linenumber
    """,
}

_RUNNER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_compilation_cache_dir",
                  os.path.join({repo!r}, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.engine import Engine

assert jax.default_backend() != "cpu", f"expected hardware, got {{jax.default_backend()}}"
from tests.tpch_queries import QUERIES

sqls = dict(QUERIES)
sqls.update({extra!r})
eng = Engine()
eng.register_catalog("tpch", TpchConnector({scale}))
out = {{}}
for name in {names!r}:
    rows = eng.query(sqls[name])
    out[name] = [list(r) for r in rows]
deng = Engine(distributed=True)
deng.register_catalog("tpch", TpchConnector({scale}))
for name in {dist_names!r}:
    rows = deng.query(sqls[name])
    out[name + "_dist"] = [list(r) for r in rows]
print("\nRESULT:" + json.dumps(out))
"""


@pytest.fixture(scope="module")
def tpu_results():
    if not _HW or _HW == "cpu":
        pytest.skip("no TPU platform available (explicitly CPU)")
    env = dict(os.environ)
    if _HW == "auto":
        env.pop("JAX_PLATFORMS", None)  # let jax autodetect the accelerator
    else:
        env["JAX_PLATFORMS"] = _HW
    env.pop("XLA_FLAGS", None)  # drop the CPU suite's virtual-device forcing
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = _RUNNER.format(
        repo=repo, scale=_SCALE, names=_TPU_QUERIES,
        dist_names=_TPU_DISTRIBUTED, extra=_EXTRA_SQL,
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=repo,
        capture_output=True,
        text=True,
        timeout=3600,
    )
    if proc.returncode != 0:
        pytest.skip(f"TPU subprocess failed (hardware unavailable?):\n{proc.stderr[-2000:]}")
    payload = [l for l in proc.stdout.splitlines() if l.startswith("RESULT:")]
    assert payload, f"no RESULT line in TPU subprocess output:\n{proc.stdout[-2000:]}"
    return json.loads(payload[-1][len("RESULT:"):])


@pytest.mark.parametrize(
    "name", _TPU_QUERIES + [q + "_dist" for q in _TPU_DISTRIBUTED]
)
def test_tpch_on_tpu(name, tpu_results, oracle):
    base = name[: -len("_dist")] if name.endswith("_dist") else name
    got = [tuple(r) for r in tpu_results[name]]
    want = oracle.query(_EXTRA_SQL.get(base) or QUERIES[base])
    from tests.tpch_queries import ORDERED

    ordered = ORDERED.get(base, True) if base not in _EXTRA_SQL else True
    assert_rows_equal(got, want, ordered=ordered, rtol=1e-6)


def test_integer_results_exact_on_tpu(tpu_results, oracle):
    """Counts and BIGINT sums from the chip are bit-exact, not approximate."""
    got = [tuple(r) for r in tpu_results["q01"]]
    want = oracle.query(QUERIES["q01"])
    assert len(got) == len(want)
    for g, w in zip(got, want):
        # q01: count_order is the last column, count(*) semantics
        assert int(g[-1]) == int(w[-1])
