"""Window function differential tests vs sqlite (which has full window
support), mirroring the reference's AbstractTestWindowQueries suite
(testing/trino-testing/.../AbstractTestWindowQueries.java)."""

import pytest

from tests.oracle import assert_rows_equal

WINDOW_QUERIES = {
    "row_number": """
        select o_custkey, o_orderkey, row_number() over
          (partition by o_custkey order by o_orderdate, o_orderkey) as rn
        from orders where o_custkey < 100
    """,
    "rank_dense": """
        select o_custkey, o_orderpriority,
          rank() over (partition by o_custkey order by o_orderpriority) as r,
          dense_rank() over (partition by o_custkey order by o_orderpriority) as dr
        from orders where o_custkey < 50
    """,
    "running_sum": """
        select o_custkey, o_orderkey,
          sum(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey
                                  rows unbounded preceding) as running
        from orders where o_custkey < 60
    """,
    "range_peers": """
        select o_custkey, o_orderdate,
          count(*) over (partition by o_custkey order by o_orderdate) as cnt_range
        from orders where o_custkey < 60
    """,
    "whole_partition": """
        select o_custkey, o_orderkey,
          sum(o_totalprice) over (partition by o_custkey) as total,
          count(*) over (partition by o_custkey) as n,
          max(o_totalprice) over (partition by o_custkey) as mx
        from orders where o_custkey < 80
    """,
    "global_window": """
        select o_orderkey, sum(o_totalprice) over () as grand_total
        from orders where o_orderkey < 200
    """,
    # RANGE offsets order by a NUMERIC column: sqlite holds our DATE columns
    # as TEXT, so its value-distance arithmetic over dates cannot oracle
    "range_offset_frame": """
        select o_custkey, o_orderkey,
          sum(o_totalprice) over (partition by o_custkey order by o_totalprice
                                  range between 20000 preceding and 20000 following) as s20k,
          count(*) over (partition by o_custkey order by o_totalprice
                         range between 50000 preceding and current row) as c50k
        from orders where o_custkey < 60
    """,
    "range_offset_desc": """
        select o_custkey, o_orderkey,
          count(*) over (partition by o_custkey order by o_totalprice desc
                         range between 30000 preceding and 30000 following) as c30k
        from orders where o_custkey < 40
    """,
    "lag_lead": """
        select o_custkey, o_orderkey,
          lag(o_orderkey) over (partition by o_custkey order by o_orderdate, o_orderkey) as prev_k,
          lead(o_orderkey) over (partition by o_custkey order by o_orderdate, o_orderkey) as next_k
        from orders where o_custkey < 40
    """,
    "first_last": """
        select o_custkey, o_orderkey,
          first_value(o_orderkey) over (partition by o_custkey order by o_orderdate, o_orderkey) as fv
        from orders where o_custkey < 40
    """,
    "window_over_agg": """
        select o_custkey, sum(o_totalprice) as s,
          rank() over (order by sum(o_totalprice) desc) as r
        from orders where o_custkey < 30 group by o_custkey
    """,
    "avg_min_running": """
        select o_custkey, o_orderkey,
          avg(o_totalprice) over (partition by o_custkey order by o_orderkey
                                  rows unbounded preceding) as ra,
          min(o_totalprice) over (partition by o_custkey order by o_orderkey
                                  rows unbounded preceding) as rm
        from orders where o_custkey < 40
    """,
    "offset_frame_sum": """
        select o_custkey, o_orderkey,
          sum(o_totalprice) over (partition by o_custkey order by o_orderkey
                                  rows between 2 preceding and 1 following) as s,
          count(*) over (partition by o_custkey order by o_orderkey
                         rows between 1 preceding and 1 following) as c
        from orders where o_custkey < 40
    """,
    "offset_frame_minmax": """
        select o_custkey, o_orderkey,
          min(o_totalprice) over (partition by o_custkey order by o_orderkey
                                  rows between 2 preceding and current row) as mn,
          max(o_totalprice) over (partition by o_custkey order by o_orderkey
                                  rows between current row and unbounded following) as mx
        from orders where o_custkey < 40
    """,
    "ntile_ranks": """
        select o_custkey, o_orderkey,
          ntile(3) over (partition by o_custkey order by o_orderkey) as nt,
          percent_rank() over (partition by o_custkey order by o_orderkey) as pr,
          cume_dist() over (partition by o_custkey order by o_orderkey) as cd
        from orders where o_custkey < 40
    """,
    "nth_value": """
        select o_custkey, o_orderkey,
          nth_value(o_orderkey, 2) over (partition by o_custkey
                                         order by o_orderkey
                                         rows between unbounded preceding
                                         and unbounded following) as nv
        from orders where o_custkey < 40
    """,
}


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", sorted(WINDOW_QUERIES))
def test_window(name, engine, oracle):
    sql = WINDOW_QUERIES[name]
    got = engine.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=False)


def test_window_distributed(tpch_tiny, oracle):
    import jax

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    # 4 virtual devices: the sharding surface (repartition-by-partition-keys,
    # per-shard windows) compiles in half the time of the 8-device mesh and
    # exercises the same collectives; the 8-device path is covered by
    # test_tpch_distributed and the driver's dryrun_multichip gate.
    eng = Engine(distributed=True, devices=jax.devices()[:4])
    eng.register_catalog("tpch", TpchConnector(0.01))
    sql = WINDOW_QUERIES["whole_partition"]
    assert_rows_equal(eng.query(sql), oracle.query(sql), ordered=False)
    # global (unpartitioned) windows gather to one shard — distinct codepath
    sql = WINDOW_QUERIES["global_window"]
    assert_rows_equal(eng.query(sql), oracle.query(sql), ordered=False)
