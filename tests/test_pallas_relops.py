"""Differential tests: Pallas hash data-plane kernels vs the sort oracle.

Every case runs the SAME relops entry point twice — once with the kernel
policy disabled (legacy sort path, the oracle) and once with kernels enabled
in interpret mode — and asserts identical results.  Group output order is a
deliberate non-guarantee (the engine's Aggregate output is unordered until a
Sort), so group-by comparisons align rows by key; join comparisons align by
full output row.

Covers the satellite checklist: nulls in keys and arguments, dictionary-
coded keys, decimal128 limb aggregation, empty/all-filtered inputs, hash-
collision stress near table capacity, the overflow-to-sort fallback
boundary, and the session kill-switch restoring the legacy path.
"""

import decimal

import numpy as np
import pytest
import jax.numpy as jnp

from trino_tpu.data.types import BIGINT, DOUBLE, INTEGER, DecimalType
from trino_tpu.ops import kernels, relops
from trino_tpu.ops.expr import ColumnVal
from trino_tpu.ops.relops import AggSpec


@pytest.fixture(autouse=True)
def _restore_policy():
    yield
    kernels.set_policy(kernels.KernelPolicy())


def _cv(data, valid=None, dict_=None, typ=None, data2=None):
    return ColumnVal(
        jnp.asarray(data),
        None if valid is None else jnp.asarray(valid),
        dict_,
        typ,
        None if data2 is None else jnp.asarray(data2),
    )


def _norm_groups(out):
    """Group rows keyed/sorted by key tuple: (keys..., aggs...) per live
    group, order-independent."""
    out_keys, out_aggs, out_live, n_groups = out
    live = np.asarray(out_live)
    rows = []
    for g in range(live.shape[0]):
        if not live[g]:
            continue
        row = []
        for k in out_keys:
            d, v = np.asarray(k[0])[g], k[1]
            ok = True if v is None else bool(np.asarray(v)[g])
            khi = k[2] if len(k) > 2 else None
            if khi is not None:
                full = int(np.asarray(khi)[g]) * (1 << 64) + int(np.uint64(d))
                row.append((ok, full if ok else None))
            else:
                row.append((ok, d.item() if ok else None))
        for a in out_aggs:
            d = np.asarray(a[0])[g]
            ok = True if a[1] is None else bool(np.asarray(a[1])[g])
            if len(a) == 4:  # decimal128: (lo, valid, None, hi)
                full = int(np.asarray(a[3])[g]) * (1 << 64) + int(
                    np.uint64(d)
                )
                row.append((ok, full if ok else None))
            else:
                row.append((ok, round(float(d), 6) if ok else None))
        rows.append(tuple(row))
    return sorted(rows, key=repr), int(np.asarray(n_groups))


def _compare_groupby(keys, args, specs, live, G, expect_impl="pallas"):
    kernels.set_policy(kernels.KernelPolicy(enabled=False))
    legacy = _norm_groups(
        relops.group_aggregate(keys, args, specs, jnp.asarray(live), G)
    )
    kernels.set_policy(kernels.KernelPolicy(enabled=True, interpret=True))
    ev = kernels.begin_capture()
    try:
        hashed = _norm_groups(
            relops.group_aggregate(keys, args, specs, jnp.asarray(live), G)
        )
    finally:
        kernels.end_capture()
    impls = {e[1] for e in ev if e[0] == "group_by"}
    assert hashed == legacy
    if expect_impl is not None:
        assert expect_impl in impls, (impls, ev)
    return legacy


def test_groupby_nulls_in_keys_and_args():
    rng = np.random.default_rng(7)
    n = 3000
    keys = [
        _cv(rng.integers(0, 40, n), None, None, BIGINT),
        _cv(rng.integers(-5, 5, n).astype(np.int32),
            rng.random(n) > 0.1, None, INTEGER),
    ]
    arg = _cv(rng.integers(-1000, 1000, n), rng.random(n) > 0.15, None, BIGINT)
    specs = [AggSpec("sum"), AggSpec("count"), AggSpec("min"),
             AggSpec("max"), AggSpec("avg"), AggSpec("count_star")]
    live = rng.random(n) > 0.2
    _compare_groupby(keys, [arg] * 5 + [None], specs, live, 1024)


def test_groupby_dict_coded_keys():
    from trino_tpu.data.page import Dictionary
    from trino_tpu.data.types import VARCHAR

    rng = np.random.default_rng(11)
    n = 2000
    d = Dictionary(np.asarray([f"v{i}" for i in range(30)], object))
    keys = [
        _cv(rng.integers(0, 30, n).astype(np.int32), None, d, VARCHAR),
        # second key forces the general (non-direct-code) path; wide-
        # magnitude values exercise both 16-bit word halves
        _cv(rng.integers(0, 8, n) * ((1 << 37) + 12345), None, None, BIGINT),
    ]
    arg = _cv(rng.normal(0, 10, n), None, None, DOUBLE)
    live = rng.random(n) > 0.3
    _compare_groupby(keys, [arg, arg], [AggSpec("sum"), AggSpec("avg")],
                     live, 1024)


def test_groupby_decimal128_limb_sum():
    rng = np.random.default_rng(13)
    n = 1500
    t = DecimalType(38, 2)
    lo = rng.integers(-(1 << 62), 1 << 62, n)
    hi = rng.integers(-4, 4, n)
    keys = [_cv(rng.integers(0, 20, n), None, None, BIGINT)]
    arg = _cv(lo, rng.random(n) > 0.1, None, t, data2=hi)
    live = rng.random(n) > 0.2
    _compare_groupby(keys, [arg], [AggSpec("sum", type=t)], live, 1024)


def test_groupby_decimal128_keys():
    rng = np.random.default_rng(17)
    n = 1200
    t = DecimalType(38, 0)
    keys = [_cv(rng.integers(0, 25, n), None, None, t,
                data2=rng.integers(-2, 2, n))]
    arg = _cv(rng.integers(0, 100, n), None, None, BIGINT)
    _compare_groupby(keys, [arg], [AggSpec("sum")], np.ones(n, bool), 1024)


def test_groupby_empty_and_all_filtered():
    rng = np.random.default_rng(19)
    n = 1000
    keys = [_cv(rng.integers(0, 10, n), None, None, BIGINT)]
    arg = _cv(rng.integers(0, 100, n), None, None, BIGINT)
    legacy = _compare_groupby(keys, [arg], [AggSpec("sum")],
                              np.zeros(n, bool), 512)
    assert legacy == ([], 0)


def test_groupby_collision_stress_near_capacity():
    # cap 512 -> table 1024 slots at 0.5 load: every slot's probe chain is
    # exercised, duplicate keys race to claim the same slot across rounds
    rng = np.random.default_rng(23)
    n = 8192
    uniq = rng.integers(-(1 << 60), 1 << 60, 500)
    data = uniq[rng.integers(0, 500, n)]
    keys = [_cv(data, None, None, BIGINT)]
    arg = _cv(rng.integers(-50, 50, n), None, None, BIGINT)
    _compare_groupby(keys, [arg, arg, None],
                     [AggSpec("sum"), AggSpec("min"), AggSpec("count_star")],
                     np.ones(n, bool), 512)


def test_groupby_overflow_inflates_then_sorts():
    """More distinct groups than the capacity tier: the kernel reports an
    inflated n_groups (the executor's retry signal); the doubled tier then
    succeeds and matches the oracle; a tier past the policy limit dispatches
    the sort fallback."""
    rng = np.random.default_rng(29)
    n = 4000
    data = rng.integers(0, 700, n)  # ~700 distinct > 512 cap
    keys = [_cv(data, None, None, BIGINT)]
    arg = _cv(rng.integers(0, 9, n), None, None, BIGINT)
    kernels.set_policy(kernels.KernelPolicy(enabled=True, interpret=True))
    out = relops.group_aggregate(keys, [arg], [AggSpec("sum")],
                                 jnp.ones(n, bool), 512)
    assert int(np.asarray(out[3])) > 512  # overflow -> retry signal
    _compare_groupby(keys, [arg], [AggSpec("sum")], np.ones(n, bool), 1024)
    # past the policy limit the gate must dispatch "fallback" (sort runs)
    kernels.set_policy(kernels.KernelPolicy(
        enabled=True, interpret=True, hash_agg_max_groups=512))
    ev = kernels.begin_capture()
    try:
        relops.group_aggregate(keys, [arg], [AggSpec("sum")],
                               jnp.ones(n, bool), 1024)
    finally:
        kernels.end_capture()
    assert ("group_by", "fallback") in {(e[0], e[1]) for e in ev}


def _compare_join(kind, seed, C=1 << 15):
    rng = np.random.default_rng(seed)
    nl, nr = 2000, 300
    lc = [_cv(rng.integers(0, 100, nl), None, None, BIGINT)]
    lk = [_cv(rng.integers(0, 50, nl), rng.random(nl) > 0.05, None, BIGINT)]
    rc = [_cv(rng.integers(0, 100, nr), None, None, BIGINT)]
    rk = [_cv(rng.integers(0, 60, nr), rng.random(nr) > 0.05, None, BIGINT)]
    ll = jnp.asarray(rng.random(nl) > 0.1)
    rl = jnp.asarray(rng.random(nr) > 0.1)

    def rows(cols, lv):
        lv = np.asarray(lv)
        mats = [
            (np.asarray(c.data),
             None if c.valid is None else np.asarray(c.valid))
            for c in cols
        ]
        return sorted(
            (
                tuple(
                    d[i].item() if v is None or v[i] else None
                    for d, v in mats
                )
                for i in range(lv.shape[0])
                if lv[i]
            ),
            key=repr,
        )

    kernels.set_policy(kernels.KernelPolicy(enabled=False))
    cols0, live0, req0 = relops.equi_join(kind, lc, ll, rc, rl, lk, rk, None, C)
    kernels.set_policy(kernels.KernelPolicy(enabled=True, interpret=True))
    ev = kernels.begin_capture()
    try:
        cols1, live1, req1 = relops.equi_join(
            kind, lc, ll, rc, rl, lk, rk, None, C
        )
    finally:
        kernels.end_capture()
    assert int(req0) == int(req1)
    assert rows(cols0, live0) == rows(cols1, live1)
    assert ("join", "pallas") in {(e[0], e[1]) for e in ev}


@pytest.mark.parametrize("kind", ["inner", "semi", "anti", "left", "null_anti"])
def test_join_kinds_match_sort(kind):
    _compare_join(kind, seed=11)


def test_join_build_over_limit_dispatches_fallback():
    rng = np.random.default_rng(31)
    nl, nr = 500, 4000  # build side past the policy limit
    lk = [_cv(rng.integers(0, 50, nl), None, None, BIGINT)]
    rk = [_cv(rng.integers(0, 50, nr), None, None, BIGINT)]
    lc = [_cv(rng.integers(0, 9, nl), None, None, BIGINT)]
    rc = [_cv(rng.integers(0, 9, nr), None, None, BIGINT)]
    kernels.set_policy(kernels.KernelPolicy(
        enabled=True, interpret=True, hash_join_max_build=1024))
    ev = kernels.begin_capture()
    try:
        relops.equi_join("inner", lc, jnp.ones(nl, bool), rc,
                         jnp.ones(nr, bool), lk, rk, None, 1 << 16)
    finally:
        kernels.end_capture()
    assert ("join", "fallback") in {(e[0], e[1]) for e in ev}


def test_kill_switch_restores_legacy_dispatch():
    rng = np.random.default_rng(37)
    n = 800
    keys = [_cv(rng.integers(0, 10, n), None, None, BIGINT)]
    arg = _cv(rng.integers(0, 100, n), None, None, BIGINT)
    kernels.set_policy(kernels.KernelPolicy(enabled=False))
    ev = kernels.begin_capture()
    try:
        relops.group_aggregate(keys, [arg], [AggSpec("sum")],
                               jnp.ones(n, bool), 512)
    finally:
        kernels.end_capture()
    impls = {e[1] for e in ev if e[0] == "group_by"}
    assert impls == {"sort"}


# ------------------------------------------------------- engine-level fused


@pytest.fixture(scope="module")
def kernel_engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", ["q01", "q06"])
def test_fused_pipeline_engine_differential(kernel_engine, name):
    """q01/q06 shapes fuse scan->filter->project->aggregate into one Pallas
    pass; the session kill-switch restores the legacy plan, and both agree
    (f32-matmul partials floor at ~1e-8 relative, same bound as the
    segreduce kernel tier)."""
    from tests.oracle import assert_rows_equal
    from tests.tpch_queries import ORDERED, QUERIES

    eng = kernel_engine
    sql = QUERIES[name]
    eng.session.set("data_plane_kernels", "false")
    legacy = eng.query(sql)
    eng.session.set("data_plane_kernels", "true")
    eng.session.set("pallas_interpret", "true")
    try:
        fused = eng.query(sql)
        ex = eng.execute(f"EXPLAIN ANALYZE {sql}")
    finally:
        eng.session.set("pallas_interpret", "false")
    lines = [r[0] for r in ex if str(r[0]).startswith("-- kernel:")]
    assert any("pallas fused_pipeline" in l for l in lines), lines
    # the fused kernel's f32 partial sums land within ~1e-7 relative BY
    # DESIGN (ops/pallas/fused.py accuracy note) — that applies to its
    # decimal outputs too, so compare them under the float tolerance
    # instead of the oracle's exact-Decimal equality
    def _approx(rows):
        return [
            tuple(
                float(v) if isinstance(v, decimal.Decimal) else v
                for v in r
            )
            for r in rows
        ]

    assert_rows_equal(
        _approx(fused), _approx(legacy), ordered=ORDERED[name], rtol=1e-6
    )


def test_fused_dispatch_metric_increments(kernel_engine):
    # dispatch counts at TRACE time, so use a q06 variant no other test has
    # traced (a jit-cache hit would legitimately not re-count)
    sql = """
    select sum(l_extendedprice * l_discount) as revenue
    from lineitem
    where l_shipdate >= date '1995-01-01' and l_quantity < 23
    """
    eng = kernel_engine
    eng.session.set("pallas_interpret", "true")
    try:
        before = kernels._DISPATCH.value("fused_pipeline", "pallas")
        eng.query(sql)
        after = kernels._DISPATCH.value("fused_pipeline", "pallas")
    finally:
        eng.session.set("pallas_interpret", "false")
    assert after > before
