"""Coordinator fleet: leases, adoption, router, failover (runtime/fleet.py).

Unit tier: lease lifecycle (acquire / renew / expire / steal), the
single-winner adoption claim, GC-owner election + the fleet-wide live-query
union, shard stability, decorrelated backoff spread, the snapshot-reading
journal replay under a concurrent foreign writer, and client endpoint-list
failover.

Cluster tier (slow/chaos, scripts/chaos_tier.sh fleet): a two-coordinator
fleet behind the FleetRouter — router shard routing end to end, and the
tentpole scenario: kill one coordinator mid multi-stage query and the
survivor adopts it off the dead member's journal with ZERO client-visible
failures and ZERO recompute of spool-committed stages.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from trino_tpu.client import StatementClient
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.runtime.failure import Backoff
from trino_tpu.runtime.fleet import FleetMember, FleetRouter, shard_for
from trino_tpu.runtime.journal import QueryJournal
from trino_tpu.testing.runner import DistributedQueryRunner

# ---------------------------------------------------------------- fixtures


class _Clock:
    """Settable clock for lease tests — expiry without sleeping."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class GatedMemoryConnector(MemoryConnector):
    """Memory connector whose reads block on a gate — holds a query
    mid-flight — and count per-table reads (the recompute witness)."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.gated_table = None
        self.reads: dict[str, int] = {}
        self._rlock = threading.Lock()

    def read_split(self, split, columns):
        with self._rlock:
            self.reads[split.table] = self.reads.get(split.table, 0) + 1
        if split.table == self.gated_table:
            assert self.gate.wait(timeout=120), "test gate never opened"
        return super().read_split(split, columns)


def _make_tables(conn):
    conn.create_table("build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)])
    conn.insert("build", {"k": np.arange(50, dtype=np.int64),
                          "w": np.arange(50, dtype=np.int64) * 10})
    conn.create_table("probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("probe", {"k": np.arange(2000, dtype=np.int64) % 50,
                          "v": np.arange(2000, dtype=np.int64)})
    return int((np.arange(2000) + (np.arange(2000) % 50) * 10).sum())


JOIN_SQL = "select sum(v + w) from probe, build where probe.k = build.k"


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


def _committed_dirs(spool_dir):
    if not os.path.isdir(spool_dir):
        return []
    return [n for n in os.listdir(spool_dir)
            if os.path.exists(os.path.join(spool_dir, n, "COMMITTED"))]


# ------------------------------------------------------------ lease lifecycle


def test_lease_acquire_renew_expire_steal(tmp_path):
    clock = _Clock()
    a = FleetMember(str(tmp_path), coordinator_id="c0", url="http://a",
                    ttl_s=10.0, clock=clock)
    b = FleetMember(str(tmp_path), coordinator_id="c1", url="http://b",
                    ttl_s=10.0, clock=clock)
    assert a.acquire() == 1
    assert b.acquire() == 1

    # renew embeds live queries; peers read them from the lease file
    assert a.renew({"q_x", "q_y"})
    [lease] = [l for l in b.peers() if l["coordinator_id"] == "c0"]
    assert lease["live_queries"] == ["q_x", "q_y"]
    assert b.expired_peers() == []

    # TTL runs out without renewal: the peer becomes an adoption candidate
    clock.t += 11.0
    assert b.renew({"q_z"})  # b renewed itself first
    expired = b.expired_peers()
    assert [l["coordinator_id"] for l in expired] == ["c0"]

    # a restart of the same identity bumps PAST the prior epoch
    a2 = FleetMember(str(tmp_path), coordinator_id="c0", url="http://a",
                     ttl_s=10.0, clock=clock)
    assert a2.acquire() == 2
    assert b.expired_peers() == []  # fresh lease: no longer expired

    # a second process taking the same UNEXPIRED identity is a steal; the
    # loser's renew sees the higher epoch and stands down
    a3 = FleetMember(str(tmp_path), coordinator_id="c0", url="http://a",
                     ttl_s=10.0, clock=clock)
    assert a3.acquire() == 3
    assert a2.renew() is False
    assert a3.renew()

    # graceful release removes the lease entirely — nothing to adopt
    a3.release()
    clock.t += 100.0
    assert [l["coordinator_id"] for l in b.expired_peers()] == []


def test_adoption_claim_single_winner(tmp_path):
    clock = _Clock()
    dead = FleetMember(str(tmp_path), coordinator_id="c9", ttl_s=1.0, clock=clock)
    dead.acquire()
    dead.renew({"q_dead"})
    clock.t += 5.0

    s1 = FleetMember(str(tmp_path), coordinator_id="c0", ttl_s=10.0, clock=clock)
    s2 = FleetMember(str(tmp_path), coordinator_id="c1", ttl_s=10.0, clock=clock)
    s1.acquire(); s2.acquire()
    [lease1] = s1.expired_peers()
    [lease2] = s2.expired_peers()
    wins = [s1.try_adopt(lease1), s2.try_adopt(lease2)]
    assert sorted(wins) == [False, True], "exactly one survivor may adopt"
    # the adopted marker stops further sweeps from seeing the corpse
    assert s1.expired_peers() == [] and s2.expired_peers() == []
    # a NEW incarnation of c9 gets a fresh epoch -> freshly adoptable later
    dead2 = FleetMember(str(tmp_path), coordinator_id="c9", ttl_s=1.0, clock=clock)
    assert dead2.acquire() == 2


def test_gc_owner_election_and_live_union(tmp_path):
    clock = _Clock()
    a = FleetMember(str(tmp_path), coordinator_id="c0", ttl_s=10.0, clock=clock)
    b = FleetMember(str(tmp_path), coordinator_id="c1", ttl_s=10.0, clock=clock)
    a.acquire(); b.acquire()
    a.renew({"q_a"}); b.renew({"q_b"})

    # exactly one owner for destructive sweeps: smallest unexpired id
    assert a.is_gc_owner() and not b.is_gc_owner()

    # both members compute the same fleet-wide live union
    assert a.fleet_live_queries() == {"q_a", "q_b"}
    assert b.fleet_live_queries() == {"q_a", "q_b"}

    # c0 dies: c1 takes over GC ownership, and the DEAD member's queries
    # stay in the union until adoption — their spool output is exactly
    # what the adopter must re-read, so GC must not touch it
    clock.t += 11.0
    b.renew({"q_b"})
    assert not a.is_gc_owner() and b.is_gc_owner()
    assert b.fleet_live_queries() == {"q_a", "q_b"}


def test_fleet_info_snapshot(tmp_path):
    clock = _Clock()
    a = FleetMember(str(tmp_path), coordinator_id="c0", url="http://a",
                    ttl_s=10.0, clock=clock)
    a.acquire()
    a.renew({"q_1"})
    info = a.info()
    assert info["coordinator_id"] == "c0" and info["gc_owner"]
    [m] = info["members"]
    assert m["alive"] and m["live_queries"] == 1 and m["url"] == "http://a"


# ------------------------------------------------------------------ sharding


def test_shard_stability_and_router_order():
    # deterministic across calls/processes (sha1, not salted hash())
    assert shard_for("q_abc123", 2) == shard_for("q_abc123", 2)
    assert all(0 <= shard_for(f"q_{i}", 3) < 3 for i in range(100))
    # non-degenerate: both shards of a 2-fleet get traffic
    shards = {shard_for(f"q_{i:04x}", 2) for i in range(64)}
    assert shards == {0, 1}

    urls = ["http://c0", "http://c1", "http://c2"]
    router = FleetRouter(urls)
    try:
        for qid in ("q_aa", "q_bb", "q_cc"):
            order = router.order_for(qid)
            # the shard owner is first, every member is a failover target
            assert order[0] == urls[shard_for(qid, 3)]
            assert sorted(order) == sorted(urls)
            assert order == router.order_for(qid)  # stable for the query
        # no query id: natural order (admission pre-mint)
        assert router.order_for(None) == urls
        # body rewrite points every member URL back at the router
        body = b'{"nextUri": "http://c1/v1/statement/q_aa/1"}'
        assert router.url.encode() in router.rewrite(body)
        assert b"http://c1" not in router.rewrite(body)
    finally:
        router.stop()


# ----------------------------------------------------- decorrelated backoff


def test_backoff_decorrelated_jitter_spread():
    # first-retry delays from a cohort of clients must SPREAD over
    # [min, 3*min], not cluster around one center: this is what keeps a
    # mass re-attach after a coordinator death from arriving in waves
    firsts = [
        Backoff(min_delay=0.1, max_delay=2.0, decorrelated=True,
                rng=random.Random(i)).delay()
        for i in range(200)
    ]
    assert all(0.1 <= d <= 0.3 + 1e-9 for d in firsts)
    assert len({round(d, 4) for d in firsts}) > 50, "delays did not spread"
    spread = max(firsts) - min(firsts)
    assert spread > 0.1, f"cohort clustered: spread={spread}"

    # the walk stays within [min, max] and is capped at max_delay
    b = Backoff(min_delay=0.1, max_delay=2.0, decorrelated=True,
                rng=random.Random(7))
    seq = [b.delay() for _ in range(50)]
    assert all(0.1 <= d <= 2.0 for d in seq)
    assert max(seq) <= 2.0
    # success() resets the walk to the first-retry distribution
    b.success()
    assert 0.1 <= b.delay() <= 0.3 + 1e-9

    # default (correlated) schedule is untouched: deterministic centers
    c = Backoff(min_delay=0.1, max_delay=2.0, jitter=0.0)
    c.failure(); d1 = c.delay()
    c.failure(); d2 = c.delay()
    assert (d1, d2) == (0.1, 0.2)


# -------------------------------------------- journal under foreign writers


def test_journal_replay_with_concurrent_foreign_writer(tmp_path):
    """An adopter replays a journal file another process may still be
    appending to (the dying peer's last buffered write, a slow NFS flush):
    replay must fold every COMPLETE record and ignore a torn tail."""
    p = str(tmp_path / "journal-c9.jsonl")
    j = QueryJournal(p)
    j.append("admit", "q_aa", sql="select 1", session={}, spooled=True)
    j.append("dispatch", "q_aa", fragment=1, ntasks=2, attempt=0)
    j.append("commit", "q_aa", fragment=1, part=0, task_id="t0")
    j.close()

    # a foreign writer holds the file open and has written HALF a record
    f = open(p, "a")
    f.write('{"kind": "commit", "query_id": "q_aa", "fragm')
    f.flush()

    states = QueryJournal.replay(p)
    assert states["q_aa"].state == "INFLIGHT"
    assert states["q_aa"].commits == {1: {0: "t0"}}

    # the writer completes the line + adds one more record: a SECOND
    # snapshot read picks both up (replay is a pure function of the bytes
    # present at stat time)
    f.write('ent": 1, "part": 1, "task_id": "t1"}\n')
    f.write(json.dumps({"kind": "finish", "query_id": "q_aa",
                        "state": "FINISHED", "error": None,
                        "error_code": None}) + "\n")
    f.flush()
    f.close()
    states2 = QueryJournal.replay(p)
    assert states2["q_aa"].state == "FINISHED"
    assert states2["q_aa"].commits == {1: {0: "t0", 1: "t1"}}


# ------------------------------------------------- client endpoint failover


class _StubCoordinator:
    """Minimal /v1/statement server: answers every POST with a complete
    inline result — enough to witness the client's endpoint failover."""

    def __init__(self):
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                outer.hits += 1
                body = json.dumps({
                    "id": "q_stub", "columns": ["one"], "data": [[1]],
                    "stats": {"state": "FINISHED"},
                }).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.hits = 0
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self._t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._t.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_client_endpoint_list_failover():
    stub = _StubCoordinator()
    # a port from a server we already closed: guaranteed refused
    probe = ThreadingHTTPServer(("127.0.0.1", 0), BaseHTTPRequestHandler)
    dead_url = f"http://127.0.0.1:{probe.server_address[1]}"
    probe.server_close()
    try:
        sc = StatementClient([dead_url, stub.url])
        assert sc.endpoints == [dead_url, stub.url]
        cols, rows = sc.execute("select 1")
        assert rows == [[1]] and stub.hits == 1
    finally:
        stub.stop()


# ------------------------------------------------------------- cluster tier


def _fleet_cluster(conn, spool_dir):
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.3,
        num_coordinators=2, fleet_ttl_s=1.5,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    for c in runner.coordinators:
        c.session.set("retry_policy", "TASK")
        c.session.set("exchange_spool_dir", spool_dir)
        c.session.set("resume_policy", "RESUME")
    return runner


class _ClientThread(threading.Thread):
    """One protocol client riding a query across the coordinator kill."""

    def __init__(self, url, sql):
        super().__init__(daemon=True)
        self.client = StatementClient(url, reattach_max_elapsed_s=90.0)
        self.sql = sql
        self.result = None
        self.error = None

    def run(self):
        try:
            self.result = self.client.execute(self.sql, timeout=120)
        except Exception as e:  # re-raised on the main thread by the test
            self.error = e


@pytest.mark.slow
@pytest.mark.chaos
def test_router_shards_and_serves_two_coordinators(tmp_path):
    conn = MemoryConnector()
    expect = _make_tables(conn)
    runner = _fleet_cluster(conn, str(tmp_path / "spool"))
    try:
        # queries through the router land on the coordinator the minted
        # id hashes to — and return correct rows through URL rewriting
        for _ in range(4):
            rows = runner.query_via_protocol(JOIN_SQL)
            assert int(rows[0][0]) == expect
        owners = {i: 0 for i in range(2)}
        for i, c in enumerate(runner.coordinators):
            with c._lock:
                for qid in c.queries:
                    owners[i] += 1
                    assert shard_for(qid, 2) == i, (
                        f"{qid} landed off-shard on c{i}"
                    )
        assert sum(owners.values()) >= 4
        # both members lease-visible and one GC owner fleet-wide
        infos = [c.fleet.info() for c in runner.coordinators]
        assert [i["gc_owner"] for i in infos].count(True) == 1
        assert all(len(i["members"]) == 2 for i in infos)
    finally:
        runner.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_kill_one_of_two_adoption_zero_recompute(tmp_path):
    """The tentpole: kill the coordinator that owns a gated multi-stage
    join after its build side spool-COMMITTED.  The survivor must adopt
    the query off the dead member's journal, re-read (not recompute) the
    committed build stage, and the client — polling through the router —
    must see ZERO failures."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    spool = str(tmp_path / "spool")
    runner = _fleet_cluster(conn, spool)
    try:
        conn.gated_table = "probe"
        t = _ClientThread(runner.client_url, JOIN_SQL)
        t.start()
        ready = _wait(
            lambda: _committed_dirs(spool) and conn.reads.get("probe", 0) > 0,
            timeout=60,
        )
        assert ready, "build stage never committed / probe never started"

        owner = None
        for i, c in enumerate(runner.coordinators):
            with c._lock:
                if any(not r["done"].is_set() for r in c.queries.values()):
                    owner = i
        assert owner is not None, "no coordinator owns the in-flight query"
        builds_before = conn.reads.get("build", 0)
        assert builds_before > 0

        runner.kill_coordinator(owner)
        conn.gate.set()
        t.join(timeout=120)
        assert not t.is_alive(), "client never finished after the kill"
        assert t.error is None, f"client saw a failure: {t.error!r}"
        _, rows = t.result
        assert int(rows[0][0]) == expect

        # profiler-witnessed zero recompute: the spool-committed build
        # stage was re-read, not re-run
        assert conn.reads.get("build", 0) == builds_before

        survivor = runner.coordinators[1 - owner]
        with survivor._lock:
            adopted = [
                (qid, rec) for qid, rec in survivor.queries.items()
                if rec.get("adopted_from")
            ]
        assert adopted, "survivor never adopted the dead member's query"
        qid, rec = adopted[0]
        fleet_info = (rec.get("query_info") or {}).get("fleet") or {}
        assert fleet_info.get("adopted")
        assert fleet_info.get("adopted_from") == f"c{owner}"
        assert fleet_info.get("stages_resumed", 0) >= 1

        # observability: adoption + lease expiry counters moved, and the
        # survivor's /metrics carries them
        body = urllib.request.urlopen(
            f"{survivor.url}/metrics", timeout=10
        ).read().decode()
        adoption_lines = [
            ln for ln in body.splitlines()
            if ln.startswith("trino_tpu_fleet_adoptions_total")
            and not ln.startswith("#")
        ]
        assert adoption_lines and float(adoption_lines[0].split()[-1]) >= 1
        assert 'trino_tpu_fleet_lease_transitions_total{event="expire"}' in body
    finally:
        conn.gate.set()
        runner.stop()


# ------------------------------------------- router failover-response audit


def _static_backend(code, body=b"{}", headers=None):
    """One fake coordinator that answers every request with a fixed
    verdict — the router's failover contract is tested against it."""

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _go(self):
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        do_GET = do_POST = do_DELETE = _go

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def test_router_midpoll_502_fails_over_and_counts_retry():
    """A member mid-teardown answers a poll with 502: the router must try
    the peer (the query may have been adopted), count the hop in
    trino_tpu_fleet_router_retries_total, and the client sees only the
    peer's 200."""
    from trino_tpu.runtime.fleet import FLEET_ROUTER_RETRIES

    bad_srv, bad = _static_backend(502, b'{"error": "teardown"}')
    ok_srv, ok = _static_backend(200, b'{"ok": true}')
    # a query id whose shard OWNER is the 502 member, so the poll hits the
    # bad coordinator first and must fail over
    qid = next(q for q in (f"q_{i}" for i in range(100))
               if shard_for(q, 2) == 0)
    router = FleetRouter([bad, ok]).start()
    try:
        before = FLEET_ROUTER_RETRIES.value()
        with urllib.request.urlopen(
            f"{router.url}/v1/statement/{qid}/0", timeout=10
        ) as r:
            assert r.status == 200 and b"ok" in r.read()
        assert FLEET_ROUTER_RETRIES.value() == before + 1
    finally:
        router.stop()
        bad_srv.shutdown()
        ok_srv.shutdown()


def test_router_unanimous_502_passes_through_with_retry_after():
    """Every member says 502: transient, pass it through — and the reply
    MUST carry Retry-After even though no backend set one (the router's
    failover-response contract: every 429/502/503 tells the client when
    to come back)."""
    b0_srv, b0 = _static_backend(502, b'{"error": "x"}')
    b1_srv, b1 = _static_backend(502, b'{"error": "x"}')
    router = FleetRouter([b0, b1]).start()
    try:
        req = urllib.request.Request(f"{router.url}/v1/statement/q_ab/0")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 502
        assert ei.value.headers.get("Retry-After") == "1"
    finally:
        router.stop()
        b0_srv.shutdown()
        b1_srv.shutdown()


@pytest.mark.parametrize("code", [429, 503])
def test_router_injects_retry_after_on_bare_shed(code):
    """A backend that sheds (429) or is mid-adoption (503) WITHOUT a
    Retry-After hint: the router adds its 1s default instead of silently
    dropping the backpressure signal; a backend-set value passes through
    untouched."""
    srv, url = _static_backend(code, b'{"error": "busy"}')
    srv2, url2 = _static_backend(code, b'{"error": "busy"}',
                                 headers={"Retry-After": "7"})
    for backend_srv, backend, want in ((srv, url, "1"), (srv2, url2, "7")):
        router = FleetRouter([backend]).start()
        try:
            req = urllib.request.Request(
                f"{router.url}/v1/statement/q_cd/0"
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == code
            assert ei.value.headers.get("Retry-After") == want
        finally:
            router.stop()
    srv.shutdown()
    srv2.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_adoption_consults_commit_marker_never_double_applies(tmp_path):
    """Write-plane adoption guard (runtime/txn.py): a peer adopting a dead
    member's journaled in-flight INSERT must consult the commit marker
    before RESUME.  The dead member committed but never acked — the
    adopter replays the write as a NO-OP, and the row-count oracle proves
    the insert applied exactly once across the whole failover."""
    from trino_tpu.runtime.txn import TXN_TOTAL

    conn = MemoryConnector()
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("t", {"k": np.arange(6, dtype=np.int64),
                      "v": np.arange(6, dtype=np.int64) * 10})
    runner = _fleet_cluster(conn, str(tmp_path / "spool"))
    try:
        c0 = runner.coordinators[0]
        noop0 = TXN_TOTAL.value("replayed_noop")
        # crash c0 at the committed-unacked boundary of the INSERT
        runner.inject_write_failure(phase="ack", coordinator_index=0)

        def _go():
            try:
                c0.execute_query("insert into t select k + 100, v from t")
            except Exception:
                pass  # the dying coordinator returns nothing useful

        threading.Thread(target=_go, daemon=True).start()
        assert _wait(lambda: c0._killed), "COMMIT_CRASH never fired"
        # oracle BEFORE adoption: the connector commit landed (6 -> 12)
        assert _wait(lambda: conn.estimated_row_count("t") == 12)
        # the survivor adopts off c0's expired lease and replays the
        # intent against the commit marker — a re-execution would land a
        # THIRD copy of the rows
        c1 = runner.coordinators[1]
        assert _wait(
            lambda: TXN_TOTAL.value("replayed_noop") == noop0 + 1,
            timeout=30,
        ), "adopter never replayed the write as a no-op"
        adopted = [
            rec for rec in c1.queries.values() if rec.get("adopted_from")
        ]
        assert adopted, "survivor never adopted the peer's query"
        assert _wait(lambda: adopted[0]["done"].is_set())
        assert adopted[0]["sm"].state == "FINISHED"
        assert adopted[0]["result"] == [(6,)]
        # oracle AFTER adoption: exactly-once — still 12, never 18
        assert conn.estimated_row_count("t") == 12
        # the adopter re-journaled the peer's marker: a second failover
        # would ALSO no-op off the adopter's own journal
        adopted_jq = QueryJournal.replay(c1.fleet.journal_path_for())
        qid = next(iter(
            q for q in adopted_jq.values() if q.write_commits
        ))
        assert qid.write_commits and qid.state == "FINISHED"
    finally:
        runner.stop()
