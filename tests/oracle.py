"""Differential-testing oracle: sqlite3 over the same generated data.

The reference validates engine results by running every test query on both
Trino and H2 and diffing (testing/trino-testing/.../AbstractTestQueryFramework.java:344,
H2QueryRunner).  Here the trusted engine is sqlite (stdlib), loaded with the
identical numpy tables the TPU engine scans, so any disagreement is an engine
bug, not a data difference.

sqlite speaks a slightly different dialect; `to_sqlite` rewrites the few
constructs TPC-H needs (date literals, interval arithmetic, extract,
substring) so tests keep a single SQL source of truth.
"""

from __future__ import annotations

import math
import re
import sqlite3
from typing import Sequence

import numpy as np

from trino_tpu.data.types import DATE, Type, days_to_date
from trino_tpu.connectors.tpch.generator import TPCH_SCHEMAS


def _fold_decimal_literals(sql: str) -> str:
    """Fold literal-literal +|-|* exactly, as the engine's decimal typing
    does (0.06 + 0.01 is exactly 0.07 in DECIMAL; in sqlite's f64 it is
    0.06999..., which flips `between` boundaries on rows at the edge)."""
    import decimal

    pat = re.compile(r"(?<![\w.])(\d+\.\d+|\d+)\s*([+\-*])\s*(\d+\.\d+|\d+)(?![\w.])")

    def fold(m: re.Match) -> str:
        a = decimal.Decimal(m.group(1))
        b = decimal.Decimal(m.group(3))
        r = {"+": a + b, "-": a - b, "*": a * b}[m.group(2)]
        return format(r, "f")

    # fold only outside quoted strings ('1994-01-01' must not become 1993)
    parts = re.split(r"('(?:[^']|'')*')", sql)
    for i in range(0, len(parts), 2):
        prev = None
        while prev != parts[i]:
            prev = parts[i]
            parts[i] = pat.sub(fold, parts[i])
    return "".join(parts)


def _split_top_commas(s: str) -> list[str]:
    parts: list[str] = []
    depth = 0
    cur: list[str] = []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return parts


def _norm_expr(s: str) -> str:
    return re.sub(r"\s+", " ", s.strip()).lower()


def _expand_rollup(sql: str) -> str:
    """sqlite has no ROLLUP/grouping(): expand `group by rollup (k1..kn)`
    into a UNION ALL of the n+1 grouping levels, NULL-ing rolled-away keys
    in the select list and folding grouping(k) to 0/1 literals."""
    m = re.search(r"group\s+by\s+rollup\s*\(", sql, flags=re.IGNORECASE)
    if not m:
        return sql
    # balanced-paren extent of the rollup key list
    i = m.end()
    depth = 1
    while depth:
        if sql[i] == "(":
            depth += 1
        elif sql[i] == ")":
            depth -= 1
        i += 1
    keys = _split_top_commas(sql[m.end(): i - 1])
    gb_start, gb_end = m.start(), i

    def depth_at(pos: int) -> int:
        d = 0
        for ch in sql[:pos]:
            if ch == "(":
                d += 1
            elif ch == ")":
                d -= 1
        return d
    d0 = depth_at(gb_start)
    # owning SELECT: nearest preceding `select` at the same paren depth
    sel_start = None
    for sm in re.finditer(r"\bselect\b", sql[:gb_start], flags=re.IGNORECASE):
        if depth_at(sm.start()) == d0:
            sel_start = sm.start()
    assert sel_start is not None, "rollup: owning select not found"
    # end of the select block: first order by / limit / closing paren at d0
    block_end = len(sql)
    d = d0
    j = gb_end
    while j < len(sql):
        ch = sql[j]
        if ch == "(":
            d += 1
        elif ch == ")":
            d -= 1
            if d < d0:
                block_end = j
                break
        if d == d0:
            tail = sql[j:]
            if re.match(r"order\s+by\b", tail, flags=re.IGNORECASE) or re.match(
                r"limit\b", tail, flags=re.IGNORECASE
            ):
                block_end = j
                break
        j += 1
    block = sql[sel_start:block_end]
    head = block[: gb_start - sel_start]  # select ... from ... where ...
    after_gb = block[gb_end - sel_start:]  # having ... (if any)

    # select-items segment: between `select` and the top-level ` from `
    hm = re.match(r"select\s+", head, flags=re.IGNORECASE)
    items_from = hm.end()
    d = 0
    items_to = None
    for k in range(items_from, len(head)):
        ch = head[k]
        if ch == "(":
            d += 1
        elif ch == ")":
            d -= 1
        elif d == 0 and re.match(r"\bfrom\b", head[k:], flags=re.IGNORECASE):
            items_to = k
            break
    assert items_to is not None, "rollup: FROM not found"
    items = _split_top_commas(head[items_from:items_to])
    norm_keys = [_norm_expr(k) for k in keys]

    def item_variant(item: str, level: int) -> str:
        # fold grouping(k) -> 0/1 for this level
        def fold_grouping(mm: re.Match) -> str:
            arg = _norm_expr(mm.group(1))
            ki = norm_keys.index(arg) if arg in norm_keys else -1
            return "1" if (ki >= level or ki < 0) else "0"

        item = re.sub(
            r"grouping\s*\(([^()]*)\)", fold_grouping, item, flags=re.IGNORECASE
        )
        ni = _norm_expr(item)
        for ki, nk in enumerate(norm_keys):
            if ki < level:
                continue  # key survives at this level
            if ni == nk:
                name = re.split(r"[.\s]", item.strip())[-1]
                return f"null as {name}"
            am = re.match(
                r"(.*?)\s+as\s+(\w+)\s*$", item.strip(),
                flags=re.IGNORECASE | re.DOTALL,
            )
            if am and _norm_expr(am.group(1)) == nk:
                return f"null as {am.group(2)}"
        return item

    variants = []
    for level in range(len(keys), -1, -1):
        sel_items = ", ".join(item_variant(it, level) for it in items)
        gb = (
            " group by " + ", ".join(keys[:level]) if level else " "
        )
        variants.append(
            "select " + sel_items + " " + head[items_to:] + gb + after_gb
        )
    wrapped = "select * from (" + " union all ".join(variants) + ") _rollup_x "
    return sql[:sel_start] + wrapped + sql[block_end:]


_ORDER_STOP = re.compile(r"(limit|rows|range|groups)\b", re.IGNORECASE)


def _null_item(item: str) -> str:
    """Append the Trino default null ordering (NULLS LAST for ASC, FIRST
    for DESC) to one ORDER BY item; sqlite's default is the opposite."""
    if re.search(r"\bnulls\s+(first|last)\b", item, flags=re.IGNORECASE):
        return item
    s = item.rstrip()
    if not s:
        return item
    ws = item[len(s):]
    desc = re.search(r"\bdesc\s*$", s, flags=re.IGNORECASE)
    return s + (" nulls first" if desc else " nulls last") + ws


def _fix_null_order(sql: str) -> str:
    """Rewrite every ORDER BY item (top level and windows) to spell out the
    engine's null ordering, since sqlite's default differs."""
    out: list[str] = []
    i = 0
    while True:
        m = re.search(r"\border\s+by\b", sql[i:], flags=re.IGNORECASE)
        if not m:
            out.append(sql[i:])
            break
        start = i + m.end()
        out.append(sql[i:start])
        j = start
        depth = 0
        item_start = start
        pieces: list[str] = []
        while j < len(sql):
            ch = sql[j]
            if ch == "(":
                depth += 1
            elif ch == ")":
                if depth == 0:
                    break
                depth -= 1
            elif ch == "," and depth == 0:
                pieces.append(_null_item(sql[item_start:j]))
                pieces.append(",")
                item_start = j + 1
            elif depth == 0 and not sql[j - 1].isalnum() and sql[j - 1] != "_":
                if _ORDER_STOP.match(sql, j):
                    break
            j += 1
        pieces.append(_null_item(sql[item_start:j]))
        out.append("".join(pieces))
        i = j
    return "".join(out)


class _StdAgg:
    """Welford-free simple two-pass stddev/variance aggregate for sqlite."""

    def __init__(self, samp: bool, sqrt: bool):
        self.samp, self.sqrt = samp, sqrt
        self.vals: list[float] = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < (2 if self.samp else 1):
            return None
        mean = sum(self.vals) / n
        ss = sum((x - mean) ** 2 for x in self.vals)
        var = ss / (n - 1) if self.samp else ss / n
        return math.sqrt(var) if self.sqrt else var


def to_sqlite(sql: str) -> str:
    sql = _fold_decimal_literals(sql)
    sql = _expand_rollup(sql)
    sql = _fix_null_order(sql)
    # date '1994-01-01' [+-] interval 'n' unit  ->  date('1994-01-01', '+n units')
    def _interval(m: re.Match) -> str:
        base, sign, n, unit = m.group(1), m.group(2), m.group(3), m.group(4)
        return f"date({base}, '{sign}{n} {unit}s')"

    out = re.sub(
        r"date\s+('[\d-]+')\s*([+-])\s*interval\s+'(\d+)'\s+(day|month|year)",
        _interval,
        sql,
        flags=re.IGNORECASE,
    )
    # bare date literals
    out = re.sub(r"\bdate\s+('[\d-]+')", r"\1", out, flags=re.IGNORECASE)
    # extract(year from x) -> cast(strftime('%Y', x) as integer)
    out = re.sub(
        r"extract\s*\(\s*year\s+from\s+([^)]+)\)",
        r"CAST(strftime('%Y', \1) AS INTEGER)",
        out,
        flags=re.IGNORECASE,
    )
    out = re.sub(
        r"extract\s*\(\s*month\s+from\s+([^)]+)\)",
        r"CAST(strftime('%m', \1) AS INTEGER)",
        out,
        flags=re.IGNORECASE,
    )
    # substring(x from a for b) -> substr(x, a, b); substring( -> substr(
    out = re.sub(
        r"substring\s*\(\s*([^\s,)]+)\s+from\s+(\d+)\s+for\s+(\d+)\s*\)",
        r"substr(\1, \2, \3)",
        out,
        flags=re.IGNORECASE,
    )
    out = re.sub(r"\bsubstring\s*\(", "substr(", out, flags=re.IGNORECASE)
    return out


class SqliteOracle:
    def __init__(
        self,
        tables: dict[str, dict[str, np.ndarray]],
        schemas: dict[str, list] | None = None,
    ):
        all_schemas = dict(TPCH_SCHEMAS)
        if schemas is not None:
            all_schemas.update(schemas)
        self.conn = sqlite3.connect(":memory:")
        self.conn.create_function("power", 2, lambda a, b: float(a) ** float(b))
        for name_, samp, sqrt_ in (
            ("stddev_samp", True, True), ("stddev_pop", False, True),
            ("var_samp", True, False), ("var_pop", False, False),
        ):
            self.conn.create_aggregate(
                name_, 1,
                (lambda s=samp, q=sqrt_: _StdAgg(s, q)),  # type: ignore[arg-type]
            )
        for name, cols in tables.items():
            schema = dict(all_schemas[name])
            col_defs = ", ".join(f"{c} {_sqlite_type(schema[c])}" for c in cols)
            self.conn.execute(f"CREATE TABLE {name} ({col_defs})")
            arrays = []
            for c, arr in cols.items():
                if schema[c] == DATE:
                    arrays.append([days_to_date(int(d)).isoformat() for d in arr])
                elif schema[c].is_decimal:
                    # engine lanes are scaled int64; sqlite sees plain REALs
                    s = 10.0 ** schema[c].scale
                    arrays.append([int(v) / s for v in arr])
                elif arr.dtype == object:
                    arrays.append([str(v) for v in arr])
                elif np.issubdtype(arr.dtype, np.floating):
                    arrays.append([float(v) for v in arr])
                else:
                    arrays.append([int(v) for v in arr])
            rows = list(zip(*arrays))
            ph = ", ".join("?" for _ in cols)
            self.conn.executemany(f"INSERT INTO {name} VALUES ({ph})", rows)
            # index join keys: without these, OR-of-conjunct queries like
            # TPC-DS q48 send sqlite's planner into an unindexed nested loop
            # that runs for minutes even at tiny scale
            for c in cols:
                if c.endswith("_sk") or c.endswith("key"):
                    self.conn.execute(
                        f"CREATE INDEX IF NOT EXISTS idx_{name}_{c} ON {name} ({c})"
                    )
        self.conn.execute("ANALYZE")
        self.conn.commit()

    def query(self, sql: str) -> list[tuple]:
        cur = self.conn.execute(to_sqlite(sql))
        return [tuple(r) for r in cur.fetchall()]


def _sqlite_type(t: Type) -> str:
    if t.is_string or t == DATE:
        return "TEXT"
    if t.is_floating or t.is_decimal:
        return "REAL"
    return "INTEGER"


def assert_rows_equal(
    actual: Sequence[tuple],
    expected: Sequence[tuple],
    ordered: bool = False,
    rtol: float = 1e-9,
    atol: float = 1e-6,
) -> None:
    """Diff two result sets with float tolerance (sum order differs between
    engines, so exact float equality is not meaningful)."""
    assert len(actual) == len(expected), (
        f"row count mismatch: {len(actual)} vs {len(expected)}\n"
        f"actual[:5]={list(actual)[:5]}\nexpected[:5]={list(expected)[:5]}"
    )
    a, e = list(actual), list(expected)
    if not ordered:
        a = sorted(a, key=_sort_key)
        e = sorted(e, key=_sort_key)
    mismatch = _first_mismatch(a, e, rtol, atol)
    if mismatch is not None and not ordered:
        # Rounding in the sort key can misalign rows whose floats are equal
        # within tolerance but round differently; fall back to greedy
        # tolerant matching (result sets here are small).
        unmatched = list(range(len(e)))
        for i, ra in enumerate(a):
            hit = next(
                (k for k in unmatched if _rows_close(ra, e[k], rtol, atol)), None
            )
            assert hit is not None, f"no expected row matches actual row {i}: {ra}\n{mismatch}"
            unmatched.remove(hit)
        return
    assert mismatch is None, mismatch


def _first_mismatch(a, e, rtol, atol):
    for i, (ra, re_) in enumerate(zip(a, e)):
        if len(ra) != len(re_):
            return f"row {i}: arity {len(ra)} vs {len(re_)}"
        for j, (va, ve) in enumerate(zip(ra, re_)):
            if not _vals_close(va, ve, rtol, atol):
                return (
                    f"row {i} col {j}: {va!r} vs {ve!r}\nactual row: {ra}\nexpected row: {re_}"
                )
    return None


def _vals_close(va, ve, rtol, atol) -> bool:
    if va is None or ve is None:
        return va is None and ve is None
    if isinstance(va, float) or isinstance(ve, float):
        try:
            return math.isclose(float(va), float(ve), rel_tol=rtol, abs_tol=atol)
        except (TypeError, ValueError):
            return False
    return va == ve


def _rows_close(ra, re_, rtol, atol) -> bool:
    return len(ra) == len(re_) and all(_vals_close(x, y, rtol, atol) for x, y in zip(ra, re_))


def _sort_key(row: tuple):
    return tuple((v is None, _norm(v)) for v in row)


def _norm(v):
    if v is None:
        return ""
    if isinstance(v, float):
        return round(v, 6)
    if isinstance(v, int):
        return float(v)
    return str(v)
