"""Query observability plane: distributed EXPLAIN ANALYZE, the operator
stats pipeline, Prometheus /metrics exposition, and W3C trace propagation
(reference: QueryInfo/StageStats/OperatorStats, the JMX metrics surface,
and the OpenTelemetry propagator on task HTTP calls)."""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.testing import DistributedQueryRunner
from trino_tpu.utils import metrics as M
from trino_tpu.utils.tracing import (
    InMemorySpanExporter,
    Tracer,
    parse_traceparent,
    traceparent,
)

# ------------------------------------------------------------- metrics unit

# Prometheus text exposition 0.0.4: every sample line is
# `name{label="v",...} value` with a float-parseable value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? [^ ]+$'
)


def _assert_prometheus_parses(text: str) -> dict:
    """Validate the exposition format; return {sample_line_name: value}."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
        name_part, value = line.rsplit(" ", 1)
        float(value)  # must parse
        samples[name_part] = float(value)
    return samples


def test_counter_gauge_histogram_render():
    reg = M.MetricsRegistry()
    c = reg.counter("t_requests_total", "requests", ("code",))
    c.labels("200").inc()
    c.labels("200").inc(2)
    c.labels("500").inc()
    g = reg.gauge("t_inflight", "in flight")
    g.set(7)
    h = reg.histogram("t_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    samples = _assert_prometheus_parses(text)
    assert samples['t_requests_total{code="200"}'] == 3
    assert samples['t_requests_total{code="500"}'] == 1
    assert samples["t_inflight"] == 7
    assert samples['t_seconds_bucket{le="0.1"}'] == 1
    assert samples['t_seconds_bucket{le="1"}'] == 2
    assert samples['t_seconds_bucket{le="+Inf"}'] == 3
    assert samples["t_seconds_count"] == 3
    assert "# HELP t_requests_total requests" in text
    assert "# TYPE t_seconds histogram" in text


def test_registry_get_or_create_and_mismatch():
    reg = M.MetricsRegistry()
    a = reg.counter("t_x_total", "x")
    assert reg.counter("t_x_total", "x") is a
    with pytest.raises(ValueError):
        reg.counter("t_x_total", "x", ("label",))  # same name, new shape
    with pytest.raises(ValueError):
        reg.gauge("t_x_total", "x")  # same name, different kind


def test_counter_thread_safety():
    reg = M.MetricsRegistry()
    c = reg.counter("t_threads_total", "t")

    def bump():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8000


# ------------------------------------------------------------ tracing unit


def test_traceparent_round_trip():
    tracer = Tracer()
    with tracer.span("query") as span:
        header = traceparent(span)
    assert re.match(r"^00-[0-9a-f]{32}-[0-9a-f]{16}-01$", header)
    trace_id, span_id = parse_traceparent(header)
    assert trace_id == span.trace_id and span_id == span.span_id
    assert parse_traceparent("junk") is None
    assert parse_traceparent("00-zz-yy-01") is None
    assert parse_traceparent(None or "") is None


def test_tracer_join_adopts_remote_trace():
    coord, worker = Tracer(), Tracer()
    with coord.span("query") as qspan:
        header = traceparent(qspan)
    exp = InMemorySpanExporter()
    worker.add_exporter(exp)
    assert worker.join(header)
    with worker.span("task"):
        pass
    (task_span,) = exp.snapshot()
    assert task_span.trace_id == qspan.trace_id
    assert task_span.parent_id == qspan.span_id
    # the joined context is one-shot: the next root is a fresh trace
    with worker.span("task2"):
        pass
    assert exp.snapshot()[-1].trace_id != qspan.trace_id


def test_tracer_concurrent_roots_thread_safe():
    tracer = Tracer()
    exp = InMemorySpanExporter()
    tracer.add_exporter(exp)

    def run(i):
        with tracer.span("query", i=i):
            with tracer.span("child"):
                pass

    threads = [threading.Thread(target=run, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = exp.snapshot()
    assert len(spans) == 16
    assert len({s.trace_id for s in spans}) == 16  # no cross-thread bleed
    assert all(len(s.children) == 1 for s in spans)


# ----------------------------------------------------- distributed pipeline


@pytest.fixture(scope="module")
def cluster():
    runner = DistributedQueryRunner(num_workers=2)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    yield runner
    runner.stop()


ANALYZE_SQL = (
    "explain analyze select l_returnflag, count(*) c from lineitem "
    "where l_quantity < 30 group by l_returnflag order by c desc"
)


def test_distributed_explain_analyze_all_stages_annotated(cluster):
    rows = cluster.query(ANALYZE_SQL)
    text = "\n".join(r[0] for r in rows)
    frags = [ln for ln in text.splitlines() if ln.startswith("Fragment")]
    assert len(frags) >= 2, text  # multi-stage plan: root + worker stages
    # EVERY operator line in EVERY stage carries rows AND eager ms —
    # no silent stats-less fallback
    for ln in text.splitlines():
        if ln.startswith(("Fragment", "--")) or not ln.strip():
            continue
        assert "[rows: " in ln, f"stats-less operator line: {ln!r}"
        assert " ms]" in ln, f"un-timed operator line: {ln!r}"
    assert "slowest operator:" in text
    assert "cluster cpu:" in text
    # worker stages report their wall interval relative to query start
    assert any("wall:" in f for f in frags[1:])


def test_query_info_endpoint(cluster):
    cluster.query("select count(*) from orders")
    qid = list(cluster.coordinator.queries)[-1]
    with urllib.request.urlopen(
        f"{cluster.coordinator.url}/v1/query/{qid}"
    ) as r:
        info = json.loads(r.read())
    assert info["state"] == "FINISHED"
    assert info["stage_count"] >= 2
    assert info["cpu_ms"] > 0
    for stage in info["stages"]:
        assert stage["operators"], f"stage {stage['stage_id']} has no stats"
        for s in stage["operators"].values():
            assert s["rows"] >= 0 and s["invocations"] >= 1
    # every non-root stage ran real tasks with exchange accounting
    worker_tasks = [
        t for st in info["stages"] for t in st["tasks"]
        if t["worker"] != "coordinator"
    ]
    assert worker_tasks and all(t["wall_ms"] is not None for t in worker_tasks)


def test_metrics_endpoints_parse_and_counters_move(cluster):
    def scrape(url):
        with urllib.request.urlopen(f"{url}/metrics") as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            return _assert_prometheus_parses(r.read().decode())

    before = scrape(cluster.coordinator.url)
    cluster.query("select count(*) from region")
    after = scrape(cluster.coordinator.url)
    fin = 'trino_tpu_queries_total{state="FINISHED"}'
    assert after.get(fin, 0) == before.get(fin, 0) + 1
    assert after["trino_tpu_tasks_dispatched_total"] > before.get(
        "trino_tpu_tasks_dispatched_total", 0
    )
    assert after["trino_tpu_query_seconds_count"] >= 1
    wsamples = scrape(cluster.workers[0].url)
    assert wsamples['trino_tpu_worker_tasks_total{event="finished"}'] >= 1
    assert "trino_tpu_exchange_served_bytes_total" in wsamples
    # process-global data-plane counters ride along on every scrape
    assert any(k.startswith("trino_tpu_jit_cache_lookups_total") for k in wsamples)


def test_trace_propagates_coordinator_to_workers(cluster):
    wexps = []
    for w in cluster.workers:
        exp = InMemorySpanExporter()
        w.tracer.add_exporter(exp)
        wexps.append(exp)
    cexp = InMemorySpanExporter()
    cluster.coordinator.tracer.add_exporter(cexp)
    try:
        cluster.query("select count(*) from nation")
    finally:
        cluster.coordinator.tracer._exporters.clear()
        for w in cluster.workers:
            w.tracer._exporters.clear()
    (qspan,) = [s for s in cexp.snapshot() if s.name == "query"]
    task_spans = [s for exp in wexps for s in exp.snapshot() if s.name == "task"]
    assert task_spans, "no worker task spans exported"
    assert all(s.trace_id == qspan.trace_id for s in task_spans)
    assert all(s.parent_id == qspan.span_id for s in task_spans)


def test_coordinator_events_enriched(cluster):
    events = []
    cluster.coordinator.add_event_listener(events.append)
    try:
        cluster.query("select count(*) from region")
    finally:
        cluster.coordinator.events._listeners.clear()
    kinds = [e.kind for e in events]
    assert kinds == ["created", "completed"]
    done = events[-1]
    assert done.rows == 1 and done.wall_s > 0
    assert done.stage_count >= 2
    assert done.cpu_ms > 0


def test_explain_format_json_session_property(cluster):
    coord = cluster.coordinator
    coord.session.set("explain_format", "json")
    try:
        rows = cluster.query("explain select count(*) from region")
        obj = json.loads(rows[0][0])
        assert obj["operator"] and isinstance(obj["children"], list)
        rows = cluster.query("explain analyze select count(*) from region")
        info = json.loads(rows[0][0])
        assert info["stage_count"] >= 2
        assert all(st["operators"] for st in info["stages"])
    finally:
        coord.session.set("explain_format", "text")


def test_explain_format_json_local_engine():
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    eng.execute("set session explain_format = 'json'")
    obj = json.loads(eng.execute("explain select count(*) from region")[0][0])
    assert obj["operator"] == "Aggregate" or obj["children"]
    out = json.loads(
        eng.execute("explain analyze select count(*) from region")[0][0]
    )
    assert out["output_rows"] == 1
    stats = [n.get("stats") for n in _walk_obj(out["plan"])]
    assert any(s and "rows" in s for s in stats)


def _walk_obj(obj):
    yield obj
    for c in obj.get("children", []):
        yield from _walk_obj(c)


def test_ui_has_wall_and_state_age_columns(cluster):
    with urllib.request.urlopen(f"{cluster.coordinator.url}/ui") as r:
        page = r.read().decode()
    assert "wall (s)" in page and "in state (s)" in page
    assert "seen (s)" in page


# ------------------------------------------------------- chaos + counters


def test_retry_counters_under_injected_faults():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT

    conn = MemoryConnector()
    conn.create_table("t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    rng = np.random.default_rng(7)
    conn.insert("t", {
        "k": rng.integers(0, 50, 20_000).astype(np.int64),
        "v": rng.integers(0, 1000, 20_000).astype(np.int64),
    })
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="mem", heartbeat_interval=0.3
    )
    runner.register_catalog("mem", conn)
    runner.start()
    try:
        sql = "select k, sum(v) from t group by k order by k"
        clean = runner.query(sql)
        runner.coordinator.session.set("retry_policy", "TASK")
        runner.inject_task_failure(worker_index=0, mode="ERROR")
        assert runner.query(sql) == clean
        qid = list(runner.coordinator.queries)[-1]
        with urllib.request.urlopen(
            f"{runner.coordinator.url}/v1/query/{qid}"
        ) as r:
            info = json.loads(r.read())
        assert info["task_retries"] >= 1
        with urllib.request.urlopen(f"{runner.coordinator.url}/metrics") as r:
            samples = _assert_prometheus_parses(r.read().decode())
        assert samples["trino_tpu_task_retries_total"] >= 1
        wsamples = _assert_prometheus_parses(
            urllib.request.urlopen(
                f"{runner.workers[0].url}/metrics"
            ).read().decode()
        )
        assert wsamples['trino_tpu_worker_tasks_total{event="failed"}'] >= 1
    finally:
        runner.stop()
