"""Pallas kernel tests (interpreter mode on CPU).

The fused segmented-reduce kernel (ops/pallas/segreduce.py) replaces the
reference's FlatHash + Accumulator pipeline (operator/FlatHash.java:38,
operator/aggregation/) on TPU.  These tests run the actual kernel through the
Pallas interpreter so its logic — limb-exact int64 sums with carry sweeps,
Kahan float compensation, masked min/max — is exercised by the CPU suite;
the TPU tier (tests/test_tpch_tpu.py) runs it compiled on hardware.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from trino_tpu.ops.pallas import segreduce
from trino_tpu.ops.pallas.segreduce import SegRed, fused_segment_reduce


@pytest.fixture
def rng():
    return np.random.RandomState(7)


def _np_refs(seg, G):
    present = np.zeros(G, bool)
    present[np.unique(np.minimum(seg, G - 1))] = True
    return present


def test_fused_segment_reduce_all_ops(rng):
    n, G = 5000, 300
    seg = rng.randint(0, G, size=n).astype(np.int32)
    fvals = (rng.rand(n) * 1e5).astype(np.float64)
    ivals = rng.randint(-(1 << 45), 1 << 45, size=n).astype(np.int64)
    valid = rng.rand(n) > 0.3
    dates = rng.randint(0, 20000, size=n).astype(np.int32)

    reds = [
        SegRed("sum", jnp.asarray(fvals), jnp.asarray(valid)),
        SegRed("sum", jnp.asarray(ivals), None),
        SegRed("count", None, jnp.asarray(valid)),
        SegRed("min", jnp.asarray(fvals), jnp.asarray(valid)),
        SegRed("max", jnp.asarray(dates), None),
    ]
    out = [np.asarray(o) for o in fused_segment_reduce(jnp.asarray(seg), reds, G, interpret=True)]

    ref_fsum = np.bincount(seg[valid], weights=fvals[valid], minlength=G)
    ref_isum = np.zeros(G, np.int64)
    np.add.at(ref_isum, seg, ivals)
    ref_cnt = np.bincount(seg[valid], minlength=G).astype(np.int64)
    ref_min = np.full(G, np.inf)
    np.minimum.at(ref_min, seg[valid], fvals[valid])
    ref_max = np.zeros(G, np.int64)
    np.maximum.at(ref_max, seg, dates)

    nz = ref_cnt > 0
    np.testing.assert_allclose(out[0][nz], ref_fsum[nz], rtol=1e-5)
    assert np.array_equal(out[1], ref_isum), "int64 sums must be bit-exact"
    assert np.array_equal(out[2], ref_cnt)
    np.testing.assert_allclose(out[3][nz], ref_min[nz], rtol=1e-6)
    assert np.array_equal(out[4], ref_max.astype(np.int32))


def test_int64_sum_exact_with_carries(rng):
    # > 32 chunks of 1024 rows forces the in-kernel carry sweep
    n = 40 * 1024 + 13
    seg = rng.randint(0, 5, size=n).astype(np.int32)
    big = rng.randint(-(1 << 60), 1 << 60, size=n).astype(np.int64)
    out = fused_segment_reduce(
        jnp.asarray(seg), [SegRed("sum", jnp.asarray(big), None)], 5, interpret=True
    )
    ref = np.zeros(5, np.int64)
    np.add.at(ref, seg, big)
    assert np.array_equal(np.asarray(out[0]), ref)


def test_dead_lane_convention(rng):
    # rows with seg >= G contribute to nothing
    n, G = 2048, 10
    seg = rng.randint(0, G, size=n).astype(np.int32)
    dead = rng.rand(n) > 0.5
    seg[dead] = G  # the executor's dead-lane overflow bucket
    vals = np.ones(n)
    out = fused_segment_reduce(
        jnp.asarray(seg),
        [SegRed("sum", jnp.asarray(vals), None), SegRed("count", None, None)],
        G,
        interpret=True,
    )
    ref = np.bincount(seg[~dead], minlength=G)[:G]
    np.testing.assert_allclose(np.asarray(out[0]), ref)
    # count with valid=None counts every row incl. dead; engine always passes
    # live as valid — assert the sum matched instead.


def test_matches_xla_fallback(rng):
    n, G = 3000, 777
    seg = rng.randint(0, G, size=n).astype(np.int32)
    f = rng.randn(n) * 100
    i = rng.randint(-1000, 1000, size=n).astype(np.int64)
    v = rng.rand(n) > 0.2
    reds = [
        SegRed("sum", jnp.asarray(f), jnp.asarray(v)),
        SegRed("sum", jnp.asarray(i), jnp.asarray(v)),
        SegRed("count", None, jnp.asarray(v)),
        SegRed("min", jnp.asarray(i).astype(jnp.int32), jnp.asarray(v)),
        SegRed("max", jnp.asarray(f), jnp.asarray(v)),
    ]
    a = fused_segment_reduce(jnp.asarray(seg), reds, G, interpret=True)
    b = fused_segment_reduce(jnp.asarray(seg), reds, G)  # cpu -> xla fallback
    cnt = np.asarray(b[2])
    nz = cnt > 0
    for x, y in zip(a, b):
        np.testing.assert_allclose(
            np.asarray(x)[nz].astype(np.float64),
            np.asarray(y)[nz].astype(np.float64),
            rtol=1e-5,
            atol=1e-5,  # f32 matmul accumulation under cancellation
        )


def _topn_ref(rows, keys_idx, ascending, k):
    def keyf(r):
        out = []
        for i, asc in zip(keys_idx, ascending):
            v = r[i]
            out.append(v if asc else -v)
        return tuple(out)

    return sorted(rows, key=keyf)[:k]


@pytest.mark.parametrize("dtype", ["float64", "int64", "int32"])
@pytest.mark.parametrize("ascending", [True, False])
def test_radix_topn_matches_sort(rng, dtype, ascending):
    """relops.top_n radix-select path == plain-sort path, incl. ties/NULLs."""
    from trino_tpu.data.types import BIGINT, DOUBLE, INTEGER
    from trino_tpu.ops.expr import ColumnVal
    from trino_tpu.ops.pallas import topk
    from trino_tpu.ops.relops import SortSpec, top_n

    n, k, cap = 4096, 50, 1024
    if dtype == "float64":
        vals = np.round(rng.randn(n) * 1000, 2)
        t = DOUBLE
    else:
        vals = rng.randint(-10000, 10000, size=n).astype(dtype)
        t = BIGINT if dtype == "int64" else INTEGER
    payload = np.arange(n, dtype=np.int64)
    valid = rng.rand(n) > 0.05
    live = jnp.asarray(rng.rand(n) > 0.1)

    key = ColumnVal(jnp.asarray(vals), jnp.asarray(valid), None, t)
    pay = ColumnVal(jnp.asarray(payload), None, None, BIGINT)
    spec = SortSpec(ascending=ascending, nulls_first=False)

    def run():
        c = cap
        while True:  # the executor's capacity-retry protocol in miniature
            cols, out_live, req = top_n([key, pay], live, [key], [spec], k, c)
            if int(req) <= c:
                break
            c = max(int(req), c * 2)
        lv = np.asarray(out_live)
        return [
            (
                None if (cols[0].valid is not None and not np.asarray(cols[0].valid)[i]) else float(np.asarray(cols[0].data)[i]),
                int(np.asarray(cols[1].data)[i]),
            )
            for i in range(len(lv))
            if lv[i]
        ]

    segreduce.INTERPRET = True
    topk.FORCE = True
    try:
        got = run()
    finally:
        segreduce.INTERPRET = False
        topk.FORCE = False
    want = run()  # sort fallback (cap path off)
    # key values must agree positionally; payload may differ on exact ties
    assert len(got) == len(want)
    assert [g[0] for g in got] == [w[0] for w in want]


def test_engine_q1_through_pallas_interpreter(tpch_tiny, oracle):
    """TPC-H Q1 executed with the Pallas kernel (interpreted) end-to-end."""
    from tests.oracle import assert_rows_equal
    from tests.tpch_queries import QUERIES
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    segreduce.INTERPRET = True
    try:
        eng = Engine()
        eng.register_catalog("tpch", TpchConnector(0.01))
        sql = QUERIES["q01"]
        got = eng.query(sql)
        want = oracle.query(sql)
        # f32-matmul Kahan sums floor at ~1e-8 relative; 1e-6 is the
        # tolerance the on-TPU tier uses as well (tests/test_tpch_tpu.py)
        assert_rows_equal(got, want, ordered=True, rtol=1e-6)
    finally:
        segreduce.INTERPRET = False


# ---------------------------------------------------- hash-table kernels


def _np_partition(keys_tuples, gid, live):
    """key tuple -> set of row indices, built from a gid assignment."""
    by_gid = {}
    for i, g in enumerate(gid):
        if not live[i]:
            assert g == -1
            continue
        by_gid.setdefault(int(g), set()).add(i)
    return {frozenset(v) for v in by_gid.values()}


def test_hash_build_partitions_like_unique(rng):
    from trino_tpu.ops.pallas import hashagg

    n = 20000
    live = rng.rand(n) > 0.2
    w0 = (rng.randint(0, 1 << 31, size=n) % 53).astype(np.int32)
    w1 = ((w0 * 7 + 11) % 97).astype(np.int32)  # correlated second word
    gid, table, n_groups, overflow = hashagg.build_hash_table(
        [jnp.asarray(w0), jnp.asarray(w1)], jnp.asarray(live), 256,
        interpret=True,
    )
    gid = np.asarray(gid)
    assert not bool(overflow)
    keys = list(zip(w0.tolist(), w1.tolist()))
    want = {
        frozenset(i for i in range(n) if live[i] and keys[i] == k)
        for k in {keys[i] for i in range(n) if live[i]}
    }
    assert _np_partition(keys, gid, live) == want
    assert int(n_groups) == len(want)
    lg = gid[live]
    assert lg.min() >= 0 and lg.max() == len(want) - 1  # dense claim ids


def test_hash_build_overflow_sets_flag(rng):
    from trino_tpu.ops.pallas import hashagg

    n = 4096
    w = np.arange(n, dtype=np.int32)  # every row distinct
    gid, table, n_groups, overflow = hashagg.build_hash_table(
        [jnp.asarray(w)], jnp.ones(n, bool), 512, interpret=True
    )
    assert bool(overflow)
    assert int(n_groups) > 512  # inflated count drives the caller's retry


def test_hash_probe_hits_and_misses(rng):
    from trino_tpu.ops.pallas import hashagg, hashjoin

    nb, npr = 500, 6000
    bw = rng.randint(0, 300, size=nb).astype(np.int32)
    b_live = rng.rand(nb) > 0.1
    gid_b, table, n_groups, overflow = hashagg.build_hash_table(
        [jnp.asarray(bw)], jnp.asarray(b_live), 1024, interpret=True
    )
    assert not bool(overflow)
    gid_b = np.asarray(gid_b)
    key_gid = {int(bw[i]): int(gid_b[i]) for i in range(nb) if b_live[i]}

    pw = rng.randint(0, 600, size=npr).astype(np.int32)  # half miss
    p_live = rng.rand(npr) > 0.1
    gid_p, unresolved = hashjoin.probe_hash_table(
        [jnp.asarray(pw)], jnp.asarray(p_live), table, interpret=True
    )
    assert not bool(unresolved)
    gid_p = np.asarray(gid_p)
    for i in range(npr):
        want = key_gid.get(int(pw[i]), -1) if p_live[i] else -1
        assert int(gid_p[i]) == want, (i, int(pw[i]))


def test_hash_build_full_load_collision_stress(rng):
    # cap == distinct count: the table runs at its max load factor, so long
    # probe chains and cross-chunk slot races all occur
    from trino_tpu.ops.pallas import hashagg

    n = 16384
    uniq = rng.randint(-(1 << 31), 1 << 31, size=2048).astype(np.int32)
    w = uniq[rng.randint(0, 2048, size=n)]
    gid, table, n_groups, overflow = hashagg.build_hash_table(
        [jnp.asarray(w)], jnp.ones(n, bool), 2048, interpret=True
    )
    assert not bool(overflow)
    assert int(n_groups) == len(set(w.tolist()))
    gid = np.asarray(gid)
    seen = {}
    for i in range(n):
        k = int(w[i])
        assert seen.setdefault(k, int(gid[i])) == int(gid[i])
