"""Lambda expressions + higher-order functions and the new aggregate set
(reference: sql/gen/LambdaBytecodeGenerator, operator/scalar/
ArrayTransformFunction family, aggregation/CorrelationAggregation,
ArrayAggregationFunction, MapAggAggregationFunction; VERDICT r3 missing #2/#4).
"""

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT, DOUBLE, VARCHAR
from trino_tpu.runtime.engine import Engine

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def engine():
    conn = MemoryConnector()
    conn.create_table(
        "t",
        [ColumnSchema("k", BIGINT), ColumnSchema("g", VARCHAR),
         ColumnSchema("x", DOUBLE), ColumnSchema("y", DOUBLE),
         ColumnSchema("s", VARCHAR)],
    )
    rng = np.random.default_rng(5)
    n = 300
    x = rng.normal(size=n)
    conn.insert("t", {
        "k": np.arange(n, dtype=np.int64),
        "g": np.asarray([f"g{i % 3}" for i in range(n)], dtype=object),
        "x": x,
        "y": 3.0 * x + rng.normal(size=n) * 0.01,
        "s": np.asarray([f"s{i % 4}" for i in range(n)], dtype=object),
    })
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    return eng


# ----------------------------------------------------------------- lambdas


def test_transform_filter_literal_arrays(engine):
    assert engine.execute("select transform(array[1,2,3], x -> x * 2)") == [([2, 4, 6],)]
    assert engine.execute("select filter(array[1,2,3,4], x -> x > 2)") == [([3, 4],)]
    assert engine.execute(
        "select transform(array['a','bb'], x -> length(x))"
    ) == [([1, 2],)]


def test_reduce_and_matches(engine):
    assert engine.execute(
        "select reduce(array[1,2,3,4], 0, (s, x) -> s + x, s -> s)"
    ) == [(10,)]
    assert engine.execute(
        "select reduce(array[2,3], 1, (s, x) -> s * x, s -> s * 10)"
    ) == [(60,)]
    rows = engine.execute(
        "select any_match(array[1,2], x -> x > 1), all_match(array[1,2], x -> x > 1),"
        " none_match(array[1,2], x -> x > 5)"
    )
    assert rows == [(True, False, True)]


def test_zip_with_and_nested(engine):
    assert engine.execute(
        "select zip_with(array[1,2,3], array[10,20,30], (x, y) -> x + y)"
    ) == [([11, 22, 33],)]
    # nested HOF: lambda inside lambda-produced array
    assert engine.execute(
        "select transform(filter(array[1,2,3,4], x -> x % 2 = 0), x -> x + 1)"
    ) == [([3, 5],)]


def test_hof_over_column_arrays(engine):
    rows = engine.execute(
        "select g, cardinality(filter(split(s, 's'), x -> length(x) > 0)) as c"
        " from t where k < 4 order by k"
    )
    assert [r[1] for r in rows] == [1, 1, 1, 1]


def test_map_hofs(engine):
    assert engine.execute(
        "select transform_values(map(array['a','b'], array[1,2]), (k, v) -> v * 10)"
    ) == [({"a": 10, "b": 20},)]
    assert engine.execute(
        "select map_filter(map(array['a','b'], array[1,2]), (k, v) -> v > 1)"
    ) == [({"b": 2},)]


def test_lambda_capture_rejected(engine):
    with pytest.raises(Exception, match="capture"):
        engine.execute("select transform(array[1,2], x -> x + k) from t")


# -------------------------------------------------------- new aggregates


def _np_corr(y, x):
    return float(np.corrcoef(y, x)[0, 1])


def test_corr_covar_regr(engine):
    import numpy as np  # noqa: F811

    conn = engine.catalogs.get("mem")
    x = conn.read_split(conn.get_splits("t", 1)[0], ["x"])["x"]
    y = conn.read_split(conn.get_splits("t", 1)[0], ["y"])["y"]
    rows = engine.execute(
        "select corr(y, x) as c, covar_pop(y, x) as cp, covar_samp(y, x) as cs,"
        " regr_slope(y, x) as sl, regr_intercept(y, x) as ic from t"
    )
    c, cp, cs, sl, ic = rows[0]
    assert abs(c - _np_corr(y, x)) < 1e-6
    assert abs(cp - float(np.cov(y, x, bias=True)[0, 1])) < 1e-6
    assert abs(cs - float(np.cov(y, x)[0, 1])) < 1e-6
    slope, intercept = np.polyfit(x, y, 1)
    assert abs(sl - slope) < 1e-6
    assert abs(ic - intercept) < 1e-6


def test_corr_grouped(engine):
    rows = engine.execute("select g, corr(y, x) as c from t group by g order by g")
    assert len(rows) == 3
    for _, c in rows:
        assert c > 0.99


def test_array_agg(engine):
    rows = engine.execute(
        "select g, array_agg(k) as a from t where k < 6 group by g order by g"
    )
    assert rows == [("g0", [0, 3]), ("g1", [1, 4]), ("g2", [2, 5])]
    # global + empty-ish group
    rows = engine.execute("select array_agg(k) from t where k < 3")
    assert sorted(rows[0][0]) == [0, 1, 2]


def test_map_agg_and_listagg(engine):
    rows = engine.execute(
        "select g, map_agg(s, k) as m from t where k < 6 group by g order by g"
    )
    assert rows[0][1] == {"s0": 0, "s3": 3}
    rows = engine.execute(
        "select g, listagg(s, '|') as l from t where k < 6 group by g order by g"
    )
    assert rows == [("g0", "s0|s3"), ("g1", "s1|s0"), ("g2", "s2|s1")]


def test_array_agg_distributed_gather():
    """Host-collected aggregates run single-node semantics in the
    distributed engine via raw-row repartition/gather (distribute.py
    _raw_only)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    conn = MemoryConnector()
    conn.create_table("d", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("d", {
        "k": np.arange(16, dtype=np.int64) % 4,
        "v": np.arange(16, dtype=np.int64),
    })
    eng = Engine(default_catalog="mem", distributed=True)
    eng.register_catalog("mem", conn)
    rows = eng.execute("select k, array_agg(v) as a from d group by k order by k")
    assert [r[0] for r in rows] == [0, 1, 2, 3]
    assert sorted(rows[0][1]) == [0, 4, 8, 12]


def test_review_fixes(engine):
    """Round-4 review regressions: DISTINCT in array_agg/listagg, exact
    bigint division in lambda bodies, HOF arity errors, qualified DESCRIBE."""
    rows = engine.execute(
        "select array_agg(distinct g) as a, listagg(distinct g, ',') as l"
        " from t where k < 9"
    )
    assert sorted(rows[0][0]) == ["g0", "g1", "g2"]
    assert rows[0][1].count("g0") == 1
    assert engine.execute(
        "select transform(array[9007199254740993], v -> v / 1)"
    ) == [([9007199254740993],)]
    assert engine.execute(
        "select transform(array[-7, 7], v -> v % 3)"
    ) == [([-1, 1],)]
    with pytest.raises(Exception, match="argument"):
        engine.execute("select reduce(array[1,2], 0)")
    engine.execute("create view sch.lv as select k from t where k < 2")
    assert engine.execute("describe sch.lv") == [("k", "bigint")]
    engine.execute("drop view sch.lv")
