"""ORC connector: stripe splits, null round-trips, write path
(reference: lib/trino-orc OrcReader/OrcRecordReader)."""

import pytest


@pytest.fixture()
def engine(tmp_path):
    from trino_tpu.connectors.orc import OrcConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="orc")
    eng.register_catalog("orc", OrcConnector(str(tmp_path)))
    return eng


def test_roundtrip_with_nulls(engine):
    engine.execute("create table t (k bigint, v double, s varchar)")
    engine.execute("insert into t values (1, 1.5, 'a'), (2, 2.5, null), (3, null, 'c')")
    engine.execute("insert into t values (4, 4.5, 'd')")  # second file
    assert engine.execute("select k, v, s from t order by k") == [
        (1, 1.5, "a"), (2, 2.5, None), (3, None, "c"), (4, 4.5, "d"),
    ]
    assert engine.execute("select count(*), count(v), sum(v) from t") == [(4, 3, 8.5)]


def test_ctas_orc(engine):
    engine.execute("create table src (k bigint)")
    engine.execute("insert into src values (1), (2), (3)")
    engine.execute("create table dst as select k * 2 as k2 from src where k > 1")
    assert engine.execute("select k2 from dst order by k2") == [(4,), (6,)]


def test_matches_parquet_connector(engine, tmp_path):
    """Same rows through ORC and Parquet produce identical results."""
    from trino_tpu.connectors.parquet import ParquetConnector

    engine.register_catalog("parquet", ParquetConnector(str(tmp_path / "pq")))
    for cat in ("orc", "parquet"):
        engine.execute(f"create table {cat}.data (k bigint, s varchar)")
        engine.execute(f"insert into {cat}.data values (1, 'x'), (2, 'y'), (3, 'x')")
    a = engine.execute("select s, count(*) from orc.data group by s order by s")
    b = engine.execute("select s, count(*) from parquet.data group by s order by s")
    assert a == b == [("x", 2), ("y", 1)]


def test_stripe_splits_distributed(engine):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    from trino_tpu.connectors.orc import OrcConnector
    from trino_tpu.runtime.engine import Engine

    root = engine.catalogs.get("orc").root
    engine.execute("create table big (k bigint)")
    engine.execute(
        "insert into big values " + ", ".join(f"({i})" for i in range(100))
    )
    eng = Engine(default_catalog="orc", distributed=True)
    eng.register_catalog("orc", OrcConnector(root))
    assert eng.execute("select count(*), sum(k) from big") == [(100, 4950)]
