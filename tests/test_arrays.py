"""ARRAY type, array functions, and UNNEST (reference: spi/block/ArrayBlock,
operator/unnest/UnnestOperator, sql/tree/Unnest).

Arrays are dictionary-coded distinct tuples (data/types.py ArrayType);
UNNEST is a static-shape expansion kernel under the capacity-retry protocol
(ops/relops.py unnest_expand).
"""

import pytest


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    return eng


# ------------------------------------------------------------ array functions


def test_array_literal_functions(engine):
    assert engine.execute(
        "select cardinality(array[1,2,3]), element_at(array[10,20], 2), "
        "contains(array[1,2], 5), contains(array[1,2], 2)"
    ) == [(3, 20, False, True)]


def test_subscript(engine):
    assert engine.execute("select array[7,8,9][2]") == [(8,)]


def test_element_at_out_of_bounds_is_null(engine):
    assert engine.execute("select element_at(array[1,2], 5)") == [(None,)]


def test_element_at_negative_index(engine):
    assert engine.execute("select element_at(array[1,2,3], -1)") == [(3,)]


def test_sequence(engine):
    assert engine.execute("select sequence(2, 5)") == [([2, 3, 4, 5],)]
    assert engine.execute("select sequence(5, 1, -2)") == [([5, 3, 1],)]


def test_array_sort_distinct_join_minmax(engine):
    assert engine.execute("select array_sort(array[3,1,2])") == [([1, 2, 3],)]
    assert engine.execute("select array_distinct(array[1,2,1,3,2])") == [([1, 2, 3],)]
    assert engine.execute("select array_join(array[1,2,3], '-')") == [("1-2-3",)]
    assert engine.execute(
        "select array_min(array[4,2,9]), array_max(array[4,2,9])"
    ) == [(2, 9)]
    assert engine.execute("select array_position(array[5,6,7], 6)") == [(2,)]


def test_split(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a,b'), (2,'c'), (3,'')")
    assert engine.execute("select k, cardinality(split(s, ',')) from t order by k") == [
        (1, 2), (2, 1), (3, 1),
    ]
    assert engine.execute("select split(s, ',')[1] from t order by k") == [
        ("a",), ("c",), ("",),
    ]


def test_dynamic_element_at(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1), (2), (3), (4)")
    # index is a traced lane, not a literal -> 2-D table gather path
    assert engine.execute(
        "select k, element_at(array[10,20,30], k) from t order by k"
    ) == [(1, 10), (2, 20), (3, 30), (4, None)]


def test_dynamic_contains(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1), (2), (5)")
    assert engine.execute(
        "select k, contains(array[1,5], k) from t order by k"
    ) == [(1, True), (2, False), (5, True)]


def test_array_column_in_table(engine):
    import numpy as np

    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import ArrayType, BIGINT

    conn = engine.catalogs.get("memory")
    conn.create_table(
        "arr_t",
        [ColumnSchema("k", BIGINT), ColumnSchema("v", ArrayType(BIGINT))],
    )
    vals = np.empty(3, dtype=object)
    vals[0], vals[1], vals[2] = (1, 2), (), (3, 4, 5)
    conn.insert("arr_t", {"k": np.asarray([1, 2, 3]), "v": vals})
    assert engine.execute("select k, cardinality(v) from arr_t order by k") == [
        (1, 2), (2, 0), (3, 3),
    ]
    assert engine.execute(
        "select k, x from arr_t cross join unnest(v) as u(x) order by k, x"
    ) == [(1, 1), (1, 2), (3, 3), (3, 4), (3, 5)]
    assert engine.execute("select k, v from arr_t order by k") == [
        (1, [1, 2]), (2, []), (3, [3, 4, 5]),
    ]


# ------------------------------------------------------------------- UNNEST


def test_unnest_standalone(engine):
    assert engine.execute("select * from unnest(array[1,2,3])") == [(1,), (2,), (3,)]


def test_unnest_with_ordinality(engine):
    assert engine.execute(
        "select x, o from unnest(sequence(5,7)) with ordinality as u(x, o)"
    ) == [(5, 1), (6, 2), (7, 3)]


def test_unnest_lateral_split(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a,b'), (2,'c')")
    assert engine.execute(
        "select k, part from t cross join unnest(split(s, ',')) as u(part) "
        "order by k, part"
    ) == [(1, "a"), (1, "b"), (2, "c")]


def test_unnest_in_from_list(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a,b'), (2,'c')")
    assert engine.execute(
        "select k, part from t, unnest(split(s, ',')) as u(part) "
        "order by k, part"
    ) == [(1, "a"), (1, "b"), (2, "c")]


def test_unnest_filter_on_element(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a,b'), (2,'b,c')")
    assert engine.execute(
        "select k from t, unnest(split(s, ',')) as u(part) where part = 'b' "
        "order by k"
    ) == [(1,), (2,)]


def test_unnest_zip(engine):
    # multiple arrays zip to the longest; shorter ones NULL-pad
    assert engine.execute(
        "select * from unnest(array[1,2,3], array[10,20])"
    ) == [(1, 10), (2, 20), (3, None)]


def test_left_join_unnest_keeps_empty(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a'), (2,'')")
    # split('') gives [''], so use a filter-produced empty... use nullif to
    # make row 2's array NULL: LEFT JOIN UNNEST keeps it with NULL element
    rows = engine.execute(
        "select k, x from t left join unnest(split(nullif(s,''), ',')) as u(x) "
        "on true order by k"
    )
    assert rows == [(1, "a"), (2, None)]


def test_unnest_aggregate(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'x,y,z'), (2,'x,y'), (3,'x')")
    assert engine.execute(
        "select part, count(*) as c from t, unnest(split(s, ',')) as u(part) "
        "group by part order by part"
    ) == [("x", 3), ("y", 2), ("z", 1)]


def test_unnest_distributed():
    import jax

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    eng = Engine(default_catalog="memory", distributed=True)
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table t (k bigint, s varchar)")
    eng.execute("insert into t values (1,'a,b'), (2,'b,c'), (3,'c,d'), (4,'d,e')")
    assert eng.execute(
        "select part, count(*) as c from t, unnest(split(s, ',')) as u(part) "
        "group by part order by part"
    ) == [("a", 1), ("b", 2), ("c", 2), ("d", 2), ("e", 1)]


def test_arrays_wire_roundtrip():
    """ARRAY columns cross the HTTP data plane as JSON text and re-encode in
    the receiver's dictionary (runtime/wire.py)."""
    import numpy as np

    from trino_tpu.data.page import Column, Page
    from trino_tpu.data.types import ArrayType, BIGINT
    from trino_tpu.runtime.wire import page_to_wire_chunks, wire_to_page

    vals = np.empty(3, dtype=object)
    vals[0], vals[1], vals[2] = (1, 2), (), (3,)
    col = Column.from_numpy(ArrayType(BIGINT), vals)
    blobs = page_to_wire_chunks(Page((col,)))
    page = wire_to_page(blobs, [ArrayType(BIGINT)])
    assert page.to_pylist() == [([1, 2],), ([],), ([3],)]


def test_unnest_select_star_order(engine):
    # SELECT * emits columns in WRITTEN FROM order even when UNNEST is first
    engine.execute("create table so (k bigint)")
    engine.execute("insert into so values (5)")
    assert engine.execute(
        "select * from unnest(array[7]) as un(y), so"
    ) == [(7, 5)]


def test_array_minmax_strings(engine):
    assert engine.execute(
        "select array_min(array['b','a']), array_max(array['b','a'])"
    ) == [("a", "b")]


def test_sequence_limit_is_cheap(engine):
    from trino_tpu.plan.planner import PlanningError

    with pytest.raises(PlanningError):
        engine.execute("select sequence(1, 10000000000)")


def test_array_null_elements(engine):
    # min/max -> NULL when a NULL element is present; sort puts NULLs last
    assert engine.execute(
        "select array_min(array[3,null,1]), array_sort(array[3,null,1])"
    ) == [(None, [1, 3, None])]


def test_outer_unnest_ordinality_null(engine):
    engine.execute("create table uo (k bigint, s varchar)")
    engine.execute("insert into uo values (1, 'a'), (2, '')")
    assert engine.execute(
        "select k, x, o from uo left join "
        "unnest(split(nullif(s,''), ',')) with ordinality as un(x, o) "
        "on true order by k"
    ) == [(1, "a", 1), (2, None, None)]
