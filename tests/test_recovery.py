"""Coordinator crash recovery: durable query journal, in-flight resumption
from the spool, client re-attach.

Reference behaviors being matched:
- the FTE promise that a stage output COMMITTED to durable storage is
  RE-READ, never recomputed (spi/exchange/ExchangeManager +
  trino-exchange-filesystem) — here extended across COORDINATOR death via
  the query journal (runtime/journal.py);
- StatementClientV1 polling nextUri through transient coordinator
  unavailability instead of failing the first refused connect.
"""

import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from trino_tpu.client import QueryFailed, StatementClient
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import CatalogManager, ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.runtime.journal import QueryJournal
from trino_tpu.runtime.spool import SpooledExchange
from trino_tpu.testing import DistributedQueryRunner

pytestmark = pytest.mark.smoke

JOIN_SQL = "select sum(v + w) from probe, build where probe.k = build.k"


class GatedMemoryConnector(MemoryConnector):
    """read_split blocks on `gate` for `gated_table` and counts reads per
    table — deterministic kill-mid-query timing plus proof of which stages
    recomputed after a restart (same fixture shape as test_spool)."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.gated_table = None
        self.reads: dict[str, int] = {}
        self._rlock = threading.Lock()

    def read_split(self, split, columns):
        with self._rlock:
            self.reads[split.table] = self.reads.get(split.table, 0) + 1
        if split.table == self.gated_table:
            assert self.gate.wait(timeout=120), "test gate never opened"
        return super().read_split(split, columns)


def _make_tables(conn):
    conn.create_table("build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)])
    conn.insert("build", {"k": np.arange(50, dtype=np.int64),
                          "w": np.arange(50, dtype=np.int64) * 10})
    conn.create_table("probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("probe", {"k": np.arange(2000, dtype=np.int64) % 50,
                          "v": np.arange(2000, dtype=np.int64)})
    return int((np.arange(2000) + (np.arange(2000) % 50) * 10).sum())


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


def _committed_dirs(spool_dir):
    if not os.path.isdir(spool_dir):
        return []
    return [n for n in os.listdir(spool_dir)
            if os.path.exists(os.path.join(spool_dir, n, "COMMITTED"))]


def _start_cluster(tmp_path, conn):
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.2,
        journal_path=str(tmp_path / "journal.jsonl"),
    )
    runner.register_catalog("memory", conn)
    runner.start()
    runner.coordinator.session.set("retry_policy", "TASK")
    runner.coordinator.session.set("exchange_spool_dir", str(tmp_path / "spool"))
    return runner


def _restart_session(tmp_path, policy):
    return {
        "retry_policy": "TASK",
        "exchange_spool_dir": str(tmp_path / "spool"),
        "resume_policy": policy,
    }


class _ClientThread(threading.Thread):
    """One protocol client riding a query across the coordinator restart."""

    def __init__(self, url, sql):
        super().__init__(daemon=True)
        self.client = StatementClient(url, reattach_max_elapsed_s=60.0)
        self.sql = sql
        self.result = None
        self.error = None

    def run(self):
        try:
            self.result = self.client.execute(self.sql, timeout=120)
        except Exception as e:  # re-raised on the main thread by the test
            self.error = e


def _crash_mid_query(tmp_path, conn, policy):
    """Start the gated join, wait until the build side COMMITTED to the
    spool and the probe side is mid-read, then kill the coordinator and
    boot a replacement on the same port with the given resume policy."""
    runner = _start_cluster(tmp_path, conn)
    spool = str(tmp_path / "spool")
    conn.gated_table = "probe"
    t = _ClientThread(runner.coordinator.url, JOIN_SQL)
    t.start()
    ready = _wait(
        lambda: _committed_dirs(spool) and conn.reads.get("probe", 0) > 0,
        timeout=60,
    )
    assert ready, "build stage never committed / probe stage never started"
    builds_before = conn.reads.get("build", 0)
    assert builds_before > 0
    port = runner.kill_coordinator()
    runner.restart_coordinator(port, session=_restart_session(tmp_path, policy))
    return runner, t, builds_before


def test_journal_roundtrip(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j = QueryJournal(p)
    j.append("admit", "q_aa", sql="select 1", session={"retry_policy": "TASK"},
             spooled=True)
    j.append("dispatch", "q_aa", fragment=1, ntasks=2, attempt=0)
    j.append("commit", "q_aa", fragment=1, part=0, task_id="q_aa_a0_f1_p0_t0")
    j.append("admit", "q_bb", sql="select 2", session={}, spooled=False)
    j.append("finish", "q_bb", state="FINISHED", error=None, error_code=None)
    j.close()
    with open(p, "a") as f:
        f.write('{"kind": "adm')  # torn trailing write at crash
    states = QueryJournal.replay(p)
    aa = states["q_aa"]
    assert aa.state == "INFLIGHT"
    assert aa.sql == "select 1"
    assert aa.session == {"retry_policy": "TASK"}
    assert aa.spooled is True
    assert aa.dispatches == {1: 2}
    assert aa.commits == {1: {0: "q_aa_a0_f1_p0_t0"}}
    assert aa.next_attempt == 1  # pre-crash attempt 0 -> resume tags at 1
    bb = states["q_bb"]
    assert bb.state == "FINISHED"
    assert QueryJournal.replay(str(tmp_path / "missing.jsonl")) == {}


def test_resume_skips_committed_stages(tmp_path):
    """resume_policy=RESUME: the client's poll loop rides through the
    restart, the query completes correctly, and the spool-committed build
    stage is re-read — ZERO build recomputation."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    runner, t, builds_before = _crash_mid_query(tmp_path, conn, "RESUME")
    try:
        conn.gate.set()
        t.join(timeout=120)
        assert not t.is_alive(), "client never finished after restart"
        assert t.error is None, f"query failed across restart: {t.error!r}"
        _, rows = t.result
        assert int(rows[0][0]) == expect
        # committed build output came from the spool, not a re-run
        assert conn.reads.get("build", 0) == builds_before
        coord = runner.coordinator
        assert _wait(lambda: coord._m_resumed.value("completed") >= 1, 15)
        body = urllib.request.urlopen(f"{coord.url}/metrics", timeout=10).read()
        assert b'trino_tpu_queries_resumed_total{outcome="completed"}' in body
        assert b"trino_tpu_journal_records_total" in body
    finally:
        conn.gate.set()
        runner.stop()


def test_restart_policy_recomputes_everything(tmp_path):
    """resume_policy=RESTART ignores the journaled commits: the query still
    completes correctly across the restart but the build side re-runs."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    runner, t, builds_before = _crash_mid_query(tmp_path, conn, "RESTART")
    try:
        conn.gate.set()
        t.join(timeout=120)
        assert not t.is_alive(), "client never finished after restart"
        assert t.error is None, f"query failed across restart: {t.error!r}"
        _, rows = t.result
        assert int(rows[0][0]) == expect
        assert _wait(lambda: conn.reads.get("build", 0) > builds_before, 10)
    finally:
        conn.gate.set()
        runner.stop()


def test_resume_policy_fail_typed_error_and_cleanup(tmp_path):
    """resume_policy=FAIL: the re-attached client gets the typed
    COORDINATOR_RESTART failure (410 on the poll), the orphan sweep cancels
    the dead query's worker tasks, and the spool GC reclaims its dirs."""
    conn = GatedMemoryConnector()
    _make_tables(conn)
    runner, t, _ = _crash_mid_query(tmp_path, conn, "FAIL")
    spool = str(tmp_path / "spool")
    try:
        t.join(timeout=60)
        assert not t.is_alive(), "client never observed the refusal"
        assert isinstance(t.error, QueryFailed), f"got {t.error!r}"
        assert t.error.error_code == "COORDINATOR_RESTART"
        assert runner.coordinator._m_resumed.value("refused") >= 1
        # the new coordinator's sweep cancels tasks of the abandoned query
        assert _wait(lambda: all(len(w.tasks) == 0 for w in runner.workers), 15)
        conn.gate.set()  # release reader threads parked inside read_split
        # age-0 GC reclaims the crashed query's committed + staging dirs
        runner.coordinator.session.set("spool_gc_age_s", "0")
        assert _wait(
            lambda: not any(
                os.path.isdir(os.path.join(spool, n))
                for n in os.listdir(spool)
            ),
            timeout=20,
        ), f"spool dirs never reclaimed: {os.listdir(spool)}"
    finally:
        conn.gate.set()
        runner.stop()


def test_first_commit_wins_late_precrash_attempt(tmp_path):
    """A pre-crash attempt finishing AFTER the resumed attempt committed
    must lose the rename race and never clobber the winner's chunks."""
    sp = SpooledExchange(str(tmp_path))
    assert sp.commit_task("q_x_a1_f1_p0_t0", {0: [b"winner"]}, attempt="1")
    assert not sp.commit_task("q_x_a1_f1_p0_t0", {0: [b"late"]}, attempt="0")
    assert sp.read_chunks("q_x_a1_f1_p0_t0", 0) == [b"winner"]
    # the loser's staging dir was discarded, not published
    assert not os.path.exists(
        os.path.join(str(tmp_path), "q_x_a1_f1_p0_t0.tmp-0")
    )


def test_spool_gc(tmp_path):
    d = str(tmp_path)
    sp = SpooledExchange(d)
    sp.commit_task("q_dead_a0_f1_p0_t0", {0: [b"x"]})
    sp.commit_task("q_live_a0_f1_p0_t0", {0: [b"y"]})
    os.makedirs(os.path.join(d, "q_dead_a0_f1_p1_t0.tmp-0", "buf0"))
    with open(os.path.join(d, "spill_0001.bin"), "wb") as f:
        f.write(b"z")  # stray file sharing the dir is NOT spool-owned
    assert sp.gc({"q_live"}, age_s=0.0) == {"committed": 1, "staging": 1}
    assert sp.is_committed("q_live_a0_f1_p0_t0")
    assert not sp.is_committed("q_dead_a0_f1_p0_t0")
    assert os.path.exists(os.path.join(d, "spill_0001.bin"))
    # young dirs under an age threshold survive (another coordinator may
    # still be writing them)
    sp.commit_task("q_dead2_a0_f1_p0_t0", {0: [b"x"]})
    assert sp.gc({"q_live"}, age_s=3600.0) == {"committed": 0, "staging": 0}
    assert sp.is_committed("q_dead2_a0_f1_p0_t0")


def test_journal_replay_folds_terminal_into_history(tmp_path):
    """Queries the journal knows FINISHED before the crash become history
    records on the replacement coordinator, not resumed queries."""
    from trino_tpu.runtime.coordinator import Coordinator

    p = str(tmp_path / "j.jsonl")
    j = QueryJournal(p)
    j.append("admit", "q_done", sql="select 1", session={}, spooled=False)
    j.append("finish", "q_done", state="FINISHED", error=None, error_code=None)
    j.close()
    coord = Coordinator(CatalogManager(), "memory", journal_path=p)
    coord.start()
    try:
        info = coord.history.get("q_done")
        assert info is not None and info["state"] == "FINISHED"
        assert "q_done" not in coord.queries
    finally:
        coord.stop()
