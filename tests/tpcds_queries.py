"""TPC-DS query subset (official query shapes, substitution parameters
chosen to select rows in the generated distributions).

Exercises the star-schema join patterns, partial/final aggregation over
repartition exchanges, window-over-aggregate ratios, correlated scalar
subqueries over CTEs, and EXISTS — the patterns north-star config #4
(TPC-DS Q64/Q95-class plans) is made of.
"""

QUERIES: dict[str, str] = {}
ORDERED: dict[str, bool] = {}

QUERIES["q01"] = """
with customer_total_return as (
  select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
         sum(sr_return_amt) as ctr_total_return
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk and d_year = 2000
  group by sr_customer_sk, sr_store_sk
)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (
    select avg(ctr_total_return) * 1.2 from customer_total_return ctr2
    where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk and s_state = 'CA'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
"""
ORDERED["q01"] = True

QUERIES["q03"] = """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manufact_id = 128 and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
"""
ORDERED["q03"] = False  # ties in sum_agg

QUERIES["q07"] = """
select i_item_id, avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""
ORDERED["q07"] = True

QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(ws_ext_sales_price) as itemrevenue,
  sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price)) over
    (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
ORDERED["q12"] = False

QUERIES["q19"] = """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 8 and d_moy = 11 and d_year = 1998
  and ss_customer_sk = c_customer_sk and c_current_addr_sk = ca_address_sk
  and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, brand_id, i_manufact_id
limit 100
"""
ORDERED["q19"] = False

QUERIES["q26"] = """
select i_item_id, avg(cs_quantity) as agg1, avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3, avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
  and cd_gender = 'F' and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""
ORDERED["q26"] = True

QUERIES["q42"] = """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as s
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
"""
ORDERED["q42"] = False

QUERIES["q52"] = """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
"""
ORDERED["q52"] = False

QUERIES["q55"] = """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
"""
ORDERED["q55"] = False

QUERIES["q96"] = """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
  and s_store_name = 'ese'
order by cnt
limit 100
"""
ORDERED["q96"] = True

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(ss_ext_sales_price) as itemrevenue,
  sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price)) over
    (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Jewelry', 'Sports', 'Books')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '2001-01-12' and date '2001-01-12' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
"""
ORDERED["q98"] = False

QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(cs_ext_sales_price) as itemrevenue,
  sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price)) over
    (partition by i_class) as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
ORDERED["q20"] = False

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 20 and 50
  and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-02-01' + interval '60' day
  and i_manufact_id in (678, 964, 918, 849)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""
ORDERED["q37"] = True

# Q94: unshipped-from-same-warehouse web orders with returns excluded —
# the EXISTS + NOT EXISTS self-join shape (north-star config #4 class)
QUERIES["q94"] = """
select count(distinct ws_order_number) as order_count,
  sum(ws_ext_ship_cost) as total_shipping_cost,
  sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-02-01' + interval '60' day
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ws1.ws_web_site_sk = web_site_sk
  and exists (select 1 from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select 1 from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
"""
ORDERED["q94"] = True

# Q95: same skeleton but the multi-warehouse order set rides a CTE consumed
# by two IN subqueries — CTE self-join + repeated-CTE CSE
QUERIES["q95"] = """
with ws_wh as
 (select ws1.ws_order_number as won
    from web_sales ws1, web_sales ws2
   where ws1.ws_order_number = ws2.ws_order_number
     and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) as order_count,
  sum(ws_ext_ship_cost) as total_shipping_cost,
  sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address
where d_date between date '1999-02-01' and date '1999-02-01' + interval '60' day
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ws1.ws_order_number in (select won from ws_wh)
  and ws1.ws_order_number in
      (select wr_order_number from web_returns, ws_wh
        where wr_order_number = won)
"""
ORDERED["q95"] = True

# Q64-lite: the cross-channel CTE joined against itself across two years —
# the structural core of Q64's cs1/cs2 pattern (full Q64's 20-way dimension
# join reuses patterns covered elsewhere in this suite)
QUERIES["q64lite"] = """
with cross_sales as
 (select i_item_sk as item_sk, d_year as syear,
         sum(ss_ext_sales_price) as sale,
         sum(ss_net_profit) as profit
    from store_sales, date_dim, item
   where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
     and exists (select 1 from store_returns
                  where ss_ticket_number = sr_ticket_number
                    and ss_item_sk = sr_item_sk)
   group by i_item_sk, d_year)
select cs1.item_sk, cs1.syear, cs1.sale, cs2.syear, cs2.sale
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 1999 and cs2.syear = 2000
  and cs2.sale > cs1.sale
order by cs1.item_sk, cs1.sale
limit 100
"""
ORDERED["q64lite"] = False
