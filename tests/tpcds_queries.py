"""TPC-DS query subset (official query shapes, substitution parameters
chosen to select rows in the generated distributions).

Exercises the star-schema join patterns, partial/final aggregation over
repartition exchanges, window-over-aggregate ratios, correlated scalar
subqueries over CTEs, and EXISTS — the patterns north-star config #4
(TPC-DS Q64/Q95-class plans) is made of.
"""

QUERIES: dict[str, str] = {}
ORDERED: dict[str, bool] = {}

QUERIES["q01"] = """
with customer_total_return as (
  select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
         sum(sr_return_amt) as ctr_total_return
  from store_returns, date_dim
  where sr_returned_date_sk = d_date_sk and d_year = 2000
  group by sr_customer_sk, sr_store_sk
)
select c_customer_id
from customer_total_return ctr1, store, customer
where ctr1.ctr_total_return > (
    select avg(ctr_total_return) * 1.2 from customer_total_return ctr2
    where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  and s_store_sk = ctr1.ctr_store_sk and s_state = 'CA'
  and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id
limit 100
"""
ORDERED["q01"] = True

QUERIES["q03"] = """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manufact_id = 128 and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
"""
ORDERED["q03"] = False  # ties in sum_agg

QUERIES["q07"] = """
select i_item_id, avg(ss_quantity) as agg1, avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""
ORDERED["q07"] = True

QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(ws_ext_sales_price) as itemrevenue,
  sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price)) over
    (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
ORDERED["q12"] = False

QUERIES["q19"] = """
select i_brand_id as brand_id, i_brand as brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 8 and d_moy = 11 and d_year = 1998
  and ss_customer_sk = c_customer_sk and c_current_addr_sk = ca_address_sk
  and substring(ca_zip, 1, 5) <> substring(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, brand_id, i_manufact_id
limit 100
"""
ORDERED["q19"] = False

QUERIES["q26"] = """
select i_item_id, avg(cs_quantity) as agg1, avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3, avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
  and cd_gender = 'F' and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""
ORDERED["q26"] = True

QUERIES["q42"] = """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as s
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
"""
ORDERED["q42"] = False

QUERIES["q52"] = """
select d_year, i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
"""
ORDERED["q52"] = False

QUERIES["q55"] = """
select i_brand_id as brand_id, i_brand as brand,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
"""
ORDERED["q55"] = False

QUERIES["q96"] = """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7
  and s_store_name = 'ese'
order by cnt
limit 100
"""
ORDERED["q96"] = True

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(ss_ext_sales_price) as itemrevenue,
  sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price)) over
    (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Jewelry', 'Sports', 'Books')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '2001-01-12' and date '2001-01-12' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
"""
ORDERED["q98"] = False

QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
  sum(cs_ext_sales_price) as itemrevenue,
  sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price)) over
    (partition by i_class) as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
ORDERED["q20"] = False

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 20 and 50
  and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-02-01' + interval '60' day
  and i_manufact_id in (678, 964, 918, 849)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""
ORDERED["q37"] = True

# Q94: unshipped-from-same-warehouse web orders with returns excluded —
# the EXISTS + NOT EXISTS self-join shape (north-star config #4 class)
QUERIES["q94"] = """
select count(distinct ws_order_number) as order_count,
  sum(ws_ext_ship_cost) as total_shipping_cost,
  sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-02-01' + interval '60' day
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ws1.ws_web_site_sk = web_site_sk
  and exists (select 1 from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select 1 from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
"""
ORDERED["q94"] = True

# Q95: same skeleton but the multi-warehouse order set rides a CTE consumed
# by two IN subqueries — CTE self-join + repeated-CTE CSE
QUERIES["q95"] = """
with ws_wh as
 (select ws1.ws_order_number as won
    from web_sales ws1, web_sales ws2
   where ws1.ws_order_number = ws2.ws_order_number
     and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) as order_count,
  sum(ws_ext_ship_cost) as total_shipping_cost,
  sum(ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address
where d_date between date '1999-02-01' and date '1999-02-01' + interval '60' day
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk
  and ws1.ws_order_number in (select won from ws_wh)
  and ws1.ws_order_number in
      (select wr_order_number from web_returns, ws_wh
        where wr_order_number = won)
"""
ORDERED["q95"] = True

# Q64 (full): the 18-relation cross_sales region — store_sales x returns x
# cs_ui x 3 date roles x 2 demographic/household/address/income-band roles x
# store x promotion x item — self-joined across consecutive years.  The join
# graph exceeds the reorder DP limit, exercising the greedy order
# (plan/reorder.py _greedy_order).  Substitution parameters adapted to the
# generated distributions (price band widened; colors from the generator's
# palette).
QUERIES["q64"] = """
with cs_ui as
 (select cs_item_sk,
         sum(cs_ext_list_price) as sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) as refund
    from catalog_sales, catalog_returns
   where cs_item_sk = cr_item_sk and cs_order_number = cr_order_number
   group by cs_item_sk
  having sum(cs_ext_list_price) > 2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)),
cross_sales as
 (select i_product_name as product_name, i_item_sk as item_sk,
         s_store_name as store_name, s_zip as store_zip,
         ad1.ca_street_number as b_street_number, ad1.ca_street_name as b_street_name,
         ad1.ca_city as b_city, ad1.ca_zip as b_zip,
         ad2.ca_street_number as c_street_number, ad2.ca_street_name as c_street_name,
         ad2.ca_city as c_city, ad2.ca_zip as c_zip,
         d1.d_year as syear, d2.d_year as fsyear, d3.d_year as s2year,
         count(*) as cnt,
         sum(ss_wholesale_cost) as s1, sum(ss_list_price) as s2,
         sum(ss_coupon_amt) as s3
    from store_sales, store_returns, cs_ui,
         date_dim d1, date_dim d2, date_dim d3,
         store, customer, customer_demographics cd1, customer_demographics cd2,
         promotion, household_demographics hd1, household_demographics hd2,
         customer_address ad1, customer_address ad2,
         income_band ib1, income_band ib2, item
   where ss_store_sk = s_store_sk
     and ss_sold_date_sk = d1.d_date_sk
     and ss_customer_sk = c_customer_sk
     and ss_cdemo_sk = cd1.cd_demo_sk
     and ss_hdemo_sk = hd1.hd_demo_sk
     and ss_addr_sk = ad1.ca_address_sk
     and ss_item_sk = i_item_sk
     and ss_item_sk = sr_item_sk
     and ss_ticket_number = sr_ticket_number
     and ss_item_sk = cs_ui.cs_item_sk
     and c_current_cdemo_sk = cd2.cd_demo_sk
     and c_current_hdemo_sk = hd2.hd_demo_sk
     and c_current_addr_sk = ad2.ca_address_sk
     and c_first_sales_date_sk = d2.d_date_sk
     and c_first_shipto_date_sk = d3.d_date_sk
     and ss_promo_sk = p_promo_sk
     and hd1.hd_income_band_sk = ib1.ib_income_band_sk
     and hd2.hd_income_band_sk = ib2.ib_income_band_sk
     and cd1.cd_marital_status <> cd2.cd_marital_status
     and i_color in ('azure', 'beige', 'black', 'blue', 'brown', 'green')
     and i_current_price between 1 and 1 + 98
     and i_current_price between 1 + 1 and 1 + 99
   group by i_product_name, i_item_sk, s_store_name, s_zip,
            ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
            ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
            d1.d_year, d2.d_year, d3.d_year)
select cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear, cs1.cnt,
       cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,
       cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32, cs2.syear as syear2,
       cs2.cnt as cnt2
  from cross_sales cs1, cross_sales cs2
 where cs1.item_sk = cs2.item_sk
   and cs1.syear = 1999
   and cs2.syear = 1999 + 1
   and cs2.cnt <= cs1.cnt
   and cs1.store_name = cs2.store_name
   and cs1.store_zip = cs2.store_zip
 order by cs1.product_name, cs1.store_name, cs2.cnt, cs1.s1, s12
"""
ORDERED["q64"] = False

# Q64-lite: the cross-channel CTE joined against itself across two years —
# the structural core of Q64's cs1/cs2 pattern (full Q64's 20-way dimension
# join reuses patterns covered elsewhere in this suite)
QUERIES["q64lite"] = """
with cross_sales as
 (select i_item_sk as item_sk, d_year as syear,
         sum(ss_ext_sales_price) as sale,
         sum(ss_net_profit) as profit
    from store_sales, date_dim, item
   where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
     and exists (select 1 from store_returns
                  where ss_ticket_number = sr_ticket_number
                    and ss_item_sk = sr_item_sk)
   group by i_item_sk, d_year)
select cs1.item_sk, cs1.syear, cs1.sale, cs2.syear, cs2.sale
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 1999 and cs2.syear = 2000
  and cs2.sale > cs1.sale
order by cs1.item_sk, cs1.sale
limit 100
"""
ORDERED["q64lite"] = False

QUERIES["q06"] = """
select a.ca_state as state, count(*) as cnt
from customer_address a, customer c, store_sales s, date_dim d, item i
where a.ca_address_sk = c.c_current_addr_sk
  and c.c_customer_sk = s.ss_customer_sk
  and s.ss_sold_date_sk = d.d_date_sk
  and s.ss_item_sk = i.i_item_sk
  and d.d_month_seq = (select distinct d_month_seq from date_dim
                        where d_year = 1999 and d_moy = 1)
  and i.i_current_price > (select 1.2 * avg(j.i_current_price) from item j
                            where j.i_category = i.i_category)
group by a.ca_state
having count(*) >= 1
order by cnt, a.ca_state
limit 100
"""
ORDERED["q06"] = False

QUERIES["q13"] = """
select avg(ss_quantity) as a1, avg(ss_ext_sales_price) as a2,
       avg(ss_ext_wholesale_cost) as a3, sum(ss_ext_wholesale_cost) as a4
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00 and hd_dep_count = 3)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'S' and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00 and hd_dep_count = 1)
    or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
        and ss_sales_price between 150.00 and 200.00 and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'MI') and ss_net_profit between 100 and 200)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('CA', 'GA', 'NY') and ss_net_profit between 150 and 300)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('VA', 'TN', 'WA') and ss_net_profit between 50 and 250))
"""
ORDERED["q13"] = True

QUERIES["q15"] = """
select ca_zip, sum(cs_sales_price) as total
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substring(ca_zip, 1, 2) in ('85', '86', '88') or ca_state in ('CA', 'WA', 'GA')
       or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk and d_qoy = 1 and d_year = 2000
group by ca_zip
order by ca_zip
limit 100
"""
ORDERED["q15"] = True

QUERIES["q25"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4 and d1.d_year = 2000 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10 and d2.d_year = 2000
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10 and d3.d_year = 2000
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""
ORDERED["q25"] = True

QUERIES["q29"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4 and d1.d_year = 1999 and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 4 + 3 and d2.d_year = 1999
  and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_year in (1999, 1999 + 1, 1999 + 2)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""
ORDERED["q29"] = True

QUERIES["q32"] = """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id < 200
  and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and date '2000-01-27' + interval '90' day
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (
      select 1.3 * avg(cs_ext_discount_amt)
      from catalog_sales cs2, date_dim d2
      where cs2.cs_item_sk = i_item_sk
        and d2.d_date between date '2000-01-27' and date '2000-01-27' + interval '90' day
        and d2.d_date_sk = cs2.cs_sold_date_sk)
"""
ORDERED["q32"] = True

QUERIES["q34"] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and (hd_buy_potential = '>10000' or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and (hd_dep_count * 1.0 / hd_vehicle_count) > 1.2
        and d_year in (1998, 1998 + 1, 1998 + 2)
        and s_county in ('Adams County', 'Bronx County', 'Cook County', 'Dallas County')
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 20
order by c_last_name, c_first_name, c_salutation, c_preferred_cust_flag desc,
         ss_ticket_number
"""
ORDERED["q34"] = False

QUERIES["q38"] = """
select count(*) as cnt from (
  select distinct c_last_name, c_first_name, d_date
  from store_sales, date_dim, customer
  where store_sales.ss_sold_date_sk = date_dim.d_date_sk
    and store_sales.ss_customer_sk = customer.c_customer_sk
    and d_month_seq between 96 and 96 + 11
  intersect
  select distinct c_last_name, c_first_name, d_date
  from catalog_sales, date_dim, customer
  where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
    and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
    and d_month_seq between 96 and 96 + 11
  intersect
  select distinct c_last_name, c_first_name, d_date
  from web_sales, date_dim, customer
  where web_sales.ws_sold_date_sk = date_dim.d_date_sk
    and web_sales.ws_bill_customer_sk = customer.c_customer_sk
    and d_month_seq between 96 and 96 + 11
) hot_cust
"""
ORDERED["q38"] = True

QUERIES["q40"] = """
select w_state, i_item_id,
  sum(case when d_date < date '2000-03-11'
           then cs_sales_price - coalesce(cr_refunded_cash, 0) else 0 end) as sales_before,
  sum(case when d_date >= date '2000-03-11'
           then cs_sales_price - coalesce(cr_refunded_cash, 0) else 0 end) as sales_after
from catalog_sales
     left outer join catalog_returns
       on (cs_order_number = cr_order_number and cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
where i_current_price between 10 and 60
  and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between date '2000-02-10' and date '2000-03-11' + interval '30' day
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
"""
ORDERED["q40"] = True

QUERIES["q43"] = """
select s_store_name, s_store_id,
  sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) as sun_sales,
  sum(case when d_day_name = 'Monday' then ss_sales_price else null end) as mon_sales,
  sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) as tue_sales,
  sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) as wed_sales,
  sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) as thu_sales,
  sum(case when d_day_name = 'Friday' then ss_sales_price else null end) as fri_sales,
  sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) as sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and s_gmt_offset = -5 and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
"""
ORDERED["q43"] = True

QUERIES["q46"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics, customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
        and d_dow in (6, 0)
        and d_year in (1999, 1999 + 1, 1999 + 2)
        and s_city in ('Midway', 'Fairview')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
"""
ORDERED["q46"] = True

QUERIES["q50"] = """
select s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30) then 1 else 0 end) as d30,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30)
            and (sr_returned_date_sk - ss_sold_date_sk <= 60) then 1 else 0 end) as d60,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60)
            and (sr_returned_date_sk - ss_sold_date_sk <= 90) then 1 else 0 end) as d90,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90)
            and (sr_returned_date_sk - ss_sold_date_sk <= 120) then 1 else 0 end) as d120,
  sum(case when (sr_returned_date_sk - ss_sold_date_sk > 120) then 1 else 0 end) as d120plus
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk and ss_store_sk = s_store_sk
group by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
order by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
limit 100
"""
ORDERED["q50"] = True

QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) as ave
        from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
                from store_sales, date_dim
               where ss_sold_date_sk = d_date_sk and d_month_seq between 96 and 96 + 11
               group by ss_store_sk, ss_item_sk) sa
       group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
        from store_sales, date_dim
       where ss_sold_date_sk = d_date_sk and d_month_seq between 96 and 96 + 11
       group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc
limit 100
"""
ORDERED["q65"] = False  # revenue ties across items with equal desc

QUERIES["q73"] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dom between 1 and 2
        and (hd_buy_potential = '>10000' or hd_buy_potential = '0-500')
        and hd_vehicle_count > 0
        and (hd_dep_count * 1.0 / hd_vehicle_count) > 1
        and d_year in (2000, 2000 + 1, 2000 + 2)
        and s_county in ('Kent County', 'Lake County', 'Polk County', 'Wayne County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_last_name asc
"""
ORDERED["q73"] = False  # count ties

QUERIES["q90"] = """
select cast(amc as double) / cast(pmc as double) as am_pm_ratio
from (select count(*) as amc from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and t_hour between 8 and 9
        and household_demographics.hd_dep_count = 6) at1,
     (select count(*) as pmc from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = time_dim.t_time_sk
        and ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        and ws_web_page_sk = web_page.wp_web_page_sk
        and t_hour between 19 and 20
        and household_demographics.hd_dep_count = 6) pt1
order by am_pm_ratio
"""
ORDERED["q90"] = True

QUERIES["q93"] = """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end as act_sales
      from store_sales
           left outer join store_returns
             on (sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number),
           reason
      where sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Stopped working') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
"""
ORDERED["q93"] = True

QUERIES["q97"] = """
with ssci as
 (select ss_customer_sk as customer_sk, ss_item_sk as item_sk
    from store_sales, date_dim
   where ss_sold_date_sk = d_date_sk and d_month_seq between 96 and 96 + 11
   group by ss_customer_sk, ss_item_sk),
csci as
 (select cs_bill_customer_sk as customer_sk, cs_item_sk as item_sk
    from catalog_sales, date_dim
   where cs_sold_date_sk = d_date_sk and d_month_seq between 96 and 96 + 11
   group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null and csci.customer_sk is null
                then 1 else 0 end) as store_only,
       sum(case when ssci.customer_sk is null and csci.customer_sk is not null
                then 1 else 0 end) as catalog_only,
       sum(case when ssci.customer_sk is not null and csci.customer_sk is not null
                then 1 else 0 end) as store_and_catalog
from ssci full outer join csci
  on (ssci.customer_sk = csci.customer_sk and ssci.item_sk = csci.item_sk)
"""
ORDERED["q97"] = True

QUERIES["q99"] = """
select substring(w_warehouse_name, 1, 20) as wname, sm_type, cc_name,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30) then 1 else 0 end) as d30,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
            and (cs_ship_date_sk - cs_sold_date_sk <= 60) then 1 else 0 end) as d60,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
            and (cs_ship_date_sk - cs_sold_date_sk <= 90) then 1 else 0 end) as d90,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
            and (cs_ship_date_sk - cs_sold_date_sk <= 120) then 1 else 0 end) as d120,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 120) then 1 else 0 end) as d120plus
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 96 and 96 + 23
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substring(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
"""
ORDERED["q99"] = True
