"""UNION / INTERSECT / EXCEPT differential tests vs sqlite."""

import pytest

from tests.oracle import assert_rows_equal

SETOP_QUERIES = {
    "union_all": """
        select n_name as name from nation where n_regionkey = 0
        union all
        select r_name as name from region
    """,
    "union_distinct": """
        select n_regionkey as k from nation
        union
        select r_regionkey as k from region
    """,
    "union_mixed_types": """
        select n_nationkey as v from nation where n_nationkey < 3
        union all
        select s_acctbal as v from supplier where s_suppkey < 4
    """,
    "intersect": """
        select n_regionkey as k from nation where n_nationkey < 10
        intersect
        select r_regionkey as k from region where r_regionkey > 1
    """,
    "except": """
        select r_regionkey as k from region
        except
        select n_regionkey as k from nation where n_nationkey < 5
    """,
    "union_order_limit": """
        select c_custkey as k from customer where c_custkey < 50
        union
        select o_custkey as k from orders where o_custkey < 60
        order by k desc
        limit 7
    """,
    "chained": """
        select n_regionkey as k from nation
        union
        select r_regionkey as k from region
        except
        select 0 as k from region
    """,
}


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", sorted(SETOP_QUERIES))
def test_setop(name, engine, oracle):
    sql = SETOP_QUERIES[name]
    got = engine.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=("order by" in sql))


def test_setop_distributed(tpch_tiny, oracle):
    import jax

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(distributed=True, devices=jax.devices()[:8])
    eng.register_catalog("tpch", TpchConnector(0.01))
    for name in ("union_all", "union_distinct", "except"):
        sql = SETOP_QUERIES[name]
        assert_rows_equal(eng.query(sql), oracle.query(sql), ordered=False)
