"""Out-of-core execution + memory accounting tests.

Reference strategy: the spill suites force tiny memory limits and assert
queries still answer correctly (SpillableHashAggregationBuilder,
HashBuilderOperator spill states).  Here a tiny query_max_memory_bytes
budget forces the partitioned disk-spilled path; results must be identical
to the in-memory engine and the oracle.
"""

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.engine import Engine
from trino_tpu.runtime.memory import MemoryContext, MemoryExceeded, QueryMemoryPool


@pytest.fixture(scope="module")
def engine():
    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


def test_memory_pool_accounting():
    pool = QueryMemoryPool(budget=1000)
    ctx = MemoryContext(pool, "op1")
    ctx.set(600)
    assert pool.used == 600
    ctx.set(300)  # shrink frees
    assert pool.used == 300
    with pytest.raises(MemoryExceeded):
        MemoryContext(pool, "op2").set(800)
    ctx.close()
    assert pool.used == 0
    assert pool.peak == 600


@pytest.mark.parametrize("name", ["q01", "q18", "q03"])
def test_out_of_core_matches_oracle(name, engine, oracle):
    """A budget far below the table footprint forces spill; results match.

    q18 is north-star config #3's shape: high-cardinality group-by feeding
    a join and TopN — exactly the state that outgrows HBM at scale.
    """
    # small enough to force several slices, big enough that the part count
    # (capped at 16) keeps per-slice compiles from dominating the suite
    engine.session.set("query_max_memory_bytes", "3000000")  # ~3 MB
    try:
        got = engine.query(QUERIES[name])
        assert engine.last_spill.spill_files > 0, "expected disk-spilled exchanges"
        assert engine.last_spill.spilled_bytes > 0
        want = oracle.query(QUERIES[name])
        assert_rows_equal(got, want, ordered=ORDERED[name])
    finally:
        engine.session.set("query_max_memory_bytes", "0")


def test_budget_large_enough_stays_in_memory(engine):
    engine.session.set("query_max_memory_bytes", str(10**12))
    engine.last_spill = None
    try:
        rows = engine.query("select count(*) from lineitem")
        assert rows[0][0] > 0
        assert engine.last_spill is None  # estimate under budget: no spill
    finally:
        engine.session.set("query_max_memory_bytes", "0")


def test_reactive_spill_on_device_oom(engine, oracle):
    """The pre-plan estimate admits the query, but execution hits device OOM
    (simulated RESOURCE_EXHAUSTED): the engine falls back to the out-of-core
    partitioned executor automatically — no session hint — and the result
    still matches the oracle (VERDICT r2 'reactive spill')."""
    sql = ("select l_returnflag, count(*) as c, sum(l_quantity) as q "
           "from lineitem group by l_returnflag order by l_returnflag")
    expected = oracle.query(sql)

    real_execute = engine.executor.execute
    calls = {"n": 0}

    def oom_once(plan, *a, **kw):
        if calls["n"] == 0:
            calls["n"] += 1
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to allocate"
                " 99999999999 bytes"
            )
        return real_execute(plan, *a, **kw)

    engine.executor.execute = oom_once
    # pretend the device reports a budget so the try/except path engages
    engine.session.set("query_max_memory_bytes", str(10**12))
    try:
        got = engine.query(sql)
    finally:
        engine.executor.execute = real_execute
        engine.session.set("query_max_memory_bytes", "0")
    from tests.oracle import assert_rows_equal

    assert_rows_equal(got, expected, ordered=True)
    assert calls["n"] == 1, "OOM fallback never engaged"
    assert engine.last_spill.spilled_bytes > 0
