"""Split-driven scan execution (runtime/splits.py): morsel enumeration,
lazy scheduling with bounded per-worker queues, split-level retry and
straggler stealing, memory-revocation parking, and the scale-invariance
promise — data size moves the split COUNT, never the compiled shapes.

Reference behaviors being matched:
- SourcePartitionedScheduler's lazy split queueing + bounded node queues
  (execution/scheduler/SourcePartitionedScheduler.java);
- FTE retry one level finer: a lost morsel is re-assigned ALONE, and a
  spool-COMMITTED morsel is re-served, never re-read (the exactly-once
  proof here is a literal connector read count);
- the pow2 capacity-bucketing signature collapse (ROADMAP): the same
  query at two data scales compiles the same NUMBER of jit signatures.
"""

import os
import threading
import time

import numpy as np
import pytest

from tests.tpch_queries import QUERIES
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import CatalogManager, ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.plan.nodes import TableScan
from trino_tpu.runtime.splits import (
    SplitScheduler,
    current_backlog,
    scan_split_plan,
)
from trino_tpu.testing import DistributedQueryRunner
from trino_tpu.utils.profiler import PROFILER

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------- helpers


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


def _committed_dirs(spool_dir):
    if not os.path.isdir(spool_dir):
        return []
    return [n for n in os.listdir(spool_dir)
            if os.path.exists(os.path.join(spool_dir, n, "COMMITTED"))]


def _split_info(coord):
    """The `splits` block of the most recent query that had one."""
    out = None
    for rec in coord.queries.values():
        qi = rec.get("query_info") or {}
        if qi.get("splits"):
            out = qi["splits"]
    return out


def _cluster(tmp_path, conn, catalog="memory", workers=2, **session):
    runner = DistributedQueryRunner(
        num_workers=workers, default_catalog=catalog, heartbeat_interval=0.2,
    )
    runner.register_catalog(catalog, conn)
    runner.start()
    s = runner.coordinator.session
    s.set("retry_policy", "TASK")
    s.set("exchange_spool_dir", str(tmp_path / "spool"))
    s.set("split_driven_scans", "true")
    for k, v in session.items():
        s.set(k, str(v))
    return runner


def _sched(n, depth=2, parked=None):
    s = SplitScheduler(n, queue_depth=depth, is_parked=parked)
    for p in range(n):
        s.add(p)
    return s


# ------------------------------------------------- scheduler unit behavior


def test_assign_bounded_queues_least_loaded():
    s = _sched(10, depth=2)
    got = s.assign(["w0", "w1"])
    # 2 workers x depth 2: the queue bound, not the pool size, is the cap
    assert len(got) == 4
    assert {w for _, w in got} == {"w0", "w1"}
    assert s.backlog() == 6
    # a full cluster assigns nothing more until a slot frees
    assert s.assign(["w0", "w1"]) == []
    p0, w0 = got[0]
    s.on_done(p0)
    more = s.assign(["w0", "w1"])
    assert len(more) == 1 and more[0][1] == w0  # exactly the freed slot
    s.close()
    assert s.backlog() == 0


def test_backlog_is_process_wide_and_released_on_close():
    base = current_backlog()
    s = _sched(5, depth=1)
    assert current_backlog() == base + 5
    s.assign(["w0"])  # one split in flight, four still queued
    assert current_backlog() == base + 4
    s.close()
    assert current_backlog() == base


def test_parked_worker_splits_wait_instead_of_resliced():
    parked = {"w0"}
    s = _sched(4, depth=2, parked=lambda u: u in parked)
    got = s.assign(["w0", "w1"])
    # the revoked worker gets NOTHING; its share waits in the pool
    assert len(got) == 2 and all(w == "w1" for _, w in got)
    assert s.backlog() == 2
    assert s.stats["parked"] == 1
    parked.clear()  # lease re-granted: the parked splits drain normally
    got2 = s.assign(["w0", "w1"])
    assert len(got2) == 2 and all(w == "w0" for _, w in got2)
    s.close()


def test_retry_reassigns_single_split_away_from_failure():
    s = _sched(2, depth=2)
    owners = dict(s.assign(["w0", "w1"]))
    p = next(p for p, w in owners.items() if w == "w0")
    assert s.retry(p, ["w0", "w1"], exclude="w0") == "w1"
    assert s.stats["retries"] == 1
    # sole survivor: the excluded worker is still better than nothing
    assert s.retry(p, ["w1"], exclude="w1") == "w1"
    s.close()


def test_steal_requires_dry_pool_and_is_once_per_split():
    s = _sched(3, depth=2)
    assigned = dict(s.assign(["w0"]))  # w0 full (2), one split queued
    assert s.steal(["w0", "w1"]) is None  # pool not dry: assign, don't steal
    more = s.assign(["w0", "w1"])
    assert len(more) == 1 and more[0][1] == "w1"
    s.on_done(more[0][0])  # w1 idle, pool dry, w0 straggling
    st = s.steal(["w0", "w1"])
    assert st is not None
    p, thief = st
    assert thief == "w1" and p in assigned
    assert s.steal(["w0", "w1"], parts={p}) is None  # one steal per split
    s.steal_abort(p, thief)  # thief died pre-POST: bookkeeping undone
    assert s.steal(["w0", "w1"], parts={p}) == (p, thief)
    s.close()


def test_steal_respects_lagging_parts_filter():
    s = _sched(2, depth=2)
    owners = dict(s.assign(["w0"]))
    parts = set(owners)
    lagging = {min(parts)}
    st = s.steal(["w0", "w1"], parts=lagging)
    assert st is not None and st[0] == min(parts)
    s.close()


# --------------------------------------------------------- split planning


def _mem_catalogs(conn):
    cm = CatalogManager()
    cm.register("memory", conn)
    return cm


def test_scan_split_plan_pow2_count_scales_pad_does_not():
    conn = MemoryConnector()
    conn.create_table("t", [ColumnSchema("k", BIGINT)])
    conn.insert("t", {"k": np.arange(1000, dtype=np.int64)})
    cats = _mem_catalogs(conn)
    scan = TableScan("memory", "t", ("k",), (BIGINT,))
    n, pad = scan_split_plan(scan, cats, 100)
    assert pad == 128  # pow2 bucket of the target
    assert n == -(-1000 // 128)
    # 10x the data: the pad (the compiled shape) is IDENTICAL — only the
    # morsel count moves
    conn.insert("t", {"k": np.arange(9000, dtype=np.int64)})
    n2, pad2 = scan_split_plan(scan, cats, 100)
    assert (n2, pad2) == (-(-10000 // 128), pad)


def test_scan_split_plan_skips_bucketed_tables():
    class Bucketed(MemoryConnector):
        def table_partitioning(self, table):
            return (("k",), 4)

    conn = Bucketed()
    conn.create_table("t", [ColumnSchema("k", BIGINT)])
    conn.insert("t", {"k": np.arange(100, dtype=np.int64)})
    cats = CatalogManager()
    cats.register("memory", conn)
    scan = TableScan("memory", "t", ("k",), (BIGINT,))
    # morselizing a connector-bucketed scan would break collocated-join
    # alignment: the fragment keeps its bucket-count fan-out
    assert scan_split_plan(scan, cats, 100) is None


# ------------------------------------------------------- cluster behavior


def _make_table(conn, nrows, groups=7):
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("t", {"k": np.arange(nrows, dtype=np.int64) % groups,
                      "v": np.arange(nrows, dtype=np.int64)})
    return int(np.arange(nrows).sum())


def test_split_lost_fault_retries_one_morsel_not_the_scan(tmp_path):
    conn = MemoryConnector()
    oracle = _make_table(conn, 2000)
    runner = _cluster(tmp_path, conn, split_target_rows=256)
    try:
        runner.inject_task_failure(
            worker_index=0, task_id="*", mode="SPLIT_LOST", count=1
        )
        rows = runner.query("select sum(v) from t")
        assert [list(r) for r in rows] == [[oracle]]
        info = _split_info(runner.coordinator)
        assert info["splits"] == 8 and info["completed"] == 8
        # ONE morsel was re-assigned; the other seven were never touched
        assert info["retries"] == 1
    finally:
        runner.stop()


class HalfGatedConnector(MemoryConnector):
    """The first `free_reads` morsel reads pass (and their task outputs
    COMMIT to the spool); every later read blocks on `gate`.  Counts each
    read_split per table — the exactly-once proof is this count: a
    committed morsel is re-SERVED downstream, never re-read."""

    def __init__(self, free_reads):
        super().__init__()
        self.gate = threading.Event()
        self.gated_table = None
        self.free_reads = free_reads
        self.reads: dict[str, int] = {}
        self._rlock = threading.Lock()

    def read_split(self, split, columns):
        with self._rlock:
            self.reads[split.table] = self.reads.get(split.table, 0) + 1
            n = self.reads[split.table]
        if split.table == self.gated_table and n > self.free_reads:
            assert self.gate.wait(timeout=120), "test gate never opened"
        return super().read_split(split, columns)


@pytest.mark.chaos
def test_worker_kill_mid_scan_split_retry_exactly_once(tmp_path):
    """The headline chaos scenario: kill a worker holding part of a scan's
    splits mid-read.  Zero client-visible failures; only the LOST morsels
    are re-read (retries < splits); every spool-committed morsel is served
    from its committed task dir, never recomputed."""
    conn = HalfGatedConnector(free_reads=4)
    oracle = _make_table(conn, 2000)
    conn.gated_table = "t"
    runner = _cluster(
        tmp_path, conn, split_target_rows=256, split_queue_depth=1
    )
    spool = str(tmp_path / "spool")
    res: dict = {}

    def go():
        try:
            res["rows"] = runner.query("select sum(v) from t")
        except Exception as e:  # pragma: no cover - re-raised below
            res["err"] = e

    th = threading.Thread(target=go, daemon=True)
    try:
        th.start()
        # four morsels committed, both workers blocked mid-read on a fifth
        # and sixth — the query is genuinely mid-scan
        ready = _wait(
            lambda: len(_committed_dirs(spool)) >= 4
            and conn.reads.get("t", 0) >= 6,
            timeout=60,
        )
        assert ready, (
            f"scan never reached mid-flight: committed="
            f"{len(_committed_dirs(spool))} reads={conn.reads}"
        )
        runner.kill_worker(1)
        conn.gate.set()
        th.join(timeout=120)
        assert not th.is_alive(), "query wedged after worker death"
        assert "err" not in res, f"client saw a failure: {res.get('err')}"
        assert [list(r) for r in res["rows"]] == [[oracle]]
        info = _split_info(runner.coordinator)
        assert info["splits"] == 8 and info["completed"] == 8
        # split-level retry: strictly fewer re-runs than morsels
        assert 1 <= info["retries"] < info["splits"]
        # exactly-once: total connector reads = one per morsel plus ONLY
        # the lost attempts — the four pre-kill committed morsels were
        # never read again
        budget = info["splits"] + info["retries"] + info["steals"]
        assert info["splits"] <= conn.reads["t"] <= budget, (
            conn.reads, info,
        )
    finally:
        conn.gate.set()
        runner.stop()


# ------------------------------------------------- signature invariance


def _used_sigs(before, after):
    def uses(e):
        return (e.get("executes", 0) + e.get("compiles", 0)
                + e.get("fallback_executes", 0))

    return {s for s, e in after.items() if uses(e) > uses(before.get(s, {}))}


def test_jit_signature_count_invariant_across_scales(tmp_path):
    """Same query, 8x the data: more morsels, the SAME number of distinct
    jit signatures (profiler-witnessed) — the planner no longer bakes data
    size into scan shapes."""
    sql = "select k, sum(v) from t group by k order by k"
    used, splits = [], []
    for i, nrows in enumerate((1000, 8000)):
        conn = MemoryConnector()
        _make_table(conn, nrows)
        sub = tmp_path / f"scale{i}"
        sub.mkdir()
        runner = _cluster(sub, conn, split_target_rows=256)
        try:
            before = PROFILER.snapshot()
            rows = runner.query(sql)
            after = PROFILER.snapshot()
            exp = {k: 0 for k in range(7)}
            for r in range(nrows):
                exp[r % 7] += r
            assert [list(r) for r in rows] == [
                [k, exp[k]] for k in sorted(exp)
            ]
            splits.append(_split_info(runner.coordinator)["splits"])
            used.append(_used_sigs(before, after))
        finally:
            runner.stop()
    assert splits == [4, 32]  # data scale moved the morsel COUNT...
    assert used[0], "no jit signatures witnessed"
    # ...and nothing else: same signature count at both scales
    assert len(used[0]) == len(used[1]), (splits, used)


@pytest.mark.slow
@pytest.mark.chaos
def test_tpch_worker_kill_mid_scan_at_scale(tmp_path):
    """The acceptance drill at data scale: kill a worker mid-scan of
    TPC-H lineitem at CHAOS_SF (default sf1; crank it for bigger hosts).
    Zero client-visible failures, split retries strictly below the split
    count, and the connector read count proves committed morsels were
    never recomputed."""
    from trino_tpu.connectors.tpch import TpchConnector, tpch_data

    sf = float(os.environ.get("CHAOS_SF", "1"))

    class GatedTpch(TpchConnector):
        def __init__(self, scale, free_reads):
            super().__init__(scale)
            self.gate = threading.Event()
            self.free_reads = free_reads
            self.reads = 0
            self._rlock = threading.Lock()

        def read_split(self, split, columns):
            if split.table == "lineitem":
                with self._rlock:
                    self.reads += 1
                    n = self.reads
                if n > self.free_reads:
                    assert self.gate.wait(timeout=300), "gate never opened"
            return super().read_split(split, columns)

    li = tpch_data("lineitem", sf)  # generate outside the timed drill
    nrows = len(li["l_quantity"])
    oracle_count = nrows
    conn = GatedTpch(sf, free_reads=4)
    runner = _cluster(
        tmp_path, conn, catalog="tpch",
        split_target_rows=65536, split_queue_depth=1,
    )
    spool = str(tmp_path / "spool")
    res: dict = {}

    def go():
        try:
            res["rows"] = runner.query("select count(*) from lineitem")
        except Exception as e:
            res["err"] = e

    th = threading.Thread(target=go, daemon=True)
    try:
        th.start()
        ready = _wait(
            lambda: len(_committed_dirs(spool)) >= 4 and conn.reads >= 6,
            timeout=120,
        )
        assert ready, (
            f"scan never reached mid-flight: committed="
            f"{len(_committed_dirs(spool))} reads={conn.reads}"
        )
        runner.kill_worker(1)
        conn.gate.set()
        th.join(timeout=300)
        assert not th.is_alive(), "query wedged after worker death"
        assert "err" not in res, f"client saw a failure: {res.get('err')}"
        assert [list(r) for r in res["rows"]] == [[oracle_count]]
        info = _split_info(runner.coordinator)
        expected_splits = -(-nrows // 65536)
        assert info["splits"] == expected_splits
        assert info["completed"] == expected_splits
        assert 1 <= info["retries"] < info["splits"]
        budget = info["splits"] + info["retries"] + info["steals"]
        assert info["splits"] <= conn.reads <= budget, (conn.reads, info)
    finally:
        conn.gate.set()
        runner.stop()


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("name", ["q01", "q06"])
def test_tpch_signature_invariance_two_scales(name, tmp_path):
    from trino_tpu.connectors.tpch import TpchConnector

    used, splits = [], []
    for i, sf in enumerate((0.01, 0.02)):
        sub = tmp_path / f"sf{i}"
        sub.mkdir()
        runner = _cluster(
            sub, TpchConnector(sf), catalog="tpch", split_target_rows=8192
        )
        try:
            before = PROFILER.snapshot()
            rows = runner.query(QUERIES[name])
            after = PROFILER.snapshot()
            assert rows, f"{name} at sf={sf} returned nothing"
            splits.append(_split_info(runner.coordinator)["splits"])
            used.append(_used_sigs(before, after))
        finally:
            runner.stop()
    assert splits[1] > splits[0]  # 2x lineitem -> more morsels
    assert used[0], "no jit signatures witnessed"
    assert len(used[0]) == len(used[1]), (splits, used)


# ------------------------------------------------- file-backed splits


def _write_parquet_dir(root, table, files=3, groups_per_file=2, rows=1000):
    """A partitioned parquet table: `files` files x `groups_per_file` row
    groups of `rows` rows each, bigint k/v."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    tdir = os.path.join(str(root), table)
    os.makedirs(tdir, exist_ok=True)
    total = 0
    for f in range(files):
        n = rows * groups_per_file
        k = np.arange(total, total + n, dtype=np.int64)
        t = pa.table({"k": k, "v": k * 2})
        pq.write_table(
            t, os.path.join(tdir, f"part-{f}.parquet"), row_group_size=rows
        )
        total += n
    return total


def test_file_backed_scan_unit_plan(tmp_path):
    """The Parquet connector exposes its physical (file, row-group) units
    and the split plan deals one unit per morsel: the split COUNT follows
    the layout, the pad pow2-buckets the largest unit."""
    pytest.importorskip("pyarrow")
    from trino_tpu.connectors.parquet import ParquetConnector

    _write_parquet_dir(tmp_path, "t", files=3, groups_per_file=2, rows=1000)
    conn = ParquetConnector(str(tmp_path))
    assert conn.scan_unit_plan("t") == (6, 1000)

    cat = CatalogManager()
    cat.register("pq", conn)
    plan = scan_split_plan(
        TableScan("pq", "t", ("k", "v"), (BIGINT, BIGINT)), cat, 65536
    )
    assert plan is not None
    nsplits, pad = plan
    assert nsplits == 6  # one morsel per (file, row-group) unit
    assert pad == 1024  # pow2 over the 1000-row max unit
    # splits enumerate one unit each, file by file: every bucket reads
    # exactly 1000 rows
    splits = conn.get_splits("t", nsplits)
    assert len(splits) == 6
    assert all(
        len(conn.read_split(s, ["k"])["k"]) == 1000 for s in splits
    )


def test_file_backed_splits_distributed_query(tmp_path):
    """A partitioned parquet dir streams file-by-file through the split
    scheduler: 6 units -> 6 morsels, all completed, rows exact."""
    pytest.importorskip("pyarrow")
    from trino_tpu.connectors.parquet import ParquetConnector

    total = _write_parquet_dir(
        tmp_path / "data", "t", files=3, groups_per_file=2, rows=1000
    )
    runner = _cluster(
        tmp_path, ParquetConnector(str(tmp_path / "data")), catalog="pq",
        split_target_rows=65536,
    )
    try:
        rows = runner.query("select count(*), sum(v) from t")
        n = total
        assert [list(r) for r in rows] == [[n, int(np.arange(n).sum()) * 2]]
        info = _split_info(runner.coordinator)
        assert info["splits"] == 6
        assert info["completed"] == 6
    finally:
        runner.stop()
