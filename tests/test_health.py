"""Unit tests for the consumer-side exchange link scorer
(trino_tpu/runtime/health.py): EWMA grading over errors and latency,
decay back to HEALTHY, the DEAD breaker's half-open probe window, and the
hedge-delay quantile that paces the spool hedge race."""

import pytest

from trino_tpu.runtime.health import (
    DEAD,
    DEGRADED,
    HEALTHY,
    SUSPECT,
    LinkHealth,
)

PRODUCER = "http://127.0.0.1:9999"


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


@pytest.fixture
def clock():
    return FakeClock()


def test_unknown_link_is_healthy_and_usable():
    lh = LinkHealth()
    assert lh.state(PRODUCER) == HEALTHY
    assert lh.is_usable(PRODUCER)
    assert lh.should_probe(PRODUCER)
    assert lh.impaired() == {}


def test_error_ewma_grades_degraded_suspect_dead(clock):
    lh = LinkHealth(clock=clock)
    for _ in range(10):
        lh.record_success(PRODUCER, 0.01)
    assert lh.state(PRODUCER) == HEALTHY
    # one failure: error EWMA jumps to alpha (0.3) >= suspect threshold
    lh.record_failure(PRODUCER)
    assert lh.state(PRODUCER) == SUSPECT
    # consecutive failures ratchet to DEAD regardless of EWMA
    lh.record_failure(PRODUCER)
    lh.record_failure(PRODUCER)
    assert lh.state(PRODUCER) == DEAD
    assert lh.impaired() == {PRODUCER: DEAD}


def test_latency_only_gray_failure_reaches_suspect(clock):
    """GRAY_SLOW signature: zero errors, latency blows up vs the link's
    own baseline — the scorer must still leave HEALTHY."""
    lh = LinkHealth(clock=clock)
    for _ in range(8):
        lh.record_success(PRODUCER, 0.002)
    assert lh.state(PRODUCER) == HEALTHY
    states = set()
    # gradual slowdown first (5x the baseline), then the full gray stall:
    # the grade must walk HEALTHY -> DEGRADED -> SUSPECT
    for _ in range(6):
        lh.record_success(PRODUCER, 0.010)
        states.add(lh.state(PRODUCER))
    for _ in range(20):
        lh.record_success(PRODUCER, 0.5)  # 250x the baseline
        states.add(lh.state(PRODUCER))
    assert lh.state(PRODUCER) == SUSPECT
    assert DEGRADED in states  # passed through the intermediate grade
    # never DEAD: a slow link is not a dead link
    assert DEAD not in states


def test_success_decays_error_ewma_back_to_healthy(clock):
    lh = LinkHealth(clock=clock)
    for _ in range(5):
        lh.record_success(PRODUCER, 0.01)
    lh.record_failure(PRODUCER)
    assert lh.state(PRODUCER) == SUSPECT
    for _ in range(20):
        lh.record_success(PRODUCER, 0.01)
    assert lh.state(PRODUCER) == HEALTHY


def test_dead_link_half_open_probe_window(clock):
    lh = LinkHealth(clock=clock, probe_interval=2.0)
    for _ in range(3):
        lh.record_failure(PRODUCER)
    assert lh.state(PRODUCER) == DEAD
    # window closed right after the failure: not usable, no probe
    assert not lh.is_usable(PRODUCER)
    assert not lh.should_probe(PRODUCER)
    clock.advance(2.5)
    # window open: exactly one fetch loop wins the probe slot
    assert lh.is_usable(PRODUCER)
    assert lh.should_probe(PRODUCER)
    # the probe stamp closes the window for concurrent loops
    assert not lh.should_probe(PRODUCER)


def test_successful_probe_fully_restores_dead_link(clock):
    lh = LinkHealth(clock=clock, probe_interval=2.0)
    for _ in range(4):
        lh.record_failure(PRODUCER)
    assert lh.state(PRODUCER) == DEAD
    clock.advance(3.0)
    assert lh.should_probe(PRODUCER)
    lh.record_success(PRODUCER, 0.01)
    # same contract as the worker breaker: one good probe = full restore
    assert lh.state(PRODUCER) == HEALTHY
    assert lh.is_usable(PRODUCER)


def test_failed_probe_keeps_link_dead_and_recloses_window(clock):
    lh = LinkHealth(clock=clock, probe_interval=2.0)
    for _ in range(3):
        lh.record_failure(PRODUCER)
    clock.advance(3.0)
    assert lh.should_probe(PRODUCER)
    lh.record_failure(PRODUCER)
    assert lh.state(PRODUCER) == DEAD
    assert not lh.should_probe(PRODUCER)  # window re-anchored


def test_transition_callback_fires_outside_lock(clock):
    seen = []
    lh = LinkHealth(
        clock=clock,
        on_transition=lambda p, old, new: seen.append((p, old, new)),
    )
    for _ in range(3):
        lh.record_failure(PRODUCER)
    assert (PRODUCER, HEALTHY, SUSPECT) in seen
    assert seen[-1][2] == DEAD
    # callbacks may re-enter the scorer (flight recorder handlers do)
    seen.clear()
    lh2 = LinkHealth(
        clock=clock, on_transition=lambda p, o, n: lh2.state(p)
    )
    lh2.record_failure(PRODUCER)  # deadlock here = regression


def test_hedge_delay_default_until_enough_history(clock):
    lh = LinkHealth(clock=clock)
    assert lh.hedge_delay(PRODUCER, default=0.25) == 0.25
    for _ in range(3):
        lh.record_success(PRODUCER, 0.01)
    assert lh.hedge_delay(PRODUCER, default=0.25) == 0.25  # < 4 samples


def test_hedge_delay_tracks_latency_quantile(clock):
    lh = LinkHealth(clock=clock)
    for i in range(50):
        lh.record_success(PRODUCER, 0.010)
    lh.record_success(PRODUCER, 0.100)  # one tail outlier
    # p50 x3 stays near the typical latency, not the outlier (floor=0
    # here: the default 0.05 floor would clip a 30ms answer)
    mid = lh.hedge_delay(PRODUCER, quantile=0.5, multiplier=3.0, floor=0.0)
    assert mid == pytest.approx(0.030, rel=0.2)
    # p100 x3 sees the outlier
    assert lh.hedge_delay(PRODUCER, quantile=1.0, multiplier=3.0) == (
        pytest.approx(0.300, rel=0.01)
    )
    # the floor bounds pathologically fast links
    assert lh.hedge_delay(PRODUCER, quantile=0.0, floor=0.05) == 0.05


def test_snapshot_wire_shape(clock):
    lh = LinkHealth(clock=clock)
    lh.record_success(PRODUCER, 0.010)
    lh.record_failure(PRODUCER)
    snap = lh.snapshot()
    cell = snap[PRODUCER]
    assert cell["state"] == SUSPECT
    assert cell["samples"] == 2
    assert cell["consecutive_failures"] == 1
    assert cell["latency_ewma_ms"] == pytest.approx(10.0)
    assert cell["baseline_ms"] == pytest.approx(10.0)
    assert 0.0 < cell["error_ewma"] <= 1.0


def test_forget_and_reset(clock):
    lh = LinkHealth(clock=clock)
    lh.record_failure(PRODUCER)
    lh.record_failure("http://other:1")
    lh.forget(PRODUCER)
    assert lh.state(PRODUCER) == HEALTHY
    assert lh.snapshot().keys() == {"http://other:1"}
    lh.reset()
    assert lh.snapshot() == {}
