"""Differential TPC-H suite: engine vs sqlite oracle over identical data.

The reference's AbstractTestQueryFramework.assertQuery pattern
(testing/trino-testing/.../AbstractTestQueryFramework.java:344): run each
query on both engines, diff rows with float tolerance.
"""

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_query(name, engine, oracle):
    sql = QUERIES[name]
    got = engine.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])
