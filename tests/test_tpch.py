"""Differential TPC-H suite: engine vs sqlite oracle over identical data.

The reference's AbstractTestQueryFramework.assertQuery pattern
(testing/trino-testing/.../AbstractTestQueryFramework.java:344): run each
query on both engines, diff rows with float tolerance.
"""

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", sorted(QUERIES))
def test_tpch_query(name, engine, oracle):
    sql = QUERIES[name]
    got = engine.query(sql)
    expected = oracle.query(sql)
    assert_rows_equal(got, expected, ordered=ORDERED[name])


def test_adaptive_compaction_tightens_and_stays_correct():
    """Compact points (plan/optimizer.py insert_compaction) start as
    pass-throughs; after one run the executor shrinks them to the OBSERVED
    surviving count (the AdaptivePlanner-style runtime feedback), and
    results stay identical.  Uses a selective filter over a >=64k-row
    frame (the insertion gate)."""
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.plan.nodes import Compact, walk
    from trino_tpu.runtime.engine import Engine

    rng = np.random.default_rng(9)
    n = 200_000
    conn = MemoryConnector()
    conn.create_table(
        "big", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("big", {
        "k": rng.integers(0, 1_000_000, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    sql = "select sum(v), count(*) from big where k < 500"  # ~0.05% survive
    plan = eng.plan(sql)
    compacts = [i for i, x in enumerate(walk(plan)) if isinstance(x, Compact)]
    assert compacts, "no compaction point inserted over a 200k-row filter"
    got1 = eng.query(sql)
    caps1 = dict(eng.executor._learned_caps[plan])
    got2 = eng.query(sql)  # runs at the tightened tier
    ks = np.asarray(conn._data["big"]["k"])
    vs = np.asarray(conn._data["big"]["v"])
    want = [(int(vs[ks < 500].sum()), int((ks < 500).sum()))]
    assert got1 == want and got2 == want
    # at least one compact tier collapsed far below the 200k input frame
    from trino_tpu.exec.compiler import _node_ids

    node_ids = _node_ids(plan)
    tight = [
        caps1[i] for i in caps1
        if isinstance(node_ids.get(i), Compact) and caps1[i] <= 16384
    ]
    assert tight, f"no compact tier tightened: {caps1}"
