"""Graceful lifecycle plane: drain, speculation, watchdogs, shedding.

Reference behaviors being matched:
- server/GracefulShutdownHandler + NodeState.DRAINING: a draining worker
  rejects new tasks (503), finishes running ones, keeps serving its output
  buffers, then deregisters — consumers never notice (zero retries).
- execution/scheduler speculative execution: a straggler past the
  speculation quantile gets a backup attempt; the spool commit arbitrates
  exactly-once.
- QueryTracker.enforceTimeLimits: typed EXCEEDED_TIME_LIMIT /
  EXCEEDED_QUEUED_TIME_LIMIT kills surfaced to the client.
- dispatcher/DispatchManager backpressure: past the dispatch queue bound
  new statements get 429 + Retry-After instead of unbounded queueing.
"""

import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from tests.test_spool import GatedMemoryConnector, _make_tables
from trino_tpu.client import QueryFailed, StatementClient
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.testing import DistributedQueryRunner

pytestmark = pytest.mark.smoke

JOIN_SQL = "select sum(v + w) from probe, build where probe.k = build.k"


def _start_cluster(conn, tmp_path=None, num_workers=2, heartbeat=0.2):
    runner = DistributedQueryRunner(
        num_workers=num_workers, default_catalog="memory",
        heartbeat_interval=heartbeat,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    if tmp_path is not None:
        runner.coordinator.session.set("retry_policy", "TASK")
        runner.coordinator.session.set("exchange_spool_dir", str(tmp_path))
    return runner


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            return False
        time.sleep(interval)
    return True


def _await_query(runner, qid, timeout=120.0):
    sm = runner.coordinator.queries[qid]["sm"]
    assert _wait(lambda: sm.done, timeout), f"query stuck in {sm.state}"
    return sm


# --------------------------------------------------------------- drain


@pytest.mark.chaos
def test_drain_mid_query_zero_retries(tmp_path):
    """Drain 1 of 2 workers mid-query: the query finishes correctly with
    ZERO task retries and ZERO quarantine transitions — drain is invisible
    to the data plane, unlike a crash."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    runner = _start_cluster(conn, tmp_path, heartbeat=0.1)
    coord = runner.coordinator
    try:
        conn.gated_table = "probe"
        qid = coord.submit_query(JOIN_SQL)
        assert _wait(lambda: conn.entered > 0, 60), "probe stage never started"

        victim = runner.workers[1]
        runner.drain_worker(1)
        # the breaker must flip the worker to DRAINING (not QUARANTINED)
        # before we let the query proceed — no dispatch race
        det = coord.failure_detector
        assert _wait(lambda: det.state(victim.url) == "DRAINING", 10), (
            f"breaker never saw DRAINING (state={det.state(victim.url)})"
        )
        conn.gate.set()

        sm = _await_query(runner, qid)
        record = coord.queries[qid]
        assert sm.state == "FINISHED", f"query {sm.state}: {sm.error}"
        assert record["result"] == [(expect,)]

        # the whole point: drain is NOT a failure
        assert record.get("task_retries", 0) == 0, "drain caused task retries"
        assert coord._m_retries.value() == 0
        assert coord._m_breaker.value("QUARANTINED") == 0, (
            "drain tripped the circuit breaker"
        )
        with urllib.request.urlopen(coord.url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        for line in text.splitlines():
            if 'to="QUARANTINED"' in line:
                assert line.rstrip().endswith(" 0"), line
        assert "trino_tpu_worker_drains_total 1" in _worker_metrics(victim)

        # drain completes: running tasks done, buffers served, deregistered
        assert _wait(lambda: victim.state == "drained", 30), (
            f"drain never completed (state={victim.state})"
        )
        assert _wait(lambda: victim.url not in coord.workers, 10), (
            "drained worker never deregistered"
        )
    finally:
        conn.gate.set()
        runner.stop()


def _worker_metrics(worker) -> str:
    with urllib.request.urlopen(worker.url + "/metrics", timeout=10) as r:
        return r.read().decode()


@pytest.mark.chaos
def test_draining_worker_rejects_new_tasks():
    """New task POSTs against a DRAINING worker get 503 + Retry-After."""
    import json

    conn = MemoryConnector()
    runner = _start_cluster(conn, num_workers=1)
    try:
        w = runner.workers[0]
        runner.drain_worker(0)
        assert _wait(lambda: w.state in ("draining", "drained"), 10)
        req = urllib.request.Request(
            f"{w.url}/v1/task/t_reject",
            data=json.dumps(
                {"task_id": "t_reject", "fragment": {}, "sources": []}
            ).encode(),
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        ei.value.read()
    finally:
        runner.stop()


@pytest.mark.chaos
@pytest.mark.slow  # ~30s: the survivor rides out the exchange Backoff
# deadline against the dead worker before escalating; run via
# `scripts/chaos_tier.sh kill9` (the fast drain twin stays in tier-1)
def test_kill9_recovers_from_spool(tmp_path):
    """The contrast case: a hard kill (SIGKILL analogue) of the same worker
    is NOT invisible — recovery comes only from TASK retry re-reading the
    committed spool output."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    runner = _start_cluster(conn, tmp_path, heartbeat=0.3)
    coord = runner.coordinator
    try:
        conn.gated_table = "probe"
        qid = coord.submit_query(JOIN_SQL)
        assert _wait(lambda: conn.entered > 0, 60), "probe stage never started"
        time.sleep(0.3)  # pre-probe stages commit to the spool
        runner.kill_worker(1)
        conn.gate.set()

        sm = _await_query(runner, qid)
        record = coord.queries[qid]
        assert sm.state == "FINISHED", f"query {sm.state}: {sm.error}"
        assert record["result"] == [(expect,)]
        # unlike drain, the crash shows up as retry/heal work
        recovered = record.get("task_retries", 0) + record.get("task_heals", 0)
        assert recovered >= 1, "kill -9 was absorbed without any retry/heal?"
    finally:
        conn.gate.set()
        runner.stop()


# --------------------------------------------------------------- watchdogs


def test_no_progress_watchdog_kills_wedged_task(tmp_path):
    """A SLOW-wedged task whose stats freeze while RUNNING is failed by the
    worker watchdog well under the fault duration; TASK retry completes the
    query elsewhere."""
    conn = MemoryConnector()
    expect = _make_tables(conn)
    runner = _start_cluster(conn, tmp_path, heartbeat=0.2)
    coord = runner.coordinator
    try:
        # warm-up: JIT compile so the timed run below measures the
        # watchdog, not compilation
        assert runner.query(JOIN_SQL) == [(expect,)]

        coord.session.set("task_no_progress_timeout_s", "1.0")
        runner.inject_task_failure(worker_index=0, mode="SLOW",
                                   delay_ms=8000, count=1)
        t0 = time.monotonic()
        assert runner.query(JOIN_SQL) == [(expect,)]
        elapsed = time.monotonic() - t0
        assert elapsed < 10, f"wedged task survived {elapsed:.1f}s"
        kills = sum(w._m_no_progress.value() for w in runner.workers)
        assert kills >= 1, "watchdog never fired"
    finally:
        runner.stop()


def test_query_max_run_time_typed_reason():
    """query_max_run_time_s=1 kills a wedged query with a typed
    EXCEEDED_TIME_LIMIT reason the client can branch on."""
    conn = GatedMemoryConnector()
    _make_tables(conn)
    runner = _start_cluster(conn, heartbeat=0.1)
    try:
        runner.coordinator.session.set("query_max_run_time_s", "1")
        conn.gated_table = "probe"
        client = StatementClient(runner.coordinator.url)
        with pytest.raises(QueryFailed) as ei:
            client.execute(JOIN_SQL, timeout=60)
        assert "EXCEEDED_TIME_LIMIT" in str(ei.value)
        assert ei.value.error_code == "EXCEEDED_TIME_LIMIT"
        assert runner.coordinator._m_deadline.value("run_time") >= 1
    finally:
        conn.gate.set()
        runner.stop()


def test_query_max_queued_time_kill(tmp_path):
    """A query stuck QUEUED in its resource group past
    query_max_queued_time_s is shed with EXCEEDED_QUEUED_TIME_LIMIT while
    the running query ahead of it is untouched."""
    from trino_tpu.runtime.resourcegroups import (
        ResourceGroupConfig, ResourceGroupManager,
    )

    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    runner = _start_cluster(conn, heartbeat=0.1)
    coord = runner.coordinator
    try:
        # one concurrency slot: the second query must queue behind the first
        coord.resource_groups = ResourceGroupManager(
            ResourceGroupConfig(name="global", max_concurrency=1)
        )
        coord.session.set("query_max_queued_time_s", "0.5")
        conn.gated_table = "probe"
        q1 = coord.submit_query(JOIN_SQL)
        assert _wait(lambda: conn.entered > 0, 60), "q1 never started"
        q2 = coord.submit_query(JOIN_SQL)

        sm2 = _await_query(runner, q2, timeout=15)
        assert sm2.state == "FAILED"
        assert sm2.error_code == "EXCEEDED_QUEUED_TIME_LIMIT"
        assert coord._m_deadline.value("queued_time") >= 1

        conn.gate.set()
        sm1 = _await_query(runner, q1)
        assert sm1.state == "FINISHED", f"q1 {sm1.state}: {sm1.error}"
        assert coord.queries[q1]["result"] == [(expect,)]
    finally:
        conn.gate.set()
        runner.stop()


# --------------------------------------------------------------- speculation


def test_speculation_backup_wins(tmp_path):
    """Under retry_policy=TASK with speculation enabled, a SLOW straggler
    gets a backup attempt on the other worker; exactly one attempt commits
    and the query returns well before the fault duration."""
    conn = MemoryConnector()
    expect = _make_tables(conn)
    runner = _start_cluster(conn, tmp_path, heartbeat=0.2)
    coord = runner.coordinator
    try:
        # warm-up (JIT) before timing anything
        assert runner.query(JOIN_SQL) == [(expect,)]

        # whole-task speculation is the machinery under test; split-driven
        # stages (the default since the storage-governance release) handle
        # stragglers by split STEALING instead and never speculate, so this
        # test pins the classic whole-scan path
        coord.session.set("split_driven_scans", "false")
        coord.session.set("speculation_enabled", "true")
        coord.session.set("speculation_quantile", "1.5")
        runner.inject_task_failure(worker_index=0, mode="SLOW",
                                   delay_ms=6000, count=1)
        t0 = time.monotonic()
        assert runner.query(JOIN_SQL) == [(expect,)]
        elapsed = time.monotonic() - t0
        assert elapsed < 5.5, (
            f"{elapsed:.1f}s — the straggler was waited out, not speculated"
        )
        spec = coord._m_speculative
        assert spec.value("launched") >= 1, "no backup attempt launched"
        assert spec.value("won") + spec.value("lost") >= 1
        # exactly-once: the losing attempt must not have left a second
        # commit or a staging dir behind
        leftovers = [n for n in os.listdir(tmp_path) if ".tmp-" in n]
        assert not leftovers, f"staging dirs leaked: {leftovers}"
    finally:
        runner.stop()


# --------------------------------------------------------------- shedding


def test_load_shedding_429():
    """Past dispatch_queue_limit active queries, POST /v1/statement is
    answered 429 + Retry-After before resource-group admission; a client
    honoring the backpressure succeeds once load clears."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)
    runner = _start_cluster(conn, heartbeat=0.2)
    coord = runner.coordinator
    try:
        coord.session.set("dispatch_queue_limit", "1")
        conn.gated_table = "probe"
        client = StatementClient(coord.url)
        client.submit(JOIN_SQL)  # fills the only dispatch slot
        assert _wait(lambda: conn.entered > 0, 60), "q1 never started"

        req = urllib.request.Request(
            f"{coord.url}/v1/statement", data=JOIN_SQL.encode()
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After")
        ei.value.read()
        assert coord._m_shed.value() >= 1

        # a backpressure-aware client rides out the shed window
        threading.Timer(0.5, conn.gate.set).start()
        patient = StatementClient(coord.url, shed_retries=30)
        _, rows = patient.execute(JOIN_SQL, timeout=120)
        assert [tuple(r) for r in rows] == [(expect,)]
    finally:
        conn.gate.set()
        runner.stop()


# --------------------------------------------------------------- spool


def test_spool_first_commit_wins(tmp_path):
    """Two attempts of the same task commit concurrently-ish: the first
    rename wins, the second returns False and leaves no staging dir."""
    from trino_tpu.runtime.spool import SpooledExchange

    spool = SpooledExchange(str(tmp_path))
    assert spool.commit_task("q1_t0", {0: [b"winner"]}, attempt="0") is True
    assert spool.commit_task("q1_t0", {0: [b"loser"]}, attempt="s1") is False
    assert spool.read_chunks("q1_t0", 0) == [b"winner"]
    assert not [n for n in os.listdir(tmp_path) if ".tmp-" in n]


def test_spool_remove_query_prefix_safe(tmp_path):
    """remove_query("q1") must not eat q10's output (prefix collision)."""
    from trino_tpu.runtime.spool import SpooledExchange

    spool = SpooledExchange(str(tmp_path))
    spool.commit_task("q1_t0", {0: [b"one"]})
    spool.commit_task("q10_t0", {0: [b"ten"]})
    spool.remove_query("q1")
    assert not spool.is_committed("q1_t0")
    assert spool.is_committed("q10_t0")
    assert spool.read_chunks("q10_t0", 0) == [b"ten"]
