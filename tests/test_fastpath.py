"""Serving fast path: parameterized plan cache, zero-retrace EXECUTE,
small-query batching, and the prepared-statement protocol surface.

The contract under test (runtime/fastpath.py): a PREPAREd statement plans
once, compiles once, and every subsequent EXECUTE with different parameter
values reuses the same XLA program — values travel as jit *arguments*, not
trace-time constants.  The profiler ledger (utils/profiler.py) is the
witness: one signature, compiles == 1, executes == number of bindings.
"""

import threading

import pytest

from trino_tpu.utils.profiler import PROFILER

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def engine():
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="tpch")
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


def _new_sigs(before, after):
    """Signatures whose execute count grew between two profiler snapshots."""
    out = {}
    for sig, e in after.items():
        prev = before.get(sig, {"executes": 0, "compiles": 0})
        de = e["executes"] - prev["executes"]
        if de > 0:
            out[sig] = {
                "executes": de,
                "compiles": e["compiles"] - prev["compiles"],
            }
    return out


# --------------------------------------------------------------- zero retrace
def test_zero_retrace_across_bindings(engine):
    """>= 3 distinct bindings share ONE compiled signature (compiles == 1)."""
    engine.execute(
        "PREPARE zr FROM select l_returnflag, count(*) c, sum(l_quantity) q "
        "from lineitem where l_quantity < ? group by l_returnflag "
        "order by l_returnflag"
    )
    before = PROFILER.snapshot()
    for v in (11.0, 24.0, 37.0, 49.0):
        engine.execute(f"EXECUTE zr USING {v}")
    grown = _new_sigs(before, PROFILER.snapshot())
    assert len(grown) == 1, f"expected one fastpath signature, got {grown}"
    (_, stats), = grown.items()
    assert stats["compiles"] == 1, f"retraced across bindings: {stats}"
    assert stats["executes"] == 4


def test_bindings_match_full_replan_oracle(engine):
    engine.execute(
        "PREPARE orc FROM select l_returnflag, count(*) c from lineitem "
        "where l_quantity < ? group by l_returnflag order by l_returnflag"
    )
    for v in (5.0, 24.0, 49.0):
        got = engine.execute(f"EXECUTE orc USING {v}")
        want = engine.query(
            "select l_returnflag, count(*) c from lineitem "
            f"where l_quantity < {v} group by l_returnflag order by l_returnflag"
        )
        assert got == want, (v, got, want)


def test_bigint_binding(engine):
    engine.execute("PREPARE bk FROM select n_name from nation where n_regionkey = ? order by n_name")
    for k in (0, 1, 2):
        got = engine.execute(f"EXECUTE bk USING {k}")
        want = engine.query(
            f"select n_name from nation where n_regionkey = {k} order by n_name"
        )
        assert got == want


# ----------------------------------------------------------------- plan cache
def test_plan_cache_hit_events(engine):
    from trino_tpu.runtime.fastpath import PLAN_CACHE_EVENTS

    engine.execute("PREPARE pc FROM select count(*) from orders where o_custkey = ?")
    h0, m0 = PLAN_CACHE_EVENTS.value("hit"), PLAN_CACHE_EVENTS.value("miss")
    engine.execute("EXECUTE pc USING 7")
    engine.execute("EXECUTE pc USING 13")
    engine.execute("EXECUTE pc USING 29")
    assert PLAN_CACHE_EVENTS.value("miss") - m0 == 1
    assert PLAN_CACHE_EVENTS.value("hit") - h0 == 2


def test_injection_quote_bearing_string_param(engine):
    """A quote-bearing varchar parameter stays DATA (the old textual
    substitution would have spliced it into the predicate)."""
    engine.execute("PREPARE inj FROM select count(*) from nation where n_name = ?")
    got = engine.execute("EXECUTE inj USING 'x'' or ''1''=''1'")
    assert got == [(0,)]
    got = engine.execute("EXECUTE inj USING 'FRANCE'")
    assert got == [(1,)]


def test_plan_cache_invalidation_on_dml():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine
    from trino_tpu.runtime.fastpath import PLAN_CACHE_EVENTS

    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", MemoryConnector())
    eng.execute("CREATE TABLE t (a bigint, b bigint)")
    eng.execute("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    eng.execute("PREPARE p FROM select b from t where a = ?")
    assert eng.execute("EXECUTE p USING 2") == [(20,)]
    inv0 = PLAN_CACHE_EVENTS.value("invalidated")
    eng.execute("INSERT INTO t VALUES (4, 40)")
    # the stale plan (pinned to the pre-INSERT table version) must NOT serve
    assert eng.execute("EXECUTE p USING 4") == [(40,)]
    assert PLAN_CACHE_EVENTS.value("invalidated") > inv0


def test_kill_switch_falls_back_to_legacy(engine):
    from trino_tpu.runtime.fastpath import PLAN_CACHE_EVENTS

    engine.execute("PREPARE ks FROM select count(*) from nation where n_regionkey = ?")
    engine.execute("SET SESSION prepared_fastpath_enabled = false")
    try:
        before = PLAN_CACHE_EVENTS.value("hit") + PLAN_CACHE_EVENTS.value("miss")
        got = engine.execute("EXECUTE ks USING 0")
        assert got == [(5,)]
        after = PLAN_CACHE_EVENTS.value("hit") + PLAN_CACHE_EVENTS.value("miss")
        assert after == before, "kill switch did not bypass the plan cache"
    finally:
        engine.execute("SET SESSION prepared_fastpath_enabled = true")


def test_execute_arity_mismatch(engine):
    engine.execute("PREPARE ar FROM select count(*) from nation where n_regionkey = ?")
    with pytest.raises(Exception, match="parameter"):
        engine.execute("EXECUTE ar USING 1, 2")


# ------------------------------------------------------------------- batching
def test_batched_dispatch_matches_sequential_oracle(engine):
    from trino_tpu.runtime.fastpath import EXECUTE_BATCH

    engine.execute(
        "PREPARE bat FROM select l_returnflag, count(*) c from lineitem "
        "where l_quantity < ? group by l_returnflag order by l_returnflag"
    )
    engine.execute("EXECUTE bat USING 24.0")  # warm: learn caps + compile
    b0 = EXECUTE_BATCH.value("batched")
    engine.execute("SET SESSION execute_batch_window_ms = 25")
    try:
        vals = [5.0, 10.0, 15.0, 20.0, 25.0, 30.0]
        results, errors = {}, []

        def run(v):
            try:
                results[v] = engine.execute(f"EXECUTE bat USING {v}")
            except Exception as e:  # surfaced below; threads must not die silently
                errors.append(e)

        ts = [threading.Thread(target=run, args=(v,)) for v in vals]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    finally:
        engine.execute("SET SESSION execute_batch_window_ms = 0")
    assert not errors, errors
    for v in vals:
        want = engine.query(
            "select l_returnflag, count(*) c from lineitem "
            f"where l_quantity < {v} group by l_returnflag order by l_returnflag"
        )
        assert results[v] == want, (v, results[v], want)
    assert EXECUTE_BATCH.value("batched") > b0, "window never formed a batch"


def test_unbatchable_plan_falls_back_pipelined(engine):
    """A plan marked un-vmappable still answers every query in the window
    (per-query pipelined dispatch), counted under outcome=fallback."""
    from trino_tpu.runtime.fastpath import EXECUTE_BATCH

    engine.execute(
        "PREPARE nb FROM select count(*) c from orders where o_custkey = ?"
    )
    engine.execute("EXECUTE nb USING 7")  # warm + create the cache entry
    fp = engine.fastpath()
    with fp._lock:
        entries = [e for k, e in fp._cache.items() if k[0].startswith("select count(*) c from orders")]
    assert entries, "prepared plan missing from the cache"
    for e in entries:
        e.batchable = False  # force the can't-batch path
    f0 = EXECUTE_BATCH.value("fallback")
    engine.execute("SET SESSION execute_batch_window_ms = 25")
    try:
        keys = [1, 2, 3, 4]
        results, errors = {}, []

        def run(k):
            try:
                results[k] = engine.execute(f"EXECUTE nb USING {k}")
            except Exception as e:
                errors.append(e)

        ts = [threading.Thread(target=run, args=(k,)) for k in keys]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
    finally:
        engine.execute("SET SESSION execute_batch_window_ms = 0")
    assert not errors, errors
    for k in keys:
        want = engine.query(f"select count(*) c from orders where o_custkey = {k}")
        assert results[k] == want, (k, results[k], want)
    assert EXECUTE_BATCH.value("fallback") > f0


# ------------------------------------------------------------------- protocol
@pytest.fixture(scope="module")
def cluster():
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing.runner import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=2, default_catalog="tpch")
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    yield runner
    runner.stop()


def test_protocol_prepare_execute_deallocate(cluster):
    from trino_tpu.client import StatementClient

    c = StatementClient(cluster.coordinator.url)
    sql = (
        "select l_returnflag, count(*) c from lineitem where l_quantity < ? "
        "group by l_returnflag order by l_returnflag"
    )
    c.execute(f"PREPARE proto FROM {sql}")
    # server-side PREPARE echoes into the client registry (addedPrepare)
    assert c.prepared.get("proto") == sql

    cols, rows = c.execute("EXECUTE proto USING 24.0")
    assert cols == ["l_returnflag", "c"], cols
    assert rows

    # a FRESH client holding only the header registry (no server session):
    # the X-Trino-Prepared-Statement header alone must resolve the EXECUTE
    c2 = StatementClient(cluster.coordinator.url)
    c2.prepared["proto"] = sql
    cols2, rows2 = c2.execute("EXECUTE proto USING 24.0")
    assert (cols2, rows2) == (cols, rows)

    c.execute("DEALLOCATE PREPARE proto")
    assert "proto" not in c.prepared  # deallocatedPrepare delta applied


def test_protocol_explain_analyze_footer(cluster):
    from trino_tpu.client import StatementClient

    c = StatementClient(cluster.coordinator.url)
    c.prepared["ef"] = "select count(*) from nation where n_regionkey = ?"
    c.execute("EXECUTE ef USING 1")
    _, rows = c.execute("EXPLAIN ANALYZE EXECUTE ef USING 1")
    text = "\n".join(r[0] for r in rows)
    assert "-- fastpath:" in text, text
    assert "plan_cache=hit" in text, text


def test_dbapi_binds_instead_of_splicing(cluster):
    from trino_tpu.client.dbapi import connect

    conn = connect(cluster.coordinator.url)
    cur = conn.cursor()
    # regression: a quote-bearing parameter must not terminate the predicate
    cur.execute(
        "select count(*) from nation where n_name = ?", ("x' or '1'='1",)
    )
    assert cur.fetchone() == (0,)
    cur.execute("select count(*) from nation where n_name = ?", ("FRANCE",))
    assert cur.fetchone() == (1,)
    # the statement went through the prepared registry, not text splicing
    assert any(k.startswith("dbapi_") for k in conn._client.prepared)
    # repeats reuse the registry slot (one server plan-cache entry)
    n = len(conn._client.prepared)
    cur.execute("select count(*) from nation where n_name = ?", ("KENYA",))
    assert len(conn._client.prepared) == n
