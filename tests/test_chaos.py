"""Chaos tier: TPC-H under seeded random fault schedules.

The resilience claim of the fault-tolerant runtime is not "each fault is
handled somewhere" but "ANY schedule of recoverable faults leaves results
oracle-identical".  This tier samples that space deterministically: a
seeded ChaosRunner arms 1-2 random faults (ERROR / TIMEOUT / SLOW /
EXCHANGE_DROP, random target worker, random delay/count) before every
query, runs TPC-H on a retry_policy=TASK cluster, and diffs against the
sqlite oracle.  A failure replays exactly from the seed.

Run: scripts/chaos_tier.sh  (pytest -m chaos; excluded from tier-1).
"""

import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES

CHAOS_QUERIES = ["q01", "q03", "q06", "q13", "q18"]
ROUNDS = 2
SEED = 1234


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_tpch_matches_oracle(tpch_tiny, oracle):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing.chaos import make_chaos_cluster

    runner, chaos = make_chaos_cluster(
        lambda: TpchConnector(0.01), num_workers=3, seed=SEED
    )
    try:
        for rnd in range(ROUNDS):
            for name in CHAOS_QUERIES:
                sql = QUERIES[name]
                got = chaos.run_query(sql)
                assert_rows_equal(
                    got, oracle.query(sql), ordered=ORDERED[name]
                ), f"round {rnd} {name} diverged under {chaos.schedule[-1]}"
        # the schedule must actually have bitten, in enough distinct ways
        fired = chaos.fired_modes()
        assert len(fired) >= 3, (
            f"only {fired} fired across {chaos.schedule}; "
            f"pick a different SEED"
        )
    finally:
        runner.stop()


def test_chaos_harness_smoke():
    """Fast seeded chaos pass over a memory table — keeps the harness
    itself covered by tier-1 without the TPC-H cost."""
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.testing.chaos import ChaosRunner, make_chaos_cluster

    def catalog():
        conn = MemoryConnector()
        conn.create_table(
            "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
        )
        rng = np.random.default_rng(2)
        conn.insert("t", {
            "k": rng.integers(0, 20, 5000).astype(np.int64),
            "v": rng.integers(0, 100, 5000).astype(np.int64),
        })
        return conn

    runner, chaos = make_chaos_cluster(
        catalog, num_workers=2, default_catalog="mem", seed=99
    )
    try:
        sql = "select k, sum(v) from t group by k order by k"
        clean = runner.query(sql)
        for _ in range(3):
            assert chaos.run_query(sql) == clean
        assert chaos.schedule and chaos.armed_modes()
    finally:
        runner.stop()
