"""Operator-level observability: EXPLAIN ANALYZE per-operator stats and
query event listeners (reference: OperatorStats/ExplainAnalyzeOperator,
EventListener SPI)."""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.engine import Engine


@pytest.fixture(scope="module")
def engine():
    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


def test_explain_analyze_per_operator(engine):
    rows = engine.execute(
        "explain analyze select l_returnflag, count(*) from lineitem "
        "where l_quantity < 10 group by l_returnflag"
    )
    text = "\n".join(r[0] for r in rows)
    assert "TableScan" in text and "Aggregate" in text
    # every operator line carries a row count annotation
    assert text.count("[rows:") >= 3
    assert "slowest operator:" in text
    assert "ms" in text


def test_explain_analyze_rows_are_real(engine):
    rows = engine.execute("explain analyze select count(*) from lineitem")
    text = "\n".join(r[0] for r in rows)
    # the aggregate output is exactly one row
    assert "[rows: 1" in text


def test_event_listener(engine):
    events = []
    engine.add_event_listener(events.append)
    engine.query("select count(*) from orders")
    kinds = [e.kind for e in events]
    assert kinds == ["created", "completed"]
    assert events[1].rows == 1
    assert events[1].wall_s >= 0
    engine.events._listeners.clear()


def test_event_listener_failure_isolated(engine):
    """A broken listener must not break the query (reference semantics)."""

    def bad(_ev):
        raise RuntimeError("listener bug")

    engine.add_event_listener(bad)
    try:
        rows = engine.query("select count(*) from orders")
        assert rows[0][0] > 0
    finally:
        engine.events._listeners.clear()


def test_tracing_spans(engine):
    """Query execution emits a query span with planner/execute children
    (reference: OpenTelemetry spans, SqlQueryExecution.java:473)."""
    from trino_tpu.utils.tracing import InMemorySpanExporter

    exp = InMemorySpanExporter()
    engine.tracer.add_exporter(exp)
    try:
        engine.query("select count(*) from region")
        root = exp.traces[-1]
        assert root.name == "query"
        assert root.attributes.get("rows") == 1
        assert root.find("planner") is not None
        assert root.find("execute") is not None
        assert root.duration_ms >= root.find("planner").duration_ms
        d = root.to_dict()
        assert d["name"] == "query" and len(d["children"]) == 2
    finally:
        engine.tracer._exporters.clear()


def test_tracing_error_recorded(engine):
    from trino_tpu.utils.tracing import InMemorySpanExporter

    exp = InMemorySpanExporter()
    engine.tracer.add_exporter(exp)
    try:
        with pytest.raises(Exception):
            engine.query("select * from no_such_table")
        assert "error" in exp.traces[-1].attributes
    finally:
        engine.tracer._exporters.clear()
