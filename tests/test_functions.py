"""Scalar function library tests (reference: operator/scalar/, 247 files).

Functions sqlite shares are diffed against the oracle; the rest are checked
against python-computed expectations over the same generated rows.  String
functions evaluate once per distinct dictionary value host-side and gather
by code on device (DictionaryAwarePageProjection's trick); float math runs
in f64 lanes on the VPU."""

import math
import re
import sqlite3

import numpy as np
import pytest

from tests.oracle import assert_rows_equal

SQLITE_SHARED = {
    "string_basic": (
        "select upper(n_name), lower(n_name), trim(n_comment),"
        " replace(n_name, 'A', 'x'), length(n_name) from nation"
    ),
    "math_basic": (
        "select abs(-s_acctbal), round(s_acctbal, 0), sign(s_acctbal)"
        " from supplier"
    ),
    "conditional": (
        "select nullif(n_regionkey, 2), coalesce(nullif(n_regionkey, 0), 99)"
        " from nation"
    ),
    "concat_op": (
        "select n_name || '-' || r_name from nation, region"
        " where n_regionkey = r_regionkey"
    ),
    "hidden_order_col": "select s_name from supplier order by s_acctbal desc limit 5",
}


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize(
    "name",
    [
        # the oracle side of math_basic needs sqlite >= 3.35 (sign())
        pytest.param(
            n,
            marks=pytest.mark.skipif(
                sqlite3.sqlite_version_info < (3, 35),
                reason=f"sqlite {sqlite3.sqlite_version} lacks sign()",
            ),
        )
        if n == "math_basic"
        else n
        for n in sorted(SQLITE_SHARED)
    ],
)
def test_function_vs_oracle(name, engine, oracle):
    sql = SQLITE_SHARED[name]
    assert_rows_equal(
        engine.query(sql), oracle.query(sql), ordered="order by" in sql
    )


def test_string_functions_python(engine, tpch_tiny):
    names = [str(v) for v in tpch_tiny["nation"]["n_name"]]
    comments = [str(v) for v in tpch_tiny["nation"]["n_comment"]]
    order = np.argsort(tpch_tiny["nation"]["n_nationkey"])
    rows = engine.query(
        "select strpos(n_name, 'AN'), starts_with(n_name, 'A'),"
        " lpad(n_name, 5, '*'), rpad(n_name, 4, '.'),"
        " split_part(n_comment, ' ', 1), reverse(n_name)"
        " from nation order by n_nationkey"
    )
    for i, oi in enumerate(order):
        s, c = names[oi], comments[oi]
        lpad = ("*" * 5)[: max(0, 5 - len(s))] + s if len(s) < 5 else s[:5]
        rpad = s + ("." * 4)[: max(0, 4 - len(s))] if len(s) < 4 else s[:4]
        exp = (
            s.find("AN") + 1, s.startswith("A"), lpad, rpad,
            c.split(" ")[0], s[::-1],
        )
        assert rows[i] == exp, (rows[i], exp)


def test_regexp_functions(engine, tpch_tiny):
    names = [str(v) for v in tpch_tiny["nation"]["n_name"]]
    order = np.argsort(tpch_tiny["nation"]["n_nationkey"])
    rows = engine.query(
        "select regexp_like(n_name, '^[A-C]'), regexp_replace(n_name, '[AEIOU]', '_'),"
        " regexp_extract(n_name, '([A-Z]+)A', 1) from nation order by n_nationkey"
    )
    for i, oi in enumerate(order):
        s = names[oi]
        m = re.search("([A-Z]+)A", s)
        exp = (
            bool(re.search("^[A-C]", s)),
            re.sub("[AEIOU]", "_", s),
            m.group(1) if m else None,  # no match is NULL, not ''
        )
        assert rows[i] == exp, (rows[i], exp)


def test_float_math(engine):
    rows = engine.query(
        "select ln(s_suppkey), exp(1.0), log10(100), sqrt(s_suppkey),"
        " greatest(s_suppkey, 50), least(s_suppkey, 50),"
        " bitwise_and(s_suppkey, 6), bitwise_or(s_suppkey, 8)"
        " from supplier order by s_suppkey limit 3"
    )
    k = 1
    assert abs(rows[0][0] - math.log(k)) < 1e-9
    assert abs(rows[0][1] - math.e) < 1e-9
    assert rows[0][2] == 2.0
    assert rows[0][4:] == (50, 1, 0, 9)
    # ln of a non-positive argument is NULL, not NaN
    rows = engine.query("select ln(n_regionkey - 2) from nation where n_regionkey = 0")
    assert all(r[0] is None for r in rows)


def test_trig_domain_null(engine):
    rows = engine.query("select asin(n_regionkey) from nation where n_regionkey >= 2")
    assert all(r[0] is None for r in rows)


def test_date_functions(engine):
    rows = engine.query(
        "select date_trunc('month', d), date_trunc('year', d), date_trunc('week', d),"
        " quarter(d), day_of_week(d), day_of_year(d), last_day_of_month(d),"
        " date_diff('day', date '2024-01-01', d)"
        " from (select date '2024-02-15' as d from nation limit 1)"
    )
    assert rows[0] == (
        "2024-02-01", "2024-01-01", "2024-02-12", 1, 4, 46, "2024-02-29", 45,
    )


def test_null_producing_string_functions(engine):
    rows = engine.query(
        "select split_part(n_name, 'ZZZZ', 3), regexp_extract(n_name, 'q(x)?'),"
        " truncate(3.456, 2), 'n=' || n_name || '!' from nation limit 1"
    )
    assert rows[0][0] is None  # out-of-range split index
    assert rows[0][1] is None  # unmatched regex
    assert abs(rows[0][2] - 3.45) < 1e-9  # truncate honors the scale arg
    assert rows[0][3].startswith("n=") and rows[0][3].endswith("!")


def test_functions_in_where_and_group(engine, oracle):
    # functions compose with filters and aggregation
    sql = (
        "select upper(o_orderstatus), count(*) from orders"
        " where length(o_orderpriority) > 5 group by upper(o_orderstatus)"
    )
    assert_rows_equal(engine.query(sql), oracle.query(sql), ordered=False)


def test_json_functions(engine):
    # JSON over varchar lanes: parse once per distinct value host-side
    # (reference: operator/scalar/JsonFunctions)
    rows = engine.query(
        "select json_extract_scalar(j, '$.a'), json_extract_scalar(j, '$.b[1]'),"
        " json_extract(j, '$.b'), json_array_length(j),"
        " json_array_length(json_extract(j, '$.b')), json_size(j, '$')"
        " from (select '{\"a\": \"x\", \"b\": [10, 20, 30]}' as j from nation limit 1)"
    )
    assert rows[0] == ("x", "20", "[10,20,30]", None, 3, 2)


def test_json_malformed_is_null(engine):
    rows = engine.query(
        "select json_extract_scalar(j, '$.a') from"
        " (select 'not json' as j from nation limit 1)"
    )
    assert rows[0] == (None,)


def test_try_cast(engine):
    rows = engine.query(
        "select try_cast(s as bigint), try_cast(s as double),"
        " try_cast(s as date) from"
        " (select 'abc' as s from nation limit 1)"
    )
    assert rows[0] == (None, None, None)
    rows = engine.query(
        "select try_cast(s as bigint) from (select '42' as s from nation limit 1)"
    )
    assert rows[0] == (42,)
    rows = engine.query("select try_cast('2024-01-15' as date)")
    assert rows[0] == ("2024-01-15",)


def test_try_cast_column(engine, tpch_tiny):
    # mixed parseable/unparseable values in one dictionary
    rows = engine.query(
        "select try_cast(substring(n_name, 1, 1) as bigint) from nation limit 3"
    )
    assert all(r[0] is None for r in rows)  # letters never parse


def test_json_path_strictness(engine):
    # unsupported JSONPath syntax is an error, not a silent prefix match
    with pytest.raises(Exception, match="JSON path"):
        engine.query(
            "select json_extract(j, '$.b[*]') from"
            " (select '{}' as j from nation limit 1)"
        )


def test_table_function_sequence():
    """FROM TABLE(sequence(...)) — the polymorphic table-function surface
    (reference: spi/function/table/, LeafTableFunctionOperator); positional
    and named (=>) arguments."""
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", MemoryConnector())
    assert eng.query(
        "SELECT sum(sequential_number) AS s FROM TABLE(sequence(1, 100))"
    ) == [(5050,)]
    assert eng.query(
        "SELECT count(*) FROM TABLE(sequence(start => 0, stop => 20, step => 5))"
    ) == [(5,)]
    # joins like any relation
    assert eng.query(
        "SELECT count(*) FROM TABLE(sequence(1, 10)) a"
        " JOIN TABLE(sequence(1, 20)) b ON a.sequential_number = b.sequential_number"
    ) == [(10,)]
