"""Cluster telemetry observatory: the per-node time-series plane
(utils/timeseries.py ring TSDB + sampler), the roofline registry
(utils/roofline.py calibration cache), the BANDWIDTH_REGRESSION anomaly
sentinel, and the federated /v1/timeseries endpoints on both roles.

Reference behaviors being matched:
- the engine's worker stats heartbeats + Web UI cluster charts: every
  node continuously samples its own resource counters into a bounded
  ring and the coordinator folds all lanes into one cluster picture;
- roofline attribution: achieved GB/s per executed signature against a
  device bandwidth ceiling (TPU HBM table / calibrated STREAM triad);
- the post-mortem bundle carries the query-window utilization slice so
  "what was the node doing at the time" survives the ring's horizon.
"""

import json
import time
import types
import urllib.request

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.runtime.history import QueryHistoryStore
from trino_tpu.testing import DistributedQueryRunner
from trino_tpu.utils import roofline as R
from trino_tpu.utils import timeseries as TS

pytestmark = pytest.mark.smoke


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            return False
        time.sleep(interval)
    return True


# ------------------------------------------------------- ring TSDB (unit)


def test_ring_bounds_drop_oldest():
    st = TS.TimeSeriesStore(ring_size=16)
    for i in range(20):
        st.record("n1", "s", float(i), ts=1000.0 + i)
    lane = st.snapshot()["n1"]["s"]
    assert len(lane) == 16
    # oldest fell off the back: the lane starts at point 4, ends at 19
    assert lane[0] == [1004.0, 4.0]
    assert lane[-1] == [1019.0, 19.0]
    stats = st.stats()
    assert stats["points"] == 20
    assert stats["dropped"] == 4
    assert stats["lanes"] == 1


def test_snapshot_filters_since_series_nodes_limit():
    st = TS.TimeSeriesStore(ring_size=64)
    for i in range(10):
        st.record("a", "cpu_s", float(i), ts=100.0 + i)
        st.record("a", "rss_bytes", float(i * 2), ts=100.0 + i)
        st.record("b", "cpu_s", float(i * 3), ts=100.0 + i)

    # since= is strictly newer-than
    snap = st.snapshot(since=105.0)
    assert [p[0] for p in snap["a"]["cpu_s"]] == [106.0, 107.0, 108.0, 109.0]

    # series filter drops other lanes entirely
    snap = st.snapshot(series=["rss_bytes"])
    assert set(snap) == {"a"}
    assert set(snap["a"]) == {"rss_bytes"}

    # node filter
    snap = st.snapshot(nodes=["b"])
    assert set(snap) == {"b"}

    # limit keeps the NEWEST points
    snap = st.snapshot(limit=3)
    assert [p[1] for p in snap["b"]["cpu_s"]] == [21.0, 24.0, 27.0]


def test_disabled_store_is_noop_and_configure_resize_drops():
    st = TS.TimeSeriesStore(ring_size=32, enabled=False)
    st.record("n", "s", 1.0)
    assert st.snapshot() == {}
    assert st.stats()["points"] == 0

    st.configure(enabled=True)
    st.record("n", "s", 1.0)
    assert len(st.snapshot()["n"]["s"]) == 1

    # resizing drops history (documented configure() contract)
    st.configure(ring_size=64)
    assert st.snapshot() == {}
    assert st.stats()["ring_size"] == 64
    # same-size configure keeps history
    st.record("n", "s", 2.0)
    st.configure(ring_size=64)
    assert len(st.snapshot()["n"]["s"]) == 1


# --------------------------------------------------------- sampler (unit)


def test_sampler_sources_deltas_and_error_isolation():
    st = TS.TimeSeriesStore(ring_size=32)
    counter = {"v": 100.0}

    def _cum():
        counter["v"] += 7.0
        return counter["v"]

    def _boom():
        raise RuntimeError("subsystem died")

    s = TS.Sampler(
        "node-x",
        {
            "gauge": lambda: 42.0,
            "cum": _cum,
            "skipped": lambda: None,
            "broken": _boom,
        },
        deltas={"cum"},
        store=st,
    )
    s.sample_once(ts=1.0)
    s.sample_once(ts=2.0)
    lanes = st.snapshot()["node-x"]
    assert [p[1] for p in lanes["gauge"]] == [42.0, 42.0]
    # first tick only establishes the delta baseline; second records +7
    assert [p[1] for p in lanes["cum"]] == [7.0]
    assert "skipped" not in lanes
    assert "broken" not in lanes
    assert s.ticks == 2


def test_sampler_cadence_and_clean_shutdown():
    st = TS.TimeSeriesStore(ring_size=256, sample_interval_s=0.05)
    s = TS.Sampler("node-y", {"g": lambda: 1.0}, store=st, interval_s=0.02)
    s.start()
    assert _wait(lambda: s.ticks >= 5, timeout=5.0)
    s.stop()
    assert s._thread is None  # joined, not abandoned
    ticks = s.ticks
    time.sleep(0.1)
    assert s.ticks == ticks  # no zombie sampling after stop
    assert len(st.snapshot()["node-y"]["g"]) == ticks

    # a disabled store refuses to start the thread at all
    st.configure(enabled=False)
    s2 = TS.Sampler("node-z", {"g": lambda: 1.0}, store=st)
    s2.start()
    assert s2._thread is None


# -------------------------------------------------- roofline cache (unit)


def test_cpu_roofline_cache_roundtrip(tmp_path):
    path = str(tmp_path / "roofline.json")

    # a cached figure is returned verbatim — no re-probe
    with open(path, "w") as f:
        json.dump({"cpu_gbps": 123.0, "ts": 0}, f)
    assert R.calibrate_cpu_gbps(cache_path=path) == 123.0

    # force=True re-probes and rewrites the cache
    fresh = R.calibrate_cpu_gbps(cache_path=path, force=True)
    assert fresh > 0
    with open(path) as f:
        saved = json.load(f)
    assert saved["cpu_gbps"] == round(fresh, 3)
    assert saved["cpu_gbps"] != 123.0

    # a corrupt cache falls back to probing instead of dying
    with open(path, "w") as f:
        f.write("{not json")
    assert R.calibrate_cpu_gbps(cache_path=path) > 0


def test_device_roofline_memo_and_pct(tmp_path):
    path = str(tmp_path / "roofline.json")
    R.reset_cache()
    try:
        info = R.device_roofline(cache_path=path)
        assert info["platform"]
        assert info["hbm_gbps"] > 0
        assert info["source"] in ("table", "calibrated", "default")
        # memoized: second call answers identically without the path
        assert R.device_roofline() == info
        # achieving exactly the ceiling is 100% of roofline
        assert R.pct_of_roofline(info["hbm_gbps"]) == pytest.approx(100.0)
        assert R.pct_of_roofline(0.0) == 0.0
    finally:
        R.reset_cache()  # don't leak the tmp-path memo into other tests


# -------------------------------------- bandwidth baseline/sentinel (unit)


def test_history_baseline_gb_per_sec_p50():
    store = QueryHistoryStore(capacity=50)
    for i, gbps in enumerate([4.0, 5.0, 6.0]):
        store.record({
            "query_id": f"bw-{i}", "state": "FINISHED", "planhash": "ph-bw",
            "wall_ms": 100.0, "device_gb_per_sec": gbps,
        })
    # an eager-only run (no roofline figure) must not zero the baseline
    store.record({
        "query_id": "bw-eager", "state": "FINISHED", "planhash": "ph-bw",
        "wall_ms": 100.0,
    })
    base = store.baseline("ph-bw", min_samples=3)
    assert base is not None
    assert base["samples"] == 4
    assert base["gb_per_sec_p50"] == 5.0


# --------------------------------------------------------- cluster fixture


AGG_SQL = "select sum(v) from probe"


@pytest.fixture(scope="module")
def cluster():
    conn = MemoryConnector()
    conn.create_table(
        "probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("probe", {
        "k": np.arange(2000, dtype=np.int64) % 50,
        "v": np.arange(2000, dtype=np.int64),
    })
    # fast ticks so cluster asserts see points within a test's patience;
    # restored after the module so other files keep the 1 s default
    prev = TS.STORE.sample_interval_s
    TS.configure(sample_interval_s=0.1)
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.2,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        yield runner
    finally:
        runner.stop()
        TS.configure(sample_interval_s=prev)


def _run(runner, sql=AGG_SQL):
    coord = runner.coordinator
    qid = coord.submit_query(sql)
    sm = coord.queries[qid]["sm"]
    assert _wait(lambda: sm.done, 60.0), f"query stuck in {sm.state}"
    assert sm.state == "FINISHED", sm.error
    return qid


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


# -------------------------------------------- federated endpoints (cluster)


def test_timeseries_endpoints_both_roles_federated(cluster):
    coord = cluster.coordinator
    _run(cluster)
    want = {coord.url} | {w.url for w in cluster.workers}
    assert _wait(
        lambda: want <= set(_get_json(f"{coord.url}/v1/timeseries")["nodes"]),
        timeout=15.0, interval=0.2,
    ), "coordinator view never federated all node lanes"

    payload = _get_json(f"{coord.url}/v1/timeseries")
    assert payload["node"] == coord.url
    assert payload["stats"]["points"] > 0
    for node in want:
        lanes = payload["nodes"][node]
        assert "cpu_s" in lanes and "rss_bytes" in lanes
        assert all(v >= 0 for _, v in lanes["cpu_s"])
    # the process did real work; its cpu lane cannot be all-zero
    assert sum(v for _, v in payload["nodes"][coord.url]["cpu_s"]) > 0

    # a worker serves ONLY its own lane
    w0 = cluster.workers[0]
    wp = _get_json(f"{w0.url}/v1/timeseries")
    assert wp["node"] == w0.url
    assert "cpu_s" in wp["series"]

    # series filter over the wire
    only = _get_json(f"{coord.url}/v1/timeseries?series=rss_bytes")
    for lanes in only["nodes"].values():
        assert set(lanes) <= {"rss_bytes"}


def test_timeseries_since_filter_over_wire(cluster):
    coord = cluster.coordinator
    cut = time.time()
    time.sleep(0.4)  # a few 0.1 s ticks past the cut
    payload = _get_json(f"{coord.url}/v1/timeseries?since={cut}")
    lanes = payload["nodes"].get(coord.url) or {}
    assert lanes, "no fresh points after the cut"
    for pts in lanes.values():
        assert all(ts > cut for ts, _ in pts)


# ------------------------------------------------- rss regression (cluster)


def test_rss_current_below_peak_and_heartbeat_carries_both(cluster):
    # unit: the sampled figure is CURRENT residency, the peak is the
    # lifetime high-water mark — sampled <= peak must hold (the /v1/info
    # handler clamps the few-page statm-vs-ru_maxrss lag)
    assert TS.current_rss_bytes() > 0
    assert TS.peak_rss_bytes() > 0

    for w in cluster.workers:
        info = _get_json(f"{w.url}/v1/info")
        assert info["rss_bytes"] > 0
        assert info["peak_rss_bytes"] > 0
        assert info["rss_bytes"] <= info["peak_rss_bytes"]

    # the heartbeat carries both onto the coordinator's membership view
    coord = cluster.coordinator
    assert _wait(
        lambda: all(
            getattr(wi, "rss_bytes", None) and getattr(
                wi, "peak_rss_bytes", None)
            for wi in coord.workers.values()
        ),
        timeout=10.0, interval=0.1,
    ), "heartbeats never delivered rss figures"
    for wi in coord.workers.values():
        assert wi.rss_bytes <= wi.peak_rss_bytes


# ------------------------------------------ bandwidth sentinel (cluster)


def _bw_record(coord, qid, planhash, gbps):
    """A synthetic finished-run record shaped like the live one — only
    the fields _score_anomalies reads."""
    return {
        "sm": types.SimpleNamespace(query_id=qid),
        "sql": "select bw_probe",
        "cache": {"planhash": planhash},
        "query_info": {
            "query_id": qid, "wall_ms": 100.0, "spill_ms": 0.0,
            "task_retries": 0, "compile_signatures": {},
            "device_gb_per_sec": gbps,
        },
    }


def _seed_bw_baseline(coord, planhash, gbps=10.0, n=4):
    for i in range(n):
        coord.history.record({
            "query_id": f"{planhash}-seed-{i}", "state": "FINISHED",
            "planhash": planhash, "wall_ms": 100.0,
            "device_gb_per_sec": gbps,
        })


def test_bandwidth_regression_fires_on_slow_run(cluster):
    coord = cluster.coordinator
    _seed_bw_baseline(coord, "ph-bw-pos", gbps=10.0)
    rec = _bw_record(coord, "q-bw-pos", "ph-bw-pos", gbps=1.0)
    coord._score_anomalies(rec)
    kinds = [a["kind"] for a in rec["query_info"]["anomalies"]]
    assert kinds == ["BANDWIDTH_REGRESSION"]
    a = rec["query_info"]["anomalies"][0]
    assert a["baseline_p50"] == 10.0
    assert a["factor"] == 10.0


def test_bandwidth_regression_stays_quiet(cluster):
    coord = cluster.coordinator
    _seed_bw_baseline(coord, "ph-bw-neg", gbps=10.0)

    # within 2x of baseline: clean
    rec = _bw_record(coord, "q-bw-neg", "ph-bw-neg", gbps=8.0)
    coord._score_anomalies(rec)
    assert rec["query_info"]["anomalies"] == []

    # no roofline figure at all (eager-only plan): silent, not divide-by-0
    rec = _bw_record(coord, "q-bw-none", "ph-bw-neg", gbps=None)
    coord._score_anomalies(rec)
    assert rec["query_info"]["anomalies"] == []

    # noise-band floor: a baseline under the floor never flags
    coord.session.set("anomaly_bandwidth_min_gb_per_sec", "50")
    try:
        rec = _bw_record(coord, "q-bw-floor", "ph-bw-neg", gbps=1.0)
        coord._score_anomalies(rec)
        assert rec["query_info"]["anomalies"] == []
    finally:
        coord.session.set("anomaly_bandwidth_min_gb_per_sec", "0.05")


# ------------------------------------- roofline figures on QueryInfo (live)


def test_query_info_carries_roofline_and_exchange(cluster):
    coord = cluster.coordinator
    qid = _run(cluster)
    qi = coord.queries[qid]["query_info"]
    # exchange accounting exists for any multi-stage plan
    assert isinstance(qi.get("exchange"), list)
    # the compiled path yields roofline figures; the eager fallback
    # (no cost_analysis) legitimately leaves them None — accept both,
    # but whatever is present must be self-consistent
    if qi.get("device_gb_per_sec") is not None:
        assert qi["device_gb_per_sec"] > 0
        roof = qi["roofline"]
        assert roof["device"]["hbm_gbps"] > 0
        for sig in roof["signatures"]:
            assert sig["executes"] >= 1
            assert sig["gb_per_sec"] >= 0
            assert 0 <= sig["pct_of_roofline"]


# ------------------------------------------- post-mortem slice (cluster)


def test_postmortem_bundle_carries_timeseries_slice(cluster):
    coord = cluster.coordinator
    qid = _run(cluster)
    time.sleep(0.25)  # let a couple of ticks land inside the window
    assert coord.write_postmortem(qid, trigger="observatory-test")
    path = coord.postmortem_path(qid)

    slice_rec = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") == "timeseries":
                slice_rec = rec
                break
    assert slice_rec is not None, "bundle has no timeseries slice"
    t0, t1 = slice_rec["window"]
    assert t0 is not None and t1 is not None and t1 >= t0
    assert slice_rec["nodes"], "slice carries no node lanes"
    assert coord.url in slice_rec["nodes"]

    # the report renderer understands the bundle end-to-end
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "observatory_report",
        pathlib.Path(__file__).resolve().parent.parent
        / "scripts" / "observatory_report.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    nodes, queries = mod.from_bundle(path)
    assert nodes == slice_rec["nodes"]
    assert any(q.get("query_id") == qid for q in queries)
    text = "\n".join(mod.render_timeline(nodes, None, 40))
    assert coord.url in text


# --------------------------------------------- observe drill (chaos tier)


@pytest.mark.slow
def test_observe_drill_gray_slow_memory_pressure():
    """`chaos_tier.sh observe`: GRAY_SLOW stretches the exchange window
    while tasks hold their memory reservations, MEMORY_PRESSURE shrinks
    one pool mid-run — the observatory must show memory-pool reserved
    RISING then FALLING, and the post-mortem slice must cover it."""
    conn = MemoryConnector()
    conn.create_table(
        "probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("probe", {
        "k": np.arange(2000, dtype=np.int64) % 50,
        "v": np.arange(2000, dtype=np.int64),
    })
    prev = TS.STORE.sample_interval_s
    TS.configure(sample_interval_s=0.05)
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.2,
        node_memory_bytes=200_000,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    coord = runner.coordinator
    try:
        coord.session.set("task_memory_reserve_bytes", "50000")
        coord.session.set("memory_blocked_timeout_s", "30")
        t_start = time.time()
        # one forced baseline tick per worker BEFORE the shrink so the
        # capacity lane shows the drop (MEMORY_PRESSURE is consumed at
        # arm time — it resizes the pool the moment it is injected)
        for w in runner.workers:
            w.sampler.sample_once()
        # latency-only gray failure on worker 0's exchange pages: every
        # consumer fetch waits while ITS reservation is held — the
        # deterministic "reserved stays up for several ticks" lever
        runner.gray_slow(0, delay_ms=300)
        # and shrink worker 0's pool mid-drill (capacity lane must move)
        runner.memory_pressure(0, capacity_bytes=120_000)
        qid = _run(runner)

        # one forced tick per worker AFTER completion pins the fall
        for w in runner.workers:
            w.sampler.sample_once()

        snap = TS.snapshot(
            nodes=[w.url for w in runner.workers],
            series=["mem_reserved_bytes", "mem_capacity_bytes"],
            since=t_start,
        )
        rises = falls = shrunk = False
        for lanes in snap.values():
            pts = [v for _, v in lanes.get("mem_reserved_bytes") or []]
            if pts and max(pts) > 0:
                rises = True
                if pts[-1] < max(pts):
                    falls = True
            caps = [v for _, v in lanes.get("mem_capacity_bytes") or []]
            if caps and min(caps) <= 120_000 < max(caps):
                shrunk = True
        assert rises, "no sampler tick saw a held reservation"
        assert falls, "reserved never fell back after the query finished"
        assert shrunk, "MEMORY_PRESSURE capacity drop not visible"

        # the bundle's slice covers the drill window
        assert coord.write_postmortem(qid, trigger="observe-drill")
        with open(coord.postmortem_path(qid), encoding="utf-8") as f:
            slices = [
                json.loads(ln) for ln in f
                if '"timeseries"' in ln and json.loads(ln).get("type")
                == "timeseries"
            ]
        assert slices
        t0, t1 = slices[0]["window"]
        sm = coord.queries[qid]["sm"]
        assert t0 <= sm.created_at + 0.001
        assert t1 >= sm.finished_at - 0.001
        covered = [
            v for lanes in slices[0]["nodes"].values()
            for _, v in lanes.get("mem_reserved_bytes") or []
        ]
        assert covered and max(covered) > 0, (
            "slice does not cover the pressure window"
        )
    finally:
        runner.stop()
        TS.configure(sample_interval_s=prev)
