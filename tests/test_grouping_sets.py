"""GROUPING SETS / ROLLUP / CUBE / GROUPING() differential tests.

sqlite has no grouping-set syntax, so every expected result is the
equivalent UNION ALL of plain GROUP BY queries over the same rows —
which is also the semantic definition (SQL:1999; reference lowering:
QueryPlanner.planGroupingSets -> GroupIdNode + single AggregationNode)."""

import pytest

from tests.oracle import assert_rows_equal

CASES = {
    "rollup": (
        "select n_regionkey, n_nationkey % 3 as m, count(*) from nation"
        " group by rollup(n_regionkey, n_nationkey % 3)",
        """select n_regionkey, n_nationkey % 3 as m, count(*) from nation group by 1, 2
           union all select n_regionkey, null, count(*) from nation group by 1
           union all select null, null, count(*) from nation""",
    ),
    "cube": (
        "select n_regionkey, n_nationkey % 3 as m, count(*), sum(n_nationkey)"
        " from nation group by cube(n_regionkey, n_nationkey % 3)",
        """select n_regionkey, n_nationkey % 3, count(*), sum(n_nationkey) from nation group by 1, 2
           union all select n_regionkey, null, count(*), sum(n_nationkey) from nation group by 1
           union all select null, n_nationkey % 3, count(*), sum(n_nationkey) from nation group by 2
           union all select null, null, count(*), sum(n_nationkey) from nation""",
    ),
    "explicit_sets": (
        "select o_orderstatus, o_orderpriority, count(*) from orders"
        " group by grouping sets ((o_orderstatus), (o_orderpriority), ())",
        """select o_orderstatus, null, count(*) from orders group by 1
           union all select null, o_orderpriority, count(*) from orders group by 2
           union all select null, null, count(*) from orders""",
    ),
    "distinct_agg": (
        "select s_nationkey, count(distinct s_suppkey % 10) from supplier"
        " group by rollup(s_nationkey)",
        """select s_nationkey, count(distinct s_suppkey % 10) from supplier group by 1
           union all select null, count(distinct s_suppkey % 10) from supplier""",
    ),
    "mixed_plain_rollup": (
        "select o_orderstatus, o_orderpriority, count(*) from orders"
        " group by o_orderstatus, rollup(o_orderpriority)",
        """select o_orderstatus, o_orderpriority, count(*) from orders group by 1, 2
           union all select o_orderstatus, null, count(*) from orders group by 1""",
    ),
}


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize("name", sorted(CASES))
def test_grouping_sets(name, engine, oracle):
    sql, oracle_sql = CASES[name]
    assert_rows_equal(engine.query(sql), oracle.query(oracle_sql), ordered=False)


def test_grouping_function(engine):
    rows = engine.query(
        "select n_regionkey, grouping(n_regionkey), count(*) from nation"
        " group by rollup(n_regionkey) order by 2, 1"
    )
    assert rows[-1] == (None, 1, 25)
    assert all(r[1] == 0 for r in rows[:-1])
    rows = engine.query(
        "select n_regionkey, n_nationkey % 3, grouping(n_regionkey, n_nationkey % 3),"
        " count(*) from nation group by cube(n_regionkey, n_nationkey % 3)"
    )
    assert sorted(set(r[2] for r in rows)) == [0, 1, 2, 3]


def test_grouping_sets_distributed(oracle):
    import jax

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(distributed=True, devices=jax.devices()[:4])
    eng.register_catalog("tpch", TpchConnector(0.01))
    sql, oracle_sql = CASES["rollup"]
    assert_rows_equal(eng.query(sql), oracle.query(oracle_sql), ordered=False)
