"""MATCH_RECOGNIZE row-pattern recognition tests.

Expected results are hand-derived per the SQL:2016 semantics the reference
implements (core/trino-main/.../operator/window/matcher/Matcher.java:28 and
sql/analyzer/PatternRecognitionAnalyzer.java): greedy/reluctant quantifier
preferment, leftmost-alternative preference, AFTER MATCH SKIP modes,
FINAL measure semantics under ONE ROW PER MATCH and RUNNING semantics under
ALL ROWS PER MATCH (Trino's defaults).  sqlite has no MATCH_RECOGNIZE, so
these are expected-value tests rather than oracle diffs.
"""

import pytest


@pytest.fixture(scope="module")
def mr_engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table ticks (sym varchar, ts bigint, price double)")
    eng.execute(
        "insert into ticks values "
        "('a',1,10.0),('a',2,8.0),('a',3,7.0),('a',4,9.0),('a',5,12.0),"
        "('b',1,5.0),('b',2,6.0),('b',3,4.0),('b',4,3.0),('b',5,7.0)"
    )
    eng.execute("create table seq (ts bigint, x bigint)")
    eng.execute("insert into seq values (1,1),(2,2),(3,3),(4,4),(5,5)")
    return eng


def test_v_shape_one_row_per_match(mr_engine):
    """The canonical down+ up+ V-pattern, FINAL measures."""
    rows = mr_engine.query("""
      select * from ticks match_recognize (
        partition by sym order by ts
        measures match_number() as mno, classifier() as cls,
                 first(down.ts) as start_ts, last(up.ts) as end_ts
        one row per match
        after match skip past last row
        pattern (down+ up+)
        define down as price < prev(price), up as price > prev(price)
      )
    """)
    assert rows == [("a", 1, "UP", 2, 5), ("b", 1, "UP", 3, 5)]


def test_all_rows_per_match_running_classifier(mr_engine):
    """ALL ROWS PER MATCH: one output row per matched row, RUNNING
    CLASSIFIER() = the current row's label."""
    rows = mr_engine.query("""
      select sym, ts, cls, mno from ticks match_recognize (
        partition by sym order by ts
        measures classifier() as cls, match_number() as mno
        all rows per match
        pattern (down+ up+)
        define down as price < prev(price), up as price > prev(price)
      ) where sym = 'b'
    """)
    assert rows == [
        ("b", 3, "DOWN", 1),
        ("b", 4, "DOWN", 1),
        ("b", 5, "UP", 1),
    ]


def test_greedy_plus_takes_longest(mr_engine):
    rows = mr_engine.query("""
      select * from seq match_recognize (
        order by ts
        measures last(b.ts) as b_at
        one row per match
        pattern (a+ b)
        define b as x >= 3
      )
    """)
    # greedy a+ consumes up to ts4 so b lands on the LAST row satisfying it
    assert rows == [(5,)]


def test_reluctant_plus_takes_shortest(mr_engine):
    rows = mr_engine.query("""
      select * from seq match_recognize (
        order by ts
        measures last(b.ts) as b_at
        one row per match
        pattern (a+? b)
        define b as x >= 3
      )
    """)
    # reluctant a+? consumes the minimum: a={1}, b tries ts2 (x=2 fails),
    # extends to a={1,2}, b=ts3 succeeds; a second match then starts at ts4
    # (a={4}, b=ts5)
    assert rows == [(3,), (5,)]


def test_bounded_repetition(mr_engine):
    rows = mr_engine.query("""
      select * from seq match_recognize (
        order by ts
        measures first(a.ts) as f, last(a.ts) as l
        one row per match
        after match skip past last row
        pattern (a{2,3})
        define a as x < 10
      )
    """)
    # greedy {2,3}: first match takes 3 rows, remainder takes 2
    assert rows == [(1, 3), (4, 5)]


def test_alternation_prefers_left(mr_engine):
    mr_engine.execute("create table alt_t (ts bigint, x bigint)")
    mr_engine.execute("insert into alt_t values (1,5),(2,20)")
    rows = mr_engine.query("""
      select * from alt_t match_recognize (
        order by ts
        measures first(a.ts) as ats, classifier() as cls
        one row per match
        pattern (a | b)
        define a as x > 10, b as x > 0
      )
    """)
    # row1: a fails -> b; row2: both match, left alternative (a) preferred
    assert rows == [(None, "B"), (2, "A")]


def test_after_match_skip_modes(mr_engine):
    past = mr_engine.query("""
      select * from seq match_recognize (
        order by ts
        measures first(a.ts) as f, last(a.ts) as l
        one row per match
        after match skip past last row
        pattern (a a)
        define a as x <= 4
      )
    """)
    assert past == [(1, 2), (3, 4)]
    nxt = mr_engine.query("""
      select * from seq match_recognize (
        order by ts
        measures first(a.ts) as f, last(a.ts) as l
        one row per match
        after match skip to next row
        pattern (a a)
        define a as x <= 4
      )
    """)
    # overlapping matches allowed
    assert nxt == [(1, 2), (2, 3), (3, 4)]


def test_prev_with_offset(mr_engine):
    mr_engine.execute("create table po (ts bigint, price double)")
    mr_engine.execute("insert into po values (1,1.0),(2,2.0),(3,5.0),(4,1.0)")
    rows = mr_engine.query("""
      select * from po match_recognize (
        order by ts
        measures first(a.ts) as at
        one row per match
        pattern (a)
        define a as price > prev(price, 2)
      )
    """)
    # only ts3 has prev(price,2)=1.0 with 5.0 > 1.0; ts4: 1.0 > 2.0 false
    assert rows == [(3,)]


def test_next_navigation(mr_engine):
    mr_engine.execute("create table nx (ts bigint, price double)")
    mr_engine.execute("insert into nx values (1,3.0),(2,5.0),(3,2.0),(4,4.0)")
    rows = mr_engine.query("""
      select * from nx match_recognize (
        order by ts
        measures first(a.ts) as at
        one row per match
        after match skip past last row
        pattern (a)
        define a as price < next(price)
      )
    """)
    # ts1 (3<5) and ts3 (2<4); ts4's NEXT is out of partition -> NULL -> false
    assert rows == [(1,), (3,)]


def test_pattern_cannot_cross_partitions(mr_engine):
    mr_engine.execute("create table pi (p varchar, ts bigint, x bigint)")
    mr_engine.execute("insert into pi values ('p1',1,1),('p2',1,1)")
    rows = mr_engine.query("""
      select * from pi match_recognize (
        partition by p order by ts
        measures first(a.ts) as f
        one row per match
        pattern (a a)
        define a as x = 1
      )
    """)
    assert rows == []


def test_optional_quantifier(mr_engine):
    mr_engine.execute("create table oq (ts bigint, x bigint)")
    mr_engine.execute("insert into oq values (1,1),(2,3),(3,1),(4,2),(5,3)")
    rows = mr_engine.query("""
      select * from oq match_recognize (
        order by ts
        measures first(a.ts) as f, last(c.ts) as l
        one row per match
        after match skip past last row
        pattern (a b? c)
        define a as x = 1, b as x = 2, c as x = 3
      )
    """)
    # match1: a=ts1, b absent, c=ts2; match2: a=ts3, b=ts4, c=ts5
    assert rows == [(1, 2), (3, 5)]


def test_measure_arithmetic_over_primitives(mr_engine):
    rows = mr_engine.query("""
      select sym, delta from ticks match_recognize (
        partition by sym order by ts
        measures last(up.price) - first(down.price) as delta
        one row per match
        pattern (down+ up+)
        define down as price < prev(price), up as price > prev(price)
      )
    """)
    # a: 12.0 - 8.0; b: 7.0 - 4.0
    assert rows == [("a", 4.0), ("b", 3.0)]


def test_match_number_counts_per_partition(mr_engine):
    mr_engine.execute("create table mn (p varchar, ts bigint, x bigint)")
    mr_engine.execute(
        "insert into mn values ('p1',1,1),('p1',2,1),('p2',1,1),('p2',2,1)"
    )
    rows = mr_engine.query("""
      select * from mn match_recognize (
        partition by p order by ts
        measures match_number() as mno, first(a.ts) as f
        one row per match
        after match skip past last row
        pattern (a)
        define a as x = 1
      )
    """)
    assert rows == [("p1", 1, 1), ("p1", 2, 2), ("p2", 1, 1), ("p2", 2, 2)]


def test_star_quantifier_and_undefined_label(mr_engine):
    rows = mr_engine.query("""
      select * from seq match_recognize (
        order by ts
        measures first(a.ts) as f, last(b.ts) as l
        one row per match
        pattern (a b*)
        define a as x = 1
      )
    """)
    # b undefined -> always matches; greedy b* takes the rest of the rows
    assert rows == [(1, 5)]


def test_match_recognize_as_subquery_input(mr_engine):
    """The MATCH_RECOGNIZE relation composes with downstream operators."""
    rows = mr_engine.query("""
      select count(*), max(end_ts) from (
        select * from ticks match_recognize (
          partition by sym order by ts
          measures last(up.ts) as end_ts
          one row per match
          pattern (down+ up+)
          define down as price < prev(price), up as price > prev(price)
        )
      )
    """)
    assert rows == [(2, 5)]


def test_null_partition_keys_group_together(mr_engine):
    """NULL partition-key rows form ONE partition (garbage under the
    validity mask must not split the run)."""
    mr_engine.execute("create table npk (p bigint, ts bigint, x bigint)")
    mr_engine.execute(
        "insert into npk values (null,1,1),(null,2,1),(1,1,1),(1,2,1)"
    )
    rows = mr_engine.query("""
      select * from npk match_recognize (
        partition by p order by ts
        measures first(a.ts) as f, last(a.ts) as l
        one row per match
        after match skip past last row
        pattern (a a)
        define a as x = 1
      )
    """)
    # both the NULL partition and partition 1 match across their two rows
    assert sorted(rows, key=lambda r: (r[0] is None, r)) == [
        (1, 1, 2), (None, 1, 2)
    ]


def test_nested_prev_navigation(mr_engine):
    """PREV over an expression containing another PREV (nested lowering)."""
    mr_engine.execute("create table nv2 (ts bigint, x double)")
    mr_engine.execute(
        "insert into nv2 values (1,1.0),(2,2.0),(3,4.0),(4,5.0)"
    )
    rows = mr_engine.query("""
      select * from nv2 match_recognize (
        order by ts
        measures first(a.ts) as at
        one row per match
        after match skip past last row
        pattern (a)
        define a as x - prev(x) > prev(x - prev(x))
      )
    """)
    # ts3: delta=2 > prev delta=1 -> match; ts4: delta=1 > 2 false;
    # ts2: prev delta is NULL -> false
    assert rows == [(3,)]
