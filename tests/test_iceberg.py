"""Iceberg-lite lakehouse connector: snapshot commits, time travel,
metadata tables, stats-based pruning (reference: plugin/trino-iceberg)."""

import pytest


@pytest.fixture()
def engine(tmp_path):
    from trino_tpu.connectors.iceberg import IcebergConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="iceberg")
    eng.register_catalog("iceberg", IcebergConnector(str(tmp_path / "wh")))
    return eng


def test_create_insert_select(engine):
    engine.execute("create table t (k bigint, v double, s varchar)")
    engine.execute("insert into t values (1, 1.5, 'a'), (2, 2.5, 'b')")
    engine.execute("insert into t values (3, 3.5, 'c')")
    assert engine.execute("select k, v, s from t order by k") == [
        (1, 1.5, "a"), (2, 2.5, "b"), (3, 3.5, "c"),
    ]
    assert engine.execute("select count(*) from t") == [(3,)]


def test_ctas(engine):
    engine.execute("create table src (k bigint)")
    engine.execute("insert into src values (1), (2)")
    engine.execute("create table dst as select k * 10 as k10 from src")
    assert engine.execute("select k10 from dst order by k10") == [(10,), (20,)]


def test_snapshots_metadata_table(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1)")
    engine.execute("insert into t values (2), (3)")
    rows = engine.execute(
        'select snapshot_id, file_count, row_count from "t$snapshots" '
        "order by snapshot_id"
    )
    assert rows == [(1, 0, 0), (2, 1, 1), (3, 2, 3)]


def test_time_travel(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1)")       # snapshot 2
    engine.execute("insert into t values (2), (3)")  # snapshot 3
    assert engine.execute('select k from "t@2" order by k') == [(1,)]
    assert engine.execute('select k from "t@3" order by k') == [(1,), (2,), (3,)]
    assert engine.execute("select count(*) from t") == [(3,)]


def test_rollback(engine):
    conn = engine.catalogs.get("iceberg")
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1)")  # snapshot 2
    engine.execute("insert into t values (2)")  # snapshot 3
    conn.rollback_to_snapshot("t", 2)
    assert engine.execute("select k from t") == [(1,)]
    # history preserved: snapshot 3 still queryable
    assert engine.execute('select k from "t@3" order by k') == [(1,), (2,)]


def test_dml_on_iceberg(engine):
    engine.execute("create table t (k bigint, v double)")
    engine.execute("insert into t values (1, 1.0), (2, 2.0), (3, 3.0)")
    assert engine.execute("delete from t where k = 2") == [(1,)]
    assert engine.execute("select k from t order by k") == [(1,), (3,)]
    engine.execute("update t set v = v * 10 where k = 3")
    assert engine.execute("select k, v from t order by k") == [(1, 1.0), (3, 30.0)]
    # every mutation is a snapshot: time travel back before the delete
    snaps = engine.catalogs.get("iceberg").snapshots("t")
    assert len(snaps) >= 4


def test_nulls_roundtrip(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1, null), (2, 'x')")
    assert engine.execute("select k, s from t order by k") == [(1, None), (2, "x")]
    assert engine.execute("select count(s) from t") == [(1,)]


def test_stats_for_cbo(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (5), (10), (15)")
    stats = engine.catalogs.get("iceberg").table_stats("t")
    assert stats.row_count == 3
    assert stats.columns["k"].min == 5.0 and stats.columns["k"].max == 15.0


def test_transactions_snapshot_on_iceberg(engine):
    # iceberg snapshot/restore hooks are snapshot-id pins; commit keeps them
    engine.execute("create table t (k bigint)")
    engine.execute("start transaction")
    engine.execute("insert into t values (1)")
    engine.execute("commit")
    assert engine.execute("select count(*) from t") == [(1,)]


def test_drop_table_rollback(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (7)")
    engine.execute("start transaction")
    engine.execute("drop table t")
    assert engine.execute("show tables") == []
    engine.execute("rollback")
    assert engine.execute("select k from t") == [(7,)]
