"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding (pjit /
shard_map / all_to_all exchanges) is exercised without TPU hardware -- the
same trick the reference uses with DistributedQueryRunner launching N servers
in one JVM over loopback (testing/trino-testing/.../DistributedQueryRunner.java:107):
the full stack runs, only the transport is local.

Env vars MUST be set before jax initializes its backends, hence here.
"""

import os

# Force CPU: the environment's sitecustomize pins JAX_PLATFORMS=axon (one
# real TPU chip), but correctness tests need (a) true float64 — TPU silently
# computes f64 at f32 precision — and (b) 8 virtual devices for the
# multi-chip exchange tests.  Hence a hard override, not setdefault.
# Stash the hardware platform before forcing CPU so the on-TPU differential
# tier (tests/test_tpch_tpu.py) can re-enable it in a subprocess.  An unset
# JAX_PLATFORMS means "autodetect" — stash "auto" (not ""), so the tier still
# probes for hardware on plain TPU VMs where nothing was exported.
os.environ.setdefault("TRINO_TPU_HW_PLATFORM", os.environ.get("JAX_PLATFORMS") or "auto")
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# sitecustomize may have imported jax already (axon boot); the config update
# still wins as long as no backend has been used yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's dominant cost is compiling
# the same fragment programs run after run (8-device shard_map plans take
# minutes); the on-disk cache makes re-runs hit warm compiles.
_repo_root = os.path.dirname(os.path.dirname(__file__))
import sys  # noqa: E402

sys.path.insert(0, _repo_root)
from trino_tpu.utils.compilecache import enable_persistent_cache  # noqa: E402

# host-fingerprinted dir: XLA:CPU AOT entries from another machine fail to
# load (and recompile) on hosts with different CPU features
enable_persistent_cache(_repo_root)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---------------------------------------------------------------- CI tiers
# Two tiers (reference: fast PR checks vs nightly product tests,
# testing/trino-product-tests/):
#   smoke — `pytest -m smoke`, < 5 min on 1 CPU: data plane, Pallas
#           interpreter kernels, a few TPC-H locals, ONE 8-device
#           distributed query, multihost control-plane basics.
#   full  — everything (the default; what the driver runs).
_SMOKE = {
    "tests/test_data_plane.py": None,  # None = whole module
    "tests/test_native_serde.py": None,
    "tests/test_pallas.py": None,
    "tests/test_tpch.py": {"q01", "q06", "q03"},
    "tests/test_tpch_distributed.py": {"q01"},
    "tests/test_multihost.py": {
        "test_client_protocol",
        "test_discovery_and_heartbeat",
        "test_task_level_retry",
    },
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "smoke: fast CI tier (< 5 min on 1 CPU); run with -m smoke"
    )
    config.addinivalue_line(
        "markers", "tpu: requires real TPU hardware (skipped on CPU-only hosts)"
    )
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (`-m 'not slow'`)"
    )
    config.addinivalue_line(
        "markers",
        "chaos: randomized-fault resilience tier; run with scripts/chaos_tier.sh",
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        rel = os.path.relpath(str(item.fspath), os.path.dirname(os.path.dirname(__file__)))
        sel = _SMOKE.get(rel)
        if sel is None and rel not in _SMOKE:
            continue
        if sel is None:
            item.add_marker(pytest.mark.smoke)
        else:
            name = item.name
            base = name.split("[")[0]
            param = name[len(base) + 1 : -1] if "[" in name else None
            if base in sel or (param is not None and param in sel):
                item.add_marker(pytest.mark.smoke)


@pytest.fixture(scope="session")
def tpch_tiny():
    """TPC-H tiny (SF 0.01) tables as numpy dicts, generated once per session."""
    from trino_tpu.connectors.tpch import tpch_data
    from trino_tpu.connectors.tpch.generator import TPCH_SCHEMAS

    return {t: tpch_data(t, 0.01) for t in TPCH_SCHEMAS}


@pytest.fixture(scope="session")
def oracle(tpch_tiny):
    """sqlite differential oracle over the same generated data (the
    reference's H2QueryRunner analogue)."""
    from tests.oracle import SqliteOracle

    return SqliteOracle(tpch_tiny)
