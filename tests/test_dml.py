"""Row-level DML (DELETE / UPDATE / MERGE), prepared statements, and
transactions (reference: sql/tree/{Delete,Update,Merge,Prepare,Execute},
operator/MergeWriterOperator, transaction/TransactionManager).

Differential where it counts: the same operation sequence is applied to an
in-memory sqlite database and results diffed after each write.
"""

import sqlite3

import pytest


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    return eng


@pytest.fixture()
def mirror(engine):
    """(engine, sqlite) pair that applies the same SQL to both and diffs."""
    db = sqlite3.connect(":memory:")

    class Mirror:
        def both(self, sql):
            engine.execute(sql)
            db.execute(sql)

        def check(self, sql):
            got = engine.execute(sql)
            want = [tuple(r) for r in db.execute(sql).fetchall()]
            assert got == want, f"{sql}\n got={got}\nwant={want}"

    m = Mirror()
    m.both("create table t (k bigint, v double, s varchar)")
    m.both("insert into t values (1, 1.5, 'a'), (2, 2.5, 'b'), "
           "(3, NULL, 'c'), (4, 4.0, NULL), (5, 5.5, 'b')")
    return m


# ------------------------------------------------------------------- DELETE


def test_delete_where(mirror):
    mirror.both("delete from t where v > 2.0")
    mirror.check("select k, v, s from t order by k")


def test_delete_null_predicate_survives(mirror):
    # v is NULL for k=3: predicate is NULL there -> row must survive
    mirror.both("delete from t where v < 100.0")
    mirror.check("select k, v, s from t order by k")


def test_delete_string_predicate(mirror):
    mirror.both("delete from t where s = 'b'")
    mirror.check("select k, v, s from t order by k")


def test_delete_all(engine):
    engine.execute("create table d (x bigint)")
    engine.execute("insert into d values (1), (2), (3)")
    assert engine.execute("delete from d") == [(3,)]
    assert engine.execute("select count(*) from d") == [(0,)]


def test_delete_count(engine):
    engine.execute("create table d (x bigint)")
    engine.execute("insert into d values (1), (2), (3), (4)")
    assert engine.execute("delete from d where x >= 3") == [(2,)]


# ------------------------------------------------------------------- UPDATE


def test_update_where(mirror):
    mirror.both("update t set v = v * 10 where k <= 2")
    mirror.check("select k, v, s from t order by k")


def test_update_multiple_columns(mirror):
    mirror.both("update t set v = 0.0, s = 'z' where k = 4")
    mirror.check("select k, v, s from t order by k")


def test_update_all_rows(mirror):
    mirror.both("update t set v = 1.0")
    mirror.check("select k, v, s from t order by k")


def test_update_null_predicate_untouched(mirror):
    # rows where the predicate is NULL must keep their values
    mirror.both("update t set s = 'hit' where v > 0")
    mirror.check("select k, v, s from t order by k")


def test_update_string_case(engine):
    engine.execute("create table u (k bigint, s varchar)")
    engine.execute("insert into u values (1, 'a'), (2, 'b')")
    engine.execute("update u set s = upper(s) where k = 2")
    assert engine.execute("select s from u order by k") == [("a",), ("B",)]


def test_update_count(engine):
    engine.execute("create table u (k bigint)")
    engine.execute("insert into u values (1), (2), (3)")
    assert engine.execute("update u set k = k + 100 where k >= 2") == [(2,)]


# -------------------------------------------------------------------- MERGE


def test_merge_update_delete_insert(engine):
    engine.execute("create table tgt (k bigint, v double)")
    engine.execute("insert into tgt values (1, 10.0), (2, 20.0), (3, 30.0)")
    engine.execute("create table src (k bigint, v double)")
    engine.execute("insert into src values (2, 200.0), (3, 300.0), (4, 400.0)")
    n = engine.execute(
        "merge into tgt t using src s on t.k = s.k "
        "when matched and s.v > 250 then delete "
        "when matched then update set v = s.v "
        "when not matched then insert (k, v) values (s.k, s.v)"
    )
    assert n == [(3,)]  # 1 update + 1 delete + 1 insert
    assert engine.execute("select k, v from tgt order by k") == [
        (1, 10.0), (2, 200.0), (4, 400.0),
    ]


def test_merge_clause_order_first_match_wins(engine):
    # an earlier UPDATE clause must shadow a later DELETE clause
    engine.execute("create table tgt (k bigint, v double)")
    engine.execute("insert into tgt values (1, 10.0)")
    engine.execute("create table src (k bigint, v double)")
    engine.execute("insert into src values (1, 99.0)")
    engine.execute(
        "merge into tgt t using src s on t.k = s.k "
        "when matched and s.v > 50 then update set v = s.v "
        "when matched then delete"
    )
    assert engine.execute("select k, v from tgt") == [(1, 99.0)]


def test_merge_subquery_source(engine):
    engine.execute("create table tgt (k bigint, v double)")
    engine.execute("insert into tgt values (1, 1.0)")
    engine.execute("create table raw (k bigint, v double)")
    engine.execute("insert into raw values (1, 5.0), (1, 7.0), (2, 9.0)")
    engine.execute(
        "merge into tgt t using "
        "(select k, sum(v) as sv from raw group by k) s on t.k = s.k "
        "when matched then update set v = s.sv "
        "when not matched then insert (k, v) values (s.k, s.sv)"
    )
    assert engine.execute("select k, v from tgt order by k") == [(1, 12.0), (2, 9.0)]


def test_merge_insert_only(engine):
    engine.execute("create table tgt (k bigint, v double)")
    engine.execute("insert into tgt values (1, 1.0)")
    engine.execute("create table src (k bigint, v double)")
    engine.execute("insert into src values (1, 9.0), (5, 55.0)")
    n = engine.execute(
        "merge into tgt t using src s on t.k = s.k "
        "when not matched then insert values (s.k, s.v)"
    )
    assert n == [(1,)]
    assert engine.execute("select k, v from tgt order by k") == [(1, 1.0), (5, 55.0)]


# --------------------------------------------------- PREPARE / EXECUTE


def test_prepare_execute(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a'), (2,'b'), (3,'c')")
    engine.execute("prepare q1 from select k, s from t where k > ? order by k")
    assert engine.execute("execute q1 using 1") == [(2, "b"), (3, "c")]
    assert engine.execute("execute q1 using 2") == [(3, "c")]


def test_prepare_string_param(engine):
    engine.execute("create table t (k bigint, s varchar)")
    engine.execute("insert into t values (1,'a'), (2,'b')")
    engine.execute("prepare q from select k from t where s = ?")
    assert engine.execute("execute q using 'b'") == [(2,)]


def test_prepare_dml(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1), (2), (3)")
    engine.execute("prepare d from delete from t where k = ?")
    assert engine.execute("execute d using 2") == [(1,)]
    assert engine.execute("select k from t order by k") == [(1,), (3,)]


def test_deallocate(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("prepare q from select * from t")
    engine.execute("deallocate prepare q")
    with pytest.raises(KeyError):
        engine.execute("execute q")


def test_execute_unknown_raises(engine):
    with pytest.raises(KeyError):
        engine.execute("execute nope")


# ------------------------------------------------------------- transactions


def test_transaction_rollback(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1), (2)")
    engine.execute("start transaction")
    engine.execute("insert into t values (9)")
    engine.execute("delete from t where k = 1")
    assert engine.execute("select k from t order by k") == [(2,), (9,)]
    engine.execute("rollback")
    assert engine.execute("select k from t order by k") == [(1,), (2,)]


def test_transaction_commit(engine):
    engine.execute("create table t (k bigint)")
    engine.execute("insert into t values (1)")
    engine.execute("begin")
    engine.execute("update t set k = 100")
    engine.execute("commit")
    assert engine.execute("select k from t") == [(100,)]


def test_transaction_rollback_ddl(engine):
    engine.execute("start transaction")
    engine.execute("create table t2 (k bigint)")
    engine.execute("rollback")
    assert engine.execute("show tables") == []


def test_nested_transaction_raises(engine):
    engine.execute("start transaction")
    with pytest.raises(RuntimeError):
        engine.execute("start transaction")
    engine.execute("commit")
    with pytest.raises(RuntimeError):
        engine.execute("commit")


def test_merge_multi_match_is_error(engine):
    # reference semantics: 'One MERGE target table row matched more than one
    # source row' is an error, not silent duplication
    engine.execute("create table tgt (k bigint, v double)")
    engine.execute("insert into tgt values (1, 1.0)")
    engine.execute("create table src (k bigint, v double)")
    engine.execute("insert into src values (1, 5.0), (1, 7.0)")
    with pytest.raises(ValueError):
        engine.execute(
            "merge into tgt t using src s on t.k = s.k "
            "when matched then update set v = s.v"
        )
    # target unchanged
    assert engine.execute("select k, v from tgt") == [(1, 1.0)]


def test_update_count_pre_image(engine):
    # WHERE references the assigned column: count on the pre-image
    engine.execute("create table u2 (x bigint)")
    engine.execute("insert into u2 values (6), (7), (1)")
    assert engine.execute("update u2 set x = 0 where x > 5") == [(2,)]


def test_insert_arity_mismatch_raises(engine):
    engine.execute("create table a1 (x bigint)")
    engine.execute("insert into a1 values (1)")
    with pytest.raises(ValueError):
        engine.execute("insert into a1 (x) select x, x from a1")


def test_merge_insert_only_multimatch_source(engine):
    # insert-only MERGE must not rewrite (and so cannot duplicate) the
    # target, even when one target row matches several source rows;
    # unaliased table source resolves by its table name
    engine.execute("create table t2 (k bigint, v double)")
    engine.execute("insert into t2 values (1, 10.0)")
    engine.execute("create table s2 (k bigint, v double)")
    engine.execute("insert into s2 values (1, 111.0), (1, 222.0), (2, 20.0)")
    n = engine.execute(
        "merge into t2 using s2 on t2.k = s2.k "
        "when not matched then insert (k, v) values (s2.k, s2.v)"
    )
    assert n == [(1,)]
    assert engine.execute("select k, v from t2 order by k") == [(1, 10.0), (2, 20.0)]
