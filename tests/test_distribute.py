"""Cost-based join distribution (plan/distribute.py).

Reference behavior being matched: iterative/rule/
DetermineJoinDistributionType.java:51 — AUTOMATIC compares the bytes a
broadcast replicates (build x D devices) against the bytes a partitioned
join moves (both sides once), instead of a fixed build-row constant.
"""

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT, VARCHAR
from trino_tpu.plan.distribute import distribute
from trino_tpu.plan.nodes import Exchange, Join, walk
from trino_tpu.runtime.engine import Engine

pytestmark = pytest.mark.smoke

_D = 8  # devices


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(11)
    conn = MemoryConnector()
    n_probe, n_build = 100_000, 50_000
    conn.create_table(
        "probe", [ColumnSchema("p_id", BIGINT), ColumnSchema("p_key", BIGINT)]
    )
    conn.insert("probe", {
        "p_id": np.arange(n_probe, dtype=np.int64),
        "p_key": rng.integers(0, n_build, n_probe).astype(np.int64),
    })
    # wide build: many varchar columns make each row expensive to replicate
    wide_cols = [ColumnSchema("b_id", BIGINT)] + [
        ColumnSchema(f"b_s{i}", VARCHAR) for i in range(6)
    ]
    conn.create_table("build", wide_cols)
    data = {"b_id": np.arange(n_build, dtype=np.int64)}
    for i in range(6):
        data[f"b_s{i}"] = np.asarray(
            [f"v{i}_{j % 97}" for j in range(n_build)], dtype=object
        )
    conn.insert("build", data)
    # small dimension: cheap to replicate even x8
    conn.create_table(
        "dim", [ColumnSchema("d_id", BIGINT), ColumnSchema("d_name", VARCHAR)]
    )
    conn.insert("dim", {
        "d_id": np.arange(50, dtype=np.int64),
        "d_name": np.asarray([f"d{i}" for i in range(50)], dtype=object),
    })
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    return eng


def _join_modes(plan):
    return [
        (n.kind, n.distribution)
        for n in walk(plan)
        if isinstance(n, Join) and n.kind != "cross"
    ]


def _exchange_kinds(plan):
    return [n.kind for n in walk(plan) if isinstance(n, Exchange)]


def test_wide_build_chooses_partitioned(engine):
    """50k wide rows x 8 devices costs more to replicate than moving both
    sides once: AUTOMATIC must pick PARTITIONED (the old 100k-row constant
    chose broadcast here)."""
    plan = engine.planner.plan(
        "SELECT count(*) AS c FROM probe JOIN build ON p_key = b_id"
    )
    from trino_tpu.plan.optimizer import optimize

    plan = optimize(plan, engine.catalogs, engine.session)
    dist = distribute(plan, engine.catalogs, _D, engine.session)
    modes = _join_modes(dist)
    assert ("inner", "partitioned") in modes, modes
    assert "repartition" in _exchange_kinds(dist)


def test_small_build_still_broadcasts(engine):
    plan = engine.planner.plan(
        "SELECT count(*) AS c FROM probe JOIN dim ON p_key = d_id"
    )
    from trino_tpu.plan.optimizer import optimize

    plan = optimize(plan, engine.catalogs, engine.session)
    dist = distribute(plan, engine.catalogs, _D, engine.session)
    modes = _join_modes(dist)
    assert ("inner", "broadcast") in modes, modes


def test_session_override_forces_broadcast(engine):
    engine.session.set("join_distribution_type", "BROADCAST")
    try:
        plan = engine.planner.plan(
            "SELECT count(*) AS c FROM probe JOIN build ON p_key = b_id"
        )
        from trino_tpu.plan.optimizer import optimize

        plan = optimize(plan, engine.catalogs, engine.session)
        dist = distribute(plan, engine.catalogs, _D, engine.session)
        assert ("inner", "broadcast") in _join_modes(dist)
    finally:
        engine.session.set("join_distribution_type", "AUTOMATIC")
