"""Compile-cliff resilience plane (exec/compilesvc.py).

The engine's tallest latency cliff is a cold XLA signature: minutes of
compile wall in front of a sub-second query.  This suite covers the
whole plane: graceful fallback execution under compile_wait_budget_ms
(differential-checked rows, compiled program swapping in later),
compile-storm admission (N concurrent queries, ONE build), hard compile
deadlines with typed COMPILE_TIMEOUT attribution, the per-signature
circuit breaker with half-open recovery, startup cache warming from the
query history, the pow2 capacity-bucketing signature collapse (ROADMAP
2a), the AOT pytree-pin lazy-retrace bugfix, and the COMPILE_SLOW /
COMPILE_FAIL chaos modes on a live cluster
(scripts/chaos_tier.sh compile runs the `chaos` subset).
"""

import threading
import time

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.exec.compilesvc import (
    COMPILE_DEDUP, COMPILE_TIMEOUTS, FALLBACKS, CompileService,
    SignatureBreaker,
)
from trino_tpu.runtime.engine import Engine
from trino_tpu.runtime.failure import FaultInjector
from trino_tpu.runtime.history import QueryHistoryStore
from trino_tpu.utils.profiler import PROFILER, _PCACHE_EVENTS

GROUP_SQL = "select k, sum(v) as s from t group by k order by k"


def _make_engine(seed=0, n=4000):
    """Local engine over a seeded memory table plus the oracle rows for
    GROUP_SQL, computed in numpy (differential check — no engine path)."""
    conn = MemoryConnector()
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 16, n).astype(np.int64)
    v = rng.integers(0, 100, n).astype(np.int64)
    conn.insert("t", {"k": k, "v": v})
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    # isolated service: no done-map / breaker bleed between tests
    eng.executor.compile_service = CompileService()
    expected = [
        (int(key), int(v[k == key].sum())) for key in sorted(set(k.tolist()))
    ]
    return eng, expected


# ----------------------------------------------------- fallback + swap-in


def test_budget_exhausted_falls_back_then_swaps_in_compiled():
    """ISSUE acceptance core: under an injected slow compile and a small
    wait budget, a cold-signature query returns correct rows well under
    the compile wall via fallback; once the background compile lands, the
    next execution runs the compiled program with zero new fallbacks."""
    eng, expected = _make_engine(seed=0)
    eng.session.set("compile_wait_budget_ms", "200")
    inj = FaultInjector()
    inj.arm(task_id="*", mode="COMPILE_SLOW", delay_ms=2500, count=1)
    eng.executor.fault_injector = inj
    fb0 = FALLBACKS.value("compile_wait")

    t0 = time.perf_counter()
    rows = eng.query(GROUP_SQL)
    wall = time.perf_counter() - t0
    assert rows == expected
    assert wall < 2.0, f"fallback did not dodge the 2.5s compile wall: {wall}"
    assert ("COMPILE_SLOW", "local") in inj.fired
    assert eng.executor.last_fallback_reason == "compile_wait"
    ev = eng.executor.fallback_events[-1]
    assert ev["mode"] == "fallback" and ev["reason"] == "compile_wait"
    assert FALLBACKS.value("compile_wait") >= fb0 + 1

    # profiler ledger attributes the degraded execution separately
    snap = PROFILER.snapshot(ev["signature"])
    assert snap["fallback_executes"] >= 1
    assert snap["fallbacks"].get("compile_wait", 0) >= 1

    # the compile finished in the background: swap in, zero new fallbacks
    eng.executor.compile_service.drain(timeout_s=30)
    n_fallbacks = len(eng.executor.fallback_events)
    assert eng.query(GROUP_SQL) == expected
    assert len(eng.executor.fallback_events) == n_fallbacks
    swapped = eng.executor.compile_events[-1]
    assert swapped["mode"] == "async" and "reason" not in swapped


def test_explain_analyze_footer_names_fallback():
    eng, _ = _make_engine(seed=6, n=1000)
    eng.session.set("compile_wait_budget_ms", "100")
    inj = FaultInjector()
    inj.arm(task_id="*", mode="COMPILE_SLOW", delay_ms=1500, count=1)
    eng.executor.fault_injector = inj
    lines = [r[0] for r in eng.execute(f"explain analyze {GROUP_SQL}")]
    compile_lines = [ln for ln in lines if ln.startswith("-- compile:")]
    assert any("fallback (compile_wait" in ln for ln in compile_lines), lines
    eng.executor.compile_service.drain(timeout_s=30)


# --------------------------------------------------------- storm admission


def test_compile_storm_collapses_to_one_build():
    svc = CompileService(max_workers=4)
    dedup0 = COMPILE_DEDUP.value()

    def build():
        time.sleep(0.5)
        return {"program": object()}

    key = ("storm-sig", True, "treedef", "avals")
    results = []
    barrier = threading.Barrier(6)

    def go():
        barrier.wait()
        results.append(svc.obtain(key, "storm-sig", build))

    threads = [threading.Thread(target=go) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert svc.builds == 1, "compile storm was not deduplicated"
    assert all(r.status == "ready" for r in results)
    programs = {id(r.result["program"]) for r in results}
    assert len(programs) == 1, "joiners got different programs"
    assert sum(1 for r in results if r.fresh) == 1
    assert COMPILE_DEDUP.value() == dedup0 + 5
    # and the done-map serves later obtains without a new build
    assert svc.obtain(key, "storm-sig", build).status == "ready"
    assert svc.builds == 1


# -------------------------------------------------------------- deadlines


def test_compile_deadline_is_typed_and_never_hangs():
    svc = CompileService(max_workers=2)
    sig = "deadline-sig"
    t_before = COMPILE_TIMEOUTS.value()
    timeouts_before = (PROFILER.snapshot(sig) or {}).get("timeouts", 0)

    def build():
        time.sleep(2.0)
        return "program"

    key = (sig, 1)
    t0 = time.perf_counter()
    out = svc.obtain(key, sig, build, wait_budget_s=None, deadline_s=0.3)
    wall = time.perf_counter() - t0
    assert out.status == "timeout" and out.reason == "compile_timeout"
    assert wall < 1.5, f"deadline did not bound the wait: {wall}"
    assert COMPILE_TIMEOUTS.value() == t_before + 1
    assert PROFILER.snapshot(sig)["timeouts"] == timeouts_before + 1
    # a late completion still lands for future swap-in
    svc.drain(timeout_s=10)
    assert svc.obtain(key, sig, build).status == "ready"


def test_executor_deadline_records_typed_compile_timeout():
    eng, expected = _make_engine(seed=1)
    # budget 0 == wait for the compile, bounded only by the deadline
    eng.session.set("compile_deadline_s", "0.3")
    inj = FaultInjector()
    inj.arm(task_id="*", mode="COMPILE_SLOW", delay_ms=2000, count=1)
    eng.executor.fault_injector = inj
    t0 = time.perf_counter()
    assert eng.query(GROUP_SQL) == expected
    assert time.perf_counter() - t0 < 1.8, "query hung past compile_deadline_s"
    ev = eng.executor.fallback_events[-1]
    assert ev["reason"] == "compile_timeout"
    assert ev["error"] == "COMPILE_TIMEOUT"
    eng.executor.compile_service.drain(timeout_s=30)


# -------------------------------------------------------- circuit breaker


def test_breaker_opens_and_half_open_probe_recovers():
    svc = CompileService(
        max_workers=2,
        breaker=SignatureBreaker(threshold=3, min_open_s=0.05, max_open_s=0.2),
    )
    sig = "breaker-sig"

    def boom():
        raise RuntimeError("injected compile failure")

    for i in range(3):
        out = svc.obtain((sig, i), sig, boom)
        assert out.status == "error" and out.reason == "compile_error"
    assert svc.breaker.state(sig) == "OPEN"

    # open breaker: no new build attempts (no churn)
    builds = svc.builds
    out = svc.obtain((sig, 3), sig, boom)
    assert out.status == "breaker_open" and out.reason == "breaker_open"
    assert svc.builds == builds

    # half-open probe that FAILS re-opens with a longer window
    time.sleep(0.35)
    out = svc.obtain((sig, 4), sig, boom)
    assert out.status == "error"
    assert svc.breaker.state(sig) == "OPEN"
    assert svc.obtain((sig, 5), sig, boom).status == "breaker_open"

    # half-open probe that SUCCEEDS closes the breaker
    time.sleep(0.35)
    out = svc.obtain((sig, 6), sig, lambda: "ok")
    assert out.status == "ready" and out.result == "ok"
    assert svc.breaker.state(sig) == "CLOSED"


def test_compile_fail_falls_back_and_breaker_stops_churn():
    eng, expected = _make_engine(seed=2)
    svc = CompileService(
        breaker=SignatureBreaker(threshold=3, min_open_s=30.0, max_open_s=30.0)
    )
    eng.executor.compile_service = svc
    inj = FaultInjector()
    inj.arm(task_id="*", mode="COMPILE_FAIL", count=10)
    eng.executor.fault_injector = inj
    for _ in range(3):
        assert eng.query(GROUP_SQL) == expected  # degraded, never failed
        assert eng.executor.last_fallback_reason == "compile_error"
    sig = eng.executor.fallback_events[-1]["signature"]
    assert svc.breaker.state(sig) == "OPEN"
    # poisoned signature pins fallback WITHOUT new compile attempts
    builds = svc.builds
    assert eng.query(GROUP_SQL) == expected
    assert eng.executor.last_fallback_reason == "breaker_open"
    assert svc.builds == builds


# ----------------------------------------------------------- cache warming


def test_top_statements_ranks_by_recurrence_then_recency():
    from trino_tpu.runtime.warmup import top_statements

    store = QueryHistoryStore(capacity=50)
    store.record({"query_id": "q1", "state": "FINISHED", "sql": "select a from t"})
    store.record({"query_id": "q2", "state": "FINISHED", "sql": "select b from t"})
    store.record({"query_id": "q3", "state": "FINISHED", "sql": "select a from t"})
    store.record({"query_id": "q4", "state": "FINISHED", "sql": "insert into t values (1)"})
    store.record({"query_id": "q5", "state": "FAILED", "sql": "select broken from t"})
    store.record({"query_id": "q6", "state": "FINISHED", "sql": "<planned>"})
    top = top_statements(store, 5)
    assert top == ["select a from t", "select b from t"]
    assert top_statements(store, 1) == ["select a from t"]


def test_engine_warm_from_history_prepays_the_compile():
    eng, expected = _make_engine(seed=3)
    store = QueryHistoryStore(capacity=10)
    store.record({"query_id": "w1", "state": "FINISHED", "sql": GROUP_SQL})
    store.record({"query_id": "w2", "state": "FINISHED", "sql": GROUP_SQL})
    store.record({"query_id": "w3", "state": "FAILED", "sql": "select nope"})
    warm0 = _PCACHE_EVENTS.value("warm")
    assert eng.warm_from_history(store, limit=4) == 1
    assert _PCACHE_EVENTS.value("warm") == warm0 + 1
    # the replay compiled the program: the client query is a pure hit
    n_events = len(eng.executor.compile_events)
    assert eng.query(GROUP_SQL) == expected
    assert len(eng.executor.compile_events) == n_events


def test_coordinator_startup_warming_env_gated(tmp_path, monkeypatch):
    import json

    from trino_tpu.testing import DistributedQueryRunner

    sql = "select k, sum(v + 3) as s from t group by k order by k"
    hist = tmp_path / "history.jsonl"
    hist.write_text(
        json.dumps({"query_id": "h1", "state": "FINISHED", "sql": sql}) + "\n"
        + json.dumps({"query_id": "h2", "state": "FINISHED", "sql": sql}) + "\n"
    )
    monkeypatch.setenv("TRINO_TPU_HISTORY_FILE", str(hist))
    monkeypatch.setenv("TRINO_TPU_WARM_SIGNATURES", "2")
    conn = MemoryConnector()
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    rng = np.random.default_rng(7)
    conn.insert("t", {
        "k": rng.integers(0, 8, 2000).astype(np.int64),
        "v": rng.integers(0, 50, 2000).astype(np.int64),
    })
    warm0 = _PCACHE_EVENTS.value("warm")
    runner = DistributedQueryRunner(num_workers=1, default_catalog="mem")
    runner.register_catalog("mem", conn)
    runner.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if _PCACHE_EVENTS.value("warm") >= warm0 + 1:
                break
            time.sleep(0.2)
        assert _PCACHE_EVENTS.value("warm") >= warm0 + 1, (
            "startup warmer never replayed the history statement"
        )
    finally:
        runner.stop()


# ------------------------------------- capacity bucketing (ROADMAP 2a)


def test_pow2_bucketing_collapses_near_identical_capacities():
    """Planner/learned capacities get quantized onto pow2 tiers before the
    jit boundary, so nudged capacities (stats drift, learned-cap growth)
    collapse onto the SAME signature instead of forcing a recompile."""
    eng, expected = _make_engine(seed=4)
    assert eng.query(GROUP_SQL) == expected
    (plan_key, caps) = next(iter(eng.executor._learned_caps.items()))
    assert caps and all(
        v >= 1 and (v & (v - 1)) == 0 for v in caps.values()
    ), f"learned caps not on the pow2 grid: {caps}"
    n_events = len(eng.executor.compile_events)
    sigs_before = set(PROFILER.snapshot().keys())
    # nudge caps off the grid; quantization must route them back
    eng.executor._learned_caps[plan_key] = {
        nid: (v - 1 if v > 2 else v) for nid, v in caps.items()
    }
    assert eng.query(GROUP_SQL) == expected
    assert len(eng.executor.compile_events) == n_events, (
        "nudged capacities recompiled instead of collapsing onto the tier"
    )
    assert set(PROFILER.snapshot().keys()) == sigs_before


# ------------------------------------------- AOT pytree-pin lazy retrace


def test_aot_structure_mismatch_retraces_lazily():
    """An AOT program is pinned to one input pytree; a structure drift the
    cache key missed must lazily retrace (counted as a miss), not fail
    the query."""
    from trino_tpu.exec.compiler import _JIT_CACHE_LOOKUPS

    eng, expected = _make_engine(seed=5)
    assert eng.query(GROUP_SQL) == expected
    ex = eng.executor

    def _pinned(inputs):
        raise TypeError("Argument types differ from the types for which this "
                        "computation was compiled")

    for key, (fn, holder, sig) in list(ex._jit_cache.items()):
        ex._jit_cache[key] = (_pinned, holder, sig)
    miss0 = _JIT_CACHE_LOOKUPS.value("miss")
    assert eng.query(GROUP_SQL) == expected
    assert _JIT_CACHE_LOOKUPS.value("miss") >= miss0 + 1
    assert all(entry[0] is not _pinned for entry in ex._jit_cache.values())


# -------------------------------------------------- cluster chaos modes


def _cluster(n_rows=5000, seed=11):
    from trino_tpu.testing import DistributedQueryRunner

    conn = MemoryConnector()
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 12, n_rows).astype(np.int64)
    v = rng.integers(0, 100, n_rows).astype(np.int64)
    conn.insert("t", {"k": k, "v": v})
    runner = DistributedQueryRunner(num_workers=2, default_catalog="mem")
    runner.register_catalog("mem", conn)
    runner.start()
    return runner, k, v


def test_chaos_compile_slow_completes_via_fallback(monkeypatch):
    """ISSUE acceptance, distributed: 10s COMPILE_SLOW on every worker +
    compile_wait_budget_ms=500 — the query returns differential-checked
    rows well under the compile wall via fallback."""
    import trino_tpu.exec.compilesvc as compilesvc

    # fresh service: the 10s builds must not occupy the process-global
    # pool other tests' compiles run on
    monkeypatch.setattr(compilesvc, "SERVICE", CompileService())
    sql = "select k, sum(v + 7) as s from t group by k order by k"
    runner, k, v = _cluster(seed=11)
    expected = [
        (int(key), int((v[k == key] + 7).sum()))
        for key in sorted(set(k.tolist()))
    ]
    try:
        runner.coordinator.session.set("compile_wait_budget_ms", "500")
        for i in range(len(runner.workers)):
            runner.inject_task_failure(
                worker_index=i, mode="COMPILE_SLOW", delay_ms=10_000, count=1
            )
        fb0 = FALLBACKS.value("compile_wait")
        t0 = time.perf_counter()
        rows = runner.query(sql)
        wall = time.perf_counter() - t0
        assert rows == expected
        assert wall < 8.0, f"query did not dodge the 10s compile wall: {wall}"
        fired = {m for w in runner.workers for (m, _) in w.fault_injector.fired}
        assert "COMPILE_SLOW" in fired, "the injected fault never bit"
        assert FALLBACKS.value("compile_wait") >= fb0 + 1
    finally:
        runner.stop()


def test_chaos_compile_fail_completes_via_fallback(monkeypatch):
    """COMPILE_FAIL on every worker: queries succeed via fallback (typed
    compile_error attribution), and a clean re-run compiles normally."""
    import trino_tpu.exec.compilesvc as compilesvc

    monkeypatch.setattr(compilesvc, "SERVICE", CompileService())
    sql = "select k, max(v) - min(v) as d from t group by k order by k"
    runner, k, v = _cluster(seed=13)
    expected = [
        (int(key), int(v[k == key].max() - v[k == key].min()))
        for key in sorted(set(k.tolist()))
    ]
    try:
        for i in range(len(runner.workers)):
            runner.inject_task_failure(
                worker_index=i, mode="COMPILE_FAIL", count=10
            )
        fb0 = FALLBACKS.value("compile_error")
        t0 = time.perf_counter()
        rows = runner.query(sql)
        wall = time.perf_counter() - t0
        assert rows == expected
        assert wall < 30.0, "query hung on failing compiles"
        assert FALLBACKS.value("compile_error") >= fb0 + 1
        fired = {m for w in runner.workers for (m, _) in w.fault_injector.fired}
        assert "COMPILE_FAIL" in fired
        # faults disarmed: the same query compiles and matches again
        for w in runner.workers:
            w.fault_injector.clear()
        assert runner.query(sql) == expected
    finally:
        runner.stop()


def test_chaos_harness_arms_compile_modes():
    """ChaosRunner determinism: COMPILE_MODES ride the seeded schedule with
    a delay for COMPILE_SLOW, without perturbing existing mode tuples."""
    from trino_tpu.testing.chaos import (
        COMPILE_MODES, CORRUPTION_MODES, RECOVERABLE_MODES,
    )

    assert COMPILE_MODES == ("COMPILE_SLOW", "COMPILE_FAIL")
    # seeded-replay compatibility: existing tuples unchanged
    assert RECOVERABLE_MODES == ("ERROR", "TIMEOUT", "SLOW", "EXCHANGE_DROP")
    assert CORRUPTION_MODES == RECOVERABLE_MODES + ("CORRUPT",)
    assert set(COMPILE_MODES) <= set(FaultInjector.MODES)
