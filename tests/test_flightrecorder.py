"""Flight recorder, cross-node post-mortem bundles, anomaly sentinel.

Fast unit tests (tier-1): ring semantics, overflow drop accounting,
snapshot filters, baseline math and cold-start silence, bundle render,
metrics_lint drift directions.

Cluster drills (marked slow; `scripts/chaos_tier.sh postmortem`): the
worker-kill chaos drill producing a correlated multi-node bundle, the
seeded slow-query sentinel drill, bundle survival across a coordinator
restart, and the 2-thread QueryInfo race regression.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


# --------------------------------------------------------------- ring unit


def test_ring_overflow_drop_accounting():
    from trino_tpu.utils.flightrecorder import FlightRecorder

    fr = FlightRecorder(ring_size=32)
    for i in range(100):
        fr.record("tick", node="n1", query_id=f"q{i}")
    st = fr.stats()
    assert st["events"] == 100
    assert st["held"] == 32
    assert st["dropped"] == 68  # every overwrite counted, never silent
    snap = fr.snapshot()
    assert len(snap) == 32
    # the ring keeps the NEWEST events, in seq order
    assert [e["seq"] for e in snap] == list(range(69, 101))


def test_ring_disabled_records_nothing():
    from trino_tpu.utils.flightrecorder import FlightRecorder

    fr = FlightRecorder(ring_size=32, enabled=False)
    fr.record("tick", node="n1")
    assert fr.stats()["events"] == 0 and fr.snapshot() == []
    fr.configure(enabled=True)
    fr.record("tick", node="n1")
    assert fr.stats()["events"] == 1


def test_snapshot_filters_query_task_kind_node():
    from trino_tpu.utils.flightrecorder import FlightRecorder

    fr = FlightRecorder(ring_size=64)
    fr.record("task_start", node="w1", task_id="q_aa_f1_p0_t0")
    fr.record("task_start", node="w2", query_id="q_bb")
    fr.record("compile_done", node="compilesvc", task_id="q_aa_f1_p0_t0")
    fr.record("task_finish", node="w1", query_id="q_aa")
    # query filter matches the event's own query id OR the task-id prefix
    qa = fr.snapshot(query_id="q_aa")
    assert [e["kind"] for e in qa] == ["task_start", "compile_done", "task_finish"]
    assert fr.snapshot(query_id="q_aa", kinds=("task_finish",))[0]["node"] == "w1"
    assert {e["node"] for e in fr.snapshot(nodes=("w1",))} == {"w1"}
    assert len(fr.snapshot(query_id="q_aa", limit=1)) == 1


def test_ring_thread_safety_under_contention():
    from trino_tpu.utils.flightrecorder import FlightRecorder

    fr = FlightRecorder(ring_size=128)

    def hammer(n):
        for i in range(500):
            fr.record("tick", node=f"n{n}", query_id=f"q{i}")

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    st = fr.stats()
    assert st["events"] == 2000
    assert st["held"] == 128
    assert st["dropped"] == 2000 - 128
    seqs = [e["seq"] for e in fr.snapshot()]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)


# ------------------------------------------------------------ baseline unit


def _mk_store():
    from trino_tpu.runtime.history import QueryHistoryStore

    return QueryHistoryStore(capacity=50)


def _clean_run(qid, wall_ms, **kw):
    rec = {
        "query_id": qid, "state": "FINISHED", "planhash": "ph1",
        "wall_ms": wall_ms, "spill_ms": 0.0, "task_retries": 0,
        "compile_count": 2, "peak_memory_bytes": 1 << 20, "rows": 10,
        "anomalies": [],
    }
    rec.update(kw)
    return rec


def test_baseline_cold_start_stays_silent():
    store = _mk_store()
    store.record(_clean_run("q1", 100.0))
    store.record(_clean_run("q2", 110.0))
    # below min_samples: no baseline, so the sentinel cannot false-flag
    assert store.baseline("ph1", min_samples=3) is None
    assert store.baseline("", min_samples=1) is None


def test_baseline_math_and_sample_hygiene():
    store = _mk_store()
    for i, w in enumerate((100.0, 120.0, 140.0)):
        store.record(_clean_run(f"q{i}", w))
    # excluded: cached runs, FAILED runs, and runs already flagged —
    # one slow outlier must not drag the baseline up
    store.record(_clean_run("qc", 9000.0, cached=True))
    store.record(_clean_run("qf", 9000.0, state="FAILED"))
    store.record(
        _clean_run("qa", 9000.0, anomalies=[{"kind": "SLOW_VS_BASELINE"}])
    )
    base = store.baseline("ph1", min_samples=3)
    assert base["samples"] == 3
    assert base["wall_ms_p50"] == 120.0
    assert base["wall_ms_p95"] == 140.0
    assert base["retries_p50"] == 0
    assert base["compiles_p50"] == 2


# ------------------------------------------------------- report render unit


def test_postmortem_report_renders_lanes_and_highlights(tmp_path):
    sys.path.insert(0, SCRIPTS)
    import postmortem_report

    recs = [
        {"type": "header", "query_id": "q_x", "trigger": "failure",
         "state": "FAILED", "error": "boom", "events": 3,
         "anomalies": [{"kind": "RETRY_STORM", "task_retries": 4}],
         "nodes": ["http://c:1", "http://w:2"], "unreachable_nodes": ["http://w:3"]},
        {"type": "query_info", "phase_ledger": {"running_ms": 12.0}},
        {"type": "journal", "kind": "submit", "query_id": "q_x"},
        {"type": "event", "seq": 1, "kind": "task_dispatch",
         "node": "http://c:1", "query_id": "q_x", "ts": 10.0},
        {"type": "event", "seq": 2, "kind": "task_fail",
         "node": "http://w:2", "task_id": "q_x_f1_p0_t0", "ts": 10.5,
         "detail": {"error": "boom"}},
        {"type": "event", "seq": 3, "kind": "worker_dead",
         "node": "http://c:1", "ts": 10.6, "detail": {"worker": "http://w:3"}},
    ]
    bundle = tmp_path / "bundle.jsonl"
    bundle.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    out = postmortem_report.render(postmortem_report.load_bundle(str(bundle)))
    assert "POST-MORTEM  q_x" in out
    assert "anomaly: RETRY_STORM" in out
    assert "lane 0: http://c:1" in out and "lane 1: http://w:2" in out
    assert "unreachable, slice missing" in out  # the dead node is visible
    # failure events are highlighted with a leading '!'
    failures = [ln for ln in out.splitlines() if ln.startswith("!")]
    assert any("task_fail" in ln for ln in failures)
    assert any("worker_dead" in ln for ln in failures)
    # both lanes draw their own glyph column
    assert any("●│" in ln for ln in out.splitlines())
    assert any("│●" in ln for ln in out.splitlines())


# -------------------------------------------------------- metrics_lint unit


def test_metrics_lint_fails_both_drift_directions(tmp_path):
    sys.path.insert(0, SCRIPTS)
    import metrics_lint

    expo = tmp_path / "expo.txt"
    expo.write_text(
        "# HELP trino_tpu_documented_total fine\n"
        "# TYPE trino_tpu_documented_total counter\n"
        "trino_tpu_documented_total 1\n"
        "# HELP trino_tpu_surprise_total exposed but not in the README\n"
        "# TYPE trino_tpu_surprise_total counter\n"
        "trino_tpu_surprise_total 1\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "`trino_tpu_documented_total` and `trino_tpu_ghost_total` docs\n"
    )
    failures = metrics_lint.lint([str(expo)], str(readme))
    assert any("trino_tpu_ghost_total" in f and "README documents" in f
               for f in failures)
    assert any("trino_tpu_surprise_total" in f and "does not document" in f
               for f in failures)
    # fixing the README clears both
    readme.write_text("`trino_tpu_documented_total` `trino_tpu_surprise_total`\n")
    assert metrics_lint.lint([str(expo)], str(readme)) == []


# ----------------------------------------------------------- cluster drills


def _mk_cluster(tmpdir, num_workers=3, heartbeat=0.3, **kw):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(
        num_workers=num_workers, heartbeat_interval=heartbeat, **kw
    )
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    runner.coordinator.session.set("exchange_spool_dir", tmpdir)
    runner.coordinator.session.set("retry_policy", "TASK")
    runner.coordinator.session.set("result_cache_enabled", "false")
    return runner


def _post_json(url, body=b"{}", timeout=30):
    req = urllib.request.Request(url, data=body)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_kill_worker_postmortem_bundle(tpch_tiny, oracle):
    """The chaos drill: kill a worker mid-query under retry_policy=TASK —
    the query must still succeed, and the post-mortem bundle must contain
    one correlated timeline with the kill, the retry dispatch, and events
    from every involved node."""
    sys.path.insert(0, SCRIPTS)
    import postmortem_report

    from tests.oracle import assert_rows_equal

    sp = tempfile.mkdtemp(prefix="fr_pm_spool_")
    # heartbeat slower than the drill: the kill must NOT be detected
    # before dispatch, so the scheduler hits the dead URL and retries
    runner = _mk_cluster(sp, num_workers=3, heartbeat=1.0)
    try:
        from trino_tpu.utils import flightrecorder as _fr

        sql = (
            "select l_returnflag, sum(l_quantity) s, count(*) c "
            "from lineitem group by l_returnflag order by l_returnflag"
        )
        runner.query(sql)  # warm caches on all three workers
        n_dead0 = len(_fr.snapshot(kinds=("worker_dead",)))
        runner.workers[1].stop()
        got = runner.query(sql)
        assert_rows_equal(got, oracle.query(sql))
        qid = list(runner.coordinator.queries)[-1]

        # the heartbeat marks the killed worker dead within ~2 intervals;
        # the bundle pulls cluster-scoped worker_dead events into the
        # query's timeline, so wait for the transition before bundling
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(_fr.snapshot(kinds=("worker_dead",))) > n_dead0:
                break
            time.sleep(0.2)

        pm = _post_json(
            f"{runner.coordinator.url}/v1/query/{qid}/postmortem"
        )
        assert pm["trigger"] == "on_demand"
        assert os.path.exists(pm["path"])

        recs = postmortem_report.load_bundle(pm["path"])
        header = next(r for r in recs if r["type"] == "header")
        events = [r for r in recs if r["type"] == "event"]
        kinds = {e["kind"] for e in events}
        # the kill is in the timeline...
        assert "worker_dead" in kinds, kinds
        # ...so is the retry that routed around it...
        assert "task_retry" in kinds, kinds
        # ...and execution events from >= 2 distinct surviving nodes
        exec_nodes = {
            e["node"] for e in events
            if e["kind"] in ("task_start", "task_finish", "task_fail")
        }
        assert len(exec_nodes) >= 2, exec_nodes
        # every surviving node that ran tasks answered the fan-out
        assert len(header["nodes"]) >= 2
        out = postmortem_report.render(recs)
        assert "TIMELINE" in out and "worker_dead" in out
        assert "task_retry" in out
        # the rendered timeline is one merged, ordered view
        assert f"POST-MORTEM  {qid}" in out
    finally:
        runner.stop()


@pytest.mark.slow
def test_anomaly_sentinel_slow_vs_baseline(tpch_tiny):
    """Sentinel drill: >=3 clean runs build a baseline; a seeded slow
    re-run is flagged SLOW_VS_BASELINE in QueryInfo, history, /metrics,
    and the EXPLAIN ANALYZE footer; the next clean re-run is NOT flagged
    (the anomalous run never enters the baseline)."""
    sp = tempfile.mkdtemp(prefix="fr_sent_spool_")
    runner = _mk_cluster(sp, num_workers=2)
    try:
        coord = runner.coordinator
        sql = (
            "explain analyze select l_returnflag, sum(l_quantity) s "
            "from lineitem group by l_returnflag order by l_returnflag"
        )
        # one extra warm-up keeps the cold compile out of the p95
        for _ in range(4):
            runner.query(sql)
            qid = list(coord.queries)[-1]
            rec = coord.queries[qid]
            assert rec.get("anomalies") == [], (
                "clean/cold runs must never be flagged"
            )
        # seed the slowdown: every task on both workers sleeps first
        for i in range(2):
            runner.inject_task_failure(
                i, task_id="*", mode="SLOW", delay_ms=6000, count=10
            )
        rows = runner.query(sql)
        text = "\n".join(r[0] for r in rows)
        slow_qid = list(coord.queries)[-1]
        slow_rec = coord.queries[slow_qid]
        kinds = [a["kind"] for a in slow_rec.get("anomalies") or []]
        assert "SLOW_VS_BASELINE" in kinds, (kinds, text)
        # EXPLAIN ANALYZE footer
        assert "-- anomaly: SLOW_VS_BASELINE" in text, text
        # QueryInfo over the wire
        info = _get_json(f"{coord.url}/v1/query/{slow_qid}")
        assert any(
            a["kind"] == "SLOW_VS_BASELINE" for a in info["anomalies"]
        )
        # history record carries the anomaly (and is baseline-excluded)
        hist = coord.history.get(slow_qid)
        assert hist and any(
            a["kind"] == "SLOW_VS_BASELINE" for a in hist["anomalies"]
        )
        # the sentinel metric moved
        with urllib.request.urlopen(f"{coord.url}/metrics", timeout=5) as r:
            metrics = r.read().decode()
        assert (
            'trino_tpu_query_anomalies_total{kind="SLOW_VS_BASELINE"}'
            in metrics
        )
        # a flagged run auto-triggers a post-mortem bundle
        assert slow_rec.get("postmortem_path"), "anomaly must write a bundle"
        assert 'trino_tpu_postmortem_bundles_total{trigger="anomaly"}' in metrics

        # drain any unconsumed SLOW rules, then a clean re-run: NOT flagged
        for w in runner.workers:
            w.fault_injector.clear()
        runner.query(sql)
        clean_qid = list(coord.queries)[-1]
        assert coord.queries[clean_qid].get("anomalies") == [], (
            "clean re-run after a flagged one must not be flagged"
        )
    finally:
        runner.stop()


@pytest.mark.slow
def test_postmortem_bundle_survives_coordinator_restart(tpch_tiny):
    """The bundle lives in the spool, not coordinator memory: a restarted
    coordinator (same port, same spool dir) still serves it."""
    sp = tempfile.mkdtemp(prefix="fr_restart_spool_")
    runner = _mk_cluster(sp, num_workers=2)
    try:
        runner.query("select count(*) from orders")
        qid = list(runner.coordinator.queries)[-1]
        pm = _post_json(
            f"{runner.coordinator.url}/v1/query/{qid}/postmortem"
        )
        assert os.path.exists(pm["path"])

        port = runner.kill_coordinator()
        coord = runner.restart_coordinator(
            port, session={"exchange_spool_dir": sp}
        )
        blob = urllib.request.urlopen(
            f"{coord.url}/v1/query/{qid}/postmortem", timeout=10
        ).read().decode()
        header = json.loads(blob.splitlines()[0])
        assert header["type"] == "header" and header["query_id"] == qid
    finally:
        runner.stop()


@pytest.mark.slow
def test_query_info_concurrent_reads_during_run(tpch_tiny):
    """Regression for the stats-fold race discipline extended to the new
    anomalies/progress fields: two reader threads hammer /v1/query/{id}
    and /progress WHILE the query runs and folds stats — every response
    must parse and be internally consistent, no 500s, no torn dicts."""
    sp = tempfile.mkdtemp(prefix="fr_race_spool_")
    runner = _mk_cluster(sp, num_workers=2)
    try:
        coord = runner.coordinator
        # slow every task down so the readers overlap live execution
        for i in range(2):
            runner.inject_task_failure(
                i, task_id="*", mode="SLOW", delay_ms=800, count=10
            )
        before = set(coord.queries)
        errors: list[str] = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                new = [q for q in list(coord.queries) if q not in before]
                if not new:
                    time.sleep(0.01)
                    continue
                qid = new[-1]
                for path in (f"/v1/query/{qid}", f"/v1/query/{qid}/progress"):
                    try:
                        info = _get_json(f"{coord.url}{path}", timeout=10)
                    except urllib.error.HTTPError as e:
                        if e.code != 404:  # not yet registered is fine
                            errors.append(f"{path}: HTTP {e.code}")
                        continue
                    except Exception as e:  # noqa: BLE001
                        errors.append(f"{path}: {e}")
                        continue
                    if path.endswith("/progress"):
                        frac = info.get("fraction")
                        if frac is not None and not (0.0 <= frac <= 1.0):
                            errors.append(f"fraction out of range: {frac}")
                        for st in (info.get("stages") or {}).values():
                            if st["completed"] > st["total"]:
                                errors.append(f"torn stage: {st}")
                    else:
                        if not isinstance(info.get("anomalies", []), list):
                            errors.append("anomalies not a list")
                time.sleep(0.002)

        threads = [threading.Thread(target=reader) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            got = runner.query(
                "select l_returnflag, count(*) from lineitem "
                "group by l_returnflag"
            )
            assert len(got) == 3
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors, errors[:10]
        # after the run the progress endpoint reports completion
        qid = list(coord.queries)[-1]
        pg = _get_json(f"{coord.url}/v1/query/{qid}/progress")
        assert pg["fraction"] == 1.0 and pg["eta_s"] == 0.0
        assert pg["splits_completed"] == pg["splits_total"] > 0
    finally:
        runner.stop()
