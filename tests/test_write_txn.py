"""Transactional write plane (runtime/txn.py): staged commits, exactly-once
DML replay, write-conflict arbitration, and the staging janitor.

The acceptance drill: kill the coordinator at each write-phase boundary
(pre-stage / staged-uncommitted / committed-unacked) and assert the target
table is exactly the pre-image XOR the post-image — never torn — with
exactly-once application after restart replay.  Plus the two-writer
WRITE_CONFLICT arbitration drill and the DISK_FULL-during-staging abort
with janitor reclaim of orphaned staging bytes.
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.runtime.engine import Engine
from trino_tpu.runtime.failure import FaultInjector, InjectedCommitCrash
from trino_tpu.runtime.journal import QueryJournal
from trino_tpu.runtime.txn import (
    RECLAIMED_TOTAL, STAGING_BYTES, TXN_TOTAL, WriteConflict,
)
from trino_tpu.testing import DistributedQueryRunner

pytestmark = pytest.mark.smoke


# ---------------------------------------------------------------- fixtures


def _seed(conn, n: int = 5):
    conn.create_table("t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("t", {"k": np.arange(n, dtype=np.int64),
                      "v": np.arange(n, dtype=np.int64) * 10})


def _engine(conn=None):
    conn = conn if conn is not None else MemoryConnector()
    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", conn)
    return eng, conn


def _table_rows(conn):
    cols = conn.read_split(conn.get_splits("t", 1)[0], ["k", "v"])
    return sorted(zip(cols["k"].tolist(), cols["v"].tolist()))


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


def _wait_port_free(port, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = socket.socket()
        # match ThreadingHTTPServer's bind semantics: TIME_WAIT remnants of
        # accepted connections share the listener port and must not count
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind(("127.0.0.1", port))
            s.close()
            return
        except OSError:
            s.close()
            time.sleep(0.05)
    raise TimeoutError(f"port {port} never freed")


def _start_cluster(tmp_path, conn):
    runner = DistributedQueryRunner(
        num_workers=1, default_catalog="memory", heartbeat_interval=0.2,
        journal_path=str(tmp_path / "journal.jsonl"),
    )
    runner.register_catalog("memory", conn)
    runner.start()
    return runner


def _crash_write(runner, sql, phase):
    """Arm COMMIT_CRASH at `phase`, fire `sql`, wait for the simulated
    coordinator death (no abort, no terminal journal record)."""
    runner.inject_write_failure(phase=phase)
    coord = runner.coordinator

    def _go():
        try:
            coord.execute_query(sql)
        except Exception:
            pass  # the dying coordinator returns nothing useful

    threading.Thread(target=_go, daemon=True).start()
    assert _wait(lambda: coord._killed), "COMMIT_CRASH never fired"


# ------------------------------------------- engine-level phase boundaries


@pytest.mark.parametrize("phase", ["intent", "commit", "ack"])
def test_crash_leaves_pre_xor_post_image(phase):
    """At every phase boundary the table is exactly the pre-image XOR the
    post-image — staged data is invisible until the single commit point."""
    eng, conn = _engine()
    _seed(conn)
    eng.write_fault_injector = FaultInjector()
    pre = _table_rows(conn)
    post = sorted(pre + [(k + 100, v) for k, v in pre])
    eng.write_fault_injector.arm(task_id=f"{phase}:", mode="COMMIT_CRASH")
    with pytest.raises(InjectedCommitCrash):
        eng.execute("insert into t select k + 100, v from t")
    got = _table_rows(conn)
    if phase == "ack":
        assert got == post  # connector committed before the crash
    else:
        assert got == pre  # nothing leaked out of staging
    # crash means no abort ran: pre-commit phases leave an orphaned staging
    # namespace behind for replay/janitor reclaim
    orphans = conn.orphaned_staging()
    if phase in ("intent", "commit"):
        assert len(orphans) == 1
        txn_id = next(iter(orphans))
        assert conn.reclaim_staging(txn_id) >= 0
    assert conn.orphaned_staging() == {} or phase == "ack"
    assert _table_rows(conn) == (post if phase == "ack" else pre)


def test_write_stall_fault_delays_but_commits():
    eng, conn = _engine()
    _seed(conn)
    eng.write_fault_injector = FaultInjector()
    eng.write_fault_injector.arm(
        task_id="commit:", mode="WRITE_STALL", delay_ms=120
    )
    t0 = time.monotonic()
    eng.execute("insert into t values (99, 990)")
    assert time.monotonic() - t0 >= 0.1
    assert (99, 990) in _table_rows(conn)


def test_staging_gauge_drains_on_commit_and_abort():
    eng, conn = _engine()
    _seed(conn)
    base = STAGING_BYTES.value()
    eng.execute("insert into t values (7, 70)")
    assert STAGING_BYTES.value() == base  # committed: fully drained
    eng.write_fault_injector = FaultInjector()
    eng.write_fault_injector.arm(task_id="commit:", mode="COMMIT_CRASH")
    with pytest.raises(InjectedCommitCrash):
        eng.execute("insert into t values (8, 80)")
    # the crash skipped _settle: the orphan's bytes are still accounted
    # until reclaim (what the staging-bytes gauge is FOR)
    assert STAGING_BYTES.value() > base
    for txn_id in list(conn.orphaned_staging()):
        conn.reclaim_staging(txn_id)
    # reclaim frees connector-side staging; the global gauge drains when a
    # coordinator replay/janitor settles the txn — engine-level reclaim
    # only clamps it back on the next transaction's settle
    eng.execute("insert into t values (9, 90)")
    assert STAGING_BYTES.value() >= base


# ------------------------------------------------ coordinator crash replay


@pytest.mark.parametrize("phase", ["intent", "commit"])
def test_coordinator_crash_uncommitted_replays_to_clean_abort(tmp_path, phase):
    conn = MemoryConnector()
    _seed(conn)
    runner = _start_cluster(tmp_path, conn)
    try:
        runner.query("insert into t values (100, 1000)")
        pre = _table_rows(conn)
        aborted0 = TXN_TOTAL.value("aborted")
        _crash_write(runner, "insert into t select k + 200, v from t", phase)
        assert _table_rows(conn) == pre, "staged data leaked into the table"
        assert len(conn.orphaned_staging()) == 1
        port = runner.coordinator.port
        _wait_port_free(port)
        coord2 = runner.restart_coordinator(port=port)
        assert _wait(lambda: conn.orphaned_staging() == {}), \
            "replay never reclaimed the orphaned staging"
        assert _table_rows(conn) == pre, "abort replay mutated the table"
        assert _wait(lambda: TXN_TOTAL.value("aborted") == aborted0 + 1)
        assert _wait(lambda: all(
            rec["done"].is_set() for rec in coord2.queries.values()
        ))
        jq = QueryJournal.replay(str(tmp_path / "journal.jsonl"))
        crashed = [q for q in jq.values() if q.write_aborts]
        assert len(crashed) == 1
        assert crashed[0].state == "FAILED"
        assert crashed[0].error_code == "WRITE_ABORTED"
    finally:
        runner.stop()


def test_coordinator_crash_committed_unacked_replays_noop(tmp_path):
    conn = MemoryConnector()
    _seed(conn)
    runner = _start_cluster(tmp_path, conn)
    try:
        pre = _table_rows(conn)
        post = sorted(pre + [(k + 200, v) for k, v in pre])
        noop0 = TXN_TOTAL.value("replayed_noop")
        _crash_write(runner, "insert into t select k + 200, v from t", "ack")
        assert _table_rows(conn) == post, "commit landed before the crash"
        port = runner.coordinator.port
        _wait_port_free(port)
        coord2 = runner.restart_coordinator(port=port)
        assert _wait(lambda: TXN_TOTAL.value("replayed_noop") == noop0 + 1)
        # exactly once: replay applied NOTHING on top of the commit
        assert _table_rows(conn) == post
        assert conn.orphaned_staging() == {}
        jq = QueryJournal.replay(str(tmp_path / "journal.jsonl"))
        committed = [q for q in jq.values() if q.write_commits]
        assert committed and all(q.state == "FINISHED" for q in committed)
        # the recovered query answers with the committed row count
        qid = [qid for qid, q in jq.items()
               if q.write_commits and len(q.write_intents) == 1][-1]
        record = coord2.queries[qid]
        assert _wait(lambda: record["done"].is_set())
        assert record["result"] == [(len(pre),)]
    finally:
        runner.stop()


def test_ack_crash_journal_marker_lost_connector_marker_wins(tmp_path):
    """The coordinator can die between the connector commit and the journal
    fsync of the marker: connector state is truth, and replay must repair
    the journal instead of double-applying."""
    conn = MemoryConnector()
    _seed(conn)
    runner = _start_cluster(tmp_path, conn)
    try:
        pre = _table_rows(conn)
        post = sorted(pre + [(k + 300, v) for k, v in pre])
        _crash_write(runner, "insert into t select k + 300, v from t", "ack")
        assert _table_rows(conn) == post
        # simulate the marker never reaching the journal: rewrite the file
        # without its write_commit records
        import json
        jpath = str(tmp_path / "journal.jsonl")
        with open(jpath) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        with open(jpath, "w") as f:
            for r in recs:
                if r.get("kind") != "write_commit":
                    f.write(json.dumps(r) + "\n")
        port = runner.coordinator.port
        _wait_port_free(port)
        runner.restart_coordinator(port=port)
        assert _wait(
            lambda: any(
                q.write_commits
                for q in QueryJournal.replay(jpath).values()
            )
        ), "replay never repaired the journal from the connector marker"
        assert _table_rows(conn) == post, "double-applied a committed write"
    finally:
        runner.stop()


# ------------------------------------------------------ conflict arbitration


def test_two_writer_conflict_retries_then_wins():
    conn = MemoryConnector()
    _seed(conn)
    eng, _ = _engine(conn)
    rival, _ = _engine(conn)
    conflicts0 = TXN_TOTAL.value("conflict")
    fired = []

    class RacingConnector:
        pass

    # deterministic race: the rival commits between this writer's snapshot
    # and its commit, exactly once — hooked at stage time via a query that
    # triggers the rival from the attempt body
    from trino_tpu.runtime.txn import run_write

    def attempt(txn):
        if not fired:
            fired.append(1)
            rival.execute("insert into t values (999, 9990)")
        txn.stage_insert({"k": np.array([50], dtype=np.int64),
                          "v": np.array([500], dtype=np.int64)})
        return 1

    n = run_write(eng, "memory", "t", "insert", attempt)
    assert n == 1
    assert TXN_TOTAL.value("conflict") == conflicts0 + 1
    rows = _table_rows(conn)
    assert (50, 500) in rows and (999, 9990) in rows
    assert eng._last_txn_info["retries"] == 1
    assert eng._last_txn_info["outcome"] == "committed"


def test_conflict_budget_exhausted_raises_typed_error():
    conn = MemoryConnector()
    _seed(conn)
    eng, _ = _engine(conn)
    rival, _ = _engine(conn)
    eng.session.set("write_conflict_retries", "1")
    from trino_tpu.runtime.txn import run_write

    def always_racing(txn):
        rival.execute("insert into t values (777, 7770)")  # every attempt
        txn.stage_insert({"k": np.array([51], dtype=np.int64),
                          "v": np.array([510], dtype=np.int64)})
        return 1

    with pytest.raises(WriteConflict, match=r"\[WRITE_CONFLICT\]"):
        run_write(eng, "memory", "t", "insert", always_racing)
    assert (51, 510) not in _table_rows(conn), "loser's staging leaked"


# --------------------------------------------- cache invalidation ordering


class FailingApplyConnector(MemoryConnector):
    """Commit-time failure lever: the CAS passes but applying the staged
    data blows up — run_write must abort WITHOUT touching the caches."""

    def __init__(self):
        super().__init__()
        self.fail_next_apply = False

    def _apply_staged(self, handle):
        if self.fail_next_apply:
            self.fail_next_apply = False
            raise RuntimeError("injected apply failure")
        return super()._apply_staged(handle)


def test_failed_update_leaves_result_cache_warm(tmp_path):
    """Satellite regression: invalidate exactly once, at the commit point,
    never on abort — a failed UPDATE leaves the warm result-cache entry
    valid; the following successful UPDATE drops it."""
    conn = FailingApplyConnector()
    _seed(conn)
    runner = DistributedQueryRunner(
        num_workers=1, default_catalog="memory", heartbeat_interval=0.5,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        coord = runner.coordinator
        coord.session.set("result_cache_min_recurrences", "0")
        sql = "select sum(v) from t"
        want = runner.query(sql)
        assert coord.result_cache.entries_for_table("memory", "t") == 1
        # the failing UPDATE itself runs (and caches) helper SELECTs — the
        # regression is that no PRE-EXISTING entry gets dropped on abort
        keys0 = set(coord.result_cache._by_table.get("memory.t", ()))
        conn.fail_next_apply = True
        with pytest.raises(Exception, match="injected apply failure"):
            runner.query("update t set v = v + 1 where k = 1")
        assert keys0 <= set(coord.result_cache._by_table.get("memory.t", ())), \
            "abort must NOT invalidate the cache"
        assert _table_rows(conn) == sorted(
            (k, v) for k, v in zip(range(5), range(0, 50, 10))
        )
        assert runner.query(sql) == want  # warm entry still valid
        runner.query("update t set v = v + 1 where k = 1")
        assert coord.result_cache.entries_for_table("memory", "t") == 0, \
            "commit must invalidate the warm entry"
    finally:
        runner.stop()


# -------------------------------------------------- DISK_FULL and janitor


def test_disk_full_during_staging_aborts_clean(tmp_path):
    pytest.importorskip("pyarrow")
    from trino_tpu.connectors.parquet import ParquetConnector
    from trino_tpu.runtime.disk import DiskExceeded, NodeDiskPool

    conn = ParquetConnector(str(tmp_path / "wh"))
    _seed(conn, n=50)
    eng, _ = _engine(conn)
    pre = eng.execute("select k, v from t order by k")
    conn.disk_pool = NodeDiskPool(64, name="write-stage-test")
    conn.write_stage_timeout_s = 0.2
    aborted0 = TXN_TOTAL.value("aborted")
    with pytest.raises(DiskExceeded):
        eng.execute("insert into t select k + 100, v from t")
    assert TXN_TOTAL.value("aborted") == aborted0 + 1
    assert conn.orphaned_staging() == {}, "abort left staging behind"
    assert conn.disk_pool.reserved == 0, "abort leaked a disk lease"
    conn.disk_pool = None
    conn._invalidate("t")
    assert eng.execute("select k, v from t order by k") == pre


def test_janitor_reclaims_orphaned_staging(tmp_path):
    pytest.importorskip("pyarrow")
    from trino_tpu.connectors.parquet import ParquetConnector

    conn = ParquetConnector(str(tmp_path / "wh"))
    _seed(conn, n=10)
    # a writer that died without journal or abort: stage and walk away
    handle = conn.begin_write("t", "q_dead-w0", "insert")
    handle.stage_insert({"k": np.array([5], dtype=np.int64),
                         "v": np.array([55], dtype=np.int64)})
    assert list(conn.orphaned_staging()) == ["q_dead-w0"]
    runner = DistributedQueryRunner(
        num_workers=0, default_catalog="memory", heartbeat_interval=0.5,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        coord = runner.coordinator
        coord.session.set("write_staging_grace_s", "0.05")
        reclaimed0 = RECLAIMED_TOTAL.value()
        time.sleep(0.1)  # age past the grace window
        coord._gc_write_staging()
        assert conn.orphaned_staging() == {}
        assert RECLAIMED_TOTAL.value() > reclaimed0
        eng, _ = _engine(conn)
        assert len(eng.execute("select * from t")) == 10
    finally:
        runner.stop()


def test_janitor_spares_live_and_in_grace_staging(tmp_path):
    pytest.importorskip("pyarrow")
    from trino_tpu.connectors.parquet import ParquetConnector

    conn = ParquetConnector(str(tmp_path / "wh"))
    _seed(conn, n=3)
    handle = conn.begin_write("t", "q_young-w0", "insert")
    handle.stage_insert({"k": np.array([9], dtype=np.int64),
                         "v": np.array([99], dtype=np.int64)})
    runner = DistributedQueryRunner(
        num_workers=0, default_catalog="memory", heartbeat_interval=0.5,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        coord = runner.coordinator
        coord.session.set("write_staging_grace_s", "3600")
        coord._gc_write_staging()
        assert list(conn.orphaned_staging()) == ["q_young-w0"], \
            "janitor reclaimed staging inside the grace window"
        conn.abort_write(handle)
        assert conn.orphaned_staging() == {}
    finally:
        runner.stop()


# ---------------------------------------------------------- explain footer


def test_explain_analyze_write_txn_footer():
    eng, conn = _engine()
    _seed(conn)
    lines = [r[0] for r in eng.execute(
        "explain analyze insert into t select k + 10, v from t"
    )]
    txn_lines = [l for l in lines if l.startswith("-- txn:")]
    assert len(txn_lines) == 1
    footer = txn_lines[0]
    assert "outcome=committed" in footer
    assert "op=insert" in footer
    assert "table=memory.t" in footer
    # EXPLAIN ANALYZE really executed the write
    assert len(_table_rows(conn)) == 10
