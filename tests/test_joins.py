"""Outer-join differential tests vs the sqlite oracle (sqlite >= 3.39 has
RIGHT/FULL).  Reference semantics: LookupJoinOperator + LookupOuterOperator
(operator/join/) — unmatched probe rows null-extend the build columns and,
for FULL, unmatched build rows null-extend the probe columns exactly once,
even when the join is hash-partitioned across devices."""

import sqlite3

import pytest

from tests.oracle import assert_rows_equal

# the differential oracle needs sqlite >= 3.39 for RIGHT/FULL OUTER JOIN;
# on older runtimes those cases have no oracle to diff against
_HAS_FULL_JOIN = sqlite3.sqlite_version_info >= (3, 39)
_NEEDS_ORACLE_FULL = pytest.mark.skipif(
    not _HAS_FULL_JOIN,
    reason=f"sqlite {sqlite3.sqlite_version} lacks RIGHT/FULL OUTER JOIN",
)

QUERIES = {
    "full_basic": (
        "select n_name, r_name from nation full outer join region"
        " on n_regionkey = r_regionkey and r_regionkey < 3"
    ),
    "full_many": (
        "select c_custkey, s_suppkey from customer full outer join supplier"
        " on c_nationkey = s_nationkey and s_suppkey < 20 and c_custkey < 100"
    ),
    "full_aggregated": (
        "select count(*), count(c_custkey), count(s_suppkey) from customer"
        " full outer join supplier on c_nationkey = s_nationkey"
        " and s_suppkey % 7 = 0 and c_custkey % 11 = 0"
    ),
    "right_basic": (
        "select n_name, r_name from nation right join region"
        " on n_regionkey = r_regionkey and n_nationkey < 3"
    ),
    "right_outer_kw": (
        "select s_suppkey, n_name from supplier right outer join nation"
        " on s_nationkey = n_nationkey and s_suppkey < 10"
    ),
    "left_basic": (
        "select n_name, s_suppkey from nation left join supplier"
        " on n_nationkey = s_nationkey and s_suppkey < 5"
    ),
}


@pytest.fixture(scope="module")
def engine(tpch_tiny):
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng


@pytest.mark.parametrize(
    "name",
    [
        pytest.param(n, marks=_NEEDS_ORACLE_FULL)
        if n.startswith(("full", "right"))
        else n
        for n in sorted(QUERIES)
    ],
)
def test_outer_join(name, engine, oracle):
    sql = QUERIES[name]
    assert_rows_equal(engine.query(sql), oracle.query(sql), ordered=False)


@_NEEDS_ORACLE_FULL
def test_outer_join_distributed(oracle):
    import jax

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(distributed=True, devices=jax.devices()[:4])
    eng.register_catalog("tpch", TpchConnector(0.01))
    for name in ("full_many", "right_basic"):
        sql = QUERIES[name]
        assert_rows_equal(eng.query(sql), oracle.query(sql), ordered=False)
