"""Dynamic filtering tests (reference: DynamicFilterService +
BaseDynamicPartitionPruningTest): build-side domains prune probe scans
host-side before upload, without changing results."""

import numpy as np
import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.compiler import LocalExecutor
from trino_tpu.exec.dynfilter import ScanFilter, collect_dynamic_filters


def test_collect_from_fragmented_broadcast_join():
    """A broadcast join fragment (Join(scan…, RemoteSource)) yields a range
    filter on the probe scan column from the fetched build page."""
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.plan.distribute import distribute
    from trino_tpu.plan.fragmenter import fragment_plan
    from trino_tpu.plan.planner import Planner
    from trino_tpu.runtime.session import SessionProperties
    from trino_tpu.runtime.wire import page_to_wire_chunks, wire_to_page

    catalogs = CatalogManager()
    catalogs.register("tpch", TpchConnector(0.01))
    planner = Planner(catalogs, "tpch")
    plan = planner.plan(
        "select o_orderkey from orders, customer "
        "where o_custkey = c_custkey and c_acctbal > 9000"
    )
    dplan = distribute(plan, catalogs, 2, SessionProperties())
    frags = fragment_plan(dplan)
    # find the fragment with a RemoteSource-fed join
    from trino_tpu.plan.nodes import Join, RemoteSource

    target = None
    for f in frags:
        def joins(n):
            out = [n] if isinstance(n, Join) else []
            for c in n.children:
                out.extend(joins(c))
            return out

        for j in joins(f.root):
            if isinstance(j.right, RemoteSource):
                target = (f, j)
    assert target is not None, "expected a broadcast join fragment"
    f, j = target
    # execute the build fragment locally to get its page
    build_frag = next(fr for fr in frags if fr.id == j.right.fragment_id)
    ex = LocalExecutor(catalogs, "tpch")
    build_page = ex.execute(build_frag.root)
    blobs = page_to_wire_chunks(build_page)
    fetched = wire_to_page(blobs, list(build_frag.root.output_types))
    filters = collect_dynamic_filters(f.root, {build_frag.id: fetched})
    assert filters, "expected a dynamic filter on the probe scan"
    sf = next(iter(filters.values()))[0]
    assert sf.column == "o_custkey"
    assert sf.min <= sf.max


def test_scan_pruning_counts_and_correctness():
    """Executor-level: a range filter on the scan prunes rows host-side and
    results stay correct (the pruned rows could not have matched)."""
    catalogs_rows = TpchConnector(0.01)
    from trino_tpu.connectors.spi import CatalogManager
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", catalogs_rows)
    plan = eng.plan("select count(*), sum(o_totalprice) from orders where o_custkey <= 50")
    unfiltered = eng.executor.execute(plan).to_pylist()

    ex2 = Engine()
    ex2.register_catalog("tpch", TpchConnector(0.01))
    plan2 = ex2.plan("select count(*), sum(o_totalprice) from orders where o_custkey <= 50")
    from trino_tpu.exec.compiler import _node_ids
    from trino_tpu.plan.nodes import TableScan

    scan_id = next(
        i for i, n in _node_ids(plan2).items() if isinstance(n, TableScan)
    )
    ex2.executor.scan_filters = {scan_id: (ScanFilter("o_custkey", 1, 50),)}
    filtered = ex2.executor.execute(plan2).to_pylist()
    assert filtered == unfiltered
    assert ex2.executor.rows_pruned > 0


def test_multihost_query_with_dynamic_filtering(oracle):
    """End-to-end over the HTTP runtime: q03's broadcast customer build side
    prunes the orders scan on the workers; results match the oracle."""
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(num_workers=2)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        sql = QUERIES["q10"]
        got = runner.query(sql)
        assert_rows_equal(got, oracle.query(sql), ordered=ORDERED["q10"])
    finally:
        runner.stop()


def test_string_dictionary_set_filter():
    """TPC-DS-class star join keyed on a STRING: the build side's distinct
    dictionary values become a membership domain that prunes probe rows
    host-side (reference: DynamicFilterService discrete TupleDomain sets).
    rows_pruned > 0 on the string key, results unchanged."""
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import CatalogManager, ColumnSchema
    from trino_tpu.data.types import BIGINT, VARCHAR
    from trino_tpu.plan.distribute import distribute
    from trino_tpu.plan.fragmenter import fragment_plan
    from trino_tpu.plan.nodes import Join, RemoteSource
    from trino_tpu.plan.planner import Planner
    from trino_tpu.runtime.session import SessionProperties
    from trino_tpu.runtime.wire import page_to_wire_chunks, wire_to_page

    conn = MemoryConnector()
    # fact keyed by a string date-name; dim restricted to 2 of 20 names
    names = np.asarray([f"day_{i:02d}" for i in range(20)], dtype=object)
    conn.create_table("fact", [ColumnSchema("f_day", VARCHAR),
                               ColumnSchema("f_val", BIGINT)])
    rng = np.random.default_rng(3)
    conn.insert("fact", {"f_day": names[rng.integers(0, 20, 5000)],
                         "f_val": rng.integers(0, 100, 5000).astype(np.int64)})
    conn.create_table("dim", [ColumnSchema("d_day", VARCHAR),
                              ColumnSchema("d_keep", BIGINT)])
    conn.insert("dim", {"d_day": names,
                        "d_keep": (np.arange(20) < 2).astype(np.int64)})

    catalogs = CatalogManager()
    catalogs.register("mem", conn)
    planner = Planner(catalogs, "mem")
    sql = ("select sum(f_val) from fact, dim "
           "where f_day = d_day and d_keep = 1")
    plan = planner.plan(sql)
    dplan = distribute(plan, catalogs, 2, SessionProperties())
    frags = fragment_plan(dplan)

    target = None
    for f in frags:
        def joins(n):
            out = [n] if isinstance(n, Join) else []
            for c in n.children:
                out.extend(joins(c))
            return out

        for j in joins(f.root):
            if isinstance(j.right, RemoteSource):
                target = (f, j)
    assert target is not None, "expected a broadcast join fragment"
    f, j = target
    build_frag = next(fr for fr in frags if fr.id == j.right.fragment_id)
    ex = LocalExecutor(catalogs, "mem")
    build_page = ex.execute(build_frag.root)
    fetched = wire_to_page(
        page_to_wire_chunks(build_page), list(build_frag.root.output_types)
    )
    filters = collect_dynamic_filters(f.root, {build_frag.id: fetched})
    assert filters, "expected a string dynamic filter"
    sf = next(iter(filters.values()))[0]
    assert sf.column == "f_day" and sf.values is not None
    assert set(sf.values) == {"day_00", "day_01"}

    # execute the probe fragment with the filter: pruning + correct result
    ex2 = LocalExecutor(catalogs, "mem")
    ex2.scan_filters = filters
    page = ex2.execute(f.root, {build_frag.id: fetched})
    assert ex2.rows_pruned > 0, "string set domain never pruned"
    f_day = conn._data["fact"]["f_day"]
    f_val = conn._data["fact"]["f_val"]
    expect = int(f_val[np.isin(f_day, ["day_00", "day_01"])].sum())
    # the fragment may end in a partial aggregate; sum its outputs
    rows = page.to_pylist()
    got = sum(r[0] for r in rows if r[0] is not None)
    assert got == expect, (got, expect)
