"""Profiling & query-history plane: the compile profiler's per-signature
ledger (utils/profiler.py), the phase ledger on the query state machine,
the bounded persistent history store (runtime/history.py) with its
/v1/query surface and post-expiry fallback, and the perf-regression /
metrics-lint gates (scripts/perf_gate.py, scripts/metrics_lint.py)."""

import importlib.util
import json
import os
import urllib.error
import urllib.request

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runtime.history import QueryHistoryStore
from trino_tpu.runtime.statemachine import QueryStateMachine
from trino_tpu.testing import DistributedQueryRunner
from trino_tpu.utils.profiler import CompileProfiler

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------- history store


def test_history_ring_evicts_oldest_first():
    store = QueryHistoryStore(capacity=3)
    for i in range(5):
        store.record({"query_id": f"q{i}", "state": "FINISHED"})
    assert len(store) == 3
    assert store.get("q0") is None and store.get("q1") is None
    assert [r["query_id"] for r in store.list()] == ["q4", "q3", "q2"]


def test_history_merge_refreshes_ring_position():
    store = QueryHistoryStore(capacity=2)
    store.record({"query_id": "a", "state": "FINISHED"})
    store.record({"query_id": "b", "state": "FINISHED"})
    # merging 'a' makes it the freshest entry, so the next insert evicts 'b'
    store.record({"query_id": "a", "wall_s": 1.5})
    store.record({"query_id": "c", "state": "FAILED"})
    assert store.get("b") is None
    merged = store.get("a")
    assert merged["state"] == "FINISHED" and merged["wall_s"] == 1.5


def test_history_jsonl_restart_round_trip(tmp_path):
    path = str(tmp_path / "history.jsonl")
    store = QueryHistoryStore(capacity=10, path=path)
    store.record({"query_id": "q1", "state": "FINISHED", "wall_s": 0.5})
    store.record({"query_id": "q2", "state": "FAILED", "error": "boom"})
    store.record({"query_id": "q1", "rows": 42})  # later merge line
    with open(path, "a") as f:
        f.write('{"query_id": "torn')  # crash mid-append
    reborn = QueryHistoryStore(capacity=10, path=path)
    assert len(reborn) == 2
    q1 = reborn.get("q1")
    assert q1["state"] == "FINISHED" and q1["rows"] == 42
    assert reborn.get("q2")["error"] == "boom"


def test_history_list_filters_state_and_limit():
    store = QueryHistoryStore(capacity=10)
    for i in range(4):
        store.record({
            "query_id": f"q{i}",
            "state": "FAILED" if i % 2 else "FINISHED",
        })
    failed = store.list(state="failed")
    assert [r["query_id"] for r in failed] == ["q3", "q1"]
    assert len(store.list(limit=2)) == 2


def test_history_as_event_listener():
    from trino_tpu.runtime.events import QueryEvent

    store = QueryHistoryStore(capacity=10)
    store(QueryEvent(kind="created", query_id="q1", sql="select 1"))
    assert len(store) == 0  # only terminal events are recorded
    store(
        QueryEvent(
            kind="completed", query_id="q1", sql="select 1",
            wall_s=0.1, rows=1, cpu_ms=5.0,
        )
    )
    rec = store.get("q1")
    assert rec["state"] == "FINISHED" and rec["cpu_ms"] == 5.0


# ----------------------------------------------------------- phase ledger


def test_statemachine_phase_seconds():
    sm = QueryStateMachine("q")
    for s in ("PLANNING", "STARTING", "RUNNING", "FINISHING", "FINISHED"):
        sm.transition(s)
    phases = sm.phase_seconds()
    assert set(phases) == {
        "QUEUED", "PLANNING", "STARTING", "RUNNING", "FINISHING"
    }
    assert all(v >= 0.0 for v in phases.values())
    # terminal time does not accrue: the ledger sums to created->finished
    total = sum(phases.values())
    assert abs(total - (sm.finished_at - sm.created_at)) < 1e-6


# ------------------------------------------------------- compile profiler


def test_compile_profiler_hit_miss_counters():
    prof = CompileProfiler()
    prof.record_compile("sigA", 0.2, "miss", {"flops": 100.0})
    prof.record_compile("sigA", 0.05, "hit")
    prof.record_compile("sigB", 0.01, "uncached")
    prof.record_execute("sigA", 0.003)
    counts = prof.cache_counts()
    assert counts == {"hit": 1, "miss": 1, "uncached": 1}
    snap = prof.snapshot("sigA")
    assert snap["compiles"] == 2
    assert snap["cache"] == {"hit": 1, "miss": 1, "uncached": 0}
    assert snap["executes"] == 1 and snap["execute_s"] > 0
    assert snap["flops"] == 100.0
    full = prof.snapshot()
    assert set(full) == {"sigA", "sigB"}
    prof.reset()
    assert prof.snapshot() == {}


def test_signature_of_is_stable_and_distinguishes_caps():
    from trino_tpu.utils.profiler import signature_of

    eng_plan = _tiny_plan()
    a = signature_of(eng_plan, {1: 64})
    b = signature_of(eng_plan, {1: 64})
    c = signature_of(eng_plan, {1: 128})
    assert a == b  # deterministic across calls (sha1, not salted hash())
    assert a != c  # overflow-retry tier gets its own signature


def _tiny_plan():
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    return eng.plan("select count(*) from region")


def test_local_executor_records_compile_events():
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    assert eng.execute("select count(*) from region") == [(5,)]
    ev = eng.executor.compile_events
    assert ev, "cold execute must record a compile event"
    assert ev[0]["signature"] and ev[0]["compile_s"] > 0
    assert ev[0]["cache"] in ("hit", "miss", "uncached")
    # second run may recompile once (adaptive compaction tightens tiers);
    # after that the jit cache is steady — no new compile events
    eng.execute("select count(*) from region")
    n = len(eng.executor.compile_events)
    eng.execute("select count(*) from region")
    assert len(eng.executor.compile_events) == n
    assert eng.executor.last_compile_ms == 0.0
    assert eng.executor.last_execute_ms > 0.0


def test_local_explain_analyze_profile_footer():
    from trino_tpu.runtime.engine import Engine

    eng = Engine()
    eng.register_catalog("tpch", TpchConnector(0.01))
    rows = eng.execute("explain analyze select count(*) from nation")
    text = "\n".join(r[0] for r in rows)
    assert "-- phases: compile" in text
    assert "-- compile: " in text  # named jit signature attribution


# ------------------------------------------------------------- perf gate


def test_perf_gate_new_regression_fails():
    gate = _load_script("perf_gate")
    old = {"queries": {"q1": {"wall_s": 1.0}}, "warm_regressions": []}
    new = {
        "queries": {"q1": {"wall_s": 1.1}},
        "warm_regressions": [{"query": "q1", "warm_s": 300.0, "bound": 240.0}],
    }
    failures = gate.compare(old, new)
    assert len(failures) == 1 and "q1" in failures[0]
    # already-known regressions don't re-fail; missing old field == empty
    assert gate.compare(new, new) == []
    assert gate.compare({"queries": {}}, new)  # old predates the field


def test_perf_gate_wall_ratio():
    gate = _load_script("perf_gate")
    old = {"queries": {"q1": {"wall_s": 1.0}, "q2": {"wall_s": 0.001}}}
    new = {"queries": {"q1": {"wall_s": 2.0}, "q2": {"wall_s": 0.01}}}
    failures = gate.compare(old, new)
    # q1 doubled (gated); q2 is sub-50ms jitter (ignored)
    assert len(failures) == 1 and "q1" in failures[0]
    assert gate.compare(old, old) == []


@pytest.mark.skipif(
    not os.path.exists(os.path.join(_REPO, "BENCH_r04.json")),
    reason="bench artifacts not present",
)
def test_perf_gate_on_recorded_bench_runs():
    gate = _load_script("perf_gate")
    r04 = os.path.join(_REPO, "BENCH_r04.json")
    r05 = os.path.join(_REPO, "BENCH_r05.json")
    assert gate.main([r04, r05]) == 2  # r05 introduced the q03 regression
    assert gate.main([r04, r04]) == 0
    assert gate.main([r05, r05]) == 0  # known regression doesn't re-fail


# ----------------------------------------------------------- metrics lint


def test_metrics_lint_brace_expansion_and_help(tmp_path):
    mlint = _load_script("metrics_lint")
    assert sorted(mlint._expand("trino_tpu_x_{a,b}_total")) == [
        "trino_tpu_x_a_total", "trino_tpu_x_b_total",
    ]
    assert mlint._expand('trino_tpu_y_total{state="x"}') == ["trino_tpu_y_total"]
    readme = tmp_path / "README.md"
    readme.write_text("uses `trino_tpu_a_total` and `trino_tpu_{b,c}_total`")
    good = tmp_path / "good.prom"
    good.write_text(
        "# HELP trino_tpu_a_total a\n# TYPE trino_tpu_a_total counter\n"
        "# HELP trino_tpu_b_total b\n# TYPE trino_tpu_b_total counter\n"
        "# HELP trino_tpu_c_total c\n# TYPE trino_tpu_c_total counter\n"
    )
    assert mlint.lint([str(good)], str(readme)) == []
    bad = tmp_path / "bad.prom"
    bad.write_text(
        "# HELP trino_tpu_a_total\n# TYPE trino_tpu_a_total counter\n"
        "# HELP trino_tpu_b_total b\n# TYPE trino_tpu_b_total counter\n"
    )
    failures = mlint.lint([str(bad)], str(readme))
    assert any("no HELP" in f for f in failures)
    assert any("trino_tpu_c_total" in f for f in failures)


# ------------------------------------------------- cluster integration


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("hist") / "history.jsonl")
    runner = DistributedQueryRunner(num_workers=2)
    runner.register_catalog("tpch", TpchConnector(0.01))
    # the coordinator is built by start(); route its history to a temp file
    os.environ["TRINO_TPU_HISTORY_FILE"] = path
    try:
        runner.start()
    finally:
        os.environ.pop("TRINO_TPU_HISTORY_FILE", None)
    runner.history_path = path
    yield runner
    runner.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def test_query_listing_and_history_fallback_after_expiry(cluster):
    cluster.query("select count(*) from orders")
    coord = cluster.coordinator
    with coord._lock:
        qid = list(coord.queries)[-1]

    listing = _get(f"{coord.url}/v1/query")["queries"]
    assert any(q["query_id"] == qid and q["source"] == "live" for q in listing)

    info = _get(f"{coord.url}/v1/query/{qid}")
    ledger = info.get("phase_ledger") or {}
    assert "compiling_ms" in ledger and "executing_ms" in ledger
    assert ledger.get("queued_ms", -1.0) >= 0.0
    assert info.get("compile_signatures"), "expected named jit signatures"

    # expiry drops the live record; the endpoint falls back to history
    coord.expire_query(qid)
    with coord._lock:
        assert qid not in coord.queries
    info2 = _get(f"{coord.url}/v1/query/{qid}")
    assert info2["expired"] and info2["state"] == "FINISHED"
    assert info2.get("phase_ledger")
    listing2 = _get(f"{coord.url}/v1/query")["queries"]
    assert any(
        q["query_id"] == qid and q["source"] == "history" for q in listing2
    )
    # unknown ids still 404
    with pytest.raises(urllib.error.HTTPError):
        _get(f"{coord.url}/v1/query/q_nonexistent")


def test_history_survives_coordinator_restart(cluster):
    from trino_tpu.runtime.coordinator import Coordinator

    cluster.query("select count(*) from region")
    coord = cluster.coordinator
    with coord._lock:
        qid = list(coord.queries)[-1]
    # a second coordinator over the same JSONL replays the ring on boot
    reborn = Coordinator(
        coord.catalogs, coord.default_catalog,
        history_path=cluster.history_path,
    )
    rec = reborn.history.get(qid)
    assert rec is not None and rec["state"] == "FINISHED"
    assert rec.get("phase_ledger")


def test_distributed_analyze_shows_ledger_and_signatures(cluster):
    rows = cluster.query(
        "explain analyze select count(*) from lineitem where l_quantity < 10"
    )
    text = "\n".join(r[0] for r in rows)
    assert "-- phases: " in text
    assert "compiling" in text and "exchange_wait" in text
    assert "-- compile: " in text  # per-signature attribution


def test_ui_history_table(cluster):
    cluster.query("select count(*) from nation")
    with urllib.request.urlopen(f"{cluster.coordinator.url}/ui") as r:
        page = r.read().decode()
    assert "history (" in page
