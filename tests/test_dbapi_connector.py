"""DB-API connector framework (reference: plugin/trino-base-jdbc + derived
plugins): schema discovery through the driver, projection/row-range
pushdown, write path, joins against native catalogs."""

import pytest


@pytest.fixture()
def engine(tmp_path):
    from trino_tpu.connectors.dbapi import SqliteConnector
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    db = str(tmp_path / "ext.db")
    import sqlite3

    conn = sqlite3.connect(db)
    conn.execute("create table ext (k integer, v real, s text)")
    conn.executemany(
        "insert into ext values (?, ?, ?)",
        [(1, 1.5, "a"), (2, 2.5, "b"), (3, None, "c"), (4, 4.5, None)],
    )
    conn.commit()
    conn.close()

    eng = Engine(default_catalog="sqlite")
    eng.register_catalog("sqlite", SqliteConnector(db, splits_per_table=2))
    eng.register_catalog("memory", MemoryConnector())
    return eng


def test_schema_discovery(engine):
    assert engine.execute("show tables") == [("ext",)]
    assert engine.execute("describe ext") == [
        ("k", "bigint"), ("v", "double"), ("s", "varchar"),
    ]


def test_scan_with_nulls(engine):
    assert engine.execute("select k, v, s from ext order by k") == [
        (1, 1.5, "a"), (2, 2.5, "b"), (3, None, "c"), (4, 4.5, None),
    ]


def test_aggregate_over_dbapi(engine):
    assert engine.execute("select count(*), sum(v) from ext") == [(4, 8.5)]


def test_join_with_memory_catalog(engine):
    engine.execute("create table memory.dim (k bigint, name varchar)")
    engine.execute("insert into memory.dim values (1, 'one'), (3, 'three')")
    rows = engine.execute(
        "select e.k, d.name from ext e join memory.dim d on e.k = d.k order by e.k"
    )
    assert rows == [(1, "one"), (3, "three")]


def test_write_path(engine):
    engine.execute("create table out_t (k bigint, s varchar)")
    engine.execute("insert into out_t values (10, 'x'), (20, null)")
    assert engine.execute("select k, s from out_t order by k") == [
        (10, "x"), (20, None),
    ]
    # verify it actually landed in sqlite
    import sqlite3

    db = engine.catalogs.get("sqlite").database
    raw = sqlite3.connect(db).execute("select k, s from out_t order by k").fetchall()
    assert raw == [(10, "x"), (20, None)]


def test_dml_through_engine(engine):
    engine.execute("create table d (k bigint)")
    engine.execute("insert into d values (1), (2), (3)")
    # DELETE needs truncate support; DbApiConnector has none -> rewrite path
    # is unavailable, but sqlite-side data is still queryable
    assert engine.execute("select count(*) from d") == [(3,)]


def test_distributed_scan_splits(engine):
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    from trino_tpu.connectors.dbapi import SqliteConnector
    from trino_tpu.runtime.engine import Engine

    db = engine.catalogs.get("sqlite").database
    eng = Engine(default_catalog="sqlite", distributed=True)
    eng.register_catalog("sqlite", SqliteConnector(db, splits_per_table=2))
    assert eng.execute("select count(*), sum(k) from ext") == [(4, 10)]


def test_decimal_scaling(engine, tmp_path):
    import sqlite3

    from trino_tpu.connectors.dbapi import SqliteConnector

    db = str(tmp_path / "dec.db")
    c = sqlite3.connect(db)
    c.execute("create table m (price decimal(10,2))")
    c.execute("insert into m values (12.34)")
    c.commit()
    c.close()
    engine.register_catalog("sq", SqliteConnector(db))
    assert engine.execute("select price from sq.m") == [(12.34,)]
