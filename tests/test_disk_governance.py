"""Storage-pressure survivability (runtime/disk.py + the self-healing
spool): disk-pool lease accounting, the refresh -> reclaim -> block ->
typed-shed escalation, ENOSPC conversion at the single write gate, the
adopt-pin vs reclaim race, and the cluster-level chaos contracts —
DISK_FULL on one node rotates work away via task retry, SPOOL_LOST on a
committed partition drives a producer REPRODUCTION under
first-commit-wins, and neither ever surfaces to the client.

Fast unit tests run in tier-1; the cluster drills are slow+chaos and run
via `scripts/chaos_tier.sh disk` (CHAOS_SF cranks the at-scale drill).
"""

import os
import threading
import time

import numpy as np
import pytest

from trino_tpu.runtime.disk import (
    EXCEEDED_SPILL_LIMIT,
    DiskExceeded,
    NodeDiskPool,
    guarded_write,
)
from trino_tpu.runtime.spool import SpooledExchange, _pin, _unpin


# ---------------------------------------------------------------- helpers


def _wait(pred, timeout=30.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return bool(pred())


# ------------------------------------------------- disk pool lease plane


def test_reserve_release_accounting():
    pool = NodeDiskPool(100)
    a = pool.reserve("q1_t0", 40)
    b = pool.reserve("q1_t1", 30)
    assert pool.reserved == 70 and pool.peak == 70
    a.release()
    a.release()  # idempotent: finish and delete may both release
    assert pool.reserved == 30
    b.release()
    assert pool.reserved == 0 and pool.peak == 70


def test_oversized_reservation_sheds_typed():
    pool = NodeDiskPool(100)
    with pytest.raises(DiskExceeded) as ei:
        pool.reserve("q1_t0", 101, timeout_s=5.0)
    assert EXCEEDED_SPILL_LIMIT in str(ei.value)
    assert pool.sheds == 1
    assert pool.reserved == 0  # nothing leaked


def test_block_until_peer_release():
    pool = NodeDiskPool(100)
    held = pool.reserve("q1_t0", 80)
    threading.Timer(0.2, held.release).start()
    t0 = time.monotonic()
    lease = pool.reserve("q2_t0", 60, timeout_s=10.0)
    assert time.monotonic() - t0 >= 0.1  # it actually parked
    assert pool.blocked_ms_total > 0
    assert pool.reserved == 60
    lease.release()


def test_blocked_timeout_sheds_typed():
    pool = NodeDiskPool(100)
    pool.reserve("q1_t0", 80)
    with pytest.raises(DiskExceeded) as ei:
        pool.reserve("q2_t0", 60, timeout_s=0.2)
    assert "disk_blocked_timeout_s exceeded" in str(ei.value)
    assert EXCEEDED_SPILL_LIMIT in str(ei.value)


def test_refresh_harvests_deleted_path_leases(tmp_path):
    """A lease whose backing path another actor deleted (spool GC,
    remove_query, consumer ack) returns its bytes at the next pressure
    event — no cross-actor release plumbing."""
    pool = NodeDiskPool(100)
    gone = tmp_path / "q1_t0"
    gone.write_bytes(b"x" * 10)
    pool.reserve("q1_t0", 90, path=str(gone))
    os.remove(gone)  # out-of-band deletion
    # full pool, but refresh harvests the dead lease instead of blocking
    lease = pool.reserve("q2_t0", 50, timeout_s=5.0)
    assert pool.reserved == 50
    lease.release()


def test_release_prefix_frees_only_that_query(tmp_path):
    pool = NodeDiskPool(100)
    pool.reserve("q1_a0_f0_p0_t0", 30)
    pool.reserve("q1_a0_f1_p0_t1", 30)
    keep = pool.reserve("q2_a0_f0_p0_t0", 30)
    assert pool.release_prefix("q1") == 60
    assert pool.reserved == 30
    keep.release()


def test_set_capacity_shrink_and_grow():
    pool = NodeDiskPool(100)
    pool.reserve("q1_t0", 50)
    pool.set_capacity(40)  # DISK_FULL chaos: below current reservations
    with pytest.raises(DiskExceeded):
        pool.reserve("q2_t0", 10, timeout_s=0.2)
    got: list = []

    def blocked_writer():
        got.append(pool.reserve("q2_t0", 10, timeout_s=30.0))

    th = threading.Thread(target=blocked_writer, daemon=True)
    th.start()
    assert _wait(lambda: pool.blocked == 1, timeout=5.0)
    pool.set_capacity(100)  # growing wakes the parked writer
    th.join(timeout=5.0)
    assert got and pool.reserved == 60


def test_guarded_write_converts_enospc(tmp_path, monkeypatch):
    import builtins
    import errno

    path = str(tmp_path / "chunk.bin")
    assert guarded_write(path, b"abc") == 3  # the happy path writes

    real_open = builtins.open

    def full_disk(p, *a, **k):
        if str(p) == path:
            raise OSError(errno.ENOSPC, "No space left on device")
        return real_open(p, *a, **k)

    monkeypatch.setattr(builtins, "open", full_disk)
    with pytest.raises(DiskExceeded) as ei:
        guarded_write(path, b"abcdef")
    assert "ENOSPC" in str(ei.value) and EXCEEDED_SPILL_LIMIT in str(ei.value)
    monkeypatch.undo()
    assert not os.path.exists(path)  # the partial file was removed


# ------------------------------------- pressure reclaim escalation order


def _committed(spool, task_id, nbytes):
    assert spool.commit_task(task_id, {0: [b"x" * nbytes]})
    return os.path.join(spool.dir, task_id)


def test_reclaim_evicts_memo_before_nonlive_never_live(tmp_path):
    """The escalation a full pool runs before any writer blocks: fragment
    memo namespaces first (a cache), then non-live query dirs — and a
    LIVE query's dirs are untouchable no matter the pressure."""
    d = str(tmp_path / "spool")
    spool = SpooledExchange(d)
    memo = _committed(spool, "memo_k1_p0", 40)
    dead = _committed(spool, "dead_a0_f0_p0_t0", 40)
    live = _committed(spool, "live_a0_f0_p0_t0", 40)
    os.utime(memo, (1, 1))  # oldest; deterministic eviction order
    os.utime(dead, (2, 2))

    freed = spool.reclaim(10, live_query_ids=["live"])
    assert freed >= 40
    assert not os.path.exists(memo)  # memo evicted FIRST...
    assert os.path.exists(dead)  # ...and nothing more than needed

    freed = spool.reclaim(10, live_query_ids=["live"])
    assert freed >= 40
    assert not os.path.exists(dead)  # escalated to non-live dirs

    assert spool.reclaim(10, live_query_ids=["live"]) == 0
    assert os.path.exists(live)  # live is never evictable


def test_worker_side_reclaim_stops_after_memo(tmp_path):
    """A worker cannot know fleet-wide liveness, so its reclaim call
    (live_query_ids=None) must stop after memo namespaces."""
    d = str(tmp_path / "spool")
    spool = SpooledExchange(d)
    memo = _committed(spool, "memo_k1_p0", 40)
    q = _committed(spool, "q_a0_f0_p0_t0", 40)
    assert spool.reclaim(1000, live_query_ids=None) >= 40
    assert not os.path.exists(memo)
    assert os.path.exists(q)  # only the coordinator may evict query dirs


def test_pool_reclaimer_escalation_frees_a_blocked_commit(tmp_path):
    """End-to-end: a commit against a FULL pool runs the spool's reclaim
    (memo eviction), the refresh pass harvests the evicted dirs' leases,
    and the commit lands — no block, no shed."""
    d = str(tmp_path / "spool")
    pool = NodeDiskPool(100)
    spool = SpooledExchange(d, disk_pool=pool)
    spool.disk_blocked_timeout_s = 5.0
    assert spool.commit_task("memo_k1_p0", {0: [b"x" * 80]})
    assert pool.reserved == 80
    # pool is near-full; the next commit's reserve must evict the memo
    assert spool.commit_task("q_a0_f0_p0_t0", {0: [b"y" * 60]})
    assert not os.path.exists(os.path.join(d, "memo_k1_p0"))
    assert os.path.exists(os.path.join(d, "q_a0_f0_p0_t0", "COMMITTED"))
    assert pool.reserved == 60


def test_adopt_pin_blocks_reclaim_and_gc(tmp_path):
    """Race regression: a spool dir mid-adoption (a fleet peer renaming a
    dead coordinator's task output to its own query id) is PINNED — a
    concurrent pressure reclaim or gc sweeping 'non-live' dirs must skip
    it, else the adopter re-reads a deleted partition."""
    d = str(tmp_path / "spool")
    spool = SpooledExchange(d)
    path = _committed(spool, "orphan_a0_f0_p0_t0", 40)
    _pin(d, "orphan_a0_f0_p0_t0")
    try:
        # neither pressure reclaim nor the age-based sweep may touch it
        assert spool.reclaim(1000, live_query_ids=[]) == 0
        spool.gc([], age_s=0.0)
        assert os.path.exists(path)
    finally:
        _unpin(d, "orphan_a0_f0_p0_t0")
    # unpinned, the same pressure call evicts it
    assert spool.reclaim(1000, live_query_ids=[]) >= 40
    assert not os.path.exists(path)


def test_adopt_itself_pins_across_the_rename(tmp_path):
    """The public adopt() path pins old+new names for the rename window
    and unpins after — the dir survives under its new name."""
    d = str(tmp_path / "spool")
    spool = SpooledExchange(d)
    _committed(spool, "dead_a0_f0_p0_t0", 40)
    assert spool.adopt("dead_a0_f0_p0_t0", "heir_a0_f0_p0_t0")
    assert spool.is_committed("heir_a0_f0_p0_t0")
    # pins were released: the adopted dir is evictable once non-live again
    assert spool.reclaim(1000, live_query_ids=[]) >= 40


# ------------------------------------------------------- cluster contracts


def _mem_catalog(rows=20000, groups=50):
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT

    conn = MemoryConnector()
    conn.create_table(
        "t", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    rng = np.random.default_rng(7)
    conn.insert("t", {
        "k": rng.integers(0, groups, rows).astype(np.int64),
        "v": rng.integers(0, 100, rows).astype(np.int64),
    })
    return conn


def _storage_cluster(tmp_path, disk_budget_bytes=64 << 20, workers=2):
    from trino_tpu.testing import DistributedQueryRunner

    runner = DistributedQueryRunner(
        num_workers=workers,
        default_catalog="mem",
        heartbeat_interval=0.2,
        disk_budget_bytes=disk_budget_bytes,
    )
    runner.register_catalog("mem", _mem_catalog())
    runner.start()
    s = runner.coordinator.session
    s.set("retry_policy", "TASK")
    s.set("exchange_spool_dir", str(tmp_path / "spool"))
    # repeated identical SQL must actually RE-RUN (the drills below run
    # the same query clean-then-chaotic and need fresh spool commits)
    s.set("result_cache_enabled", "false")
    for w in runner.workers:
        w.disk_blocked_timeout_s = 0.5  # fast block->shed in tests
    return runner


SQL = "select k, sum(v) from t group by k order by k"


@pytest.mark.slow
@pytest.mark.chaos
def test_gc_pressure_reclaim_respects_fleet_live_union(tmp_path):
    """The coordinator's heartbeat-driven pressure reclaim passes the
    LOCAL ∪ FLEET live set: a PEER coordinator's running query — live
    only in the fleet lease files — must survive the sweep while a
    dead query's dirs are evicted."""
    runner = _storage_cluster(tmp_path)
    try:
        coord = runner.coordinator
        d = str(tmp_path / "spool")
        spool = SpooledExchange(d)
        peer = _committed(spool, "peer_a0_f0_p0_t0", 40)
        dead = _committed(spool, "dead_a0_f0_p0_t0", 40)

        class FakeFleet:
            def is_gc_owner(self):
                return True

            def fleet_live_queries(self):
                return {"peer"}  # live on a PEER member only

        coord.fleet = FakeFleet()
        try:
            # fake a pressure heartbeat: one node's pool is >80% used
            w = next(iter(coord.workers.values()))
            w.disk = {"capacity": 100, "reserved": 95}
            coord._gc_spool()
        finally:
            coord.fleet = None
        assert os.path.exists(peer), "evicted a fleet-live query's spool"
        assert not os.path.exists(dead), "pressure reclaim never ran"
    finally:
        runner.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_disk_full_one_node_query_survives(tmp_path):
    """DISK_FULL shrinks one worker's pool mid-run: every spool commit
    there reclaims, blocks 0.5s, then sheds typed — task retry rotates
    the attempts to the healthy node and the CLIENT sees only rows."""
    runner = _storage_cluster(tmp_path)
    try:
        clean = runner.query(SQL)
        runner.disk_full(0, capacity_bytes=64)  # far below any commit
        assert runner.query(SQL) == clean
        pool = runner.workers[0].disk_pool
        assert pool.sheds >= 1, "the shrunk pool never actually shed"
        # the typed error stayed inside the retry loop: the record shows a
        # finished query, not a failure
        rec = list(runner.coordinator.queries.values())[-1]
        assert rec["sm"].state == "FINISHED"
    finally:
        runner.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_spool_lost_drives_reproduction(tmp_path):
    """SPOOL_LOST deletes committed partitions right before consumers
    read them; the coordinator parses the typed marker, re-runs each
    producer under first-commit-wins, and the query succeeds with
    spool_reproductions > 0 (the self-healing metric)."""
    runner = _storage_cluster(tmp_path)
    try:
        clean = runner.query(SQL)
        before = runner.coordinator._m_spool_repro.value()
        for i in range(len(runner.workers)):
            runner.inject_task_failure(i, mode="SPOOL_LOST")
        assert runner.query(SQL) == clean
        rec = list(runner.coordinator.queries.values())[-1]
        repro = rec.get("spool_reproductions", 0)
        assert repro >= 1, "no producer was ever reproduced"
        limit = int(runner.coordinator.session.get("spool_reproduce_limit"))
        assert repro <= limit, f"reproductions {repro} exceeded the bound"
        assert runner.coordinator._m_spool_repro.value() - before == repro
    finally:
        for w in runner.workers:
            w.fault_injector.clear()
        runner.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_spool_lost_out_of_band_deletion_heals(tmp_path, monkeypatch):
    """No injector at all: an operator (or a dying disk) rm -rf's a
    committed partition the instant it lands — the consumer (or root)
    fetch hits the hole and the coordinator reproduces the producer.
    The deletion rides a commit hook rather than a polling thread so the
    drill bites deterministically (a 20k-row query commits and cleans up
    faster than any filesystem poller can observe)."""
    import shutil

    runner = _storage_cluster(tmp_path)
    try:
        clean = runner.query(SQL)
        spool_dir = str(tmp_path / "spool")
        victim: list = []
        lock = threading.Lock()
        orig_commit = SpooledExchange.commit_task

        def commit_then_reap(self, task_id, buffers, attempt="0"):
            out = orig_commit(self, task_id, buffers, attempt=attempt)
            with lock:
                first = not victim
                if first:
                    victim.append(task_id)
            if first:
                # out-of-band: straight rm -rf on the committed dir, no
                # injector — the reproduced attempt re-commits unmolested
                shutil.rmtree(
                    os.path.join(spool_dir, task_id), ignore_errors=True
                )
            return out

        monkeypatch.setattr(SpooledExchange, "commit_task", commit_then_reap)
        rows = runner.query(SQL)
        assert rows == clean
        assert victim, "nothing ever committed — the drill never bit"
        rec = list(runner.coordinator.queries.values())[-1]
        assert rec["sm"].state == "FINISHED"
    finally:
        runner.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_storage_chaos_drill_tpch(tmp_path, tpch_tiny, oracle):
    """The acceptance drill: TPC-H under seeded schedules drawn from
    RECOVERABLE + STORAGE modes with split_driven_scans on — SPOOL_LOST
    and DISK_FULL both fire across the run, results stay
    oracle-identical, zero client-visible failures, and
    spool_reproductions_total moved.  CHAOS_SF cranks the data scale
    (CI runs the tiny tier; the sf10 bar runs on big hosts)."""
    from tests.oracle import assert_rows_equal
    from tests.tpch_queries import ORDERED, QUERIES
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.testing.chaos import (
        RECOVERABLE_MODES,
        STORAGE_MODES,
        make_chaos_cluster,
    )

    sf = float(os.environ.get("CHAOS_SF", "0.01"))
    if sf != 0.01:
        # at-scale run: the session oracle holds sf0.01 — rebuild it over
        # the same generated data at the requested scale
        from tests.oracle import SqliteOracle
        from trino_tpu.connectors.tpch import tpch_data
        from trino_tpu.connectors.tpch.generator import TPCH_SCHEMAS

        oracle = SqliteOracle({t: tpch_data(t, sf) for t in TPCH_SCHEMAS})
    budget = 256 << 20
    runner, chaos = make_chaos_cluster(
        lambda: TpchConnector(sf), num_workers=2, seed=4242,
        modes=RECOVERABLE_MODES + STORAGE_MODES,
        disk_budget_bytes=budget,
    )
    s = runner.coordinator.session
    s.set("exchange_spool_dir", str(tmp_path / "spool"))
    s.set("split_driven_scans", "true")
    for w in runner.workers:
        w.disk_blocked_timeout_s = 0.5
    try:
        before = runner.coordinator._m_spool_repro.value()
        for name in ("q01", "q03", "q06", "q13"):
            sql = QUERIES[name]
            # guarantee the storage modes bite at least once per query on
            # top of whatever the seeded schedule draws
            runner.inject_task_failure(0, mode="SPOOL_LOST")
            runner.disk_full(1, capacity_bytes=1 << 20)
            got = chaos.run_query(sql)
            assert_rows_equal(got, oracle.query(sql), ordered=ORDERED[name])
            for w in runner.workers:  # DISK_FULL shrink persists; reset
                if w.disk_pool is not None:
                    w.disk_pool.set_capacity(budget)
        assert runner.coordinator._m_spool_repro.value() > before, (
            "SPOOL_LOST fired but nothing was ever reproduced"
        )
    finally:
        runner.stop()
