"""Cluster memory governance: node pools, blocked-on-memory, revocation,
low-memory killer — plus end-to-end page integrity.

Reference behaviors being matched:
- memory/ClusterMemoryManager.java:92 + TotalReservationLowMemoryKiller: a
  node over budget past the killer delay loses the query with the largest
  cluster-wide total reservation, with a typed CLUSTER_OUT_OF_MEMORY error.
- lib/trino-memory-context LocalMemoryContext.java:31: setBytes against a
  full pool returns a non-immediate future — the task parks BLOCKED and
  resumes when a peer frees bytes.
- Revocable memory + spill: before killing anything, revocable leases are
  force-spilled (the worker honors the shrunken lease with sliced
  out-of-core execution, exec/spill.py's idiom) so both queries finish.
- serde/PagesSerdeUtil page checksums: every wire chunk carries a crc32
  frame; a flipped bit anywhere surfaces as PAGE_TRANSPORT_ERROR and the
  fetch retries from its token instead of producing wrong rows.
"""

import threading
import time

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.runtime import memory as memory_mod
from trino_tpu.runtime.memory import (
    ClusterMemoryManager,
    MemoryExceeded,
    NodeMemoryPool,
    QueryMemoryPool,
)
from trino_tpu.runtime.spool import SpooledExchange
from trino_tpu.runtime.wire import (
    FRAME_MAGIC,
    PageTransportError,
    frame_chunk,
    unframe_chunk,
)
from trino_tpu.testing import DistributedQueryRunner

pytestmark = pytest.mark.smoke


@pytest.fixture(autouse=True)
def _whole_scan_tasks(monkeypatch):
    """This file asserts the classic whole-scan memory plane: eager
    round-robin task dispatch (every worker gets a task whose lease the
    asserts watch) and REVOKE_SPILL_PARTS sliced re-execution under a
    revoked lease.  split_driven_scans — ON by default since the
    storage-governance release — replaces both with morsel scheduling
    (lazy least-loaded placement, parked revocations), whose memory
    interactions tests/test_splits.py covers.  Pin the classic path."""
    import dataclasses

    from trino_tpu.runtime import session as session_mod

    monkeypatch.setitem(
        session_mod.PROPERTIES,
        "split_driven_scans",
        dataclasses.replace(
            session_mod.PROPERTIES["split_driven_scans"], default=False
        ),
    )


def _wait(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            return False
        time.sleep(interval)
    return True


# ------------------------------------------------------ page integrity (unit)


def test_frame_roundtrip_and_corruption_detection():
    blob = b"some serialized page bytes" * 17
    framed = frame_chunk(blob)
    assert framed[:4] == FRAME_MAGIC
    assert unframe_chunk(framed) == blob

    # flip one payload byte: crc must catch it, with the typed error code
    mut = bytearray(framed)
    mut[len(mut) // 2] ^= 0xFF
    with pytest.raises(PageTransportError, match=r"\[PAGE_TRANSPORT_ERROR\]"):
        unframe_chunk(bytes(mut))

    # flip a checksum byte: same
    mut = bytearray(framed)
    mut[5] ^= 0x01
    with pytest.raises(PageTransportError):
        unframe_chunk(bytes(mut))

    # truncated / foreign bytes are rejected, not misread
    with pytest.raises(PageTransportError):
        unframe_chunk(framed[:6])
    with pytest.raises(PageTransportError):
        unframe_chunk(b"XXXX" + framed[4:])


def test_spool_read_verifies_frame(tmp_path):
    """Silent disk corruption of a committed spool chunk surfaces as a typed
    PAGE_TRANSPORT_ERROR at read time, never as wrong rows."""
    spool = SpooledExchange(str(tmp_path))
    good = frame_chunk(b"payload bytes for buffer zero" * 9)
    assert spool.commit_task("q1_a0_f0_t0", {0: [good]})
    assert spool.read_chunks("q1_a0_f0_t0", 0) == [good]

    path = spool.chunk_path("q1_a0_f0_t0", 0, 0)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(PageTransportError, match="spool chunk"):
        spool.read_chunks("q1_a0_f0_t0", 0)


# -------------------------------------------------------- node pool (unit)


def test_blocked_reserve_unblocks_on_peer_free():
    pool = NodeMemoryPool(1000)
    a = pool.reserve("qa", 800)
    got = {}
    blocked_seen = threading.Event()

    def second():
        got["lease"] = pool.reserve(
            "qb", 500, on_block=lambda: blocked_seen.set()
        )

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert blocked_seen.wait(5), "second reservation never parked"
    assert _wait(lambda: pool.blocked == 1, 5)
    assert "lease" not in got  # genuinely parked, not failed

    a.release()  # peer frees -> waiter resumes
    t.join(timeout=10)
    assert not t.is_alive(), "blocked reservation never resumed"
    assert pool.blocked == 0
    assert pool.reserved == 500
    assert pool.blocked_ms_total > 0  # the wait was measured
    got["lease"].release()
    assert pool.reserved == 0


def test_blocked_reserve_timeout_escalates():
    pool = NodeMemoryPool(100)
    hold = pool.reserve("qa", 100)
    with pytest.raises(MemoryExceeded, match="memory_blocked_timeout_s"):
        pool.reserve("qb", 50, timeout_s=0.15)
    hold.release()


def test_reserve_larger_than_pool_fails_fast():
    pool = NodeMemoryPool(100)
    # waiting can never succeed: no timeout needed, immediate escalation
    with pytest.raises(MemoryExceeded):
        pool.reserve("qa", 101, timeout_s=None)


def test_blocked_reserve_aborts_with_task_cancel():
    pool = NodeMemoryPool(100)
    hold = pool.reserve("qa", 100)
    canceled = threading.Event()
    err = []

    def second():
        try:
            pool.reserve("qb", 50, abort=canceled.is_set)
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert _wait(lambda: pool.blocked == 1, 5)
    canceled.set()
    t.join(timeout=10)
    assert err and "canceled" in str(err[0])
    assert pool.blocked == 0
    hold.release()


def test_revoke_query_shrinks_revocable_and_wakes_waiters():
    pool = NodeMemoryPool(1000)
    revoked = threading.Event()
    pool.reserve("qa", 800, revocable=True, on_revoke=revoked.set)
    b = pool.reserve("qb", 100)  # non-revocable, different query

    freed = pool.revoke_query("qa", spill_parts=4)
    assert freed == 800 - 800 // 4
    assert revoked.is_set()
    assert pool.revocations == 1
    snap = pool.snapshot()
    assert snap["by_query"]["qa"]["reserved"] == 200
    assert snap["by_query"]["qa"]["revocable"] == 0  # already revoked
    assert snap["by_query"]["qb"] == {"reserved": 100, "revocable": 0}
    # idempotent: nothing left to revoke for qa, qb is not revocable
    assert pool.revoke_query("qa") == 0
    assert pool.revoke_query("qb") == 0
    b.release()


def test_memory_pressure_shrink_marks_pool_over_budget():
    pool = NodeMemoryPool(1000)
    pool.reserve("qa", 800)
    pool.set_capacity(300)  # MEMORY_PRESSURE chaos lever
    snap = pool.snapshot()
    assert snap["reserved"] > snap["capacity"]  # the killer's over signal


def test_free_underflow_counted_not_masked(capsys):
    before = memory_mod._UNDERFLOWS.value()
    qp = QueryMemoryPool(budget=1000, name="underflow-test")
    qp.reserve(100)
    qp.free(150)  # double-free: 50 bytes more than reserved
    assert qp.used == 0  # balance still floors at zero...
    assert memory_mod._UNDERFLOWS.value() == before + 1  # ...but counted
    assert "underflow" in capsys.readouterr().err

    npool = NodeMemoryPool(1000, name="underflow-node")
    npool.reserve("qa", 100).detach()
    npool.free("qa", 150)
    assert npool.reserved == 0
    assert memory_mod._UNDERFLOWS.value() == before + 2


def test_query_pool_layers_under_node_pool():
    node = NodeMemoryPool(1000)
    qp = QueryMemoryPool(budget=600, parent=node, query_id="qa")
    qp.reserve(400)
    assert node.reserved == 400
    with pytest.raises(MemoryExceeded):  # query budget first
        qp.reserve(300)
    assert node.reserved == 400  # failed reserve did not leak into the node
    qp.free(400)
    assert node.reserved == 0 and qp.used == 0


# ------------------------------------------- cluster memory manager (unit)


def test_cluster_manager_escalates_revoke_then_kill():
    t = [0.0]
    mgr = ClusterMemoryManager(clock=lambda: t[0])
    snap = {
        "w1": {
            "capacity": 100, "reserved": 150, "blocked": 1,
            "by_query": {
                "qa": {"reserved": 100, "revocable": 80},
                "qb": {"reserved": 50, "revocable": 0},
            },
        }
    }
    # pressure must PERSIST past the delay before anything fires
    assert mgr.sweep(snap, killer_delay_s=5.0) == []
    t[0] = 6.0
    acts = mgr.sweep(snap, killer_delay_s=5.0)
    assert acts == [
        {"action": "revoke", "node": "w1", "query_id": "qa", "bytes": 80}
    ]
    # the revoke resets the clock: the spill gets a delay window to land
    assert mgr.sweep(snap, killer_delay_s=5.0) == []
    # nothing revocable (or revocation disabled) -> kill, not revoke
    t[0] = 12.0
    acts = mgr.sweep(snap, killer_delay_s=5.0, revocation_enabled=False)
    assert acts == [{"action": "kill", "query_id": "qa", "bytes": 100}]


def test_killer_victim_is_largest_total_reservation():
    t = [0.0]
    mgr = ClusterMemoryManager(clock=lambda: t[0])
    snaps = {
        "w1": {
            "capacity": 100, "reserved": 120, "blocked": 0,
            "by_query": {
                "qa": {"reserved": 70, "revocable": 0},
                "qb": {"reserved": 50, "revocable": 0},
            },
        },
        "w2": {
            "capacity": 100, "reserved": 80, "blocked": 0,
            "by_query": {"qb": {"reserved": 80, "revocable": 0}},
        },
    }
    mgr.sweep(snaps, killer_delay_s=1.0, revocation_enabled=False)
    t[0] = 2.0
    acts = mgr.sweep(snaps, killer_delay_s=1.0, revocation_enabled=False)
    # qb holds less than qa ON the pressured node, but 130 bytes cluster-wide
    # (Trino's TotalReservationLowMemoryKiller picks the cluster total)
    assert acts == [{"action": "kill", "query_id": "qb", "bytes": 130}]


def test_cluster_manager_pressure_clears_when_node_recovers():
    t = [0.0]
    mgr = ClusterMemoryManager(clock=lambda: t[0])
    over = {"w1": {"capacity": 100, "reserved": 150, "blocked": 0,
                   "by_query": {"qa": {"reserved": 150, "revocable": 0}}}}
    ok = {"w1": {"capacity": 100, "reserved": 50, "blocked": 0,
                 "by_query": {"qa": {"reserved": 50, "revocable": 0}}}}
    mgr.sweep(over, killer_delay_s=5.0)
    t[0] = 3.0
    mgr.sweep(ok, killer_delay_s=5.0)  # recovered: timer resets
    t[0] = 6.0
    assert mgr.sweep(over, killer_delay_s=5.0) == []  # fresh window


# ------------------------------------------------------------- e2e clusters


def _make_probe(conn, rows=2000):
    conn.create_table(
        "probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)]
    )
    conn.insert("probe", {
        "k": np.arange(rows, dtype=np.int64) % 50,
        "v": np.arange(rows, dtype=np.int64),
    })
    return int(np.arange(rows).sum())


def _make_join_tables(conn):
    conn.create_table(
        "build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)]
    )
    conn.insert("build", {
        "k": np.arange(50, dtype=np.int64),
        "w": np.arange(50, dtype=np.int64) * 10,
    })
    expect_probe = _make_probe(conn)
    return expect_probe + int(((np.arange(2000) % 50) * 10).sum())


AGG_SQL = "select sum(v) from probe"
JOIN_SQL = "select sum(v + w) from probe, build where probe.k = build.k"


def _governed_cluster(conn, node_bytes, reserve, killer_delay="0.3"):
    # 2 workers: single-worker plans collapse into the coordinator-local
    # result fragment and never touch a node pool
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory",
        heartbeat_interval=0.1, node_memory_bytes=node_bytes,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    coord = runner.coordinator
    coord.session.set("retry_policy", "TASK")
    coord.session.set("task_memory_reserve_bytes", str(reserve))
    coord.session.set("low_memory_killer_delay_s", killer_delay)
    coord.session.set("memory_blocked_timeout_s", "30")
    return runner


def _await(runner, qid, timeout=120.0):
    sm = runner.coordinator.queries[qid]["sm"]
    assert _wait(lambda: sm.done, timeout), f"query stuck in {sm.state}"
    return sm


def test_revocation_spill_clears_pressure_without_kill():
    """Acceptance (a): two concurrent queries whose reservations exceed one
    worker's pool.  The first holds revocable state, so sustained pressure
    triggers REVOCATION (forced sliced/spilled execution) — both queries
    finish correctly, at least one revocation fires, nothing is killed."""
    conn = MemoryConnector()
    expect = _make_probe(conn)
    runner = _governed_cluster(conn, node_bytes=1000, reserve=600)
    coord = runner.coordinator
    try:
        # SLOW fires AFTER the reservation: the first query's scan task
        # holds its 600 bytes while sleeping — deterministic pressure
        runner.inject_task_failure(0, mode="SLOW", delay_ms=2500, count=1)
        qa = coord.submit_query(AGG_SQL)
        pool = runner.workers[0].memory_pool
        assert _wait(lambda: pool.reserved >= 600, 30), "qa never reserved"
        qb = coord.submit_query(AGG_SQL)  # 600 + 600 > 1000: qb parks

        assert _wait(lambda: pool.revocations >= 1, 30), (
            "pressure never triggered a revocation"
        )
        sm_a, sm_b = _await(runner, qa), _await(runner, qb)
        assert sm_a.state == "FINISHED", f"qa {sm_a.state}: {sm_a.error}"
        assert sm_b.state == "FINISHED", f"qb {sm_b.state}: {sm_b.error}"
        assert coord.queries[qa]["result"] == [(expect,)]
        assert coord.queries[qb]["result"] == [(expect,)]

        assert coord.oom_kills == 0, "revocation should have prevented kills"
        assert coord._m_revocations_requested.value() >= 1
        assert runner.workers[0]._m_revocations.value() >= 1
        assert pool.snapshot()["blocked_ms_total"] > 0  # qb really parked
    finally:
        runner.stop()


def test_low_memory_killer_kills_largest_reservation():
    """Acceptance (b): same pressure with revocation DISABLED — exactly one
    query (the largest reservation holder) dies with a typed
    CLUSTER_OUT_OF_MEMORY error; the other completes correctly."""
    conn = MemoryConnector()
    expect = _make_probe(conn)
    runner = _governed_cluster(conn, node_bytes=1000, reserve=600)
    coord = runner.coordinator
    coord.session.set("memory_revocation_enabled", "false")
    try:
        runner.inject_task_failure(0, mode="SLOW", delay_ms=2500, count=1)
        qa = coord.submit_query(AGG_SQL)
        pool = runner.workers[0].memory_pool
        assert _wait(lambda: pool.reserved >= 600, 30), "qa never reserved"
        qb = coord.submit_query(AGG_SQL)

        sm_a, sm_b = _await(runner, qa), _await(runner, qb)
        assert sm_a.state == "FAILED", (
            f"killer never fired: qa {sm_a.state}"
        )
        assert "CLUSTER_OUT_OF_MEMORY" in (sm_a.error or "")
        assert sm_b.state == "FINISHED", f"qb {sm_b.state}: {sm_b.error}"
        assert coord.queries[qb]["result"] == [(expect,)]

        assert coord.oom_kills == 1, "exactly one victim"
        assert coord._m_oom_kills.value() == 1
        assert pool.revocations == 0  # revocation was disabled
    finally:
        runner.stop()


@pytest.mark.chaos
def test_corrupted_frames_detected_and_refetched():
    """CORRUPT chaos: served page frames get a flipped byte.  The consumer's
    crc32 check rejects them and re-fetches the same token — the query
    returns byte-correct results, never corrupted rows."""
    conn = MemoryConnector()
    expect = _make_join_tables(conn)
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory", heartbeat_interval=0.3
    )
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        from trino_tpu.runtime import wire as wire_mod

        before = wire_mod._TRANSPORT_ERRORS.value()
        for i in range(2):
            runner.inject_task_failure(i, mode="CORRUPT", count=2)
        assert runner.query(JOIN_SQL) == [(expect,)]

        fired = {
            m for w in runner.workers for (m, _) in w.fault_injector.fired
        }
        assert "CORRUPT" in fired, "no frame was actually corrupted"
        assert wire_mod._TRANSPORT_ERRORS.value() > before, (
            "corruption was served but never detected"
        )

        # satellite: the distributed EXPLAIN ANALYZE memory line renders
        lines = [r[0] for r in runner.query("explain analyze " + JOIN_SQL)]
        assert any(
            "peak memory:" in ln and "blocked on memory:" in ln
            for ln in lines
        ), lines
    finally:
        runner.stop()


@pytest.mark.chaos
def test_memory_pressure_chaos_returns_correct_rows():
    """MEMORY_PRESSURE chaos on a 2-worker cluster: one worker's pool is
    shrunk mid-query below its live reservations (over-budget on the next
    heartbeats), then restored.  The query still returns correct rows and
    nothing is killed (the pressure window is shorter than the killer
    delay)."""
    conn = MemoryConnector()
    expect = _make_join_tables(conn)
    runner = DistributedQueryRunner(
        num_workers=2, default_catalog="memory",
        heartbeat_interval=0.2, node_memory_bytes=10_000,
    )
    runner.register_catalog("memory", conn)
    runner.start()
    coord = runner.coordinator
    try:
        coord.session.set("retry_policy", "TASK")
        coord.session.set("task_memory_reserve_bytes", "2000")
        # default low_memory_killer_delay_s (5s) >> the pressure window

        runner.inject_task_failure(0, mode="SLOW", delay_ms=1500, count=1)
        qid = coord.submit_query(JOIN_SQL)
        pool = runner.workers[0].memory_pool
        assert _wait(lambda: pool.reserved >= 2000, 30), "no reservation"

        runner.memory_pressure(0, 500)  # reserved 2000 > capacity 500
        assert pool.capacity == 500
        time.sleep(0.5)  # let heartbeats observe the over-budget node
        assert coord.workers[runner.workers[0].url].mem is not None
        runner.memory_pressure(0, 10_000)  # restore; waiters wake

        sm = _await(runner, qid)
        assert sm.state == "FINISHED", f"{sm.state}: {sm.error}"
        assert coord.queries[qid]["result"] == [(expect,)]
        assert coord.oom_kills == 0
        fired = {m for (m, _) in runner.workers[0].fault_injector.fired}
        assert "MEMORY_PRESSURE" in fired
    finally:
        runner.stop()
