"""Extended aggregate library: approx_distinct, approx_percentile, the
stddev/variance family, bool_and/bool_or, count_if, arbitrary
(reference: operator/aggregation/ — 224 accumulator files; here a small
orthogonal kernel core plus planner rewrites, ops/relops.py _fused_aggs)."""

import math

import pytest


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table t (g varchar, x double, b boolean)")
    eng.execute(
        "insert into t values ('a', 1.0, true), ('a', 2.0, true), "
        "('a', 3.0, false), ('b', 10.0, true), ('b', 10.0, true), "
        "('b', null, true)"
    )
    return eng


def test_approx_distinct(engine):
    assert engine.execute("select approx_distinct(x) from t") == [(4,)]
    assert engine.execute(
        "select g, approx_distinct(x) from t group by g order by g"
    ) == [("a", 3), ("b", 1)]


def test_stddev_variance_grouped(engine):
    rows = engine.execute(
        "select g, stddev(x), var_samp(x), stddev_pop(x), var_pop(x) "
        "from t group by g order by g"
    )
    g, sd, vs, sp, vp = rows[0]
    assert g == "a"
    assert abs(sd - 1.0) < 1e-9 and abs(vs - 1.0) < 1e-9
    assert abs(vp - 2.0 / 3.0) < 1e-9 and abs(sp - math.sqrt(2.0 / 3.0)) < 1e-9
    g, sd, vs, sp, vp = rows[1]
    assert g == "b" and sd == 0.0 and vp == 0.0


def test_stddev_single_value_is_null(engine):
    # sample stddev of one value: NULL (n-1 == 0)
    engine.execute("create table one (x double)")
    engine.execute("insert into one values (5.0)")
    assert engine.execute("select stddev(x), stddev_pop(x) from one") == [(None, 0.0)]


def test_bool_and_or(engine):
    assert engine.execute(
        "select g, bool_and(b), bool_or(b), every(b) from t group by g order by g"
    ) == [("a", False, True, False), ("b", True, True, True)]


def test_count_if(engine):
    assert engine.execute("select count_if(b) from t") == [(5,)]
    assert engine.execute("select count_if(x > 2.5) from t") == [(3,)]


def test_approx_percentile_global(engine):
    # values 1,2,3,10,10 -> median 3
    assert engine.execute("select approx_percentile(x, 0.5) from t") == [(3.0,)]
    assert engine.execute("select approx_percentile(x, 0.0) from t") == [(1.0,)]
    assert engine.execute("select approx_percentile(x, 1.0) from t") == [(10.0,)]


def test_approx_percentile_grouped(engine):
    assert engine.execute(
        "select g, approx_percentile(x, 0.5) from t group by g order by g"
    ) == [("a", 2.0), ("b", 10.0)]


def test_approx_percentile_ignores_nulls(engine):
    # group b has a NULL x: percentile over {10, 10}
    assert engine.execute(
        "select approx_percentile(x, 0.99) from t where g = 'b'"
    ) == [(10.0,)]


def test_arbitrary(engine):
    assert engine.execute("select arbitrary(g) from t where g = 'b'") == [("b",)]
    assert engine.execute("select any_value(x) from t where g = 'a'") == [(1.0,)]


def test_distributed_new_aggs():
    import jax

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    eng = Engine(default_catalog="memory", distributed=True)
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table t (g bigint, x double)")
    eng.execute(
        "insert into t values (1, 1.0), (1, 2.0), (1, 3.0), (2, 10.0), "
        "(2, 20.0), (1, 4.0), (2, 30.0), (1, 5.0)"
    )
    rows = eng.execute(
        "select g, stddev_pop(x), approx_percentile(x, 0.5), approx_distinct(x) "
        "from t group by g order by g"
    )
    g, sp, med, ad = rows[0]
    assert g == 1 and abs(sp - math.sqrt(2.0)) < 1e-9 and med == 3.0 and ad == 5
    g, sp, med, ad = rows[1]
    assert g == 2 and med == 20.0 and ad == 3
    # keyless raw-only aggregate gathers then aggregates once
    # (nearest-rank: sorted [1,2,3,4,5,10,20,30], index round(0.5*7) == 4)
    assert eng.execute("select approx_percentile(x, 0.5) from t") == [(5.0,)]


def test_approx_distinct_hll_accuracy_at_scale():
    """approx_distinct is a real HyperLogLog sketch (constant state per
    group): at 50k distinct values the estimate lands within the ~1.6%
    standard error band (we assert 5%), and per-group estimates track each
    group's true cardinality."""
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.runtime.engine import Engine

    rng = np.random.default_rng(11)
    n = 200_000
    conn = MemoryConnector()
    conn.create_table("big", [ColumnSchema("g", BIGINT), ColumnSchema("x", BIGINT)])
    g = rng.integers(0, 2, n).astype(np.int64)
    # group 0: ~50k distinct, group 1: ~500 distinct
    x = np.where(g == 0, rng.integers(0, 50_000, n), rng.integers(0, 500, n))
    conn.insert("big", {"g": g, "x": x.astype(np.int64)})
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    rows = eng.query(
        "select g, approx_distinct(x) as ad from big group by g order by g"
    )
    true0 = len(np.unique(x[g == 0]))
    true1 = len(np.unique(x[g == 1]))
    (g0, ad0), (g1, ad1) = rows
    assert abs(ad0 - true0) / true0 < 0.05, (ad0, true0)
    assert abs(ad1 - true1) / true1 < 0.05, (ad1, true1)


def test_approx_distinct_distributed_matches_local():
    """SPMD approx_distinct repartitions RAW rows on the group keys (an HLL
    of per-worker estimates would be garbage); the distributed estimate
    must equal the local one exactly (same sketch over the same rows)."""
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT
    from trino_tpu.runtime.engine import Engine

    rng = np.random.default_rng(5)
    n = 40_000
    g = rng.integers(0, 3, n).astype(np.int64)
    x = rng.integers(0, 8000, n).astype(np.int64)
    conn = MemoryConnector()
    conn.create_table("d", [ColumnSchema("g", BIGINT), ColumnSchema("x", BIGINT)])
    conn.insert("d", {"g": g, "x": x})
    sql = "select g, approx_distinct(x) as ad from d group by g order by g"
    local = Engine(default_catalog="mem")
    local.register_catalog("mem", conn)
    dist = Engine(default_catalog="mem", distributed=True)
    dist.register_catalog("mem", conn)
    got_local = local.query(sql)
    got_dist = dist.query(sql)
    assert got_local == got_dist, (got_local, got_dist)
    for gv, ad in got_local:
        true = len(np.unique(x[g == gv]))
        assert abs(ad - true) / true < 0.05, (gv, ad, true)


# ---------------------------------------------------------------- ordered
# array_agg(x ORDER BY y) / listagg WITHIN GROUP (reference: ordered
# aggregation inputs, docs/src/main/sphinx/functions/aggregate.md:20;
# sqlite has no ordered array_agg, so these are expected-value tests)


@pytest.fixture(scope="module")
def ordered_engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table oa (g bigint, x bigint, s varchar, y bigint)")
    eng.execute(
        "insert into oa values (1,10,'a',3),(1,20,'b',1),(1,30,'c',2),"
        "(2,5,'d',2),(2,6,'e',1),(1,40,'f',null)"
    )
    return eng


def test_array_agg_order_by(ordered_engine):
    rows = ordered_engine.query(
        "select g, array_agg(x order by y) from oa group by g order by g"
    )
    # nulls last by default: y=NULL row (x=40) collects last
    assert rows == [(1, [20, 30, 10, 40]), (2, [6, 5])]


def test_array_agg_order_by_desc(ordered_engine):
    rows = ordered_engine.query(
        "select g, array_agg(x order by y desc) from oa group by g order by g"
    )
    # Trino default null ordering: NULLS FIRST under DESC
    assert rows == [(1, [40, 10, 30, 20]), (2, [5, 6])]


def test_array_agg_order_by_nulls_first(ordered_engine):
    rows = ordered_engine.query(
        "select g, array_agg(x order by y nulls first) from oa group by g order by g"
    )
    assert rows == [(1, [40, 20, 30, 10]), (2, [6, 5])]


def test_listagg_within_group(ordered_engine):
    rows = ordered_engine.query(
        "select g, listagg(s, '-') within group (order by y) "
        "from oa group by g order by g"
    )
    assert rows == [(1, "b-c-a-f"), (2, "e-d")]


def test_array_agg_order_by_global(ordered_engine):
    rows = ordered_engine.query("select array_agg(s order by x desc) from oa")
    assert rows == [(["f", "c", "b", "a", "e", "d"],)]


def test_array_agg_order_by_second_key(ordered_engine):
    rows = ordered_engine.query(
        "select array_agg(s order by g desc, y) from oa"
    )
    # g=2 first (y asc: e,d), then g=1 (y asc: b,c,a, null-y f last)
    assert rows == [(["e", "d", "b", "c", "a", "f"],)]


def test_order_by_rejected_for_plain_aggs(ordered_engine):
    import pytest as _pytest

    from trino_tpu.plan.planner import PlanningError

    with _pytest.raises(PlanningError):
        ordered_engine.query("select sum(x order by y) from oa")


def test_ordered_agg_rejected_with_over(ordered_engine):
    """array_agg(x ORDER BY y) OVER (...) must error, not silently drop
    the ordering (parse_over rebuilds the call)."""
    import pytest as _pytest

    from trino_tpu.sql.lexer import SqlSyntaxError

    with _pytest.raises(SqlSyntaxError):
        ordered_engine.query(
            "select array_agg(x order by y) over (partition by g) from oa"
        )
