"""Spooled durable exchange + bounded worker output memory.

Reference behaviors being matched:
- spi/exchange/ExchangeManager.java:39 + trino-exchange-filesystem: under
  TASK retry a dead producer whose output COMMITTED to the spool is
  re-pointed at storage — consumers RE-READ, nothing recomputes.
- execution/buffer/OutputBufferMemoryManager: un-acked output chunks past
  the worker's byte budget live on disk, not RAM.
"""

import threading
import time

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT
from trino_tpu.testing import DistributedQueryRunner

pytestmark = pytest.mark.smoke


class GatedMemoryConnector(MemoryConnector):
    """read_split blocks on `gate` for `gated_table` — deterministic timing
    for kill-mid-query tests (same fixture shape as test_multihost)."""

    def __init__(self):
        super().__init__()
        self.gate = threading.Event()
        self.gated_table = None
        self.entered = 0
        self._elock = threading.Lock()

    def read_split(self, split, columns):
        if split.table == self.gated_table:
            with self._elock:
                self.entered += 1
            assert self.gate.wait(timeout=60), "test gate never opened"
        return super().read_split(split, columns)


def _make_tables(conn):
    conn.create_table("build", [ColumnSchema("k", BIGINT), ColumnSchema("w", BIGINT)])
    conn.insert("build", {"k": np.arange(50, dtype=np.int64),
                          "w": np.arange(50, dtype=np.int64) * 10})
    conn.create_table("probe", [ColumnSchema("k", BIGINT), ColumnSchema("v", BIGINT)])
    conn.insert("probe", {"k": np.arange(2000, dtype=np.int64) % 50,
                          "v": np.arange(2000, dtype=np.int64)})
    return int((np.arange(2000) + (np.arange(2000) % 50) * 10).sum())


def test_spooled_exchange_reread_not_recompute(tmp_path):
    """Kill a worker holding FINISHED producer output mid-query.  With the
    spooled exchange, the committed output is re-read from storage: the
    query succeeds AND no producer task is re-posted (the pre-spool heal
    recomputed the dead producer's whole subtree)."""
    conn = GatedMemoryConnector()
    expect = _make_tables(conn)

    runner = DistributedQueryRunner(num_workers=2, default_catalog="memory",
                                    heartbeat_interval=0.3)
    runner.register_catalog("memory", conn)
    runner.start()
    try:
        runner.coordinator.session.set("retry_policy", "TASK")
        runner.coordinator.session.set("exchange_spool_dir", str(tmp_path))
        sql = "select sum(v + w) from probe, build where probe.k = build.k"

        conn.gated_table = "probe"
        qid = runner.coordinator.submit_query(sql)
        deadline = time.monotonic() + 60
        while conn.entered == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert conn.entered > 0, "probe stage never started"
        time.sleep(0.3)
        # every pre-probe stage FINISHED => committed to the spool
        victim = runner.workers[1]
        tasks_before = {w: len(w.tasks) for w in runner.workers}
        victim.stop()
        conn.gate.set()

        sm = runner.coordinator.queries[qid]["sm"]
        deadline = time.monotonic() + 120
        while sm.state not in ("FINISHED", "FAILED") and time.monotonic() < deadline:
            time.sleep(0.1)
        assert sm.state == "FINISHED", f"query {sm.state}: {sm.error}"
        assert runner.coordinator.queries[qid]["result"] == [(expect,)]
        # the surviving worker got the re-scheduled PROBE task (1 new task at
        # most per stage) but NO recomputed build/scan task chain: committed
        # output is re-read, not recomputed.  Build scan stage posted 2 tasks
        # initially; count must not grow beyond the probe retry.
        survivor = runner.workers[0]
        new_tasks = len(survivor.tasks) - tasks_before[survivor]
        assert new_tasks <= 2, f"{new_tasks} tasks re-posted — recompute, not re-read"
    finally:
        conn.gate.set()
        runner.stop()


def test_spool_commit_marker(tmp_path):
    """A task dir without the COMMITTED marker is invisible (crash-atomic)."""
    from trino_tpu.runtime.spool import SpooledExchange

    spool = SpooledExchange(str(tmp_path))
    spool.commit_task("q1_t0", {0: [b"abc", b"defg"], 1: [b"x"]})
    assert spool.is_committed("q1_t0")
    assert spool.read_chunks("q1_t0", 0) == [b"abc", b"defg"]
    assert spool.read_chunks("q1_t0", 1) == [b"x"]
    assert spool.read_chunks("q1_t0", 2) == []  # absent buffer: empty

    # partial write (no marker) is not readable
    import os

    os.makedirs(tmp_path / "q1_t1" / "buf0", exist_ok=True)
    (tmp_path / "q1_t1" / "buf0" / "000000.bin").write_bytes(b"partial")
    assert not spool.is_committed("q1_t1")
    with pytest.raises(FileNotFoundError):
        spool.read_chunks("q1_t1", 0)

    # query cleanup drops only that query's tasks
    spool.commit_task("q2_t0", {0: [b"keep"]})
    spool.remove_query("q1")
    assert not spool.is_committed("q1_t0")
    assert spool.read_chunks("q2_t0", 0) == [b"keep"]


def test_worker_buffer_memory_bound(oracle, tpch_tiny):
    """With a byte budget configured, a streaming query's un-acked output
    past the bound lives on disk: buffered_bytes stays under the budget and
    results are still correct (OutputBufferMemoryManager's contract)."""
    from trino_tpu.connectors.tpch import TpchConnector

    bound = 4096
    runner = DistributedQueryRunner(num_workers=2,
                                    worker_buffer_memory_bytes=bound)
    runner.register_catalog("tpch", TpchConnector(0.01))
    runner.start()
    try:
        sql = ("select l_orderkey, l_partkey, l_quantity, l_extendedprice "
               "from lineitem where l_quantity < 30")
        got = runner.query(sql)
        expected = oracle.query(sql)
        from tests.oracle import assert_rows_equal

        assert_rows_equal(got, expected, ordered=False)
        assert any(w.spilled_chunks > 0 for w in runner.workers), (
            "bound never forced a spill — test is vacuous"
        )
        for w in runner.workers:
            assert w.buffered_bytes() <= bound, (
                f"buffered {w.buffered_bytes()} > bound {bound}"
            )
    finally:
        runner.stop()
