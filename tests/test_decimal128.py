"""decimal(p > 18) with real two-limb int64 arithmetic (data/dec128.py —
the Int128Math analogue, core/trino-spi/.../type/Int128Math.java:1).

Columns whose values exceed the int64 lane carry a second (high-limb) lane;
+/−/negate/abs/compare and SUM are exact at full 128-bit width.  Expected
values are computed with python's unbounded ints — the sqlite oracle cannot
hold beyond-int64 integers, so these are differential against exact host
arithmetic over the same rows.
"""

from decimal import Decimal

import numpy as np
import pytest


BIG = [2**70, -(2**70) + 7, 2**63, -(2**63) - 1, 12345, -1, 10**24, 0]


@pytest.fixture(scope="module")
def d128_engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import BIGINT, DecimalType
    from trino_tpu.runtime.engine import Engine

    conn = MemoryConnector()
    conn.create_table(
        "big",
        [
            ColumnSchema("k", BIGINT),
            ColumnSchema("x", DecimalType(38, 0)),
            ColumnSchema("y", DecimalType(38, 0)),
        ],
    )
    x = np.empty(len(BIG), dtype=object)
    x[:] = BIG
    y = np.empty(len(BIG), dtype=object)
    y[:] = [v + 1 for v in BIG]
    k = np.asarray([i % 2 for i in range(len(BIG))], dtype=np.int64)
    conn.insert("big", {"k": k, "x": x, "y": y})
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    return eng


def test_ingest_and_roundtrip(d128_engine):
    rows = d128_engine.query("select x from big")
    got = sorted(int(r[0]) for r in rows)
    assert got == sorted(BIG)


def test_add_sub_neg(d128_engine):
    rows = d128_engine.query("select x + y, x - y, -x from big")
    for (s, d, m), v in zip(rows, BIG):
        assert int(s) == v + (v + 1)
        assert int(d) == -1
        assert int(m) == -v


def test_compare(d128_engine):
    rows = d128_engine.query("select count(*) from big where x < y")
    assert rows == [(len(BIG),)]
    rows = d128_engine.query("select x from big where x > 9223372036854775807")
    assert sorted(int(r[0]) for r in rows) == sorted(
        v for v in BIG if v > 2**63 - 1
    )


def test_sum_exact_beyond_int64(d128_engine):
    rows = d128_engine.query("select sum(x) from big")
    assert int(rows[0][0]) == sum(BIG)


def test_grouped_sum(d128_engine):
    rows = d128_engine.query("select k, sum(x) from big group by k order by k")
    exp = {0: sum(v for i, v in enumerate(BIG) if i % 2 == 0),
           1: sum(v for i, v in enumerate(BIG) if i % 2 == 1)}
    assert {r[0]: int(r[1]) for r in rows} == exp


def test_count_over_limbed(d128_engine):
    assert d128_engine.query("select count(x) from big") == [(len(BIG),)]


def test_cast_to_double(d128_engine):
    rows = d128_engine.query("select cast(x as double) from big")
    for (got,), v in zip(rows, BIG):
        assert got == pytest.approx(float(v), rel=1e-15)


def test_filter_then_sum(d128_engine):
    rows = d128_engine.query("select sum(x) from big where x > 0")
    assert int(rows[0][0]) == sum(v for v in BIG if v > 0)


def test_scaled_decimal128(d128_engine):
    from trino_tpu.connectors.spi import ColumnSchema
    from trino_tpu.data.types import DecimalType

    conn = d128_engine.catalogs.get("mem")
    vals = [10**30 + 25, -(10**30) - 75]  # decimal(38,2): x / 100
    v = np.empty(2, dtype=object)
    v[:] = vals
    conn.create_table("money", [ColumnSchema("amt", DecimalType(38, 2))])
    conn.insert("money", {"amt": v})
    rows = d128_engine.query("select sum(amt) from money")
    assert rows[0][0] == Decimal(sum(vals)).scaleb(-2)


def test_small_values_stay_single_lane():
    """decimal(38) columns whose values fit int64 keep the single-lane fast
    path (no second limb allocated)."""
    from trino_tpu.data.page import Column
    from trino_tpu.data.types import DecimalType

    v = np.empty(3, dtype=object)
    v[:] = [1, -2, 3]
    col = Column.from_numpy(DecimalType(38, 0), v)
    assert col.data2 is None
    big = np.empty(1, dtype=object)
    big[:] = [2**100]
    col = Column.from_numpy(DecimalType(38, 0), big)
    assert col.data2 is not None


def test_order_by_decimal128(d128_engine):
    """ORDER BY over two-limb lanes sorts at full 128-bit width (the
    (hi signed, lo unsigned) lexicographic operand pair)."""
    rows = d128_engine.query("select x from big order by x")
    assert [int(r[0]) for r in rows] == sorted(BIG)
    rows = d128_engine.query("select x from big order by x desc limit 3")
    assert [int(r[0]) for r in rows] == sorted(BIG, reverse=True)[:3]


def test_join_on_decimal128_keys(d128_engine):
    """Equi-join keys on two-limb lanes compare at full 128-bit width: the
    exact-equality verify checks BOTH limbs, so values that collide on the
    lo limb alone never match (and the gathers carry the hi limb through)."""
    rows = d128_engine.query(
        "select a.k, b.y from big a join big b on a.x = b.x order by b.y"
    )
    # every BIG value is unique: the self-join matches each row exactly once
    assert len(rows) == len(BIG)
    assert sorted(int(r[1]) for r in rows) == sorted(v + 1 for v in BIG)


def test_join_carries_decimal128_payload(d128_engine):
    """A decimal128 VALUE column rides the join's expansion gathers and
    aggregates exactly on the far side."""
    rows = d128_engine.query(
        "select sum(b.y) from big a join big b on a.x = b.x"
    )
    assert int(rows[0][0]) == sum(v + 1 for v in BIG)


def test_case_over_decimal128(d128_engine):
    """CASE selects over both limbs; the single-lane 0 literal in the ELSE
    branch sign-extends into limb space."""
    rows = d128_engine.query(
        "select sum(case when k = 0 then x else 0 end) from big"
    )
    assert int(rows[0][0]) == sum(v for i, v in enumerate(BIG) if i % 2 == 0)


def test_max_over_decimal128(d128_engine):
    """min/max reduce lexicographically over (hi signed, lo unsigned)."""
    rows = d128_engine.query("select max(x), min(x) from big")
    assert int(rows[0][0]) == max(BIG)
    assert int(rows[0][1]) == min(BIG)


def test_unsupported_ops_refuse_loudly(d128_engine):
    with pytest.raises(Exception):
        # window functions over decimal128 lanes are still a loud refusal
        d128_engine.query("select sum(x) over () from big")


def test_mul128(d128_engine):
    """decimal128 multiplication (Int128Math.multiply analogue): exact
    low-128 products, including big x small and sign combinations."""
    rows = d128_engine.query("select x * 3, x * y from big where x = 1180591620717411303424")
    (r3, rxy), = rows
    v = 2**70
    assert int(r3) == v * 3
    wrapped = (v * (v + 1)) % (1 << 128)  # low 128 bits, signed
    if wrapped >= 1 << 127:
        wrapped -= 1 << 128
    assert int(rxy) == wrapped
    rows = d128_engine.query("select sum(x * 2) from big")
    assert int(rows[0][0]) == 2 * sum(BIG)
