"""Parquet connector tests: file ingestion must be indistinguishable from
the generator connector (reference: lib/trino-parquet ParquetReader +
BaseConnectorTest contract suites)."""

import os

import numpy as np
import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES
from trino_tpu.connectors.parquet import ParquetConnector
from trino_tpu.connectors.tpch import TpchConnector, tpch_data
from trino_tpu.connectors.tpch.generator import TPCH_SCHEMAS
from trino_tpu.runtime.engine import Engine


@pytest.fixture(scope="module")
def parquet_root(tmp_path_factory):
    """TPC-H tiny written to parquet (multiple row groups for lineitem so
    splits exercise the row-group enumeration)."""
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import _numpy_to_arrow
    import pyarrow as pa

    root = tmp_path_factory.mktemp("pq")
    for table, schema in TPCH_SCHEMAS.items():
        data = tpch_data(table, 0.01)
        names = [c for c, _ in schema]
        cols = {c: _numpy_to_arrow(data[c], t) for c, t in schema}
        t = pa.table(cols)
        os.makedirs(root / table, exist_ok=True)
        pq.write_table(
            t,
            root / table / "part-0.parquet",
            row_group_size=20_000 if table == "lineitem" else None,
        )
    return str(root)


@pytest.fixture(scope="module")
def pq_engine(parquet_root):
    eng = Engine(default_catalog="parquet")
    eng.register_catalog("parquet", ParquetConnector(parquet_root))
    return eng


def test_schema_discovery(pq_engine, parquet_root):
    conn = ParquetConnector(parquet_root)
    assert set(conn.list_tables()) == set(TPCH_SCHEMAS)
    sch = conn.table_schema("lineitem")
    want = dict(TPCH_SCHEMAS["lineitem"])
    for c in sch.columns:
        assert c.type == want[c.name], c.name
    assert conn.estimated_row_count("lineitem") > 0


def test_row_group_splits(parquet_root):
    conn = ParquetConnector(parquet_root)
    splits = conn.get_splits("lineitem", 3)
    assert len(splits) == 3
    rows = 0
    for s in splits:
        arrs = conn.read_split(s, ["l_orderkey"])
        rows += len(arrs["l_orderkey"])
    assert rows == conn.estimated_row_count("lineitem")


@pytest.mark.parametrize("name", ["q01", "q03", "q06", "q12"])
def test_tpch_over_parquet(name, pq_engine, oracle):
    got = pq_engine.query(QUERIES[name])
    want = oracle.query(QUERIES[name])
    assert_rows_equal(got, want, ordered=ORDERED[name])


def test_ctas_into_parquet(tmp_path, parquet_root):
    """CREATE TABLE AS writes real parquet files that read back identically."""
    eng = Engine(default_catalog="out")
    eng.register_catalog("out", ParquetConnector(str(tmp_path)))
    eng.register_catalog("parquet", ParquetConnector(parquet_root))
    eng.execute(
        "create table big_parts as select p_partkey, p_retailprice, p_brand"
        " from parquet.part where p_retailprice > 1500"
    )
    got = eng.query("select count(*), min(p_retailprice) from big_parts")
    want = eng.query(
        "select count(*), min(p_retailprice) from parquet.part where p_retailprice > 1500"
    )
    assert got == want
    # the data really is parquet on disk
    import pyarrow.parquet as pq

    files = [f for f in os.listdir(tmp_path / "big_parts") if f.endswith(".parquet")]
    assert files
    assert pq.ParquetFile(tmp_path / "big_parts" / files[0]).metadata.num_rows > 0


def test_schema_qualified_name_falls_back_to_default_catalog(pq_engine):
    """Trino 2-part semantics: an unregistered first part is a SCHEMA in the
    default catalog, not an unknown catalog error."""
    rows = pq_engine.query("select count(*) from tiny.nation")
    assert rows[0][0] == 25


def test_nulls_round_trip(tmp_path):
    """NULLs in parquet files surface as SQL NULLs."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    t = pa.table(
        {
            "k": pa.array([1, 2, 3, 4], type=pa.int64()),
            "v": pa.array([10.5, None, 30.5, None], type=pa.float64()),
            "s": pa.array(["a", "b", None, "d"], type=pa.string()),
        }
    )
    os.makedirs(tmp_path / "t", exist_ok=True)
    pq.write_table(t, tmp_path / "t" / "f.parquet")
    eng = Engine(default_catalog="parquet")
    eng.register_catalog("parquet", ParquetConnector(str(tmp_path)))
    rows = eng.query("select k, v, s from t order by k")
    assert rows == [(1, 10.5, "a"), (2, None, "b"), (3, 30.5, None), (4, None, "d")]
    assert eng.query("select count(v), count(*) from t") == [(2, 4)]


def test_parquet_map_row_types(tmp_path):
    """MAP and ROW columns ingest from parquet as dict-coded columns
    (reference: spi/block/MapBlock, RowBlock): field dereference, map
    subscript, map_keys/values/cardinality, grouping on a row column."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import ParquetConnector
    from trino_tpu.runtime.engine import Engine

    t = pa.table({
        "id": pa.array([1, 2, 3, 4], pa.int64()),
        "attrs": pa.array(
            [{"a": 1, "b": 2}, {"a": 5}, None, {"b": 9, "c": 7}],
            pa.map_(pa.string(), pa.int64()),
        ),
        "loc": pa.array(
            [{"city": "ny", "zip": 10001}, {"city": "sf", "zip": 94110},
             {"city": "ny", "zip": 10001}, None],
            pa.struct([("city", pa.string()), ("zip", pa.int64())]),
        ),
    })
    import os

    os.makedirs(tmp_path / "m", exist_ok=True)
    pq.write_table(t, tmp_path / "m" / "part0.parquet")
    eng = Engine(default_catalog="pq")
    eng.register_catalog("pq", ParquetConnector(str(tmp_path)))

    rows = eng.query("select id, cardinality(attrs) as c from m order by id")
    assert rows == [(1, 2), (2, 1), (3, None), (4, 2)]
    rows = eng.query("select id, attrs['a'] as a from m order by id")
    assert rows == [(1, 1), (2, 5), (3, None), (4, None)]
    rows = eng.query("select id, element_at(attrs, 'b') as b from m order by id")
    assert rows == [(1, 2), (2, None), (3, None), (4, 9)]
    rows = eng.query("select id, loc.city as city, loc.zip as z from m order by id")
    assert rows == [(1, "ny", 10001), (2, "sf", 94110), (3, "ny", 10001), (4, None, None)]
    # grouping on a ROW column (equality by interned code)
    rows = eng.query("select loc.city as city, count(*) as c from m"
                     " where loc.zip is not null group by loc.city order by city")
    assert rows == [("ny", 2), ("sf", 1)]
    # map_keys/map_values produce arrays
    rows = eng.query("select id, map_keys(attrs) as k, map_values(attrs) as v"
                     " from m where id = 1")
    assert rows == [(1, ["a", "b"], [1, 2])]


def test_parquet_long_decimal(tmp_path):
    """DECIMAL(p>18) columns ingest (decimal128 storage) with int64 lanes:
    realistic long-decimal values aggregate exactly; a value past int64
    raises instead of corrupting (Int128 two-limb lanes are the upgrade
    path, reference spi/type/Int128Math.java)."""
    import decimal
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import ParquetConnector
    from trino_tpu.runtime.engine import Engine

    os.makedirs(tmp_path / "d", exist_ok=True)
    vals = [decimal.Decimal("123456789012345.12"), decimal.Decimal("-7.50"), None]
    t = pa.table({
        "id": pa.array([1, 2, 3], pa.int64()),
        "amt": pa.array(vals, pa.decimal128(38, 2)),
    })
    pq.write_table(t, tmp_path / "d" / "p0.parquet")
    eng = Engine(default_catalog="pq")
    eng.register_catalog("pq", ParquetConnector(str(tmp_path)))
    # long decimals surface as exact Decimal (one python surface for p>18)
    assert eng.query("select sum(amt) from d") == [
        (decimal.Decimal("123456789012337.62"),)
    ]
    assert eng.query("select count(amt) from d") == [(2,)]
    rows = eng.query("select id from d where amt < 0")
    assert rows == [(2,)]

    # a value beyond int64 lanes must REJECT, not truncate
    os.makedirs(tmp_path / "big", exist_ok=True)
    t2 = pa.table({
        "amt": pa.array([decimal.Decimal("9" * 30)], pa.decimal128(38, 0)),
    })
    pq.write_table(t2, tmp_path / "big" / "p0.parquet")
    import pytest as _pytest

    with _pytest.raises(Exception, match="int64|exceeds"):
        eng.query("select sum(amt) from big")


def test_parquet_struct_with_null_field(tmp_path):
    """A struct with a NULL field value must ingest (interning is hash-based,
    not sort-based — None is not <-comparable) and dereference to NULL."""
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import ParquetConnector
    from trino_tpu.runtime.engine import Engine

    os.makedirs(tmp_path / "s", exist_ok=True)
    t = pa.table({
        "id": pa.array([1, 2], pa.int64()),
        "loc": pa.array(
            [{"city": None, "zip": 1}, {"city": "sf", "zip": None}],
            pa.struct([("city", pa.string()), ("zip", pa.int64())]),
        ),
    })
    pq.write_table(t, tmp_path / "s" / "p0.parquet")
    eng = Engine(default_catalog="pq")
    eng.register_catalog("pq", ParquetConnector(str(tmp_path)))
    rows = eng.query("select id, loc.city as c, loc.zip as z from s order by id")
    assert rows == [(1, None, 1), (2, "sf", None)]
