"""Parquet connector tests: file ingestion must be indistinguishable from
the generator connector (reference: lib/trino-parquet ParquetReader +
BaseConnectorTest contract suites)."""

import os

import numpy as np
import pytest

from tests.oracle import assert_rows_equal
from tests.tpch_queries import ORDERED, QUERIES
from trino_tpu.connectors.parquet import ParquetConnector
from trino_tpu.connectors.tpch import TpchConnector, tpch_data
from trino_tpu.connectors.tpch.generator import TPCH_SCHEMAS
from trino_tpu.runtime.engine import Engine


@pytest.fixture(scope="module")
def parquet_root(tmp_path_factory):
    """TPC-H tiny written to parquet (multiple row groups for lineitem so
    splits exercise the row-group enumeration)."""
    import pyarrow.parquet as pq

    from trino_tpu.connectors.parquet import _numpy_to_arrow
    import pyarrow as pa

    root = tmp_path_factory.mktemp("pq")
    for table, schema in TPCH_SCHEMAS.items():
        data = tpch_data(table, 0.01)
        names = [c for c, _ in schema]
        cols = {c: _numpy_to_arrow(data[c], t) for c, t in schema}
        t = pa.table(cols)
        os.makedirs(root / table, exist_ok=True)
        pq.write_table(
            t,
            root / table / "part-0.parquet",
            row_group_size=20_000 if table == "lineitem" else None,
        )
    return str(root)


@pytest.fixture(scope="module")
def pq_engine(parquet_root):
    eng = Engine(default_catalog="parquet")
    eng.register_catalog("parquet", ParquetConnector(parquet_root))
    return eng


def test_schema_discovery(pq_engine, parquet_root):
    conn = ParquetConnector(parquet_root)
    assert set(conn.list_tables()) == set(TPCH_SCHEMAS)
    sch = conn.table_schema("lineitem")
    want = dict(TPCH_SCHEMAS["lineitem"])
    for c in sch.columns:
        assert c.type == want[c.name], c.name
    assert conn.estimated_row_count("lineitem") > 0


def test_row_group_splits(parquet_root):
    conn = ParquetConnector(parquet_root)
    splits = conn.get_splits("lineitem", 3)
    assert len(splits) == 3
    rows = 0
    for s in splits:
        arrs = conn.read_split(s, ["l_orderkey"])
        rows += len(arrs["l_orderkey"])
    assert rows == conn.estimated_row_count("lineitem")


@pytest.mark.parametrize("name", ["q01", "q03", "q06", "q12"])
def test_tpch_over_parquet(name, pq_engine, oracle):
    got = pq_engine.query(QUERIES[name])
    want = oracle.query(QUERIES[name])
    assert_rows_equal(got, want, ordered=ORDERED[name])


def test_ctas_into_parquet(tmp_path, parquet_root):
    """CREATE TABLE AS writes real parquet files that read back identically."""
    eng = Engine(default_catalog="out")
    eng.register_catalog("out", ParquetConnector(str(tmp_path)))
    eng.register_catalog("parquet", ParquetConnector(parquet_root))
    eng.execute(
        "create table big_parts as select p_partkey, p_retailprice, p_brand"
        " from parquet.part where p_retailprice > 1500"
    )
    got = eng.query("select count(*), min(p_retailprice) from big_parts")
    want = eng.query(
        "select count(*), min(p_retailprice) from parquet.part where p_retailprice > 1500"
    )
    assert got == want
    # the data really is parquet on disk
    import pyarrow.parquet as pq

    files = [f for f in os.listdir(tmp_path / "big_parts") if f.endswith(".parquet")]
    assert files
    assert pq.ParquetFile(tmp_path / "big_parts" / files[0]).metadata.num_rows > 0


def test_schema_qualified_name_falls_back_to_default_catalog(pq_engine):
    """Trino 2-part semantics: an unregistered first part is a SCHEMA in the
    default catalog, not an unknown catalog error."""
    rows = pq_engine.query("select count(*) from tiny.nation")
    assert rows[0][0] == 25


def test_nulls_round_trip(tmp_path):
    """NULLs in parquet files surface as SQL NULLs."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    t = pa.table(
        {
            "k": pa.array([1, 2, 3, 4], type=pa.int64()),
            "v": pa.array([10.5, None, 30.5, None], type=pa.float64()),
            "s": pa.array(["a", "b", None, "d"], type=pa.string()),
        }
    )
    os.makedirs(tmp_path / "t", exist_ok=True)
    pq.write_table(t, tmp_path / "t" / "f.parquet")
    eng = Engine(default_catalog="parquet")
    eng.register_catalog("parquet", ParquetConnector(str(tmp_path)))
    rows = eng.query("select k, v, s from t order by k")
    assert rows == [(1, 10.5, "a"), (2, None, "b"), (3, 30.5, None), (4, None, "d")]
    assert eng.query("select count(v), count(*) from t") == [(2, 4)]
