"""DDL/DML/introspection statement tests over the memory connector
(reference: BaseConnectorTest write paths + DataDefinitionTask tests)."""

import pytest


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    return eng


def test_create_insert_select(engine):
    engine.execute("create table t (a bigint, b varchar, c double)")
    assert engine.execute("show tables") == [("t",)]
    assert engine.execute("describe t") == [
        ("a", "bigint"), ("b", "varchar"), ("c", "double"),
    ]
    n = engine.execute("insert into t values (1, 'x', 1.5), (2, 'y', 2.5), (3, 'x', 3.5)")
    assert n == [(3,)]
    rows = engine.execute("select b, sum(a) as s from t group by b order by b")
    assert rows == [("x", 4), ("y", 2)]


def test_insert_select_roundtrip(engine):
    engine.execute("create table src (k bigint, v double)")
    engine.execute("insert into src values (1, 10.0), (2, 20.0), (3, 30.0)")
    engine.execute("create table dst (k bigint, v double)")
    engine.execute("insert into dst select k, v * 2 from src where k <= 2")
    assert engine.execute("select k, v from dst order by k") == [(1, 20.0), (2, 40.0)]


def test_ctas(engine):
    engine.execute("create table src (k bigint)")
    engine.execute("insert into src values (5), (6)")
    n = engine.execute("create table copy as select k + 1 as k1 from src")
    assert n == [(2,)]
    assert engine.execute("select k1 from copy order by k1") == [(6,), (7,)]


def test_drop(engine):
    engine.execute("create table t (a bigint)")
    engine.execute("drop table t")
    assert engine.execute("show tables") == []
    assert engine.execute("drop table if exists t") == [(0,)]


def test_insert_invalidates_scan_cache(engine):
    engine.execute("create table t (a bigint)")
    engine.execute("insert into t values (1)")
    assert engine.execute("select count(*) from t") == [(1,)]
    engine.execute("insert into t values (2), (3)")
    assert engine.execute("select count(*) from t") == [(3,)]


def test_explain_and_session(engine):
    engine.execute("create table t (a bigint)")
    lines = engine.execute("explain select * from t")
    assert any("TableScan" in row[0] for row in lines)
    engine.execute("set session join_distribution_type = 'BROADCAST'")
    assert engine.session.get("join_distribution_type") == "BROADCAST"
    with pytest.raises(Exception):
        engine.execute("set session nonexistent_prop = 1")


def test_blackhole(engine):
    from trino_tpu.connectors.memory import BlackholeConnector

    bh = BlackholeConnector()
    eng2_catalog = bh
    engine.register_catalog("blackhole", bh)
    bh.create_table("sink", [])
    # write through the engine's default catalog is memory; use connector API
    import numpy as np

    bh.insert("sink", {"x": np.arange(10)})
    assert bh.rows_swallowed == 10
    assert bh.read_split(bh.get_splits("sink", 1)[0], []) == {}


# ------------------------------------------------------------------- views
# reference: core/trino-parser/.../tree/CreateView.java + StatementAnalyzer
# view expansion; VERDICT r3 missing #3


def test_create_view_and_query(engine):
    engine.execute("create table vt (a bigint, b varchar)")
    engine.execute("insert into vt values (1, 'x'), (2, 'y'), (3, 'x')")
    engine.execute("create view v1 as select b, sum(a) as s from vt group by b")
    assert engine.execute("select * from v1 order by b") == [("x", 4), ("y", 2)]
    # views appear in SHOW TABLES and DESCRIBE with derived types
    assert ("v1",) in engine.execute("show tables")
    assert engine.execute("describe v1") == [("b", "varchar"), ("s", "bigint")]
    assert "CREATE VIEW v1 AS" in engine.execute("show create view v1")[0][0]


def test_view_over_view_and_join(engine):
    engine.execute("create table base (k bigint, v bigint)")
    engine.execute("insert into base values (1, 10), (2, 20), (3, 30)")
    engine.execute("create view even as select k, v from base where k % 2 = 0")
    engine.execute("create view doubled as select k, v * 2 as v2 from even")
    assert engine.execute("select k, v2 from doubled order by k") == [(2, 40)]
    # a view joins like a table, with an alias
    rows = engine.execute(
        "select d.v2, b.v from doubled d join base b on d.k = b.k"
    )
    assert rows == [(40, 20)]


def test_view_replace_drop_and_errors(engine):
    engine.execute("create table rt (x bigint)")
    engine.execute("insert into rt values (1), (2)")
    engine.execute("create view rv as select x from rt")
    with pytest.raises(Exception, match="already exists"):
        engine.execute("create view rv as select x + 1 as y from rt")
    engine.execute("create or replace view rv as select x + 1 as y from rt")
    assert engine.execute("select * from rv order by y") == [(2,), (3,)]
    engine.execute("drop view rv")
    with pytest.raises(Exception):
        engine.execute("select * from rv")
    engine.execute("drop view if exists rv")  # no error
    with pytest.raises(Exception, match="not found"):
        engine.execute("drop view rv")


def test_view_validated_at_create(engine):
    with pytest.raises(Exception):
        engine.execute("create view bad as select nope from missing_table")
    # failed create leaves no trace
    assert ("bad",) not in engine.execute("show tables")


def test_view_cycle_detected(engine):
    engine.execute("create table ct (x bigint)")
    engine.execute("create view cv1 as select x from ct")
    engine.execute("create view cv2 as select x from cv1")
    with pytest.raises(Exception, match="cycle"):
        engine.execute("create or replace view cv1 as select x from cv2")
    # the failed replace must roll back to the previous definition
    engine.execute("insert into ct values (7)")
    assert engine.execute("select * from cv2") == [(7,)]


def test_view_base_table_access_control(engine):
    """SELECT on a view checks the expanded base tables (reference:
    checkCanSelectFromColumns on the analyzed tables)."""
    from trino_tpu.runtime.security import FileBasedAccessControl

    engine.execute("create table sec (x bigint)")
    engine.execute("insert into sec values (1)")
    engine.execute("create view sv as select x from sec")
    engine.access_control = FileBasedAccessControl(
        {"tables": [{"user": "*", "table": "other", "privileges": ["SELECT"]}]}
    )
    try:
        with pytest.raises(Exception):
            engine.execute("select * from sv")
    finally:
        from trino_tpu.runtime.security import AllowAllAccessControl

        engine.access_control = AllowAllAccessControl()


def test_view_cannot_shadow_table(engine):
    engine.execute("create table shadowed (x bigint)")
    with pytest.raises(Exception, match="already exists"):
        engine.execute("create view shadowed as select 1 as y")


def test_view_ddl_in_rolled_back_transaction(engine):
    engine.execute("create table txt (x bigint)")
    engine.execute("insert into txt values (1)")
    engine.execute("create view keepv as select x from txt")
    engine.execute("start transaction")
    engine.execute("create view tempv as select x + 1 as y from txt")
    engine.execute("drop view keepv")
    engine.execute("rollback")
    # rolled-back view DDL leaves no trace; pre-existing view survives
    assert ("tempv",) not in engine.execute("show tables")
    assert engine.execute("select * from keepv") == [(1,)]


def test_schema_qualified_view_name(engine):
    engine.execute("create table qt (x bigint)")
    engine.execute("insert into qt values (9)")
    engine.execute("create view myschema.qv as select x from qt")
    assert engine.execute("select * from myschema.qv") == [(9,)]
    assert engine.execute("select * from qv") == [(9,)]
    engine.execute("drop view myschema.qv")
    assert ("qv",) not in engine.execute("show tables")
