"""DDL/DML/introspection statement tests over the memory connector
(reference: BaseConnectorTest write paths + DataDefinitionTask tests)."""

import pytest


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    return eng


def test_create_insert_select(engine):
    engine.execute("create table t (a bigint, b varchar, c double)")
    assert engine.execute("show tables") == [("t",)]
    assert engine.execute("describe t") == [
        ("a", "bigint"), ("b", "varchar"), ("c", "double"),
    ]
    n = engine.execute("insert into t values (1, 'x', 1.5), (2, 'y', 2.5), (3, 'x', 3.5)")
    assert n == [(3,)]
    rows = engine.execute("select b, sum(a) as s from t group by b order by b")
    assert rows == [("x", 4), ("y", 2)]


def test_insert_select_roundtrip(engine):
    engine.execute("create table src (k bigint, v double)")
    engine.execute("insert into src values (1, 10.0), (2, 20.0), (3, 30.0)")
    engine.execute("create table dst (k bigint, v double)")
    engine.execute("insert into dst select k, v * 2 from src where k <= 2")
    assert engine.execute("select k, v from dst order by k") == [(1, 20.0), (2, 40.0)]


def test_ctas(engine):
    engine.execute("create table src (k bigint)")
    engine.execute("insert into src values (5), (6)")
    n = engine.execute("create table copy as select k + 1 as k1 from src")
    assert n == [(2,)]
    assert engine.execute("select k1 from copy order by k1") == [(6,), (7,)]


def test_drop(engine):
    engine.execute("create table t (a bigint)")
    engine.execute("drop table t")
    assert engine.execute("show tables") == []
    assert engine.execute("drop table if exists t") == [(0,)]


def test_insert_invalidates_scan_cache(engine):
    engine.execute("create table t (a bigint)")
    engine.execute("insert into t values (1)")
    assert engine.execute("select count(*) from t") == [(1,)]
    engine.execute("insert into t values (2), (3)")
    assert engine.execute("select count(*) from t") == [(3,)]


def test_explain_and_session(engine):
    engine.execute("create table t (a bigint)")
    lines = engine.execute("explain select * from t")
    assert any("TableScan" in row[0] for row in lines)
    engine.execute("set session join_distribution_type = 'BROADCAST'")
    assert engine.session.get("join_distribution_type") == "BROADCAST"
    with pytest.raises(Exception):
        engine.execute("set session nonexistent_prop = 1")


def test_blackhole(engine):
    from trino_tpu.connectors.memory import BlackholeConnector

    bh = BlackholeConnector()
    eng2_catalog = bh
    engine.register_catalog("blackhole", bh)
    bh.create_table("sink", [])
    # write through the engine's default catalog is memory; use connector API
    import numpy as np

    bh.insert("sink", {"x": np.arange(10)})
    assert bh.rows_swallowed == 10
    assert bh.read_split(bh.get_splits("sink", 1)[0], []) == {}
