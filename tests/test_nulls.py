"""NULL-semantics regression tests (round-1 advisor findings).

Covers: null-aware NOT IN (three-valued logic), validity preservation through
the CTAS / INSERT...SELECT write path, grouped COUNT(DISTINCT) with NULL
lanes, NULL-key routing in the wire partitioner, and the native serde's
all-empty-string dictionary round trip.  Reference semantics:
SemiJoinNode null-aware rewrite, spi Block isNull bitmaps through
ConnectorPageSink.
"""

import numpy as np
import pytest


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    return eng


def _setup_not_in(engine, probe_vals, build_vals):
    engine.execute("drop table if exists probe")
    engine.execute("drop table if exists build")
    engine.execute("create table probe (x bigint)")
    engine.execute("create table build (y bigint)")
    if probe_vals:
        engine.execute(
            "insert into probe values "
            + ", ".join(f"({'null' if v is None else v})" for v in probe_vals)
        )
    if build_vals:
        engine.execute(
            "insert into build values "
            + ", ".join(f"({'null' if v is None else v})" for v in build_vals)
        )


def test_not_in_basic(engine):
    _setup_not_in(engine, [1, 2, 3], [2])
    rows = engine.execute(
        "select x from probe where x not in (select y from build) order by x"
    )
    assert rows == [(1,), (3,)]


def test_not_in_null_probe_filtered(engine):
    # NULL NOT IN (non-empty set) => NULL => filtered
    _setup_not_in(engine, [1, None, 3], [2])
    rows = engine.execute(
        "select x from probe where x not in (select y from build) order by x"
    )
    assert rows == [(1,), (3,)]


def test_not_in_null_in_build_filters_all_nonmatches(engine):
    # x NOT IN (..., NULL) is never TRUE: matches are FALSE, rest are NULL
    _setup_not_in(engine, [1, 2, 3], [2, None])
    rows = engine.execute(
        "select x from probe where x not in (select y from build)"
    )
    assert rows == []


def test_not_in_empty_build_keeps_all(engine):
    # x NOT IN (empty) is TRUE for every row, including NULL x
    _setup_not_in(engine, [1, None], [])
    rows = engine.execute(
        "select count(*) from probe where x not in (select y from build)"
    )
    assert rows == [(2,)]


def test_in_subquery_still_positive(engine):
    _setup_not_in(engine, [1, None, 3], [3, None])
    rows = engine.execute(
        "select x from probe where x in (select y from build)"
    )
    assert rows == [(3,)]


# --------------------------------------------------------------- write path


def test_ctas_preserves_nulls_from_left_join(engine):
    engine.execute("create table l (k bigint)")
    engine.execute("insert into l values (1), (2)")
    engine.execute("create table r (k bigint, v double)")
    engine.execute("insert into r values (1, 10.0)")
    engine.execute(
        "create table joined as "
        "select l.k as k, r.v as v from l left join r on l.k = r.k"
    )
    rows = engine.execute("select k, v from joined order by k")
    assert rows == [(1, 10.0), (2, None)]
    # and NULL-ness survives further queries over the written table
    assert engine.execute("select count(v) from joined") == [(1,)]
    assert engine.execute("select k from joined where v is null") == [(2,)]


def test_insert_select_preserves_null_literals(engine):
    engine.execute("create table t (a bigint, b varchar)")
    engine.execute("insert into t values (1, 'x'), (null, null)")
    engine.execute("create table u (a bigint, b varchar)")
    engine.execute("insert into u select a, b from t")
    rows = engine.execute("select a, b from u order by a nulls first")
    assert rows == [(None, None), (1, "x")]


# ------------------------------------------------- grouped COUNT(DISTINCT)


def test_grouped_count_distinct_ignores_nulls(engine):
    engine.execute("create table t (g bigint, v bigint)")
    engine.execute(
        "insert into t values "
        "(1, 10), (1, 10), (1, null), (1, 20), "
        "(2, null), (2, null), "
        "(3, 30)"
    )
    rows = engine.execute(
        "select g, count(distinct v) from t group by g order by g"
    )
    assert rows == [(1, 2), (2, 0), (3, 1)]


# ------------------------------------------------------ wire partitioning


def test_partition_page_routes_null_keys_to_part0():
    from trino_tpu.data.page import Column, Page
    from trino_tpu.data.types import BIGINT
    from trino_tpu.native import page_serde
    from trino_tpu.plan.ir import FieldRef
    from trino_tpu.runtime.wire import partition_page

    data = np.array([1, 2, 3, 4, 5, 6, 7, 8], dtype=np.int64)
    valid = np.array([True, False, True, False, True, True, False, True])
    page = Page((Column.from_numpy(BIGINT, data, valid),))
    parts = partition_page(page, [FieldRef(0, BIGINT)], 4)
    # every NULL-key row must land in partition 0
    null_rows = 0
    for p, chunks in enumerate(parts):
        for blob in chunks:
            cols = page_serde().deserialize_columns(blob)
            v = cols.get("v0000")
            if v is None:
                continue
            n_null = int((~v.astype(bool)).sum())
            if p != 0:
                assert n_null == 0, f"NULL-key row routed to partition {p}"
            null_rows += n_null
    assert null_rows == 3


def test_distributed_group_by_nullable_key(engine):
    # one NULL group even when rows spread across partitions
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory", distributed=True)
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table t (g bigint, v bigint)")
    vals = [(i % 3 if i % 4 else None, i) for i in range(40)]
    eng.execute(
        "insert into t values "
        + ", ".join(f"({'null' if g is None else g}, {v})" for g, v in vals)
    )
    rows = eng.execute("select g, count(*) from t group by g order by g nulls first")
    expect = {}
    for g, _ in vals:
        expect[g] = expect.get(g, 0) + 1
    assert rows == sorted(
        expect.items(), key=lambda kv: (kv[0] is not None, kv[0] or 0)
    )


# ------------------------------------------------------------ native serde


def test_serde_all_empty_string_dictionary_roundtrip():
    from trino_tpu.native import page_serde

    cols = {
        "s": np.array(["", "", ""], dtype=object),
        "x": np.arange(3, dtype=np.int64),
    }
    out = page_serde().serialize_columns(cols)
    back = page_serde().deserialize_columns(out)
    assert list(back["s"]) == ["", "", ""]
    assert len(back["x"]) == 3


def test_serde_truncated_frame_rejected():
    from trino_tpu.native import page_serde

    cols = {"x": np.arange(100, dtype=np.int64)}
    blob = page_serde().serialize_columns(cols)
    for cut in (4, 10, len(blob) // 2):
        with pytest.raises(Exception):
            page_serde().deserialize_columns(blob[:cut])
