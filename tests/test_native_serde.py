"""C++ page serde tests (native/pageserde.cpp via ctypes) — round-trip,
compression effectiveness, corruption detection (the reference's
TestPagesSerde coverage)."""

import numpy as np
import pytest

from trino_tpu.native import PageSerde, page_serde


def test_native_build():
    serde = page_serde()
    assert serde.native, "C++ serde failed to build (g++/zstd expected in image)"


def test_roundtrip_buffers():
    serde = page_serde()
    bufs = [np.arange(10000, dtype=np.int64).tobytes(), b"hello world" * 100, b""]
    wire = serde.serialize(bufs, nrows=10000)
    back, nrows = serde.deserialize(wire)
    assert nrows == 10000
    assert back == bufs


def test_compression_kicks_in():
    serde = page_serde()
    repetitive = np.zeros(100_000, dtype=np.int64).tobytes()
    wire = serde.serialize([repetitive], nrows=100_000)
    assert len(wire) < len(repetitive) // 10


def test_roundtrip_columns():
    serde = page_serde()
    cols = {
        "a": np.arange(1000, dtype=np.int64),
        "b": np.linspace(0, 1, 1000),
        "s": np.asarray([f"val{i % 7}" for i in range(1000)], dtype=object),
        "d": np.arange(1000, dtype=np.int32),
    }
    wire = serde.serialize_columns(cols)
    back = serde.deserialize_columns(wire)
    assert sorted(back) == sorted(cols)
    for k in cols:
        if cols[k].dtype == object:
            assert list(back[k]) == list(cols[k])
        else:
            np.testing.assert_array_equal(back[k], cols[k])


def test_corruption_detected():
    serde = page_serde()
    if not serde.native:
        pytest.skip("python fallback has no checksum")
    wire = bytearray(serde.serialize([b"x" * 10000], nrows=1))
    wire[len(wire) // 2] ^= 0xFF
    with pytest.raises(RuntimeError):
        serde.deserialize(bytes(wire))


def test_empty_page():
    serde = page_serde()
    wire = serde.serialize_columns({})
    assert serde.deserialize_columns(wire) == {}
