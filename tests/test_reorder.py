"""Join-reordering pass (plan/reorder.py): plan change, correctness, and the
capacity (peak intermediate size) win.

Reference behavior being matched: iterative/rule/ReorderJoins.java — the
optimizer rewrites a syntactically bad join order into the cost-optimal one
using stats, without changing results.
"""

import numpy as np
import pytest

from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.spi import ColumnSchema
from trino_tpu.data.types import BIGINT, VARCHAR
from trino_tpu.plan.nodes import Join, TableScan, walk
from trino_tpu.runtime.engine import Engine

pytestmark = pytest.mark.smoke


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(7)
    conn = MemoryConnector()
    n_fact, n_dim, n_tiny = 20_000, 5_000, 20
    conn.create_table(
        "fact",
        [ColumnSchema("f_id", BIGINT), ColumnSchema("f_dim", BIGINT),
         ColumnSchema("f_tiny", BIGINT), ColumnSchema("f_val", BIGINT)],
    )
    conn.insert("fact", {
        "f_id": np.arange(n_fact, dtype=np.int64),
        "f_dim": rng.integers(0, n_dim, n_fact).astype(np.int64),
        "f_tiny": rng.integers(0, n_tiny, n_fact).astype(np.int64),
        "f_val": rng.integers(0, 1000, n_fact).astype(np.int64),
    })
    conn.create_table(
        "dim", [ColumnSchema("d_id", BIGINT), ColumnSchema("d_name", VARCHAR)]
    )
    conn.insert("dim", {
        "d_id": np.arange(n_dim, dtype=np.int64),
        "d_name": np.asarray([f"d{i}" for i in range(n_dim)], dtype=object),
    })
    conn.create_table(
        "tiny", [ColumnSchema("t_id", BIGINT), ColumnSchema("t_name", VARCHAR)]
    )
    conn.insert("tiny", {
        "t_id": np.arange(n_tiny, dtype=np.int64),
        "t_name": np.asarray([f"t{i}" for i in range(n_tiny)], dtype=object),
    })
    eng = Engine(default_catalog="mem")
    eng.register_catalog("mem", conn)
    return eng


# written worst-first: dim (biggest non-fact) joins first, the selective
# tiny-with-filter join last
_SQL = """
SELECT t_name, count(*) AS c, sum(f_val) AS s
FROM fact
JOIN dim ON f_dim = d_id
JOIN tiny ON f_tiny = t_id
WHERE t_id < 2
GROUP BY t_name
ORDER BY t_name
"""


def _join_leaf_order(plan):
    """Table names in scan (pre-)order — the executed join order."""
    return [n.table for n in walk(plan) if isinstance(n, TableScan)]


def test_reorder_changes_plan(engine):
    from trino_tpu.plan.optimizer import optimize

    baseline = optimize(engine.planner.plan(_SQL))  # no catalogs: no reorder
    reordered = optimize(engine.planner.plan(_SQL), engine.catalogs)
    assert _join_leaf_order(baseline) == ["fact", "dim", "tiny"]
    # the filtered tiny relation (sel 2/20 -> ~2k rows out) must join before
    # the 5k-row dim relation
    order = _join_leaf_order(reordered)
    assert order.index("tiny") < order.index("dim"), order


def test_reorder_correctness(engine):
    rows = engine.query(_SQL)
    # recompute expected with numpy over the raw columns
    conn = engine.catalogs.get("mem")
    f = conn._data["fact"]
    keep = f["f_tiny"] < 2
    expected = []
    for t in (0, 1):
        m = keep & (f["f_tiny"] == t)
        expected.append((f"t{t}", int(m.sum()), int(f["f_val"][m].sum())))
    assert rows == expected


def test_reorder_shrinks_intermediates(engine):
    """The measured win: rows actually flowing through the join operators
    drop when the selective join runs first (per-operator row counts from
    the EXPLAIN ANALYZE machinery — real executed work, not estimates)."""
    from trino_tpu.exec.compiler import LocalExecutor, _node_ids
    from trino_tpu.plan.optimizer import optimize

    def join_rows_executed(plan):
        ex = LocalExecutor(engine.catalogs, "mem")
        _, stats = ex.explain_analyze(plan)
        nodes = _node_ids(plan)
        return sum(
            s["rows"]
            for nid, s in stats.items()
            if "rows" in s and isinstance(nodes[nid], Join)
        )

    baseline = optimize(engine.planner.plan(_SQL))  # pushdown, no reorder
    reordered = optimize(engine.planner.plan(_SQL), engine.catalogs)
    rows_base = join_rows_executed(baseline)
    rows_reord = join_rows_executed(reordered)
    # bad order: fact x dim joins all 20k rows first; good order: the
    # t_id < 2 filter cuts the spine to ~2k before dim ever joins
    assert rows_reord < rows_base / 2, (rows_reord, rows_base)


def test_scalar_subquery_single_row_and_multi_row_error(engine):
    """Uncorrelated non-aggregate scalar subqueries broadcast their single
    row; more than one row raises (reference: EnforceSingleRowOperator)."""
    rows = engine.query(
        "SELECT count(*) AS c FROM fact"
        " WHERE f_tiny = (SELECT t_id FROM tiny WHERE t_id = 3)"
    )
    conn = engine.catalogs.get("mem")
    expected = int((conn._data["fact"]["f_tiny"] == 3).sum())
    assert rows == [(expected,)]

    import pytest as _pytest

    with _pytest.raises(Exception, match="multiple rows"):
        engine.query(
            "SELECT count(*) AS c FROM fact"
            " WHERE f_tiny = (SELECT t_id FROM tiny WHERE t_id < 3)"
        )


def test_scalar_subquery_multi_row_error_distributed(engine):
    """The EnforceSingleRow guard also fires on the SPMD path (the count is
    pmax-reduced across devices after the gather exchange)."""
    from trino_tpu.runtime.engine import Engine

    deng = Engine(default_catalog="mem", distributed=True)
    deng.register_catalog("mem", engine.catalogs.get("mem"))
    ok = deng.query(
        "SELECT count(*) AS c FROM fact"
        " WHERE f_tiny = (SELECT t_id FROM tiny WHERE t_id = 3)"
    )
    conn = engine.catalogs.get("mem")
    import numpy as np

    assert ok == [(int((conn._data["fact"]["f_tiny"] == 3).sum()),)]
    import pytest as _pytest

    with _pytest.raises(Exception, match="multiple rows"):
        deng.query(
            "SELECT count(*) AS c FROM fact"
            " WHERE f_tiny = (SELECT t_id FROM tiny WHERE t_id < 3)"
        )
