"""Config-file deployment surface (runtime/config.py + trino_tpu.server):
etc/config.properties + etc/catalog/*.properties boot a coordinator/worker
pair the way the reference's airlift bootstrap + CatalogManager do."""

import json
import threading
import urllib.request

import pytest

pytestmark = pytest.mark.smoke


def test_load_properties_and_catalogs(tmp_path):
    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(
        "coordinator=true\n"
        "# a comment\n"
        "http-server.http.port=0\n"
        "retry-policy=TASK\n"
        "exchange.spool-dir=/tmp/spool_x\n"
        "memory.heap-headroom-per-node=123456\n"
    )
    (etc / "catalog" / "tiny.properties").write_text(
        "connector.name=tpch\ntpch.scale=0.01\n"
    )
    (etc / "catalog" / "mem.properties").write_text("connector.name=memory\n")

    from trino_tpu.runtime.config import load_catalogs, load_node_config

    cfg = load_node_config(str(etc))
    assert cfg.coordinator and cfg.retry_policy == "TASK"
    assert cfg.exchange_spool_dir == "/tmp/spool_x"
    assert cfg.cluster_memory_limit_bytes == 123456
    catalogs = load_catalogs(str(etc))
    assert sorted(catalogs.names()) == ["mem", "tiny"]
    assert catalogs.get("tiny").table_schema("region") is not None


def test_server_boot_coordinator_and_worker(tmp_path):
    """Boot a coordinator and a worker purely from etc/ files (in-process —
    the launcher's wiring, not its sleep loop) and run a query through the
    wire protocol."""
    etc_c = tmp_path / "coord" / "etc"
    (etc_c / "catalog").mkdir(parents=True)
    (etc_c / "config.properties").write_text("coordinator=true\n")
    (etc_c / "catalog" / "tpch.properties").write_text(
        "connector.name=tpch\ntpch.scale=0.01\n"
    )

    from trino_tpu.runtime.config import load_catalogs, load_node_config
    from trino_tpu.runtime.coordinator import Coordinator
    from trino_tpu.runtime.worker import Worker

    cfg = load_node_config(str(etc_c))
    catalogs = load_catalogs(str(etc_c))
    coord = Coordinator(catalogs, "tpch", port=cfg.port).start()
    try:
        etc_w = tmp_path / "worker" / "etc"
        (etc_w / "catalog").mkdir(parents=True)
        (etc_w / "config.properties").write_text(
            f"coordinator=false\ndiscovery.uri={coord.url}\ntask.concurrency=2\n"
        )
        (etc_w / "catalog" / "tpch.properties").write_text(
            "connector.name=tpch\ntpch.scale=0.01\n"
        )
        wcfg = load_node_config(str(etc_w))
        assert not wcfg.coordinator and wcfg.task_concurrency == 2
        worker = Worker(
            load_catalogs(str(etc_w)), "tpch", task_concurrency=wcfg.task_concurrency
        ).start()
        try:
            req = urllib.request.Request(
                f"{wcfg.discovery_uri}/v1/announce",
                data=json.dumps({"url": worker.url}).encode(),
            )
            urllib.request.urlopen(req, timeout=10).read()
            rows = coord.execute_query("select count(*) from region")
            assert rows == [(5,)]
        finally:
            worker.stop()
    finally:
        coord.stop()
