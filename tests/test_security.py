"""Access control (reference: security/AccessControlManager +
plugin/trino-file-based-access-control): SELECT checked per plan scan,
writes checked at statement dispatch, session properties gated."""

import pytest

from trino_tpu.runtime.security import (
    AccessDeniedError, AllowAllAccessControl, FileBasedAccessControl,
)


@pytest.fixture()
def engine():
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runtime.engine import Engine

    eng = Engine(default_catalog="memory")
    eng.register_catalog("memory", MemoryConnector())
    eng.execute("create table open_t (k bigint)")
    eng.execute("insert into open_t values (1)")
    eng.execute("create table secret_t (k bigint)")
    eng.execute("insert into secret_t values (99)")
    return eng


RULES = {
    "tables": [
        {"user": "admin", "catalog": "*", "table": "*", "privileges": ["OWNERSHIP"]},
        {"user": "*", "catalog": "memory", "table": "open_t", "privileges": ["SELECT"]},
    ],
    "session_properties": [
        {"user": "admin", "property": "*", "allow": True},
        {"user": "*", "property": "join_distribution_type", "allow": True},
    ],
}


def test_allow_all_default(engine):
    assert isinstance(engine.access_control, AllowAllAccessControl)
    assert engine.execute("select k from secret_t") == [(99,)]


def test_select_denied(engine):
    engine.access_control = FileBasedAccessControl(RULES)
    engine.user = "bob"
    assert engine.execute("select k from open_t") == [(1,)]
    with pytest.raises(AccessDeniedError):
        engine.execute("select k from secret_t")
    # denial applies through subqueries/joins too (check is per plan scan)
    with pytest.raises(AccessDeniedError):
        engine.execute("select * from open_t where k in (select k from secret_t)")


def test_write_denied(engine):
    engine.access_control = FileBasedAccessControl(RULES)
    engine.user = "bob"
    with pytest.raises(AccessDeniedError):
        engine.execute("insert into open_t values (2)")
    with pytest.raises(AccessDeniedError):
        engine.execute("delete from open_t")
    with pytest.raises(AccessDeniedError):
        engine.execute("drop table open_t")
    with pytest.raises(AccessDeniedError):
        engine.execute("create table new_t (x bigint)")


def test_admin_ownership(engine):
    engine.access_control = FileBasedAccessControl(RULES)
    engine.user = "admin"
    engine.execute("insert into secret_t values (100)")
    assert engine.execute("select count(*) from secret_t") == [(2,)]
    engine.execute("drop table secret_t")


def test_session_property_rules(engine):
    engine.access_control = FileBasedAccessControl(RULES)
    engine.user = "bob"
    engine.execute("set session join_distribution_type = 'BROADCAST'")
    with pytest.raises(AccessDeniedError):
        engine.execute("set session broadcast_join_row_limit = 10")
    engine.user = "admin"
    engine.execute("set session broadcast_join_row_limit = 10")
