"""L0 data plane tests: types, pages, dictionary encoding, TPC-H generator."""

import numpy as np
import pytest

from trino_tpu.data.page import Column, Dictionary, Page
from trino_tpu.data.types import BIGINT, DATE, DOUBLE, VARCHAR, date_to_days, parse_type
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.connectors.tpch.generator import TPCH_SCHEMAS


def test_parse_type():
    assert parse_type("bigint") is BIGINT
    assert parse_type("varchar(25)").is_string
    assert parse_type("decimal(12,2)").scale == 2


def test_dictionary_roundtrip():
    codes, d = Dictionary.encode(["b", "a", "b", "c"])
    assert [d.values[c] for c in codes] == ["b", "a", "b", "c"]
    assert d.code_of("c") == codes[3]
    assert d.code_of("zzz") == -1
    mask = d.mask_where(lambda v: v >= "b")
    assert list(mask[codes]) == [True, False, True, True]


def test_page_to_pylist_with_live_mask():
    import jax.numpy as jnp

    page = Page.from_numpy(
        [BIGINT, DOUBLE, VARCHAR, DATE],
        [
            np.array([1, 2, 3]),
            np.array([1.5, 2.5, 3.5]),
            np.array(["x", "y", "x"], dtype=object),
            np.array([date_to_days("1994-01-01")] * 3),
        ],
    )
    page = page.with_live(jnp.asarray(np.array([True, False, True])))
    rows = page.to_pylist()
    assert rows == [(1, 1.5, "x", "1994-01-01"), (3, 3.5, "x", "1994-01-01")]
    assert int(page.row_count()) == 2


def test_tpch_generator_shapes(tpch_tiny):
    assert len(tpch_tiny["region"]["r_regionkey"]) == 5
    assert len(tpch_tiny["nation"]["n_nationkey"]) == 25
    assert len(tpch_tiny["orders"]["o_orderkey"]) == 15_000
    n_lines = len(tpch_tiny["lineitem"]["l_orderkey"])
    assert 45_000 < n_lines < 75_000
    # schema columns all present, deterministic regeneration
    for t, schema in TPCH_SCHEMAS.items():
        assert set(tpch_tiny[t]) == {c for c, _ in schema}
    from trino_tpu.connectors.tpch.generator import generate_table

    again = generate_table("supplier", 0.01)
    assert np.array_equal(again["s_acctbal"], tpch_tiny["supplier"]["s_acctbal"])


def test_tpch_orders_lineitem_consistency(tpch_tiny):
    """o_totalprice must equal the sum over the order's lines.

    Money columns are DECIMAL(12,2) scaled-int64 lanes; unscale to compute."""
    li, od = {k: v / 100.0 for k, v in tpch_tiny["lineitem"].items()
              if k in ("l_extendedprice", "l_tax", "l_discount")}, tpch_tiny["orders"]
    li["l_orderkey"] = tpch_tiny["lineitem"]["l_orderkey"]
    line_total = np.round(li["l_extendedprice"] * (1 + li["l_tax"]) * (1 - li["l_discount"]), 2)
    keys = {k: i for i, k in enumerate(od["o_orderkey"])}
    sums = np.zeros(len(od["o_orderkey"]))
    for k, v in zip(li["l_orderkey"], line_total):
        sums[keys[k]] += v
    assert np.allclose(np.round(sums, 2), od["o_totalprice"] / 100.0, atol=0.05)


def test_connector_splits(tpch_tiny):
    conn = TpchConnector(0.01)
    splits = conn.get_splits("orders", 4)
    assert len(splits) == 4
    parts = [conn.read_split(s, ["o_orderkey"]) for s in splits]
    combined = np.concatenate([p["o_orderkey"] for p in parts])
    assert np.array_equal(combined, tpch_tiny["orders"]["o_orderkey"])


def test_oracle_basics(oracle):
    (count,) = oracle.query("SELECT count(*) FROM nation")[0]
    assert count == 25
    rows = oracle.query(
        "SELECT n_name FROM nation, region WHERE n_regionkey = r_regionkey AND r_name = 'ASIA'"
    )
    assert {r[0] for r in rows} == {"INDIA", "INDONESIA", "JAPAN", "CHINA", "VIETNAM"}


def test_oracle_translation():
    from tests.oracle import to_sqlite

    out = to_sqlite("SELECT * FROM t WHERE d < date '1994-01-01' + interval '1' year")
    assert "date('1994-01-01', '+1 years')" in out
    out = to_sqlite("SELECT extract(year from o_orderdate) FROM orders")
    assert "strftime" in out
