"""Expression evaluation over device pages.

This is the replacement for the reference's runtime bytecode generation
(sql/gen/ExpressionCompiler.java:38, PageFunctionCompiler.java:103): instead
of emitting JVM bytecode per expression, IR expressions are traced into the
enclosing jax.jit as vectorized jnp ops, so XLA fuses filter+project chains
into single kernels for free.

Value model: every IR expression evaluates to a ColumnVal
    data  : jnp array [capacity]  (for VARCHAR: int32 dictionary codes)
    valid : bool mask or None (None == all valid) — SQL NULLs
    dict  : host Dictionary for VARCHAR values (static at trace time)

NULL semantics are Kleene 3-valued logic for and/or, strict for everything
else (reference: sql/ir + interpreter semantics).

Dictionary-encoded strings: any string operation (comparison with a literal,
LIKE, substring, IN list) is evaluated ONCE per distinct dictionary value on
the host at trace time, producing a lookup table the device gathers by code
— the reference's DictionaryAwarePageProjection fast path made the only
path, which is exactly what a TPU wants (no varlen bytes in HBM).
"""

from __future__ import annotations

import re
import threading
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..data.page import Column, Dictionary, Page
from ..data.types import BOOLEAN, DATE, DOUBLE, Type, UNKNOWN, VARCHAR
from ..plan.ir import Call, CaseWhen, Const, FieldRef, InListIr, IrExpr, LikeIr, Param

__all__ = [
    "ColumnVal", "eval_expr", "eval_predicate", "column_val", "to_column",
    "param_context",
]


class _ParamContext(threading.local):
    """Prepared-statement parameter values live here during a plan trace
    (exec/compiler.py pushes around _trace_plan).  Inside jit the values are
    tracers — ir.Param evaluates to a runtime scalar broadcast, never a
    trace-time constant, so one compiled program serves every binding."""

    def __init__(self):
        self.values = ()


_PARAMS = _ParamContext()


@contextmanager
def param_context(values):
    prev = _PARAMS.values
    _PARAMS.values = tuple(values) if values is not None else ()
    try:
        yield
    finally:
        _PARAMS.values = prev


@dataclass
class ColumnVal:
    data: jnp.ndarray
    valid: Optional[jnp.ndarray]
    dict: Optional[Dictionary] = None
    type: Optional[Type] = None
    # decimal128 high limb (data/dec128.py): value = data2*2^64 + u64(data).
    # None for every non-limbed column; ops that cannot carry the second
    # lane (sorts, joins, exchanges) raise rather than silently truncate.
    data2: Optional[jnp.ndarray] = None


def column_val(col: Column) -> ColumnVal:
    return ColumnVal(col.data, col.valid, col.dictionary, col.type, col.data2)


def to_column(v: ColumnVal, type_: Type) -> Column:
    return Column(type_, v.data, v.valid, v.dict, v.data2)


def _and_valid(a: Optional[jnp.ndarray], b: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _valid_mask(v: ColumnVal) -> jnp.ndarray:
    if v.valid is None:
        return jnp.ones(v.data.shape, dtype=jnp.bool_)
    return v.valid


def eval_expr(e: IrExpr, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    """Evaluate IR over the input columns; n = page capacity (for consts)."""
    if isinstance(e, FieldRef):
        return cols[e.index]
    if isinstance(e, Const):
        return _const_val(e, n)
    if isinstance(e, Param):
        return _param_val(e, n)
    if isinstance(e, Call):
        return _call(e, cols, n)
    if isinstance(e, CaseWhen):
        return _case(e, cols, n)
    if isinstance(e, InListIr):
        return _in_list(e, cols, n)
    if isinstance(e, LikeIr):
        return _like(e, cols, n)
    raise NotImplementedError(f"eval: {e}")


def eval_predicate(e: IrExpr, cols: Sequence[ColumnVal], n: int) -> jnp.ndarray:
    """Boolean predicate -> selection mask (NULL -> False, the reference's
    FilterAndProject semantics)."""
    v = eval_expr(e, cols, n)
    m = v.data.astype(jnp.bool_)
    if v.valid is not None:
        m = m & v.valid
    return m


# ----------------------------------------------------------------- literals


def _const_val(e: Const, n: int) -> ColumnVal:
    if e.type.is_array:
        # array literal: 1-entry dictionary of tuples (same lowering as
        # string literals); NULL array -> all-invalid codes
        v = () if e.value is None else tuple(e.value)
        d = Dictionary(_obj_array([v]))
        valid = jnp.zeros((n,), dtype=jnp.bool_) if e.value is None else None
        return ColumnVal(jnp.zeros((n,), dtype=jnp.int32), valid, d, e.type)
    if e.value is None:
        if e.type.is_string:
            # typed NULL varchar (e.g. GROUPING SETS null-extends a key):
            # 1-entry dictionary keeps the string machinery uniform
            d = Dictionary(np.asarray([""], dtype=object))
            return ColumnVal(
                jnp.zeros((n,), dtype=jnp.int32),
                jnp.zeros((n,), dtype=jnp.bool_),
                d,
                e.type,
            )
        dt = jnp.bool_ if e.type == BOOLEAN else _np_to_jnp(e.type)
        return ColumnVal(
            jnp.zeros((n,), dtype=dt), jnp.zeros((n,), dtype=jnp.bool_), None, e.type
        )
    if e.type == VARCHAR:
        # a string literal used as a value (not in a comparison): 1-entry dict
        d = Dictionary(np.asarray([e.value], dtype=object))
        return ColumnVal(jnp.zeros((n,), dtype=jnp.int32), None, d, e.type)
    if (
        e.type.is_decimal
        and isinstance(e.value, int)
        and not -(1 << 63) <= e.value < (1 << 63)
    ):
        # beyond-int64 decimal literal: two-limb lanes (data/dec128.py)
        from ..data.dec128 import split_py

        hi, lo = split_py(e.value)
        return ColumnVal(
            jnp.full((n,), lo, dtype=jnp.int64), None, None, e.type,
            data2=jnp.full((n,), hi, dtype=jnp.int64),
        )
    return ColumnVal(
        jnp.full((n,), e.value, dtype=_np_to_jnp(e.type)), None, None, e.type
    )


def _param_val(e: Param, n: int) -> ColumnVal:
    values = _PARAMS.values
    if e.index >= len(values):
        raise NotImplementedError(
            f"parameter ${e.index} evaluated outside a binding context"
        )
    dt = jnp.bool_ if e.type == BOOLEAN else _np_to_jnp(e.type)
    scalar = jnp.asarray(values[e.index]).astype(dt)
    return ColumnVal(jnp.broadcast_to(scalar, (n,)), None, None, e.type)


def _np_to_jnp(t: Type):
    return jnp.dtype(t.np_dtype)


# -------------------------------------------------------------------- calls


_HOF_OPS = {
    "transform", "filter_arr", "reduce", "any_match", "all_match",
    "none_match", "zip_with", "transform_keys", "transform_values",
    "map_filter",
}


def _call(e: Call, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    op = e.op
    if op in _HOF_OPS:
        return _hof_fn(op, e, cols, n)
    if op == "map_construct":
        return _map_construct(e, cols, n)
    if op in ("and", "or"):
        return _kleene(op, e, cols, n)
    if op == "not":
        a = eval_expr(e.args[0], cols, n)
        return ColumnVal(~a.data.astype(jnp.bool_), a.valid, None, BOOLEAN)
    if op == "is_null":
        a = eval_expr(e.args[0], cols, n)
        data = (
            jnp.zeros((n,), dtype=jnp.bool_) if a.valid is None else ~a.valid
        )
        return ColumnVal(data, None, None, BOOLEAN)
    if op == "coalesce":
        vals = [eval_expr(a, cols, n) for a in e.args]
        if any(v.data2 is not None for v in vals):
            raise NotImplementedError("decimal128 through coalesce")
        out = vals[-1]
        for v in reversed(vals[:-1]):
            if v.valid is None:
                out = v
            else:
                # merge in a dtype wide enough for BOTH branches: lanes may
                # be narrowed int32 (data/page.py) while the fallback still
                # carries true int64 values — casting the fallback down
                # would silently truncate it
                merged = jnp.promote_types(v.data.dtype, out.data.dtype)
                out = ColumnVal(
                    jnp.where(
                        v.valid, v.data.astype(merged), out.data.astype(merged)
                    ),
                    None if out.valid is None else (v.valid | out.valid),
                    v.dict,
                    v.type,
                )
        return out
    if op == "cast":
        a = eval_expr(e.args[0], cols, n)
        return _cast(a, e.type, n)
    if op == "substring":
        return _substring(e, cols, n)
    if op == "length":
        a = eval_expr(e.args[0], cols, n)
        table = np.asarray([len(v) for v in a.dict.values], dtype=np.int64)
        return ColumnVal(jnp.take(jnp.asarray(table), a.data), a.valid, None, e.type)
    if op in ("extract_year", "extract_month", "extract_day"):
        a = eval_expr(e.args[0], cols, n)
        y, m, d = _civil_from_days(a.data.astype(jnp.int64))
        out = {"extract_year": y, "extract_month": m, "extract_day": d}[op]
        return ColumnVal(out, a.valid, None, e.type)
    if op == "add_days":
        a = eval_expr(e.args[0], cols, n)
        b = eval_expr(e.args[1], cols, n)
        return ColumnVal(
            (a.data.astype(jnp.int64) + b.data.astype(jnp.int64)).astype(a.data.dtype),
            _and_valid(a.valid, b.valid),
            None,
            DATE,
        )

    args = [eval_expr(a, cols, n) for a in e.args]

    # comparisons involving dictionary-encoded strings -> host tables
    if op in ("eq", "ne", "lt", "le", "gt", "ge") and any(
        v.dict is not None for v in args
    ):
        return _string_compare(op, args, e, n)

    valid = None
    for v in args:
        valid = _and_valid(valid, v.valid)

    if (
        op in ("neg", "abs", "eq", "ne", "lt", "le", "gt", "ge", "add", "sub",
               "mul", "div", "mod")
        and any(v.data2 is not None for v in args)
    ) or (
        # single-lane decimal arithmetic whose RESULT type exceeds int64
        # digits: compute at 128-bit width rather than silently wrapping
        # the int64 lanes (reference: Int128Math.multiply / add)
        op in ("add", "sub", "mul")
        and e.type.is_decimal
        and e.type.precision > 18
        and all(v.type is not None and v.type.is_decimal for v in args)
    ):
        return _limbed_op(op, args, valid, e)
    if op == "neg":
        return ColumnVal(-args[0].data, valid, None, e.type)
    if op in ("eq", "ne", "lt", "le", "gt", "ge"):
        a, b = args[0].data, args[1].data
        a, b = _numeric_align(a, b)
        fn = {
            "eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less,
            "le": jnp.less_equal, "gt": jnp.greater, "ge": jnp.greater_equal,
        }[op]
        return ColumnVal(fn(a, b), valid, None, BOOLEAN)
    if op in ("add", "sub", "mul", "div", "mod"):
        a, b = _numeric_align(args[0].data, args[1].data)
        target = _np_to_jnp(e.type)
        a = a.astype(target)
        b = b.astype(target)
        if op == "add":
            out = a + b
        elif op == "sub":
            out = a - b
        elif op == "mul":
            out = a * b
        elif op == "div":
            if e.type.is_floating:
                out = a / jnp.where(b == 0, jnp.ones_like(b), b)
                valid = _and_valid(valid, b != 0)
            else:
                safe_b = jnp.where(b == 0, jnp.ones_like(b), b)
                out = (
                    jnp.sign(a) * jnp.sign(safe_b) * (jnp.abs(a) // jnp.abs(safe_b))
                ).astype(target)  # SQL truncating division
                valid = _and_valid(valid, b != 0)
        else:  # mod (sign of dividend, SQL semantics)
            safe_b = jnp.where(b == 0, jnp.ones_like(b), b)
            out = a - safe_b * (
                jnp.sign(a) * jnp.sign(safe_b) * (jnp.abs(a) // jnp.abs(safe_b))
            ).astype(target) if not e.type.is_floating else jnp.fmod(a, safe_b)
            valid = _and_valid(valid, b != 0)
        return ColumnVal(out, valid, None, e.type)
    if op == "abs":
        return ColumnVal(jnp.abs(args[0].data), valid, None, e.type)
    if op == "round":
        if len(args) == 2:
            # digit count is a plan-time literal, never a traced lane
            assert isinstance(e.args[1], Const), "round() digits must be a literal"
            digits = int(e.args[1].value)
            f = 10.0 ** digits
            return ColumnVal(jnp.round(args[0].data * f) / f, valid, None, e.type)
        return ColumnVal(jnp.round(args[0].data), valid, None, e.type)
    if op == "floor":
        return ColumnVal(jnp.floor(args[0].data.astype(jnp.float64)), valid, None, e.type)
    if op == "ceil":
        return ColumnVal(jnp.ceil(args[0].data.astype(jnp.float64)), valid, None, e.type)
    if op == "sqrt":
        return ColumnVal(jnp.sqrt(args[0].data.astype(jnp.float64)), valid, None, e.type)
    if op == "power":
        a, b = _numeric_align(args[0].data, args[1].data)
        return ColumnVal(
            jnp.power(a.astype(jnp.float64), b.astype(jnp.float64)), valid, None, e.type
        )

    # ---- float math (f64 lanes on the VPU) --------------------------------
    _F64_UNARY = {
        "ln": jnp.log, "log2": jnp.log2, "log10": jnp.log10, "exp": jnp.exp,
        "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
        "acos": jnp.arccos, "atan": jnp.arctan, "cbrt": jnp.cbrt,
        "degrees": jnp.degrees, "radians": jnp.radians,
    }
    if op in _F64_UNARY:
        x = args[0].data.astype(jnp.float64)
        out = _F64_UNARY[op](x)
        # domain errors are NULL, not NaN (SQL semantics)
        dom = ~jnp.isnan(out)
        return ColumnVal(out, _and_valid(valid, dom), None, e.type)
    if op == "atan2":
        a, b = _numeric_align(args[0].data, args[1].data)
        return ColumnVal(
            jnp.arctan2(a.astype(jnp.float64), b.astype(jnp.float64)),
            valid, None, e.type,
        )
    if op == "sign":
        return ColumnVal(jnp.sign(args[0].data), valid, None, e.type)
    if op == "truncate":
        x = args[0].data.astype(jnp.float64)
        if len(e.args) == 2:
            assert isinstance(e.args[1], Const), "truncate() scale must be a literal"
            f = 10.0 ** int(e.args[1].value)
            return ColumnVal(jnp.trunc(x * f) / f, valid, None, e.type)
        return ColumnVal(jnp.trunc(x), valid, None, e.type)
    if op in ("bitwise_and", "bitwise_or", "bitwise_xor", "shift_left", "shift_right"):
        a = args[0].data.astype(jnp.int64)
        b = args[1].data.astype(jnp.int64)
        out = {
            "bitwise_and": lambda: a & b,
            "bitwise_or": lambda: a | b,
            "bitwise_xor": lambda: a ^ b,
            "shift_left": lambda: a << b,
            "shift_right": lambda: a >> b,
        }[op]()
        return ColumnVal(out, valid, None, e.type)

    # ---- conditional ------------------------------------------------------
    if op == "nullif":
        a, b = args
        if a.dict is not None or b.dict is not None:
            eqv = _string_compare("eq", [a, b], e, n)
            eq_mask = eqv.data.astype(jnp.bool_)
        else:
            x, y = _numeric_align(a.data, b.data)
            eq_mask = x == y
        bv = jnp.ones((n,), jnp.bool_) if b.valid is None else b.valid
        both = eq_mask & bv  # NULLIF only nulls when b is non-null and equal
        av = jnp.ones((n,), jnp.bool_) if a.valid is None else a.valid
        return ColumnVal(a.data, av & ~both, a.dict, a.type)
    if op in ("greatest", "least"):
        fn = jnp.maximum if op == "greatest" else jnp.minimum
        acc = args[0].data.astype(_np_to_jnp(e.type))
        for v in args[1:]:
            acc = fn(acc, v.data.astype(_np_to_jnp(e.type)))
        return ColumnVal(acc, valid, None, e.type)  # NULL if any arg NULL

    # ---- date -------------------------------------------------------------
    if op in ("extract_quarter", "extract_dow", "extract_doy", "extract_week"):
        a = args[0]
        days = a.data.astype(jnp.int64)
        y, m, d = _civil_from_days(days)
        if op == "extract_quarter":
            out = (m + 2) // 3
        elif op == "extract_dow":
            # ISO day-of-week 1..7 (Mon=1); epoch 1970-01-01 was a Thursday
            out = (days + 3) % 7 + 1
        else:
            jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
            doy = days - jan1 + 1
            if op == "extract_doy":
                out = doy
            else:  # ISO week number (approximation: week of Jan-4 anchor)
                jan4 = jan1 + 3
                wk_anchor = jan4 - ((jan4 + 3) % 7)
                out = jnp.maximum((days - wk_anchor) // 7 + 1, 1)
        return ColumnVal(out, a.valid, None, e.type)
    if op == "date_trunc":
        # unit is compile-time constant (args[1] folded by the planner)
        unit = e.args[1].value  # type: ignore[union-attr]
        a = args[0]
        days = a.data.astype(jnp.int64)
        y, m, d = _civil_from_days(days)
        one = jnp.ones_like(m)
        if unit == "year":
            out = _days_from_civil(y, one, one)
        elif unit == "quarter":
            out = _days_from_civil(y, ((m - 1) // 3) * 3 + 1, one)
        elif unit == "month":
            out = _days_from_civil(y, m, one)
        elif unit == "week":  # ISO week start (Monday)
            out = days - (days + 3) % 7
        elif unit == "day":
            out = days
        else:
            raise NotImplementedError(f"date_trunc unit {unit}")
        return ColumnVal(out.astype(a.data.dtype), a.valid, None, DATE)
    if op == "last_day_of_month":
        a = args[0]
        days = a.data.astype(jnp.int64)
        y, m, _ = _civil_from_days(days)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        out = _days_from_civil(ny, nm, jnp.ones_like(nm)) - 1
        return ColumnVal(out.astype(a.data.dtype), a.valid, None, DATE)
    if op == "date_diff_days":
        a, b = args
        out = b.data.astype(jnp.int64) - a.data.astype(jnp.int64)
        return ColumnVal(out, valid, None, e.type)

    if op == "try_cast":
        # varchar -> T with failures as NULL (reference: TryCastFunction):
        # parse once per distinct dictionary value on the host
        a = args[0]
        target = e.type
        from ..data.types import date_to_days as _d2d

        parsed, ok = [], []
        for v in a.dict.values:
            s = str(v).strip()
            try:
                if target == DATE:
                    p = _d2d(s)
                elif target.is_decimal:
                    p = int(round(float(s) * (10.0**target.scale)))
                elif target.is_floating:
                    p = float(s)
                elif target == BOOLEAN:
                    p = {"true": True, "false": False}[s.lower()]
                else:
                    p = int(s)
                parsed.append(p)
                ok.append(True)
            except Exception:
                parsed.append(0)
                ok.append(False)
        table = jnp.asarray(np.asarray(parsed, dtype=target.np_dtype))
        ok_lane = jnp.take(jnp.asarray(np.asarray(ok, dtype=bool)), a.data)
        return ColumnVal(
            jnp.take(table, a.data), _and_valid(a.valid, ok_lane), None, target
        )

    # ---- json (host maps over the dictionary) -----------------------------
    if op in ("json_extract_scalar", "json_extract", "json_array_length",
              "json_size"):
        return _json_fn(op, e, args, n)

    # ---- maps / rows (host maps over the dictionary of distinct values) ---
    if op in ("map_element_at", "map_keys", "map_values") or (
        op == "cardinality" and e.args[0].type.is_map
    ):
        return _map_fn(op, e, args, n)
    if op == "row_field":
        return _row_field(e, args, n)

    # ---- arrays (host maps over the dictionary of distinct arrays) --------
    if op in ("cardinality", "element_at", "contains", "array_position",
              "array_distinct", "array_sort", "array_join", "array_min",
              "array_max"):
        return _array_fn(op, e, args, n)
    if op == "split":
        delim = _const_str(e.args[1])
        a = args[0]
        new_vals = [tuple(str(v).split(delim)) for v in a.dict.values]
        uniq, remap = np.unique(_obj_array(new_vals), return_inverse=True)
        codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
        return ColumnVal(codes, a.valid, Dictionary(uniq), e.type)

    # ---- strings (host maps over the dictionary, device gathers) ----------
    if op in _STR_UNARY:
        return _dict_map_str(args[0], _STR_UNARY[op], e.type)
    if op in ("replace", "strpos", "starts_with", "lpad", "rpad", "split_part",
              "regexp_like", "regexp_replace", "regexp_extract", "concat_str"):
        return _string_fn(op, e, args, n)
    raise NotImplementedError(f"call op: {op}")


def _json_path(path: str):
    """Parse the JSONPath subset '$', '$.key', '$[i]', '$.a[1].b'
    (reference: the json-path grammar JsonPath.g4; this covers the
    json_extract_scalar usage the docs call the 'simple' paths)."""
    import re as _re

    if not path.startswith("$"):
        raise ValueError(f"invalid JSON path: {path!r}")
    steps = []
    pos = 0
    rest = path[1:]
    for m in _re.finditer(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]|\[\"([^\"]+)\"\]", rest):
        if m.start() != pos:  # unparsed segment => unsupported path syntax
            raise ValueError(f"unsupported JSON path: {path!r}")
        pos = m.end()
        if m.group(1) is not None:
            steps.append(m.group(1))
        elif m.group(2) is not None:
            steps.append(int(m.group(2)))
        else:
            steps.append(m.group(3))
    if pos != len(rest):
        raise ValueError(f"unsupported JSON path: {path!r}")
    return steps


def _json_eval(text: str, steps):
    import json as _json

    try:
        v = _json.loads(text)
    except Exception:
        return None, False
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or s >= len(v):
                return None, False
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None, False
            v = v[s]
    return v, True


def _json_fn(op: str, e: Call, args: list, n: int) -> ColumnVal:
    """JSON functions over dict-coded varchar: parse each distinct value
    once on the host (reference: operator/scalar/JsonFunctions +
    JsonExtract)."""
    import json as _json

    a = args[0]
    steps = _json_path(_const_str(e.args[1])) if len(e.args) > 1 else []
    raw = []
    for v in a.dict.values:
        val, found = _json_eval(str(v), steps)
        if op == "json_extract_scalar":
            if not found or isinstance(val, (dict, list)) or val is None:
                raw.append(None)
            elif isinstance(val, bool):
                raw.append("true" if val else "false")
            else:
                raw.append(str(val))
        elif op == "json_extract":
            raw.append(_json.dumps(val, separators=(",", ":")) if found else None)
        elif op == "json_array_length":
            raw.append(len(val) if found and isinstance(val, list) else None)
        else:  # json_size: members of object/array, 0 for scalars
            if not found:
                raw.append(None)
            elif isinstance(val, (dict, list)):
                raw.append(len(val))
            else:
                raw.append(0)
    ok = np.asarray([r is not None for r in raw], dtype=bool)
    ok_lane = jnp.take(jnp.asarray(ok), a.data)
    valid = _and_valid(a.valid, ok_lane)
    if op in ("json_array_length", "json_size"):
        table = np.asarray([r if r is not None else 0 for r in raw], dtype=np.int64)
        return ColumnVal(jnp.take(jnp.asarray(table), a.data), valid, None, e.type)
    uniq, remap = np.unique(
        np.asarray([r if r is not None else "" for r in raw], dtype=object),
        return_inverse=True,
    )
    codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
    return ColumnVal(codes, valid, Dictionary(uniq), e.type)


def _obj_array(items) -> np.ndarray:
    """Object ndarray of python values built element-wise (np.asarray would
    promote equal-length tuples to a 2-D array)."""
    out = np.empty(len(items), dtype=object)
    for i, v in enumerate(items):
        out[i] = v
    return out


def _dict_object_out(values, base: ColumnVal, out_type) -> ColumnVal:
    """Re-encode per-distinct host results as a new dict column gathered by
    the base column's codes."""
    uniq, remap = np.unique(_obj_array(values), return_inverse=True)
    codes = jnp.take(jnp.asarray(remap.astype(np.int32)), base.data)
    return ColumnVal(codes, base.valid, Dictionary(uniq), out_type)


def _map_fn(op: str, e: Call, args: list[ColumnVal], n: int) -> ColumnVal:
    """Map functions over dict-coded MAP columns (canonical form: key-sorted
    tuple of (k, v) pairs) — the per-distinct-value host strategy (reference:
    MapBlock + scalar map functions, operator/scalar/MapKeys etc.)."""
    m = args[0]
    vals = m.dict.values  # object array of pair-tuples
    if op == "cardinality":
        table = jnp.asarray(np.asarray([len(v) for v in vals], dtype=np.int64))
        return ColumnVal(jnp.take(table, m.data), m.valid, None, e.type)
    if op in ("map_keys", "map_values"):
        ix = 0 if op == "map_keys" else 1
        return _dict_object_out(
            [tuple(p[ix] for p in v) for v in vals], m, e.type
        )
    # map_element_at: m[key]; literal keys are the common shape
    key_ir = e.args[1]
    assert isinstance(key_ir, Const), "map subscript key must be a literal"
    want = key_ir.value
    picked = [dict(v).get(want) for v in vals]
    ok = np.asarray([p is not None for p in picked], dtype=bool)
    vt = e.type
    if vt.is_string:
        uniq, remap = np.unique(
            np.asarray([p if p is not None else "" for p in picked], dtype=object),
            return_inverse=True,
        )
        codes = jnp.take(jnp.asarray(remap.astype(np.int32)), m.data)
        okl = jnp.take(jnp.asarray(ok), m.data)
        return ColumnVal(codes, _and_valid(m.valid, okl), Dictionary(uniq), vt)
    table = jnp.asarray(
        np.asarray([p if p is not None else 0 for p in picked], dtype=vt.np_dtype)
    )
    out = jnp.take(table, m.data)
    okl = jnp.take(jnp.asarray(ok), m.data)
    return ColumnVal(out, _and_valid(m.valid, okl), None, vt)


def _row_field(e: Call, args: list[ColumnVal], n: int) -> ColumnVal:
    """row.field access: gather a per-distinct field table by row code
    (reference: RowBlock field blocks + DereferenceExpression)."""
    r = args[0]
    ix = int(e.args[1].value)  # Const field index, planner-resolved
    vals = r.dict.values  # tuples of field values
    ft = e.type
    picked = [v[ix] if ix < len(v) else None for v in vals]
    ok = np.asarray([p is not None for p in picked], dtype=bool)
    if ft.is_string or ft.is_dict_object:
        return _dict_object_out(
            [p if p is not None else ("" if ft.is_string else ()) for p in picked],
            r, ft,
        ) if ft.is_dict_object else _dict_object_str(picked, r, ft, ok)
    table = jnp.asarray(
        np.asarray([p if p is not None else 0 for p in picked], dtype=ft.np_dtype)
    )
    okl = jnp.take(jnp.asarray(ok), r.data)
    return ColumnVal(jnp.take(table, r.data), _and_valid(r.valid, okl), None, ft)


def _dict_object_str(picked, base: ColumnVal, ft, ok) -> ColumnVal:
    uniq, remap = np.unique(
        np.asarray([p if p is not None else "" for p in picked], dtype=object),
        return_inverse=True,
    )
    codes = jnp.take(jnp.asarray(remap.astype(np.int32)), base.data)
    okl = jnp.take(jnp.asarray(ok), base.data)
    return ColumnVal(codes, _and_valid(base.valid, okl), Dictionary(uniq), ft)


# ---------------------------------------------------------------- lambdas


def _py_eval(ir, env: dict):
    """Host interpreter for lambda bodies over python scalars (reference:
    LambdaBytecodeGenerator compiles these to JVM bytecode; here dictionary
    interning means each body runs once per DISTINCT value, so an
    interpreter is cheap).  Returns a python value; None == SQL NULL."""
    from ..plan.ir import (
        CaseWhen as _CW, Call as _Call, Const as _Const, InListIr as _InL,
        LambdaVarIr as _LV, LikeIr as _Like,
    )

    if isinstance(ir, _Const):
        v = ir.value
        if v is not None and ir.type.is_decimal:
            return v / (10.0 ** ir.type.scale)
        return v
    if isinstance(ir, _LV):
        return env[ir.name]
    if isinstance(ir, _CW):
        for cond, res in ir.whens:
            if _py_eval(cond, env) is True:
                return _py_eval(res, env)
        return None if ir.default is None else _py_eval(ir.default, env)
    if isinstance(ir, _InL):
        v = _py_eval(ir.operand, env)
        if v is None:
            return None
        hit = v in ir.values
        return (not hit) if ir.negated else hit
    if isinstance(ir, _Like):
        v = _py_eval(ir.operand, env)
        if v is None:
            return None
        hit = bool(_like_regex(ir.pattern).match(str(v)))
        return (not hit) if ir.negated else hit
    if not isinstance(ir, _Call):
        raise NotImplementedError(f"lambda body node {type(ir).__name__}")

    op = ir.op
    if op == "and":
        vals = [_py_eval(a, env) for a in ir.args]
        if any(v is False for v in vals):
            return False
        return None if any(v is None for v in vals) else True
    if op == "or":
        vals = [_py_eval(a, env) for a in ir.args]
        if any(v is True for v in vals):
            return True
        return None if any(v is None for v in vals) else False
    if op == "not":
        v = _py_eval(ir.args[0], env)
        return None if v is None else (not v)
    if op == "is_null":
        return _py_eval(ir.args[0], env) is None
    if op == "coalesce":
        for a in ir.args:
            v = _py_eval(a, env)
            if v is not None:
                return v
        return None
    if op == "cast":
        v = _py_eval(ir.args[0], env)
        if v is None:
            return None
        t = ir.type
        if t.is_string:
            return str(v)
        if t.is_floating or t.is_decimal:
            return float(v)
        if getattr(t, "is_integer", False):
            return int(v)
        return v

    vals = [_py_eval(a, env) for a in ir.args]
    if any(v is None for v in vals):  # strict NULL propagation
        return None
    if op == "add":
        return vals[0] + vals[1]
    if op == "sub":
        return vals[0] - vals[1]
    if op == "mul":
        return vals[0] * vals[1]
    if op == "div":
        if vals[1] == 0:
            return None
        if isinstance(vals[0], int) and isinstance(vals[1], int):
            # SQL integer division truncates toward zero; stay exact in int
            q = abs(vals[0]) // abs(vals[1])
            return -q if (vals[0] < 0) != (vals[1] < 0) else q
        return vals[0] / vals[1]
    if op == "mod":
        if vals[1] == 0:
            return None
        if isinstance(vals[0], int) and isinstance(vals[1], int):
            # sign follows the dividend (SQL), exact in int
            r = abs(vals[0]) % abs(vals[1])
            return -r if vals[0] < 0 else r
        import math as _math

        return vals[0] - vals[1] * float(_math.trunc(vals[0] / vals[1]))
    if op == "neg":
        return -vals[0]
    if op in ("eq", "ne", "lt", "le", "gt", "ge"):
        import operator as _op

        f = {"eq": _op.eq, "ne": _op.ne, "lt": _op.lt,
             "le": _op.le, "gt": _op.gt, "ge": _op.ge}[op]
        return f(vals[0], vals[1])
    if op == "abs":
        return abs(vals[0])
    if op in ("upper", "lower", "trim", "ltrim", "rtrim"):
        return {
            "upper": str.upper, "lower": str.lower, "trim": str.strip,
            "ltrim": str.lstrip, "rtrim": str.rstrip,
        }[op](str(vals[0]))
    if op == "length":
        return len(str(vals[0]))
    if op == "concat_str":
        return "".join(str(v) for v in vals)
    if op == "nullif":
        return None if vals[0] == vals[1] else vals[0]
    if op in ("sqrt", "ln", "exp", "floor", "ceil", "round", "power"):
        import math as _math

        if op == "sqrt":
            return _math.sqrt(vals[0]) if vals[0] >= 0 else None
        if op == "ln":
            return _math.log(vals[0]) if vals[0] > 0 else None
        if op == "exp":
            return _math.exp(vals[0])
        if op == "floor":
            return float(_math.floor(vals[0]))
        if op == "ceil":
            return float(_math.ceil(vals[0]))
        if op == "round":
            return round(vals[0], int(vals[1]) if len(vals) > 1 else 0)
        return float(vals[0]) ** float(vals[1])
    raise NotImplementedError(f"lambda body op {op}")


def _coerce_elem(v, t):
    """Canonicalize an interpreter result for interning (numpy scalars ->
    python; decimal results stay float — _lambda outputs are cast f64)."""
    if v is None:
        return None
    if isinstance(v, np.generic):
        v = v.item()
    if getattr(t, "is_integer", False):
        return int(v)
    if t.is_floating:
        return float(v)
    if t.is_string:
        return str(v)
    return v


def _map_construct(e: Call, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    """map(keys_array, values_array) — 2-D pair table over (key-code,
    value-code), canonical sorted-pair interning (data/types.py MapType)."""
    a = eval_expr(e.args[0], cols, n)
    b = eval_expr(e.args[1], cols, n)
    avals, bvals = a.dict.values, b.dict.values
    mat = np.zeros((len(avals), len(bvals)), dtype=np.int32)
    okm = np.zeros((len(avals), len(bvals)), dtype=bool)
    table: dict = {}
    for i, ks in enumerate(avals):
        for j, vs in enumerate(bvals):
            if len(ks) != len(vs):
                mat[i, j] = 0  # length mismatch -> NULL (Trino: error)
                continue
            d = dict(zip(ks, vs))
            try:
                items = sorted(d.items())
            except TypeError:
                items = sorted(d.items(), key=lambda it: repr(it[0]))
            mat[i, j] = table.setdefault(tuple(items), len(table))
            okm[i, j] = True
    uniq = np.empty(max(len(table), 1), dtype=object)
    uniq[0] = ()
    for v, c in table.items():
        uniq[c] = v
    codes = jnp.asarray(mat)[a.data, b.data]
    ok = jnp.asarray(okm)[a.data, b.data]
    return ColumnVal(
        codes, _and_valid(_and_valid(a.valid, b.valid), ok), Dictionary(uniq), e.type
    )


def _hof_fn(op: str, e: Call, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    """Higher-order functions over dict-coded arrays/maps: the lambda body is
    interpreted once per DISTINCT container value on the host; device lanes
    just re-gather codes (reference: ArrayTransformFunction et al., compiled
    per row by LambdaBytecodeGenerator — interning beats codegen here)."""
    from ..plan.ir import LambdaIr

    a = eval_expr(e.args[0], cols, n)
    vals = a.dict.values  # object array of tuples (arrays) or pair-tuples (maps)

    def intern_out(new_vals, out_type) -> ColumnVal:
        # dict-based interning, no sort: results may mix None with values
        # inside tuples, which np.unique's comparison sort would reject
        table: dict = {}
        remap_ = np.empty(len(new_vals), dtype=np.int32)
        for i, v in enumerate(new_vals):
            remap_[i] = table.setdefault(v, len(table))
        uniq = np.empty(max(len(table), 1), dtype=object)
        uniq[0] = ()
        for v, c in table.items():
            uniq[c] = v
        codes = jnp.take(jnp.asarray(remap_), a.data)
        return ColumnVal(codes, a.valid, Dictionary(uniq), out_type)

    def bool_out(table, ok) -> ColumnVal:
        t = jnp.take(jnp.asarray(np.asarray(table, dtype=np.bool_)), a.data)
        okl = jnp.take(jnp.asarray(np.asarray(ok, dtype=np.bool_)), a.data)
        return ColumnVal(t, _and_valid(a.valid, okl), None, BOOLEAN)

    if op == "transform":
        lam: LambdaIr = e.args[1]
        p = lam.params[0]
        et = e.type.element
        out = [
            tuple(_coerce_elem(_py_eval(lam.body, {p: x}), et) for x in v)
            for v in vals
        ]
        return intern_out(out, e.type)
    if op == "filter_arr":
        lam = e.args[1]
        p = lam.params[0]
        out = [
            tuple(x for x in v if _py_eval(lam.body, {p: x}) is True)
            for v in vals
        ]
        return intern_out(out, e.type)
    if op in ("any_match", "all_match", "none_match"):
        lam = e.args[1]
        p = lam.params[0]
        table, ok = [], []
        for v in vals:
            results = [_py_eval(lam.body, {p: x}) for x in v]
            if op == "any_match":
                val = (
                    True if any(r is True for r in results)
                    else (None if any(r is None for r in results) else False)
                )
            elif op == "all_match":
                val = (
                    False if any(r is False for r in results)
                    else (None if any(r is None for r in results) else True)
                )
            else:
                val = (
                    False if any(r is True for r in results)
                    else (None if any(r is None for r in results) else True)
                )
            table.append(bool(val) if val is not None else False)
            ok.append(val is not None)
        return bool_out(table, ok)
    if op == "reduce":
        init_ir, comb, fin = e.args[1], e.args[2], e.args[3]
        init = _py_eval(init_ir, {})
        sp, xp = comb.params
        table, ok = [], []
        for v in vals:
            state = init
            for x in v:
                state = _py_eval(comb.body, {sp: state, xp: x})
            r = _py_eval(fin.body, {fin.params[0]: state})
            table.append(_coerce_elem(r, e.type))
            ok.append(r is not None)
        if e.type.is_string:
            return _dict_object_str(
                [t if t is not None else "" for t in table], a, e.type,
                np.asarray(ok, dtype=bool),
            )
        arr = np.asarray(
            [t if t is not None else 0 for t in table], dtype=e.type.np_dtype
        )
        out = jnp.take(jnp.asarray(arr), a.data)
        okl = jnp.take(jnp.asarray(np.asarray(ok, dtype=bool)), a.data)
        return ColumnVal(out, _and_valid(a.valid, okl), None, e.type)
    if op == "zip_with":
        b = eval_expr(e.args[1], cols, n)
        lam = e.args[2]
        xp, yp = lam.params
        et = e.type.element
        bvals = b.dict.values
        # 2-D result-code table over (a-code, b-code); device gathers by pair
        mat = np.zeros((len(vals), len(bvals)), dtype=np.int32)
        table: dict = {}
        for i, va in enumerate(vals):
            for j, vb in enumerate(bvals):
                ln = max(len(va), len(vb))
                pa = tuple(va) + (None,) * (ln - len(va))
                pb = tuple(vb) + (None,) * (ln - len(vb))
                res = tuple(
                    _coerce_elem(_py_eval(lam.body, {xp: x, yp: y}), et)
                    for x, y in zip(pa, pb)
                )
                mat[i, j] = table.setdefault(res, len(table))
        uniq = np.empty(max(len(table), 1), dtype=object)
        uniq[0] = ()
        for val, code in table.items():
            uniq[code] = val
        codes = jnp.asarray(mat)[a.data, b.data]
        return ColumnVal(codes, _and_valid(a.valid, b.valid), Dictionary(uniq), e.type)
    # map HOFs: values are canonical tuples of (k, v) pairs
    lam = e.args[1]
    kp, vp = lam.params
    if op == "transform_keys":
        kt = e.type.key
        out = []
        for m in vals:
            d = {
                _coerce_elem(_py_eval(lam.body, {kp: k, vp: v}), kt): v
                for k, v in m
            }
            try:  # canonical map form: pairs sorted by key (data/types.py)
                items = sorted(d.items())
            except TypeError:
                items = sorted(d.items(), key=lambda it: repr(it[0]))
            out.append(tuple(items))
        return intern_out(out, e.type)
    if op == "transform_values":
        vt = e.type.value
        out = [
            tuple(
                (k, _coerce_elem(_py_eval(lam.body, {kp: k, vp: v}), vt))
                for k, v in m
            )
            for m in vals
        ]
        return intern_out(out, e.type)
    # map_filter
    out = [
        tuple((k, v) for k, v in m if _py_eval(lam.body, {kp: k, vp: v}) is True)
        for m in vals
    ]
    return intern_out(out, e.type)


def _array_fn(op: str, e: Call, args: list[ColumnVal], n: int) -> ColumnVal:
    """Array functions over dict-coded ARRAY columns: evaluated once per
    distinct array on the host, gathered by code on device (the same
    per-distinct-value strategy as the string ops — data/types.py ArrayType)."""
    a = args[0]
    vals = a.dict.values  # object array of tuples

    def scalar_out(table: np.ndarray, dtype, extra_valid=None) -> ColumnVal:
        t = jnp.asarray(table.astype(dtype))
        out = jnp.take(t, a.data)
        valid = a.valid
        if extra_valid is not None:
            ok = jnp.take(jnp.asarray(extra_valid), a.data)
            valid = ok if valid is None else (valid & ok)
        return ColumnVal(out, valid, None, e.type)

    def array_out(new_vals) -> ColumnVal:
        uniq, remap = np.unique(_obj_array(new_vals), return_inverse=True)
        codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
        return ColumnVal(codes, a.valid, Dictionary(uniq), e.type)

    if op == "cardinality":
        return scalar_out(np.asarray([len(v) for v in vals]), np.int64)
    if op == "element_at":
        ix_ir = e.args[1]
        el_t = e.type
        if isinstance(ix_ir, Const):
            i = int(ix_ir.value)

            def pick(v):
                # 1-based; negative counts from the end; OOB -> NULL
                if i == 0 or abs(i) > len(v):
                    return None
                return v[i - 1] if i > 0 else v[i]

            picked = [pick(v) for v in vals]
            ok = np.asarray([p is not None for p in picked], dtype=bool)
            if el_t.is_string:
                uniq, remap = np.unique(
                    np.asarray([p if p is not None else "" for p in picked], dtype=object),
                    return_inverse=True,
                )
                codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
                okl = jnp.take(jnp.asarray(ok), a.data)
                valid = okl if a.valid is None else (a.valid & okl)
                return ColumnVal(codes, valid, Dictionary(uniq), el_t)
            table = np.asarray(
                [p if p is not None else 0 for p in picked], dtype=el_t.np_dtype
            )
            return scalar_out(table, el_t.np_dtype, extra_valid=ok)
        # dynamic index: 2-D padded element table gathered by (code, ix)
        ix = args[1]
        lens = np.asarray([len(v) for v in vals], dtype=np.int64)
        maxlen = max(1, int(lens.max()) if len(lens) else 1)
        if el_t.is_string:
            flat = sorted({str(x) for v in vals for x in v}) or [""]
            ed = Dictionary(np.asarray(flat, dtype=object))
            mat = np.zeros((len(vals), maxlen), dtype=np.int32)
            for r, v in enumerate(vals):
                for c, x in enumerate(v):
                    mat[r, c] = ed.code_of(str(x))
        else:
            ed = None
            mat = np.zeros((len(vals), maxlen), dtype=el_t.np_dtype)
            for r, v in enumerate(vals):
                for c, x in enumerate(v):
                    mat[r, c] = x
        ixd = ix.data.astype(jnp.int64)
        ln = jnp.take(jnp.asarray(lens), a.data)
        pos = jnp.where(ixd > 0, ixd - 1, ln + ixd)  # 1-based / from-end
        ok = (pos >= 0) & (pos < ln)
        pos_c = jnp.clip(pos, 0, maxlen - 1)
        out = jnp.asarray(mat)[a.data, pos_c]
        valid = _and_valid(_and_valid(a.valid, ix.valid), ok)
        return ColumnVal(out, valid, ed, el_t)
    if op == "contains":
        x_ir = e.args[1]
        if isinstance(x_ir, Const):
            want = x_ir.value
            table = np.asarray(
                [any(el == want for el in v) for v in vals], dtype=np.bool_
            )
            return scalar_out(table, np.bool_)
        # dynamic needle: compare against padded 2-D table
        x = args[1]
        lens = np.asarray([len(v) for v in vals], dtype=np.int64)
        maxlen = max(1, int(lens.max()) if len(lens) else 1)
        if x.dict is not None:
            # element strings -> needle's code space (-2 == absent, never equal)
            mat = np.full((len(vals), maxlen), -2, dtype=np.int64)
            for r, v in enumerate(vals):
                for c, el in enumerate(v):
                    mat[r, c] = x.dict.code_of(str(el))
            needle = x.data.astype(jnp.int64)
        else:
            mat = np.zeros((len(vals), maxlen), dtype=np.float64)
            for r, v in enumerate(vals):
                for c, el in enumerate(v):
                    mat[r, c] = el
            needle = x.data.astype(jnp.float64)
        rows = jnp.asarray(mat)[a.data]  # [n, maxlen]
        ln = jnp.take(jnp.asarray(lens), a.data)
        inlen = jnp.arange(mat.shape[1])[None, :] < ln[:, None]
        hit = jnp.any((rows == needle[:, None]) & inlen, axis=1)
        return ColumnVal(hit, _and_valid(a.valid, x.valid), None, BOOLEAN)
    if op == "array_position":
        x_ir = e.args[1]
        assert isinstance(x_ir, Const), "array_position needle must be a literal"
        want = x_ir.value

        def pos_of(v):
            for i, el in enumerate(v):
                if el == want:
                    return i + 1
            return 0

        return scalar_out(np.asarray([pos_of(v) for v in vals]), np.int64)
    if op == "array_distinct":
        def dedup(v):
            seen, out = set(), []
            for x in v:
                if x not in seen:
                    seen.add(x)
                    out.append(x)
            return tuple(out)

        return array_out([dedup(v) for v in vals])
    if op == "array_sort":
        # NULL elements sort last (Trino semantics)
        return array_out(
            [
                tuple(sorted(x for x in v if x is not None))
                + (None,) * sum(1 for x in v if x is None)
                for v in vals
            ]
        )
    if op == "array_join":
        delim = _const_str(e.args[1])
        strs = [delim.join(str(x) for x in v) for v in vals]
        uniq, remap = np.unique(np.asarray(strs, dtype=object), return_inverse=True)
        codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
        return ColumnVal(codes, a.valid, Dictionary(uniq), VARCHAR)
    if op in ("array_min", "array_max"):
        # empty -> NULL; any NULL element -> NULL (Trino semantics)
        f = min if op == "array_min" else max
        picked = [
            None if (not len(v) or any(x is None for x in v)) else f(v)
            for v in vals
        ]
        ok = np.asarray([p is not None for p in picked], dtype=bool)
        if e.type.is_string:
            uniq, remap = np.unique(
                np.asarray(
                    [str(p) if p is not None else "" for p in picked], dtype=object
                ),
                return_inverse=True,
            )
            codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
            okl = jnp.take(jnp.asarray(ok), a.data)
            return ColumnVal(
                codes, _and_valid(a.valid, okl), Dictionary(uniq), e.type
            )
        table = np.asarray(
            [p if p is not None else 0 for p in picked], dtype=e.type.np_dtype
        )
        return scalar_out(table, e.type.np_dtype, extra_valid=ok)
    raise NotImplementedError(f"array op {op}")


_STR_UNARY = {
    "upper": str.upper,
    "lower": str.lower,
    "trim": str.strip,
    "ltrim": str.lstrip,
    "rtrim": str.rstrip,
    "reverse_str": lambda s: s[::-1],
}


def _dict_map_str_nullable(a: ColumnVal, fn, out_type) -> ColumnVal:
    """Like _dict_map_str but fn may return None (SQL NULL — e.g. a regex
    that does not match).  NULL-producing codes merge their validity into
    the column's mask."""
    raw = [fn(str(v)) for v in a.dict.values]
    ok = np.asarray([r is not None for r in raw], dtype=bool)
    vals = np.asarray([r if r is not None else "" for r in raw], dtype=object)
    uniq, remap = np.unique(vals, return_inverse=True)
    codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
    ok_lane = jnp.take(jnp.asarray(ok), a.data)
    return ColumnVal(
        codes, _and_valid(a.valid, ok_lane), Dictionary(uniq), out_type
    )


def _dict_map_str(a: ColumnVal, fn, out_type) -> ColumnVal:
    """str -> str function applied once per distinct dictionary VALUE; the
    page's rows just gather the remapped codes (the reference's
    DictionaryAwarePageProjection does the same per-distinct-value trick)."""
    vals = [fn(str(v)) for v in a.dict.values]
    uniq, remap = np.unique(np.asarray(vals, dtype=object), return_inverse=True)
    codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
    return ColumnVal(codes, a.valid, Dictionary(uniq), out_type)


def _const_str(e_arg) -> str:
    assert isinstance(e_arg, Const), "argument must be a literal"
    return str(e_arg.value)


def _string_fn(op: str, e: Call, args: list[ColumnVal], n: int) -> ColumnVal:
    """String functions with extra (literal) arguments.  All evaluate on the
    dictionary host-side; scalar results gather through a host table."""
    import re as _re

    a = args[0]

    def str_out(fn) -> ColumnVal:
        return _dict_map_str(a, fn, e.type)

    def scalar_out(table: np.ndarray, dtype) -> ColumnVal:
        t = jnp.asarray(table.astype(dtype))
        return ColumnVal(jnp.take(t, a.data), a.valid, None, e.type)

    if op == "replace":
        old, new = _const_str(e.args[1]), _const_str(e.args[2])
        return str_out(lambda s: s.replace(old, new))
    if op == "strpos":
        needle = _const_str(e.args[1])
        return scalar_out(
            np.asarray([str(v).find(needle) + 1 for v in a.dict.values]), np.int64
        )
    if op == "starts_with":
        prefix = _const_str(e.args[1])
        return scalar_out(
            np.asarray([str(v).startswith(prefix) for v in a.dict.values]), np.bool_
        )
    if op in ("lpad", "rpad"):
        width = int(e.args[1].value)  # type: ignore[union-attr]
        pad = _const_str(e.args[2]) if len(e.args) > 2 else " "

        def _pad(s: str) -> str:
            if len(s) >= width:
                return s[:width]
            fill = (pad * width)[: width - len(s)]
            return fill + s if op == "lpad" else s + fill

        return str_out(_pad)
    if op == "split_part":
        delim, ix = _const_str(e.args[1]), int(e.args[2].value)  # type: ignore[union-attr]

        def _split(s: str):
            parts = s.split(delim)
            # out-of-range index is NULL (Trino semantics), not ''
            return parts[ix - 1] if 1 <= ix <= len(parts) else None

        return _dict_map_str_nullable(a, _split, e.type)
    if op == "regexp_like":
        pat = _re.compile(_const_str(e.args[1]))
        return scalar_out(
            np.asarray([bool(pat.search(str(v))) for v in a.dict.values]), np.bool_
        )
    if op == "regexp_replace":
        pat = _re.compile(_const_str(e.args[1]))
        repl = _const_str(e.args[2]) if len(e.args) > 2 else ""
        return str_out(lambda s: pat.sub(repl, s))
    if op == "regexp_extract":
        pat = _re.compile(_const_str(e.args[1]))
        group = int(e.args[2].value) if len(e.args) > 2 else 0  # type: ignore[union-attr]

        def _ext(s: str):
            # no match / non-participating group is NULL (Trino semantics)
            m = pat.search(s)
            return m.group(group) if m else None

        return _dict_map_str_nullable(a, _ext, e.type)
    if op == "concat_str":
        # n-ary concat over dict-coded and literal operands.  Pairwise dict x
        # dict combine is bounded by |A| * |B| distinct outputs — fine for
        # the low-cardinality dictionaries string columns encode to.
        out = args[0]
        for nxt_ir, nxt in zip(e.args[1:], args[1:]):
            if isinstance(nxt_ir, Const):
                lit = str(nxt_ir.value)
                out = _dict_map_str(out, lambda s, _l=lit: s + _l, e.type)
                continue
            if len(out.dict) * len(nxt.dict) > 1_000_000:
                raise NotImplementedError(
                    "concat of two high-cardinality string columns"
                )
            pair_vals = np.asarray(
                [str(x) + str(y) for x in out.dict.values for y in nxt.dict.values],
                dtype=object,
            )
            uniq, remap = np.unique(pair_vals, return_inverse=True)
            pair_code = out.data * len(nxt.dict) + nxt.data
            codes = jnp.take(jnp.asarray(remap.astype(np.int32)), pair_code)
            out = ColumnVal(
                codes, _and_valid(out.valid, nxt.valid), Dictionary(uniq), e.type
            )
        return out
    raise NotImplementedError(f"string op {op}")


def _numeric_align(a: jnp.ndarray, b: jnp.ndarray):
    if a.dtype == b.dtype:
        return a, b
    target = jnp.promote_types(a.dtype, b.dtype)
    return a.astype(target), b.astype(target)


def _cast(a: ColumnVal, target: Type, n: int) -> ColumnVal:
    if a.type == target:
        return a
    if target == VARCHAR:
        raise NotImplementedError("cast to varchar")
    # DECIMAL rescaling on int64 lanes (reference: spi/type/DecimalConversions
    # — rescale by powers of ten, round half away from zero when narrowing)
    if a.data2 is not None:
        if target.is_decimal and target.scale == (
            a.type.scale if a.type is not None else 0
        ):
            # precision widening at the same scale: lanes unchanged
            return ColumnVal(a.data, a.valid, None, target, data2=a.data2)
        if target.is_floating:
            # limbed decimal128 -> double.  v = lo_signed + 2^64*(hi + [lo<0])
            # — the signed-lo form avoids the catastrophic cancellation of
            # hi*2^64 + u64(lo) for small negatives (u64(-1) rounds to 2^64
            # in f64, summing to 0.0 instead of -1.0)
            lo = a.data.astype(jnp.int64)
            src_scale = a.type.scale if a.type is not None else 0
            hi_adj = a.data2 + jnp.where(lo < 0, 1, 0).astype(a.data2.dtype)
            out = (
                lo.astype(jnp.float64)
                + hi_adj.astype(jnp.float64) * float(2**64)
            ) / (10.0**src_scale)
            return ColumnVal(out.astype(_np_to_jnp(target)), a.valid, None, target)
        raise NotImplementedError(f"cast decimal128 to {target.name}")
    if target.is_decimal or (a.type is not None and a.type.is_decimal):
        src_scale = a.type.scale if (a.type is not None and a.type.is_decimal) else 0
        if target.is_decimal:
            if a.type is not None and a.type.is_floating:
                data = jnp.round(a.data.astype(jnp.float64) * (10.0**target.scale))
                return ColumnVal(data.astype(jnp.int64), a.valid, None, target)
            d = a.data.astype(jnp.int64)
            if target.scale >= src_scale:
                out = d * (10 ** (target.scale - src_scale))
            else:
                div = 10 ** (src_scale - target.scale)
                out = jnp.sign(d) * ((jnp.abs(d) + div // 2) // div)
            return ColumnVal(out, a.valid, None, target)
        # decimal source -> non-decimal target
        d = a.data.astype(jnp.int64)
        if target.is_floating:
            out = d.astype(jnp.float64) / (10.0**src_scale)
            return ColumnVal(out.astype(_np_to_jnp(target)), a.valid, None, target)
        div = 10**src_scale
        out = jnp.sign(d) * ((jnp.abs(d) + div // 2) // div)
        return ColumnVal(out.astype(_np_to_jnp(target)), a.valid, None, target)
    if a.dict is not None:
        # varchar -> numeric/date via host parse of dictionary values
        if target == DATE:
            from ..data.types import date_to_days

            table = np.asarray([date_to_days(v) for v in a.dict.values], dtype=np.int32)
        elif target.is_floating:
            table = np.asarray([float(v) for v in a.dict.values], dtype=target.np_dtype)
        else:
            table = np.asarray([int(v) for v in a.dict.values], dtype=target.np_dtype)
        return ColumnVal(jnp.take(jnp.asarray(table), a.data), a.valid, None, target)
    return ColumnVal(a.data.astype(_np_to_jnp(target)), a.valid, None, target)


def _kleene(op: str, e: Call, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    a = eval_expr(e.args[0], cols, n)
    b = eval_expr(e.args[1], cols, n)
    ad = a.data.astype(jnp.bool_)
    bd = b.data.astype(jnp.bool_)
    av = _valid_mask(a) if a.valid is not None else None
    bv = _valid_mask(b) if b.valid is not None else None
    if op == "and":
        data = (ad if av is None else (ad & av)) & (bd if bv is None else (bd & bv))
        if av is None and bv is None:
            valid = None
        else:
            # null AND false == false (valid); null AND true == null
            a_false = (~ad) if av is None else (av & ~ad)
            b_false = (~bd) if bv is None else (bv & ~bd)
            both_valid = _and_valid(av, bv)
            valid = (both_valid if both_valid is not None else jnp.ones((n,), jnp.bool_)) | a_false | b_false
        return ColumnVal(data, valid, None, BOOLEAN)
    else:
        data = (ad if av is None else (ad & av)) | (bd if bv is None else (bd & bv))
        if av is None and bv is None:
            valid = None
        else:
            a_true = ad if av is None else (av & ad)
            b_true = bd if bv is None else (bv & bd)
            both_valid = _and_valid(av, bv)
            valid = (both_valid if both_valid is not None else jnp.ones((n,), jnp.bool_)) | a_true | b_true
        return ColumnVal(data, valid, None, BOOLEAN)


def _case(e: CaseWhen, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    if e.default is not None:
        out = eval_expr(e.default, cols, n)
    else:
        out = ColumnVal(
            jnp.zeros((n,), dtype=_np_to_jnp(e.type)),
            jnp.zeros((n,), dtype=jnp.bool_),
            None,
            e.type,
        )
    evaluated = [
        (eval_expr(cond, cols, n), eval_expr(res, cols, n)) for cond, res in e.whens
    ]
    # decimal128 CASE: select over BOTH limbs; single-lane branches (narrow
    # decimal literals like 0) sign-extend into limb space via _as_limbs
    limbed = out.data2 is not None or any(
        r.data2 is not None for _, r in evaluated
    )
    if out.dict is not None or any(r.dict is not None for _, r in evaluated):
        # varchar CASE: union the branch dictionaries on the host, remap each
        # branch's codes into union space, select codes on device — the same
        # per-distinct-value strategy as every other string op here
        branches = [out] + [r for _, r in evaluated]
        # dict-less varchar branches are NULL literals (all varchar columns
        # are dictionary-coded): their codes never surface through the
        # all-false validity mask, so they contribute nothing to the union
        # (e.g. `case when grouping(k) = 0 then k end` — implicit NULL else)
        if any(
            b.dict is None and not (b.type is None or b.type.is_string)
            for b in branches
        ):
            raise NotImplementedError("CASE mixing varchar and non-varchar results")
        union = np.unique(
            np.concatenate([
                np.asarray(b.dict.values, dtype=object)
                for b in branches if b.dict is not None
            ])
        )
        udict = Dictionary(union)

        def remap(b: ColumnVal) -> ColumnVal:
            if b.dict is None:  # NULL branch: any code, validity masks it
                return ColumnVal(
                    jnp.zeros(b.data.shape, jnp.int32), b.valid, udict, e.type
                )
            table = np.searchsorted(union, np.asarray(b.dict.values, dtype=object))
            codes = jnp.take(jnp.asarray(table.astype(np.int32)), b.data)
            return ColumnVal(codes, b.valid, udict, e.type)

        out = remap(out)
        evaluated = [(c, remap(r)) for c, r in evaluated]
    out_data, out_valid = out.data, out.valid
    result_dict = out.dict
    out_hi = None
    if limbed:
        out_data, out_hi = _as_limbs(out)
    for c, r in reversed(evaluated):
        cm = c.data.astype(jnp.bool_)
        if c.valid is not None:
            cm = cm & c.valid
        if limbed:
            rlo, rhi = _as_limbs(r)
            out_data = jnp.where(cm, rlo, out_data)
            out_hi = jnp.where(cm, rhi, out_hi)
        else:
            out_data = jnp.where(cm, r.data.astype(out_data.dtype), out_data)
        rv = _valid_mask(r) if r.valid is not None else None
        if out_valid is None and rv is None:
            out_valid = None
        else:
            ov = out_valid if out_valid is not None else jnp.ones((n,), jnp.bool_)
            rvm = rv if rv is not None else jnp.ones((n,), jnp.bool_)
            out_valid = jnp.where(cm, rvm, ov)
    return ColumnVal(out_data, out_valid, result_dict, e.type, data2=out_hi)


def _as_limbs(v: ColumnVal):
    """(lo, hi) int64 pair; single-lane numeric operands sign-extend."""
    lo = v.data.astype(jnp.int64)
    if v.data2 is not None:
        return lo, v.data2.astype(jnp.int64)
    return lo, lo >> 63  # arithmetic shift: 0 for >=0, -1 for <0


def _limbed_op(op: str, args, valid, e) -> ColumnVal:
    """decimal128 elementwise ops over two-limb lanes (reference:
    spi/type/Int128Math.java add/subtract/compare).  Operands were already
    scale-aligned by the planner, like the single-lane decimal path."""
    from ..data import dec128 as d

    if op in ("div", "mod"):
        raise NotImplementedError(
            f"decimal128 {op} (128-bit divide lanes; cast to double instead)"
        )
    alo, ahi = _as_limbs(args[0])
    if op == "mul":
        blo, bhi = _as_limbs(args[1])
        lo, hi = d.mul128(alo, ahi, blo, bhi)
        return ColumnVal(lo, valid, None, e.type, data2=hi)
    if op == "neg":
        lo, hi = d.neg128(alo, ahi)
        return ColumnVal(lo, valid, None, e.type, data2=hi)
    if op == "abs":
        lo, hi = d.neg128(alo, ahi)
        neg = ahi < 0
        return ColumnVal(
            jnp.where(neg, lo, alo), valid, None, e.type,
            data2=jnp.where(neg, hi, ahi),
        )
    blo, bhi = _as_limbs(args[1])
    if op in ("add", "sub"):
        lo, hi = (
            d.add128(alo, ahi, blo, bhi)
            if op == "add"
            else d.sub128(alo, ahi, blo, bhi)
        )
        return ColumnVal(lo, valid, None, e.type, data2=hi)
    lt, eq = d.cmp128(alo, ahi, blo, bhi)
    out = {
        "eq": eq, "ne": ~eq, "lt": lt, "le": lt | eq,
        "gt": ~(lt | eq), "ge": ~lt,
    }[op]
    return ColumnVal(out, valid, None, BOOLEAN)


# ---------------------------------------------------- dictionary (host) ops


def _string_compare(op: str, args: list[ColumnVal], e: Call, n: int) -> ColumnVal:
    a, b = args
    valid = _and_valid(a.valid, b.valid)
    if a.dict is not None and b.dict is not None:
        if len(b.dict) == 1:
            return _dict_vs_const(op, a, str(b.dict.values[0]), valid)
        if len(a.dict) == 1:
            flip = {"eq": "eq", "ne": "ne", "lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
            return _dict_vs_const(flip[op], b, str(a.dict.values[0]), valid)
        if a.dict is b.dict:
            fn = {
                "eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less,
                "le": jnp.less_equal, "gt": jnp.greater, "ge": jnp.greater_equal,
            }[op]
            if op in ("eq", "ne"):
                return ColumnVal(fn(a.data, b.data), valid, None, BOOLEAN)
            ranks = jnp.asarray(a.dict.sorted_rank())
            return ColumnVal(
                fn(jnp.take(ranks, a.data), jnp.take(ranks, b.data)), valid, None, BOOLEAN
            )
        # different dictionaries: translate b's codes into a's code space (eq/ne)
        if op in ("eq", "ne"):
            trans = np.asarray(
                [a.dict.code_of(v) for v in b.dict.values], dtype=np.int32
            )
            b_in_a = jnp.take(jnp.asarray(trans), b.data)
            eq = (b_in_a >= 0) & (a.data == b_in_a)
            return ColumnVal(eq if op == "eq" else ~eq, valid, None, BOOLEAN)
        raise NotImplementedError("ordering comparison across distinct dictionaries")
    raise NotImplementedError(f"string compare {op} on {args}")


def _dict_vs_const(op: str, col: ColumnVal, const: str, valid) -> ColumnVal:
    import operator as _op

    py = {
        "eq": _op.eq, "ne": _op.ne, "lt": _op.lt, "le": _op.le, "gt": _op.gt, "ge": _op.ge,
    }[op]
    table = np.asarray([py(str(v), const) for v in col.dict.values], dtype=np.bool_)
    return ColumnVal(jnp.take(jnp.asarray(table), col.data), valid, None, BOOLEAN)


def _in_list(e: InListIr, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    a = eval_expr(e.operand, cols, n)
    if a.dict is not None:
        wanted = {str(v) for v in e.values}
        table = np.asarray([str(v) in wanted for v in a.dict.values], dtype=np.bool_)
        m = jnp.take(jnp.asarray(table), a.data)
    else:
        m = jnp.zeros((n,), dtype=jnp.bool_)
        for v in e.values:
            m = m | (a.data == v)
    if e.negated:
        m = ~m
    return ColumnVal(m, a.valid, None, BOOLEAN)


def _like(e: LikeIr, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    a = eval_expr(e.operand, cols, n)
    rx = _like_regex(e.pattern)
    table = np.asarray(
        [rx.fullmatch(str(v)) is not None for v in a.dict.values], dtype=np.bool_
    )
    m = jnp.take(jnp.asarray(table), a.data)
    if e.negated:
        m = ~m
    return ColumnVal(m, a.valid, None, BOOLEAN)


def _like_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.DOTALL)


def _substring(e: Call, cols: Sequence[ColumnVal], n: int) -> ColumnVal:
    a = eval_expr(e.args[0], cols, n)
    start = e.args[1]
    length = e.args[2] if len(e.args) > 2 else None
    assert isinstance(start, Const), "substring start must be a literal"
    s = int(start.value)
    if length is not None:
        assert isinstance(length, Const)
        ln = int(length.value)
        vals = [str(v)[s - 1 : s - 1 + ln] for v in a.dict.values]
    else:
        vals = [str(v)[s - 1 :] for v in a.dict.values]
    uniq, remap = np.unique(np.asarray(vals, dtype=object), return_inverse=True)
    new_dict = Dictionary(uniq)
    codes = jnp.take(jnp.asarray(remap.astype(np.int32)), a.data)
    return ColumnVal(codes, a.valid, new_dict, VARCHAR)


# ------------------------------------------------------------- date helpers


def _civil_from_days(z: jnp.ndarray):
    """days-since-epoch -> (year, month, day), branch-free integer math
    (public domain algorithm; vectorizes cleanly onto the VPU)."""
    z = z + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524) - jnp.floor_divide(doe, 146096),
        365,
    )
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """(year, month, day) -> days-since-epoch; exact inverse of
    _civil_from_days (same public-domain algorithm)."""
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    doy = jnp.floor_divide(153 * (m + jnp.where(m > 2, -3, 9)) + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468
