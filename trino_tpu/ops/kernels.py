"""Data-plane kernel policy and dispatch accounting.

The Pallas data-plane kernels (ops/pallas/hashagg.py, hashjoin.py, fused.py)
replace the sort-based relational hot paths (ops/relops.py) when a static
gate says the shape fits — group/build cardinality inside the VMEM hash
table, key types encodable as i32 words, aggregate set fully fusable.  This
module is the one place that decision is configured and observed:

  * KernelPolicy — per-statement knobs (runtime/session.py properties
    `data_plane_kernels`, `hash_agg_kernel_limit`, `hash_join_kernel_limit`,
    `pallas_interpret`), re-applied by the engine before each statement the
    same way compile props are.
  * record_dispatch() — increments
    trino_tpu_kernel_dispatch_total{op,impl=pallas|sort|fallback} and, while
    a plan trace is active, appends the event to that trace's capture so
    EXPLAIN ANALYZE can print `-- kernel:` footer lines.  Dispatch is
    recorded at TRACE time (kernel selection), once per compiled program —
    a jit-cache hit re-runs the selected kernel without re-counting.
  * events_for(plan) — the captured events of the last trace of `plan`
    (plans are frozen dataclasses, so they key a bounded dict directly).

impl values: "pallas" = the Pallas kernel was selected; "sort" = the static
gate chose the legacy sort path (disabled, unencodable keys, unsupported
shape, or a non-TPU backend without interpret); "fallback" = the shape was
kernel-eligible but exceeded the policy's capacity limit
(hash_agg_kernel_limit / hash_join_kernel_limit), so the sort path ran.
A selected kernel still carries a runtime overflow guard — hash-table
overflow or probe exhaustion divert that execution to the sort path without
re-counting.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..utils import metrics as _metrics

__all__ = [
    "KernelPolicy", "get_policy", "set_policy", "policy_key",
    "record_dispatch", "begin_capture", "end_capture", "remember",
    "events_for",
]


@dataclass(frozen=True)
class KernelPolicy:
    enabled: bool = True            # master kill switch (data_plane_kernels)
    hash_agg_max_groups: int = 2048  # group cap above which group-by sorts
    hash_join_max_build: int = 2048  # build rows above which joins sort
    interpret: bool = False         # run kernels interpreted (CPU CI path)


_DEFAULT = KernelPolicy()
_POLICY = _DEFAULT

_DISPATCH = _metrics.GLOBAL.counter(
    "trino_tpu_kernel_dispatch_total",
    "Data-plane kernel selections at plan-trace time, by relational op "
    "(group_by | join | fused_pipeline) and implementation (pallas = Pallas "
    "TPU kernel, sort = legacy sort path, fallback = kernel-eligible shape "
    "past the policy capacity limit, sort path ran)",
    ("op", "impl"),
)


def get_policy() -> KernelPolicy:
    return _POLICY


def set_policy(policy: KernelPolicy) -> None:
    global _POLICY
    _POLICY = policy


def policy_key() -> tuple:
    """Fingerprint for executor jit-cache keys: a changed policy must compile
    a new program (the kernel choice is baked into the trace).  The pallas
    module-level overrides ride along because they too are read at trace
    time: compiled programs outlive them in the process-global
    CompileService done-map, and an interpreted (f32-matmul) segsum program
    must never be swapped in for an exact-f64 request with the same avals."""
    from .pallas import hashagg, segreduce, topk

    p = _POLICY
    return (p.enabled, p.hash_agg_max_groups, p.hash_join_max_build,
            p.interpret, segreduce.INTERPRET, hashagg.INTERPRET, topk.FORCE)


# --------------------------------------------------------- event capture

_TLS = threading.local()
_EVENTS_LOCK = threading.Lock()
_EVENTS: dict = {}  # plan -> tuple[(op, impl, detail)]
_EVENTS_MAX = 256


def record_dispatch(op: str, impl: str, detail: str = "") -> None:
    _DISPATCH.labels(op=op, impl=impl).inc()
    cap = getattr(_TLS, "capture", None)
    if cap is not None:
        cap.append((op, impl, detail))


def begin_capture() -> list:
    cap: list = []
    _TLS.capture = cap
    return cap


def end_capture() -> None:
    _TLS.capture = None


def remember(plan, events) -> None:
    """Associate a trace's dispatch events with its plan (last trace wins —
    the retry loop's final capacities decide the kernels that actually ran)."""
    try:
        hash(plan)
    except TypeError:
        return
    with _EVENTS_LOCK:
        if len(_EVENTS) >= _EVENTS_MAX:
            _EVENTS.clear()
        _EVENTS[plan] = tuple(events)


def events_for(plan) -> tuple:
    try:
        hash(plan)
    except TypeError:
        return ()
    with _EVENTS_LOCK:
        return _EVENTS.get(plan, ())
