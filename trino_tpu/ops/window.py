"""Window function kernel.

The reference's WindowOperator (operator/WindowOperator.java, window/
framework 6.9k LoC) indexes each partition in a PagesIndex and walks frames
row by row.  The TPU formulation is one sort + segmented scans:

  sort rows by (partition keys, order keys)
  -> partition/peer boundary flags
  -> jax.lax.associative_scan with a reset-at-boundary combiner for running
     sum/count/min/max and ranks (log-depth, fully vectorized)
  -> reverse scans give partition/peer END indices for RANGE frames (peers),
     whole-partition values, and last_value; gathers fetch frame results.

All frames supported are prefix frames: 'rows' (UNBOUNDED PRECEDING ..
CURRENT ROW), 'range' (same, peers included — SQL default), 'whole'
(the full partition).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .expr import ColumnVal
from .relops import SortSpec, _sortable_key, _valid_of

__all__ = ["window_eval"]


def _seg_scan(op: str, x: jnp.ndarray, boundary: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented scan: restarts at rows where boundary is True
    (boundary[0] must be True)."""

    def combine(a, b):
        av, ab = a
        bv, bb = b
        if op == "add":
            val = jnp.where(bb, bv, av + bv)
        elif op == "max":
            val = jnp.where(bb, bv, jnp.maximum(av, bv))
        else:
            val = jnp.where(bb, bv, jnp.minimum(av, bv))
        return val, ab | bb

    out, _ = jax.lax.associative_scan(combine, (x, boundary))
    return out


def _end_indices(is_end: jnp.ndarray) -> jnp.ndarray:
    """For each row, the index of the next row (inclusive) where is_end."""
    n = is_end.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    flipped = jnp.flip(idx)
    fboundary = jnp.flip(is_end)
    ends = _seg_scan("max", flipped, fboundary)
    return jnp.flip(ends)


def window_eval(
    cols: Sequence[ColumnVal],
    live: jnp.ndarray,
    part_keys: Sequence[ColumnVal],
    order_keys: Sequence[ColumnVal],
    order_specs: Sequence[SortSpec],
    calls,  # Sequence[WindowCall]
    arg_vals: Sequence[tuple[ColumnVal, ...]],
):
    """Returns (cols ++ one ColumnVal per call, live) in window-sorted order."""
    n = live.shape[0]

    # ---- sort by (dead-last, partition keys, order keys) -------------------
    operands: list[jnp.ndarray] = [(~live).astype(jnp.int8)]
    for kv in part_keys:
        operands.append(~_valid_of(kv, n))
        operands.append(_sortable_key(kv))
    n_part_ops = len(operands) - 1
    for kv, spec in zip(order_keys, order_specs):
        null_flag = _valid_of(kv, n) if spec.nulls_first else ~_valid_of(kv, n)
        operands.append(null_flag.astype(jnp.int8))
        operands.append(_sortable_key(kv, descending=not spec.ascending))
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [iota], num_keys=len(operands), is_stable=True)
    perm = sorted_ops[-1]
    live_s = jnp.take(live, perm)

    def gather(cv: ColumnVal) -> ColumnVal:
        return ColumnVal(
            jnp.take(cv.data, perm),
            None if cv.valid is None else jnp.take(cv.valid, perm),
            cv.dict,
            cv.type,
        )

    out_cols = [gather(cv) for cv in cols]

    # ---- boundaries --------------------------------------------------------
    first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    part_ops = sorted_ops[1 : 1 + n_part_ops]
    new_part = first
    for op_arr in part_ops:
        prev = jnp.concatenate([op_arr[:1], op_arr[:-1]])
        new_part = new_part | (op_arr != prev)
    order_ops = sorted_ops[1 + n_part_ops : -1]
    new_peer = new_part
    for op_arr in order_ops:
        prev = jnp.concatenate([op_arr[:1], op_arr[:-1]])
        new_peer = new_peer | (op_arr != prev)

    is_part_end = jnp.concatenate([new_part[1:], jnp.ones((1,), jnp.bool_)])
    is_peer_end = jnp.concatenate([new_peer[1:], jnp.ones((1,), jnp.bool_)])
    part_end = _end_indices(is_part_end)
    peer_end = _end_indices(is_peer_end)
    ones = jnp.ones((n,), jnp.int64)
    row_number = _seg_scan("add", ones, new_part)
    idx32 = jnp.arange(n, dtype=jnp.int32)
    part_start = _seg_scan("max", jnp.where(new_part, idx32, -1), new_part)

    # first ORDER BY key in sorted (transformed) space — RANGE offset frames
    # resolve their bounds against it
    okey_sorted = None
    if order_keys:
        ok = order_keys[0]
        okey_sorted = (
            jnp.take(_sortable_key(ok, descending=not order_specs[0].ascending), perm),
            jnp.take(_valid_of(ok, n), perm),
            ok.type,
            bool(order_specs[0].nulls_first),
        )

    # ---- evaluate calls ----------------------------------------------------
    for call, argv in zip(calls, arg_vals):
        argv = [gather(a) for a in argv]
        out_cols.append(
            _eval_call(
                call, argv, n, new_part, new_peer, part_end, peer_end,
                row_number, live_s, part_start, okey_sorted,
            )
        )
    return out_cols, live_s


def _bounded_searchsorted(vals, target, lo0, hi0_excl, side, n):
    """Per-row binary search restricted to [lo0, hi0_excl): first index whose
    value >= target ('left') / > target ('right').  34 static halving steps
    cover any n; each step is one gather — the partition-local searchsorted
    RANGE frames need (a global searchsorted can't see partition bounds)."""
    lo = lo0.astype(jnp.int32)
    hi = hi0_excl.astype(jnp.int32)
    for _ in range(34):
        active = lo < hi
        mid = (lo + hi) >> 1
        vm = jnp.take(vals, jnp.clip(mid, 0, max(n - 1, 0)))
        pred = (vm < target) if side == "left" else (vm <= target)
        lo = jnp.where(active & pred, mid + 1, lo)
        hi = jnp.where(active & ~pred, mid, hi)
    return lo


def _literal_arg(call, i: int, argv, default=None) -> int:
    """Literal int parameter (lag/lead offset, ntile buckets, nth_value n):
    read from the Const IR on the call — the evaluated lane array is a traced
    constant under jit and cannot concretize."""
    from ..plan.ir import Const

    if len(call.args) <= i:
        return default
    e = call.args[i]
    if isinstance(e, Const) and e.value is not None:
        return int(e.value)
    return int(argv[i].data[0])  # eager path fallback


def _frame_bounds(frame: str):
    """'rows:<lo>:<hi>' -> (lo, hi) with 'u' or signed int offsets."""
    _, lo, hi = frame.split(":")
    return (lo if lo == "u" else int(lo)), (hi if hi == "u" else int(hi))


def _eval_call(
    call, argv, n, new_part, new_peer, part_end, peer_end, row_number, live_s,
    part_start, okey_sorted=None,
):
    from ..data.types import BIGINT

    fn = call.fn
    if fn == "row_number":
        return ColumnVal(row_number, None, None, call.type)
    if fn == "rank":
        # rank = row_number at the start of the peer group
        start_rn = jnp.where(new_peer, row_number, jnp.int64(0))
        rank = _seg_scan("max", start_rn, new_part)
        return ColumnVal(rank, None, None, call.type)
    if fn == "dense_rank":
        dr = _seg_scan("add", new_peer.astype(jnp.int64), new_part)
        return ColumnVal(dr, None, None, call.type)
    if fn in ("lag", "lead"):
        a = argv[0]
        k = _literal_arg(call, 1, argv, default=1)
        shift = -k if fn == "lag" else k
        data = jnp.roll(a.data, -shift)
        valid = jnp.roll(_valid_of(a, n), -shift)
        # the source row must exist in the same partition; a NULL value AT an
        # existing source row stays NULL (the default only covers rows where
        # the offset leaves the partition — reference: LagFunction semantics)
        pid = jnp.cumsum(new_part.astype(jnp.int32))
        src_pid = jnp.roll(pid, -shift)
        idx = jnp.arange(n)
        exists = (pid == src_pid) & (idx + shift >= 0) & (idx + shift < n)
        ok = valid & exists
        if len(argv) > 2:  # lag(x, k, default)
            dflt = argv[2]
            if a.dict is not None:
                # merge dictionaries so the default's code lands in the same
                # code space as the value column (a raw code-0 substitution
                # would alias whatever a.dict[0] happens to be)
                import numpy as _np

                union = _np.unique(
                    _np.concatenate(
                        [
                            _np.asarray(a.dict.values, dtype=object),
                            _np.asarray(dflt.dict.values, dtype=object),
                        ]
                    )
                )
                from ..data.page import Dictionary as _Dict

                ra = _np.searchsorted(union, _np.asarray(a.dict.values, dtype=object))
                rd = _np.searchsorted(union, _np.asarray(dflt.dict.values, dtype=object))
                data = jnp.take(jnp.asarray(ra.astype(_np.int32)), data)
                ddata = jnp.take(jnp.asarray(rd.astype(_np.int32)), dflt.data)
                data = jnp.where(exists, data, ddata)
                ok = jnp.where(exists, ok, _valid_of(dflt, n))
                return ColumnVal(data, ok, _Dict(union), call.type)
            data = jnp.where(exists, data, dflt.data.astype(data.dtype))
            ok = jnp.where(exists, ok, _valid_of(dflt, n))
        return ColumnVal(data, ok, a.dict, call.type)
    if fn == "first_value":
        a = argv[0]
        data = jnp.take(a.data, part_start)
        valid = None if a.valid is None else jnp.take(a.valid, part_start)
        return ColumnVal(data, valid, a.dict, call.type)
    if fn == "nth_value":
        a = argv[0]
        k = _literal_arg(call, 1, argv)
        pos = part_start + (k - 1)
        # frame-aware: the k-th row must be INSIDE the row's frame — for the
        # default RANGE frame that ends at the current peer group, for ROWS
        # at the current row, for 'whole' at the partition end (reference:
        # window/FrameInfo-bounded NthValueFunction)
        i32f = jnp.arange(n, dtype=jnp.int32)
        if call.frame == "whole":
            frame_end = part_end
        elif call.frame == "rows":
            frame_end = i32f
        else:  # range (peers included)
            frame_end = peer_end
        ok = (pos <= frame_end) & (pos <= part_end)
        pos_c = jnp.clip(pos, 0, n - 1)
        data = jnp.take(a.data, pos_c)
        valid = ok if a.valid is None else (ok & jnp.take(a.valid, pos_c))
        return ColumnVal(data, valid, a.dict, call.type)
    if fn == "ntile":
        k = _literal_arg(call, 0, argv)
        size = jnp.take(row_number, part_end)
        tile = (row_number - 1) * k // jnp.maximum(size, 1) + 1
        return ColumnVal(tile, None, None, call.type)
    if fn == "percent_rank":
        size = jnp.take(row_number, part_end)
        start_rn = jnp.where(new_peer, row_number, jnp.int64(0))
        rank = _seg_scan("max", start_rn, new_part)
        denom = jnp.maximum(size - 1, 1).astype(jnp.float64)
        pr = jnp.where(size > 1, (rank - 1).astype(jnp.float64) / denom, 0.0)
        return ColumnVal(pr, None, None, call.type)
    if fn == "cume_dist":
        size = jnp.take(row_number, part_end)
        peers_through = jnp.take(row_number, peer_end)
        return ColumnVal(
            peers_through.astype(jnp.float64) / jnp.maximum(size, 1).astype(jnp.float64),
            None, None, call.type,
        )
    if fn == "last_value":
        a = argv[0]
        end = part_end if call.frame == "whole" else peer_end
        data = jnp.take(a.data, end)
        valid = None if a.valid is None else jnp.take(a.valid, end)
        return ColumnVal(data, valid, a.dict, call.type)

    # aggregates over a frame -----------------------------------------------
    # prefix frames use running scans + peer/partition-end gathers; general
    # ROWS offset frames ('rows:<lo>:<hi>') use prefix DIFFERENCES for
    # sum/count/avg and shifted-lane or directional scans for min/max
    # (reference: window/FrameInfo + per-row frame walk in WindowPartition)
    offset_frame = call.frame.startswith(("rows:", "range:"))
    range_bounded_lo = False
    if call.frame.startswith("rows:"):
        lo, hi = _frame_bounds(call.frame)
        i32 = jnp.arange(n, dtype=jnp.int32)
        hi_idx = part_end if hi == "u" else jnp.minimum(i32 + hi, part_end)
        lo_idx = part_start if lo == "u" else jnp.maximum(i32 + lo, part_start)
        empty = lo_idx > hi_idx
    elif call.frame.startswith("range:"):
        # RANGE <k> PRECEDING/FOLLOWING: bounds by ORDER BY VALUE distance.
        # In _sortable_key-transformed space (descending already negated),
        # both directions reduce to [v - k_pre, v + k_fol]; rows with a NULL
        # key frame their null peer group (Trino RANGE semantics)
        if okey_sorted is None:
            raise NotImplementedError("RANGE offset frame requires ORDER BY")
        lo, hi = _frame_bounds(call.frame)
        kvals, kvalid, ktype, nulls_first = okey_sorted
        scale = 10 ** getattr(ktype, "scale", 0) if ktype.is_decimal else 1
        # NULL-key rows' lanes hold garbage (nulls order via a separate flag
        # operand): substitute the extreme that matches their sort position
        # so the searched array stays sorted AND finite offsets never reach
        # them.  Integer keys (BIGINT/date/decimal lanes) stay in int64 — an
        # f64 round-trip would mis-frame values beyond 2^53.
        if jnp.issubdtype(kvals.dtype, jnp.integer):
            info = jnp.iinfo(jnp.int64)
            kv = kvals.astype(jnp.int64)
            sent_k = jnp.int64(info.min if nulls_first else info.max)
        else:
            kv = kvals.astype(jnp.float64)
            sent_k = -jnp.inf if nulls_first else jnp.inf
        kv = jnp.where(kvalid, kv, sent_k)
        i32 = jnp.arange(n, dtype=jnp.int32)
        peer_start = _seg_scan(
            "max", jnp.where(new_peer, i32, -1), new_peer
        )
        if lo == "u":
            lo_idx = part_start
        else:
            lo_idx = _bounded_searchsorted(
                kv, kv + jnp.asarray(int(lo) * scale if kv.dtype == jnp.int64
                                     else float(lo) * scale, kv.dtype),
                part_start, part_end + 1, "left", n,
            )
            # NULL-key rows frame their null peer group on offset bounds
            lo_idx = jnp.where(kvalid, lo_idx, peer_start)
        if hi == "u":
            hi_idx = part_end
        else:
            hi_idx = (
                _bounded_searchsorted(
                    kv, kv + jnp.asarray(int(hi) * scale if kv.dtype == jnp.int64
                                         else float(hi) * scale, kv.dtype),
                    part_start, part_end + 1, "right", n,
                )
                - 1
            )
            hi_idx = jnp.where(kvalid, hi_idx, peer_end)
        range_bounded_lo = lo != "u"
        lo, hi = "u", "u"  # min/max below must route scans, never the roll
        empty = lo_idx > hi_idx

    if offset_frame:

        def frame_sum(contrib):
            running = _seg_scan("add", contrib, new_part)
            s_hi = jnp.take(running, jnp.clip(hi_idx, 0, n - 1))
            s_lo = jnp.where(
                lo_idx > part_start,
                jnp.take(running, jnp.clip(lo_idx - 1, 0, n - 1)),
                jnp.zeros_like(running),
            )
            return jnp.where(empty, jnp.zeros_like(running), s_hi - s_lo)

    if fn == "count_star":
        c = (
            frame_sum(live_s.astype(jnp.int64))
            if offset_frame
            else _frame_value(
                _seg_scan("add", live_s.astype(jnp.int64), new_part),
                call.frame, part_end, peer_end,
            )
        )
        return ColumnVal(c, None, None, call.type)

    a = argv[0]
    valid = _valid_of(a, n) & live_s
    if fn == "count":
        c = (
            frame_sum(valid.astype(jnp.int64))
            if offset_frame
            else _frame_value(
                _seg_scan("add", valid.astype(jnp.int64), new_part),
                call.frame, part_end, peer_end,
            )
        )
        return ColumnVal(c, None, None, call.type)
    if fn in ("sum", "avg"):
        acc_t = (
            jnp.float64
            if (fn == "avg" or jnp.issubdtype(a.data.dtype, jnp.floating))
            else jnp.int64
        )
        contrib = jnp.where(valid, a.data.astype(acc_t), jnp.zeros((n,), acc_t))
        if offset_frame:
            s = frame_sum(contrib)
            c = frame_sum(valid.astype(jnp.int64))
        else:
            s = _frame_value(
                _seg_scan("add", contrib, new_part), call.frame, part_end, peer_end
            )
            c = _frame_value(
                _seg_scan("add", valid.astype(jnp.int64), new_part),
                call.frame, part_end, peer_end,
            )
        if fn == "sum":
            return ColumnVal(s, c > 0, None, call.type)
        return ColumnVal(
            s.astype(jnp.float64) / jnp.maximum(c, 1).astype(jnp.float64),
            c > 0, None, call.type,
        )
    if fn in ("min", "max"):
        if a.dict is not None:
            raise NotImplementedError("window min/max over varchar")
        if jnp.issubdtype(a.data.dtype, jnp.floating):
            sent = jnp.asarray(jnp.inf if fn == "min" else -jnp.inf, a.data.dtype)
        else:
            info = jnp.iinfo(a.data.dtype)
            sent = jnp.asarray(info.max if fn == "min" else info.min, a.data.dtype)
        x = jnp.where(valid, a.data, sent)
        red = "min" if fn == "min" else "max"
        if offset_frame:
            if range_bounded_lo:
                raise NotImplementedError(
                    "min/max over a RANGE frame with a bounded PRECEDING edge"
                )
            c = frame_sum(valid.astype(jnp.int64))
            if lo != "u" and hi != "u":
                width = hi - lo + 1
                if width > 128:
                    raise NotImplementedError("ROWS frame wider than 128 for min/max")
                pid = jnp.cumsum(new_part.astype(jnp.int32))
                acc = jnp.full((n,), sent)
                for s_off in range(lo, hi + 1):
                    shifted = jnp.roll(x, -s_off)
                    src_pid = jnp.roll(pid, -s_off)
                    in_rng = (i32 + s_off >= 0) & (i32 + s_off < n) & (src_pid == pid)
                    cand = jnp.where(in_rng, shifted, sent)
                    acc = jnp.minimum(acc, cand) if fn == "min" else jnp.maximum(acc, cand)
                return ColumnVal(acc, c > 0, None, call.type)
            if lo == "u":  # [part_start, i+hi]: forward scan gathered at hi
                r = _seg_scan(red, x, new_part)
                v = jnp.take(r, jnp.clip(hi_idx, 0, n - 1))
                return ColumnVal(v, c > 0, None, call.type)
            # hi == 'u': [i+lo, part_end]: suffix scan gathered at lo
            rsuf = jnp.flip(
                _seg_scan(
                    red,
                    jnp.flip(x),
                    jnp.flip(jnp.concatenate([new_part[1:], jnp.ones((1,), jnp.bool_)])),
                )
            )
            v = jnp.take(rsuf, jnp.clip(lo_idx, 0, n - 1))
            return ColumnVal(v, c > 0, None, call.type)
        r = _seg_scan(red, x, new_part)
        rc = _seg_scan("add", valid.astype(jnp.int64), new_part)
        v = _frame_value(r, call.frame, part_end, peer_end)
        c = _frame_value(rc, call.frame, part_end, peer_end)
        return ColumnVal(v, c > 0, None, call.type)
    raise NotImplementedError(f"window function {fn}")


def _frame_value(running: jnp.ndarray, frame: str, part_end, peer_end):
    if frame == "rows":
        return running
    end = part_end if frame == "whole" else peer_end
    return jnp.take(running, end)
