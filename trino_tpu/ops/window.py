"""Window function kernel.

The reference's WindowOperator (operator/WindowOperator.java, window/
framework 6.9k LoC) indexes each partition in a PagesIndex and walks frames
row by row.  The TPU formulation is one sort + segmented scans:

  sort rows by (partition keys, order keys)
  -> partition/peer boundary flags
  -> jax.lax.associative_scan with a reset-at-boundary combiner for running
     sum/count/min/max and ranks (log-depth, fully vectorized)
  -> reverse scans give partition/peer END indices for RANGE frames (peers),
     whole-partition values, and last_value; gathers fetch frame results.

All frames supported are prefix frames: 'rows' (UNBOUNDED PRECEDING ..
CURRENT ROW), 'range' (same, peers included — SQL default), 'whole'
(the full partition).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .expr import ColumnVal
from .relops import SortSpec, _sortable_key, _valid_of

__all__ = ["window_eval"]


def _seg_scan(op: str, x: jnp.ndarray, boundary: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented scan: restarts at rows where boundary is True
    (boundary[0] must be True)."""

    def combine(a, b):
        av, ab = a
        bv, bb = b
        if op == "add":
            val = jnp.where(bb, bv, av + bv)
        elif op == "max":
            val = jnp.where(bb, bv, jnp.maximum(av, bv))
        else:
            val = jnp.where(bb, bv, jnp.minimum(av, bv))
        return val, ab | bb

    out, _ = jax.lax.associative_scan(combine, (x, boundary))
    return out


def _end_indices(is_end: jnp.ndarray) -> jnp.ndarray:
    """For each row, the index of the next row (inclusive) where is_end."""
    n = is_end.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    flipped = jnp.flip(idx)
    fboundary = jnp.flip(is_end)
    ends = _seg_scan("max", flipped, fboundary)
    return jnp.flip(ends)


def window_eval(
    cols: Sequence[ColumnVal],
    live: jnp.ndarray,
    part_keys: Sequence[ColumnVal],
    order_keys: Sequence[ColumnVal],
    order_specs: Sequence[SortSpec],
    calls,  # Sequence[WindowCall]
    arg_vals: Sequence[tuple[ColumnVal, ...]],
):
    """Returns (cols ++ one ColumnVal per call, live) in window-sorted order."""
    n = live.shape[0]

    # ---- sort by (dead-last, partition keys, order keys) -------------------
    operands: list[jnp.ndarray] = [(~live).astype(jnp.int8)]
    for kv in part_keys:
        operands.append(~_valid_of(kv, n))
        operands.append(_sortable_key(kv))
    n_part_ops = len(operands) - 1
    for kv, spec in zip(order_keys, order_specs):
        null_flag = _valid_of(kv, n) if spec.nulls_first else ~_valid_of(kv, n)
        operands.append(null_flag.astype(jnp.int8))
        operands.append(_sortable_key(kv, descending=not spec.ascending))
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [iota], num_keys=len(operands), is_stable=True)
    perm = sorted_ops[-1]
    live_s = jnp.take(live, perm)

    def gather(cv: ColumnVal) -> ColumnVal:
        return ColumnVal(
            jnp.take(cv.data, perm),
            None if cv.valid is None else jnp.take(cv.valid, perm),
            cv.dict,
            cv.type,
        )

    out_cols = [gather(cv) for cv in cols]

    # ---- boundaries --------------------------------------------------------
    first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
    part_ops = sorted_ops[1 : 1 + n_part_ops]
    new_part = first
    for op_arr in part_ops:
        prev = jnp.concatenate([op_arr[:1], op_arr[:-1]])
        new_part = new_part | (op_arr != prev)
    order_ops = sorted_ops[1 + n_part_ops : -1]
    new_peer = new_part
    for op_arr in order_ops:
        prev = jnp.concatenate([op_arr[:1], op_arr[:-1]])
        new_peer = new_peer | (op_arr != prev)

    is_part_end = jnp.concatenate([new_part[1:], jnp.ones((1,), jnp.bool_)])
    is_peer_end = jnp.concatenate([new_peer[1:], jnp.ones((1,), jnp.bool_)])
    part_end = _end_indices(is_part_end)
    peer_end = _end_indices(is_peer_end)
    ones = jnp.ones((n,), jnp.int64)
    row_number = _seg_scan("add", ones, new_part)

    # ---- evaluate calls ----------------------------------------------------
    for call, argv in zip(calls, arg_vals):
        argv = [gather(a) for a in argv]
        out_cols.append(
            _eval_call(
                call, argv, n, new_part, new_peer, part_end, peer_end,
                row_number, live_s,
            )
        )
    return out_cols, live_s


def _eval_call(call, argv, n, new_part, new_peer, part_end, peer_end, row_number, live_s):
    from ..data.types import BIGINT

    fn = call.fn
    if fn == "row_number":
        return ColumnVal(row_number, None, None, call.type)
    if fn == "rank":
        # rank = row_number at the start of the peer group
        start_rn = jnp.where(new_peer, row_number, jnp.int64(0))
        rank = _seg_scan("max", start_rn, new_part)
        return ColumnVal(rank, None, None, call.type)
    if fn == "dense_rank":
        dr = _seg_scan("add", new_peer.astype(jnp.int64), new_part)
        return ColumnVal(dr, None, None, call.type)
    if fn in ("lag", "lead"):
        a = argv[0]
        k = int(argv[1].data[0]) if len(argv) > 1 else 1
        shift = -k if fn == "lag" else k
        data = jnp.roll(a.data, -shift)
        valid = jnp.roll(_valid_of(a, n), -shift)
        # valid only if the source row is in the same partition
        pid = jnp.cumsum(new_part.astype(jnp.int32))
        src_pid = jnp.roll(pid, -shift)
        idx = jnp.arange(n)
        in_range = (idx + shift >= 0) & (idx + shift < n)
        ok = valid & (pid == src_pid) & in_range
        if len(argv) > 2:  # lag(x, k, default)
            dflt = argv[2]
            data = jnp.where(ok, data, dflt.data.astype(data.dtype))
            ok = ok | _valid_of(dflt, n)
        return ColumnVal(data, ok, a.dict, call.type)
    if fn == "first_value":
        a = argv[0]
        # value at partition start: running 'carry first' via masked max of idx
        idx = jnp.arange(n, dtype=jnp.int32)
        start_idx = _seg_scan("max", jnp.where(new_part, idx, -1), new_part)
        data = jnp.take(a.data, start_idx)
        valid = None if a.valid is None else jnp.take(a.valid, start_idx)
        return ColumnVal(data, valid, a.dict, call.type)
    if fn == "last_value":
        a = argv[0]
        end = part_end if call.frame == "whole" else peer_end
        data = jnp.take(a.data, end)
        valid = None if a.valid is None else jnp.take(a.valid, end)
        return ColumnVal(data, valid, a.dict, call.type)

    # aggregates over a prefix frame ----------------------------------------
    if fn == "count_star":
        running = _seg_scan("add", live_s.astype(jnp.int64), new_part)
        return ColumnVal(_frame_value(running, call.frame, part_end, peer_end), None, None, call.type)

    a = argv[0]
    valid = _valid_of(a, n) & live_s
    if fn == "count":
        running = _seg_scan("add", valid.astype(jnp.int64), new_part)
        return ColumnVal(_frame_value(running, call.frame, part_end, peer_end), None, None, call.type)
    if fn in ("sum", "avg"):
        acc_t = (
            jnp.float64
            if (fn == "avg" or jnp.issubdtype(a.data.dtype, jnp.floating))
            else jnp.int64
        )
        contrib = jnp.where(valid, a.data.astype(acc_t), jnp.zeros((n,), acc_t))
        rsum = _seg_scan("add", contrib, new_part)
        rcnt = _seg_scan("add", valid.astype(jnp.int64), new_part)
        s = _frame_value(rsum, call.frame, part_end, peer_end)
        c = _frame_value(rcnt, call.frame, part_end, peer_end)
        if fn == "sum":
            return ColumnVal(s, c > 0, None, call.type)
        return ColumnVal(
            s.astype(jnp.float64) / jnp.maximum(c, 1).astype(jnp.float64),
            c > 0, None, call.type,
        )
    if fn in ("min", "max"):
        if a.dict is not None:
            raise NotImplementedError("window min/max over varchar")
        if jnp.issubdtype(a.data.dtype, jnp.floating):
            sent = jnp.asarray(jnp.inf if fn == "min" else -jnp.inf, a.data.dtype)
        else:
            info = jnp.iinfo(a.data.dtype)
            sent = jnp.asarray(info.max if fn == "min" else info.min, a.data.dtype)
        x = jnp.where(valid, a.data, sent)
        r = _seg_scan("min" if fn == "min" else "max", x, new_part)
        rc = _seg_scan("add", valid.astype(jnp.int64), new_part)
        v = _frame_value(r, call.frame, part_end, peer_end)
        c = _frame_value(rc, call.frame, part_end, peer_end)
        return ColumnVal(v, c > 0, None, call.type)
    raise NotImplementedError(f"window function {fn}")


def _frame_value(running: jnp.ndarray, frame: str, part_end, peer_end):
    if frame == "rows":
        return running
    end = part_end if frame == "whole" else peer_end
    return jnp.take(running, end)
