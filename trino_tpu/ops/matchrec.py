"""Row-pattern recognition: the MATCH_RECOGNIZE matcher.

The reference implements this as an NFA-program interpreter over one row at a
time (core/trino-main/src/main/java/io/trino/operator/window/matcher/
Matcher.java + IrRowPatternToProgramRewriter).  Here the DEFINE predicates
are evaluated VECTORIZED over the whole sorted page first (one device pass
per label — masks, not per-row virtual calls), and only the pattern walk
itself — inherently sequential under AFTER MATCH SKIP semantics — runs as a
compact backtracking VM over those boolean masks on the host.

Pattern compilation (Thompson construction with greedy/reluctant priority):

    instructions:
      ("row", label_idx)   consume one row that satisfies label's mask
      ("split", a, b)      try a first, then b (priority = preferment order)
      ("jmp", a)
      ("match",)

SQL preferment (greedy quantifiers prefer longer, alternation prefers the
left branch) maps exactly to the split priority of a backtracking walk.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["compile_pattern", "find_matches", "host_sort_rank"]

_MAX_REPEAT_UNROLL = 64  # {n,m} unroll guard
_STEP_BUDGET_FACTOR = 512  # backtracking step cap per start row (VM safety)


def compile_pattern(pattern) -> tuple[tuple[tuple, ...], tuple[str, ...]]:
    """Pattern AST (sql/ast.py Pat*) -> (program, labels)."""
    from ..sql.ast import PatAlt, PatConcat, PatLabel, PatQuant

    labels: list[str] = []
    label_ix: dict[str, int] = {}
    prog: list[tuple] = []

    def lab(name: str) -> int:
        if name not in label_ix:
            label_ix[name] = len(labels)
            labels.append(name)
        return label_ix[name]

    def emit(node) -> None:
        if isinstance(node, PatLabel):
            prog.append(("row", lab(node.label)))
            return
        if isinstance(node, PatConcat):
            for p in node.parts:
                emit(p)
            return
        if isinstance(node, PatAlt):
            # chain of splits preferring the leftmost branch
            jumps: list[int] = []
            for i, p in enumerate(node.parts):
                if i < len(node.parts) - 1:
                    split_at = len(prog)
                    prog.append(None)  # placeholder split
                    emit(p)
                    jumps.append(len(prog))
                    prog.append(None)  # placeholder jmp to end
                    prog[split_at] = ("split", split_at + 1, len(prog))
                else:
                    emit(p)
            end = len(prog)
            for j in jumps:
                prog[j] = ("jmp", end)
            return
        if isinstance(node, PatQuant):
            lo = node.lo
            hi = node.hi
            if hi is not None and hi - lo > _MAX_REPEAT_UNROLL:
                raise ValueError(f"pattern repetition too large: {{{lo},{hi}}}")
            for _ in range(lo):
                emit(node.child)
            if hi is None:
                # (child)* loop: split(body, exit) for greedy,
                # split(exit, body) for reluctant
                loop_at = len(prog)
                prog.append(None)
                emit(node.child)
                prog.append(("jmp", loop_at))
                exit_at = len(prog)
                prog[loop_at] = (
                    ("split", loop_at + 1, exit_at)
                    if node.greedy
                    else ("split", exit_at, loop_at + 1)
                )
            else:
                # (child){0, hi-lo}: nested optional copies
                exits: list[int] = []
                for _ in range(hi - lo):
                    split_at = len(prog)
                    prog.append(None)
                    exits.append(split_at)
                    emit(node.child)
                end = len(prog)
                for split_at in exits:
                    prog[split_at] = (
                        ("split", split_at + 1, end)
                        if node.greedy
                        else ("split", end, split_at + 1)
                    )
            return
        raise TypeError(f"unknown pattern node {node!r}")

    emit(pattern)
    prog.append(("match",))
    return tuple(prog), tuple(labels)


def _run_vm(
    program: Sequence[tuple],
    masks: np.ndarray,  # [L, n] bool — label eligibility per sorted row
    start: int,
    end: int,
) -> Optional[list[tuple[int, int]]]:
    """Find the PREFERRED match starting exactly at `start`, as a list of
    (row, label_idx) assignments (possibly spanning to < end).  Returns None
    when no non-empty match starts here.  Iterative backtracking: the trail
    of split decisions is the stack; priority order of `split` encodes SQL
    preferment."""
    # stack entries: (pc, pos, n_assigned, alt_pc) — alt_pc is the branch to
    # take when backtracking into this entry
    assigned: list[tuple[int, int]] = []
    stack: list[tuple[int, int, int]] = []  # (alt_pc, pos, n_assigned)
    pc, pos = 0, start
    budget = _STEP_BUDGET_FACTOR * max(end - start, 1)
    while True:
        budget -= 1
        if budget <= 0:
            raise RuntimeError(
                "row pattern exceeded step budget (catastrophic backtracking"
                " or empty-loop pattern)"
            )
        op = program[pc]
        kind = op[0]
        if kind == "row":
            if pos < end and masks[op[1], pos]:
                assigned.append((pos, op[1]))
                pos += 1
                pc += 1
                continue
        elif kind == "jmp":
            pc = op[1]
            continue
        elif kind == "split":
            stack.append((op[2], pos, len(assigned)))
            pc = op[1]
            continue
        else:  # match
            if assigned:
                return assigned
            # empty match: treat as failure (v1 skips empty matches rather
            # than emitting empty-match rows)
        # backtrack
        if not stack:
            return None
        pc, pos, keep = stack.pop()
        del assigned[keep:]


def find_matches(
    program: Sequence[tuple],
    masks: np.ndarray,  # [L, n] bool over SORTED rows
    part_start: np.ndarray,  # [n] int — partition start index per row
    after_skip: str,
) -> list[tuple[int, list[tuple[int, int]]]]:
    """Walk every partition; returns the match list
    [(match_number, [(sorted_row, label_idx), ...]), ...] in output order.
    match_number is 1-based and counts per partition (SQL MATCH_NUMBER()).
    With AFTER MATCH SKIP TO NEXT ROW matches may overlap, so rows can
    appear in several matches — a list, not a per-row array."""
    n = masks.shape[1] if masks.ndim == 2 else 0
    out: list[tuple[int, list[tuple[int, int]]]] = []
    i = 0
    while i < n:
        p0 = part_start[i]
        p_end = i
        while p_end < n and part_start[p_end] == p0:
            p_end += 1
        start = i
        mno = 0
        while start < p_end:
            found = _run_vm(program, masks, start, p_end)
            if found is None:
                start += 1
                continue
            mno += 1
            out.append((mno, found))
            last_row = found[-1][0]
            if after_skip == "next_row":
                start = start + 1
            else:  # past_last
                start = last_row + 1
        i = p_end
    return out


# --------------------------------------------------------------- execution
# The full MATCH_RECOGNIZE operator: sort -> vectorized DEFINE masks ->
# host VM walk -> measure evaluation.  Runs host-side over concrete arrays
# (the LocalExecutor forces the eager path for plans containing a
# MatchRecognize node, exactly as for host-collected aggregates): the walk
# is inherently sequential under AFTER MATCH SKIP semantics, matching the
# reference's single-threaded per-partition Matcher
# (operator/window/matcher/Matcher.java:28).


def host_sort_rank(data: np.ndarray, valid, dictionary, ascending: bool,
                   nulls_first: bool) -> tuple[np.ndarray, np.ndarray]:
    """(null_rank, value_rank) int arrays for np.lexsort, encoding NULL
    placement and direction (dictionary codes are unordered, so string keys
    rank through their decoded values).  Shared by the MATCH_RECOGNIZE
    global sort and relops' ordered host-collected aggregates."""
    n = len(data)
    if dictionary is not None:
        decoded = dictionary.values[np.clip(data, 0, max(len(dictionary) - 1, 0))]
        _, rank = np.unique(decoded, return_inverse=True)
    else:
        try:
            _, rank = np.unique(data, return_inverse=True)
        except TypeError:  # mixed-type object lanes: rank by repr
            _, rank = np.unique(
                np.asarray([repr(v) for v in data], dtype=object),
                return_inverse=True,
            )
    rank = rank.astype(np.int64)
    if not ascending:
        rank = -rank
    if valid is None:
        null_rank = np.zeros(n, dtype=np.int8)
    else:
        is_null = ~np.asarray(valid)
        null_rank = np.where(is_null, -1 if nulls_first else 1, 0).astype(np.int8)
        rank = np.where(is_null, 0, rank)
    return null_rank, rank


def execute_match(node, cols, live):
    """Execute a MatchRecognize plan node over concrete columns.

    cols: list[ColumnVal] (child schema), live: bool array.
    Returns (out_cols: list[ColumnVal], out_live: np.ndarray).
    """
    import jax.numpy as jnp

    from ..data.page import Dictionary
    from .expr import ColumnVal, eval_expr, eval_predicate

    live_np = np.asarray(live)
    sel = np.nonzero(live_np)[0]
    n = len(sel)

    def compact(cv: ColumnVal) -> ColumnVal:
        data = np.asarray(cv.data)[sel]
        valid = None if cv.valid is None else np.asarray(cv.valid)[sel]
        return ColumnVal(jnp.asarray(data), None if valid is None else jnp.asarray(valid),
                         cv.dict, cv.type)

    ccols = [compact(c) for c in cols]

    # ---- 1. global sort: partition keys, then ORDER BY keys -------------
    pkeys = [eval_expr(k, ccols, n) for k in node.partition_keys]
    okeys = [eval_expr(sk.expr, ccols, n) for sk in node.order_keys]
    lex: list[np.ndarray] = []  # np.lexsort: LAST array is the primary key
    for sk, kv in reversed(list(zip(node.order_keys, okeys))):
        nr, r = host_sort_rank(np.asarray(kv.data),
                           None if kv.valid is None else np.asarray(kv.valid),
                           kv.dict, sk.ascending, sk.nulls_first)
        lex.append(r)
        lex.append(nr)
    for kv in reversed(pkeys):
        nr, r = host_sort_rank(np.asarray(kv.data),
                           None if kv.valid is None else np.asarray(kv.valid),
                           kv.dict, True, True)
        lex.append(r)
        lex.append(nr)
    order = np.lexsort(lex) if lex else np.arange(n)

    def take(cv: ColumnVal) -> ColumnVal:
        data = np.asarray(cv.data)[order]
        valid = None if cv.valid is None else np.asarray(cv.valid)[order]
        return ColumnVal(jnp.asarray(data), None if valid is None else jnp.asarray(valid),
                         cv.dict, cv.type)

    scols = [take(c) for c in ccols]
    spkeys = [take(k) for k in pkeys]

    # ---- 2. partition runs ---------------------------------------------
    if pkeys and n:
        same = np.ones(n, dtype=bool)
        for kv in spkeys:
            d = np.asarray(kv.data)
            eq = d[1:] == d[:-1]
            if kv.valid is not None:
                # NULL keys group together: two rows match when both are
                # NULL (garbage under the mask must not split the run) or
                # both valid with equal data
                v = np.asarray(kv.valid)
                eq = np.where(~v[1:] & ~v[:-1], True, eq & v[1:] & v[:-1])
            same[1:] &= eq
        same[0] = False
        part_start = np.maximum.accumulate(
            np.where(~same, np.arange(n), 0))
    else:
        part_start = np.zeros(n, dtype=np.int64)

    # ---- 3. PREV/NEXT shifted columns ----------------------------------
    nav_cols = []
    for inner, k in node.prev_exprs:
        # nested navigation (PREV(x - PREV(x))): the planner lowers inner
        # calls first, so expression j may reference FieldRef(C + i) for
        # i < j — evaluate against child cols plus nav cols built so far
        v = eval_expr(inner, scols + nav_cols, n)
        data = np.asarray(v.data)
        valid = np.ones(n, dtype=bool) if v.valid is None else np.asarray(v.valid).copy()
        j = np.arange(n) - k  # k>0: PREV, k<0: NEXT
        inb = (j >= 0) & (j < n)
        jc = np.clip(j, 0, max(n - 1, 0))
        inb &= part_start[jc] == part_start  # same partition only
        data = np.where(inb, data[jc], np.zeros_like(data[:1]))
        valid = np.where(inb, valid[jc], False)
        nav_cols.append(ColumnVal(jnp.asarray(data), jnp.asarray(valid),
                                  v.dict, v.type))

    # ---- 4. vectorized DEFINE masks ------------------------------------
    define_input = scols + nav_cols
    L = len(node.labels)
    masks = np.zeros((L, max(n, 1)), dtype=bool)
    for li, ir in enumerate(node.defines):
        masks[li, :n] = np.asarray(eval_predicate(ir, define_input, n))[:n]

    # ---- 5. the walk ----------------------------------------------------
    matches = find_matches(node.program, masks[:, :n], part_start,
                           node.after_skip) if n else []

    # ---- 6. primitive columns per output row ---------------------------
    label_dict = Dictionary(np.asarray([l.upper() for l in node.labels],
                                       dtype=object))

    _field_cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}

    def field_np(ix: int) -> tuple[np.ndarray, np.ndarray]:
        # one device->host fetch per referenced field, NOT per output row
        hit = _field_cache.get(ix)
        if hit is None:
            cv = scols[ix]
            d = np.asarray(cv.data)
            v = np.ones(n, dtype=bool) if cv.valid is None else np.asarray(cv.valid)
            hit = _field_cache[ix] = (d, v)
        return hit

    out_rows: list[int] = []  # sorted-row index per output row (ALL ROWS)
    prim_vals: list[list] = [[] for _ in node.prims]  # python values; None=NULL
    match_of_row: list[tuple[int, list]] = []  # (mno, rows) per output row

    if node.all_rows:
        for mno, assigned in matches:
            for pos, (row, lab) in enumerate(assigned):
                out_rows.append(row)
                match_of_row.append((mno, assigned[: pos + 1]))
    else:
        for mno, assigned in matches:
            match_of_row.append((mno, assigned))

    for pi, (kind, lab_ix, f_ix) in enumerate(node.prims):
        vals = prim_vals[pi]
        for mno, assigned in match_of_row:
            if kind == "match_number":
                vals.append(mno)
                continue
            if kind == "classifier":
                # RUNNING (ALL ROWS): label of the current row;
                # FINAL (ONE ROW): label of the last row of the match
                vals.append(assigned[-1][1])
                continue
            rows = [r for r, l in assigned if lab_ix < 0 or l == lab_ix]
            if not rows:
                vals.append(None)
                continue
            r = rows[0] if kind == "first" else rows[-1]
            d, v = field_np(f_ix)
            vals.append(d[r].item() if v[r] else None)

    m_out = len(match_of_row)

    def prim_column(pi: int) -> ColumnVal:
        kind = node.prims[pi][0]
        tt = node.prim_types[pi]
        vals = prim_vals[pi]
        valid = np.asarray([v is not None for v in vals], dtype=bool)
        if kind == "classifier":
            data = np.asarray([v if v is not None else 0 for v in vals],
                              dtype=np.int32)
            return ColumnVal(jnp.asarray(data), jnp.asarray(valid),
                             label_dict, tt)
        f_ix = node.prims[pi][2]
        dictionary = scols[f_ix].dict if f_ix >= 0 else None
        data = np.asarray([v if v is not None else 0 for v in vals],
                          dtype=tt.np_dtype)
        return ColumnVal(jnp.asarray(data), jnp.asarray(valid), dictionary, tt)

    prim_cols = [prim_column(i) for i in range(len(node.prims))]
    measure_cols = [eval_expr(ir, prim_cols, max(m_out, 1))
                    for ir in node.measures]

    # slice consts/broadcasts down and pad everything to >= 1 row
    cap = max(m_out, 1)

    def fit(cv: ColumnVal) -> ColumnVal:
        data = np.asarray(cv.data)
        if data.shape[0] < cap:
            data = np.concatenate(
                [data, np.zeros((cap - data.shape[0],), dtype=data.dtype)])
        else:
            data = data[:cap]
        valid = cv.valid
        if valid is not None:
            valid = np.asarray(valid)
            if valid.shape[0] < cap:
                valid = np.concatenate(
                    [valid, np.zeros((cap - valid.shape[0],), dtype=bool)])
            else:
                valid = valid[:cap]
            valid = jnp.asarray(valid)
        return ColumnVal(jnp.asarray(data), valid, cv.dict, cv.type)

    if node.all_rows:
        rows_idx = np.asarray(out_rows, dtype=np.int64)

        def gather(cv: ColumnVal) -> ColumnVal:
            d = np.asarray(cv.data)[rows_idx] if m_out else np.asarray(cv.data)[:0]
            v = None
            if cv.valid is not None:
                v = np.asarray(cv.valid)[rows_idx] if m_out else np.asarray(cv.valid)[:0]
                v = jnp.asarray(v)
            return ColumnVal(jnp.asarray(d), v, cv.dict, cv.type)

        out_cols = [fit(gather(c)) for c in scols] + [fit(c) for c in measure_cols]
    else:
        first_rows = np.asarray(
            [assigned[0][0] for _, assigned in match_of_row], dtype=np.int64)

        def at_first(cv: ColumnVal) -> ColumnVal:
            d = np.asarray(cv.data)[first_rows] if m_out else np.asarray(cv.data)[:0]
            v = None
            if cv.valid is not None:
                v = np.asarray(cv.valid)[first_rows] if m_out else np.asarray(cv.valid)[:0]
                v = jnp.asarray(v)
            return ColumnVal(jnp.asarray(d), v, cv.dict, cv.type)

        out_cols = [fit(at_first(k)) for k in spkeys] + [fit(c) for c in measure_cols]

    out_live = np.arange(cap) < m_out
    return out_cols, jnp.asarray(out_live)
