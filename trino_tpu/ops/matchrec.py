"""Row-pattern recognition: the MATCH_RECOGNIZE matcher.

The reference implements this as an NFA-program interpreter over one row at a
time (core/trino-main/src/main/java/io/trino/operator/window/matcher/
Matcher.java + IrRowPatternToProgramRewriter).  Here the DEFINE predicates
are evaluated VECTORIZED over the whole sorted page first (one device pass
per label — masks, not per-row virtual calls), and only the pattern walk
itself — inherently sequential under AFTER MATCH SKIP semantics — runs as a
compact backtracking VM over those boolean masks on the host.

Pattern compilation (Thompson construction with greedy/reluctant priority):

    instructions:
      ("row", label_idx)   consume one row that satisfies label's mask
      ("split", a, b)      try a first, then b (priority = preferment order)
      ("jmp", a)
      ("match",)

SQL preferment (greedy quantifiers prefer longer, alternation prefers the
left branch) maps exactly to the split priority of a backtracking walk.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

__all__ = ["compile_pattern", "find_matches"]

_MAX_REPEAT_UNROLL = 64  # {n,m} unroll guard
_STEP_BUDGET_FACTOR = 512  # backtracking step cap per start row (VM safety)


def compile_pattern(pattern) -> tuple[tuple[tuple, ...], tuple[str, ...]]:
    """Pattern AST (sql/ast.py Pat*) -> (program, labels)."""
    from ..sql.ast import PatAlt, PatConcat, PatLabel, PatQuant

    labels: list[str] = []
    label_ix: dict[str, int] = {}
    prog: list[tuple] = []

    def lab(name: str) -> int:
        if name not in label_ix:
            label_ix[name] = len(labels)
            labels.append(name)
        return label_ix[name]

    def emit(node) -> None:
        if isinstance(node, PatLabel):
            prog.append(("row", lab(node.label)))
            return
        if isinstance(node, PatConcat):
            for p in node.parts:
                emit(p)
            return
        if isinstance(node, PatAlt):
            # chain of splits preferring the leftmost branch
            jumps: list[int] = []
            for i, p in enumerate(node.parts):
                if i < len(node.parts) - 1:
                    split_at = len(prog)
                    prog.append(None)  # placeholder split
                    emit(p)
                    jumps.append(len(prog))
                    prog.append(None)  # placeholder jmp to end
                    prog[split_at] = ("split", split_at + 1, len(prog))
                else:
                    emit(p)
            end = len(prog)
            for j in jumps:
                prog[j] = ("jmp", end)
            return
        if isinstance(node, PatQuant):
            lo = node.lo
            hi = node.hi
            if hi is not None and hi - lo > _MAX_REPEAT_UNROLL:
                raise ValueError(f"pattern repetition too large: {{{lo},{hi}}}")
            for _ in range(lo):
                emit(node.child)
            if hi is None:
                # (child)* loop: split(body, exit) for greedy,
                # split(exit, body) for reluctant
                loop_at = len(prog)
                prog.append(None)
                emit(node.child)
                prog.append(("jmp", loop_at))
                exit_at = len(prog)
                prog[loop_at] = (
                    ("split", loop_at + 1, exit_at)
                    if node.greedy
                    else ("split", exit_at, loop_at + 1)
                )
            else:
                # (child){0, hi-lo}: nested optional copies
                exits: list[int] = []
                for _ in range(hi - lo):
                    split_at = len(prog)
                    prog.append(None)
                    exits.append(split_at)
                    emit(node.child)
                end = len(prog)
                for split_at in exits:
                    prog[split_at] = (
                        ("split", split_at + 1, end)
                        if node.greedy
                        else ("split", end, split_at + 1)
                    )
            return
        raise TypeError(f"unknown pattern node {node!r}")

    emit(pattern)
    prog.append(("match",))
    return tuple(prog), tuple(labels)


def _run_vm(
    program: Sequence[tuple],
    masks: np.ndarray,  # [L, n] bool — label eligibility per sorted row
    start: int,
    end: int,
) -> Optional[list[tuple[int, int]]]:
    """Find the PREFERRED match starting exactly at `start`, as a list of
    (row, label_idx) assignments (possibly spanning to < end).  Returns None
    when no non-empty match starts here.  Iterative backtracking: the trail
    of split decisions is the stack; priority order of `split` encodes SQL
    preferment."""
    # stack entries: (pc, pos, n_assigned, alt_pc) — alt_pc is the branch to
    # take when backtracking into this entry
    assigned: list[tuple[int, int]] = []
    stack: list[tuple[int, int, int]] = []  # (alt_pc, pos, n_assigned)
    pc, pos = 0, start
    budget = _STEP_BUDGET_FACTOR * max(end - start, 1)
    while True:
        budget -= 1
        if budget <= 0:
            raise RuntimeError(
                "row pattern exceeded step budget (catastrophic backtracking"
                " or empty-loop pattern)"
            )
        op = program[pc]
        kind = op[0]
        if kind == "row":
            if pos < end and masks[op[1], pos]:
                assigned.append((pos, op[1]))
                pos += 1
                pc += 1
                continue
        elif kind == "jmp":
            pc = op[1]
            continue
        elif kind == "split":
            stack.append((op[2], pos, len(assigned)))
            pc = op[1]
            continue
        else:  # match
            if assigned:
                return assigned
            # empty match: treat as failure (v1 skips empty matches rather
            # than emitting empty-match rows)
        # backtrack
        if not stack:
            return None
        pc, pos, keep = stack.pop()
        del assigned[keep:]


def find_matches(
    program: Sequence[tuple],
    masks: np.ndarray,  # [L, n] bool over SORTED rows
    part_start: np.ndarray,  # [n] int — partition start index per row
    after_skip: str,
) -> list[tuple[int, list[tuple[int, int]]]]:
    """Walk every partition; returns the match list
    [(match_number, [(sorted_row, label_idx), ...]), ...] in output order.
    match_number is 1-based and counts per partition (SQL MATCH_NUMBER()).
    With AFTER MATCH SKIP TO NEXT ROW matches may overlap, so rows can
    appear in several matches — a list, not a per-row array."""
    n = masks.shape[1] if masks.ndim == 2 else 0
    out: list[tuple[int, list[tuple[int, int]]]] = []
    i = 0
    while i < n:
        p0 = part_start[i]
        p_end = i
        while p_end < n and part_start[p_end] == p0:
            p_end += 1
        start = i
        mno = 0
        while start < p_end:
            found = _run_vm(program, masks, start, p_end)
            if found is None:
                start += 1
                continue
            mno += 1
            out.append((mno, found))
            last_row = found[-1][0]
            if after_skip == "next_row":
                start = start + 1
            else:  # past_last
                start = last_row + 1
        i = p_end
    return out
