"""Relational operator kernels: static-shape, mask-based, jit-traceable.

These replace the reference's virtual-call operator chain (operator/*.java)
with whole-page device kernels:

- aggregation: the reference's FlatHash Swiss-table (operator/FlatHash.java:38)
  becomes a SORT-BASED group-by: lax.sort on the key columns, run-boundary
  detection, then segment_sum/min/max.  On TPU, a bitonic sort over HBM-
  resident lanes beats scalar hash probing by orders of magnitude, and the
  fixed reduction tree makes float aggregation deterministic (a north-star
  requirement the Java engine itself cannot honor across runs).
- equi-join: the reference's PagesHash + JoinProbe (operator/join/) becomes
  sort + vectorized binary search (searchsorted) + prefix-sum expansion.
  Output capacity is static; the kernel reports the true match count so the
  host can retry at a bigger tier (exec/executor.py), mirroring how the
  reference's planner-fed stats size hash tables.
- sort/topn: multi-key lax.sort with direction/null-order key transforms.

Every kernel takes and returns columns + a `live` mask; dead lanes carry
garbage and are never branched on (XLA sees straight-line vector code).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..data.page import Dictionary
from .expr import ColumnVal

__all__ = [
    "group_aggregate", "equi_join", "broadcast_single_row", "sort_rows",
    "compact_rows", "top_n", "limit_mask", "unnest_expand", "AggSpec",
    "SortSpec",
]


@dataclass(frozen=True)
class AggSpec:
    fn: str  # sum | count | count_star | min | max | avg | bool_and |
    #          bool_or | stddev_samp | stddev_pop | var_samp | var_pop |
    #          percentile | corr | covar_samp | covar_pop | regr_slope |
    #          regr_intercept | array_agg | map_agg | listagg
    distinct: bool = False
    param: Optional[float] = None  # percentile's p
    sep: Optional[str] = None  # listagg separator
    type: Optional[object] = None  # result SqlType (decimal SUMs with
    #          precision > 18 accumulate in two-limb int128 even when the
    #          input column is single-lane; without it int64 wraps silently)


# aggregates computed on the HOST over the sorted grouping (their outputs
# are dict-coded structured values a traced kernel cannot intern); the
# executor routes plans containing them through eager execution
HOST_AGGS = frozenset({"array_agg", "map_agg", "listagg"})

# two-argument moment aggregates (pairwise sums on the device)
MOMENT_AGGS = frozenset(
    {"corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept"}
)


@dataclass(frozen=True)
class SortSpec:
    ascending: bool = True
    nulls_first: bool = False


def _valid_of(v: ColumnVal, n: int) -> jnp.ndarray:
    return jnp.ones((n,), jnp.bool_) if v.valid is None else v.valid


_MATMUL_SEGMENT_LIMIT = 1024

_SEARCHSORTED_SORT_MIN = 4096


def searchsorted_tpu(a: jnp.ndarray, v: jnp.ndarray, side: str = "left"):
    """jnp.searchsorted with the method picked for TPU: the default binary
    search lowers to log2(n) SEQUENTIAL gather rounds over HBM (~1.8s for
    8M probes into 8M keys — measured; it was the q03/q18 bottleneck), while
    'sort' does one fused bitonic pass over a++v (~30ms).  Small query sets
    keep the scan — sorting the whole haystack for a handful of lookups
    loses."""
    method = "sort" if v.size >= _SEARCHSORTED_SORT_MIN else "scan"
    return jnp.searchsorted(a, v, side=side, method=method)


def _segment_sum(
    values: jnp.ndarray, seg: jnp.ndarray, num: int, sorted_segments: bool = False
) -> jnp.ndarray:
    """Backend-aware segment sum.  On CPU, XLA's scatter-add is fine.  On
    TPU, scatter serializes — but a one-hot matmul runs on the MXU, which is
    exactly how a TPU wants to aggregate (SURVEY §7: keep the FLOPs where
    the systolic array is).  Used when the segment count is small enough
    that the [n, G] one-hot is cheap.  For NONDECREASING seg (the sorted
    group-by's order) large segment counts use boundary cumsum diffs —
    gathers and scans only, never a big scatter."""
    if jax.default_backend() != "cpu" and num <= _MATMUL_SEGMENT_LIMIT:
        if jnp.issubdtype(values.dtype, jnp.integer):
            return _limb_segment_sum(values, seg, num)
        return _chunked_f32_segment_sum(values, seg, num).astype(values.dtype)
    if sorted_segments and jax.default_backend() != "cpu":
        from .pallas.segreduce import SegRed, _sorted_fallback

        return _sorted_fallback(seg, [SegRed("sum", values, None)], num)[0].astype(
            values.dtype
        )
    return jax.ops.segment_sum(values, seg, num_segments=num)


def _limb_segment_sum(values: jnp.ndarray, seg: jnp.ndarray, num: int):
    """EXACT int64 segment sum on the MXU: decompose |v| into 15-bit limbs so
    every 512-row chunk partial stays below 2^24 (exact in f32), sum each
    limb with the f32 einsum, recombine in f64 (exact to 2^53 — counts and
    SQL-realistic BIGINT sums)."""
    sign = jnp.sign(values).astype(jnp.float64)
    mag = jnp.abs(values.astype(jnp.int64))
    total = jnp.zeros((num,), jnp.float64)
    for limb in range(4):  # 60 bits
        part = ((mag >> (15 * limb)) & 0x7FFF).astype(jnp.float64) * sign
        total = total + _chunked_f32_segment_sum(part, seg, num) * float(1 << (15 * limb))
    return jnp.round(total).astype(values.dtype)


_CHUNK = 512


def _chunked_f32_segment_sum(values: jnp.ndarray, seg: jnp.ndarray, num: int):
    """f32 MXU einsum per 512-row chunk, f64 accumulation across chunks.

    Per-chunk f32 error is ~sqrt(512) ulp and chunk partials are combined
    exactly-ish in f64, giving ~1e-8 relative error on money-scale sums —
    inside the differential-test tolerance, at MXU speed.  (The emulated-f64
    matmul this replaces is ~5x slower; true exactness comes with the Pallas
    segment-reduce kernel.)"""
    n = values.shape[0]
    C = -(-n // _CHUNK)
    pad = C * _CHUNK - n
    v = jnp.pad(values.astype(jnp.float32), (0, pad)).reshape(C, _CHUNK)
    s = jnp.pad(seg, (0, pad), constant_values=num).reshape(C, _CHUNK)
    onehot = jax.nn.one_hot(s, num, dtype=jnp.float32, axis=-1)  # [C, K, G]
    partial = jnp.einsum("ck,ckg->cg", v, onehot)  # MXU
    return partial.astype(jnp.float64).sum(axis=0)


def _sortable_operands(v: ColumnVal, descending: bool = False) -> list:
    """Sort operand list for one key: one array for single-lane columns,
    TWO for decimal128 (lexicographic (hi signed, lo unsigned) == 128-bit
    numeric order; descending negates at 128-bit width first)."""
    if v.data2 is not None:
        from ..data.dec128 import neg128

        lo = v.data.astype(jnp.int64)
        hi = v.data2.astype(jnp.int64)
        if descending:
            lo, hi = neg128(lo, hi)
        lo_u = jax.lax.bitcast_convert_type(lo, jnp.uint64)
        return [hi, lo_u]
    return [_sortable_key(v, descending)]


def _sortable_key(v: ColumnVal, descending: bool = False) -> jnp.ndarray:
    """Lower a column to a sortable numeric array (varchar -> dictionary rank,
    bool -> int8); negated for descending order."""
    if v.data2 is not None:
        raise NotImplementedError(
            "decimal128 lanes in this operation (two-limb keys)"
        )
    data = v.data
    if v.dict is not None:
        data = jnp.take(jnp.asarray(v.dict.sorted_rank()), v.data)
    if data.dtype == jnp.bool_:
        data = data.astype(jnp.int8)
    if descending:
        data = -data.astype(jnp.promote_types(data.dtype, jnp.int8))
    return data


# ----------------------------------------------- hash-kernel key encoding
#
# The Pallas hash kernels (ops/pallas/hashagg.py, hashjoin.py) compare keys
# as fixed lists of i32 words.  Equality over the words must coincide with
# the sort path's grouping / the join's verified match semantics:
#
#   group-by: the sort path's run boundary fires on (~valid, raw operand)
#     per key, so NULL rows group by validity AND payload — encoding the raw
#     words plus one packed validity word reproduces that exactly.  DOUBLE
#     keys are rejected: the sort path gives every NaN row its own group
#     (raw-compare diff), which no bitwise word equality can express.
#   join: matches are re-verified exactly downstream, so extra candidates
#     are harmless but MISSED ones are not — -0.0 is canonicalized to +0.0
#     (they compare equal), NULL keys are simply excluded from the build
#     and probe live sets (they never match).
#
# Dictionary-coded columns encode their CODES (the sort path's sorted_rank
# is a bijection of codes, and the join verifies by code equality), other
# integers sign-extend to two words, decimal128 to four.


def _words64(bits: jnp.ndarray) -> list:
    u = bits.astype(jnp.uint64)
    lo = jax.lax.bitcast_convert_type(
        (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32), jnp.int32
    )
    hi = jax.lax.bitcast_convert_type(
        (u >> jnp.uint64(32)).astype(jnp.uint32), jnp.int32
    )
    return [lo, hi]


def _combine64(lo32: jnp.ndarray, hi32: jnp.ndarray) -> jnp.ndarray:
    u = lo32.astype(jnp.uint32).astype(jnp.uint64) | (
        hi32.astype(jnp.uint32).astype(jnp.uint64) << jnp.uint64(32)
    )
    return jax.lax.bitcast_convert_type(u, jnp.int64)


def _hash_key_words(keys: Sequence[ColumnVal], n: int, for_join: bool):
    """Encode key columns as i32 word lists for the hash kernels, or None
    when a column's equality semantics cannot be carried by words (see
    above).  Returns (words, layout) where layout[k] is the per-key word
    kind: 'dict' | 'i32' | 'i64' | 'f64' | 'dec128'."""
    words: list = []
    layout: list[str] = []
    for kv in keys:
        if kv.dict is not None:
            words.append(kv.data.astype(jnp.int32))
            layout.append("dict")
        elif kv.data2 is not None:
            words.extend(_words64(kv.data.astype(jnp.int64)))
            words.extend(_words64(kv.data2.astype(jnp.int64)))
            layout.append("dec128")
        elif jnp.issubdtype(kv.data.dtype, jnp.floating):
            if not for_join:
                return None, None  # per-NaN-row groups are not word-equatable
            d = kv.data.astype(jnp.float64)
            d = jnp.where(d == 0.0, jnp.float64(0.0), d)  # -0.0 matches +0.0
            words.extend(_words64(jax.lax.bitcast_convert_type(d, jnp.uint64)))
            layout.append("f64")
        elif kv.data.dtype.itemsize > 4:
            words.extend(_words64(kv.data.astype(jnp.int64)))
            layout.append("i64")
        elif kv.data.dtype == jnp.bool_ or jnp.issubdtype(
            kv.data.dtype, jnp.integer
        ):
            words.append(kv.data.astype(jnp.int32))
            layout.append("i32")
        else:
            return None, None
    return words, layout


def _hash_aggregate(key_vals, agg_args, specs, live, G, agg_args2):
    """Pallas hash-table grouped aggregation: one streaming build pass
    assigns every row a dense group id (ops/pallas/hashagg.py), the fused
    segment reductions run over those ids unsorted, and the output key
    columns are decoded from the hash table itself (<= a few thousand
    entries) — no sort of the input anywhere.  Returns the group_aggregate
    result tuple, or None when the static gate picks the sort path.

    Overflow (more distinct groups than the capacity tier, or probe-budget
    exhaustion) reports an inflated n_groups through the normal required
    channel: the executor retries at a doubled tier, and once the tier
    exceeds hash_agg_max_groups this gate flips to the sort path — the
    deterministic overflow-to-sort fallback."""
    from .kernels import get_policy, record_dispatch

    if any(
        s.distinct or s.fn in ("percentile", "approx_distinct") or s.fn in HOST_AGGS
        for s in specs
    ):
        return None  # value-sorted / host aggregates need the sort anyway
    policy = get_policy()
    if not policy.enabled:
        record_dispatch("group_by", "sort", "kernels disabled")
        return None
    from .pallas import hashagg

    n = live.shape[0]
    if G > policy.hash_agg_max_groups:
        record_dispatch("group_by", "fallback", f"cap {G} > hash_agg_limit")
        return None
    interpret = policy.interpret or hashagg.INTERPRET
    if not interpret and jax.default_backend() not in ("tpu", "axon"):
        # decline before encoding: the key-word encode below is real work
        # on the eager/interpreted-fallback execution path
        record_dispatch("group_by", "sort", "cpu backend")
        return None
    enc, layout = _hash_key_words(key_vals, n, for_join=False)
    if enc is None:
        record_dispatch("group_by", "sort", "keys not word-encodable")
        return None
    validity = jnp.zeros((n,), jnp.int32)
    for k, kv in enumerate(key_vals):
        validity = validity | (_valid_of(kv, n).astype(jnp.int32) << k)
    words = enc + [validity]
    if not hashagg.shape_supported(n, len(words), G):
        record_dispatch("group_by", "sort", "shape unsupported")
        return None
    record_dispatch(
        "group_by", "pallas", f"{len(words)}w cap {G} table {hashagg.table_size(G)}"
    )

    gid, table, n_true, overflow = hashagg.build_hash_table(
        words, live, G, interpret=interpret
    )
    seg = jnp.where(live & (gid >= 0) & (gid < G), gid, G).astype(jnp.int32)
    out_aggs = _fused_aggs(
        agg_args, specs, None, seg, live, G, n, agg_args2=agg_args2
    )

    # decode the output key columns from the table entries, ordered by gid
    T = table.shape[1]
    entry_gid = jnp.where(table[0] > 0.5, table[1].astype(jnp.int32), T)
    order = jnp.argsort(entry_gid)[:G]

    def word_at(i):
        lo = jnp.take(table[2 + 2 * i], order).astype(jnp.uint32)
        hi = jnp.take(table[3 + 2 * i], order).astype(jnp.uint32)
        return jax.lax.bitcast_convert_type(lo | (hi << jnp.uint32(16)), jnp.int32)

    vword = word_at(len(enc))
    out_keys = []
    wpos = 0
    for k, (kv, kind) in enumerate(zip(key_vals, layout)):
        valid = ((vword >> k) & 1) != 0
        if kind in ("dict", "i32"):
            data = word_at(wpos).astype(kv.data.dtype)
            wpos += 1
            out_keys.append((data, valid, None))
        elif kind == "i64":
            data = _combine64(word_at(wpos), word_at(wpos + 1)).astype(kv.data.dtype)
            wpos += 2
            out_keys.append((data, valid, None))
        else:  # dec128
            lo = _combine64(word_at(wpos), word_at(wpos + 1))
            hi = _combine64(word_at(wpos + 2), word_at(wpos + 3))
            wpos += 4
            out_keys.append((lo, valid, hi))

    out_live = jnp.arange(G, dtype=jnp.int32) < jnp.minimum(n_true, G)
    n_report = jnp.where(overflow, jnp.maximum(n_true, jnp.int32(G + 1)), n_true)
    return out_keys, out_aggs, out_live, n_report


# ------------------------------------------------------------ aggregation


def group_aggregate(
    key_vals: Sequence[ColumnVal],
    agg_args: Sequence[Optional[ColumnVal]],
    specs: Sequence[AggSpec],
    live: jnp.ndarray,
    num_groups_cap: int,
    agg_args2: Optional[Sequence[Optional[ColumnVal]]] = None,
    agg_order: Optional[Sequence[tuple]] = None,
):
    """Sort-based grouped aggregation.

    Returns (out_keys: list[(data, valid, data2-or-None)], out_aggs:
    list[(data, valid) or (data, valid, Dictionary) for host-collected
    aggregates], out_live, n_groups) where outputs have capacity
    `num_groups_cap` and n_groups is the true group count (> cap ==
    overflow, host retries).
    """
    n = live.shape[0]
    G = num_groups_cap
    if agg_args2 is None:
        agg_args2 = [None] * len(specs)
    if agg_order is None:
        agg_order = [()] * len(specs)

    if not key_vals:
        return _global_aggregate(agg_args, specs, live, agg_args2, agg_order)

    fast = _direct_code_aggregate(key_vals, agg_args, specs, live, agg_args2)
    if fast is not None:
        return fast

    hashed = _hash_aggregate(key_vals, agg_args, specs, live, G, agg_args2)
    if hashed is not None:
        return hashed

    # ---- sort rows by (dead-last, keys..., [value-sorted agg arg]) --------
    # value-sorted aggregates (DISTINCT adjacency, percentile selection) ride
    # the group sort; the FIRST one shares the main sort, each additional one
    # gets its own sort pass below (group order is key-determined, so segment
    # ids align across sorts).
    vs_ix = [
        i
        for i, s in enumerate(specs)
        if (s.distinct or s.fn == "percentile") and agg_args[i] is not None
    ]

    def grouped_sort(extra: Optional[ColumnVal]):
        """Sort by (dead, keys..., extra arg) -> (perm, live_s, seg,
        new_group, n_groups).  Validity of `extra` sorts before its value so
        a NULL lane whose code equals a live value cannot become the "first
        occurrence" (the round-1 COUNT(DISTINCT) advisory bug)."""
        operands: list[jnp.ndarray] = [(~live).astype(jnp.int8)]
        for kv in key_vals:
            operands.append(~_valid_of(kv, n))  # nulls group together (last)
            operands.extend(_sortable_operands(kv))  # 2 ops for decimal128
        n_key_ops = len(operands) - 1
        if extra is not None:
            operands.append((~_valid_of(extra, n)).astype(jnp.int8))
            operands.extend(_sortable_operands(extra))
        iota = jnp.arange(n, dtype=jnp.int32)
        sorted_ops = jax.lax.sort(operands + [iota], num_keys=len(operands))
        perm = sorted_ops[-1]
        live_s = jnp.take(live, perm)
        key_ops = sorted_ops[1 : 1 + n_key_ops]
        diff = jnp.zeros((n,), jnp.bool_)
        for op in key_ops:
            prev = jnp.concatenate([op[:1], op[:-1]])
            diff = diff | (op != prev)
        first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
        new_group = live_s & (first | diff)
        seg = jnp.cumsum(new_group.astype(jnp.int32)) - 1
        seg = jnp.where(live_s, seg, G)  # dead rows -> overflow bucket
        seg = jnp.minimum(seg, G)
        n_groups = jnp.sum(new_group.astype(jnp.int32))
        return perm, live_s, seg, new_group, n_groups

    perm, live_s, seg, new_group, n_groups = grouped_sort(
        agg_args[vs_ix[0]] if vs_ix else None
    )

    # ---- output keys: first row of each segment ---------------------------
    # seg is NONDECREASING (rows sorted by keys), so the first row of group g
    # is a gather at searchsorted(seg, g) — no scatter (TPU scatters
    # serialize; this was the high-cardinality group-by bottleneck).  One
    # boundary pass is shared with the fused reductions below.
    gids = jnp.arange(G, dtype=jnp.int32)
    seg32 = jnp.minimum(seg.astype(jnp.int32), G)
    starts = searchsorted_tpu(seg32, gids, side="left")
    ends = searchsorted_tpu(seg32, gids, side="right")
    starts_i = jnp.clip(starts, 0, max(n - 1, 0))
    out_keys: list[tuple] = []
    for kv in key_vals:
        data_s = jnp.take(kv.data, perm)
        valid_s = jnp.take(_valid_of(kv, n), perm)
        hi = None
        if kv.data2 is not None:  # decimal128 keys: carry the high limb
            hi = jnp.take(jnp.take(kv.data2, perm), starts_i)
        out_keys.append(
            (jnp.take(data_s, starts_i), jnp.take(valid_s, starts_i), hi)
        )

    # ---- aggregates -------------------------------------------------------
    out_aggs = _fused_aggs(
        agg_args, specs, perm, seg, live_s, G, n,
        sorted_segments=True, boundaries=(starts, ends), agg_args2=agg_args2,
    )
    for i, (arg, spec) in enumerate(zip(agg_args, specs)):
        if out_aggs[i] is None and spec.fn == "approx_distinct":
            out_aggs[i] = _segment_hll(arg, perm, seg, live_s, G, n)
            continue
        if out_aggs[i] is None and spec.fn in HOST_AGGS:
            out_aggs[i] = _host_collect_agg(
                spec, arg, agg_args2[i], perm, seg, live_s, G, n,
                order=agg_order[i],
            )
            continue
        if out_aggs[i] is None:  # DISTINCT/percentile: need sorted adjacency
            if i == vs_ix[0]:
                p, ls, sg, ng = perm, live_s, seg, new_group
            else:  # additional value-sorted agg: its own sort pass
                p, ls, sg, ng, _ = grouped_sort(arg)
            if spec.fn == "percentile":
                out_aggs[i] = _segment_percentile(arg, spec.param, p, sg, ls, G, n)
            else:
                out_aggs[i] = _segment_agg(arg, spec, p, sg, ls, ng, G, n)

    out_live = jnp.arange(G, dtype=jnp.int32) < jnp.minimum(n_groups, G)
    return out_keys, out_aggs, out_live, n_groups


_DIRECT_DOMAIN_LIMIT = 4096


def _direct_code_aggregate(key_vals, agg_args, specs, live, agg_args2=None):
    """Fast path: every group key is a dictionary-coded column with no nulls
    and the key-domain product is small -> segment id IS the fused code; no
    sort, no scatter, just segment reductions.  This is the case the
    reference's DictionaryAwarePageProjection + BigintGroupByHash fast paths
    chase (TPC-H Q1: returnflag x linestatus = 6 groups over 6B rows at
    SF1000); on TPU it turns group-by into a bandwidth-bound reduction."""
    if agg_args2 is None:
        agg_args2 = [None] * len(specs)
    if any(
        s.distinct or s.fn in ("percentile", "approx_distinct") or s.fn in HOST_AGGS
        for s in specs
    ):
        return None
    domains = []
    for kv in key_vals:
        if kv.dict is None or kv.valid is not None:
            return None
        domains.append(len(kv.dict))
    total = 1
    for d in domains:
        total *= max(d, 1)
    if not (0 < total <= _DIRECT_DOMAIN_LIMIT):
        return None
    domains = [max(d, 1) for d in domains]  # empty dicts (all-dead pages)
    n = live.shape[0]
    G = total
    seg = jnp.zeros((n,), jnp.int32)
    for kv, d in zip(key_vals, domains):
        seg = seg * d + jnp.clip(kv.data.astype(jnp.int32), 0, d - 1)
    seg = jnp.where(live, seg, G)
    num = G + 1
    cnt_any = _segment_sum(live.astype(jnp.int64), seg, num)[:G]
    out_live = cnt_any > 0
    n_groups = jnp.sum(out_live.astype(jnp.int32))

    # decode segment index -> key codes (host-side iota tables)
    out_keys = []
    idx = np.arange(G, dtype=np.int64)
    rem = idx
    codes_per_key = []
    for d in reversed(domains):
        codes_per_key.append(rem % d)
        rem = rem // d
    codes_per_key.reverse()
    for kv, codes in zip(key_vals, codes_per_key):
        out_keys.append((jnp.asarray(codes.astype(np.int32)), None, None))

    out_aggs = _fused_aggs(agg_args, specs, None, seg, live, G, n, agg_args2=agg_args2)
    return out_keys, out_aggs, out_live, n_groups


def _fused_aggs(
    agg_args, specs, perm, seg, live_s, G, n,
    sorted_segments=False, boundaries=None, agg_args2=None,
):
    """All non-DISTINCT aggregates of a GROUP BY in one fused segmented
    reduction (ops/pallas/segreduce.py): on TPU a single Pallas pass over HBM
    computes every SUM/COUNT/AVG on the MXU (exact int64 via limb
    decomposition, Kahan-compensated doubles) and every MIN/MAX on the VPU;
    on CPU the same call falls back to XLA segment ops.  This replaces the
    reference's per-function Accumulator loop (operator/aggregation/, 224
    files) with one bandwidth-bound kernel.

    Returns a list aligned with specs; DISTINCT entries are None (the caller
    computes those with the sorted-adjacency path).
    """
    from .pallas.segreduce import SegRed, fused_segment_reduce

    reds: list = []
    count_memo: dict = {}

    def add(red) -> int:
        reds.append(red)
        return len(reds) - 1

    def add_count(valid) -> int:
        key = id(valid)
        if key not in count_memo:
            count_memo[key] = add(SegRed("count", None, valid))
        return count_memo[key]

    if agg_args2 is None:
        agg_args2 = [None] * len(specs)
    recipe: list = []
    for arg, arg2, spec in zip(agg_args, agg_args2, specs):
        if any(
            v is not None and v.data2 is not None for v in (arg, arg2)
        ) and not (
            spec.fn in ("sum", "count", "min", "max") and not spec.distinct
        ):
            raise NotImplementedError(
                f"aggregate {spec.fn} over decimal128 lanes "
                f"(sum/count/min/max only)"
            )
        if (
            spec.distinct
            or spec.fn in ("percentile", "approx_distinct")
            or spec.fn in HOST_AGGS
        ):
            recipe.append(None)
            continue
        if spec.fn in MOMENT_AGGS:
            # pairwise moments (reference: CorrelationAggregation etc.):
            # sums of y, x, xy, xx, yy over rows where BOTH args are non-NULL
            y = arg.data if perm is None else jnp.take(arg.data, perm)
            x = arg2.data if perm is None else jnp.take(arg2.data, perm)
            yv = _valid_of(arg, n)
            xv = _valid_of(arg2, n)
            if perm is not None:
                yv = jnp.take(yv, perm)
                xv = jnp.take(xv, perm)
            pv = yv & xv & live_s
            y = y.astype(jnp.float64)
            x = x.astype(jnp.float64)
            recipe.append(
                (
                    "moment", spec.fn,
                    add(SegRed("sum", y, pv)),
                    add(SegRed("sum", x, pv)),
                    add(SegRed("sum", x * y, pv)),
                    add(SegRed("sum", x * x, pv)),
                    add(SegRed("sum", y * y, pv)),
                    add(SegRed("count", None, pv)),
                )
            )
            continue
        if spec.fn == "count_star":
            recipe.append(("count", add_count(live_s)))
            continue
        data = arg.data if perm is None else jnp.take(arg.data, perm)
        valid = _valid_of(arg, n)
        if perm is not None:
            valid = jnp.take(valid, perm)
        valid = valid & live_s
        res_t = spec.type
        wide_sum = (
            spec.fn == "sum"
            and res_t is not None
            and getattr(res_t, "is_decimal", False)
            and res_t.precision > 18
            and jnp.issubdtype(data.dtype, jnp.integer)
        )
        if spec.fn == "count":
            recipe.append(("count", add_count(valid)))
        elif spec.fn == "sum" and (arg.data2 is not None or wide_sum):
            # decimal128 sum: four 32-bit limb sums (each exact in int64 for
            # n < 2^31 rows) recombined into two-limb outputs (the segreduce
            # analogue of Int128Math.addWithOverflow accumulation).  Also
            # taken when the RESULT precision > 18 over a single-lane input:
            # the int64 inputs fit, but their sum can overflow int64.
            from ..data.dec128 import limbs32

            lo64 = data.astype(jnp.int64)
            if arg.data2 is not None:
                hi = arg.data2 if perm is None else jnp.take(arg.data2, perm)
            else:
                hi = lo64 >> 63  # sign-extend the single lane
            l0, l1, l2, l3 = limbs32(lo64, hi)
            recipe.append(
                ("sum128", add(SegRed("sum", l0, valid)),
                 add(SegRed("sum", l1, valid)), add(SegRed("sum", l2, valid)),
                 add(SegRed("sum", l3, valid)), add_count(valid))
            )
        elif arg.data2 is not None and spec.fn in ("min", "max"):
            # decimal128 min/max: lexicographic two-pass — the fused pass
            # reduces the SIGNED hi limb; a follow-up segmented pass picks
            # the best UNSIGNED lo limb among rows whose hi limb equals the
            # group winner (Int128 compare order = (hi, unsigned lo);
            # reference: spi/type/Int128Math.compare).  The lo limb is
            # XOR-biased so unsigned order matches int64 signed order.
            hi = arg.data2 if perm is None else jnp.take(arg.data2, perm)
            lo_b = jnp.bitwise_xor(
                data.astype(jnp.int64), jnp.int64(-(2 ** 63))
            )
            recipe.append(
                ("minmax128", spec.fn,
                 add(SegRed(spec.fn, hi.astype(jnp.int64), valid)),
                 add_count(valid), lo_b, valid, hi.astype(jnp.int64))
            )
        elif arg.data2 is not None:
            raise NotImplementedError(
                f"aggregate {spec.fn} over decimal128 lanes "
                f"(sum/count/min/max only)"
            )
        elif spec.fn in ("sum", "avg"):
            as_int = spec.fn == "sum" and jnp.issubdtype(data.dtype, jnp.integer)
            vals = data if as_int else data.astype(jnp.float64)
            recipe.append((spec.fn, add(SegRed("sum", vals, valid)), add_count(valid)))
        elif spec.fn in ("min", "max"):
            if arg.dict is not None:
                rank = jnp.take(jnp.asarray(arg.dict.sorted_rank()), arg.data)
                rdata = rank if perm is None else jnp.take(rank, perm)
                recipe.append(
                    ("dictmm", spec.fn, arg, add(SegRed(spec.fn, rdata, valid)), add_count(valid))
                )
            else:
                recipe.append(("minmax", add(SegRed(spec.fn, data, valid)), add_count(valid)))
        elif spec.fn in ("bool_and", "bool_or"):
            # AND == min over {0,1}, OR == max (reference: aggregation/
            # BooleanAndAggregation / BooleanOrAggregation)
            b = data.astype(jnp.int32)
            red = "min" if spec.fn == "bool_and" else "max"
            recipe.append(("bool", add(SegRed(red, b, valid)), add_count(valid)))
        elif spec.fn in ("stddev_samp", "stddev_pop", "var_samp", "var_pop"):
            x = data.astype(jnp.float64)
            recipe.append(
                (
                    "var", spec.fn,
                    add(SegRed("sum", x, valid)),
                    add(SegRed("sum", x * x, valid)),
                    add_count(valid),
                )
            )
        else:
            raise NotImplementedError(f"aggregate {spec.fn}")

    results = (
        fused_segment_reduce(
            seg, reds, G, sorted_segments=sorted_segments, boundaries=boundaries
        )
        if reds
        else []
    )

    out: list = []
    for r in recipe:
        if r is None:
            out.append(None)
            continue
        kind = r[0]
        if kind == "count":
            out.append((results[r[1]], None))
        elif kind == "sum128":
            from ..data.dec128 import recombine32

            s0, s1, s2, s3, cnt = (results[r[i]] for i in range(1, 6))
            lo, hi = recombine32(
                s0.astype(jnp.int64), s1.astype(jnp.int64),
                s2.astype(jnp.int64), s3.astype(jnp.int64),
            )
            out.append((lo, cnt > 0, None, hi))
        elif kind in ("sum", "avg"):
            s, cnt = results[r[1]], results[r[2]]
            nonempty = cnt > 0
            if kind == "sum":
                out.append((s, nonempty))
            else:
                out.append((s / jnp.where(nonempty, cnt, 1).astype(jnp.float64), nonempty))
        elif kind == "minmax":
            s, cnt = results[r[1]], results[r[2]]
            out.append((s, cnt > 0))
        elif kind == "minmax128":
            _, fn, hi_i, ci, lo_b, valid_m, hi_rows = r
            hi_g, cnt = results[hi_i], results[ci]
            # second pass: best biased lo limb restricted to the rows whose
            # hi limb equals their group's winning hi limb
            at_best = valid_m & (
                hi_rows == jnp.take(hi_g.astype(jnp.int64), seg)
            )
            lo_best = fused_segment_reduce(
                seg, [SegRed(fn, lo_b, at_best)], G,
                sorted_segments=sorted_segments, boundaries=boundaries,
            )[0]
            lo_g = jnp.bitwise_xor(
                lo_best.astype(jnp.int64), jnp.int64(-(2 ** 63))
            )
            out.append((lo_g, cnt > 0, None, hi_g.astype(jnp.int64)))
        elif kind == "bool":
            s, cnt = results[r[1]], results[r[2]]
            out.append((s > 0, cnt > 0))
        elif kind == "var":
            _, fn, si, qi, ci = r
            s, ss, cnt = results[si], results[qi], results[ci]
            cf = cnt.astype(jnp.float64)
            safe_n = jnp.where(cnt > 0, cf, 1.0)
            mean = s / safe_n
            # population variance; numerical floor at 0 (catastrophic
            # cancellation on near-constant data)
            var_pop = jnp.maximum(ss / safe_n - mean * mean, 0.0)
            if fn.endswith("_pop"):
                var = var_pop
                ok = cnt > 0
            else:
                var = var_pop * safe_n / jnp.where(cnt > 1, cf - 1.0, 1.0)
                ok = cnt > 1
            if fn.startswith("stddev"):
                var = jnp.sqrt(var)
            out.append((var, ok))
        elif kind == "moment":
            _, fn, iy, ix, ixy, ixx, iyy, ic = r
            sy, sx, sxy, sxx, syy, cnt = (
                results[iy], results[ix], results[ixy],
                results[ixx], results[iyy], results[ic],
            )
            nf = jnp.where(cnt > 0, cnt, 1).astype(jnp.float64)
            cov_n = sxy - sx * sy / nf  # n * cov
            varx_n = jnp.maximum(sxx - sx * sx / nf, 0.0)  # n * var(x)
            vary_n = jnp.maximum(syy - sy * sy / nf, 0.0)
            if fn == "covar_pop":
                out.append((cov_n / nf, cnt > 0))
            elif fn == "covar_samp":
                denom = jnp.where(cnt > 1, nf - 1.0, 1.0)
                out.append((cov_n / denom, cnt > 1))
            elif fn == "corr":
                denom = jnp.sqrt(varx_n * vary_n)
                ok = (cnt > 1) & (denom > 0)
                out.append((cov_n / jnp.where(ok, denom, 1.0), ok))
            elif fn == "regr_slope":
                ok = (cnt > 1) & (varx_n > 0)
                out.append((cov_n / jnp.where(ok, varx_n, 1.0), ok))
            else:  # regr_intercept = mean(y) - slope * mean(x)
                ok = (cnt > 1) & (varx_n > 0)
                slope = cov_n / jnp.where(ok, varx_n, 1.0)
                out.append(((sy - slope * sx) / nf, ok))
        else:  # dictmm: map best rank back to a dictionary code
            _, fn, arg, si, ci = r
            best_rank, cnt = results[si], results[ci]
            inv = np.argsort(arg.dict.sorted_rank()).astype(np.int32)
            code = jnp.take(
                jnp.asarray(inv),
                jnp.clip(best_rank.astype(jnp.int32), 0, len(inv) - 1),
            )
            out.append((code, cnt > 0))
    return out


_HLL_P = 12  # m = 4096 buckets: ~1.04/sqrt(m) = 1.6% standard error


def _hll_alpha(m: int) -> float:
    if m >= 128:
        return 0.7213 / (1.0 + 1.079 / m)
    return {16: 0.673, 32: 0.697, 64: 0.709}.get(m, 0.7)


def _hash64(data: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer over the value bits — uniform 64-bit hash lanes.
    Floats hash their FULL f64 bit pattern (a f32 downcast would collide
    every double within ~1e-7 relative, blowing the HLL error bound).  The
    reference HLL also hashes 64-bit (Murmur3Hash128 in airlift stats); a
    32-bit hash saturates its value space and biases approx_distinct low by
    ~1% at 1e8 distinct, ~10% at 1e9 (ADVICE r3)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        data = jax.lax.bitcast_convert_type(data.astype(jnp.float64), jnp.int64)
    mixed = _mix64(data.astype(jnp.int64))  # uint64 lanes
    # drop the sign bit and return int64: downstream packing (seg * m +
    # bucket) runs in int64, and jax promotes int64 x uint64 to f64 (!)
    return (mixed & jnp.uint64(0x7FFF_FFFF_FFFF_FFFF)).astype(jnp.int64)


def _bitlen64(v: jnp.ndarray) -> jnp.ndarray:
    """Bit length of non-negative int64 lanes via 6 halving steps — exact for
    the full 63-bit range (a float log2 is only exact to the mantissa)."""
    v = v.astype(jnp.int64)
    bl = jnp.zeros(v.shape, jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        big = (v >> s) > 0
        bl = bl + jnp.where(big, jnp.int32(s), jnp.int32(0))
        v = jnp.where(big, v >> s, v)
    return bl + (v > 0).astype(jnp.int32)


def _segment_hll(
    arg: ColumnVal,
    perm: jnp.ndarray,
    seg: jnp.ndarray,
    live_s: jnp.ndarray,
    G: int,
    n: int,
):
    """Grouped HyperLogLog: approx_distinct with CONSTANT sketch state per
    group (reference: ApproximateCountDistinctAggregations over
    HyperLogLogType).  TPU shape: one extra sort by (group, bucket, rho)
    puts every (group, bucket)'s MAX rho at its run end; per-group sums of
    2^-rho then ride the same boundary-cumsum machinery as every other
    sorted reduction — no G x m dense state ever materializes (empty
    buckets enter the estimator arithmetically via m - nonempty)."""
    m = 1 << _HLL_P
    rest_bits = 63 - _HLL_P  # use the hash's low 63 bits (int64 sign-safe)
    data_s = jnp.take(arg.data, perm)
    valid_s = jnp.take(_valid_of(arg, n), perm) & live_s
    h = _hash64(data_s)  # int64, sign bit clear
    bucket = (h >> rest_bits).astype(jnp.int32)
    rest = h & jnp.int64((1 << rest_bits) - 1)
    # rho = leading-zero count within the rest_bits window + 1
    rho = (rest_bits + 1 - _bitlen64(rest)).astype(jnp.int32)  # [1, 52]
    combined = seg.astype(jnp.int64) * m + bucket
    dead_val = jnp.int64(G) * m
    combined = jnp.where(valid_s, combined, dead_val)
    c_s, rho_s = jax.lax.sort([combined, rho], num_keys=2)
    # run ends carry the bucket's max rho (rho ascends within a run)
    is_end = jnp.concatenate(
        [c_s[1:] != c_s[:-1], jnp.ones((1,), jnp.bool_)]
    )
    live_end = is_end & (c_s < dead_val)
    # keep gseg NONDECREASING (c_s is sorted): non-end rows stay in their
    # group's run with zero contribution — masking them to G would break the
    # boundary searchsorted's sortedness precondition
    gseg = jnp.minimum((c_s // m).astype(jnp.int32), G)
    contrib_z = jnp.where(live_end, 2.0 ** (-rho_s.astype(jnp.float64)), 0.0)
    contrib_e = live_end.astype(jnp.int64)
    # boundary-cumsum reductions apply over the sorted gseg
    from .pallas.segreduce import SegRed, _sorted_fallback

    z_part, e_cnt = _sorted_fallback(
        gseg,
        [SegRed("sum", contrib_z, None), SegRed("sum", contrib_e, None)],
        G,
    )
    e_cnt = e_cnt.astype(jnp.float64)
    z = (m - e_cnt) + z_part  # empty buckets contribute 2^0 each
    estimate = _hll_alpha(m) * m * m / jnp.maximum(z, 1e-12)
    # small-range (linear counting) correction
    v_empty = m - e_cnt
    small = m * jnp.log(m / jnp.maximum(v_empty, 1.0))
    estimate = jnp.where(
        (estimate < 2.5 * m) & (v_empty > 0), small, estimate
    )
    counts = jnp.round(estimate).astype(jnp.int64)
    counts = jnp.where(e_cnt > 0, counts, 0)
    return counts, None


def _host_collect_agg(
    spec: AggSpec,
    arg: ColumnVal,
    arg2: Optional[ColumnVal],
    perm: jnp.ndarray,
    seg: jnp.ndarray,
    live_s: jnp.ndarray,
    G: int,
    n: int,
    order: tuple = (),
):
    """array_agg / map_agg / listagg: per-group collection on the HOST over
    the sorted grouping (reference: aggregation/ArrayAggregationFunction,
    MapAggAggregationFunction, ListaggAggregationFunction).  Their outputs
    are interned structured values (dict-coded tuples) that a traced kernel
    cannot build, so the executor routes plans containing them through eager
    execution; under jit this raises at trace time."""
    import jax.core as _core

    if isinstance(seg, _core.Tracer):
        raise NotImplementedError(
            f"{spec.fn} requires eager execution (host-collected aggregate)"
        )
    from ..data.page import Dictionary

    perm_h = np.asarray(perm)
    seg_h = np.asarray(seg)
    live_h = np.asarray(live_s)

    def decode(cv: ColumnVal):
        d = np.asarray(cv.data)[perm_h]
        ok = np.asarray(_valid_of(cv, n))[perm_h] & live_h
        if cv.dict is not None:
            table = np.asarray(cv.dict.values, dtype=object)
            d = table[np.clip(d, 0, max(len(table) - 1, 0))]
        return d, ok

    vals, vok = decode(arg)
    keep = live_h & (seg_h < G)
    gs = seg_h[keep]
    v_k, ok_k = vals[keep], vok[keep]
    bounds = np.flatnonzero(np.diff(gs)) + 1
    group_ids = gs[np.concatenate([[0], bounds])] if len(gs) else np.zeros(0, np.int64)
    runs = np.split(np.arange(len(gs)), bounds)

    if order:
        # ordered collection: sort each group's run by the agg's ORDER BY
        # keys (reference: ordering-sensitive aggregation inputs,
        # OrderingCompiler over PagesIndex)
        from .matchrec import host_sort_rank

        lex: list[np.ndarray] = []
        for cv, asc, nulls_first in reversed(order):
            d, ok = decode(cv)
            null_rank, rank = host_sort_rank(
                d[keep], ok[keep], None, asc, nulls_first
            )
            lex.append(rank)
            lex.append(null_rank)
        runs = [r[np.lexsort([k[r] for k in lex])] if len(r) > 1 else r
                for r in runs]

    def _dedup_first(seq):
        seen: set = set()
        out = []
        for v in seq:
            if v not in seen:
                seen.add(v)
                out.append(v)
        return out

    results: list = []
    res_ok: list[bool] = []
    if spec.fn == "listagg":
        sep = spec.sep if spec.sep is not None else ","
        for r in runs:
            parts = [str(v_k[i]) for i in r if ok_k[i]]
            if spec.distinct:
                parts = _dedup_first(parts)
            results.append(sep.join(parts))
            res_ok.append(bool(parts))
    elif spec.fn == "array_agg":
        for r in runs:
            vals_r = [
                v_k[i].item() if isinstance(v_k[i], np.generic) else v_k[i]
                for i in r if ok_k[i]
            ]
            if spec.distinct:
                vals_r = _dedup_first(vals_r)
            results.append(tuple(vals_r))
            res_ok.append(True)
    else:  # map_agg(key, value): NULL keys skipped, last value wins
        kv, kok = decode(arg2)
        kv_k, kok_k = kv[keep], kok[keep]
        for r in runs:
            m: dict = {}
            for i in r:
                if ok_k[i]:
                    key = v_k[i].item() if isinstance(v_k[i], np.generic) else v_k[i]
                    val = (
                        (kv_k[i].item() if isinstance(kv_k[i], np.generic) else kv_k[i])
                        if kok_k[i]
                        else None
                    )
                    m[key] = val
            try:  # canonical map form: pairs sorted by key (data/types.py)
                items = sorted(m.items())
            except TypeError:
                items = sorted(m.items(), key=lambda it: repr(it[0]))
            results.append(tuple(items))
            res_ok.append(bool(m))

    # intern without sorting (tuples may mix None with values; np.unique
    # would compare them) and scatter into the [G] output frame
    table: dict = {}
    codes = np.zeros((G,), np.int32)
    valid = np.zeros((G,), bool)
    for gid, res, ok in zip(group_ids, results, res_ok):
        codes[gid] = table.setdefault(res, len(table))
        valid[gid] = ok
    uniq = np.empty(max(len(table), 1), dtype=object)
    uniq[0] = "" if spec.fn == "listagg" else ()
    for val, code in table.items():
        uniq[code] = val
    return jnp.asarray(codes), jnp.asarray(valid), Dictionary(uniq)


def _segment_agg(
    arg: Optional[ColumnVal],
    spec: AggSpec,
    perm: jnp.ndarray,
    seg: jnp.ndarray,
    live_s: jnp.ndarray,
    new_group: jnp.ndarray,
    G: int,
    n: int,
):
    """DISTINCT aggregates only — everything else is fused (_fused_aggs).

    Requires the sort-based grouping: rows arrive ordered by (group keys,
    distinct argument), so the first occurrence of each value within its
    group is an adjacency test.
    """
    num = G + 1  # +1 overflow bucket for dead lanes
    assert spec.distinct, "non-DISTINCT aggregates run through _fused_aggs"
    data_s = jnp.take(arg.data, perm)
    valid_s = jnp.take(_valid_of(arg, n), perm) & live_s
    prev = jnp.concatenate([data_s[:1], data_s[:-1]])
    new_val = new_group | (data_s != prev)
    contrib = (new_val & valid_s).astype(jnp.int64)
    if spec.fn != "count":
        raise NotImplementedError(f"DISTINCT {spec.fn}")
    out = _segment_sum(contrib, seg, num, sorted_segments=True)[:G]
    return out, None


def _segment_percentile(
    arg: ColumnVal,
    p: float,
    perm: jnp.ndarray,
    seg: jnp.ndarray,
    live_s: jnp.ndarray,
    G: int,
    n: int,
):
    """approx_percentile via exact nearest-rank selection on the grouped sort
    (the sort operands append (validity, value) for this arg, so each group's
    valid values are contiguous ascending runs).  The reference uses T-digest
    sketches (aggregation/TDigestAndPercentileAggregation); an exact answer
    over the sorted page is within any approximation contract and is the
    natural fit for the sort-based group-by."""
    data_s = jnp.take(arg.data, perm)
    valid_s = jnp.take(_valid_of(arg, n), perm) & live_s
    vcnt = _segment_sum(valid_s.astype(jnp.int64), seg, G + 1, sorted_segments=True)[:G]
    # group start among sorted rows (seg ascends over live rows, dead == G)
    starts = searchsorted_tpu(seg, jnp.arange(G, dtype=seg.dtype), side="left")
    off = jnp.floor(p * jnp.maximum(vcnt - 1, 0).astype(jnp.float64) + 0.5)
    idx = jnp.clip(starts + off.astype(jnp.int64), 0, max(n - 1, 0))
    vals = jnp.take(data_s, idx)
    return vals, vcnt > 0


def _global_aggregate(agg_args, specs, live, agg_args2=None, agg_order=None):
    """No GROUP BY: one output row even over empty input (SQL semantics).

    Non-DISTINCT aggregates run through the fused segmented reduction with a
    single segment — on TPU that means the Pallas kernel's exact-int64 and
    Kahan-compensated float paths serve global sums too (a plain jnp.sum of
    "float64" on TPU silently accumulates in f32)."""
    n = live.shape[0]
    if agg_args2 is None:
        agg_args2 = [None] * len(specs)
    if agg_order is None:
        agg_order = [()] * len(specs)
    seg = jnp.zeros((n,), jnp.int32)
    fused = _fused_aggs(agg_args, specs, None, seg, live, 1, n, agg_args2=agg_args2)
    out_aggs = []
    for i, ((arg, spec), pre) in enumerate(zip(zip(agg_args, specs), fused)):
        if pre is not None:
            out_aggs.append(pre)
            continue
        if spec.fn in HOST_AGGS:
            perm1 = jnp.arange(n, dtype=jnp.int32)
            out_aggs.append(
                _host_collect_agg(
                    spec, arg, agg_args2[i], perm1, seg, live, 1, n,
                    order=agg_order[i],
                )
            )
            continue
        valid = _valid_of(arg, n) & live
        if spec.fn == "approx_distinct":
            seg1 = jnp.zeros((n,), jnp.int32)
            perm1 = jnp.arange(n, dtype=jnp.int32)
            cnts, _ = _segment_hll(arg, perm1, seg1, live, 1, n)
            out_aggs.append((cnts, None))
            continue
        if spec.distinct:
            k = _sortable_key(arg)
            inv_s, k_s = jax.lax.sort([(~valid).astype(jnp.int8), k], num_keys=2)
            vs = ~(inv_s.astype(jnp.bool_))
            prev = jnp.concatenate([k_s[:1], k_s[:-1]])
            first = jnp.zeros((n,), jnp.bool_).at[0].set(True)
            cnt = jnp.sum(((first | (k_s != prev)) & vs).astype(jnp.int64))
            out_aggs.append((cnt.reshape(1), None))
            continue
        if spec.fn == "percentile":
            inv_s, d_s = jax.lax.sort(
                [(~valid).astype(jnp.int8), arg.data], num_keys=2
            )
            vcnt = jnp.sum(valid.astype(jnp.int64))
            off = jnp.floor(
                spec.param * jnp.maximum(vcnt - 1, 0).astype(jnp.float64) + 0.5
            )
            idx = jnp.clip(off.astype(jnp.int64), 0, max(n - 1, 0))
            out_aggs.append((jnp.take(d_s, idx).reshape(1), (vcnt > 0).reshape(1)))
            continue
        raise NotImplementedError(spec.fn)  # non-distinct is fully fused above
    out_live = jnp.ones((1,), jnp.bool_)
    return [], out_aggs, out_live, jnp.int32(1)


# ------------------------------------------------------------------- joins


_MIX_CONST = np.uint64(0x9E3779B97F4A7C15)


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer — vectorized avalanche mix."""
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(_MIX_CONST)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return x


def _combined_hash(keys: Sequence[ColumnVal], live: jnp.ndarray, n: int, sentinel: int):
    """Hash-combine key columns to int63; rows that are dead or have a null
    key get `sentinel` (never matches).  Exact key equality is re-verified
    after candidate expansion, so collisions only cost, never corrupt.

    VARCHAR columns hash by dictionary VALUE via Dictionary.hash64() (the
    one value-hash table, shared with runtime/wire.py partition_page) — so
    hash-partitioning two different varchar columns routes equal strings to
    the same shard even though their code spaces differ.  This is what lets
    string-keyed joins run PARTITIONED instead of forcing broadcast."""
    h = jnp.zeros((n,), dtype=jnp.uint64)
    ok = live
    for kv in keys:
        if kv.dict is not None:
            table = kv.dict.hash64()
            bits = jnp.take(
                jnp.asarray(table), jnp.clip(kv.data, 0, len(table) - 1)
            )
        else:
            bits = kv.data
            if jnp.issubdtype(bits.dtype, jnp.floating):
                bits = jax.lax.bitcast_convert_type(bits.astype(jnp.float64), jnp.uint64)
            else:
                bits = bits.astype(jnp.int64).astype(jnp.uint64)
            if kv.data2 is not None:
                # Values that fit in int64 carry a sign-extension high limb;
                # mix hi only when it adds information so limbed and
                # non-limbed representations of the same value hash alike.
                lo = kv.data.astype(jnp.int64)
                hi = kv.data2.astype(jnp.int64)
                extra = jnp.where(
                    hi == (lo >> 63), jnp.uint64(0), _mix64(hi.astype(jnp.uint64))
                )
                bits = bits ^ extra
        h = _mix64(h ^ _mix64(bits))
        ok = ok & _valid_of(kv, n)
    h = (h & jnp.uint64(0x3FFF_FFFF_FFFF_FFFF)).astype(jnp.int64)
    return jnp.where(ok, h, jnp.int64(sentinel))


_SENT_BUILD = (1 << 62) + 2  # sorts after every real hash
_SENT_PROBE = (1 << 62) + 1  # != build sentinel -> dead probes match nothing


def _in_null_facts(left_keys, right_keys, left_live, right_live, nl, nr):
    """The three facts SQL IN's three-valued logic turns on: does the build
    side have any live row, does it hold a NULL key, is the probe key
    non-NULL.  Shared by null_anti (NOT IN filter) and mark_in (IN column)."""
    build_any = jnp.any(right_live)
    build_has_null = jnp.zeros((), jnp.bool_)
    probe_ok = jnp.ones((nl,), jnp.bool_)
    for rk in right_keys:
        build_has_null = build_has_null | jnp.any(right_live & ~_valid_of(rk, nr))
    for lk in left_keys:
        probe_ok = probe_ok & _valid_of(lk, nl)
    return build_any, build_has_null, probe_ok


def _hash_join_gids(left_keys, right_keys, left_live, right_live, nl, nr):
    """Pallas hash-join front end: build a VMEM hash table over the (small)
    build side, probe the left side streamingly, and convert each probe
    row's dense build-group id into the (lo, hi) row-range-over-perm_b form
    the sort path's expansion tail consumes — so inner/semi/anti/left/mark
    all share the verified-match machinery below unchanged.

    Returns None when the static gate picks the sort path, else
    (ok, lo, hi, perm_b): `ok` is the runtime guard (table overflow or
    probe-budget exhaustion flips the join back to the sort path via
    lax.cond — deterministic overflow-to-sort).  Only the build side is
    ever sorted (<= hash_join_max_build rows); the probe side is one
    streaming kernel pass plus gathers."""
    from .kernels import get_policy, record_dispatch

    policy = get_policy()
    if not policy.enabled:
        record_dispatch("join", "sort", "kernels disabled")
        return None
    from .pallas import hashagg, hashjoin

    interpret = policy.interpret or hashagg.INTERPRET
    if not interpret and jax.default_backend() not in ("tpu", "axon"):
        # decline before encoding: the key-word encode below is real work
        # on the eager/interpreted-fallback execution path
        record_dispatch("join", "sort", "cpu backend")
        return None
    wl, llay = _hash_key_words(left_keys, nl, for_join=True)
    wr, rlay = _hash_key_words(right_keys, nr, for_join=True)
    if wl is None or wr is None or llay != rlay or len(wl) != len(wr):
        record_dispatch("join", "sort", "keys not word-encodable")
        return None
    if nr > policy.hash_join_max_build:
        record_dispatch("join", "fallback", f"build {nr} > hash_join_limit")
        return None
    if not hashagg.shape_supported(max(nl, nr, 1), len(wr), nr):
        record_dispatch("join", "sort", "shape unsupported")
        return None
    record_dispatch(
        "join", "pallas", f"build {nr} table {hashagg.table_size(nr)}"
    )

    blive = right_live
    for rk in right_keys:
        blive = blive & _valid_of(rk, nr)  # NULL keys never match
    plive = left_live
    for lk in left_keys:
        plive = plive & _valid_of(lk, nl)

    gid_b, table, _n_true, ovb = hashagg.build_hash_table(
        wr, blive, nr, interpret=interpret
    )
    gid_p, unres = hashjoin.probe_hash_table(
        wl, plive, table, interpret=interpret
    )
    ok = ~ovb & ~unres

    # build rows sorted by group id (dead/null rows last) give contiguous
    # per-group ranges; group starts come from a tiny searchsorted over the
    # build side only
    segb = jnp.where(
        blive & (gid_b >= 0), jnp.minimum(gid_b, nr - 1), nr
    ).astype(jnp.int32)
    iota_r = jnp.arange(nr, dtype=jnp.int32)
    segb_sorted, perm_b = jax.lax.sort([segb, iota_r], num_keys=1)
    gids = jnp.arange(nr, dtype=jnp.int32)
    gstart = jnp.searchsorted(segb_sorted, gids, side="left")
    gend = jnp.searchsorted(segb_sorted, gids, side="right")
    matched = gid_p >= 0
    gidx = jnp.clip(gid_p, 0, nr - 1)
    lo = jnp.where(matched, jnp.take(gstart, gidx), 0).astype(jnp.int64)
    cnt = jnp.where(matched, jnp.take(gend, gidx) - jnp.take(gstart, gidx), 0)
    hi = lo + cnt.astype(jnp.int64)
    return ok, lo, hi, perm_b


def equi_join(
    kind: str,
    left_cols: Sequence[ColumnVal],
    left_live: jnp.ndarray,
    right_cols: Sequence[ColumnVal],
    right_live: jnp.ndarray,
    left_keys: Sequence[ColumnVal],
    right_keys: Sequence[ColumnVal],
    residual: Optional[Callable[[list[ColumnVal], int], jnp.ndarray]],
    out_capacity: int,
):
    """Sort + searchsorted equi-join.  kind: inner | left | semi | anti |
    null_anti.

    inner/left -> (out_cols, out_live, required) with capacity
      out_capacity (+ n_left extra lanes for left-join unmatched rows).
    semi/anti  -> (left_cols, new_live, required): filters the left page.
    null_anti is the NOT IN lowering (reference: SemiJoinNode + the
      null-aware rewrite in TransformCorrelatedInPredicateToJoin): with a
      non-empty build side, probe rows whose key is NULL — or any probe row
      when the build side contains a NULL key — evaluate NOT IN to NULL and
      are filtered; an empty build side keeps every probe row.
    mark / mark_in -> (left_cols + [match BOOLEAN column], left_live,
      required): the membership test becomes a COLUMN instead of a filter —
      the lowering for EXISTS / IN in general expression positions (OR'd
      predicates, select items; reference: SemiJoinNode's
      semiJoinOutput symbol).  mark is two-valued (EXISTS); mark_in is
      SQL three-valued: NULL when the probe key is NULL or the build side
      holds a NULL key and there is no match (an empty build is FALSE).
    `required` is the true expansion size for the host's retry loop.
    """
    nl = left_live.shape[0]
    nr = right_live.shape[0]
    C = out_capacity

    def _sort_lohi():
        bh = _combined_hash(right_keys, right_live, nr, _SENT_BUILD)
        ph = _combined_hash(left_keys, left_live, nl, _SENT_PROBE)
        iota_r = jnp.arange(nr, dtype=jnp.int32)
        bh_sorted, pb = jax.lax.sort([bh, iota_r], num_keys=1)
        l = searchsorted_tpu(bh_sorted, ph, side="left").astype(jnp.int64)
        h = searchsorted_tpu(bh_sorted, ph, side="right").astype(jnp.int64)
        return l, h, pb

    hashed = _hash_join_gids(
        left_keys, right_keys, left_live, right_live, nl, nr
    )
    if hashed is not None:
        h_ok, h_lo, h_hi, h_perm = hashed
        lo, hi, perm_b = jax.lax.cond(
            h_ok, lambda: (h_lo, h_hi, h_perm), _sort_lohi
        )
    else:
        lo, hi, perm_b = _sort_lohi()
    counts = (hi - lo).astype(jnp.int64)
    cum = jnp.cumsum(counts)
    total = cum[-1]

    j = jnp.arange(C, dtype=jnp.int64)
    pidx = searchsorted_tpu(cum, j, side="right").astype(jnp.int32)
    pidx_c = jnp.minimum(pidx, nl - 1)
    start = jnp.take(cum, pidx_c) - jnp.take(counts, pidx_c)
    k = j - start
    bpos = jnp.take(lo, pidx_c).astype(jnp.int64) + k
    bpos_c = jnp.clip(bpos, 0, nr - 1).astype(jnp.int32)
    bidx = jnp.take(perm_b, bpos_c)
    in_range = j < total

    # exact key verification (hash collisions + sentinel lanes); decimal128
    # keys verify BOTH limbs — the combined hash folds only the lo limb, so
    # hi-limb collisions must be filtered here (a single-lane side
    # sign-extends into limb space, reference: spi/type/Int128Math.java)
    eq = in_range
    for lk, rk in zip(left_keys, right_keys):
        lv = jnp.take(lk.data, pidx_c)
        rv = jnp.take(rk.data, bidx)
        lval = jnp.take(_valid_of(lk, nl), pidx_c)
        rval = jnp.take(_valid_of(rk, nr), bidx)
        eq = eq & (lv == rv) & lval & rval
        if lk.data2 is not None or rk.data2 is not None:
            lhi = (
                jnp.take(lk.data2, pidx_c)
                if lk.data2 is not None
                else lv.astype(jnp.int64) >> 63
            )
            rhi = (
                jnp.take(rk.data2, bidx)
                if rk.data2 is not None
                else rv.astype(jnp.int64) >> 63
            )
            eq = eq & (lhi == rhi)

    # gather both sides into the expansion frame (decimal128 columns carry
    # their high limb through the gather)
    gathered: list[ColumnVal] = []
    for cv in left_cols:
        gathered.append(
            ColumnVal(
                jnp.take(cv.data, pidx_c),
                None if cv.valid is None else jnp.take(cv.valid, pidx_c),
                cv.dict,
                cv.type,
                None if cv.data2 is None else jnp.take(cv.data2, pidx_c),
            )
        )
    for cv in right_cols:
        gathered.append(
            ColumnVal(
                jnp.take(cv.data, bidx),
                None if cv.valid is None else jnp.take(cv.valid, bidx),
                cv.dict,
                cv.type,
                None if cv.data2 is None else jnp.take(cv.data2, bidx),
            )
        )
    match = eq
    if residual is not None:
        match = match & residual(gathered, C)

    required = total

    if kind in ("mark", "mark_in"):
        from ..data.types import BOOLEAN

        hit = jnp.zeros((nl,), jnp.bool_).at[pidx_c].max(match, mode="drop")
        if kind == "mark":
            mark = ColumnVal(hit, None, None, BOOLEAN)
        else:
            build_any, build_has_null, probe_ok = _in_null_facts(
                left_keys, right_keys, left_live, right_live, nl, nr
            )
            # TRUE on match; else FALSE when definitively absent (non-null
            # probe, no build NULLs, or empty build); else NULL (unknown)
            definite = hit | ~build_any | (probe_ok & ~build_has_null)
            mark = ColumnVal(hit, definite, None, BOOLEAN)
        return list(left_cols) + [mark], left_live, required

    if kind in ("semi", "anti", "null_anti"):
        hit = jnp.zeros((nl,), jnp.bool_).at[pidx_c].max(match, mode="drop")
        if kind == "semi":
            new_live = left_live & hit
        elif kind == "anti":
            new_live = left_live & ~hit
        else:  # null_anti: SQL three-valued NOT IN
            build_any, build_has_null, probe_ok = _in_null_facts(
                left_keys, right_keys, left_live, right_live, nl, nr
            )
            keep = jnp.where(
                build_any, ~hit & probe_ok & ~build_has_null, True
            )
            new_live = left_live & keep
        return list(left_cols), new_live, required

    if kind == "inner":
        return gathered, match, required

    if kind in ("left", "full"):
        # expansion lanes ++ unmatched left lanes with null right columns
        # (full: ++ unmatched RIGHT lanes with null left columns too)
        hit = jnp.zeros((nl,), jnp.bool_).at[pidx_c].max(match, mode="drop")
        unmatched = left_live & ~hit
        full = kind == "full"
        if full:
            bhit = jnp.zeros((nr,), jnp.bool_).at[bidx].max(match, mode="drop")
            unmatched_r = right_live & ~bhit
        out: list[ColumnVal] = []
        for i, cv in enumerate(left_cols):
            data = jnp.concatenate([gathered[i].data, cv.data])
            data2 = (
                None
                if cv.data2 is None
                else jnp.concatenate([gathered[i].data2, cv.data2])
            )
            valid = (
                None
                if cv.valid is None and not full
                else jnp.concatenate(
                    [
                        gathered[i].valid
                        if gathered[i].valid is not None
                        else jnp.ones((C,), jnp.bool_),
                        cv.valid if cv.valid is not None else jnp.ones((nl,), jnp.bool_),
                    ]
                )
            )
            if full:
                data = jnp.concatenate([data, jnp.zeros((nr,), cv.data.dtype)])
                valid = jnp.concatenate([valid, jnp.zeros((nr,), jnp.bool_)])
                if data2 is not None:
                    data2 = jnp.concatenate([data2, jnp.zeros((nr,), data2.dtype)])
            out.append(ColumnVal(data, valid, cv.dict, cv.type, data2))
        off = len(left_cols)
        for i, cv in enumerate(right_cols):
            g = gathered[off + i]
            gv = g.valid if g.valid is not None else jnp.ones((C,), jnp.bool_)
            data = jnp.concatenate([g.data, jnp.zeros((nl,), cv.data.dtype)])
            valid = jnp.concatenate([gv, jnp.zeros((nl,), jnp.bool_)])
            data2 = (
                None
                if cv.data2 is None
                else jnp.concatenate([g.data2, jnp.zeros((nl,), cv.data2.dtype)])
            )
            if full:
                data = jnp.concatenate([data, cv.data])
                valid = jnp.concatenate(
                    [
                        valid,
                        cv.valid if cv.valid is not None else jnp.ones((nr,), jnp.bool_),
                    ]
                )
                if data2 is not None:
                    data2 = jnp.concatenate([data2, cv.data2])
            out.append(ColumnVal(data, valid, cv.dict, cv.type, data2))
        out_live = jnp.concatenate([match, unmatched])
        if full:
            out_live = jnp.concatenate([out_live, unmatched_r])
        return out, out_live, required

    raise NotImplementedError(f"join kind {kind}")


def broadcast_single_row(
    left_cols: Sequence[ColumnVal],
    left_live: jnp.ndarray,
    right_cols: Sequence[ColumnVal],
    right_live: jnp.ndarray,
):
    """Cross join against a single-row relation (scalar-subquery shape):
    broadcast the one live right row across the left page."""
    nl = left_live.shape[0]
    ridx = jnp.argmax(right_live)  # the single live row
    any_right = jnp.any(right_live)
    out = list(left_cols)
    for cv in right_cols:
        val = cv.data[ridx]
        data = jnp.full((nl,), val, dtype=cv.data.dtype)
        if cv.valid is None:
            valid = jnp.broadcast_to(any_right, (nl,))
        else:
            valid = jnp.broadcast_to(cv.valid[ridx] & any_right, (nl,))
        data2 = (
            None
            if cv.data2 is None
            else jnp.full((nl,), cv.data2[ridx], dtype=cv.data2.dtype)
        )
        out.append(ColumnVal(data, valid, cv.dict, cv.type, data2))
    return out, left_live


# ------------------------------------------------------------- sort / topn


def compact_rows(cols, live, cap: int):
    """Gather live rows into `cap` lanes (dead lanes drop).  Sort-based:
    one 2-operand bitonic pass moves live rows to the front in original
    order (stable), then every column gathers the first `cap` positions —
    no scatter (TPU scatters serialize).  Returns (cols, live, required)
    with required = true live count for the capacity-retry protocol."""
    n = live.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    perm = jax.lax.sort([(~live).astype(jnp.int8), iota], num_keys=2,
                        is_stable=True)[-1]
    take = perm[:cap]
    required = jnp.sum(live.astype(jnp.int64))
    out = [
        ColumnVal(
            jnp.take(cv.data, take),
            None if cv.valid is None else jnp.take(cv.valid, take),
            cv.dict,
            cv.type,
            None if cv.data2 is None else jnp.take(cv.data2, take),
        )
        for cv in cols
    ]
    out_live = jnp.arange(cap, dtype=jnp.int64) < jnp.minimum(required, cap)
    return out, out_live, required


def sort_rows(
    cols: Sequence[ColumnVal],
    live: jnp.ndarray,
    keys: Sequence[ColumnVal],
    specs: Sequence[SortSpec],
):
    """Stable multi-key sort; dead rows sink to the end."""
    n = live.shape[0]
    operands: list[jnp.ndarray] = [(~live).astype(jnp.int8)]
    for kv, spec in zip(keys, specs):
        valid = _valid_of(kv, n)
        # smaller flag sorts first: nulls-first -> nulls get 0, else nulls get 1
        null_flag = valid if spec.nulls_first else ~valid
        operands.append(null_flag.astype(jnp.int8))
        operands.extend(_sortable_operands(kv, descending=not spec.ascending))
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [iota], num_keys=len(operands), is_stable=True)
    perm = sorted_ops[-1]
    out = [
        ColumnVal(
            jnp.take(cv.data, perm),
            None if cv.valid is None else jnp.take(cv.valid, perm),
            cv.dict,
            cv.type,
            None if cv.data2 is None else jnp.take(cv.data2, perm),
        )
        for cv in cols
    ]
    return out, jnp.take(live, perm)


def top_n(cols, live, keys, specs, count: int, cap: Optional[int] = None):
    """TopN.  Returns (cols, live, required).

    Radix-select path (TPU, large inputs): find the exact K-th threshold of
    the leading key in four histogram passes (ops/pallas/topk.py), compact
    the <= `cap` candidate rows, and sort only those — no O(n log n) sort,
    no full-width permutation of the relation (the reference's bounded-heap
    TopNOperator.java:32 economy, achieved with branch-free vector passes).
    `required` is the candidate count for the executor's capacity retry;
    the sort fallback reports 0 (never retries).
    """
    n = live.shape[0]
    from .pallas.topk import radix_topk_supported, radix_topk_threshold, sortable_u32

    if (
        cap is not None and cap >= count and keys
        and keys[0].data2 is None  # radix threshold is 32-bit single-lane
        and radix_topk_supported(n, count)
    ):
        kv, spec = keys[0], specs[0]
        valid = _valid_of(kv, n)
        u = sortable_u32(_sortable_key(kv), descending=False)
        if spec.ascending:  # first rows of the order == smallest keys
            u = ~u
        null_u = jnp.uint32(0xFFFFFFFF) if spec.nulls_first else jnp.uint32(0)
        u = jnp.where(valid, u, null_u)
        thresh = radix_topk_threshold(u, live, count)
        cand = live & (u >= thresh)
        required = jnp.sum(cand.astype(jnp.int64))
        # compact candidate row ids into the static buffer
        pos = jnp.cumsum(cand.astype(jnp.int32)) - 1
        scatter_to = jnp.where(cand, pos, cap)
        idx_buf = (
            jnp.zeros((cap,), jnp.int32)
            .at[scatter_to]
            .set(jnp.arange(n, dtype=jnp.int32), mode="drop")
        )
        lane_live = jnp.arange(cap, dtype=jnp.int64) < jnp.minimum(
            required, cap
        )

        def gather(cv: ColumnVal) -> ColumnVal:
            return ColumnVal(
                jnp.take(cv.data, idx_buf),
                None if cv.valid is None else jnp.take(cv.valid, idx_buf),
                cv.dict,
                cv.type,
                None if cv.data2 is None else jnp.take(cv.data2, idx_buf),
            )

        sub_cols = [gather(cv) for cv in cols]
        sub_keys = [gather(kv_) for kv_ in keys]
        sorted_cols, sorted_live = sort_rows(sub_cols, lane_live, sub_keys, specs)
        k = min(count, n)
        out = [
            ColumnVal(
                cv.data[:k],
                None if cv.valid is None else cv.valid[:k],
                cv.dict,
                cv.type,
                None if cv.data2 is None else cv.data2[:k],
            )
            for cv in sorted_cols
        ]
        return out, sorted_live[:k], required

    sorted_cols, sorted_live = sort_rows(cols, live, keys, specs)
    k = min(count, n)
    out = [
        ColumnVal(
            cv.data[:k],
            None if cv.valid is None else cv.valid[:k],
            cv.dict,
            cv.type,
            None if cv.data2 is None else cv.data2[:k],
        )
        for cv in sorted_cols
    ]
    return out, sorted_live[:k], jnp.int64(0)


def limit_mask(live: jnp.ndarray, count: int) -> jnp.ndarray:
    return live & (jnp.cumsum(live.astype(jnp.int64)) <= count)


def unnest_expand(
    cols: Sequence[ColumnVal],
    live: jnp.ndarray,
    arrays: Sequence[ColumnVal],
    elem_types,
    with_ordinality: bool,
    outer: bool,
    C: int,
):
    """Expand rows by array length (reference: operator/unnest/UnnestOperator).

    Arrays are dict-coded (ArrayType): per-row lengths come from a host
    length table gathered by code; elements come from a padded [n_distinct,
    maxlen] device matrix.  Expansion is the standard static-shape pattern:
    exclusive-scan of lengths -> searchsorted row lookup per output lane,
    with the true required size reported for the capacity-retry loop.
    Multiple arrays zip (Trino semantics): rows extend to the longest array,
    shorter arrays NULL-pad.  `outer` emits one NULL-element row for
    empty/NULL arrays (LEFT JOIN UNNEST ... ON TRUE).
    """
    n = int(live.shape[0])

    len_tables = []  # jnp [n_distinct] per array
    elem_mats = []  # jnp [n_distinct, maxlen] per array
    elem_dicts = []  # Dictionary | None per array
    for arr, et in zip(arrays, elem_types):
        vals = arr.dict.values
        lens_np = np.asarray([len(v) for v in vals], dtype=np.int64)
        maxlen = max(1, int(lens_np.max()) if len(lens_np) else 1)
        if et.is_string:
            flat = sorted({str(x) for v in vals for x in v}) or [""]
            ed = Dictionary(np.asarray(flat, dtype=object))
            mat = np.zeros((len(vals), maxlen), dtype=np.int32)
            for r, v in enumerate(vals):
                for c, x in enumerate(v):
                    mat[r, c] = ed.code_of(str(x))
        else:
            ed = None
            mat = np.zeros((len(vals), maxlen), dtype=et.np_dtype)
            for r, v in enumerate(vals):
                for c, x in enumerate(v):
                    mat[r, c] = 0 if x is None else x
        len_tables.append(jnp.asarray(lens_np))
        elem_mats.append(jnp.asarray(mat))
        elem_dicts.append(ed)

    # per-row expansion length = max over zipped arrays (NULL array -> 0)
    row_lens = jnp.zeros((n,), dtype=jnp.int64)
    arr_lens = []
    for arr, lt in zip(arrays, len_tables):
        ln = jnp.take(lt, arr.data)
        if arr.valid is not None:
            ln = jnp.where(arr.valid, ln, 0)
        arr_lens.append(ln)
        row_lens = jnp.maximum(row_lens, ln)
    row_lens = jnp.where(live, row_lens, 0)
    pre_outer_lens = row_lens  # before the outer null-extension bump
    if outer:
        row_lens = jnp.where(live & (row_lens == 0), 1, row_lens)

    ends = jnp.cumsum(row_lens)  # inclusive scan
    total = ends[-1] if n else jnp.int64(0)
    starts = ends - row_lens
    j = jnp.arange(C, dtype=jnp.int64)
    src = searchsorted_tpu(ends, j, side="right")
    src_c = jnp.clip(src, 0, max(n - 1, 0)).astype(jnp.int32)
    pos = j - jnp.take(starts, src_c)
    out_live = j < total

    out_cols: list[ColumnVal] = []
    for cv in cols:
        data = jnp.take(cv.data, src_c, axis=0)
        valid = None if cv.valid is None else jnp.take(cv.valid, src_c)
        out_cols.append(ColumnVal(data, valid, cv.dict, cv.type))
    for arr, lt, mat, ed, et, ln in zip(
        arrays, len_tables, elem_mats, elem_dicts, elem_types, arr_lens
    ):
        code = jnp.take(arr.data, src_c)
        in_len = pos < jnp.take(ln, src_c)
        pos_c = jnp.clip(pos, 0, mat.shape[1] - 1)
        data = mat[code, pos_c]
        valid = out_live & in_len
        if arr.valid is not None:
            valid = valid & jnp.take(arr.valid, src_c)
        out_cols.append(ColumnVal(data, valid, ed, et))
    if with_ordinality:
        from ..data.types import BIGINT

        # outer null-extension rows carry NULL ordinality (Trino semantics)
        ord_valid = None
        if outer:
            ord_valid = out_live & (pos < jnp.take(pre_outer_lens, src_c))
        out_cols.append(ColumnVal(pos + 1, ord_valid, None, BIGINT))
    return out_cols, out_live, total
