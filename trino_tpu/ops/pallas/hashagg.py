"""Pallas linear-probing hash table over VMEM — the group-by/join build pass.

Replaces the full-array sort at the head of the sort-based group-by and
equi-join (ops/relops.py) with ONE streaming pass over HBM: every row probes
a VMEM-resident open-addressing table keyed on its encoded key words and
either matches an existing entry (getting that entry's dense group id) or
claims an empty slot (allocating the next id).  The table never leaves VMEM
until the final grid step, so the pass is bandwidth-bound on the row stream
— the reference engine's BigintGroupByHash / hash-build idea mapped onto the
TPU's memory hierarchy.

Layout and idioms follow ops/pallas/segreduce.py: rows stream in 8192-row
grid steps of eight (8, 128) sub-chunks; all table reads and writes are
one-hot matmuls on the MXU (TPU vector memory has no scattered addressing —
a one-hot dot IS the gather/scatter); f32 is made exact by splitting every
32-bit key word into two 16-bit halves (integers < 2^24 are exact in f32).

Table: [16, T] f32 in VMEM scratch, T a multiple of 512 (tiled so each
one-hot stays ~2 MB).  Channels: 0 = used flag, 1 = group id, 2.. = the
lo16/hi16 halves of each key word.  Collision handling is textbook linear
probing with a bounded probe budget: a sub-chunk's rows retry a
claimed-but-lost slot before advancing (two equal new keys in one sub-chunk
must converge on one entry), and any row that exhausts the budget — or a
table that runs past its group capacity — raises the kernel's overflow flag,
which the caller turns into its deterministic overflow-to-sort fallback.

Exactness: key words round-trip the f32 table exactly (16-bit halves), row
positions and group ids stay below 2^24, and every matmul runs at HIGHEST
precision — matches and ids are exact, never probabilistic.  A 64-bit mixed
hash picks only the START slot; equality is decided on the full key words.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_enable_x64 = getattr(jax, "enable_x64", None)
if _enable_x64 is None:  # pragma: no cover - depends on installed jax
    from jax.experimental import enable_x64 as _enable_x64

# test hook: force interpret-mode execution on CPU (tests/test_pallas_relops)
INTERPRET = False

_CHUNK_S = 8
_CHUNK_L = 128
_SUB_ROWS = _CHUNK_S * _CHUNK_L  # 1024 rows per probing sub-chunk
_STEP_CHUNKS = 8
_STEP_ROWS = _SUB_ROWS * _STEP_CHUNKS  # 8192 rows per grid step
_TTILE = 512  # table lanes per one-hot tile (~2MB of VMEM per intermediate)
_PROBE_LIMIT = 64  # probe-round budget before the overflow flag trips; the
# round loop is a while_loop that exits as soon as every row in the
# sub-chunk resolved, so typical cost is 1-3 rounds

MAX_WORDS = 6  # i32 key words per row the 16-channel table can hold
_CHANNELS = 16  # used, gid, up to 2*MAX_WORDS halves, padding

_MAX_ROWS_EXACT = 1 << 24  # row positions must stay exact in f32


def _pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def table_size(cap: int) -> int:
    """Slots for `cap` distinct keys: load factor <= 0.5, tile-aligned."""
    return max(2 * _pow2(max(cap, 1)), _TTILE)


_MAX_TILES = 16  # table slots cap (8192): VMEM + compile size stay sane


def shape_supported(n: int, n_words: int, cap: int) -> bool:
    """Limits independent of backend — also enforced under interpret mode."""
    if n_words < 1 or n_words > MAX_WORDS or n >= _MAX_ROWS_EXACT:
        return False
    return table_size(cap) <= _MAX_TILES * _TTILE


def hash_table_supported(n: int, n_words: int, cap: int, backend=None) -> bool:
    backend = backend or jax.default_backend()
    return shape_supported(n, n_words, cap) and backend in ("tpu", "axon")


def hash_words(words, live) -> jnp.ndarray:
    """Combine encoded key words into a 64-bit start-slot hash (the
    _combined_hash splitmix chain from ops/relops.py over i32 words)."""
    from ..relops import _mix64

    h = jnp.zeros(live.shape, dtype=jnp.uint64)
    for w in words:
        h = _mix64(h ^ _mix64(w.astype(jnp.uint32).astype(jnp.uint64)))
    return h


def _halves_f32(w: jnp.ndarray):
    wu = w.astype(jnp.int32).astype(jnp.uint32)
    return (
        (wu & jnp.uint32(0xFFFF)).astype(jnp.float32),
        (wu >> jnp.uint32(16)).astype(jnp.float32),
    )


def _prep(arr: jnp.ndarray, n_pad: int, fill) -> jnp.ndarray:
    # the fill must carry the array's exact dtype: a weak python scalar
    # picks up the ambient x64 default, which differs between this
    # function's _enable_x64(False) scope and an enclosing fragment trace
    out = jnp.pad(
        arr, (0, n_pad - arr.shape[0]),
        constant_values=jnp.asarray(fill, arr.dtype),
    )
    return out.reshape(n_pad // _CHUNK_L, _CHUNK_L)


def _sub_prefix(wf: jnp.ndarray):
    """Row-major exclusive prefix count of a (8, 128) 0/1 mask + its total:
    lanes via a strict-lower-triangular matmul (exact f32 — counts < 2^24),
    sublanes via a statically unrolled running sum."""
    tri = (
        jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_L, _CHUNK_L), 0)
        < jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_L, _CHUNK_L), 1)
    ).astype(jnp.float32)
    pre_lane = jax.lax.dot_general(
        wf, tri, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    rows = []
    run = jnp.float32(0.0)
    for s in range(_CHUNK_S):
        rows.append(jnp.broadcast_to(run.reshape(1, 1), (1, _CHUNK_L)))
        run = run + jnp.sum(wf[s : s + 1, :])
    pre_sub = jnp.concatenate(rows, axis=0)
    return pre_lane + pre_sub, run


def _gather_channels(tbl, cur, active, T):
    """One-hot MXU gather: per active row the 16 table channels at slot
    `cur`.  Returns (channels (8,128,16) f32, a per-tile one-hot rebuilder
    used by callers that scatter)."""
    g = None
    for q in range(T // _TTILE):
        iota = (
            jax.lax.broadcasted_iota(
                jnp.int32, (_CHUNK_S, _CHUNK_L, _TTILE), 2
            )
            + q * _TTILE
        )
        oh = ((cur[:, :, None] == iota) & active[:, :, None]).astype(jnp.float32)
        tile = jnp.broadcast_to(
            tbl[:, q * _TTILE : (q + 1) * _TTILE][None],
            (_CHUNK_S, _CHANNELS, _TTILE),
        )
        part = jax.lax.dot_general(
            oh, tile, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (8, 128, 16)
        g = part if g is None else g + part
    return g


@functools.lru_cache(maxsize=64)
def _build_kernel(n_words: int, T: int, n_chunks: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_half = 2 * n_words

    def kernel(slot_ref, live_ref, planes_ref, gid_ref, table_ref, stats_ref,
               tbl, ngid, over):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            tbl[...] = jnp.zeros((_CHANNELS, T), jnp.float32)
            ngid[0] = jnp.int32(0)
            over[0] = jnp.int32(0)

        posf = (
            jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L), 0)
            * _CHUNK_L
            + jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L), 1)
        ).astype(jnp.float32)

        for c in range(_STEP_CHUNKS):
            rows = slice(c * _CHUNK_S, (c + 1) * _CHUNK_S)
            sl = slot_ref[rows, :]
            lv = live_ref[rows, :] > 0
            vals = [planes_ref[w, rows, :] for w in range(n_half)]

            off0 = jnp.zeros(sl.shape, jnp.int32)
            resolved0 = ~lv
            gid0 = jnp.full(sl.shape, -1, jnp.int32)

            def _round(carry):
                r, off, resolved, gid = carry
                cur = sl + off
                cur = jnp.where(cur >= T, cur - T, cur)
                active = ~resolved
                g = _gather_channels(tbl, cur, active, T)
                used = g[..., 0] > 0.5
                eq = used
                for w in range(n_half):
                    eq = eq & (g[..., 2 + w] == vals[w])
                match = active & eq
                gid = jnp.where(match, g[..., 1].astype(jnp.int32), gid)
                resolved = resolved | match

                # claim empty slots: one winner per slot (min row position,
                # exact in f32), losers retry the same slot next round so
                # equal new keys in one sub-chunk converge on one entry
                cand = active & ~used
                winpos = jnp.zeros(sl.shape, jnp.float32)
                for q in range(T // _TTILE):
                    iota = (
                        jax.lax.broadcasted_iota(
                            jnp.int32, (_CHUNK_S, _CHUNK_L, _TTILE), 2
                        )
                        + q * _TTILE
                    )
                    ohb = (cur[:, :, None] == iota) & cand[:, :, None]
                    masked = jnp.where(
                        ohb, posf[:, :, None], jnp.float32(2 * _SUB_ROWS)
                    )
                    m = jnp.min(jnp.min(masked, axis=1), axis=0, keepdims=True)
                    m8 = jnp.broadcast_to(m[None], (_CHUNK_S, 1, _TTILE))
                    winpos = winpos + jax.lax.dot_general(
                        ohb.astype(jnp.float32), m8,
                        (((2,), (2,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )[..., 0]
                winner = cand & (winpos == posf)

                wf = winner.astype(jnp.float32)
                rank, n_new = _sub_prefix(wf)
                base = ngid[0]
                newgid = base + rank.astype(jnp.int32)
                gid = jnp.where(winner, newgid, gid)
                resolved = resolved | winner
                ngid[0] = base + n_new.astype(jnp.int32)

                # scatter winners into their claimed slots (one per slot)
                upd = jnp.stack(
                    [wf, newgid.astype(jnp.float32) * wf]
                    + [v * wf for v in vals]
                    + [jnp.zeros(sl.shape, jnp.float32)]
                    * (_CHANNELS - 2 - n_half),
                    axis=1,
                )  # (8, 16, 128)
                for q in range(T // _TTILE):
                    iota = (
                        jax.lax.broadcasted_iota(
                            jnp.int32, (_CHUNK_S, _CHUNK_L, _TTILE), 2
                        )
                        + q * _TTILE
                    )
                    ohw = (
                        (cur[:, :, None] == iota) & winner[:, :, None]
                    ).astype(jnp.float32)
                    delta = jax.lax.dot_general(
                        upd, ohw, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST,
                    )  # (8, 16, 512)
                    ts = slice(q * _TTILE, (q + 1) * _TTILE)
                    tbl[:, ts] = tbl[:, ts] + jnp.sum(delta, axis=0)

                off = off + (active & used & ~eq).astype(jnp.int32)
                return r + 1, off, resolved, gid

            def _unresolved(carry):
                r, _off, resolved, _gid = carry
                return (r < _PROBE_LIMIT) & jnp.any(~resolved)

            _, _, resolved, gid = jax.lax.while_loop(
                _unresolved, _round, (jnp.int32(0), off0, resolved0, gid0)
            )

            over[0] = jnp.maximum(
                over[0], jnp.any(~resolved).astype(jnp.int32)
            )
            gid_ref[rows, :] = gid

        @pl.when(i == n_chunks - 1)
        def _flush():
            table_ref[...] = tbl[...]
            r0 = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L), 0)
            c0 = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L), 1)
            zero = jnp.int32(0)  # bare 0 is weak-typed: it picks up the
            # ambient x64 default, which may be on in an enclosing trace
            stats_ref[...] = jnp.where(
                (r0 == 0) & (c0 == 0), ngid[0], zero
            ) + jnp.where((r0 == 0) & (c0 == 1), over[0], zero)

    vmem = pltpu.VMEM
    step_s = _STEP_ROWS // _CHUNK_L
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec(
                (2 * n_words, step_s, _CHUNK_L),
                lambda i: (0, i, 0),
                memory_space=vmem,
            ),
        ],
        out_specs=(
            pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_CHANNELS, T), lambda i: (0, 0), memory_space=vmem),
            pl.BlockSpec((_CHUNK_S, _CHUNK_L), lambda i: (0, 0), memory_space=vmem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_chunks * step_s, _CHUNK_L), jnp.int32),
            jax.ShapeDtypeStruct((_CHANNELS, T), jnp.float32),
            jax.ShapeDtypeStruct((_CHUNK_S, _CHUNK_L), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((_CHANNELS, T), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )


def build_hash_table(words, live, cap: int, *, interpret: bool = False):
    """Insert every live row's key into a fresh table.

    words: up to MAX_WORDS i32 arrays [n] encoding the key columns.
    Returns (gid [n] int32 — dense group id in claim order, -1 for dead or
    unresolved rows; table [16, T] f32 for a subsequent probe pass;
    n_groups int32; overflow bool — probe budget exhausted or more than
    `cap` distinct keys, i.e. the caller must take its sort fallback).
    """
    interpret = bool(interpret or INTERPRET)
    n = live.shape[0]
    T = table_size(cap)
    h = hash_words(words, live)
    slot0 = (h % jnp.uint64(T)).astype(jnp.int32)

    n_pad = -(-max(n, 1) // _STEP_ROWS) * _STEP_ROWS
    n_chunks = n_pad // _STEP_ROWS
    planes = []
    for w in words:
        lo, hi = _halves_f32(w)
        planes.append(_prep(lo, n_pad, 0.0))
        planes.append(_prep(hi, n_pad, 0.0))
    call = _build_kernel(len(words), T, n_chunks, interpret)
    with _enable_x64(False):
        gid_b, table, stats = call(
            _prep(slot0, n_pad, 0),
            _prep(live.astype(jnp.int32), n_pad, 0),
            jnp.stack(planes),
        )
    gid = gid_b.reshape(-1)[:n]
    n_groups = stats[0, 0]
    overflow = (stats[0, 1] > 0) | (n_groups > cap)
    return gid, table, n_groups, overflow
