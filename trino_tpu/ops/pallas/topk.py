"""Radix-select TopN: K-selection without sorting the relation.

The reference's TopNOperator keeps a bounded heap and never sorts its input
(operator/TopNOperator.java:32).  The round-1 engine DID sort: top_n =
full multi-key lax.sort + slice — O(n log n) comparator passes and a full
permutation of every output column (VERDICT: "TopN sorts the full
relation").

TPU-native K-selection instead:

1. Map the leading sort key to a monotone uint32 ("sortable" transform:
   sign-flipped float bits, offset ints, dictionary ranks).  Descending
   order inverts the bits; NULL ordering folds in as a forced extreme.
2. Four radix passes find the exact K-th threshold byte by byte: each pass
   histograms one byte of the masked survivors — a 256-bin segmented count
   that runs through the fused Pallas one-hot kernel (segreduce.py) on TPU.
   The bin holding the K-th row is selected with a reverse cumsum + argmax,
   entirely inside the trace (no host round-trip).
3. Rows at-or-above the threshold (== candidates: every true top-K row,
   plus ties on the 32-bit prefix) are compacted by cumsum + scatter into a
   static-capacity buffer and only THEN fully sorted — an O(cap log cap)
   sort over ~K rows instead of O(n log n) over the relation, and column
   gathers touch cap rows, not n.

The candidate count is returned as `required` for the executor's
capacity-retry protocol (exec/compiler.py): heavy ties (e.g. a constant
leading key) overflow the buffer and the host retries at a larger tier,
degrading gracefully toward the full sort.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .segreduce import SegRed, fused_segment_reduce, pallas_segreduce_supported

__all__ = ["sortable_u32", "radix_topk_threshold", "radix_topk_supported"]

_RADIX_MIN_ROWS = 65_536  # below this the plain sort is cheaper

# Test hook: route TopN through the radix path regardless of backend/size.
FORCE = False


def radix_topk_supported(n_rows: int, count: int, backend: Optional[str] = None) -> bool:
    if FORCE:
        return True
    return (
        n_rows >= _RADIX_MIN_ROWS
        and count <= 4096
        and pallas_segreduce_supported(256, backend)
    )


def sortable_u32(data: jnp.ndarray, descending: bool) -> jnp.ndarray:
    """Monotone map of a numeric key into uint32 (ties allowed: i64/f64
    collapse to their top 32 bits; the caller resolves ties exactly on the
    candidate set)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        f = data.astype(jnp.float32)  # monotone (round-to-nearest keeps <=)
        u = jax.lax.bitcast_convert_type(f, jnp.uint32)
        neg = (u & jnp.uint32(0x80000000)) != 0
        u = jnp.where(neg, ~u, u | jnp.uint32(0x80000000))
    elif data.dtype == jnp.bool_:
        u = data.astype(jnp.uint32)
    elif data.dtype in (jnp.int64, np.dtype(np.int64)):
        hi = (data >> 32).astype(jnp.int64) + (1 << 31)
        u = hi.astype(jnp.uint32)
    else:
        u = (data.astype(jnp.int64) + (1 << 31)).astype(jnp.uint32)
    if descending:
        u = ~u
    return u


def radix_topk_threshold(u: jnp.ndarray, live: jnp.ndarray, k: int) -> jnp.ndarray:
    """Exact K-th-largest threshold over the uint32 keys of live rows.

    Every live row with u >= threshold is a candidate (the true top-K plus
    any 32-bit ties at the boundary).  Four 256-bin histogram passes, each
    a fused segmented count; bin selection stays inside the trace.
    """
    prefix = jnp.uint32(0)
    above = jnp.int64(0)  # rows strictly above the resolved prefix so far
    kk = jnp.int64(k)
    for p in range(4):
        shift = jnp.uint32(8 * (3 - p))
        byte = ((u >> shift) & jnp.uint32(0xFF)).astype(jnp.int32)
        if p == 0:
            in_prefix = live
        else:
            mask_bits = jnp.uint32(0xFFFFFFFF) << (shift + jnp.uint32(8))
            in_prefix = live & ((u & mask_bits) == (prefix & mask_bits))
        (hist,) = fused_segment_reduce(
            byte, [SegRed("count", None, in_prefix)], 256
        )
        # descending scan: rows above bin b = above + sum(hist[b+1:])
        rev = jnp.cumsum(hist[::-1])[::-1]  # rev[b] = sum(hist[b:])
        above_b = above + rev - hist  # strictly above each bin
        sel = (above_b < kk) & (above_b + hist >= kk)
        any_sel = jnp.any(sel)
        bin_ = jnp.argmax(sel).astype(jnp.uint32)
        # k exceeds the live rows under this prefix: take the smallest
        # non-empty bin so every such row qualifies
        nonempty = hist > 0
        low_bin = jnp.where(
            jnp.any(nonempty), 255 - jnp.argmax(nonempty[::-1]), 0
        ).astype(jnp.uint32)
        bin_ = jnp.where(any_sel, bin_, low_bin)
        above = jnp.where(any_sel, above_b[bin_.astype(jnp.int32)], above)
        prefix = prefix | (bin_ << shift)
    return prefix
