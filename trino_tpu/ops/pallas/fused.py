"""Fused scan→filter→project→aggregate Pallas pipeline.

q01/q06-shaped fragments — a TableScan feeding a stack of Filters/Projects
feeding one Aggregate whose keys are small dictionary columns — are memory
bound: the sort-based path reads the scan columns from HBM once per
relational operator (filter mask, projected expressions, key sort, segment
reduce).  This kernel reads every referenced scan column from HBM exactly
once and does everything else in VMEM:

  * the compiler (exec/compiler.py) substitutes the filter predicates and
    aggregate arguments down to scan level (plan/ir.substitute), so the
    kernel receives raw column planes plus a closed IR tree;
  * numeric lanes travel as double-float pairs (hi = f32(v),
    lo = f32(v - f64(hi))): exact for |v| < 2^47, which covers the scaled
    decimals of the TPC-H fact columns; arithmetic uses the classic
    error-free transforms (Knuth two-sum, Dekker two-product with a 4097
    split), so products like extendedprice*(1-discount) stay exact per row;
  * grouping keys are dictionary codes combined into one mixed-radix code
    of domain D <= 512 — one lane tile — and each per-1024-row partial is a
    one-hot MXU matmul: stacked streams (8, NR, 128) x one-hot (8, 128, 512)
    contracted over lanes, summed over sublanes, into an (NR, 512)
    accumulator held in VMEM across the whole grid with Neumaier
    compensation (acc + err recovered in f64 on the host).

Every aggregate lowers to a handful of f32 *streams* (per-row values summed
per group): count -> the row mask; sum -> the hi and lo parts (summed as
separate streams, recombined in f64); avg -> sum's streams plus a count.
Streams are deduplicated, so q01's six sums+avgs over four expressions cost
eleven streams, not eighteen.

Accuracy: per-row expression math is exact; only the f32 summation inside a
1024-row partial rounds (compensated across partials).  For the TPC-H
aggregates this lands within ~1e-7 relative of the exact result, far inside
the engine's comparison tolerance; exactness-critical cases (BIGINT sum's
mod-2^64 semantics) are rejected at plan time and take the sort path.

Like the hash kernels, everything here runs under pallas interpret mode on
CPU so tier-1 exercises the same code path as the TPU build.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ...plan.ir import Call, Const, FieldRef, IrExpr
from .hashagg import (
    _CHUNK_L,
    _CHUNK_S,
    _STEP_CHUNKS,
    _STEP_ROWS,
    _enable_x64,
    _prep,
)
from . import hashagg as _hashagg

# one lane tile: the mixed-radix key-code domain must fit a single 512-wide
# accumulator tile so the scatter is one matmul, no table walk
_DTILE = 512
_MAX_STREAMS = 64
# double-float pairs are exact only while the integer payload fits hi+lo
_DD_EXACT_BITS = 47

_AGG_WHITELIST = ("sum", "count", "count_star", "avg")


class _Unsupported(Exception):
    pass


# --------------------------------------------------------------- planning
#
# Static pass over the scan-level IR: decide every subexpression's kernel
# kind ("i32" | "dd" | "bool"), its decimal scale, and whether it can be
# NULL — rejecting anything the kernel can't evaluate exactly.  The same
# walk orders the input planes and deduplicates aggregate streams, so the
# result (a frozen _Recipe) is both the support proof and the kernel spec.


@dataclass(frozen=True)
class _Recipe:
    n_cols: int
    # col_idx -> ("i32", plane, valid_plane|-1) | ("dd", hi, lo, valid|-1)
    #          | ("dict", plane)
    cols: tuple
    n_i32: int
    n_f32: int
    filters: tuple  # IrExpr, scan-level
    keys: tuple     # (col_idx, domain, stride)
    domain: int
    streams: tuple  # ("rows", None) | ("cnt", e) | ("hi", e) | ("lo", e)
    aggs: tuple     # ("count", si) | ("sum", hi, lo, cnt, scale_shift, wide)
                    # | ("avg", hi, lo, cnt, scale_shift) | ("fsum", hi, lo, cnt)
                    # | ("favg", hi, lo, cnt)


def _kind_of_type(t) -> tuple[str, Optional[int]]:
    """Map a column/const Type to a kernel kind + decimal scale (None for
    floating point, 0 for integers/dates/bools)."""
    name = getattr(t, "name", "")
    if t.is_decimal:
        return "dd", t.scale
    if name in ("double", "real"):
        return "dd", None
    if name in ("integer", "date", "smallint", "tinyint"):
        return "i32", 0
    if name == "boolean":
        return "bool", 0
    raise _Unsupported(f"type {name}")


class _Planner:
    def __init__(self, cols):
        self.scan_cols = cols
        self.col_plan: dict[int, tuple] = {}
        self.n_i32 = 1  # plane 0 is the live mask
        self.n_f32 = 0
        self.streams: list = []
        self.stream_ix: dict = {}

    def use_col(self, i: int) -> tuple:
        got = self.col_plan.get(i)
        if got is not None:
            return got
        cv = self.scan_cols[i]
        if cv.data2 is not None:
            raise _Unsupported("decimal128 scan column")
        if cv.dict is not None:
            raise _Unsupported("dictionary column in expression")
        kind, scale = _kind_of_type(cv.type)
        vplane = -1
        if cv.valid is not None:
            vplane = self.n_i32
            self.n_i32 += 1
        if kind == "dd":
            plan = ("dd", self.n_f32, self.n_f32 + 1, vplane, scale)
            self.n_f32 += 2
        elif kind == "i32":
            plan = ("i32", self.n_i32, vplane, scale)
            self.n_i32 += 1
        else:  # bool rides as an i32 plane
            plan = ("bool", self.n_i32, vplane, scale)
            self.n_i32 += 1
        self.col_plan[i] = plan
        return plan

    def use_key(self, i: int) -> int:
        cv = self.scan_cols[i]
        if cv.dict is None or cv.valid is not None:
            raise _Unsupported("group key must be a no-null dictionary column")
        got = self.col_plan.get(i)
        if got is not None:
            if got[0] != "dict":
                raise _Unsupported("key column also used as a value")
            return got[1]
        plan = ("dict", self.n_i32)
        self.n_i32 += 1
        self.col_plan[i] = plan
        return plan[1]

    # ---- static type/nullability check: returns (kind, scale, nullable)

    def check(self, e: IrExpr) -> tuple[str, Optional[int], bool]:
        if isinstance(e, FieldRef):
            plan = self.use_col(e.index)
            cv = self.scan_cols[e.index]
            kind, scale = _kind_of_type(cv.type)
            return kind, scale, cv.valid is not None
        if isinstance(e, Const):
            kind, scale = _kind_of_type(e.type)
            if e.value is None:
                return kind, scale, True
            if kind == "dd" and scale is not None and abs(int(e.value)) >= (1 << _DD_EXACT_BITS):
                raise _Unsupported("decimal constant too wide")
            return kind, scale, False
        if isinstance(e, Call):
            return self._check_call(e)
        raise _Unsupported(f"expression {type(e).__name__}")

    def _check_call(self, e: Call):
        op = e.op
        if op in ("add", "sub", "mul", "neg"):
            sub = [self.check(a) for a in e.args]
            if any(k == "bool" for k, _, _ in sub):
                raise _Unsupported(f"{op} over boolean")
            scales = [s for _, s, _ in sub]
            if any(s is None for s in scales) != all(s is None for s in scales):
                raise _Unsupported("mixed decimal/double arithmetic")
            nullable = any(nl for _, _, nl in sub)
            okind, oscale = _kind_of_type(e.type)
            if okind != "dd":
                raise _Unsupported(f"integer {op}")
            if oscale is not None:
                if op == "mul":
                    if oscale != scales[0] + scales[1]:
                        raise _Unsupported("mul rescale")
                elif op == "neg":
                    if oscale != scales[0]:
                        raise _Unsupported("neg rescale")
                elif oscale != scales[0] or scales[0] != scales[1]:
                    raise _Unsupported(f"{op} operand scales differ")
            return "dd", oscale, nullable
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            (k1, s1, n1), (k2, s2, n2) = self.check(e.args[0]), self.check(e.args[1])
            if "bool" in (k1, k2):
                raise _Unsupported("comparison over boolean")
            if (s1 is None) != (s2 is None) or (
                s1 is not None and s1 != s2
            ):
                raise _Unsupported("comparison operand scales differ")
            return "bool", 0, n1 or n2
        if op in ("and", "or"):
            subs = [self.check(a) for a in e.args]
            if any(k != "bool" for k, _, _ in subs):
                raise _Unsupported(f"{op} over non-boolean")
            return "bool", 0, any(nl for _, _, nl in subs)
        if op == "not":
            k, _, nl = self.check(e.args[0])
            if k != "bool":
                raise _Unsupported("not over non-boolean")
            return "bool", 0, nl
        if op == "is_null":
            self.check(e.args[0])
            return "bool", 0, False
        if op == "cast":
            k, s, nl = self.check(e.args[0])
            okind, oscale = _kind_of_type(e.type)
            if okind != "dd":
                raise _Unsupported(f"cast to {e.type}")
            if k == "bool":
                raise _Unsupported("cast from boolean")
            if oscale is None:  # -> double: any numeric source works
                return "dd", None, nl
            if k == "i32":
                return "dd", oscale, nl
            if s is None or oscale < s:
                raise _Unsupported("narrowing or float->decimal cast")
            return "dd", oscale, nl
        raise _Unsupported(f"op {op}")

    # ---- stream dedup

    def stream(self, tag: str, e: Optional[IrExpr]) -> int:
        key = (tag, e)
        got = self.stream_ix.get(key)
        if got is not None:
            return got
        ix = len(self.streams)
        if ix >= _MAX_STREAMS:
            raise _Unsupported("too many aggregate streams")
        self.streams.append((tag, e))
        self.stream_ix[key] = ix
        return ix


def plan_pipeline(scan_cols, filters, key_exprs, agg_fns, agg_args, agg_types):
    """Try to compile the fused pipeline.  Returns (recipe, "") on success or
    (None, reason) when any piece falls outside the kernel's reach —
    the caller then runs the regular operator-at-a-time path."""
    p = _Planner(scan_cols)
    try:
        for f in filters:
            k, _, _ = p.check(f)
            if k != "bool":
                raise _Unsupported("non-boolean filter")
        keys = []
        domain = 1
        for ke in key_exprs:
            if not isinstance(ke, FieldRef):
                raise _Unsupported("computed group key")
            plane = p.use_key(ke.index)
            d = len(p.scan_cols[ke.index].dict)
            keys.append((ke.index, d))
            domain *= max(d, 1)
        if domain > _DTILE:
            raise _Unsupported(f"key domain {domain} > {_DTILE}")
        rows_s = p.stream("rows", None)
        aggs = []
        for fn, arg, otype in zip(agg_fns, agg_args, agg_types):
            if fn not in _AGG_WHITELIST:
                raise _Unsupported(f"agg {fn}")
            if fn == "count_star":
                aggs.append(("count", rows_s))
                continue
            kind, scale, nullable = p.check(arg)
            if kind == "bool":
                raise _Unsupported(f"{fn} over boolean")
            cnt_s = rows_s if not nullable else p.stream("cnt", arg)
            if fn == "count":
                aggs.append(("count", cnt_s))
                continue
            hi_s = p.stream("hi", arg)
            lo_s = p.stream("lo", arg)
            okind, oscale = _kind_of_type(otype)
            if okind != "dd":
                raise _Unsupported(f"{fn} result {otype}")
            if scale is None:  # floating point in
                if oscale is not None:
                    raise _Unsupported(f"float {fn} with decimal result")
                aggs.append((("fsum" if fn == "sum" else "favg"), hi_s, lo_s, cnt_s))
                continue
            if oscale is None or oscale < scale:
                raise _Unsupported(f"{fn} result rescale")
            shift = oscale - scale
            if fn == "sum":
                wide = bool(getattr(otype, "precision", 18) > 18)
                aggs.append(("sum", hi_s, lo_s, cnt_s, shift, wide))
            else:
                aggs.append(("avg", hi_s, lo_s, cnt_s, shift))
    except _Unsupported as ex:
        return None, str(ex)
    # mixed-radix strides, first key most significant (matches nested order)
    strides = []
    acc = 1
    for _, d in reversed(keys):
        strides.append(acc)
        acc *= max(d, 1)
    strides.reverse()
    recipe = _Recipe(
        n_cols=len(scan_cols),
        cols=tuple(sorted((i, plan) for i, plan in p.col_plan.items())),
        n_i32=p.n_i32,
        n_f32=p.n_f32,
        filters=tuple(filters),
        keys=tuple((i, d, s) for (i, d), s in zip(keys, strides)),
        domain=domain,
        streams=tuple(p.streams),
        aggs=tuple(aggs),
    )
    return recipe, ""


# ------------------------------------------------------- in-kernel evaluator
#
# Double-float (f32 pair) error-free transforms.  All classic: Knuth
# two-sum, Dekker split/two-product.  Exact per-row for payloads < 2^47.


def _two_sum(a, b):
    s = a + b
    bb = s - a
    return s, (a - (s - bb)) + (b - bb)


def _split(a):
    c = a * jnp.float32(4097.0)  # 2^12 + 1
    hi = c - (c - a)
    return hi, a - hi


def _two_prod(a, b):
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    return p, ((ah * bh - p) + ah * bl + al * bh) + al * bl


def _dd_add(x, y):
    s, e = _two_sum(x[0], y[0])
    e = e + x[1] + y[1]
    return _two_sum(s, e)


def _dd_neg(x):
    return (-x[0], -x[1])


def _dd_mul(x, y):
    p, e = _two_prod(x[0], y[0])
    e = e + x[0] * y[1] + x[1] * y[0]
    return _two_sum(p, e)


def _dd_lt(x, y):
    return (x[0] < y[0]) | ((x[0] == y[0]) & (x[1] < y[1]))


def _dd_eq(x, y):
    return (x[0] == y[0]) & (x[1] == y[1])


def _dd_const(v: float):
    import numpy as np

    hi = np.float32(v)
    lo = np.float32(float(v) - float(hi))
    return jnp.float32(hi), jnp.float32(lo)


class _Eval:
    """Evaluates the closed IR over one (8, 128) sub-chunk.  Values are
    (kind, payload..., valid) with valid None when statically non-null."""

    def __init__(self, recipe, i32, f32, shape):
        self.col_plan = dict(recipe.cols)
        self.i32 = i32  # list of (8, 128) int32 planes
        self.f32 = f32  # list of (8, 128) f32 planes
        self.shape = shape
        self.memo: dict = {}

    def _valid(self, vplane):
        return None if vplane < 0 else (self.i32[vplane] > 0)

    def ev(self, e: IrExpr):
        got = self.memo.get(e)
        if got is None:
            got = self._ev(e)
            self.memo[e] = got
        return got

    def _ev(self, e: IrExpr):
        if isinstance(e, FieldRef):
            plan = self.col_plan[e.index]
            if plan[0] == "dd":
                _, hi, lo, vp, _ = plan
                return ("dd", (self.f32[hi], self.f32[lo]), self._valid(vp))
            if plan[0] == "i32":
                _, p, vp, _ = plan
                return ("i32", self.i32[p], self._valid(vp))
            _, p, vp, _ = plan
            return ("bool", self.i32[p] > 0, self._valid(vp))
        if isinstance(e, Const):
            kind, scale = _kind_of_type(e.type)
            if e.value is None:
                zero = jnp.zeros(self.shape, jnp.float32)
                dead = jnp.zeros(self.shape, jnp.bool_)
                if kind == "dd":
                    return ("dd", (zero, zero), dead)
                if kind == "bool":
                    return ("bool", dead, dead)
                return ("i32", jnp.zeros(self.shape, jnp.int32), dead)
            if kind == "dd":
                hi, lo = _dd_const(float(e.value) if scale is None else int(e.value))
                full = jnp.full(self.shape, 1.0, jnp.float32)
                return ("dd", (hi * full, lo * full), None)
            if kind == "bool":
                return ("bool", jnp.full(self.shape, bool(e.value)), None)
            return ("i32", jnp.full(self.shape, int(e.value), jnp.int32), None)
        assert isinstance(e, Call)
        return self._call(e)

    def _dd(self, v):
        """Lift a value to dd."""
        if v[0] == "dd":
            return v[1], v[2]
        x = v[1].astype(jnp.float32)
        hi = x  # |i32| < 2^31: hi rounds, lo recovers the residual exactly
        lo = (v[1] - hi.astype(jnp.int32)).astype(jnp.float32)
        return (hi, lo), v[2]

    def _call(self, e: Call):
        op = e.op
        if op in ("add", "sub", "mul", "neg"):
            parts = [self._dd(self.ev(a)) for a in e.args]
            valid = None
            for _, vl in parts:
                valid = vl if valid is None else (valid if vl is None else valid & vl)
            if op == "neg":
                return ("dd", _dd_neg(parts[0][0]), parts[0][1])
            x, y = parts[0][0], parts[1][0]
            if op == "add":
                return ("dd", _dd_add(x, y), valid)
            if op == "sub":
                return ("dd", _dd_add(x, _dd_neg(y)), valid)
            return ("dd", _dd_mul(x, y), valid)
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            a, b = self.ev(e.args[0]), self.ev(e.args[1])
            if a[0] == "i32" and b[0] == "i32":
                x, y = a[1], b[1]
                data = {
                    "eq": x == y, "ne": x != y, "lt": x < y,
                    "le": x <= y, "gt": x > y, "ge": x >= y,
                }[op]
            else:
                (x, vx), (y, vy) = self._dd(a), self._dd(b)
                if op == "eq":
                    data = _dd_eq(x, y)
                elif op == "ne":
                    data = ~_dd_eq(x, y)
                elif op == "lt":
                    data = _dd_lt(x, y)
                elif op == "le":
                    data = ~_dd_lt(y, x)
                elif op == "gt":
                    data = _dd_lt(y, x)
                else:
                    data = ~_dd_lt(x, y)
            valid = _and_opt(a[-1], b[-1])
            return ("bool", data, valid)
        if op in ("and", "or"):
            vals = [self.ev(a) for a in e.args]
            data, valid = vals[0][1], vals[0][2]
            for v in vals[1:]:
                data, valid = _kleene(op, data, valid, v[1], v[2])
            return ("bool", data, valid)
        if op == "not":
            v = self.ev(e.args[0])
            return ("bool", ~v[1], v[2])
        if op == "is_null":
            v = self.ev(e.args[0])
            if v[-1] is None:
                return ("bool", jnp.zeros(self.shape, jnp.bool_), None)
            return ("bool", ~v[-1], None)
        if op == "cast":
            v = self.ev(e.args[0])
            s = _kind_of_type(e.args[0].type)[1]
            oscale = _kind_of_type(e.type)[1]
            (x, _), valid = self._dd(v), v[-1]
            if oscale is None:
                # -> double: divide out the source's decimal scale
                if s:
                    x = _dd_mul(x, _dd_const(10.0 ** -s))
            else:
                shift = oscale - (s if s is not None else oscale)
                if shift:
                    x = _dd_mul(x, _dd_const(10 ** shift))
            return ("dd", x, valid)
        raise AssertionError(op)  # plan_pipeline vetted the tree

    def pred(self, e: IrExpr):
        """NULL -> row fails (FilterAndProject semantics)."""
        v = self.ev(e)
        m = v[1]
        if v[2] is not None:
            m = m & v[2]
        return m

    def masked_stream(self, tag, e, mask):
        one = jnp.float32(1.0)
        zero = jnp.float32(0.0)
        if tag == "rows":
            return jnp.where(mask, one, zero)
        v = self.ev(e)
        ok = mask if v[-1] is None else (mask & v[-1])
        if tag == "cnt":
            return jnp.where(ok, one, zero)
        (hi, lo), _ = self._dd(v)
        return jnp.where(ok, hi if tag == "hi" else lo, zero)


def _and_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _kleene(op, d1, v1, d2, v2):
    """SQL three-valued AND/OR over (data, valid) pairs."""
    t1 = d1 if v1 is None else (d1 & v1)
    t2 = d2 if v2 is None else (d2 & v2)
    f1 = ~d1 if v1 is None else (~d1 & v1)
    f2 = ~d2 if v2 is None else (~d2 & v2)
    if op == "and":
        data = t1 & t2
        known = (f1 | f2) | data
    else:
        data = t1 | t2
        known = (f1 & f2) | data
    return data, (None if (v1 is None and v2 is None) else known)


# -------------------------------------------------------------- the kernel


@functools.lru_cache(maxsize=64)
def _fused_kernel(recipe: _Recipe, n_chunks: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nr = len(recipe.streams)
    key_planes = {i: dict(recipe.cols)[i][1] for i, _, _ in recipe.keys}

    def kernel(i32_ref, f32_ref, out_ref, acc, err):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            acc[...] = jnp.zeros((nr, _DTILE), jnp.float32)
            err[...] = jnp.zeros((nr, _DTILE), jnp.float32)

        for c in range(_STEP_CHUNKS):
            rows = slice(c * _CHUNK_S, (c + 1) * _CHUNK_S)
            i32 = [i32_ref[p, rows, :] for p in range(recipe.n_i32)]
            f32 = [f32_ref[p, rows, :] for p in range(max(recipe.n_f32, 1))]
            ev = _Eval(recipe, i32, f32, (_CHUNK_S, _CHUNK_L))
            mask = i32[0] > 0
            for f in recipe.filters:
                mask = mask & ev.pred(f)
            code = jnp.zeros((_CHUNK_S, _CHUNK_L), jnp.int32)
            for ci, _, stride in recipe.keys:
                code = code + i32[key_planes[ci]] * jnp.int32(stride)
            streams = [
                ev.masked_stream(tag, e, mask) for tag, e in recipe.streams
            ]
            upd = jnp.stack(streams, axis=1)  # (8, NR, 128)
            lane = jax.lax.broadcasted_iota(
                jnp.int32, (_CHUNK_S, _CHUNK_L, _DTILE), 2
            )
            oh = (code[:, :, None] == lane).astype(jnp.float32)
            part = jax.lax.dot_general(
                upd, oh,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            ).sum(axis=0)  # (NR, 512)
            # Neumaier: compensate chunk-to-chunk rounding of the running sum
            a = acc[...]
            t = a + part
            err[...] = err[...] + jnp.where(
                jnp.abs(a) >= jnp.abs(part), (a - t) + part, (part - t) + a
            )
            acc[...] = t

        @pl.when(i == n_chunks - 1)
        def _flush():
            out_ref[0] = acc[...]
            out_ref[1] = err[...]

    vmem = pltpu.VMEM
    step_s = _STEP_ROWS // _CHUNK_L
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec(
                (recipe.n_i32, step_s, _CHUNK_L),
                lambda i: (0, i, 0),
                memory_space=vmem,
            ),
            pl.BlockSpec(
                (max(recipe.n_f32, 1), step_s, _CHUNK_L),
                lambda i: (0, i, 0),
                memory_space=vmem,
            ),
        ],
        out_specs=pl.BlockSpec(
            (2, nr, _DTILE), lambda i: (0, 0, 0), memory_space=vmem
        ),
        out_shape=jax.ShapeDtypeStruct((2, nr, _DTILE), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((nr, _DTILE), jnp.float32),
            pltpu.VMEM((nr, _DTILE), jnp.float32),
        ],
        interpret=interpret,
    )


# ------------------------------------------------------------ host driver


def _dd_planes(data):
    v = data.astype(jnp.float64)
    hi = v.astype(jnp.float32)
    lo = (v - hi.astype(jnp.float64)).astype(jnp.float32)
    return hi, lo


def run(recipe: _Recipe, scan_cols, live, *, interpret: bool = False):
    """Execute the fused pipeline.

    Returns (totals f64 (NR, D), n_groups int array) — per-stream per-group
    sums; the caller assembles aggregate columns via `assemble`."""
    interpret = bool(interpret or _hashagg.INTERPRET)
    n = live.shape[0]
    n_pad = -(-max(n, 1) // _STEP_ROWS) * _STEP_ROWS
    n_chunks = n_pad // _STEP_ROWS

    i32_planes: list = [None] * recipe.n_i32
    f32_planes: list = [None] * max(recipe.n_f32, 1)
    i32_planes[0] = _prep(live.astype(jnp.int32), n_pad, 0)
    for ci, plan in recipe.cols:
        cv = scan_cols[ci]
        if plan[0] == "dd":
            _, hp, lp, vp, _ = plan
            hi, lo = _dd_planes(cv.data)
            f32_planes[hp] = _prep(hi, n_pad, 0.0)
            f32_planes[lp] = _prep(lo, n_pad, 0.0)
        elif plan[0] == "dict":
            i32_planes[plan[1]] = _prep(cv.data.astype(jnp.int32), n_pad, 0)
        else:
            _, p, vp, _ = plan
            i32_planes[p] = _prep(cv.data.astype(jnp.int32), n_pad, 0)
        if plan[0] != "dict" and plan[-2] >= 0:
            i32_planes[plan[-2]] = _prep(cv.valid.astype(jnp.int32), n_pad, 0)
    if recipe.n_f32 == 0:
        f32_planes[0] = _prep(jnp.zeros((1,), jnp.float32), n_pad, 0.0)

    call = _fused_kernel(recipe, n_chunks, interpret)
    with _enable_x64(False):
        out = call(jnp.stack(i32_planes), jnp.stack(f32_planes))
    totals = (
        out[0].astype(jnp.float64) + out[1].astype(jnp.float64)
    )[:, : recipe.domain]
    return totals


def assemble(recipe: _Recipe, totals):
    """Turn raw stream totals into aggregate output columns.

    Returns (key_codes list of (D,) int32, agg_cols list of tuples shaped
    like relops group_aggregate outputs — (data, valid) or the decimal128
    4-tuple (lo, valid, None, hi) — out_live (D,) bool, n_groups)."""
    D = recipe.domain
    rows_ix = 0  # stream 0 is always the row-mask stream
    for ix, (tag, _) in enumerate(recipe.streams):
        if tag == "rows":
            rows_ix = ix
            break
    rows = jnp.round(totals[rows_ix]).astype(jnp.int64)
    if recipe.keys:
        out_live = rows > 0
        n_groups = jnp.sum(out_live.astype(jnp.int64))
    else:
        out_live = jnp.ones((1,), jnp.bool_)
        n_groups = jnp.ones((), jnp.int64)

    key_codes = []
    lanes = jnp.arange(D, dtype=jnp.int32)
    for _, d, stride in recipe.keys:
        key_codes.append((lanes // jnp.int32(stride)) % jnp.int32(max(d, 1)))

    agg_cols = []
    for spec in recipe.aggs:
        if spec[0] == "count":
            cnt = jnp.round(totals[spec[1]]).astype(jnp.int64)
            agg_cols.append((cnt, None))
            continue
        if spec[0] in ("fsum", "favg"):
            _, hi_s, lo_s, cnt_s = spec
            tot = totals[hi_s] + totals[lo_s]
            cnt = jnp.round(totals[cnt_s])
            valid = cnt > 0
            if spec[0] == "favg":
                data = tot / jnp.maximum(cnt, 1.0)
            else:
                data = tot
            agg_cols.append((data, valid))
            continue
        if spec[0] == "sum":
            _, hi_s, lo_s, cnt_s, shift, wide = spec
            tot = (totals[hi_s] + totals[lo_s]) * float(10 ** shift)
            cnt = jnp.round(totals[cnt_s])
            valid = cnt > 0
            lo = jnp.round(tot).astype(jnp.int64)
            if wide:
                agg_cols.append((lo, valid, None, lo >> jnp.int64(63)))
            else:
                agg_cols.append((lo, valid))
            continue
        _, hi_s, lo_s, cnt_s, shift = spec
        cnt = jnp.round(totals[cnt_s])
        valid = cnt > 0
        tot = (totals[hi_s] + totals[lo_s]) * float(10 ** shift)
        data = jnp.round(tot / jnp.maximum(cnt, 1.0)).astype(jnp.int64)
        agg_cols.append((data, valid))
    return key_codes, agg_cols, out_live, n_groups
