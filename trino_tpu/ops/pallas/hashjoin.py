"""Pallas hash-join probe pass.

The build side of a hash equi-join reuses the group-by build kernel
(ops/pallas/hashagg.build_hash_table): build-side rows insert their encoded
key words into the VMEM table and get dense ids 0..n_build_groups-1 per
DISTINCT build key.  This module is the probe side: every probe row walks
the same linear-probing sequence over the (now read-only) table and either
matches an entry — returning that entry's dense id — or hits an empty slot,
which proves the key is absent (miss, id -1).  One streaming HBM pass over
the probe side, no sort of either side.

The caller (ops/relops.py equi_join) turns the dense id into the legacy
(lo, hi) row-range form by small per-group offset arrays over the build
side, so the existing match-expansion/semi/anti/outer tail is shared
verbatim between the hash and sort paths.

Probe rows that exhaust the probe budget set an `unresolved` flag; together
with the build kernel's overflow flag it diverts the whole join to the sort
path at runtime (the results of an unresolved probe are unusable).  With
the table's <= 0.5 load factor a probe walk is bounded by the longest build
cluster + 1, so the flag only trips when the build pass itself was
borderline.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .hashagg import (
    _CHANNELS,
    _CHUNK_L,
    _CHUNK_S,
    _PROBE_LIMIT,
    _STEP_CHUNKS,
    _STEP_ROWS,
    _enable_x64,
    _gather_channels,
    _prep,
    hash_words,
)
from . import hashagg as _hashagg


@functools.lru_cache(maxsize=64)
def _probe_kernel(n_words: int, T: int, n_chunks: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_half = 2 * n_words

    def kernel(slot_ref, live_ref, planes_ref, table_ref, gid_ref, stats_ref,
               over):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            over[0] = jnp.int32(0)

        for c in range(_STEP_CHUNKS):
            rows = slice(c * _CHUNK_S, (c + 1) * _CHUNK_S)
            sl = slot_ref[rows, :]
            lv = live_ref[rows, :] > 0
            vals = [planes_ref[w, rows, :] for w in range(n_half)]

            off0 = jnp.zeros(sl.shape, jnp.int32)
            resolved0 = ~lv
            gid0 = jnp.full(sl.shape, -1, jnp.int32)

            def _round(carry):
                r, off, resolved, gid = carry
                cur = sl + off
                cur = jnp.where(cur >= T, cur - T, cur)
                active = ~resolved
                g = _gather_channels(table_ref, cur, active, T)
                used = g[..., 0] > 0.5
                eq = used
                for w in range(n_half):
                    eq = eq & (g[..., 2 + w] == vals[w])
                match = active & eq
                gid = jnp.where(match, g[..., 1].astype(jnp.int32), gid)
                # an empty slot on the probe walk proves the key is absent
                resolved = resolved | match | (active & ~used)
                off = off + (active & used & ~eq).astype(jnp.int32)
                return r + 1, off, resolved, gid

            def _unresolved(carry):
                r, _off, resolved, _gid = carry
                return (r < _PROBE_LIMIT) & jnp.any(~resolved)

            _, _, resolved, gid = jax.lax.while_loop(
                _unresolved, _round, (jnp.int32(0), off0, resolved0, gid0)
            )
            over[0] = jnp.maximum(
                over[0], jnp.any(~resolved).astype(jnp.int32)
            )
            gid_ref[rows, :] = gid

        @pl.when(i == n_chunks - 1)
        def _flush():
            r0 = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L), 0)
            c0 = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L), 1)
            # jnp.int32: a weak 0 would pick up an enclosing trace's x64
            stats_ref[...] = jnp.where(
                (r0 == 0) & (c0 == 0), over[0], jnp.int32(0)
            )

    vmem = pltpu.VMEM
    step_s = _STEP_ROWS // _CHUNK_L
    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec(
                (2 * n_words, step_s, _CHUNK_L),
                lambda i: (0, i, 0),
                memory_space=vmem,
            ),
            pl.BlockSpec((_CHANNELS, T), lambda i: (0, 0), memory_space=vmem),
        ],
        out_specs=(
            pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_CHUNK_S, _CHUNK_L), lambda i: (0, 0), memory_space=vmem),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_chunks * step_s, _CHUNK_L), jnp.int32),
            jax.ShapeDtypeStruct((_CHUNK_S, _CHUNK_L), jnp.int32),
        ),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )


def probe_hash_table(words, live, table, *, interpret: bool = False):
    """Look up every live row's key in `table` (from build_hash_table).

    Returns (gid [n] int32 — the matched entry's dense id, -1 for a proven
    miss or a dead row; unresolved bool — some row exhausted the probe
    budget, results must not be used).
    """
    interpret = bool(interpret or _hashagg.INTERPRET)
    n = live.shape[0]
    T = table.shape[1]
    h = hash_words(words, live)
    slot0 = (h % jnp.uint64(T)).astype(jnp.int32)

    n_pad = -(-max(n, 1) // _STEP_ROWS) * _STEP_ROWS
    n_chunks = n_pad // _STEP_ROWS
    planes = []
    for w in words:
        lo, hi = _hashagg._halves_f32(w)
        planes.append(_prep(lo, n_pad, 0.0))
        planes.append(_prep(hi, n_pad, 0.0))
    call = _probe_kernel(len(words), T, n_chunks, interpret)
    with _enable_x64(False):
        gid_b, stats = call(
            _prep(slot0, n_pad, 0),
            _prep(live.astype(jnp.int32), n_pad, 0),
            jnp.stack(planes),
            table.astype(jnp.float32),
        )
    gid = gid_b.reshape(-1)[:n]
    return gid, stats[0, 0] > 0
