"""Fused segmented-reduction Pallas kernel (TPU group-by accumulator).

The reference aggregates through FlatHash — a Swiss-table whose SWAR probe
touches 8 control bytes per key (operator/FlatHash.java:38,59) — then runs
per-function Accumulators over the grouped rows (operator/aggregation/).
Per-row hash probing is the wrong shape for a TPU: the VPU wants 8x128
lanes of straight-line math and the MXU wants matmuls.

This kernel is the TPU-native replacement for the *accumulation* phase:
given a segment id per row (from the dictionary-code fast path or the
sort-based grouping in ops/relops.py), it computes EVERY aggregate of the
GROUP BY in ONE pass over HBM:

- all SUM/COUNT/AVG columns ride the MXU as one-hot matmuls:
  partial[a, g] = sum_k vals[a, k] * (seg[k] == g).  With
  ``precision=HIGHEST`` the bf16x6 decomposition makes integer-valued f32
  products EXACT, so the same matmul path serves both float sums and the
  limb-decomposed exact-integer sums below.
- float (DOUBLE) sums use Kahan/Neumaier compensation across row-chunks:
  TwoSum residuals accumulate in a second f32 buffer, recovering ~2x f32
  mantissa — on TPU hardware (no native f64) this is *more* accurate than
  the jnp.float64 the XLA path pretends to have (it silently computes f32).
- BIGINT sums are bit-exact: the host decomposes each value into signed
  14-bit limbs (f32-exact products; 1024-row chunk partials stay < 2^24),
  the kernel accumulates limbs in int32 with a carry-propagation sweep
  every 32 chunks, and the host recombines limbs in int64.
- MIN/MAX reduce on the VPU against the same one-hot mask, fused into the
  same HBM pass.

Grid = row chunks of 1024 (8 sublanes x 128 lanes); group axis is tiled by
512 lanes so the one-hot stays ~2MB of VMEM; accumulators live in VMEM
scratch across the (sequential) TPU grid.  Practical ceiling is G ≈ 8192
groups — beyond that the n*G one-hot work dominates and the sort-based
path in ops/relops.py wins.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

# jax moved enable_x64 between releases: public on new jax, experimental on
# 0.4.x. Resolve once at import so the kernel call site stays version-agnostic.
_enable_x64 = getattr(jax, "enable_x64", None)
if _enable_x64 is None:  # pragma: no cover - depends on installed jax
    from jax.experimental import enable_x64 as _enable_x64

__all__ = ["SegRed", "fused_segment_reduce", "pallas_segreduce_supported"]

_CHUNK_S = 8  # sublanes per row-chunk
_CHUNK_L = 128  # lanes per row-chunk
_CHUNK = _CHUNK_S * _CHUNK_L  # 1024 rows per exactness unit (one dot)
# Row-chunks processed per GRID STEP (inner unrolled loop).  Each 1024-row
# dot keeps its f32-exact partial-sum envelope; batching 8 of them per step
# amortizes the per-step grid overhead that dominated wall time on small
# queries (a sequential 1170-step grid cost ~100us/step of pure dispatch).
_STEP_CHUNKS = 8
_STEP_ROWS = _CHUNK * _STEP_CHUNKS
_GTILE = 512  # group-axis tile (lanes)
_LIMB_BITS = 14  # 1024 rows * (2^14-1) < 2^24: chunk partials f32-exact
_CARRY_EVERY = 32  # 32 * 2^24 < 2^31: int32 accumulators never overflow
_CARRY_EVERY_STEPS = _CARRY_EVERY // _STEP_CHUNKS
_MAX_GROUPS = 8192  # beyond this the n*G one-hot work loses to sorting

_SUM_EXACT_MAX_F32 = float(1 << 24)  # ints this small sum exactly per chunk

# Test hook: force the Pallas path (in interpreter mode) even on CPU so the
# kernel itself — not just the XLA fallback — is exercised by the suite.
INTERPRET = False


@dataclass(frozen=True)
class SegRed:
    """One requested reduction over the segmented rows.

    op: 'sum' | 'min' | 'max' | 'count'  ('count' == sum of valid 0/1)
    values: [n] array (ignored for 'count' when valid is given)
    valid: optional [n] bool — rows where the argument is non-NULL and live.
    """

    op: str
    values: Optional[jnp.ndarray]
    valid: Optional[jnp.ndarray]


def pallas_segreduce_supported(num_segments: int, backend: Optional[str] = None) -> bool:
    if num_segments > _MAX_GROUPS:
        return False
    return (backend or jax.default_backend()) in ("tpu", "axon")


# --------------------------------------------------------------------------
# kernel factory (cached per static config)
# --------------------------------------------------------------------------


_I32_MAX = np.int32(np.iinfo(np.int32).max)
_I32_MIN = np.int32(np.iinfo(np.int32).min)


@functools.lru_cache(maxsize=64)
def _make_kernel(
    n_chunks: int,
    af: int,  # kahan f32 sum columns
    ai: int,  # exact int32-accumulated columns
    amn: int,  # f32 min columns
    amx: int,  # f32 max columns
    imn: int,  # native-i32 min columns (exact: dates, dict ranks, INTEGER)
    imx: int,  # native-i32 max columns
    g_pad: int,
    carry_groups: tuple,  # ((start, n_limbs), ...) within the ai block
    interpret: bool,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_tiles = g_pad // _GTILE
    gt = _GTILE
    hi = jax.lax.Precision.HIGHEST

    # scratch rows must satisfy the (8, 128) tile constraint
    def pad8(k):
        return max(8, -(-k // 8) * 8)

    counts = (af, ai, amn, amx, imn, imx)

    def kernel(*refs):
        it = iter(refs)
        seg_ref = next(it)
        f_ref, i_ref, mn_ref, mx_ref, imn_ref, imx_ref = (
            next(it) if k else None for k in counts
        )
        of_ref, oi_ref, omn_ref, omx_ref, oimn_ref, oimx_ref = (
            next(it) if k else None for k in counts
        )
        facc = next(it) if af else None
        ferr = next(it) if af else None
        iacc = next(it) if ai else None
        mnacc = next(it) if amn else None
        mxacc = next(it) if amx else None
        imnacc = next(it) if imn else None
        imxacc = next(it) if imx else None

        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            if af:
                facc[:] = jnp.zeros_like(facc)
                ferr[:] = jnp.zeros_like(ferr)
            if ai:
                iacc[:] = jnp.zeros_like(iacc)
            if amn:
                mnacc[:] = jnp.full_like(mnacc, jnp.inf)
            if amx:
                mxacc[:] = jnp.full_like(mxacc, -jnp.inf)
            if imn:
                imnacc[:] = jnp.full_like(imnacc, _I32_MAX)
            if imx:
                imxacc[:] = jnp.full_like(imxacc, _I32_MIN)

        sg_all = seg_ref[:]  # [S * STEP_CHUNKS, L] int32
        fv_all = f_ref[:] if af else None  # [af, S * STEP_CHUNKS, L]
        iv_all = i_ref[:] if ai else None

        def mm_pass(ref, acc, k, mask, sl, rows, reduce, sentinel):
            v = ref[:]
            for a in range(k):
                big = jnp.where(mask, v[a][rows][:, :, None], sentinel)
                cur = reduce(big, axis=(0, 1)).reshape(1, gt)
                merge = jnp.minimum if reduce is jnp.min else jnp.maximum
                acc[a : a + 1, sl] = merge(acc[a : a + 1, sl], cur)

        for t in range(n_tiles):
            base = t * gt
            iota = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK_S, _CHUNK_L, gt), 2)
            sl = slice(base, base + gt)
            # each 1024-row sub-chunk keeps its own dot (f32-exact partial
            # sums); batching them in one grid step amortizes step overhead
            for sc in range(_STEP_CHUNKS):
                rows = slice(sc * _CHUNK_S, (sc + 1) * _CHUNK_S)
                sg = sg_all[rows]
                mask = sg[:, :, None] == (iota + base)
                oh = mask.astype(jnp.float32)

                if af:
                    fvt = jnp.transpose(fv_all[:, rows], (1, 0, 2))  # [S, af, L]
                    part = jax.lax.dot_general(
                        fvt, oh, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32, precision=hi,
                    )  # [S, af, gt]
                    p = jnp.sum(part, axis=0)
                    # Neumaier TwoSum: a + p == s + e exactly
                    a = facc[0:af, sl]
                    s = a + p
                    e = jnp.where(jnp.abs(a) >= jnp.abs(p), (a - s) + p, (p - s) + a)
                    facc[0:af, sl] = s
                    ferr[0:af, sl] += e

                if ai:
                    ivt = jnp.transpose(iv_all[:, rows], (1, 0, 2))
                    part = jax.lax.dot_general(
                        ivt, oh, (((2,), (1,)), ((0,), (0,))),
                        preferred_element_type=jnp.float32, precision=hi,
                    )
                    iacc[0:ai, sl] += jnp.sum(part, axis=0).astype(jnp.int32)

                if amn:
                    mm_pass(mn_ref, mnacc, amn, mask, sl, rows, jnp.min, jnp.float32(jnp.inf))
                if amx:
                    mm_pass(mx_ref, mxacc, amx, mask, sl, rows, jnp.max, jnp.float32(-jnp.inf))
                if imn:
                    mm_pass(imn_ref, imnacc, imn, mask, sl, rows, jnp.min, _I32_MAX)
                if imx:
                    mm_pass(imx_ref, imxacc, imx, mask, sl, rows, jnp.max, _I32_MIN)

        if carry_groups:

            @pl.when((i & (_CARRY_EVERY_STEPS - 1)) == (_CARRY_EVERY_STEPS - 1))
            def _carry():
                for (start, nl) in carry_groups:
                    for l in range(nl - 1):
                        row = iacc[start + l : start + l + 1, :]
                        c = row >> _LIMB_BITS
                        iacc[start + l : start + l + 1, :] = row - (c << _LIMB_BITS)
                        iacc[start + l + 1 : start + l + 2, :] += c

        @pl.when(i == pl.num_programs(0) - 1)
        def _fin():
            if af:
                # acc and err are returned separately: adding them in f32
                # would re-round and discard the compensation — the host
                # combines them in f64.
                of_ref[0:af, :] = facc[0:af, :]
                of_ref[af : 2 * af, :] = ferr[0:af, :]
            if ai:
                oi_ref[:] = iacc[0:ai, :]
            if amn:
                omn_ref[:] = mnacc[0:amn, :]
            if amx:
                omx_ref[:] = mxacc[0:amx, :]
            if imn:
                oimn_ref[:] = imnacc[0:imn, :]
            if imx:
                oimx_ref[:] = imxacc[0:imx, :]

    vmem = pltpu.VMEM
    step_s = _CHUNK_S * _STEP_CHUNKS
    in_specs = [pl.BlockSpec((step_s, _CHUNK_L), lambda i: (i, 0), memory_space=vmem)]
    out_specs, out_shape, scratch = [], [], []
    for k in counts:
        if k:
            in_specs.append(
                pl.BlockSpec((k, step_s, _CHUNK_L), lambda i: (0, i, 0), memory_space=vmem)
            )
    out_cfg = (
        (2 * af, jnp.float32),
        (ai, jnp.int32),
        (amn, jnp.float32),
        (amx, jnp.float32),
        (imn, jnp.int32),
        (imx, jnp.int32),
    )
    for k, dt in out_cfg:
        if k:
            out_specs.append(pl.BlockSpec((k, g_pad), lambda i: (0, 0), memory_space=vmem))
            out_shape.append(jax.ShapeDtypeStruct((k, g_pad), dt))
    if af:
        scratch += [pltpu.VMEM((pad8(af), g_pad), jnp.float32)] * 2
    if ai:
        scratch.append(pltpu.VMEM((pad8(ai), g_pad), jnp.int32))
    for k, dt in ((amn, jnp.float32), (amx, jnp.float32), (imn, jnp.int32), (imx, jnp.int32)):
        if k:
            scratch.append(pltpu.VMEM((pad8(k), g_pad), dt))

    return pl.pallas_call(
        kernel,
        grid=(n_chunks,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        scratch_shapes=scratch,
        interpret=interpret,
    )


def _limbs_for(dtype) -> int:
    if dtype in (jnp.int64, np.int64):
        return 5  # 70 bits
    return 3  # int32/date: 42 bits


def _prep_rows(arr: jnp.ndarray, n_pad: int, fill) -> jnp.ndarray:
    out = jnp.pad(arr, (0, n_pad - arr.shape[0]), constant_values=fill)
    return out.reshape(n_pad // _CHUNK_L, _CHUNK_L)


def fused_segment_reduce(
    seg: jnp.ndarray,
    reds: Sequence[SegRed],
    num_segments: int,
    *,
    interpret: bool = False,
    force_pallas: bool = False,
    sorted_segments: bool = False,
    boundaries: Optional[tuple] = None,
) -> list[jnp.ndarray]:
    """Compute every requested reduction in one fused pass.

    seg: [n] int32 segment ids in [0, num_segments); rows with seg >=
    num_segments (the caller's dead-lane convention) fall into padding
    groups and are sliced off.

    Returns one array [num_segments] per red:
      sum of floats  -> float64 (Kahan-compensated on the Pallas path)
      sum of ints    -> int64, bit-exact
      count          -> int64
      min/max        -> the input dtype
    Empty groups yield 0 for sum/count and +inf/-inf (or dtype extrema)
    for min/max; the caller masks them with its count column.
    """
    n = seg.shape[0]
    G = num_segments
    interpret = interpret or INTERPRET
    use_pallas = force_pallas or interpret or pallas_segreduce_supported(G)
    if not use_pallas:
        if sorted_segments:
            # high-cardinality group-by over the sort-based path: rows arrive
            # ordered by segment, so boundary gathers + cumsum diffs beat the
            # scatter-based segment ops (XLA scatter serializes on TPU — at
            # TPC-H SF1 Q3's ~1M groups the scatter fallback cost ~36s of
            # device time; this path is bandwidth-bound)
            return _sorted_fallback(seg, reds, G, boundaries)
        return _xla_fallback(seg, reds, G)

    g_pad = max(_GTILE, -(-(G + 1) // _GTILE) * _GTILE)
    n_pad = -(-n // _STEP_ROWS) * _STEP_ROWS
    n_chunks = n_pad // _STEP_ROWS  # grid steps (each = _STEP_CHUNKS dots)

    seg_c = jnp.clip(seg.astype(jnp.int32), 0, g_pad - 1)
    seg_c = jnp.where(seg.astype(jnp.int32) >= G, g_pad - 1, seg_c)
    seg2 = _prep_rows(seg_c, n_pad, g_pad - 1)

    f_cols: list[jnp.ndarray] = []  # kahan f32 sum columns
    i_cols: list[jnp.ndarray] = []  # exact i32-accumulated columns
    mn_cols: list[jnp.ndarray] = []  # f32 min
    mx_cols: list[jnp.ndarray] = []  # f32 max
    imn_cols: list[jnp.ndarray] = []  # exact i32 min
    imx_cols: list[jnp.ndarray] = []  # exact i32 max
    carry_groups: list[tuple[int, int]] = []
    plan: list[tuple] = []  # (kind, payload) per red, to unpack outputs
    xla_reds: list[tuple[int, SegRed]] = []  # kernel-ineligible (int64 min/max)

    def _i32_ok(dtype) -> bool:
        return dtype in (jnp.int32, np.dtype(np.int32), jnp.int16, jnp.int8,
                         np.dtype(np.int16), np.dtype(np.int8), jnp.bool_,
                         np.dtype(np.bool_))

    for ri, r in enumerate(reds):
        if r.op == "count":
            v = (
                r.valid.astype(jnp.float32)
                if r.valid is not None
                else jnp.ones((n,), jnp.float32)
            )
            plan.append(("int", len(i_cols), 1, jnp.int64))
            i_cols.append(v)
        elif r.op == "sum":
            vals = r.values
            valid = r.valid
            if jnp.issubdtype(vals.dtype, jnp.integer) or vals.dtype == jnp.bool_:
                nl = _limbs_for(vals.dtype)
                v64 = vals.astype(jnp.int64)
                if valid is not None:
                    v64 = jnp.where(valid, v64, 0)
                sign = jnp.where(v64 < 0, jnp.int64(-1), jnp.int64(1))
                mag = jnp.abs(v64)
                start = len(i_cols)
                for l in range(nl):
                    limb = ((mag >> (_LIMB_BITS * l)) & ((1 << _LIMB_BITS) - 1)) * sign
                    i_cols.append(limb.astype(jnp.float32))
                if nl > 1:
                    carry_groups.append((start, nl))
                plan.append(("limbs", start, nl, jnp.int64))
            else:
                v = vals.astype(jnp.float32)
                if valid is not None:
                    v = jnp.where(valid, v, jnp.float32(0))
                plan.append(("float", len(f_cols), 1, jnp.float64))
                f_cols.append(v)
        elif r.op in ("min", "max"):
            vals = r.values
            valid = r.valid
            if jnp.issubdtype(vals.dtype, jnp.floating):
                v = vals.astype(jnp.float32)
                sent = jnp.float32(jnp.inf if r.op == "min" else -jnp.inf)
                if valid is not None:
                    v = jnp.where(valid, v, sent)
                if r.op == "min":
                    plan.append(("min", len(mn_cols), 1, vals.dtype))
                    mn_cols.append(v)
                else:
                    plan.append(("max", len(mx_cols), 1, vals.dtype))
                    mx_cols.append(v)
            elif _i32_ok(vals.dtype):
                v = vals.astype(jnp.int32)
                sent = _I32_MAX if r.op == "min" else _I32_MIN
                if valid is not None:
                    v = jnp.where(valid, v, sent)
                if r.op == "min":
                    plan.append(("imin", len(imn_cols), 1, vals.dtype))
                    imn_cols.append(v)
                else:
                    plan.append(("imax", len(imx_cols), 1, vals.dtype))
                    imx_cols.append(v)
            else:
                # int64 min/max: no native 64-bit lanes in the kernel and an
                # f32 round-trip would corrupt values above 2^24 — use the
                # exact XLA path for just this reduction.
                plan.append(("xla", len(xla_reds), 1, vals.dtype))
                xla_reds.append((ri, r))
        else:
            raise ValueError(f"unknown reduction {r.op}")

    counts = (
        len(f_cols), len(i_cols), len(mn_cols), len(mx_cols),
        len(imn_cols), len(imx_cols),
    )
    af, ai, amn, amx, imn, imx = counts

    def stack(cols, fill):
        return jnp.stack([_prep_rows(c, n_pad, fill) for c in cols])

    args = [seg2]
    for cols, fill in (
        (f_cols, 0.0), (i_cols, 0.0), (mn_cols, np.float32(np.inf)),
        (mx_cols, np.float32(-np.inf)), (imn_cols, _I32_MAX), (imx_cols, _I32_MIN),
    ):
        if cols:
            args.append(stack(cols, fill))

    results: tuple = ()
    if any(counts):
        call = _make_kernel(
            n_chunks, af, ai, amn, amx, imn, imx, g_pad, tuple(carry_groups), interpret
        )
        # Mosaic requires i32 grid indices; under the engine's global x64 mode
        # the BlockSpec index maps trace to i64 and fail to legalize.  All
        # kernel operands/outputs are f32/i32, so scoped-disabling x64 is sound.
        with _enable_x64(False):
            results = call(*args)
        if not isinstance(results, (tuple, list)):
            results = (results,)
    it = iter(results)
    of = next(it) if af else None
    oi = next(it) if ai else None
    omn = next(it) if amn else None
    omx = next(it) if amx else None
    oimn = next(it) if imn else None
    oimx = next(it) if imx else None
    xla_out = _xla_fallback(seg, [r for _, r in xla_reds], G) if xla_reds else []

    out: list[jnp.ndarray] = []
    for kind, idx, width, dtype in plan:
        if kind == "float":
            out.append(
                of[idx, :G].astype(jnp.float64) + of[af + idx, :G].astype(jnp.float64)
            )
        elif kind == "int":
            out.append(oi[idx, :G].astype(jnp.int64))
        elif kind == "limbs":
            total = jnp.zeros((G,), jnp.int64)
            for l in range(width):
                total = total + (
                    oi[idx + l, :G].astype(jnp.int64) << (_LIMB_BITS * l)
                )
            out.append(total)
        elif kind == "min":
            out.append(omn[idx, :G].astype(dtype))
        elif kind == "max":
            out.append(omx[idx, :G].astype(dtype))
        elif kind == "imin":
            out.append(oimn[idx, :G].astype(dtype))
        elif kind == "imax":
            out.append(oimx[idx, :G].astype(dtype))
        else:  # xla
            out.append(xla_out[idx])
    return out


# --------------------------------------------------------------------------
# XLA fallback (CPU tests / G beyond the one-hot ceiling)
# --------------------------------------------------------------------------


def _seg_scan_extreme(vals, flag, is_min):
    """Per-row running min/max within each contiguous segment (flag marks
    segment starts).  The segmented-combine operator is associative, so the
    whole pass is one log-depth associative_scan — no scatter."""

    def op(a, b):
        va, fa = a
        vb, fb = b
        combined = jnp.minimum(va, vb) if is_min else jnp.maximum(va, vb)
        return jnp.where(fb, vb, combined), fa | fb

    pv, _ = jax.lax.associative_scan(op, (vals, flag))
    return pv


def _sorted_fallback(seg, reds, G, boundaries=None):
    """Segment reductions for NONDECREASING seg (the sort-based group-by's
    output order): sums/counts via diffs of one inclusive cumsum at segment
    boundaries, min/max via a segmented associative scan read at segment
    ends.  Everything is gathers + scans — the shape TPUs like.
    `boundaries` = precomputed (starts, ends) searchsorted results (the
    caller shares one boundary pass across key gathers and reductions)."""
    n = seg.shape[0]
    seg_c = jnp.minimum(seg.astype(jnp.int32), G)
    if boundaries is not None:
        starts, ends = boundaries
    else:
        from ..relops import searchsorted_tpu

        gids = jnp.arange(G, dtype=jnp.int32)
        starts = searchsorted_tpu(seg_c, gids, side="left")
        ends = searchsorted_tpu(seg_c, gids, side="right")
    nonempty = ends > starts
    ends_i = jnp.clip(ends - 1, 0, max(n - 1, 0))
    flag = (
        jnp.concatenate([jnp.ones((1,), jnp.bool_), seg_c[1:] != seg_c[:-1]])
        if n > 0
        else jnp.ones((0,), jnp.bool_)
    )

    def boundary_sum(acc):
        ce = jnp.concatenate([jnp.zeros((1,), acc.dtype), jnp.cumsum(acc)])
        zero = jnp.zeros((), acc.dtype)
        return jnp.where(nonempty, jnp.take(ce, ends) - jnp.take(ce, starts), zero)

    out = []
    for r in reds:
        if r.op == "count":
            v = (
                r.valid.astype(jnp.int64)
                if r.valid is not None
                else jnp.ones((n,), jnp.int64)
            )
            out.append(boundary_sum(v))
        elif r.op == "sum":
            vals = r.values
            if jnp.issubdtype(vals.dtype, jnp.integer) or vals.dtype == jnp.bool_:
                acc = vals.astype(jnp.int64)
            else:
                acc = vals.astype(jnp.float64)
            if r.valid is not None:
                acc = jnp.where(r.valid, acc, jnp.zeros_like(acc))
            out.append(boundary_sum(acc))
        elif r.op in ("min", "max"):
            sel = r.values
            if jnp.issubdtype(sel.dtype, jnp.floating):
                sent = jnp.asarray(jnp.inf if r.op == "min" else -jnp.inf, sel.dtype)
            else:
                info = jnp.iinfo(sel.dtype)
                sent = jnp.asarray(info.max if r.op == "min" else info.min, sel.dtype)
            if r.valid is not None:
                sel = jnp.where(r.valid, sel, sent)
            run = _seg_scan_extreme(sel, flag, r.op == "min")
            out.append(jnp.where(nonempty, jnp.take(run, ends_i), sent))
        else:
            raise NotImplementedError(r.op)
    return out


def _xla_fallback(seg, reds, G):
    n = seg.shape[0]
    num = G + 1  # overflow bucket for dead lanes
    seg_c = jnp.minimum(seg.astype(jnp.int32), G)
    out = []
    for r in reds:
        if r.op == "count":
            v = (
                r.valid.astype(jnp.int64)
                if r.valid is not None
                else jnp.ones((n,), jnp.int64)
            )
            out.append(jax.ops.segment_sum(v, seg_c, num_segments=num)[:G])
        elif r.op == "sum":
            vals = r.values
            if jnp.issubdtype(vals.dtype, jnp.integer) or vals.dtype == jnp.bool_:
                acc = vals.astype(jnp.int64)
            else:
                acc = vals.astype(jnp.float64)
            if r.valid is not None:
                acc = jnp.where(r.valid, acc, jnp.zeros_like(acc))
            out.append(jax.ops.segment_sum(acc, seg_c, num_segments=num)[:G])
        elif r.op == "min":
            sel = r.values
            if jnp.issubdtype(sel.dtype, jnp.floating):
                sent = jnp.asarray(jnp.inf, sel.dtype)
            else:
                sent = jnp.iinfo(sel.dtype).max
            if r.valid is not None:
                sel = jnp.where(r.valid, sel, sent)
            out.append(jax.ops.segment_min(sel, seg_c, num_segments=num)[:G])
        elif r.op == "max":
            sel = r.values
            if jnp.issubdtype(sel.dtype, jnp.floating):
                sent = jnp.asarray(-jnp.inf, sel.dtype)
            else:
                sent = jnp.iinfo(sel.dtype).min
            if r.valid is not None:
                sel = jnp.where(r.valid, sel, sent)
            out.append(jax.ops.segment_max(sel, seg_c, num_segments=num)[:G])
        else:
            raise ValueError(r.op)
    return out
