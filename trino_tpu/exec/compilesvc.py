"""Background compile service: the compile-cliff resilience plane.

The engine's worst failure mode is not a dead worker but a stalled
compiler — a novel jit signature can wall its query for minutes (q03:
36s -> 260-407s across bench rounds).  This module takes XLA compilation
off the query's critical path:

  - CompileService.obtain() runs every build on a small worker pool and
    DEDUPLICATES per signature key: N concurrent queries with the same
    ``{Root}+{N}n#{planhash}@{capshash}`` signature trigger exactly ONE
    compile (no compile storms); joiners wait on the same job.
  - A caller-supplied ``wait_budget_s`` bounds how long a query blocks;
    past it the outcome is ``pending`` and the caller executes via its
    fallback path while the compile finishes in the background.  The
    finished program lands in a bounded done-map and swaps in on the
    signature's next execution.
  - A hard ``deadline_s`` (measured from job creation) turns a compile
    that will never finish into a typed ``timeout`` outcome — never a
    hung query.  The job thread itself cannot be killed, but every
    waiter is released and a late completion still populates the
    done-map.
  - A per-signature circuit breaker (exponential open window riding
    runtime/failure.py's Backoff schedule) stops retry churn on
    poisoned signatures: after ``threshold`` consecutive compile
    failures the signature pins its fallback path, with a single
    half-open probe once the window elapses.

Reference analogue: the reference engine's interpretive fallback
operators next to its bytecode compiler — an expression whose
compilation fails or is too costly runs interpreted, and the compiled
form swaps in when ready (PAPER.md).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..runtime.failure import Backoff
from ..utils import flightrecorder as _fr
from ..utils.metrics import GLOBAL as _METRICS

__all__ = [
    "CompileService", "SignatureBreaker", "Outcome", "SERVICE",
    "FALLBACKS",
]

COMPILE_INFLIGHT = _METRICS.gauge(
    "trino_tpu_compile_inflight",
    "Background fragment compiles currently running or queued in the"
    " compile service",
)
COMPILE_TIMEOUTS = _METRICS.counter(
    "trino_tpu_compile_timeouts_total",
    "Compiles that exceeded their hard compile_deadline_s (the query"
    " proceeded via fallback with a typed COMPILE_TIMEOUT entry)",
)
COMPILE_DEDUP = _METRICS.counter(
    "trino_tpu_compile_dedup_total",
    "obtain() calls that joined an already-in-flight compile for the"
    " same signature instead of starting their own (storm admission)",
)
# incremented by the EXECUTORS (exec/compiler.py) when they actually run
# the fallback path; lives here so service and executor share one child
FALLBACKS = _METRICS.counter(
    "trino_tpu_fallback_executions_total",
    "Query executions that ran the eager/uncompiled fallback path"
    " instead of a compiled program, by reason (compile_wait: budget"
    " exhausted; compile_timeout: deadline exceeded; compile_error:"
    " compile raised; breaker_open: poisoned signature pinned)",
    ("reason",),
)

# breaker states (per signature, not per worker)
CLOSED = "CLOSED"
OPEN = "OPEN"
HALF_OPEN = "HALF_OPEN"


class SignatureBreaker:
    """Per-signature compile circuit breaker.

    CLOSED --`threshold` consecutive failures--> OPEN (no new compile
    attempts; callers fall back immediately).  Once the open window —
    an exponential schedule that grows with every further failure —
    elapses, allow() grants exactly ONE half-open probe; its success
    fully closes the breaker, its failure re-opens with a longer
    window.  Deterministic (jitter=0): chaos tests replay exactly.
    """

    def __init__(
        self,
        threshold: int = 3,
        min_open_s: float = 0.5,
        max_open_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.threshold = threshold
        self._clock = clock
        self._lock = threading.Lock()
        self._min_open_s = min_open_s
        self._max_open_s = max_open_s
        self._sigs: dict[str, dict] = {}

    def _get(self, sig: str) -> dict:
        e = self._sigs.get(sig)
        if e is None:
            e = self._sigs[sig] = {
                "state": CLOSED,
                "failures": 0,
                "opened_at": 0.0,
                "backoff": Backoff(
                    min_delay=self._min_open_s,
                    max_delay=self._max_open_s,
                    max_elapsed=float("inf"),
                    jitter=0.0,
                ),
            }
        return e

    def allow(self, sig: str) -> bool:
        """May a NEW compile attempt start for this signature?  CLOSED:
        yes.  OPEN: only once the open window elapsed, and then exactly
        one probe (state moves to HALF_OPEN so concurrent callers keep
        falling back until the probe resolves)."""
        with self._lock:
            e = self._get(sig)
            if e["state"] == CLOSED:
                return True
            if e["state"] == HALF_OPEN:
                return False  # probe outstanding
            window = e["backoff"].delay()
            if (self._clock() - e["opened_at"]) >= window:
                e["state"] = HALF_OPEN
                return True
            return False

    def record_failure(self, sig: str) -> None:
        with self._lock:
            e = self._get(sig)
            e["failures"] += 1
            e["backoff"].failure()
            if e["state"] == HALF_OPEN or e["failures"] >= self.threshold:
                e["state"] = OPEN
                e["opened_at"] = self._clock()

    def record_success(self, sig: str) -> None:
        with self._lock:
            e = self._get(sig)
            e["state"] = CLOSED
            e["failures"] = 0
            e["backoff"].success()

    def state(self, sig: str) -> str:
        with self._lock:
            return self._get(sig)["state"]

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            return {
                s: {"state": e["state"], "failures": e["failures"]}
                for s, e in self._sigs.items()
            }


@dataclass
class Outcome:
    """Result of CompileService.obtain().

    status: ready      — compiled program available (result holds it)
            pending    — wait budget exhausted; compile continues in the
                         background (fall back, swap in next execution)
            timeout    — hard deadline exceeded (typed COMPILE_TIMEOUT)
            error      — the build raised (error holds the exception)
            breaker_open — poisoned signature, no attempt started
    reason: the fallback-reason label for every non-ready status
    fresh:  True when THIS call created the job and waited it to
            completion (the compile wall belongs to this query).
    """

    status: str
    reason: Optional[str] = None
    result: Any = None
    error: Optional[BaseException] = None
    waited_s: float = 0.0
    fresh: bool = False


@dataclass
class _Job:
    key: Any
    sig: str
    created_at: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    timed_out: bool = False


class CompileService:
    """Worker-pool compile service with per-key in-flight dedup and a
    bounded done-map of finished programs awaiting swap-in.

    Keys must capture everything a compiled program is specialized on:
    the executor passes (signature, stats-mode, input treedef, avals).
    The treedef hashes host-side Dictionary objects BY IDENTITY
    (data/page.py), so a program never swaps in against inputs whose
    trace-time dictionaries differ — correctness bounds reuse, not the
    other way around.
    """

    _DONE_MAX = 256  # finished programs awaiting swap-in (LRU)

    def __init__(
        self,
        max_workers: Optional[int] = None,
        breaker: Optional[SignatureBreaker] = None,
    ):
        if max_workers is None:
            max_workers = int(
                os.environ.get("TRINO_TPU_COMPILE_THREADS")
                or min(8, max(2, (os.cpu_count() or 4) // 2))
            )
        self._max_workers = max(1, max_workers)
        self._pool = None  # created lazily (import-time thread pools leak)
        self._lock = threading.Lock()
        self._inflight: dict[Any, _Job] = {}
        self._done: OrderedDict[Any, Any] = OrderedDict()
        self.breaker = breaker or SignatureBreaker()
        self.builds = 0  # total build() invocations (dedup observability)

    def _ensure_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="compile-svc",
            )
        return self._pool

    # ------------------------------------------------------------- obtain
    def obtain(
        self,
        key: Any,
        sig: str,
        build: Callable[[], Any],
        wait_budget_s: Optional[float] = None,
        deadline_s: float = 0.0,
        injector=None,
        fault_task_id: str = "local",
    ) -> Outcome:
        """Get the compiled program for `key`, compiling via `build` on
        the pool if needed.  wait_budget_s None == wait until done (or
        deadline); deadline_s 0 == no deadline.  `injector` is the
        worker's FaultInjector: COMPILE_SLOW / COMPILE_FAIL faults fire
        inside the build job (runtime/failure.py)."""
        t0 = time.monotonic()
        with self._lock:
            hit = self._done.get(key)
            if hit is not None:
                self._done.move_to_end(key)
                return Outcome("ready", result=hit)
            job = self._inflight.get(key)
            fresh = job is None
            if fresh:
                if not self.breaker.allow(sig):
                    _fr.record(
                        "compile_fallback", node="compilesvc",
                        task_id=fault_task_id, signature=sig,
                        reason="breaker_open",
                    )
                    return Outcome("breaker_open", reason="breaker_open")
                job = _Job(key=key, sig=sig, created_at=t0)
                self._inflight[key] = job
                COMPILE_INFLIGHT.set(len(self._inflight))
                _fr.record(
                    "compile_start", node="compilesvc",
                    task_id=fault_task_id, signature=sig,
                )
                self._ensure_pool().submit(
                    self._run_job, job, build, injector, fault_task_id
                )
            else:
                COMPILE_DEDUP.inc()

        budget_at = None if wait_budget_s is None else t0 + wait_budget_s
        deadline_at = (
            job.created_at + deadline_s if deadline_s and deadline_s > 0
            else None
        )
        while True:
            now = time.monotonic()
            waits = [w for w in (
                None if budget_at is None else budget_at - now,
                None if deadline_at is None else deadline_at - now,
            ) if w is not None]
            if waits:
                job.done.wait(timeout=max(min(waits), 0.0))
            else:
                job.done.wait()
            waited = time.monotonic() - t0
            if job.done.is_set():
                if job.error is not None:
                    return Outcome(
                        "error", reason="compile_error", error=job.error,
                        waited_s=waited, fresh=fresh,
                    )
                return Outcome(
                    "ready", result=job.result, waited_s=waited, fresh=fresh
                )
            now = time.monotonic()
            if deadline_at is not None and now >= deadline_at:
                self._mark_timeout(job)
                _fr.record(
                    "compile_fallback", node="compilesvc",
                    task_id=fault_task_id, signature=sig,
                    reason="compile_timeout", waited_s=round(waited, 3),
                )
                return Outcome(
                    "timeout", reason="compile_timeout", waited_s=waited
                )
            if budget_at is not None and now >= budget_at:
                _fr.record(
                    "compile_fallback", node="compilesvc",
                    task_id=fault_task_id, signature=sig,
                    reason="compile_wait", waited_s=round(waited, 3),
                )
                return Outcome(
                    "pending", reason="compile_wait", waited_s=waited
                )

    def warm(self, key: Any, sig: str, build: Callable[[], Any]) -> bool:
        """Fire-and-forget compile (startup cache warming): schedule the
        build unless the key is already done/in-flight or the signature's
        breaker is open.  True == a job was scheduled."""
        with self._lock:
            if key in self._done or key in self._inflight:
                return False
            if not self.breaker.allow(sig):
                return False
            job = _Job(key=key, sig=sig, created_at=time.monotonic())
            self._inflight[key] = job
            COMPILE_INFLIGHT.set(len(self._inflight))
            self._ensure_pool().submit(self._run_job, job, build, None, "warm")
        return True

    # ------------------------------------------------------------ internals
    def _mark_timeout(self, job: _Job) -> None:
        """First waiter past the deadline records the timeout exactly once
        (metric + profiler ledger + breaker failure); later waiters and a
        late job completion see `timed_out` and skip re-recording."""
        from ..utils.profiler import PROFILER

        with self._lock:
            if job.timed_out or job.done.is_set():
                return
            job.timed_out = True
        COMPILE_TIMEOUTS.inc()
        PROFILER.record_compile_timeout(job.sig)
        self.breaker.record_failure(job.sig)

    def _run_job(self, job: _Job, build, injector, fault_task_id) -> None:
        try:
            with self._lock:
                self.builds += 1
            if injector is not None:
                injector.compile_fault(fault_task_id)
            job.result = build()
        except BaseException as exc:
            job.error = exc
            if not job.timed_out:
                self.breaker.record_failure(job.sig)
            _fr.record(
                "compile_error", node="compilesvc", task_id=fault_task_id,
                signature=job.sig, error=str(exc)[:200],
            )
        else:
            with self._lock:
                self._done[job.key] = job.result
                self._done.move_to_end(job.key)
                while len(self._done) > self._DONE_MAX:
                    self._done.popitem(last=False)
            if not job.timed_out:
                self.breaker.record_success(job.sig)
            _fr.record(
                "compile_done", node="compilesvc", task_id=fault_task_id,
                signature=job.sig,
                compile_s=round(time.monotonic() - job.created_at, 3),
            )
        finally:
            with self._lock:
                self._inflight.pop(job.key, None)
                COMPILE_INFLIGHT.set(len(self._inflight))
            job.done.set()

    def drain(self, timeout_s: float = 30.0) -> None:
        """Wait for every in-flight compile to settle (tests, shutdown)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                jobs = list(self._inflight.values())
            if not jobs:
                return
            jobs[0].done.wait(timeout=max(deadline - time.monotonic(), 0.0))

    def reset(self) -> None:
        """Forget done programs and breaker history (tests)."""
        with self._lock:
            self._done.clear()
            self.builds = 0
        self.breaker = SignatureBreaker(
            threshold=self.breaker.threshold,
            min_open_s=self.breaker._min_open_s,
            max_open_s=self.breaker._max_open_s,
            clock=self.breaker._clock,
        )

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": len(self._inflight),
                "done": len(self._done),
                "builds": self.builds,
                "breakers": self.breaker.snapshot(),
            }


# process-global service: every LocalExecutor in the process shares one
# pool and one dedup map, so concurrent worker tasks with the same
# signature storm-collapse onto a single compile
SERVICE = CompileService()
