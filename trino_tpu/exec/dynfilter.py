"""Dynamic filtering: build-side key domains narrow probe-side scans.

Reference: server/DynamicFilterService.java:103 collects per-driver build
domains (DynamicFilterSourceOperator), the coordinator narrows probe scans
(createDynamicFilter:272) before and during execution.

TPU-native placement: the consumer task that executes a partitioned join
has ALREADY fetched its build-side pages (RemoteSource buffers) before its
probe-side scan uploads to HBM — so the natural filter point is host-side,
between fetch and upload: compute [min, max] of the build join keys from
the fetched numpy columns and mask the probe scan's rows before they cost
upload bandwidth or kernel lanes.  No extra protocol, no coordinator round
trip — the information is already local at exactly the right moment.

Applies to inner and semi joins (an outer probe row must survive even when
unmatched, so left joins never prune).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..data.page import Page
from ..plan.ir import FieldRef
from ..plan.nodes import Filter, Join, PlanNode, RemoteSource, TableScan

__all__ = ["ScanFilter", "collect_dynamic_filters"]


@dataclass(frozen=True)
class ScanFilter:
    """Domain filter on one scan column (reference: TupleDomain of a
    dynamic filter): numeric [min, max] range, or — for dictionary-coded
    string keys — an explicit sorted value set (the reference's discrete
    TupleDomain; TPC-DS star joins key on strings/surrogates, so range
    domains alone leave them unpruned)."""

    column: str
    min: float = 0.0
    max: float = 0.0
    values: Optional[tuple] = None  # sorted distinct values; None == range


# build sides with more distinct strings than this skip the set domain (the
# reference's dynamic-filtering max-distinct limit); membership tests on the
# host scale with the set
_MAX_SET_VALUES = 100_000


def _scan_under(node: PlanNode) -> Optional[TableScan]:
    """The probe-side TableScan when the path preserves column indexes
    (Filter keeps its child's layout; anything else breaks the mapping)."""
    while isinstance(node, Filter):
        node = node.child
    return node if isinstance(node, TableScan) else None


def collect_dynamic_filters(
    root: PlanNode, remote_pages: dict[int, Page]
) -> dict[int, tuple["ScanFilter", ...]]:
    """-> {scan_node_id: (ScanFilter, ...)} for this fragment, keyed by the
    executor's preorder node numbering — a filter applies ONLY to the scan
    site under its join, never to other scans of the same table elsewhere
    in the fragment.

    Finds inner/semi joins whose build side is a RemoteSource with already-
    fetched pages and whose probe key maps straight to a scan column, then
    derives [min, max] of the live, valid build keys.
    """
    from .compiler import _node_ids

    ids = {id(n): nid for nid, n in _node_ids(root).items()}
    out: dict[int, list[ScanFilter]] = {}

    def visit(node: PlanNode) -> None:
        for c in node.children:
            visit(c)
        if not isinstance(node, Join) or node.kind not in ("inner", "semi"):
            return
        if not isinstance(node.right, RemoteSource):
            return
        page = remote_pages.get(node.right.fragment_id)
        if page is None:
            return
        scan = _scan_under(node.left)
        if scan is None or id(scan) not in ids:
            return
        live = np.asarray(page.live_mask())
        for lk, rk in zip(node.left_keys, node.right_keys):
            if not (isinstance(lk, FieldRef) and isinstance(rk, FieldRef)):
                continue
            if lk.index >= len(scan.column_names):
                continue
            col = page.columns[rk.index]
            if col.type.np_dtype == np.dtype(np.bool_):
                continue
            keep = live.copy()
            if col.valid is not None:
                keep &= np.asarray(col.valid)
            data = np.asarray(col.data)[keep]
            if len(data) == 0:
                continue
            if col.type.is_string:
                # dictionary-set domain: live build codes -> distinct values
                if col.dictionary is None or len(col.dictionary) > _MAX_SET_VALUES:
                    continue
                codes = np.unique(data)
                codes = codes[(codes >= 0) & (codes < len(col.dictionary))]
                values = tuple(sorted(col.dictionary.values[codes]))
                out.setdefault(ids[id(scan)], []).append(
                    ScanFilter(scan.column_names[lk.index], values=values)
                )
            else:
                out.setdefault(ids[id(scan)], []).append(
                    ScanFilter(
                        scan.column_names[lk.index],
                        float(data.min()),
                        float(data.max()),
                    )
                )

    visit(root)
    return {nid: tuple(fs) for nid, fs in out.items()}
