"""SPMD executor: one jitted program over a jax.sharding.Mesh.

The reference's distributed runtime is coordinator-driven task orchestration:
PlanFragmenter cuts the plan at exchanges, the scheduler posts fragments to
workers over HTTP, and pages stream between tasks
(execution/scheduler/PipelinedQueryScheduler.java:164, server/remotetask/
HttpRemoteTask.java:135).  On a TPU slice the natural shape is inverted:
ONE SPMD program runs the whole multi-fragment plan on every chip under
shard_map; fragment boundaries become XLA collectives over ICI (parallel/
exchange.py) instead of HTTP hops, so multi-stage joins never leave HBM.

Scans are split across devices by row range — the reference's
SOURCE_DISTRIBUTION split scheduling (SystemPartitioningHandle.java:47,
NodeScheduler.java:51) with splits pinned round-robin.

The host keeps the reference's coordinator responsibilities that remain:
capacity planning (stats), the overflow-retry loop, and result fetch.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..connectors.spi import CatalogManager
from ..data.page import Column, Page
from ..parallel.exchange import AXIS
from ..plan.nodes import Exchange, Join, PlanNode, TableScan, TopN
from .compiler import (
    _EAGER_SIZING_LIMIT, LocalExecutor, _child_ids, _node_ids, _pow2, _trace_plan,
)

__all__ = ["SpmdExecutor"]


class SpmdExecutor(LocalExecutor):
    def __init__(
        self,
        catalogs: CatalogManager,
        default_catalog: str = "tpch",
        devices: Optional[Sequence] = None,
    ):
        super().__init__(catalogs, default_catalog)
        if devices is None:
            devices = jax.devices()
        self.devices = list(devices)
        self.mesh = Mesh(np.array(self.devices), (AXIS,))

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    # ----------------------------------------------------------- input shards
    def sharded_table_page(self, node: TableScan) -> Page:
        """Global arrays laid out [D * cap_local]: device d owns rows
        [d*cap_local, (d+1)*cap_local); trailing pad rows are dead."""
        D = self.num_devices
        full = self.table_page(node.catalog, node.table, node.column_names, node.output_types)
        n = full.capacity
        cap_local = max(1, -(-n // D))
        if self.split_pad_rows:
            # pow2-bucket the per-device shard like the split-driven
            # distributed path: two data scales share shard shape classes
            pad = int(self.split_pad_rows)
            cap_local = -(-cap_local // pad) * pad
        total = D * cap_local
        cols = []
        for col in full.columns:
            data = np.zeros((total,), dtype=np.asarray(col.data).dtype)
            data[:n] = np.asarray(col.data)
            valid = None
            if col.valid is not None:
                v = np.zeros((total,), dtype=np.bool_)
                v[:n] = np.asarray(col.valid)
                valid = jnp.asarray(v)
            data2 = None
            if col.data2 is not None:
                d2 = np.zeros((total,), dtype=np.asarray(col.data2).dtype)
                d2[:n] = np.asarray(col.data2)
                data2 = jnp.asarray(d2)
            cols.append(
                Column(col.type, jnp.asarray(data), valid, col.dictionary, data2)
            )
        live = np.zeros((total,), dtype=np.bool_)
        live[:n] = True
        return Page(tuple(cols), jnp.asarray(live))

    # -------------------------------------------------------------- execution
    def execute(self, plan: PlanNode) -> Page:
        nodes = _node_ids(plan)
        scans = {i: n for i, n in nodes.items() if isinstance(n, TableScan)}
        inputs = {str(i): self.sharded_table_page(n) for i, n in scans.items()}
        caps = self._learned_caps.get(plan)
        if caps is None:
            caps = self._initial_caps_spmd(nodes, inputs)
            total_rows = sum(p.capacity for p in inputs.values())
            if total_rows <= _EAGER_SIZING_LIMIT:
                # converge capacities with EAGER shard_map execution (per-op
                # dispatch, no whole-program compile per attempt) — same
                # rationale as LocalExecutor: each retry otherwise recompiles
                # the whole SPMD program, which on a virtual 8-device CPU
                # mesh costs minutes
                for _ in range(16):
                    _, required = self._run_spmd(plan, inputs, caps, eager=True)
                    overflow = {
                        nid: int(req)
                        for nid, req in required.items()
                        if nid in caps and int(req) > caps[nid]
                    }
                    if not overflow:
                        break
                    for nid, req in overflow.items():
                        caps[nid] = _pow2(max(req, caps[nid] * 2))
        # capacity bucketing (ROADMAP 2a), same as LocalExecutor.execute:
        # quantize every fed capacity onto a pow2 tier so near-identical
        # shapes share one SPMD program; also un-aliases the learned dict
        # from the retry loop's in-place growth below
        caps = {nid: _pow2(max(int(c), 1)) for nid, c in caps.items()}
        for _ in range(14):
            out_page, required = self._run_spmd(plan, inputs, caps)
            for key, val in required.items():
                if isinstance(key, int) and key < 0 and int(val) > 1:
                    raise RuntimeError(
                        "Scalar sub-query has returned multiple rows"
                    )
            overflow = {
                nid: int(req)
                for nid, req in required.items()
                if nid in caps and int(req) > caps[nid]
            }
            if not overflow:
                self._learned_caps[plan] = caps
                if self.collect_operator_stats:
                    jax.block_until_ready([c.data for c in out_page.columns])
                    self._record_operator_stats(nodes, required)
                return out_page
            for nid, req in overflow.items():
                caps[nid] = _pow2(max(req, caps[nid] * 2))
        raise RuntimeError(f"capacity retry loop did not converge: {caps}")

    def explain_analyze(self, plan: PlanNode, remote_pages=None):
        """SPMD EXPLAIN ANALYZE: the whole plan is ONE fused program, so
        per-operator wall time is not separable — but exact per-operator row
        counts (psum-reduced over shards) come out of the compiled run.
        Returns (page, stats) with stats[nid] = {"rows": int}."""
        prev = self.collect_operator_stats
        self.collect_operator_stats = True
        try:
            page = self.execute(plan)
        finally:
            self.collect_operator_stats = prev
        stats = {
            nid: {"rows": s["rows"]}
            for nid, s in self.last_operator_stats.items()
        }
        return page, stats

    def _initial_caps_spmd(self, nodes, inputs) -> dict[int, int]:
        """Like LocalExecutor._initial_caps but sizes are per-device and
        Exchange nodes get bucket capacities."""
        D = self.num_devices
        caps: dict[int, int] = {}

        def size_of(nid: int, n: PlanNode) -> int:
            from ..plan.nodes import Aggregate, Distinct, Limit

            if isinstance(n, TableScan):
                return inputs[str(nid)].capacity // D
            child_ids = _child_ids(nodes, nid)
            child_sizes = [size_of(c, nodes[c]) for c in child_ids]
            if isinstance(n, Exchange):
                if n.kind in ("gather", "broadcast"):
                    return D * child_sizes[0]
                B = _pow2(max(64, 2 * child_sizes[0] // max(D, 1)))
                caps[nid] = B
                return D * B
            if isinstance(n, (Aggregate, Distinct)):
                caps[nid] = _pow2(max(child_sizes[0], 1))
                return caps[nid]
            if isinstance(n, Join):
                if n.kind == "cross":
                    return child_sizes[0]
                hard = _pow2(max(max(child_sizes), 1))
                if n.kind in ("semi", "anti", "null_anti", "mark", "mark_in"):
                    caps[nid] = hard
                    return child_sizes[0]
                # stats-sized expansion frame per device (same rationale as
                # LocalExecutor._initial_caps: kernel work scales with
                # CAPACITY lanes, and worst-case frames made small joins
                # cost like full-table ones); the retry loop corrects
                # underestimates
                try:
                    from ..plan.stats import estimate as _est

                    hint = int(_est(n, self.catalogs).rows * 1.3 // max(D, 1)) + 16
                    caps[nid] = min(hard, _pow2(max(2 * hint, 4096)))
                except Exception:
                    caps[nid] = hard
                if n.kind == "left":
                    return caps[nid] + child_sizes[0]
                return caps[nid]
            if isinstance(n, TopN):
                return min(n.count, child_sizes[0])
            from ..plan.nodes import Compact as _Compact

            if isinstance(n, _Compact):
                # SPMD leaves compaction points as pass-throughs (per-shard
                # capacities already divide by D; the adaptive shrink is a
                # LocalExecutor feature)
                caps[nid] = _pow2(max(child_sizes[0], 1))
                return child_sizes[0]
            from ..plan.nodes import Unnest, Values

            if isinstance(n, Values):
                return max(len(n.rows), 1)
            if isinstance(n, Unnest):
                caps[nid] = _pow2(max(child_sizes[0] * 4, 1024))
                return caps[nid]
            return child_sizes[0]

        size_of(0, nodes[0])
        return caps

    def _run_spmd(
        self,
        plan: PlanNode,
        inputs: dict[str, Page],
        caps: dict[int, int],
        eager: bool = False,
    ):
        try:
            from jax import shard_map
        except ImportError:  # older jax
            from jax.experimental.shard_map import shard_map

        D = self.num_devices
        mesh = self.mesh
        collect = self.collect_operator_stats

        def step(pages):
            return _trace_plan(plan, pages, caps, D, AXIS, collect_stats=collect)

        def smap(fn):
            try:
                return shard_map(
                    fn, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(),
                    check_vma=False,
                )
            except TypeError:  # pre-0.8 jax uses check_rep
                return shard_map(
                    fn, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(),
                    check_rep=False,
                )

        if eager:
            out_page, required = smap(step)(inputs)
            return out_page, jax.device_get(required)

        from ..ops.kernels import policy_key

        cache_key = ("spmd", plan, collect, tuple(sorted(caps.items())),
                     tuple(sorted((k, p.capacity) for k, p in inputs.items())),
                     policy_key())
        if cache_key not in self._jit_cache:
            smapped = smap(step)
            # pack overflow counters into one vector (see LocalExecutor._run:
            # per-scalar device_get RPCs dominate latency on tunneled TPUs)
            holder: dict = {"keys": None}

            def call(pages, _holder=holder):
                out_page, req = smapped(pages)
                keys = sorted(req, key=repr)
                _holder["keys"] = keys
                packed = (
                    jnp.stack([jnp.asarray(req[k], jnp.int64) for k in keys])
                    if keys
                    else jnp.zeros((0,), jnp.int64)
                )
                return out_page, packed

            self._jit_cache[cache_key] = (jax.jit(call), holder)
        fn, holder = self._jit_cache[cache_key]
        out_page, packed = fn(inputs)
        vals = np.asarray(packed)
        return out_page, dict(zip(holder["keys"], vals.tolist()))
